"""End-to-end benchmark suite: every BASELINE.md workload, recall asserted.

Workloads (BASELINE.md configs 1-5):
  1. suicide_1tx        unprotected SELFDESTRUCT, one transaction
                        (solc-compiled suicide.sol.o from the reference mount)
  2. killbilly_3tx      storage-gated selfdestruct needing a 2-tx chain
                        (the reference README's headline demo)
  3. overflow_256bit    BECToken-style 256-bit integer overflow/underflow
                        search (solc-compiled overflow.sol.o/underflow.sol.o)
  4. concolic_flip      concolic JUMPI branch-flip (input synthesis for the
                        untaken side of a recorded trace)
  5. corpus_sweep       the whole reference input corpus (17 solc contracts),
                        shardable across hosts via mythril_tpu.parallel.corpus
                        — THE HEADLINE METRIC (wide workloads are where the
                        batched device frontier pays)

Configurations, run interleaved per workload:
  baseline    host big-int probe + host work-list engine — the stand-in for
              the reference's CPU path (the mounted reference itself cannot
              run here: no z3 wheel in the image, see BASELINE.md)
  production  latency-aware hybrid probe + the batched device-resident
              frontier interpreter (args.frontier)

Every run must find its workload's known vulnerabilities (recall asserted) —
a config that loses recall does not get a number.

Output contract: one JSON snapshot line per completed workload pair (each
carrying ``"partial": true``) and a final complete line without the flag —
consumers take the LAST parseable JSON line.  A wall-clock budget
(``BENCH_BUDGET_S``, default 1500 s) trims reps 2+ deterministically so the
driver's timeout can never kill the run before a full table exists; the
latest snapshot is also mirrored to ``BENCH_partial.json``.
"""

from __future__ import annotations

import json
import os
import sys
import time
from pathlib import Path

REFERENCE_INPUTS = Path("/root/reference/tests/testdata/inputs")
# fallback corpus when the reference mount is absent (raw runtime dumps)
LOCAL_INPUTS = Path(__file__).parent / "tests" / "integration" / "inputs"
CORPUS_GLOBS = ("*.sol.o", "*.bin-runtime")

# ---------------------------------------------------------------------------
# workload 2: killbilly (hand-assembled; kept importable for tests)
# ---------------------------------------------------------------------------

# activate() selector 0x0a11ce00 -> 0x1e, kill() selector 0x41c0e1b5 -> 0x25
DISPATCH = (
    "6000" "35" "60e0" "1c" "80"
    "630a11ce00" "14" "601e" "57"
    "6341c0e1b5" "14" "6025" "57"
    "60006000fd"
)
ACTIVATE = "5b600160005500"  # 0x1e: JUMPDEST; SSTORE(0, 1); STOP
KILL = "5b" "600054" "6001" "14" "6034" "57" "60006000fd" "5b" "33" "ff"
KILLBILLY = DISPATCH + ACTIVATE + KILL
_L = f"{len(KILLBILLY) // 2:02x}"
KILLBILLY_CREATION = f"60{_L}600c60003960{_L}6000f3" + KILLBILLY


def _clear_caches() -> None:
    from mythril_tpu.analysis.module.loader import ModuleLoader
    from mythril_tpu.analysis.security import reset_callback_modules
    from mythril_tpu.querycache import reset_query_cache
    from mythril_tpu.smt.solver import clear_model_cache
    from mythril_tpu.support.model import _get_model_cached

    reset_callback_modules()
    clear_model_cache()
    _get_model_cached.cache_clear()
    # drops the in-process query cache but keeps any configured disk store
    # attached — warm runs in query_cache_compare hit via the disk tier
    reset_query_cache()
    for module in ModuleLoader().get_detection_modules():
        module.cache.clear()


def _analyze(contract, address, tx_count, modules=None, strategy="bfs",
             timeout=60):
    from mythril_tpu.analysis.security import fire_lasers
    from mythril_tpu.analysis.symbolic import SymExecWrapper

    sym = SymExecWrapper(
        contract,
        address=address,
        strategy=strategy,
        transaction_count=tx_count,
        execution_timeout=timeout,
        modules=modules,
    )
    issues = fire_lasers(sym, white_list=modules)
    return sym, issues


def _configure(production: bool) -> None:
    """baseline = host probe + host engine.  production = latency-aware
    hybrid probe + the batched device frontier ENABLED EVERYWHERE — the
    engine's own width gating (a-priori narrow gate + adaptive narrow-bail,
    frontier/engine.py) decides per run whether the device pays, so narrow
    workloads run unchanged and wide ones go device-resident."""
    from mythril_tpu.support.support_args import args

    args.probe_backend = "auto" if production else "host"
    args.frontier = production
    args.frontier_force = False
    if production:
        # one production width across workloads (wide_frontier overrides to
        # 1024): every device run shares the segment program _warm_frontier
        # compiled, so no workload pays an XLA compile inside its timer
        args.frontier_width = 256


# ---------------------------------------------------------------------------
# recall helpers
# ---------------------------------------------------------------------------


def _ttfe(issues, t0: float, swc: str = None) -> float:
    """Time-to-first-exploit: wall seconds from analysis start to the first
    (matching) issue's discovery (BASELINE.json's second metric).  Issue
    discovery stamps are process-global (report.StartTime), so they are
    rebased against this run's ``t0``."""
    from mythril_tpu.analysis.report import StartTime

    base = StartTime().global_start_time
    stamps = [
        i.discovery_time for i in issues if swc is None or i.swc_id == swc
    ]
    if not stamps:
        return float("nan")
    return _rebase_stamp(base + min(stamps), t0)


def _selects(input_hex: str, selector: int) -> bool:
    """Does this calldata dispatch to ``selector``?  EVM CALLDATALOAD
    zero-pads past calldatasize, so exact minimization may shave trailing
    zero bytes off the selector itself (0x0a11ce00 -> 3-byte calldata)."""
    data = bytes.fromhex(input_hex[2:] if input_hex.startswith("0x") else input_hex)
    padded = (data + b"\x00" * 4)[:4]
    return int.from_bytes(padded, "big") == selector


def check_recall(issues) -> None:
    """killbilly recall: SWC-106 with activate() then kill()."""
    assert issues, "exploit not found: zero issues"
    issue = issues[0]
    assert issue.swc_id == "106", f"wrong SWC id {issue.swc_id}"
    steps = issue.transaction_sequence["steps"]
    inputs = [s["input"] for s in steps]
    assert any(_selects(i, 0x0A11CE00) for i in inputs), "missing activate() tx"
    assert _selects(inputs[-1], 0x41C0E1B5), "final tx is not kill()"


def run_analysis(probe_backend: str):
    """Killbilly workload under one probe backend (kept for tests)."""
    from mythril_tpu.frontend.evmcontract import EVMContract
    from mythril_tpu.support.support_args import args as global_args

    global_args.probe_backend = probe_backend
    _clear_caches()
    contract = EVMContract(
        code=KILLBILLY, creation_code=KILLBILLY_CREATION, name="KillBilly"
    )
    t0 = time.time()
    sym, issues = _analyze(
        contract, 0x0901D12E, 3, modules=["AccidentallyKillable"], timeout=300
    )
    return sym, issues, time.time() - t0


def query_cache_compare(cache_dir=None) -> dict:
    """Warm-vs-cold query-cache comparison on the killbilly workload.

    Runs the analysis twice against one disk-backed cache directory: the
    cold run populates the store, the warm run (fresh in-process cache via
    ``_clear_caches``) must hit it.  Asserts a nonzero warm hit count and
    an issue set identical to the cold run, then returns (and ``main``
    prints) one JSON-able dict with walls, hit counters and the full
    ``querycache.*`` registry snapshot.
    """
    import tempfile

    from mythril_tpu.observability import get_registry
    from mythril_tpu.querycache import configure, get_query_cache

    def issue_set(issues):
        return sorted((i.swc_id, i.address) for i in issues)

    tmp = None
    if cache_dir is None:
        tmp = tempfile.TemporaryDirectory(prefix="mythril-querycache-")
        cache_dir = tmp.name
    try:
        configure(enabled=True, cache_dir=str(cache_dir))

        get_registry().reset(prefix="querycache.")
        _, cold_issues, cold_wall = run_analysis("host")
        cold_stats = dict(get_query_cache().stats())

        get_registry().reset(prefix="querycache.")
        _, warm_issues, warm_wall = run_analysis("host")
        warm_stats = dict(get_query_cache().stats())
        warm_hits = get_query_cache().hits_total()

        assert warm_hits > 0, f"warm run had zero cache hits: {warm_stats}"
        assert issue_set(cold_issues) == issue_set(warm_issues), (
            "warm issue set diverged from cold: "
            f"{issue_set(cold_issues)} != {issue_set(warm_issues)}"
        )
        lookups = warm_stats.get("lookups", 0)
        return {
            "metric": "query_cache_compare",
            "workload": "killbilly",
            "cache_dir": str(cache_dir),
            "cold_wall_s": round(cold_wall, 3),
            "warm_wall_s": round(warm_wall, 3),
            "warm_hits": warm_hits,
            "warm_hit_rate": round(warm_hits / lookups, 4) if lookups else 0.0,
            "issues": issue_set(cold_issues),
            "cold": cold_stats,
            "warm": warm_stats,
        }
    finally:
        configure(enabled=True, cache_dir=None)
        if tmp is not None:
            tmp.cleanup()


def _interproc_parity() -> dict:
    """Interprocedural-layer on/off bit-identity across the bench corpus.

    For every corpus member (reference corpus when mounted, plus killbilly
    and the assembled real shapes) the full analysis runs twice — interproc
    refinement on, then off — and the issue sets must be IDENTICAL: the
    refinement may only remove edges and work, never findings.  On top,
    the corrected-denominator contract is asserted over every coverage
    entry the runs produced: ``coverage_pct_reachable >= coverage_pct_raw``
    everywhere, strictly higher somewhere (dead code exists in at least
    one analyzed code object — e.g. the unreachable runtime body inside a
    creation frame).
    """
    from mythril_tpu.frontend.evmcontract import EVMContract
    from mythril_tpu.observability.exploration import get_exploration_ledger
    from mythril_tpu.staticpass import clear_cache, reset_views
    from mythril_tpu.support.support_args import args as global_args
    from mythril_tpu.support.support_utils import get_code_hash

    members = [(
        "killbilly",
        EVMContract(code=KILLBILLY, creation_code=KILLBILLY_CREATION,
                    name="KillBilly"),
        KILLBILLY,
    )]
    for path in sorted(p for g in CORPUS_GLOBS for p in _corpus_dir().glob(g)):
        code = _read_runtime(path)
        members.append((path.name, code, code.hex()))
    for name, code in _assembled_corpus():
        hex_code = code.hex() if isinstance(code, (bytes, bytearray)) else code
        members.append((name, code, hex_code))

    prev = (global_args.staticpass, global_args.staticpass_interproc)
    rows = []
    try:
        for name, contract, hex_code in members:
            def one(interproc: bool):
                global_args.staticpass = True
                global_args.staticpass_interproc = interproc
                _clear_caches()
                clear_cache()
                reset_views()
                _, issues = _analyze(contract, 0x0901D12E, 2, timeout=60)
                return sorted((i.swc_id, i.address) for i in issues)

            on_issues = one(True)
            cov = get_exploration_ledger().coverage().get(
                get_code_hash(hex_code)
            ) or {}
            off_issues = one(False)
            assert on_issues == off_issues, (
                f"{name}: interprocedural pruning changed the issue set "
                f"(over-approximation broken): {on_issues} != {off_issues}"
            )
            rows.append({
                "workload": name,
                "issues": on_issues,
                "coverage_pct_raw": cov.get("instruction_pct_raw"),
                "coverage_pct_reachable": cov.get("instruction_pct_reachable"),
            })
        # denominator contract over EVERY code object the runs touched
        # (creation frames included — that's where dead code is common)
        strictly_higher = 0
        for h, cov in get_exploration_ledger().coverage().items():
            raw = cov.get("instruction_pct_raw")
            reach = cov.get("instruction_pct_reachable")
            if raw is None or reach is None:
                continue
            assert reach >= raw, (
                f"{h}: coverage_pct_reachable {reach} < raw {raw} — the "
                "reachable denominator undercounted executed instructions"
            )
            if reach > raw:
                strictly_higher += 1
        assert strictly_higher >= 1, (
            "no analyzed code object had strictly higher reachable "
            "coverage — the corrected denominator changed nothing anywhere"
        )
    finally:
        global_args.staticpass, global_args.staticpass_interproc = prev
    return {
        "contracts": len(rows),
        "identical_issue_sets": True,
        "strictly_higher_reachable": strictly_higher,
        "rows": rows,
    }


def staticpass_compare() -> dict:
    """Static-pass on-vs-off comparison on the killbilly workload.

    Runs the full-module analysis twice — once with the static pre-analysis
    gate enabled, once with ``--no-staticpass`` semantics — and asserts the
    over-approximation contract: the issue sets are IDENTICAL while the
    gated run skipped a nonzero number of modules and elided a nonzero
    number of hooks.  A second sweep (``_interproc_parity``) toggles ONLY
    the interprocedural layer across the whole bench corpus and asserts
    bit-identical issue sets plus the reachable-coverage denominator
    contract.  Returns (and ``main`` prints) one JSON-able dict with both
    walls, both issue sets, the ``staticpass.*`` registry snapshot of the
    gated run and the per-member interproc parity rows.
    """
    from mythril_tpu.frontend.evmcontract import EVMContract
    from mythril_tpu.observability import get_registry
    from mythril_tpu.staticpass import clear_cache, reset_views
    from mythril_tpu.support.support_args import args as global_args

    def issue_set(issues):
        return sorted((i.swc_id, i.address) for i in issues)

    def one_run(enabled: bool):
        global_args.staticpass = enabled
        _clear_caches()
        clear_cache()
        reset_views()
        get_registry().reset(prefix="staticpass.")
        contract = EVMContract(
            code=KILLBILLY, creation_code=KILLBILLY_CREATION, name="KillBilly"
        )
        t0 = time.time()
        # all 14 modules: the gate needs irrelevant detectors to skip
        _, issues = _analyze(contract, 0x0901D12E, 3, modules=None, timeout=300)
        wall = time.time() - t0
        snap = {
            k: v
            for k, v in get_registry().snapshot().items()
            if k.startswith("staticpass.")
        }
        return issue_set(issues), wall, snap

    prev = global_args.staticpass
    try:
        on_issues, on_wall, on_snap = one_run(True)
        off_issues, off_wall, off_snap = one_run(False)
    finally:
        global_args.staticpass = prev

    assert on_snap.get("staticpass.modules_skipped", 0) > 0, (
        f"static pass skipped zero modules: {on_snap}"
    )
    assert on_snap.get("staticpass.hooks_elided", 0) > 0, (
        f"static pass elided zero hooks: {on_snap}"
    )
    assert off_snap.get("staticpass.modules_skipped", 0) == 0, (
        f"--no-staticpass run still gated modules: {off_snap}"
    )
    assert on_issues == off_issues, (
        "static pass changed the issue set (over-approximation broken): "
        f"{on_issues} != {off_issues}"
    )
    return {
        "metric": "staticpass_compare",
        "workload": "killbilly",
        "on_wall_s": round(on_wall, 3),
        "off_wall_s": round(off_wall, 3),
        "modules_skipped": on_snap.get("staticpass.modules_skipped", 0),
        "hooks_elided": on_snap.get("staticpass.hooks_elided", 0),
        "issues": on_issues,
        "staticpass": on_snap,
        "interproc": _interproc_parity(),
    }


def pipeline_compare() -> dict:
    """Pipelined vs synchronous frontier on two small workloads.

    Runs each workload twice with the device frontier forced on — once with
    the pipelined runner (chained dispatch + background feasibility pool),
    once with ``--no-pipeline`` semantics — and asserts the correctness
    contract: the issue sets are IDENTICAL while the pipelined run actually
    overlapped a nonzero number of segments.  Also asserts time-to-first-
    exploit parity (generous bound — CPU-backend walls jitter) so the
    opening-dispatch fix and the pipeline never push the first event behind
    a big-bucket compile again.  Returns (and ``main`` prints) one
    JSON-able dict with both walls, both issue sets and the ``pipeline.*``
    registry snapshot of the pipelined run.
    """
    from mythril_tpu.frontend.evmcontract import EVMContract
    from mythril_tpu.frontier import engine as _eng
    from mythril_tpu.observability import get_registry
    from mythril_tpu.support.support_args import args as global_args

    def issue_set(issues):
        return sorted((i.swc_id, i.address) for i in issues)

    suicide = bytes.fromhex("60003560e01c6341c0e1b51460145760006000fd5b33ff")
    workloads = [
        # (name, contract-or-code, tx_count, modules, recall swc)
        ("suicide", suicide, 1, ["AccidentallyKillable"], "106"),
        ("killbilly",
         EVMContract(code=KILLBILLY, creation_code=KILLBILLY_CREATION,
                     name="KillBilly"),
         3, ["AccidentallyKillable"], "106"),
    ]

    def one_run(target, txs, modules, pipelined: bool):
        global_args.pipeline = pipelined
        _clear_caches()
        # the per-code slow/narrow verdicts and program-warm markers are
        # deliberately process-persistent; a verdict learned in run A must
        # not change run B's control flow when comparing the two modes
        _eng._SLOW_CODES.clear()
        _eng._NARROW_CODES.clear()
        _eng._SLOW_SEGMENTS.clear()
        get_registry().reset(prefix="pipeline.")
        t0 = time.time()
        _, issues = _analyze(target, 0x0901D12E, txs, modules=modules,
                             timeout=300)
        wall = time.time() - t0
        snap = {
            k: v
            for k, v in get_registry().snapshot().items()
            if k.startswith("pipeline.")
        }
        return issue_set(issues), wall, _ttfe(issues, t0), snap

    prev = (global_args.pipeline, global_args.frontier,
            global_args.frontier_force, global_args.frontier_width)
    results = {}
    try:
        global_args.probe_backend = "auto"
        global_args.frontier = True
        global_args.frontier_force = True  # tiny contracts: bypass gates
        global_args.frontier_width = 64
        # warm both programs outside the timers: the pipelined and
        # synchronous paths jit different programs (chained-dispatch merge
        # vs plain push) and a cold XLA compile inside either timed run
        # would swamp the wall/ttfe comparison
        for pipelined in (True, False):
            one_run(suicide, 1, ["AccidentallyKillable"], pipelined)
        for name, target, txs, modules, swc in workloads:
            on_issues, on_wall, on_ttfe, on_snap = one_run(
                target, txs, modules, True
            )
            off_issues, off_wall, off_ttfe, off_snap = one_run(
                target, txs, modules, False
            )
            assert any(s == swc for s, _ in on_issues), (
                f"{name}: pipelined run lost recall: {on_issues}"
            )
            assert on_issues == off_issues, (
                f"{name}: pipeline changed the issue set: "
                f"{on_issues} != {off_issues}"
            )
            assert on_snap.get("pipeline.segments_pipelined", 0) > 0, (
                f"{name}: pipelined run overlapped zero segments: {on_snap}"
            )
            assert off_snap.get("pipeline.segments_pipelined", 0) == 0, (
                f"{name}: --no-pipeline run still pipelined: {off_snap}"
            )
            # parity, not a race: generous bound absorbs CPU-backend jitter
            if on_ttfe == on_ttfe and off_ttfe == off_ttfe:
                assert on_ttfe <= 3.0 * off_ttfe + 2.0, (
                    f"{name}: pipelined ttfe_s regressed: "
                    f"{on_ttfe:.2f}s vs {off_ttfe:.2f}s synchronous"
                )
            results[name] = {
                "pipelined_wall_s": round(on_wall, 3),
                "sync_wall_s": round(off_wall, 3),
                "pipelined_ttfe_s": round(on_ttfe, 3),
                "sync_ttfe_s": round(off_ttfe, 3),
                "issues": on_issues,
                "pipeline": on_snap,
            }
    finally:
        (global_args.pipeline, global_args.frontier,
         global_args.frontier_force, global_args.frontier_width) = prev
    return {"metric": "pipeline_compare", "workloads": results}


def prefilter_compare() -> dict:
    """Abstract pre-filter on-vs-off parity on two exploit workloads.

    Runs each workload twice with the pipelined device frontier forced on —
    once with the interval/known-bits pre-filter enabled, once with
    ``--no-prefilter`` semantics — and asserts the zero-recall-loss
    contract: the issue sets are IDENTICAL while the filtered run proved a
    nonzero number of feasibility queries UNSAT before any exact solve, and
    the harvest solver phase did not regress (generous CPU-jitter bound).
    Returns (and ``main`` prints) one JSON-able dict with both walls, both
    issue sets and the ``prefilter.*`` registry snapshot of the gated run.
    """
    from mythril_tpu import absdomain
    from mythril_tpu.frontend.evmcontract import EVMContract
    from mythril_tpu.frontier import engine as _eng
    from mythril_tpu.observability import get_registry
    from mythril_tpu.support.support_args import args as global_args

    def issue_set(issues):
        return sorted((i.swc_id, i.address) for i in issues)

    suicide = bytes.fromhex("60003560e01c6341c0e1b51460145760006000fd5b33ff")
    # x = calldataload(0); require(x < 10); x == 5 -> selfdestruct
    # (feasible exploit), x == 20 -> selfdestruct (infeasible: the branch
    # constraint contradicts the range pin, exactly the contradiction the
    # abstract harvest refutes without an exact solve)
    gated = bytes.fromhex(
        "60003580600a9010600c57005b80600514601c5780601414601c57005b33ff"
    )
    workloads = [
        # (name, contract-or-code, tx_count, modules, recall swc).
        # killbilly runs ALL detection modules: its feasibility traffic is
        # dominated by module confirmation demands and exercises the
        # fallthrough/parity side; "gated" carries the infeasible branch
        # that the pre-filter must kill before any exact solve
        ("suicide", suicide, 1, ["AccidentallyKillable"], "106"),
        ("gated", gated, 1, ["AccidentallyKillable"], "106"),
        ("killbilly",
         EVMContract(code=KILLBILLY, creation_code=KILLBILLY_CREATION,
                     name="KillBilly"),
         3, None, "106"),
    ]

    def one_run(target, txs, modules, filtered: bool):
        global_args.prefilter = filtered
        _clear_caches()
        absdomain.reset_state()  # verdict memo must not leak across modes
        _eng._SLOW_CODES.clear()
        _eng._NARROW_CODES.clear()
        _eng._SLOW_SEGMENTS.clear()
        reg = get_registry()
        reg.reset(prefix="prefilter.")
        solver_before = reg.histogram("frontier.harvest.solver_s").sum
        t0 = time.time()
        _, issues = _analyze(target, 0x0901D12E, txs, modules=modules,
                             timeout=300)
        wall = time.time() - t0
        solver_s = reg.histogram("frontier.harvest.solver_s").sum - solver_before
        snap = {
            k: v
            for k, v in reg.snapshot().items()
            if k.startswith("prefilter.")
        }
        return issue_set(issues), wall, solver_s, snap

    prev = (global_args.prefilter, global_args.frontier,
            global_args.frontier_force, global_args.frontier_width,
            global_args.pipeline)
    results = {}
    total_killed = 0
    try:
        global_args.probe_backend = "auto"
        global_args.frontier = True
        global_args.frontier_force = True  # tiny contracts: bypass gates
        global_args.frontier_width = 64
        global_args.pipeline = True
        # warm the XLA programs outside the timers (cold compiles would
        # swamp the solver_s comparison)
        one_run(suicide, 1, ["AccidentallyKillable"], True)
        for name, target, txs, modules, swc in workloads:
            on_issues, on_wall, on_solver, on_snap = one_run(
                target, txs, modules, True
            )
            off_issues, off_wall, off_solver, off_snap = one_run(
                target, txs, modules, False
            )
            assert any(s == swc for s, _ in on_issues), (
                f"{name}: filtered run lost recall: {on_issues}"
            )
            assert on_issues == off_issues, (
                f"{name}: pre-filter changed the issue set "
                "(soundness broken): "
                f"{on_issues} != {off_issues}"
            )
            assert off_snap.get("prefilter.evaluated", 0) == 0, (
                f"{name}: --no-prefilter run still evaluated: {off_snap}"
            )
            killed = on_snap.get("prefilter.killed", 0)
            total_killed += killed
            # parity, not a race: the filter must not ADD solver time
            # (generous bound absorbs CPU-backend jitter)
            assert on_solver <= 1.5 * off_solver + 2.0, (
                f"{name}: prefilter regressed harvest solver_s: "
                f"{on_solver:.2f}s vs {off_solver:.2f}s unfiltered"
            )
            results[name] = {
                "filtered_wall_s": round(on_wall, 3),
                "unfiltered_wall_s": round(off_wall, 3),
                "filtered_solver_s": round(on_solver, 3),
                "unfiltered_solver_s": round(off_solver, 3),
                "killed": killed,
                "issues": on_issues,
                "prefilter": on_snap,
            }
    finally:
        (global_args.prefilter, global_args.frontier,
         global_args.frontier_force, global_args.frontier_width,
         global_args.pipeline) = prev
    assert total_killed > 0, (
        "pre-filter killed zero queries across every exploit workload: "
        f"{results}"
    )
    return {"metric": "prefilter_compare", "workloads": results}


def devsolver_compare() -> dict:
    """Device SAT tier on-vs-off parity on the exploit workloads.

    Runs each workload twice with the pipelined device frontier forced on
    — once with the devsolver tier enabled, once with ``--no-devsolver``
    semantics — and asserts the soundness-by-construction contract: the
    issue sets are IDENTICAL while the gated run *decided* (exact UNSAT
    or concrete_eval-validated SAT) a nonzero number of queries that the
    ungated run sent to the exact tiers, with zero model-validation
    failures surviving as verdicts and no harvest-solver regression.
    Mirrors ``prefilter_compare``; one JSON-able dict per run.
    """
    from mythril_tpu import absdomain, devsolver
    from mythril_tpu.frontend.evmcontract import EVMContract
    from mythril_tpu.frontier import engine as _eng
    from mythril_tpu.observability import get_registry
    from mythril_tpu.support.support_args import args as global_args

    def issue_set(issues):
        return sorted((i.swc_id, i.address) for i in issues)

    suicide = bytes.fromhex("60003560e01c6341c0e1b51460145760006000fd5b33ff")
    gated = bytes.fromhex(
        "60003580600a9010600c57005b80600514601c5780601414601c57005b33ff"
    )
    # x=cld(0); y=cld(32); require x<16, y<16, x==y; the selfdestruct is
    # guarded by ((x^y)&15)==15 — a RELATIONAL contradiction the interval
    # and known-bits prefilter cannot see (neither var is pinned to a
    # point) but bit-level search refutes after one decision; without the
    # device tier it rides all the way to native CDCL
    relational = bytes.fromhex(
        "6000358060109010600c57005b"
        "6020358060109010601957005b"
        "80821460215700" "5b"
        "18600f16600f14602d57005b"
        "33ff"
    )
    workloads = [
        # same exploit workloads as prefilter_compare ("gated" carries
        # narrow range-pinned branch conditions, killbilly exercises the
        # wide fallthrough side) plus the devsolver's signature prey: a
        # relational infeasibility with an EMPTY issue set (swc None)
        ("suicide", suicide, 1, ["AccidentallyKillable"], "106"),
        ("gated", gated, 1, ["AccidentallyKillable"], "106"),
        ("relational", relational, 1, ["AccidentallyKillable"], None),
        ("killbilly",
         EVMContract(code=KILLBILLY, creation_code=KILLBILLY_CREATION,
                     name="KillBilly"),
         3, None, "106"),
    ]

    def one_run(target, txs, modules, gated_on: bool):
        global_args.devsolver = gated_on
        _clear_caches()
        absdomain.reset_state()
        devsolver.reset_state()  # verdict memo must not leak across modes
        _eng._SLOW_CODES.clear()
        _eng._NARROW_CODES.clear()
        _eng._SLOW_SEGMENTS.clear()
        reg = get_registry()
        reg.reset(prefix="devsolver.")
        solver_before = reg.histogram("frontier.harvest.solver_s").sum
        t0 = time.time()
        _, issues = _analyze(target, 0x0901D12E, txs, modules=modules,
                             timeout=300)
        wall = time.time() - t0
        solver_s = reg.histogram("frontier.harvest.solver_s").sum - solver_before
        snap = {
            k: v
            for k, v in reg.snapshot().items()
            if k.startswith("devsolver.")
        }
        return issue_set(issues), wall, solver_s, snap

    prev = (global_args.devsolver, global_args.prefilter,
            global_args.frontier, global_args.frontier_force,
            global_args.frontier_width, global_args.pipeline)
    results = {}
    total_decided = 0
    try:
        global_args.probe_backend = "auto"
        global_args.frontier = True
        global_args.frontier_force = True  # tiny contracts: bypass gates
        global_args.frontier_width = 64
        global_args.pipeline = True
        # warm the XLA programs outside the timers
        one_run(suicide, 1, ["AccidentallyKillable"], True)
        for name, target, txs, modules, swc in workloads:
            on_issues, on_wall, on_solver, on_snap = one_run(
                target, txs, modules, True
            )
            off_issues, off_wall, off_solver, off_snap = one_run(
                target, txs, modules, False
            )
            if swc is None:
                assert not on_issues, (
                    f"{name}: infeasible branch produced issues "
                    f"(false positive): {on_issues}"
                )
            else:
                assert any(s == swc for s, _ in on_issues), (
                    f"{name}: devsolver run lost recall: {on_issues}"
                )
            assert on_issues == off_issues, (
                f"{name}: device SAT tier changed the issue set "
                "(soundness broken): "
                f"{on_issues} != {off_issues}"
            )
            assert off_snap.get("devsolver.admitted", 0) == 0, (
                f"{name}: --no-devsolver run still admitted: {off_snap}"
            )
            decided = (on_snap.get("devsolver.decided_sat", 0)
                       + on_snap.get("devsolver.decided_unsat", 0))
            total_decided += decided
            # parity, not a race: the tier must not ADD solver time
            # (generous bound absorbs CPU-backend jitter)
            assert on_solver <= 1.5 * off_solver + 2.0, (
                f"{name}: devsolver regressed harvest solver_s: "
                f"{on_solver:.2f}s vs {off_solver:.2f}s ungated"
            )
            results[name] = {
                "gated_wall_s": round(on_wall, 3),
                "ungated_wall_s": round(off_wall, 3),
                "gated_solver_s": round(on_solver, 3),
                "ungated_solver_s": round(off_solver, 3),
                "decided": decided,
                "fallthrough": on_snap.get("devsolver.unknown", 0),
                "issues": on_issues,
                "devsolver": on_snap,
            }
    finally:
        (global_args.devsolver, global_args.prefilter,
         global_args.frontier, global_args.frontier_force,
         global_args.frontier_width, global_args.pipeline) = prev
    assert total_decided > 0, (
        "device SAT tier decided zero queries across every exploit "
        f"workload: {results}"
    )
    return {"metric": "devsolver_compare", "workloads": results}


def adaptive_compare() -> dict:
    """Coverage-guided steering on-vs-off parity on multi-code batches.

    Runs each cooperative workload twice with the pipelined device
    frontier forced on — once with the adaptive controller enabled, once
    with ``--no-adaptive`` semantics — and asserts the steering
    contract: the issue sets are BIT-IDENTICAL (the controller only
    reorders/retimes frontier compute, it never changes what is
    explored to completion) while the steered run actually exerted
    steering (``adaptive.resteered_slots > 0`` somewhere).  The
    ``loop_tail`` workload additionally runs the steered side under
    ``--coverage-target``: its long concrete loop saturates instruction
    coverage after one iteration, so the steered run must latch a
    coverage stop and dispatch FEWER segments (or less wall) than the
    unsteered run that unrolls the tail to exhaustion — the efficiency
    half of the contract.  Mirrors ``devsolver_compare``; one JSON-able
    dict per run.
    """
    from mythril_tpu.adaptive import get_adaptive_controller
    from mythril_tpu.analysis.cooperative import analyze_cooperative
    from mythril_tpu.observability import get_registry
    from mythril_tpu.observability.exploration import get_exploration_ledger
    from mythril_tpu.support.support_args import args as global_args

    def issue_set(per_name):
        return sorted(
            (name, i.swc_id, i.address, i.bytecode_hash)
            for name, issues in per_name.items()
            for i in issues
        )

    suicide = bytes.fromhex("60003560e01c6341c0e1b51460145760006000fd5b33ff")
    gated = bytes.fromhex(
        "60003580600a9010600c57005b80600514601c5780601414601c57005b33ff"
    )
    killbilly = bytes.fromhex(KILLBILLY)
    # selector dispatch to CALLER;SELFDESTRUCT at 0x1e, fallthrough into a
    # 511-iteration concrete counter loop ending in STOP: every
    # instruction except the loop-exit STOP is covered after ONE
    # iteration, so coverage saturates ~8 segments before the unroll ends
    loop_tail = bytes.fromhex(
        "60003560e01c6341c0e1b514601e5760005b600101806102001160115700"
        "5b33ff"
    )
    workloads = [
        # multi-code batches: steering only deviates from FIFO when the
        # seed queue holds distinct codes with different uncovered-edge
        # mass, so single-code runs would trivially (vacuously) pass
        ("exploit_mix",
         [("suicide", suicide), ("gated", gated),
          ("killbilly", killbilly)],
         2, {"106"}, None),
        ("wide_mix",
         [(f"wide{n}", _wide_contract(n)) for n in (3, 4, 5, 6)],
         1, {"106"}, None),
        # the efficiency workload: steered side carries --coverage-target
        ("loop_tail",
         [("loop_tail", loop_tail), ("suicide", suicide)],
         1, {"106"}, 90.0),
    ]

    def one_run(jobs, txs, steered: bool, target=None):
        global_args.adaptive = steered
        global_args.coverage_target = target if steered else None
        _clear_caches()
        get_exploration_ledger().reset_scope()
        ctrl = get_adaptive_controller()
        ctrl.reset_scope()
        reg = get_registry()
        reg.reset(prefix="adaptive.")
        seg_before = reg.counter("frontier.segments").value
        t0 = time.time()
        per_name, _states = analyze_cooperative(
            jobs, transaction_count=txs, execution_timeout=120
        )
        wall = time.time() - t0
        segments = reg.counter("frontier.segments").value - seg_before
        snap = {
            k: v for k, v in reg.snapshot().items()
            if k.startswith("adaptive.")
        }
        return issue_set(per_name), wall, segments, snap, ctrl.stop_state()

    prev = (global_args.adaptive, global_args.coverage_target,
            global_args.frontier, global_args.frontier_force,
            global_args.frontier_width, global_args.pipeline,
            global_args.loop_bound)
    results = {}
    total_resteered = 0
    any_cheaper = False
    try:
        global_args.probe_backend = "auto"
        global_args.frontier = True
        global_args.frontier_force = True  # tiny contracts: bypass gates
        global_args.frontier_width = 64
        global_args.pipeline = True
        # above loop_tail's 511 iterations so the unsteered run unrolls
        # to natural exit — identical config both sides keeps it fair
        global_args.loop_bound = 600
        # warm the XLA programs outside the timers
        one_run([("suicide", suicide)], 1, True)
        for name, jobs, txs, swcs, target in workloads:
            # unsteered first: it pays any residual compile for this
            # batch shape, so the steered wall is steady-state
            off_issues, off_wall, off_segments, off_snap, _ = one_run(
                jobs, txs, False
            )
            on_issues, on_wall, on_segments, on_snap, on_stop = one_run(
                jobs, txs, True, target
            )
            found = {s for _, s, _, _ in on_issues}
            assert swcs <= found, (
                f"{name}: steered run lost recall: wanted {swcs}, "
                f"got {found}"
            )
            assert on_issues == off_issues, (
                f"{name}: adaptive steering changed the issue set "
                f"(parity broken): {on_issues} != {off_issues}"
            )
            assert not off_snap.get("adaptive.resteered_slots", 0), (
                f"{name}: --no-adaptive run still resteered: {off_snap}"
            )
            assert not off_snap.get("adaptive.plans", 0), (
                f"{name}: --no-adaptive run still planned: {off_snap}"
            )
            resteered = on_snap.get("adaptive.resteered_slots", 0)
            total_resteered += resteered
            if target is not None:
                assert on_stop is not None, (
                    f"{name}: --coverage-target {target} never latched a "
                    f"stop verdict (coverage check dead): {on_snap}"
                )
                assert on_segments < off_segments or on_wall < off_wall, (
                    f"{name}: coverage-target stop saved nothing: "
                    f"{on_segments} vs {off_segments} segments, "
                    f"{on_wall:.2f}s vs {off_wall:.2f}s"
                )
            if on_segments < off_segments or on_wall < off_wall:
                any_cheaper = True
            results[name] = {
                "steered_wall_s": round(on_wall, 3),
                "unsteered_wall_s": round(off_wall, 3),
                "steered_segments": int(on_segments),
                "unsteered_segments": int(off_segments),
                "segments_dispatched_delta": int(on_segments - off_segments),
                "resteered_slots": int(resteered),
                "requeued_paths": int(
                    on_snap.get("adaptive.requeued_paths", 0)
                ),
                "issues": len(on_issues),
                "adaptive": on_snap,
                **({"coverage_stop": on_stop} if on_stop else {}),
            }
    finally:
        (global_args.adaptive, global_args.coverage_target,
         global_args.frontier, global_args.frontier_force,
         global_args.frontier_width, global_args.pipeline,
         global_args.loop_bound) = prev
    assert total_resteered > 0, (
        "adaptive controller resteered zero dispatch slots across every "
        f"multi-code workload (steering never engaged): {results}"
    )
    assert any_cheaper, (
        "no workload got cheaper under steering (fewer segments or "
        f"lower wall with resteered_slots > 0): {results}"
    )
    return {"metric": "adaptive_compare", "workloads": results}


def paging_compare() -> dict:
    """Large-code frontier on-vs-off parity on mixed-size batches.

    Runs each workload twice with the device frontier forced on — once
    with per-code bucket isolation + packed-code paging (the defaults),
    once under ``--no-code-paging`` semantics (one corpus-wide bucket,
    everything fully resident) — and asserts the optimization contract:
    the issue sets are BIT-IDENTICAL (paging only changes which window
    of a code is device-resident; a cold jump degrades to an ordinary
    host park, and the host engine is always correct), the isolated run
    actually split the corpus into >1 bucket class with strictly lower
    pad waste than the single-bucket counterfactual, and the paged
    workload actually faulted and repacked at least once.  Mirrors
    ``adaptive_compare``; one JSON-able dict per run."""
    from mythril_tpu.analysis.cooperative import analyze_cooperative
    from mythril_tpu.observability import get_registry
    from mythril_tpu.support.support_args import args as global_args

    def issue_set(per_name):
        return sorted(
            (name, i.swc_id, i.address, i.bytecode_hash)
            for name, issues in per_name.items()
            for i in issues
        )

    suicide = bytes.fromhex("60003560e01c6341c0e1b51460145760006000fd5b33ff")
    gated = bytes.fromhex(
        "60003580600a9010600c57005b80600514601c5780601414601c57005b33ff"
    )
    # mixed-size batches: the parity only bites when small codes share a
    # batch with an outlier big enough to page (deep cold-jump target)
    workloads = [
        ("largecode_mixed",
         [("bigkill", _largecode_contract()), ("suicide", suicide),
          ("gated", gated)],
         2, {"106"}),
        ("two_outliers",
         [("big_a", _largecode_contract(1200)),
          ("big_b", _largecode_contract(2400)), ("suicide", suicide)],
         1, {"106"}),
    ]

    def one_run(jobs, txs, paged: bool):
        global_args.code_paging = paged
        _clear_caches()
        reg = get_registry()
        before = (
            reg.counter("frontier.page_faults").value,
            reg.counter("frontier.page_repacks").value,
        )
        t0 = time.time()
        per_name, _states = analyze_cooperative(
            jobs, transaction_count=txs, execution_timeout=180
        )
        wall = time.time() - t0
        snap = {
            "bucket_classes": int(
                reg.gauge("frontier.bucket_classes").value or 0),
            "pad_waste_pct": float(
                reg.gauge("frontier.pad_waste_pct").value or 0.0),
            "pad_waste_single_bucket_pct": float(reg.gauge(
                "frontier.pad_waste_single_bucket_pct").value or 0.0),
            "page_faults": int(
                reg.counter("frontier.page_faults").value - before[0]),
            "page_repacks": int(
                reg.counter("frontier.page_repacks").value - before[1]),
        }
        return issue_set(per_name), wall, snap

    prev = (global_args.code_paging, global_args.frontier,
            global_args.frontier_force, global_args.frontier_width,
            global_args.pipeline)
    results = {}
    total_faults = 0
    try:
        global_args.probe_backend = "auto"
        global_args.frontier = True
        global_args.frontier_force = True  # tiny members: bypass gates
        global_args.frontier_width = 64
        global_args.pipeline = True
        # warm the XLA programs outside the timers
        one_run([("suicide", suicide)], 1, True)
        for name, jobs, txs, swcs in workloads:
            off_issues, off_wall, off_snap = one_run(jobs, txs, False)
            on_issues, on_wall, on_snap = one_run(jobs, txs, True)
            found = {s for _, s, _, _ in on_issues}
            assert swcs <= found, (
                f"{name}: paged run lost recall: wanted {swcs}, got {found}"
            )
            assert on_issues == off_issues, (
                f"{name}: bucket isolation / paging changed the issue set "
                f"(parity broken): {on_issues} != {off_issues}"
            )
            assert not off_snap["bucket_classes"], (
                f"{name}: --no-code-paging run still clustered bucket "
                f"classes: {off_snap}"
            )
            assert on_snap["bucket_classes"] > 1, (
                f"{name}: mixed-size batch did not split into >1 bucket "
                f"class: {on_snap}"
            )
            assert (on_snap["pad_waste_pct"]
                    < on_snap["pad_waste_single_bucket_pct"]), (
                f"{name}: per-class pad waste not below the single-bucket "
                f"counterfactual: {on_snap}"
            )
            total_faults += on_snap["page_faults"]
            results[name] = {
                "paged_wall_s": round(on_wall, 3),
                "unpaged_wall_s": round(off_wall, 3),
                "issues": len(on_issues),
                "identical_issue_sets": True,
                **on_snap,
            }
    finally:
        (global_args.code_paging, global_args.frontier,
         global_args.frontier_force, global_args.frontier_width,
         global_args.pipeline) = prev
    assert total_faults > 0, (
        "no workload ever page-faulted (the paged window never engaged "
        f"on the deep cold-jump outliers): {results}"
    )
    return {"metric": "paging_compare", "workloads": results}


def mesh_compare() -> dict:
    """Sharded-pipelined vs single-device parity across every mesh ×
    pipeline combination.

    Runs each workload four times with the device frontier forced on —
    ``--no-mesh``/``--no-pipeline`` toggled independently — and asserts the
    correctness contract: all four issue sets are IDENTICAL, the pipelined
    runs actually chained segments, and (with >1 attached device) the
    mesh runs really executed path-sharded with per-shard delta-pull bytes
    attributed to every shard.  This is the pod parity smoke CI runs under
    ``XLA_FLAGS=--xla_force_host_platform_device_count=8``; returns (and
    ``main`` prints) one JSON-able dict."""
    import jax

    from mythril_tpu.frontend.evmcontract import EVMContract
    from mythril_tpu.frontier import engine as _eng
    from mythril_tpu.frontier.stats import FrontierStatistics
    from mythril_tpu.observability import get_registry
    from mythril_tpu.support.support_args import args as global_args

    def issue_set(issues):
        return sorted((i.swc_id, i.address) for i in issues)

    n_dev = jax.device_count()
    suicide = bytes.fromhex("60003560e01c6341c0e1b51460145760006000fd5b33ff")
    workloads = [
        # (name, contract-or-code, tx_count, modules, recall swc)
        ("suicide", suicide, 1, ["AccidentallyKillable"], "106"),
        ("killbilly",
         EVMContract(code=KILLBILLY, creation_code=KILLBILLY_CREATION,
                     name="KillBilly"),
         3, ["AccidentallyKillable"], "106"),
    ]
    # (mesh, pipeline): the four escape-hatch combinations of the
    # acceptance contract, sharded-pipelined first
    combos = [(True, True), (True, False), (False, True), (False, False)]

    def one_run(target, txs, modules, mesh_on: bool, pipelined: bool):
        global_args.frontier_mesh = mesh_on
        global_args.pipeline = pipelined
        _clear_caches()
        # per-code slow/narrow verdicts and warm markers are deliberately
        # process-persistent; they must not leak control flow across modes
        _eng._SLOW_CODES.clear()
        _eng._NARROW_CODES.clear()
        _eng._SLOW_SEGMENTS.clear()
        reg = get_registry()
        reg.reset(prefix="pipeline.")
        fstats = FrontierStatistics()
        fstats.mesh_devices = 0
        t0 = time.time()
        _, issues = _analyze(target, 0x0901D12E, txs, modules=modules,
                             timeout=300)
        wall = time.time() - t0
        snap = {
            k: v
            for k, v in reg.snapshot().items()
            if k.startswith("pipeline.")
        }
        ttfe = _ttfe(issues, t0)
        return {
            "issues": issue_set(issues),
            "wall_s": round(wall, 3),
            "ttfe_s": round(ttfe, 3) if ttfe == ttfe else None,
            "mesh_devices": int(fstats.mesh_devices),
            "pipeline": snap,
        }

    prev = (global_args.pipeline, global_args.frontier_mesh,
            global_args.frontier, global_args.frontier_force,
            global_args.frontier_width)
    results = {}
    try:
        global_args.probe_backend = "auto"
        global_args.frontier = True
        global_args.frontier_force = True  # tiny contracts: bypass gates
        global_args.frontier_width = 64
        # warm every program variant outside the timers (the sharded and
        # single-device placements lower to different XLA programs)
        for mesh_on, pipelined in combos:
            one_run(suicide, 1, ["AccidentallyKillable"], mesh_on, pipelined)
        for name, target, txs, modules, swc in workloads:
            runs = {}
            for mesh_on, pipelined in combos:
                key = "mesh=%s,pipeline=%s" % (
                    "on" if mesh_on else "off",
                    "on" if pipelined else "off",
                )
                runs[key] = (
                    mesh_on, pipelined,
                    one_run(target, txs, modules, mesh_on, pipelined),
                )
            ref = runs["mesh=off,pipeline=on"][2]
            assert any(s == swc for s, _ in ref["issues"]), (
                f"{name}: single-device pipelined run lost recall: "
                f"{ref['issues']}"
            )
            for key, (mesh_on, pipelined, r) in runs.items():
                assert r["issues"] == ref["issues"], (
                    f"{name} [{key}]: issue set diverged: "
                    f"{r['issues']} != {ref['issues']}"
                )
                seg_p = r["pipeline"].get("pipeline.segments_pipelined", 0)
                if pipelined:
                    assert seg_p > 0, (
                        f"{name} [{key}]: pipelined run chained zero "
                        f"segments: {r['pipeline']}"
                    )
                else:
                    assert seg_p == 0, (
                        f"{name} [{key}]: --no-pipeline run still "
                        f"pipelined: {r['pipeline']}"
                    )
                if mesh_on and n_dev > 1:
                    assert r["mesh_devices"] == n_dev, (
                        f"{name} [{key}]: mesh run used "
                        f"{r['mesh_devices']} devices, expected {n_dev}"
                    )
                else:
                    assert r["mesh_devices"] == 0, (
                        f"{name} [{key}]: --no-mesh run placed on a mesh"
                    )
            if n_dev > 1:
                pod = runs["mesh=on,pipeline=on"][2]["pipeline"]
                assert pod.get("pipeline.delta_pulls", 0) > 0, (
                    f"{name}: sharded-pipelined run never delta-pulled: "
                    f"{pod}"
                )
                by_shard = pod.get(
                    "pipeline.delta_pull_bytes_by_shard", {}
                )
                assert len(by_shard) == n_dev and all(
                    v > 0 for v in by_shard.values()
                ), (
                    f"{name}: per-shard delta-pull attribution incomplete "
                    f"over {n_dev} devices: {by_shard}"
                )
            results[name] = {k: r for k, (_, _, r) in runs.items()}
    finally:
        (global_args.pipeline, global_args.frontier_mesh,
         global_args.frontier, global_args.frontier_force,
         global_args.frontier_width) = prev
    return {
        "metric": "mesh_compare",
        "n_devices": n_dev,
        "workloads": results,
    }


_HARVEST_PHASES = ("ingest", "solver", "replay", "commit")

# device-plane counters delta'd around every bench rep (persistent=True:
# they survive the per-analysis registry reset between runs)
_DEVICE_PLANE_COUNTERS = (
    "device.compile_wall_s_total",
    "device.recompiles_total",
    "device.shape_churn_total",
)


def harvest_compare() -> dict:
    """Sharded vs serial harvest on a multi-tx and a fork-heavy workload.

    Runs each workload twice with the device frontier forced on — once with
    the sharded harvest executor (``--harvest-workers 4``), once serial
    (``--harvest-workers 0``) — and asserts the correctness contract: the
    issue sets are IDENTICAL while the sharded run actually dispatched
    replays to the pool.  Reports per-mode walls, states/sec, the harvest
    wall share, and the per-phase ``frontier.harvest.*_s`` attribution that
    says where the remaining harvest time goes.  Returns (and ``main``
    prints) one JSON-able dict."""
    from mythril_tpu.frontend.evmcontract import EVMContract
    from mythril_tpu.frontier import engine as _eng
    from mythril_tpu.frontier.stats import FrontierStatistics
    from mythril_tpu.observability import get_registry
    from mythril_tpu.support.support_args import args as global_args

    def issue_set(issues):
        return sorted((i.swc_id, i.address) for i in issues)

    workloads = [
        # (name, contract-or-code, tx_count, modules, recall swc)
        ("killbilly",
         EVMContract(code=KILLBILLY, creation_code=KILLBILLY_CREATION,
                     name="KillBilly"),
         3, ["AccidentallyKillable"], "106"),
        # 256 concurrent fork-chained paths: the harvest-bound shape
        ("wide_fork", _wide_contract(8), 1, ["AccidentallyKillable"], "106"),
    ]

    def one_run(target, txs, modules, workers: int):
        global_args.harvest_workers = workers
        _clear_caches()
        _eng._SLOW_CODES.clear()
        _eng._NARROW_CODES.clear()
        _eng._SLOW_SEGMENTS.clear()
        reg = get_registry()
        fstats = FrontierStatistics()
        har_before = fstats.harvest_s
        phases_before = {
            p: reg.histogram("frontier.harvest.%s_s" % p).sum
            for p in _HARVEST_PHASES
        }
        sharded_before = reg.counter("frontier.harvest.sharded_paths").value
        t0 = time.time()
        sym, issues = _analyze(target, 0x0901D12E, txs, modules=modules,
                               timeout=300)
        wall = time.time() - t0
        phases = {
            p: round(
                reg.histogram("frontier.harvest.%s_s" % p).sum
                - phases_before[p], 4,
            )
            for p in _HARVEST_PHASES
        }
        return {
            "issues": issue_set(issues),
            "wall_s": round(wall, 3),
            "states_per_sec": round(sym.laser.total_states / wall, 1)
            if wall > 0 else 0.0,
            "harvest_share_pct": round(
                100 * (fstats.harvest_s - har_before) / wall, 1
            ) if wall > 0 else 0.0,
            "harvest_phase_s": phases,
            "sharded_paths": int(
                reg.counter("frontier.harvest.sharded_paths").value
                - sharded_before
            ),
        }

    prev = (global_args.harvest_workers, global_args.frontier,
            global_args.frontier_force, global_args.frontier_width)
    results = {}
    try:
        global_args.probe_backend = "auto"
        global_args.frontier = True
        global_args.frontier_force = True  # small contracts: bypass gates
        global_args.frontier_width = 64
        # warm the jitted programs outside the timers (both modes run the
        # SAME device program; only the host harvest differs)
        one_run(_wide_contract(4), 1, ["AccidentallyKillable"], 4)
        for name, target, txs, modules, swc in workloads:
            sharded = one_run(target, txs, modules, 4)
            serial = one_run(target, txs, modules, 0)
            assert any(s == swc for s, _ in sharded["issues"]), (
                f"{name}: sharded harvest lost recall: {sharded['issues']}"
            )
            assert sharded["issues"] == serial["issues"], (
                f"{name}: sharded harvest changed the issue set: "
                f"{sharded['issues']} != {serial['issues']}"
            )
            assert sharded["sharded_paths"] > 0, (
                f"{name}: sharded run never dispatched a replay shard"
            )
            assert serial["sharded_paths"] == 0, (
                f"{name}: serial run used the replay pool"
            )
            results[name] = {
                "sharded": sharded,
                "serial": serial,
                "speedup": round(
                    sharded["states_per_sec"]
                    / max(serial["states_per_sec"], 1e-9), 3,
                ),
            }
    finally:
        (global_args.harvest_workers, global_args.frontier,
         global_args.frontier_force, global_args.frontier_width) = prev
    return {"metric": "harvest_compare", "workloads": results}


# ---------------------------------------------------------------------------
# workloads
# ---------------------------------------------------------------------------


def _corpus_dir() -> Path:
    return REFERENCE_INPUTS if REFERENCE_INPUTS.is_dir() else LOCAL_INPUTS


def _read_runtime(path: Path) -> bytes:
    return bytes.fromhex(path.read_text().strip().replace("0x", ""))


class WorkloadSkip(Exception):
    """A workload's inputs are not mounted in this environment.  The driver
    drops the row (it never reaches the table, and the regression gate
    treats absent rows as skipped) instead of killing the whole suite —
    a corpus-less container still gets the synthetic rows and the gate."""


def wl_suicide(production: bool):
    _configure(production)
    path = _corpus_dir() / "suicide.sol.o"
    if not path.exists():  # fall back to the killbilly kill body
        code = bytes.fromhex("60003560e01c6341c0e1b51460145760006000fd5b33ff")
    else:
        code = _read_runtime(path)
    # the analysis completes in ~0.1-0.3 s, where scheduler jitter alone
    # swings single measurements 30%+: sum three consecutive analyses per
    # sample so the row's medians measure the engine, not the OS
    states, t0, ttfe = 0, time.time(), float("nan")
    for _ in range(3):
        _clear_caches()
        t_one = time.time()
        sym, issues = _analyze(code, 0x0901D12E, 1, modules=["AccidentallyKillable"])
        assert any(i.swc_id == "106" for i in issues), "suicide recall lost"
        states += sym.laser.total_states
        if ttfe != ttfe:
            ttfe = _ttfe(issues, t_one, "106")
    return states, time.time() - t0, ttfe


def wl_killbilly(production: bool):
    _configure(production)
    t0 = time.time()
    sym, issues, wall = run_analysis("auto" if production else "host")
    check_recall(issues)
    return sym.laser.total_states, wall, _ttfe(issues, t0, "106")


def wl_overflow(production: bool):
    _configure(production)
    states, t0 = 0, time.time()
    found = set()
    ran = 0
    ttfe = float("nan")
    for name in ("overflow.sol.o", "underflow.sol.o"):
        path = _corpus_dir() / name
        if not path.exists():
            continue
        ran += 1
        _clear_caches()
        t_file = time.time()
        sym, issues = _analyze(
            _read_runtime(path), 0x0901D12E, 2, modules=["IntegerArithmetics"]
        )
        states += sym.laser.total_states
        found |= {i.swc_id for i in issues}
        file_ttfe = _ttfe(issues, t_file, "101")
        if file_ttfe == file_ttfe and not ttfe == ttfe:
            ttfe = file_ttfe
    if ran:
        assert "101" in found, "integer overflow recall lost"
    return states, time.time() - t0, ttfe


def _wide_contract(n_branches: int) -> bytes:
    """n independent symbolic branches that immediately reconverge (2^n
    surviving paths) followed by an unprotected SELFDESTRUCT — the
    frontier-width workload the batched device interpreter is built for."""
    out = b""
    for k in range(n_branches):
        # PUSH1 k; CALLDATALOAD; PUSH1 1; AND; PUSH2 dest; JUMPI; JUMPDEST
        dest = len(out) + 10
        out += bytes([0x60, k, 0x35, 0x60, 0x01, 0x16,
                      0x61, (dest >> 8) & 0xFF, dest & 0xFF, 0x57, 0x5B])
    return out + bytes([0x33, 0xFF])  # CALLER; SELFDESTRUCT


def wl_wide_frontier(production: bool):
    """1024 concurrent paths, the batched device interpreter's home turf:
    the whole state space executes as ONE device segment at width 1024."""
    from mythril_tpu.support.support_args import args

    global _wide_warmed
    _configure(production)
    old_width = args.frontier_width
    if production:
        args.frontier_width = 1024
        # device-only efficiency block (VERDICT r4 #7): first productive
        # segment measures pure compute via chained-dispatch subtraction
        args.frontier_microbench = True
        if not _wide_warmed:
            # warmup outside the timers: the segment program compiles once
            # per (caps, size bucket) (persistently cached when the XLA
            # cache cooperates) — a one-time cost that would swamp this
            # workload; once per process, not per rep
            _clear_caches()
            _analyze(
                _wide_contract(10), 0x0901D12E, 1,
                modules=["AccidentallyKillable"], timeout=300,
            )
            _wide_warmed = True
    try:
        _clear_caches()
        from mythril_tpu.frontier.stats import FrontierStatistics

        fstats = FrontierStatistics()
        dev_before = fstats.device_instructions
        har_before = fstats.harvest_s
        mid_before = _mid_counters(fstats)
        code = _wide_contract(10)  # 1024 concurrent paths
        t0 = time.time()
        sym, issues = _analyze(
            code, 0x0901D12E, 1, modules=["AccidentallyKillable"], timeout=300
        )
        wall = time.time() - t0
        # residency/harvest/mid-frame over the TIMED run only (the warm-up
        # above also runs device segments and harvests)
        dev_delta = fstats.device_instructions - dev_before
        har_delta = fstats.harvest_s - har_before
        mid_delta = _mid_delta(fstats, mid_before)
    finally:
        args.frontier_width = old_width
        args.frontier_microbench = False
    assert any(i.swc_id == "106" for i in issues), "wide-frontier recall lost"
    return (
        sym.laser.total_states, wall, _ttfe(issues, t0, "106"),
        dev_delta if production else None,
        har_delta if production else None,
        float("nan"),  # no ttfr channel for this workload
        mid_delta if production else None,
    )


# if (calldataload(0) == 5) storage[0] = 1 else storage[0] = 2
_FLIP_CODE = "600035600514600f576002600055005b600160005500"
_FLIP_JUMPI = 8


def wl_concolic(production: bool):
    _configure(production)
    _clear_caches()  # both configs must solve the flip from scratch
    from mythril_tpu.concolic.concolic_execution import concolic_execution

    contract = "0x" + "ab" * 20
    data = {
        "initialState": {
            "accounts": {
                contract: {
                    "balance": "0x0",
                    "code": "0x" + _FLIP_CODE,
                    "nonce": 0,
                    "storage": {},
                }
            }
        },
        "steps": [
            {
                "address": contract,
                "blockCoinbase": "0x" + "00" * 20,
                "blockDifficulty": "0x0",
                "blockGasLimit": "0x989680",
                "blockNumber": "0x1",
                "blockTime": "0x1",
                "gasLimit": "0x100000",
                "gasPrice": "0x0",
                "input": "0x" + "00" * 32,
                "origin": "0x" + "cd" * 20,
                "value": "0x0",
            }
        ],
    }
    t0 = time.time()
    flips = 0
    for _ in range(3):
        _clear_caches()  # every rep must solve the flip from scratch
        results = concolic_execution(data, [_FLIP_JUMPI], solver_timeout=30000)
        assert len(results) == 1, "branch flip failed"
        word = int(results[0]["steps"][0]["input"][2:66].ljust(64, "0"), 16)
        assert word == 5, "flipped input does not take the other branch"
        flips += 1
    return flips, time.time() - t0, float("nan")


def wl_bectoken(production: bool):
    """BECToken batchTransfer (CVE-2018-10299, BASELINE.md config 3's real
    shape): a hand-assembled ERC20 with the unchecked ``cnt * _value``
    multiply, SafeMath everywhere else, keccak-mapped balances and a
    symbolic-length receiver loop (bench_contracts.py — no solc in the
    image, matching /root/reference/solidity_examples/BECToken.sol:255-268).
    Width comes from the dispatcher x requires x loop x 2-tx crossing."""
    from bench_contracts import bectoken_like

    _configure(production)  # production width 256 = the warmed bucket
    _clear_caches()
    t0 = time.time()
    sym, issues = _analyze(
        bectoken_like(), 0x0901D12E, 2,
        modules=["IntegerArithmetics"], timeout=120,
    )
    assert any(i.swc_id == "101" for i in issues), "batchTransfer recall lost"
    return sym.laser.total_states, time.time() - t0, _ttfe(issues, t0, "101")


def _largecode_contract(n_pad: int = 1500) -> bytes:
    """A creation-heavy-shaped outlier: selector dispatch to a reachable
    CALLER;SELFDESTRUCT whose JUMPDEST sits BEYOND a long straight-line
    pad tail (``n_pad`` PUSH1/POP pairs, ~``2*n_pad`` instructions).  The
    instruction count blows past the smallest size bucket (and, at the
    default residency budget, past the paged window), so the vulnerable
    jump is a cold-page jump: exactly the shape that inflated
    bectoken_batch's shared bucket in BENCH_r19."""
    sel = 0x41C0E1B5  # kill()
    tail = bytes([0x60, 0x00, 0x50]) * n_pad + bytes([0x00])  # pads + STOP
    dest = 16 + len(tail)
    assert dest < 0x10000
    head = bytes([
        0x60, 0x00, 0x35,                     # PUSH1 0; CALLDATALOAD
        0x60, 0xE0, 0x1C,                     # PUSH1 0xE0; SHR
        0x63, (sel >> 24) & 0xFF, (sel >> 16) & 0xFF,
        (sel >> 8) & 0xFF, sel & 0xFF,        # PUSH4 kill()
        0x14,                                 # EQ
        0x61, (dest >> 8) & 0xFF, dest & 0xFF,  # PUSH2 dest
        0x57,                                 # JUMPI
    ])
    assert len(head) == 16
    return head + tail + bytes([0x5B, 0x33, 0xFF])  # JUMPDEST;CALLER;SELFDESTRUCT


def wl_largecode(production: bool):
    """Large-code mixed batch: one pad-tail outlier (~3000 instructions)
    next to three small real-shape codes in ONE cooperative batch — the
    corpus shape whose shared size bucket collapsed bectoken_batch in
    BENCH_r19.  Production runs with bucket isolation + packed-code
    paging on (the defaults); baseline is the sequential host schedule.
    Recall asserted on the outlier's deep SELFDESTRUCT (the cold-page
    jump) and on the small members."""
    from bench_contracts import rubixi_like

    suicide = bytes.fromhex("60003560e01c6341c0e1b51460145760006000fd5b33ff")
    gated = bytes.fromhex(
        "60003580600a9010600c57005b80600514601c5780601414601c57005b33ff"
    )
    jobs = [
        ("bigkill", _largecode_contract()),
        ("suicide", suicide),
        ("gated", gated),
        ("rubixi", rubixi_like()),
    ]
    expected = {"bigkill": "106", "suicide": "106", "rubixi": "105"}

    _configure(production)
    if production:
        from mythril_tpu.support.support_args import args

        args.frontier_force = True  # tiny members: bypass the narrow gate
        try:
            (per_name, states, wall, t0, dev_delta, har_delta,
             mid_delta) = _cooperative_timed_run(jobs, "largecode_mixed")
        finally:
            args.frontier_force = False
    else:
        per_name = {}
        states = 0
        t0 = time.time()
        for name, code in jobs:
            _clear_caches()
            sym, issues = _analyze(code, 0x0901D12E, 2, timeout=120)
            states += sym.laser.total_states
            per_name[name] = issues
        wall = time.time() - t0
        dev_delta = har_delta = mid_delta = None

    for name, swc in expected.items():
        got = {i.swc_id for i in per_name.get(name, [])}
        assert swc in got, (
            f"largecode_mixed recall lost: {name} missing SWC-{swc}"
        )
    all_issues = [i for iss in per_name.values() for i in iss]
    ttfe = _ttfe(
        [i for i in all_issues if i.swc_id in set(expected.values())], t0
    )
    return (
        states, wall, ttfe, dev_delta, har_delta,
        _ttfr(per_name, t0, expected), mid_delta,
    )


# The real-bytecode device flagship (VERDICT r4 #4): the call-free solc
# contracts run as ONE cooperative multi-code batch with multi-selector
# seeding (core/transaction/symbolic.seed_message_call) — the work list
# starts |selectors|+1 wide per contract, so the width-256 device segment
# is saturated with REAL solc dispatch/require/arithmetic code from the
# first round.  Call-free members only: CALL-family ops park semantically,
# and this row's point is device residency on real bytecode.
WIDE_SOLC_NAMES = [
    "underflow.sol.o",
    "overflow.sol.o",
    "ether_send.sol.o",
    "exceptions.sol.o",
    "metacoin.sol.o",
    "origin.sol.o",
    "suicide.sol.o",
    "safe_funcs.sol.o",
    "environments.sol.o",
    "symbolic_exec_bytecode.sol.o",
]
WIDE_SOLC_RECALL = {
    "underflow.sol.o": "101",
    "overflow.sol.o": "101",
    "ether_send.sol.o": "105",
    "exceptions.sol.o": "110",
    "metacoin.sol.o": "101",
    "origin.sol.o": "115",
    "suicide.sol.o": "106",
    "safe_funcs.sol.o": "110",
    "environments.sol.o": "101",
}

_coop_warmed: set = set()


def _cooperative_timed_run(jobs, bucket_key: str, timeout: int = 120):
    """Warm this job set's segment-program bucket once per process (outside
    any timer), then run the tx-2 cooperative analysis timed.  Returns
    (per_name, states, wall, t0, dev_delta, har_delta, mid_delta) with the
    telemetry deltas covering the TIMED run only."""
    from mythril_tpu.analysis.cooperative import analyze_cooperative
    from mythril_tpu.frontier.stats import FrontierStatistics

    if bucket_key not in _coop_warmed:
        _clear_caches()
        analyze_cooperative(jobs, transaction_count=1, execution_timeout=20)
        _coop_warmed.add(bucket_key)
    _clear_caches()
    fstats = FrontierStatistics()
    dev_before = fstats.device_instructions
    har_before = fstats.harvest_s
    mid_before = _mid_counters(fstats)
    t0 = time.time()
    per_name, states = analyze_cooperative(
        jobs, transaction_count=2, execution_timeout=timeout
    )
    wall = time.time() - t0
    return (
        per_name, states, wall, t0,
        fstats.device_instructions - dev_before,
        fstats.harvest_s - har_before,
        _mid_delta(fstats, mid_before),
    )


def wl_wide_solc(production: bool):
    """Wide frontier from REAL solc bytecode (the answer to 'the flagship
    win is synthetic').  Baseline: the reference's natural schedule — one
    contract at a time, single symbolic seed, host engine.  Production: one
    cooperative device batch over the same contracts with the selector
    space partitioned per seed.  Same issues must be found either way
    (asserted per contract); states/sec at equal recall is the metric."""
    from mythril_tpu.support.support_args import args

    corpus_dir = _corpus_dir()
    jobs = [
        (n, _read_runtime(corpus_dir / n))
        for n in WIDE_SOLC_NAMES
        if (corpus_dir / n).exists()
    ]
    if len(jobs) < 4:
        raise WorkloadSkip("wide_solc corpus inputs not mounted")
    expected = {n: swc for n, swc in WIDE_SOLC_RECALL.items()
                if any(n == name for name, _ in jobs)}

    _configure(production)
    if production:
        args.multi_selector_seeding = True
        try:
            (per_name, states, wall, t0, dev_delta, har_delta,
             mid_delta) = _cooperative_timed_run(jobs, "wide_solc")
        finally:
            args.multi_selector_seeding = False
    else:
        per_name = {}
        states = 0
        t0 = time.time()
        for name, code in jobs:
            _clear_caches()
            sym, issues = _analyze(code, 0x0901D12E, 2, timeout=120)
            states += sym.laser.total_states
            per_name[name] = issues
        wall = time.time() - t0
        dev_delta = har_delta = mid_delta = None

    for name, swc in expected.items():
        got = {i.swc_id for i in per_name.get(name, [])}
        assert swc in got, f"wide_solc recall lost: {name} missing SWC-{swc}"
    all_issues = [i for iss in per_name.values() for i in iss]
    ttfe = _ttfe(
        [i for i in all_issues if i.swc_id in set(expected.values())], t0
    )
    return (
        states, wall, ttfe, dev_delta, har_delta,
        _ttfr(per_name, t0, expected), mid_delta,
    )


# known-vulnerable subset of the corpus: file -> SWC id that must be found
CORPUS_RECALL = {
    "suicide.sol.o": "106",
    "overflow.sol.o": "101",
    "underflow.sol.o": "101",
    "ether_send.sol.o": "105",
    "origin.sol.o": "115",
    "exceptions.sol.o": "110",
    # hand-assembled real exploit shapes (bench_contracts.py): the
    # etherstore reentrancy window and rubixi's ownership-takeover drain
    # run as ordinary corpus members in BOTH schedulings
    "etherstore.asm": "107",
    "rubixi.asm": "105",
}


def _assembled_corpus():
    """Real-shape members assembled in-repo (no solc in the image):
    (name, runtime bytecode) pairs matching the reference contracts at
    /root/reference/solidity_examples/{etherstore,rubixi}.sol."""
    from bench_contracts import etherstore_like, rubixi_like

    return [
        ("etherstore.asm", etherstore_like()),
        ("rubixi.asm", rubixi_like()),
    ]


_wide_warmed = False


def _ttfr(per_name, t0: float, expected=None) -> float:
    """Time-to-FULL-recall: wall seconds until EVERY expected corpus
    exploit has been discovered (max over contracts of the earliest
    matching stamp).  First-exploit TTFE structurally favors the
    sequential schedule (contract #1 confirms before contract #2 even
    starts); full recall is what a corpus user actually waits for, and is
    where the cooperative lockstep schedule can win."""
    from mythril_tpu.analysis.report import StartTime

    if expected is None:
        expected = CORPUS_RECALL
    base = StartTime().global_start_time
    latest = None
    for name, swc in expected.items():
        issues = per_name.get(name)
        if issues is None:
            continue  # contract lives on another shard
        stamps = [i.discovery_time for i in issues if i.swc_id == swc]
        if not stamps:
            return float("nan")
        first = min(stamps)
        latest = first if latest is None else max(latest, first)
    if latest is None:
        return float("nan")
    return _rebase_stamp(base + latest, t0)


def _mid_counters(fstats):
    return (
        fstats.mid_injections,
        fstats.mid_encode_failures,
        fstats.semantic_parks,
    )


def _mid_delta(fstats, before):
    after = _mid_counters(fstats)
    return tuple(a - b for a, b in zip(after, before))


def _rebase_stamp(wall: float, t0: float, eps: float = 0.05) -> float:
    """Rebase an absolute discovery stamp against this run's start.  A stamp
    meaningfully BEFORE t0 means the issue was served from a warm/cache path
    rather than discovered by this run — report NaN so the measurement bug
    surfaces instead of a silent perfect 0s."""
    delta = wall - t0
    if delta < -eps:
        return float("nan")
    return max(0.0, delta)


def wl_corpus(production: bool):
    """THE HEADLINE: the whole reference corpus.  Baseline analyzes one
    contract at a time (the reference's corpus flow, mythril_analyzer.py:
    138-175); production runs this shard's slice COOPERATIVELY — lockstep tx
    rounds whose combined seeds execute as one wide multi-code device
    segment (analysis/cooperative.py).  Recall is asserted over the UNION of
    shard findings (single-host: everything; multi-host launches return
    shard-local findings for the driver to union via assert_corpus_recall)."""
    _configure(production)
    from mythril_tpu.parallel.corpus import (
        assert_corpus_recall,
        run_corpus,
        shard_corpus,
        shard_identity,
    )

    corpus = sorted(p for g in CORPUS_GLOBS for p in _corpus_dir().glob(g))
    if not corpus:
        raise WorkloadSkip("no corpus inputs found")
    all_issues = []

    if production:
        mine = shard_corpus([str(p) for p in corpus])
        jobs = [(Path(p).name, _read_runtime(Path(p))) for p in mine]
        if shard_identity()[0] == 0:
            jobs += _assembled_corpus()
        (issues_by_name, states, wall, t0, dev_delta, har_delta,
         mid_delta) = _cooperative_timed_run(jobs, "corpus", timeout=60)
        findings = [
            (name, {i.swc_id for i in issues})
            for name, issues in issues_by_name.items()
        ]
        all_issues = [i for iss in issues_by_name.values() for i in iss]
    else:
        totals = {"states": 0}
        issue_lists = {}

        def analyze_one(path):
            _clear_caches()
            sym, issues = _analyze(
                _read_runtime(Path(path)), 0x0901D12E, 2, timeout=60
            )
            totals["states"] += sym.laser.total_states
            issue_lists[Path(path).name] = issues
            return {i.swc_id for i in issues}

        t0 = time.time()
        results = run_corpus([str(p) for p in corpus], analyze_one)
        findings = [(Path(p).name, res) for p, res in results]
        # the assembled real shapes run sequentially here exactly like the
        # file-backed members do (one contract at a time, the reference's
        # corpus flow); shard 0 only, mirroring the production branch
        assembled = _assembled_corpus() if shard_identity()[0] == 0 else []
        for name, code in assembled:
            _clear_caches()
            sym, issues = _analyze(code, 0x0901D12E, 2, timeout=60)
            totals["states"] += sym.laser.total_states
            issue_lists[name] = issues
            findings.append((name, {i.swc_id for i in issues}))
        wall = time.time() - t0
        states = totals["states"]
        all_issues = [i for iss in issue_lists.values() for i in iss]

    _idx, cnt = shard_identity()
    shard_names = {name for name, _ in findings}
    expected = (
        CORPUS_RECALL
        if cnt == 1
        # multi-host: this process can only vouch for its own slice; the
        # launcher unions the returned findings via assert_corpus_recall
        else {k: v for k, v in CORPUS_RECALL.items() if k in shard_names}
    )
    assert_corpus_recall([findings], expected)
    ttfe = _ttfe(
        [i for i in all_issues if i.swc_id in set(CORPUS_RECALL.values())], t0
    )
    per_name = issues_by_name if production else issue_lists
    return (
        states,
        wall,
        ttfe,
        (dev_delta if production else None),
        (har_delta if production else None),
        _ttfr(per_name, t0),
        (mid_delta if production else None),
    )


def serve_load(clients: int = 8, workers: int = 1) -> dict:
    """Analysis-as-a-service under synthetic traffic (bench.py --serve-load).

    ``clients`` concurrent submitters cycle over a small in-repo contract
    set (duplicates by construction, so admission dedup is exercised) and
    the run asserts the service's three production claims:

    1. determinism — every request's issue-digest multiset is bit-identical
       to a solo one-shot run of the same contract under the same options;
    2. throughput — the warm process serving all requests concurrently
       beats sequential one-shot submission of the SAME requests;
    3. dedup — duplicate submissions share one analysis (dedup_hits > 0).

    Emits a ``workloads.serve_load`` row (requests/sec + service ttfe_s)
    shaped exactly like the suite's rows, so ``--against`` gates service
    throughput and TTFE with zero gate changes.

    With ``workers > 1`` a second measured window replays the SAME
    traffic against a horizontal pool of N worker processes and emits a
    ``workloads.serve_pool`` row: baseline = the single-worker rate just
    measured, production = the pool rate.  Digest identity to solo runs
    is asserted unconditionally; the speedup assertion is gated on
    ``os.cpu_count()`` — on a single-core container N processes time-
    slice one core and the pool physically cannot exceed 1x, so the
    scaling claim is only *asserted* where the hardware can express it
    (the same CPU-CI caveat the frontier rows carry).
    """
    import tempfile
    import threading

    from mythril_tpu.analysis.cooperative import run_cooperative_batch
    from mythril_tpu.facade.warm import reset_analysis_scope
    from mythril_tpu.observability.metrics import get_registry
    from mythril_tpu.service import (
        AnalysisOptions,
        AnalysisService,
        ServiceConfig,
    )
    from mythril_tpu.service.codehash import issue_digest
    from bench_contracts import etherstore_like, rubixi_like

    opts = AnalysisOptions(transaction_count=2, execution_timeout=60)
    contracts = [
        ("killbilly", bytes.fromhex(KILLBILLY)),
        ("etherstore", etherstore_like()),
        ("rubixi", rubixi_like()),
        ("wide4", _wide_contract(4)),
    ]
    # clients cycle over the contract set: with clients > len(contracts)
    # the duplicate-submission path is exercised by construction
    requests = [
        (f"client{i}", *contracts[i % len(contracts)],
         "interactive" if i % 4 == 0 else "batch")
        for i in range(clients)
    ]

    # Host engine on BOTH sides: this bench host simulates device segments
    # with wall-clock linear in batch width, so pooling contracts into wide
    # shared segments is a pessimization HERE (it is the win on real
    # hardware, and the frontier workloads above measure it).  Pinning the
    # host path isolates what serve-load is actually testing — the service
    # layer: admission dedup, warm-process reuse, shared scheduling — under
    # an identical engine for baseline and production.
    _configure(False)

    # -- solo ground truth + sequential one-shot baseline ---------------
    # each request is submitted as its own cold one-shot analysis (the
    # pre-service corpus flow): per-request cache clear, one contract,
    # one run.  The XLA compile cache cannot be un-warmed in-process,
    # which only FLATTERS this baseline — the warm-vs-sequential margin
    # below is therefore conservative.
    solo_digests = {}
    solo_ttfes = []
    t_seq = time.perf_counter()
    for _client, cname, code, _tier in requests:
        _clear_caches()
        reset_analysis_scope()
        t0 = time.time()
        issues_by_name, errors, _states = run_cooperative_batch(
            [(cname, code)],
            transaction_count=opts.transaction_count,
            execution_timeout=opts.execution_timeout,
            strategy=opts.strategy,
            isolate_errors=False,
        )
        assert not errors, f"solo run failed: {errors}"
        issues = issues_by_name[cname]
        solo_digests.setdefault(
            cname, sorted(issue_digest(i) for i in issues)
        )
        ttfe = _ttfe(issues, t0)
        if ttfe == ttfe:  # not NaN
            solo_ttfes.append(ttfe)
    seq_wall = time.perf_counter() - t_seq

    # -- warm service under concurrent traffic ---------------------------
    _clear_caches()
    reset_analysis_scope()
    # request-scoped telemetry rides the measured window with the tracer
    # ON: the determinism assertion below then doubles as proof that
    # per-request span trees and phase accounting never perturb findings
    from mythril_tpu.observability.tracer import get_tracer
    from mythril_tpu.service.telemetry import PHASES as _SERVICE_PHASES

    reg = get_registry()
    for _p in _SERVICE_PHASES:
        reg.histogram(f"service.{_p}_s", persistent=True).reset()
    tracer = get_tracer()
    tracer.reset()
    tracer.enabled = True
    # the watchtower rides the measured window: its SLO verdicts land in
    # the row (the --against gate fails on breaches) and its tick cost is
    # held to the tracing budget.  Targets are CPU-CI-scaled so a clean
    # run reports zero breaches; a real service regression still trips
    # them.  Breach profiling is off — a profiler window inside the
    # measured window would perturb the rate being measured.
    slo_path = os.path.join(
        tempfile.mkdtemp(prefix="bench-slo-"), "slo.json"
    )
    with open(slo_path, "w") as f:
        json.dump({
            "interval_s": 0.5,
            "capture": {"profile": False},
            "objectives": [
                {"name": "ttfe_p95", "kind": "quantile",
                 "metric": "service.ttfe_s", "q": 0.95, "target": 30.0,
                 "fast_window_s": 60, "slow_window_s": 600},
                {"name": "queue_wait_p95", "kind": "quantile",
                 "metric": "service.queue_wait_s", "q": 0.95, "target": 60.0,
                 "fast_window_s": 60, "slow_window_s": 600},
                {"name": "error_rate", "kind": "ratio",
                 "metric": "service.request_errors",
                 "denominator": "service.requests", "target": 0.05,
                 "min_count": 4},
            ],
        }, f)
    slo_breaches_base = int(
        reg.counter("slo.breaches_total", persistent=True).snapshot() or 0
    )
    service = AnalysisService(ServiceConfig(
        default_options=opts,
        max_batch_width=max(clients, 1),
        batch_window_s=0.25,
        frontier=False,  # same engine as the baseline (comment above)
        probe=True,
        warmup=True,
        # pinned explicitly, NOT defaulted: this window is the
        # single-worker comparison leg, and the speedup attribution
        # (sequential vs warm, single vs pool) must stay honest even if
        # ServiceConfig's default worker count ever changes
        workers=1,
        watchtower=True,
        slo_file=slo_path,
    )).start()
    # NOTE: BENCH_INJECT_ADMISSION_SLEEP (the phase-gate fault hook) is
    # honored by AnalysisService.submit itself now, so the injected stall
    # lands inside the TTFE/queue-wait budgets the watchtower holds.
    # warmup is startup cost, not steady-state throughput: the timed
    # window starts from a warm process (the daemon's operating point)
    service.wait_warm(timeout=120)
    per_request = []
    lock = threading.Lock()

    def _submit(client: str, cname: str, code: bytes, tier: str) -> None:
        t0 = time.perf_counter()
        _req, stream, deduped = service.submit(code, name=client, tier=tier,
                                               tenant=client)
        first_issue = None
        issues = None
        for kind, payload in stream.events(timeout=600):
            if kind == "issue" and first_issue is None:
                first_issue = time.perf_counter() - t0
            elif kind == "error":
                raise AssertionError(f"{client}: {payload}")
            elif kind == "done":
                issues = payload["issues"]
        with lock:
            per_request.append({
                "client": client,
                "contract": cname,
                "tier": tier,
                "deduped": deduped,
                "n_issues": len(issues),
                "ttfe_s": round(first_issue, 3) if first_issue else None,
                "digests": sorted(issue_digest(i) for i in issues),
            })

    t_warm = time.perf_counter()
    threads = [
        threading.Thread(target=_submit, args=req, daemon=True)
        for req in requests
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=900)
    warm_wall = time.perf_counter() - t_warm
    drained = service.stop(drain=True, timeout=60)
    tracer.enabled = False
    request_span_count = sum(
        1 for s in tracer.spans() if s["name"] == "service.request"
    )
    tracer.reset()

    # -- the three production claims ------------------------------------
    assert len(per_request) == clients, (
        f"only {len(per_request)}/{clients} requests completed"
    )
    mismatches = [
        r["client"]
        for r in per_request
        if r["digests"] != solo_digests[r["contract"]]
    ]
    identical = not mismatches
    reg = get_registry()
    dedup_hits = int(reg.counter("service.dedup_hits", persistent=True).snapshot())
    seq_rps = clients / seq_wall if seq_wall else 0.0
    warm_rps = clients / warm_wall if warm_wall else 0.0
    service_ttfes = [
        r["ttfe_s"] for r in per_request if r["ttfe_s"] is not None
    ]

    # -- optional second window: N-worker process pool --------------------
    pool_result = None
    if workers > 1:
        pool_result = _serve_pool_window(
            requests, opts, solo_digests, workers, warm_rps
        )

    row = {
        "unit": "requests/sec",
        "baseline": round(seq_rps, 3),
        "production": round(warm_rps, 3),
        "speedup": round(warm_rps / seq_rps, 3) if seq_rps else None,
        "reps": 1,
        "spread": {
            "baseline": [round(seq_rps, 3)] * 2,
            "production": [round(warm_rps, 3)] * 2,
        },
        "spread_n": {"baseline": 1, "production": 1},
        "ttfe_s": {
            "baseline": round(_median(solo_ttfes), 3) if solo_ttfes else None,
            "production": (
                round(_median(service_ttfes), 3) if service_ttfes else None
            ),
        },
    }
    # per-phase service latency percentiles (queue-wait/execute/stream
    # decomposition from the request telemetry plane) — the --against
    # gate asserts these, so an admission or streaming regression fails
    # CI like a production-rate regression does
    phase_row = {}
    for _p in _SERVICE_PHASES:
        h = reg.histogram(f"service.{_p}_s", persistent=True)
        if h.count:
            phase_row[_p] = {
                "count": h.count,
                "p50": round(h.percentile(0.50), 4),
                "p95": round(h.percentile(0.95), 4),
            }
    row["service_phase_s"] = phase_row
    # per-workload prefilter kill rate on this corpus-like traffic (the
    # daemon mirrors the scoped counters into service.prefilter_*)
    pf_eval = int(reg.counter(
        "service.prefilter_evaluated", persistent=True).snapshot() or 0)
    pf_kill = int(reg.counter(
        "service.prefilter_killed", persistent=True).snapshot() or 0)
    row["prefilter"] = {
        "evaluated": pf_eval,
        "killed": pf_kill,
        "kill_rate": round(pf_kill / pf_eval, 4) if pf_eval else 0.0,
    }
    ds_adm = int(reg.counter(
        "service.devsolver_admitted", persistent=True).snapshot() or 0)
    ds_dec = int(reg.counter(
        "service.devsolver_decided_sat", persistent=True).snapshot() or 0
    ) + int(reg.counter(
        "service.devsolver_decided_unsat", persistent=True).snapshot() or 0)
    row["devsolver"] = {
        "admitted": ds_adm,
        "decided": ds_dec,
        "decide_rate": round(ds_dec / ds_adm, 4) if ds_adm else 0.0,
    }
    # SLO verdict for the measured window: the watchtower rode the warm
    # window above, so breaches here ARE service regressions (the counter
    # is persistent — the base snapshot isolates this window's delta)
    slo_breaches = int(
        reg.counter("slo.breaches_total", persistent=True).snapshot() or 0
    ) - slo_breaches_base
    wt = getattr(service, "watchtower", None)
    slo_ok = slo_breaches == 0
    row["slo"] = {
        "ok": slo_ok,
        "breaches": slo_breaches,
        "objectives": len(wt.objectives) if wt is not None else 0,
        "overhead_pct": (
            round(wt.overhead_pct(), 3) if wt is not None else None
        ),
    }
    row["slo_ok"] = slo_ok
    passed = (identical and dedup_hits > 0 and warm_rps > seq_rps
              and drained and slo_ok)
    if pool_result is not None:
        passed = passed and pool_result["pass"]
    result = {
        "metric": "serve_load_requests_per_sec",
        "value": row["production"],
        "clients": clients,
        "unique_contracts": len(contracts),
        "sequential_wall_s": round(seq_wall, 2),
        "warm_wall_s": round(warm_wall, 2),
        "dedup_hits": dedup_hits,
        "identical_issue_sets": identical,
        **({"mismatched_clients": mismatches} if mismatches else {}),
        "drained": drained,
        "request_spans": request_span_count,
        "per_request": [
            {k: v for k, v in r.items() if k != "digests"}
            for r in sorted(per_request, key=lambda r: r["client"])
        ],
        "workloads": {"serve_load": row},
        "service_counters": {
            k: v
            for k, v in get_registry().snapshot().items()
            if k.startswith("service.")
        },
        "pass": passed,
    }
    if pool_result is not None:
        result["workers"] = workers
        result["serve_pool"] = {
            k: v for k, v in pool_result.items() if k != "row"
        }
        result["workloads"]["serve_pool"] = pool_result["row"]
    return result


def _serve_pool_window(requests, opts, solo_digests, workers: int,
                       single_rps: float) -> dict:
    """Replay ``requests`` against an N-worker process pool; return the
    ``serve_pool`` row plus its assertion verdicts.

    Digest identity to solo runs is asserted unconditionally (process
    isolation must never change findings).  The window runs with the
    fleet fabric ON — worker tracers enabled, delta flushes riding the
    event multiplex — so the identity assertion doubles as proof the
    cross-process telemetry never perturbs findings.  The scaling
    assertion is hardware-gated: N spawned engine processes cannot beat
    one worker on a single core, so the >= 2x claim (--workers 4,
    8 clients) is only enforced when this host has the cores to express
    it.
    """
    import threading

    from mythril_tpu.facade.warm import reset_analysis_scope
    from mythril_tpu.observability.tracer import get_tracer
    from mythril_tpu.service import AnalysisService, ServiceConfig
    from mythril_tpu.service.codehash import issue_digest

    _clear_caches()
    reset_analysis_scope()
    clients = len(requests)
    tracer = get_tracer()
    tracer.reset()
    tracer.enabled = True
    service = AnalysisService(ServiceConfig(
        default_options=opts,
        # cap batch width so admitted work fans out across workers
        # instead of piling into one maximal shared batch
        max_batch_width=max(1, (clients + workers - 1) // workers),
        batch_window_s=0.05,
        frontier=False,
        probe=True,
        warmup=True,
        workers=workers,
        trace=True,
        flush_interval_s=0.25,
    )).start()
    assert service.wait_warm(timeout=300), "worker pool never became ready"
    per_request = []
    lock = threading.Lock()

    def _submit(client, cname, code, tier):
        _req, stream, deduped = service.submit(code, name=client, tier=tier,
                                               tenant=client)
        issues = None
        for kind, payload in stream.events(timeout=600):
            if kind == "error":
                raise AssertionError(f"pool {client}: {payload}")
            if kind == "done":
                issues = payload["issues"]
        with lock:
            per_request.append({
                "client": client,
                "contract": cname,
                "deduped": deduped,
                "digests": sorted(issue_digest(i) for i in issues),
            })

    t0 = time.perf_counter()
    threads = [
        threading.Thread(target=_submit, args=req, daemon=True)
        for req in requests
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=900)
    pool_wall = time.perf_counter() - t0
    stats = service.stats()
    fleet = service.fleet.summary()
    drained = service.stop(drain=True, timeout=60)
    tracer.enabled = False
    tsum = tracer.summary()
    foreign_spans = int(tsum.get("foreign_spans", 0) or 0)
    tracer.reset()
    workers_reporting = len(fleet.get("workers") or {})

    assert len(per_request) == clients, (
        f"only {len(per_request)}/{clients} pool requests completed"
    )
    mismatches = [
        r["client"] for r in per_request
        if r["digests"] != solo_digests[r["contract"]]
    ]
    identical = not mismatches
    pool_rps = clients / pool_wall if pool_wall else 0.0
    speedup = round(pool_rps / single_rps, 3) if single_rps else None
    cpus = os.cpu_count() or 1
    # hardware-gated scaling assertion (see docstring)
    if cpus >= max(4, workers) and workers >= 4:
        target = 2.0
    elif cpus >= 2:
        target = 1.0
    else:
        target = None  # single core: record, don't assert
    scaling_ok = (
        True if target is None
        else (speedup or 0.0) >= target
    )
    restarts = int(stats.get("service.worker_restarts") or 0)
    # every worker must have reported over the fabric during the window
    fleet_ok = workers_reporting == workers and foreign_spans > 0
    passed = (identical and drained and scaling_ok and restarts == 0
              and fleet_ok)
    row = {
        "unit": "requests/sec",
        "baseline": round(single_rps, 3),
        "production": round(pool_rps, 3),
        "speedup": speedup,
        "reps": 1,
        "spread": {
            "baseline": [round(single_rps, 3)] * 2,
            "production": [round(pool_rps, 3)] * 2,
        },
        "spread_n": {"baseline": 1, "production": 1},
    }
    return {
        "row": row,
        "workers": workers,
        "cpu_count": cpus,
        "pool_wall_s": round(pool_wall, 2),
        "identical_issue_sets": identical,
        **({"mismatched_clients": mismatches} if mismatches else {}),
        "speedup_target": target,
        "scaling_asserted": target is not None,
        "scaling_ok": scaling_ok,
        "worker_restarts": restarts,
        "drained": drained,
        "fleet": {
            "workers_reporting": workers_reporting,
            "replayed": fleet.get("replayed", 0),
            "discarded": fleet.get("discarded", 0),
            "foreign_spans": foreign_spans,
            "rollup_batches": (fleet.get("rollup") or {})
            .get("counters", {}).get("worker.batches", 0),
        },
        "fleet_ok": fleet_ok,
        "pass": passed,
    }


# (name, fn, unit, reps) — workloads run INTERLEAVED baseline/production
# reps and report the median with min/max spread in the JSON.  Solver-bound
# rows get >= 3 reps: their run-to-run variance is the dominant error term
# (measured +/-20-40% in round 3), and a median-of-3 with reported spread is
# the minimum honest quote.
WORKLOADS = [
    ("suicide_1tx", wl_suicide, "states/sec", 3),
    ("killbilly_3tx", wl_killbilly, "states/sec", 3),
    ("overflow_256bit", wl_overflow, "states/sec", 3),
    ("wide_frontier", wl_wide_frontier, "states/sec", 3),
    ("wide_solc", wl_wide_solc, "states/sec", 3),
    ("bectoken_batch", wl_bectoken, "states/sec", 3),
    ("largecode_mixed", wl_largecode, "states/sec", 3),
    ("concolic_flip", wl_concolic, "flips/sec", 3),
    ("corpus_sweep", wl_corpus, "states/sec", 3),
]


def _warm_frontier() -> None:
    """Compile the segment programs for the production widths OUTSIDE every
    workload timer (the XLA disk cache is invalidated by any program change,
    so a fresh build pays each (caps, bucket) combination once here)."""
    import mythril_tpu
    from mythril_tpu.support.support_args import args

    # arm (and thereby pre-seed) the persistent compile cache before the
    # first compile: the warmup's programs land on disk, so later processes
    # — and every timed workload below — start from compilecache hits
    mythril_tpu.enable_persistent_compilation_cache(args.compile_cache_dir)

    _configure(True)
    args.frontier_force = True
    try:
        for width in (256, 1024):
            args.frontier_width = width
            _clear_caches()
            _analyze(
                _wide_contract(4), 0x0901D12E, 1,
                modules=["AccidentallyKillable"], timeout=300,
            )
    finally:
        args.frontier_force = False


_DEVSOLVER_KEYS = ("admitted", "decided_sat", "decided_unsat",
                   "unknown", "model_validation_failures")

_ADAPTIVE_KEYS = ("plans", "resteered_slots", "requeued_paths",
                  "flips_planned", "flips_hit", "plateau_stops")


def _new_row_data():
    return {
        "samples": {"baseline": [], "production": []},
        "ttfes": {"baseline": [], "production": []},
        "ttfrs": {"baseline": [], "production": []},
        "residency": [],
        "harvest_shares": [],
        "harvest_phases": [],  # per-production-rep {phase: seconds} deltas
        "prefilter": [],  # per-production-rep prefilter.* counter deltas
        "devsolver": [],  # per-production-rep devsolver.* counter deltas
        "adaptive": [],  # per-production-rep adaptive.* counter deltas
        "segments": [],  # per-production-rep frontier.segments deltas
        # per-production-rep large-code frontier reads: bucket classes,
        # pad-waste (isolated vs single-bucket counterfactual), paging
        "frontier": [],
        "exploration": [],  # per-production-rep termination/coverage deltas
        # per-production-rep staticpass.reachable_edge_pct gauge reads
        # (static property of the workload's code; drift across bench
        # artifacts means the corpus or the oracle changed)
        "staticpass_edge_pct": [],
        "mids": [],  # per-production-rep (mid_reentered, mid_bounced, semantic_parked)
        # accumulated per-tag [hits, misses] deltas of the persistent XLA
        # compile cache — did this workload's programs come off disk?
        "compilecache": {"baseline": [0, 0], "production": [0, 0]},
        # first-rep XLA compile wall per tag (device plane), split OUT of
        # the rep-0 timed window so steady-state speedups stop absorbing
        # warmup noise; None until the device plane observes a compile
        "compile_s": {"baseline": None, "production": None},
        # accumulated production-run device-plane deltas:
        # [compile_wall_s, recompiles, shape_churn]
        "device": [0.0, 0, 0],
        "completed_reps": 0,
        "trimmed_reps": [],  # rep numbers the budget clock dropped
    }


def _median(vals):
    return sorted(vals)[len(vals) // 2]


def _prefilter_summary(samples) -> dict:
    """Median prefilter.* counter deltas plus the derived kill rate —
    the per-workload figure that makes the abstract pre-filter's value
    measurable on corpus-like traffic."""
    out = {
        k: _median([p[k] for p in samples])
        for k in ("evaluated", "killed", "fallthrough")
    }
    out["kill_rate"] = (
        round(out["killed"] / out["evaluated"], 4) if out["evaluated"] else 0.0
    )
    return out


def _devsolver_summary(samples) -> dict:
    """Median devsolver.* counter deltas plus the derived decide rate —
    the per-workload figure for how much exact-solver traffic the device
    SAT tier absorbed."""
    out = {
        k: _median([p[k] for p in samples])
        for k in ("admitted", "decided_sat", "decided_unsat", "unknown",
                  "model_validation_failures")
    }
    out["decided"] = out["decided_sat"] + out["decided_unsat"]
    out["decide_rate"] = (
        round(out["decided"] / out["admitted"], 4) if out["admitted"] else 0.0
    )
    return out


def _adaptive_summary(samples) -> dict:
    """Median adaptive.* counter deltas plus the derived flip hit rate —
    the per-workload figure for how much steering the coverage-guided
    controller actually exerted (and whether its concolic flip plans
    landed)."""
    out = {k: _median([s[k] for s in samples]) for k in _ADAPTIVE_KEYS}
    out["flip_hit_rate"] = (
        round(out["flips_hit"] / out["flips_planned"], 4)
        if out["flips_planned"] else 0.0
    )
    return out


def _frontier_summary(samples) -> dict:
    """Median large-code frontier reads — pad-waste after bucket isolation
    next to the single-bucket counterfactual (the row the ISSUE's
    acceptance bar compares), plus paging fault/repack volume."""
    out = {
        "bucket_classes": _median([s["bucket_classes"] for s in samples]),
        "pad_waste_pct": round(
            _median([s["pad_waste_pct"] for s in samples]), 2),
        "pad_waste_single_bucket_pct": round(
            _median([s["pad_waste_single_bucket_pct"] for s in samples]), 2),
        "page_faults": _median([s["page_faults"] for s in samples]),
        "page_repacks": _median([s["page_repacks"] for s in samples]),
        "page_resident_pct": round(
            _median([s["page_resident_pct"] for s in samples]), 1),
    }
    return out


def _exploration_summary(samples) -> dict:
    """Median termination-class deltas + instruction coverage per rep —
    the exploration-quality row the coverage gate compares."""
    from mythril_tpu.observability.exploration import TERM_CLASSES

    term = {
        cls: _median([s["terminated"].get(cls, 0) for s in samples])
        for cls in TERM_CLASSES
    }
    covs = [
        s["coverage_pct"] for s in samples
        if s.get("coverage_pct") is not None
    ]
    covs_reach = [
        s["coverage_pct_reachable"] for s in samples
        if s.get("coverage_pct_reachable") is not None
    ]
    out = {
        "terminated": {cls: n for cls, n in term.items() if n},
        "terminated_total": _median(
            [s["terminated_total"] for s in samples]
        ),
        "coverage_pct": round(_median(covs), 2) if covs else None,
        "coverage_pct_reachable": (
            round(_median(covs_reach), 2) if covs_reach else None
        ),
    }
    # host-only workloads (e.g. a 1-tx probe-sized run that bails off the
    # frontier) feed the coverage bitmaps through the instruction plugin
    # but never reach the frontier's termination stamping, so the row
    # quotes coverage with terminated_total == 0.  That pairing read as
    # "100% coverage over zero paths" in BENCH_r17 — mark it explicitly
    # instead of letting it masquerade as frontier-measured coverage.
    if not out["terminated_total"] and out["coverage_pct"] is not None:
        out["coverage_probe_derived"] = True
    return out


def _row_summary(unit: str, d: dict, configured_reps: int = None) -> dict:
    samples, ttfes, ttfrs = d["samples"], d["ttfes"], d["ttfrs"]
    rates = {tag: _median(vals) for tag, vals in samples.items() if vals}
    med_ttfe = {
        tag: (_median(vals) if vals else None) for tag, vals in ttfes.items()
    }
    dev_pct = (
        round(100 * _median(d["residency"]), 1) if d["residency"] else 0.0
    )
    return {
        "unit": unit,
        "baseline": round(rates.get("baseline", 0.0), 2),
        "production": round(rates.get("production", 0.0), 2),
        "speedup": round(rates["production"] / rates["baseline"], 3)
        if rates.get("baseline") and "production" in rates
        else None,
        "reps": d["completed_reps"],
        # sub-min-rep honesty: a row with fewer completed reps than the
        # workload configured (budget-trimmed runs) has no defensible
        # median/spread — mark it so readers and the --against gate's
        # rate checks treat it as indicative, not authoritative
        **(
            {"low_confidence": True}
            if configured_reps is not None
            and d["completed_reps"] < configured_reps
            else {}
        ),
        # per-row spread: the honest error bars round 3 lacked.  A spread
        # over fewer samples than the workload's configured reps is marked
        # by spread_n + the budget-trimmed rep numbers, so 2-rep data never
        # silently reads as the full-rep figure again (BENCH_r05).
        "spread": {
            tag: [round(min(vals), 2), round(max(vals), 2)]
            for tag, vals in samples.items()
            if vals
        },
        "spread_n": {
            tag: len(vals) for tag, vals in samples.items() if vals
        },
        **(
            {"trimmed_reps": list(d["trimmed_reps"])}
            if d.get("trimmed_reps")
            else {}
        ),
        "ttfe_s": {
            tag: (round(v, 3) if v is not None else None)
            for tag, v in med_ttfe.items()
        },
        "ttfe_spread_s": {
            tag: [round(min(vals), 3), round(max(vals), 3)]
            for tag, vals in ttfes.items()
            if vals
        },
        # corpus only: time-to-FULL-recall — the metric the cooperative
        # schedule optimizes (first-exploit TTFE structurally favors the
        # sequential schedule, which confirms contract #1 before
        # contract #2 even starts)
        **(
            {
                "ttfr_s": {
                    tag: round(_median(vals), 3)
                    for tag, vals in ttfrs.items()
                    if vals
                }
            }
            if any(ttfrs.values())
            else {}
        ),
        "device_residency_pct": dev_pct,
        # persistent-compile-cache traffic attributed to this workload's
        # runs (hits = programs loaded from disk instead of recompiled)
        "compilecache": {
            tag: {"hits": int(v[0]), "misses": int(v[1])}
            for tag, v in d.get("compilecache", {}).items()
        },
        # first-rep XLA compile wall (seconds) per tag — already excluded
        # from that rep's timed window, quoted here so warmup cost stays
        # visible instead of silently vanishing from the table
        **(
            {
                "compile_s": {
                    tag: round(v, 3)
                    for tag, v in d.get("compile_s", {}).items()
                    if v is not None
                }
            }
            if any(
                v is not None for v in d.get("compile_s", {}).values()
            )
            else {}
        ),
        # device-plane deltas over the workload's production runs: total
        # XLA compile wall + same-bucket recompiles (each one is a lost
        # compile-cache bet) + distinct-shape churn
        **(
            {
                "device": {
                    "compile_wall_s": round(d["device"][0], 3),
                    "recompiles": int(d["device"][1]),
                    **(
                        {"shape_churn": int(d["device"][2])}
                        if d["device"][2]
                        else {}
                    ),
                }
            }
            if d.get("device") and (d["device"][0] or d["device"][1])
            else {}
        ),
        "harvest_share_pct": (
            round(100 * _median(d["harvest_shares"]), 1)
            if d["harvest_shares"]
            else None
        ),
        # the harvest share split per executor phase (median across
        # production reps of the frontier.harvest.*_s histogram deltas):
        # which of ingest / solver / replay / commit owns the host cost
        **(
            {
                "harvest_phase_s": {
                    p: round(_median([h[p] for h in d["harvest_phases"]]), 3)
                    for p in _HARVEST_PHASES
                }
            }
            if d["harvest_phases"]
            else {}
        ),
        # abstract pre-filter traffic (production runs): how many feasibility
        # queries the interval/known-bits pass evaluated and proved UNSAT
        # before any exact solve, and the per-workload kill rate
        **(
            {"prefilter": _prefilter_summary(d["prefilter"])}
            if d.get("prefilter")
            else {}
        ),
        # device SAT tier traffic (production runs): how many narrow
        # queries the batched bit-blast kernel decided (exact UNSAT or
        # validated SAT) instead of reaching the exact host tiers
        **(
            {"devsolver": _devsolver_summary(d["devsolver"])}
            if d.get("devsolver")
            else {}
        ),
        # adaptive steering traffic (production runs): plans built,
        # dispatch slots resteered off FIFO order, budget-exhausted paths
        # requeued, and planned-vs-hit concolic flips — quoted whenever
        # the controller exerted any steering on this workload
        **(
            {"adaptive": _adaptive_summary(d["adaptive"])}
            if d.get("adaptive")
            and any(any(s.values()) for s in d["adaptive"])
            else {}
        ),
        # device segment dispatches per production rep: the denominator
        # the adaptive controller tries to shrink at equal issue sets
        **(
            {"segments_dispatched": _median(d["segments"])}
            if d.get("segments") and any(d["segments"])
            else {}
        ),
        # large-code frontier (production runs): bucket classes, pad waste
        # with isolation vs the single-bucket counterfactual, and paging
        # fault/repack pressure — quoted whenever the run clustered codes
        # into classes or paid any paging traffic
        **(
            {"frontier": _frontier_summary(d["frontier"])}
            if d.get("frontier")
            and any(s["bucket_classes"] or s["page_faults"]
                    or s["pad_waste_pct"] for s in d["frontier"])
            else {}
        ),
        # exploration quality (production runs): how many paths stopped,
        # why (the eight-class termination partition), and how much of
        # each contract's instruction space the run actually visited
        **(
            {"exploration": _exploration_summary(d["exploration"])}
            if d.get("exploration")
            else {}
        ),
        # reachable-edge oracle: what share of static JUMPI edges the
        # interprocedural pass proved live for this workload's code set
        **(
            {"staticpass": {"reachable_edge_pct": round(
                _median(d["staticpass_edge_pct"]), 2)}}
            if d.get("staticpass_edge_pct")
            else {}
        ),
        # mid-frame residency (production runs): how many parked/resumed
        # states re-entered the device vs bounced at encoding vs stayed
        # pinned host-side as semantic parks — the counters that quantify
        # the mid-frame re-entry claim on each workload
        **(
            {
                "mid_frame": {
                    key: _median([m[i] for m in d["mids"]])
                    for i, key in enumerate(
                        ("reentered", "bounced", "semantic_parked")
                    )
                }
            }
            if d["mids"]
            else {}
        ),
    }


_UNIT_BLURB = (
    "states/sec over the reference contract corpus "
    "(production: frontier enabled everywhere — the corpus runs "
    "cooperatively as wide multi-code device segments, narrow "
    "workloads auto-bail to host; recall asserted per workload, "
    "ttfe_s = time-to-first-exploit)"
)


def _observability_snapshot() -> dict:
    from mythril_tpu.observability import get_registry

    return get_registry().snapshot()


def _emit_snapshot(table: dict, budget_meta: dict, partial: bool) -> None:
    """One JSON line on stdout + a file copy.  Emitted after EVERY completed
    workload pair so a driver-level timeout can never zero the artifact —
    the final (non-partial) snapshot is the last JSON line printed."""
    from mythril_tpu.frontier.stats import FrontierStatistics

    headline = table.get("corpus_sweep")
    obs = _observability_snapshot()
    obj = {
        "metric": "corpus_sweep_states_per_sec",
        "value": headline["production"] if headline else None,
        "unit": _UNIT_BLURB,
        "vs_baseline": (
            round(headline["production"] / headline["baseline"], 3)
            if headline and headline["baseline"]
            else None
        ),
        "workloads": table,
        "budget": budget_meta,
        # device-only efficiency (pure segment compute via chained-dispatch
        # subtraction, independent of the host<->device link): the per-chip
        # number that tracks distance to the paths/sec north star
        **(
            {"device_microbench": FrontierStatistics().microbench}
            if FrontierStatistics().microbench
            else {}
        ),
        # machine-readable per-stage breakdown: the full metrics-registry
        # snapshot (frontier/solver counters plus the segment/harvest/
        # smt-solve wall-time histograms) accumulated over the sweep
        "observability": obs,
        # the static pre-analysis counters broken out for quick grepping
        # (they also appear inside the full observability snapshot)
        "staticpass": {
            k: v for k, v in obs.items() if k.startswith("staticpass.")
        },
    }
    if partial:
        obj["partial"] = True
    line = json.dumps(obj)
    print(line, flush=True)
    try:
        Path(__file__).with_name("BENCH_partial.json").write_text(line + "\n")
    except OSError:
        pass


# ---------------------------------------------------------------------------
# regression gate: bench.py --against PRIOR.json [--candidate CUR.json]
# ---------------------------------------------------------------------------

# metric thresholds relative to the prior snapshot.  The rate/ttfe tolerance
# is deliberately generous (CPU-jitter across container runs); the absolute
# slacks keep sub-second metrics from tripping on noise.  A halved throughput
# or a doubled TTFE still fails loudly.
GATE_TOLERANCE = 0.35
GATE_TTFE_SLACK_S = 2.0
GATE_HARVEST_SLACK_PCT = 15.0  # absolute harvest-share points
GATE_PHASE_SLACK_S = 0.75  # absolute slack on service phase p95s
GATE_COVERAGE_SLACK_PCT = 10.0  # absolute instruction-coverage points
GATE_TRACING_BUDGET_PCT = 2.0  # tracing overhead must stay under 2% of wall
# spans+flows+counters a fully-instrumented pipelined segment emits (dispatch,
# chain_merge, segment, 4 harvest phases, replay/feasibility workers, 3-point
# segment flow, worker flows, heartbeat counters) — deliberately rounded UP
GATE_SPANS_PER_SEGMENT = 40.0


def _balanced_object(text: str, start: int):
    """Return the substring of one balanced {...} object starting at
    ``text[start] == '{'``, honoring JSON string/escape rules, or None if the
    object is truncated before it closes."""
    depth = 0
    in_str = False
    esc = False
    for i in range(start, len(text)):
        c = text[i]
        if in_str:
            if esc:
                esc = False
            elif c == "\\":
                esc = True
            elif c == '"':
                in_str = False
            continue
        if c == '"':
            in_str = True
        elif c == "{":
            depth += 1
        elif c == "}":
            depth -= 1
            if depth == 0:
                return text[start : i + 1]
    return None


def _salvage_workload_rows(text: str) -> dict:
    """Recover complete per-workload row objects from a (possibly truncated)
    bench stdout fragment.  Rows are recognized as ``"name": {...}`` objects
    that carry both ``unit`` and ``production`` keys — nested objects like
    ``spread``/``ttfe_s`` and the budget/observability blocks do not match."""
    import re

    rows: dict = {}
    for m in re.finditer(r'"([A-Za-z0-9_]+)"\s*:\s*\{', text):
        obj_txt = _balanced_object(text, m.end() - 1)
        if obj_txt is None:
            continue
        try:
            obj = json.loads(obj_txt)
        except ValueError:
            continue
        if isinstance(obj, dict) and "unit" in obj and "production" in obj:
            rows[m.group(1)] = obj
    return rows


def _load_bench_doc(path: str):
    """Load a prior bench artifact into ``(workload_rows, full_doc_or_None)``.

    Accepts, in order of preference:
      1. a plain snapshot JSON with a top-level ``workloads`` table (the
         bench.py output contract / BENCH_partial.json);
      2. a driver wrapper ``{"n", "cmd", "rc", "tail", "parsed"}`` whose
         ``parsed`` field holds the snapshot;
      3. the same wrapper with ``parsed: null`` and a tail that is the LAST
         N chars of stdout — often truncated mid-JSON (BENCH_r05.json), in
         which case complete workload rows are salvaged from the fragment;
      4. raw bench stdout (JSON line per snapshot): last parseable line wins.
    """
    raw = Path(path).read_text()
    try:
        doc = json.loads(raw)
    except ValueError:
        doc = None
    text = raw
    if isinstance(doc, dict):
        if isinstance(doc.get("workloads"), dict):
            return doc["workloads"], doc
        parsed = doc.get("parsed")
        if isinstance(parsed, dict) and isinstance(
            parsed.get("workloads"), dict
        ):
            return parsed["workloads"], parsed
        text = doc.get("tail") or ""
    for line in reversed(text.splitlines()):
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            obj = json.loads(line)
        except ValueError:
            continue
        if isinstance(obj, dict) and isinstance(obj.get("workloads"), dict):
            return obj["workloads"], obj
    return _salvage_workload_rows(text), None


def _tracing_overhead_pct(span_rate_hz: float) -> dict:
    """Measure the live per-span cost of the tracer (enabled-vs-disabled
    micro-bench on THIS machine) and scale it by the run's span emission rate
    to a percent-of-wall figure.  The flight deck's contract is that leaving
    tracing on costs <2% of wall; this asserts it with measured numbers
    instead of a hope."""
    from mythril_tpu.observability.tracer import Tracer

    tr = Tracer(capacity=8192)
    n = 20000

    def _spin() -> float:
        t0 = time.perf_counter()
        for _ in range(n):
            with tr.span("bench.overhead", cat="bench"):
                pass
        return (time.perf_counter() - t0) / n

    tr.enabled = False
    cost_off = _spin()
    tr.enabled = True
    cost_on = _spin()
    per_span_s = max(cost_on - cost_off, 0.0)
    return {
        "per_span_us": round(per_span_s * 1e6, 3),
        "span_rate_hz": round(span_rate_hz, 1),
        "overhead_pct": round(100.0 * per_span_s * span_rate_hz, 4),
    }


def _fleet_export_overhead_pct(flush_interval_s: float = 0.5) -> dict:
    """Measure the worker-side cost of one fleet delta flush (collect a
    registry delta + drain a batch of spans) on THIS machine and scale
    it by the flush rate to a percent-of-wall figure.  The fabric's
    contract is the same as the tracer's: leaving it on inside every
    worker costs <2% of wall."""
    from mythril_tpu.observability.fleet import FleetPublisher
    from mythril_tpu.observability.metrics import MetricsRegistry
    from mythril_tpu.observability.tracer import Tracer

    # a representative worker registry: the scoped counter/gauge set a
    # real batch leaves behind, plus phase histograms and span traffic
    reg = MetricsRegistry()
    tr = Tracer(capacity=8192)
    tr.enabled = True
    for i in range(48):
        reg.counter(f"bench.c{i}").inc(i + 1)
    for i in range(8):
        reg.gauge(f"bench.g{i}").set(i)
    lc = reg.labeled_counter("bench.issues", label_name="swc")
    for i in range(6):
        lc.inc(str(100 + i), 1)
    h = reg.histogram("bench.lat_s")
    pub = FleetPublisher(0, registry=reg, tracer=tr)
    pub.collect()  # baseline the full metric set first
    n = 200
    t0 = time.perf_counter()
    for _ in range(n):
        # keep every flush non-empty the way a busy worker's would be
        reg.counter("bench.c0").inc()
        h.observe(0.01)
        with tr.span("bench.worker_batch", cat="bench"):
            tr.flow("f", tr.new_flow_id(), "flow.request", cat="bench")
        pub.collect()
    per_flush_s = (time.perf_counter() - t0) / n
    rate_hz = 1.0 / max(flush_interval_s, 1e-6)
    return {
        "per_flush_us": round(per_flush_s * 1e6, 3),
        "flush_rate_hz": round(rate_hz, 1),
        "overhead_pct": round(100.0 * per_flush_s * rate_hz, 4),
    }


def _gate_span_rate(doc) -> float:
    """Estimate the instrumented-run span emission rate (spans/sec) from a
    bench snapshot's observability block: completed segments over suite wall,
    times a generous spans-per-segment factor.  Falls back to a conservative
    1 kHz when the snapshot lacks the histogram."""
    fallback = 1000.0
    if not isinstance(doc, dict):
        return fallback
    obs = doc.get("observability") or {}
    seg = obs.get("frontier.segment_wall_s") or {}
    count = seg.get("count") or 0
    elapsed = (doc.get("budget") or {}).get("elapsed_s") or 0
    if count and elapsed:
        return max(count / float(elapsed) * GATE_SPANS_PER_SEGMENT, fallback)
    return fallback


def regression_gate(
    against_path: str,
    current_table: dict,
    current_doc=None,
    tol: float = GATE_TOLERANCE,
) -> int:
    """Compare ``current_table`` to the snapshot at ``against_path``; print
    violations, emit one JSON gate-report line, return a process exit code
    (0 = clean, 1 = regression, 2 = unusable prior)."""
    try:
        prior, _prior_doc = _load_bench_doc(against_path)
    except (OSError, ValueError) as exc:
        print(f"[bench] --against: cannot read {against_path}: {exc}",
              file=sys.stderr)
        return 2
    common = sorted(set(prior) & set(current_table))
    if not common:
        print(
            f"[bench] --against: no comparable workloads between "
            f"{against_path} ({sorted(prior)}) and the current run "
            f"({sorted(current_table)})",
            file=sys.stderr,
        )
        return 2

    violations = []
    checks = 0
    low_confidence_skipped = []
    for name in common:
        p, c = prior[name], current_table[name]
        # a row either side marked low_confidence (sub-min-rep data, e.g.
        # budget-trimmed to a single rep) is excluded from the RATE checks:
        # one sample has no spread, so "best rep" == the only rep and the
        # bimodal solver-bound workloads fail on scheduling luck, not
        # regressions.  The absolute checks (coverage, SLO) still apply.
        low_conf = bool(p.get("low_confidence")) or bool(
            c.get("low_confidence")
        )
        if low_conf:
            low_confidence_skipped.append(name)
        # throughput: production rate must hold within the relative
        # tolerance.  The table quotes the MEDIAN rep, but the gate asks
        # "can this tree still achieve the prior rate?" — so it compares
        # the best rep in the row's recorded spread: solver-bound rows are
        # bimodal on CPU-only containers (the adaptive slow-code bail makes
        # some reps run host-side), and a real regression slows every rep,
        # so best-of still fails loudly on an injected slowdown.
        pr, cr = p.get("production"), c.get("production")
        if pr and cr is not None and not low_conf:
            checks += 1
            spread = (c.get("spread") or {}).get("production") or []
            best = max([cr] + [s for s in spread if s is not None])
            floor = pr * (1.0 - tol)
            if best < floor:
                violations.append(
                    f"{name}: production {cr:.2f} (best rep {best:.2f}) "
                    f"< {floor:.2f} (prior {pr:.2f}, tol {tol:.0%})"
                )
        # latency: median production time-to-first-exploit
        pt = (p.get("ttfe_s") or {}).get("production")
        ct = (c.get("ttfe_s") or {}).get("production")
        if pt is not None and ct is not None and not low_conf:
            checks += 1
            ceil = pt * (1.0 + tol) + GATE_TTFE_SLACK_S
            if ct > ceil:
                violations.append(
                    f"{name}: production ttfe_s {ct:.3f} > {ceil:.3f} "
                    f"(prior {pt:.3f}, tol {tol:.0%} + "
                    f"{GATE_TTFE_SLACK_S:.1f}s)"
                )
        # host-cost share: harvest must not grow past an absolute-point band
        ph, ch = p.get("harvest_share_pct"), c.get("harvest_share_pct")
        if ph is not None and ch is not None:
            checks += 1
            ceil = ph + GATE_HARVEST_SLACK_PCT
            if ch > ceil:
                violations.append(
                    f"{name}: harvest_share_pct {ch:.1f} > {ceil:.1f} "
                    f"(prior {ph:.1f} + {GATE_HARVEST_SLACK_PCT:.0f}pt)"
                )
        # exploration quality: instruction coverage must not collapse —
        # a run can be fast because it silently stopped exploring, and the
        # rate checks alone would call that an improvement.  The gate
        # compares the REACHABLE-denominator coverage (raw coverage moves
        # whenever dead code in the corpus changes size, which is noise);
        # it falls back to the raw figure when either artifact predates
        # the reachable key
        p_expl = p.get("exploration") or {}
        c_expl = c.get("exploration") or {}
        cov_key = "coverage_pct_reachable"
        if (p_expl.get(cov_key) is None or c_expl.get(cov_key) is None):
            cov_key = "coverage_pct"
        pcov = p_expl.get(cov_key)
        ccov = c_expl.get(cov_key)
        if pcov is not None and ccov is not None:
            checks += 1
            floor_cov = pcov - GATE_COVERAGE_SLACK_PCT
            if ccov < floor_cov:
                violations.append(
                    f"{name}: exploration {cov_key} {ccov:.1f} < "
                    f"{floor_cov:.1f} (prior {pcov:.1f} - "
                    f"{GATE_COVERAGE_SLACK_PCT:.0f}pt)"
                )
        # service latency decomposition: per-phase p95 (queue_wait /
        # batch_wait / execute / stream from the serve-load row) must
        # stay within the rate tolerance plus an absolute slack — an
        # admission or streaming regression fails like a rate regression
        p_phases = p.get("service_phase_s") or {}
        c_phases = c.get("service_phase_s") or {}
        for phase in sorted(set(p_phases) & set(c_phases)):
            p95p = (p_phases.get(phase) or {}).get("p95")
            p95c = (c_phases.get(phase) or {}).get("p95")
            if p95p is None or p95c is None:
                continue
            checks += 1
            ceil = p95p * (1.0 + tol) + GATE_PHASE_SLACK_S
            if p95c > ceil:
                violations.append(
                    f"{name}: {phase} p95 {p95c:.3f}s > {ceil:.3f}s "
                    f"(prior {p95p:.3f}s, tol {tol:.0%} + "
                    f"{GATE_PHASE_SLACK_S:.2f}s)"
                )
        # watchtower SLO verdict: any breach during the measured window
        # is a service regression in absolute terms — no prior needed,
        # so the check is gated only on the CURRENT row carrying it
        # (older priors without the key compare clean)
        c_slo = c.get("slo")
        if c_slo is not None:
            checks += 1
            if not c_slo.get("ok", True):
                violations.append(
                    f"{name}: {c_slo.get('breaches', '?')} SLO breach(es) "
                    f"during the measured window "
                    f"({c_slo.get('objectives', 0)} objectives held)"
                )
            wt_pct = c_slo.get("overhead_pct")
            if wt_pct is not None:
                checks += 1
                if wt_pct >= GATE_TRACING_BUDGET_PCT:
                    violations.append(
                        f"{name}: watchtower overhead {wt_pct:.3f}% >= "
                        f"{GATE_TRACING_BUDGET_PCT:.1f}% of wall"
                    )

    overhead = _tracing_overhead_pct(_gate_span_rate(current_doc))
    checks += 1
    if overhead["overhead_pct"] >= GATE_TRACING_BUDGET_PCT:
        violations.append(
            f"tracing overhead {overhead['overhead_pct']:.3f}% >= "
            f"{GATE_TRACING_BUDGET_PCT:.1f}% of wall "
            f"({overhead['per_span_us']}us/span x "
            f"{overhead['span_rate_hz']}Hz)"
        )
    fleet_overhead = _fleet_export_overhead_pct()
    checks += 1
    if fleet_overhead["overhead_pct"] >= GATE_TRACING_BUDGET_PCT:
        violations.append(
            f"fleet export overhead {fleet_overhead['overhead_pct']:.3f}% "
            f">= {GATE_TRACING_BUDGET_PCT:.1f}% of wall "
            f"({fleet_overhead['per_flush_us']}us/flush x "
            f"{fleet_overhead['flush_rate_hz']}Hz)"
        )

    # on failure, run the drift doctor over the same pair so the gate
    # names the most-moved phase/counter per violating workload instead
    # of only the breached threshold — the "what moved" next to the
    # "what broke".  Attribution is advisory: a doctor error never
    # changes the gate verdict.
    drift = None
    if violations:
        try:
            from mythril_tpu.observability.drift import (
                attribute,
                diff_tables,
            )

            d_report = diff_tables(
                prior, current_table,
                prior_name=against_path, current_name="current",
            )
            violators = []
            for v in violations:
                w = v.split(":", 1)[0]
                if w in common and w not in violators:
                    violators.append(w)
            drift = {
                "headline": d_report.get("headline"),
                "attribution": (
                    [attribute(d_report, workload=w) for w in violators]
                    if violators
                    else [attribute(d_report)]
                ),
            }
        except Exception as exc:  # advisory only — never mask the verdict
            drift = {"error": f"{type(exc).__name__}: {exc}"}

    report = {
        "gate": {
            "against": against_path,
            "tolerance": tol,
            "workloads_compared": common,
            "checks": checks,
            "violations": violations,
            **(
                {"low_confidence_skipped": low_confidence_skipped}
                if low_confidence_skipped
                else {}
            ),
            "tracing_overhead": overhead,
            "fleet_export_overhead": fleet_overhead,
            "tracing_overhead_budget_pct": GATE_TRACING_BUDGET_PCT,
            "pass": not violations,
            **({"drift": drift} if drift else {}),
        }
    }
    print(json.dumps(report), flush=True)
    if violations:
        lines = list(violations)
        if drift and drift.get("attribution"):
            lines += drift["attribution"]
        print(
            "[bench] regression gate FAILED vs %s:\n  %s"
            % (against_path, "\n  ".join(lines)),
            file=sys.stderr,
        )
        return 1
    print(
        f"[bench] regression gate ok vs {against_path}: {checks} checks over "
        f"{len(common)} workloads, tracing overhead "
        f"{overhead['overhead_pct']:.3f}% + fleet export "
        f"{fleet_overhead['overhead_pct']:.3f}% < "
        f"{GATE_TRACING_BUDGET_PCT:.1f}%",
        file=sys.stderr,
    )
    return 0


def main() -> None:
    # the "auto" backend gates on JAX_PLATFORMS without initializing jax; on
    # machines where the TPU is autodetected but the env var is unset, pin it
    # so the measured configuration actually exercises the device hybrid
    import os

    if "--query-cache-compare" in sys.argv:
        # standalone warm-vs-cold mode: skip the full suite, emit one line
        idx = sys.argv.index("--query-cache-compare")
        operand = sys.argv[idx + 1] if len(sys.argv) > idx + 1 else None
        cache_dir = None if operand is None or operand.startswith("-") else operand
        print(json.dumps(query_cache_compare(cache_dir)), flush=True)
        return

    if "--staticpass-compare" in sys.argv:
        # standalone on-vs-off mode: skip the full suite, emit one line
        print(json.dumps(staticpass_compare()), flush=True)
        return

    if "--pipeline-compare" in sys.argv:
        # standalone pipelined-vs-sync parity mode: skip the suite, one line
        print(json.dumps(pipeline_compare()), flush=True)
        return

    if "--prefilter-compare" in sys.argv:
        # standalone abstract-prefilter parity mode: skip the suite, one line
        print(json.dumps(prefilter_compare()), flush=True)
        return

    if "--devsolver-compare" in sys.argv:
        # standalone device-SAT-tier parity mode: skip the suite, one line
        print(json.dumps(devsolver_compare()), flush=True)
        return

    if "--adaptive-compare" in sys.argv:
        # standalone steering on-vs-off parity mode: skip the suite, one line
        print(json.dumps(adaptive_compare()), flush=True)
        return

    if "--paging-compare" in sys.argv:
        # standalone large-code bucket-isolation/paging parity mode
        print(json.dumps(paging_compare()), flush=True)
        return

    if "--harvest-compare" in sys.argv:
        # standalone sharded-vs-serial harvest parity mode: one line
        print(json.dumps(harvest_compare()), flush=True)
        return

    if "--mesh-compare" in sys.argv:
        # standalone pod parity mode (all four mesh x pipeline combos)
        print(json.dumps(mesh_compare()), flush=True)
        return

    # --against PRIOR.json [--candidate CUR.json] [--gate-tolerance F]:
    # the regression gate.  With --candidate, compare two artifacts without
    # running the suite (fast CI path); without it, run the full suite and
    # gate the fresh table against the prior snapshot
    against = None
    gate_tol = GATE_TOLERANCE
    if "--against" in sys.argv:
        idx = sys.argv.index("--against")
        against = sys.argv[idx + 1] if len(sys.argv) > idx + 1 else None
        if against is None or against.startswith("-"):
            print("[bench] --against requires a FILE operand", file=sys.stderr)
            sys.exit(2)
    if "--gate-tolerance" in sys.argv:
        idx = sys.argv.index("--gate-tolerance")
        try:
            gate_tol = float(sys.argv[idx + 1])
        except (IndexError, ValueError):
            print("[bench] --gate-tolerance requires a FRACTION operand",
                  file=sys.stderr)
            sys.exit(2)
    if "--serve-load" in sys.argv:
        # standalone analysis-as-a-service traffic mode: N concurrent
        # synthetic clients against a warm in-process service, asserting
        # determinism/throughput/dedup; one JSON line, optionally gated
        # by --against (the serve_load row compares like any other)
        clients = 8
        if "--serve-clients" in sys.argv:
            idx = sys.argv.index("--serve-clients")
            try:
                clients = int(sys.argv[idx + 1])
            except (IndexError, ValueError):
                print("[bench] --serve-clients requires an N operand",
                      file=sys.stderr)
                sys.exit(2)
        workers = 1
        if "--workers" in sys.argv:
            # N > 1 adds a second measured window: the same traffic
            # against an N-worker process pool (workloads.serve_pool)
            idx = sys.argv.index("--workers")
            try:
                workers = int(sys.argv[idx + 1])
            except (IndexError, ValueError):
                print("[bench] --workers requires an N operand",
                      file=sys.stderr)
                sys.exit(2)
        result = serve_load(clients, workers=workers)
        print(json.dumps(result), flush=True)
        if against is not None:
            rc = regression_gate(against, result["workloads"], result,
                                 tol=gate_tol)
            sys.exit(rc or (0 if result["pass"] else 1))
        sys.exit(0 if result["pass"] else 1)

    if "--candidate" in sys.argv:
        if against is None:
            print("[bench] --candidate requires --against", file=sys.stderr)
            sys.exit(2)
        idx = sys.argv.index("--candidate")
        cand = sys.argv[idx + 1] if len(sys.argv) > idx + 1 else None
        if cand is None or cand.startswith("-"):
            print("[bench] --candidate requires a FILE operand",
                  file=sys.stderr)
            sys.exit(2)
        try:
            cand_table, cand_doc = _load_bench_doc(cand)
        except (OSError, ValueError) as exc:
            print(f"[bench] --candidate: cannot read {cand}: {exc}",
                  file=sys.stderr)
            sys.exit(2)
        sys.exit(regression_gate(against, cand_table, cand_doc, tol=gate_tol))

    # --ttfe-budget SECONDS: turn the production TTFE gap into a loud
    # regression — after the suite completes, any workload whose median
    # production time-to-first-exploit exceeds the budget fails the run
    ttfe_budget = None
    if "--ttfe-budget" in sys.argv:
        idx = sys.argv.index("--ttfe-budget")
        try:
            ttfe_budget = float(sys.argv[idx + 1])
        except (IndexError, ValueError):
            print("[bench] --ttfe-budget requires a SECONDS operand",
                  file=sys.stderr)
            sys.exit(2)

    # suite-internal budget clock (monotonic); the per-workload t0 stamps
    # stay time.time() because _ttfe/_rebase_stamp compare them against the
    # epoch-anchored report.StartTime discovery stamps
    t_proc = time.perf_counter()
    # global wall-clock budget: the driver kills long runs (round 4's capture
    # died rc=124 with no JSON emitted), so the suite trims itself instead —
    # rep 1 of every workload always runs (full table first), reps 2+ run
    # only while they fit the budget, trimmed in fixed row order
    budget_s = float(os.environ.get("BENCH_BUDGET_S", "1500"))
    deadline = t_proc + budget_s

    if not os.environ.get("JAX_PLATFORMS", "").startswith(("tpu", "axon", "cpu")):
        try:
            import jax

            if jax.default_backend() in ("tpu", "axon"):
                os.environ["JAX_PLATFORMS"] = jax.default_backend()
        except Exception:
            pass

    from mythril_tpu.frontier.stats import FrontierStatistics

    _warm_frontier()
    data = {name: _new_row_data() for name, _, _, _ in WORKLOADS}
    pair_cost: dict = {}  # name -> worst observed (baseline+production) wall
    trimmed: list = []
    skipped: dict = {}  # name -> reason (inputs not mounted here)
    max_reps = max(reps for _, _, _, reps in WORKLOADS)

    def budget_meta():
        meta = {
            "budget_s": budget_s,
            "elapsed_s": round(time.perf_counter() - t_proc, 1),
            "trimmed": trimmed,
        }
        if skipped:
            meta["skipped"] = dict(skipped)
        return meta

    for rep in range(max_reps):
        for name, fn, unit, reps in WORKLOADS:
            if rep >= reps or name in skipped:
                continue
            est = pair_cost.get(name, 0.0)
            if rep > 0 and time.perf_counter() + est > deadline:
                # deterministic trim: later reps go first, rep 1 never does;
                # the row's own summary carries the trimmed rep numbers so
                # its spread is readable as N-rep data
                trimmed.append({"workload": name, "rep": rep + 1})
                data[name]["trimmed_reps"].append(rep + 1)
                continue
            d = data[name]
            t_pair = time.perf_counter()
            for tag, production in (("baseline", False), ("production", True)):
                from mythril_tpu.observability import get_registry

                fstats = FrontierStatistics()
                dev_before = fstats.device_instructions
                har_before = fstats.harvest_s
                mid_before = _mid_counters(fstats)
                phases_before = {
                    p: get_registry().histogram(
                        "frontier.harvest.%s_s" % p
                    ).sum
                    for p in _HARVEST_PHASES
                }
                pf_before = {
                    k: get_registry().counter("prefilter.%s" % k).value
                    for k in ("evaluated", "killed", "fallthrough")
                }
                ds_before = {
                    k: get_registry().counter("devsolver.%s" % k).value
                    for k in _DEVSOLVER_KEYS
                }
                ad_before = {
                    k: get_registry().counter("adaptive.%s" % k).value
                    for k in _ADAPTIVE_KEYS
                }
                seg_before = fstats.segments
                page_before = (fstats.page_faults, fstats.page_repacks)
                from mythril_tpu.observability.exploration import (
                    get_exploration_ledger,
                )

                expl_before = get_exploration_ledger().terminated()
                cc_before = (
                    get_registry().counter(
                        "compilecache.hits", persistent=True
                    ).value,
                    get_registry().counter(
                        "compilecache.misses", persistent=True
                    ).value,
                )
                dp_before = tuple(
                    get_registry().counter(k, persistent=True).value
                    for k in _DEVICE_PLANE_COUNTERS
                )
                try:
                    out = fn(production)
                except WorkloadSkip as exc:
                    skipped[name] = str(exc)
                    print(f"[bench] {name:16s} skipped ({exc})",
                          file=sys.stderr)
                    break
                cc = d["compilecache"][tag]
                cc[0] += (
                    get_registry().counter(
                        "compilecache.hits", persistent=True
                    ).value - cc_before[0]
                )
                cc[1] += (
                    get_registry().counter(
                        "compilecache.misses", persistent=True
                    ).value - cc_before[1]
                )
                work, wall, ttfe = out[:3]
                # device plane: per-rep XLA compile wall / recompile /
                # shape-churn deltas attributed to this workload's run
                dp_compile, dp_rcmp, dp_churn = (
                    get_registry().counter(k, persistent=True).value - b
                    for k, b in zip(_DEVICE_PLANE_COUNTERS, dp_before)
                )
                if production:
                    d["device"][0] += dp_compile
                    d["device"][1] += dp_rcmp
                    d["device"][2] += dp_churn
                if rep == 0 and d["compile_s"][tag] is None:
                    # split the first rep's compile wall out of the timed
                    # window — steady-state reps never pay it, so leaving
                    # it in made rep-0 rates read as phantom regressions.
                    # Guard: background precompiles can overlap the wall,
                    # so never let the adjustment eat >95% of it.
                    d["compile_s"][tag] = dp_compile
                    if dp_compile > 0 and wall - dp_compile > 0.05 * wall:
                        wall -= dp_compile
                d["samples"][tag].append(work / wall if wall > 0 else 0.0)
                if ttfe == ttfe:  # not NaN
                    d["ttfes"][tag].append(ttfe)
                if len(out) > 5 and out[5] == out[5]:  # time-to-full-recall
                    d["ttfrs"][tag].append(out[5])
                # residency = device-executed instructions / states explored:
                # meaningful only for state-counting workloads, and a
                # workload that warms up internally supplies its own delta
                if production and work and unit == "states/sec":
                    dev = (
                        out[3]
                        if len(out) > 3 and out[3] is not None
                        else fstats.device_instructions - dev_before
                    )
                    d["residency"].append(dev / work)
                if production and wall > 0:
                    # walker/harvest cost as a share of the workload wall —
                    # the number that says whether host-side event replay
                    # is the frontier's cost center.  A workload with an
                    # internal warm-up supplies its own delta (out[4]),
                    # mirroring the residency channel.
                    har = (
                        out[4]
                        if len(out) > 4 and out[4] is not None
                        else fstats.harvest_s - har_before
                    )
                    d["harvest_shares"].append(har / wall)
                    d["harvest_phases"].append({
                        p: get_registry().histogram(
                            "frontier.harvest.%s_s" % p
                        ).sum - phases_before[p]
                        for p in _HARVEST_PHASES
                    })
                if production:
                    d["prefilter"].append({
                        k: get_registry().counter("prefilter.%s" % k).value
                        - pf_before[k]
                        for k in ("evaluated", "killed", "fallthrough")
                    })
                    d["devsolver"].append({
                        k: get_registry().counter("devsolver.%s" % k).value
                        - ds_before[k]
                        for k in _DEVSOLVER_KEYS
                    })
                    d["adaptive"].append({
                        k: get_registry().counter("adaptive.%s" % k).value
                        - ad_before[k]
                        for k in _ADAPTIVE_KEYS
                    })
                    d["segments"].append(fstats.segments - seg_before)
                    # large-code frontier: per-rep pad economics (gauges
                    # reflect the most recent multi-code run) + paging
                    # pressure deltas attributed to this rep
                    d["frontier"].append({
                        "bucket_classes": int(get_registry().gauge(
                            "frontier.bucket_classes").value or 0),
                        "pad_waste_pct": float(get_registry().gauge(
                            "frontier.pad_waste_pct").value or 0.0),
                        "pad_waste_single_bucket_pct": float(
                            get_registry().gauge(
                                "frontier.pad_waste_single_bucket_pct"
                            ).value or 0.0),
                        "page_faults": int(
                            fstats.page_faults - page_before[0]),
                        "page_repacks": int(
                            fstats.page_repacks - page_before[1]),
                        "page_resident_pct": float(get_registry().gauge(
                            "frontier.page_resident_pct").value or 100.0),
                    })
                    led = get_exploration_ledger()
                    t_after = led.terminated()
                    # partition invariant: every stamped path carries
                    # exactly one class (stamp() increments both sides)
                    assert sum(t_after.values()) == led.terminated_total(), (
                        "exploration termination classes do not partition: "
                        f"{t_after} != total {led.terminated_total()}"
                    )
                    term_delta = {
                        cls: max(n - expl_before.get(cls, 0), 0)
                        for cls, n in t_after.items()
                    }
                    d["exploration"].append({
                        "terminated": term_delta,
                        "terminated_total": sum(term_delta.values()),
                        "coverage_pct": led.coverage_pct(),
                        "coverage_pct_reachable":
                            led.coverage_pct_reachable(),
                    })
                    edge_pct = get_registry().gauge(
                        "staticpass.reachable_edge_pct"
                    ).snapshot()
                    if edge_pct:
                        d["staticpass_edge_pct"].append(float(edge_pct))
                if production:
                    # a workload with an internal warm-up supplies its own
                    # timed-run delta (out[6]), mirroring out[3]/out[4]
                    mid = (
                        out[6]
                        if len(out) > 6 and out[6] is not None
                        else _mid_delta(fstats, mid_before)
                    )
                    d["mids"].append(mid)
            if name in skipped:
                continue
            # LATEST pair wall, not the max: rep 0 includes once-per-process
            # warm-ups (wide_frontier/corpus segment compiles) that later
            # reps never pay — a max would over-trim them
            pair_cost[name] = time.perf_counter() - t_pair
            d["completed_reps"] += 1
            row = _row_summary(unit, d, configured_reps=reps)
            for tag in ("baseline", "production"):
                t = row["ttfe_s"].get(tag)
                print(
                    f"[bench] {name:16s} {tag:10s} {row[tag]:10.1f} {unit}"
                    f"  (rep {d['completed_reps']}"
                    + (f", ttfe {t:.2f}s" if t is not None else "")
                    + (
                        f", device {row['device_residency_pct']}%"
                        if tag == "production"
                        else ""
                    )
                    + ")",
                    file=sys.stderr,
                )
            table = {
                n: _row_summary(u, data[n], configured_reps=r)
                for n, _, u, r in WORKLOADS
                if data[n]["completed_reps"]
            }
            _emit_snapshot(table, budget_meta(), partial=True)

    table = {
        n: _row_summary(u, data[n], configured_reps=r)
        for n, _, u, r in WORKLOADS
        if data[n]["completed_reps"]
    }
    _emit_snapshot(table, budget_meta(), partial=False)

    if ttfe_budget is not None:
        violations = []
        for n, row in table.items():
            t = row.get("ttfe_s", {}).get("production")
            if t is not None and t > ttfe_budget:
                violations.append(
                    f"{n}: production ttfe_s {t:.3f} > budget "
                    f"{ttfe_budget:.3f}"
                )
        if violations:
            print(
                "[bench] TTFE budget exceeded:\n  " + "\n  ".join(violations),
                file=sys.stderr,
            )
            sys.exit(1)
        print(
            f"[bench] TTFE budget ok: every production median within "
            f"{ttfe_budget:.3f}s",
            file=sys.stderr,
        )

    if against is not None:
        doc = {
            "observability": _observability_snapshot(),
            "budget": budget_meta(),
        }
        rc = regression_gate(against, table, doc, tol=gate_tol)
        if rc:
            sys.exit(rc)


if __name__ == "__main__":
    sys.exit(main())
