"""Persistent SMT query cache (see cache.py for the tier/soundness story).

Process-wide singleton, mirroring the other telemetry/cache subsystems::

    from mythril_tpu.querycache import get_query_cache, configure

    configure(enabled=True, cache_dir="/tmp/qc")   # facade/CLI do this
    hit = get_query_cache().lookup(conjuncts, budget_ms=2000)

The solver hooks (smt/solver.py) call ``lookup``/``record``; everything
else — bench's warm-vs-cold mode, the facade's flag propagation, tests —
goes through the module-level helpers below.
"""

from mythril_tpu.querycache import canon  # noqa: F401  (import order matters:
# cache.py references this submodule through the package during its import)
from mythril_tpu.querycache.canon import (  # noqa: F401
    QueryFingerprint,
    conjunct_fingerprint,
    fingerprint,
)
from mythril_tpu.querycache.store import DiskStore  # noqa: F401
from mythril_tpu.querycache.cache import (  # noqa: F401
    QueryCache,
    materialize_counters,
)
from mythril_tpu.querycache.cache import _UNSET as _UNSET

from typing import Optional

_cache: Optional[QueryCache] = None


def get_query_cache() -> QueryCache:
    global _cache
    if _cache is None:
        _cache = QueryCache()
    return _cache


def configure(enabled=None, cache_dir=_UNSET) -> None:
    """Partial reconfiguration of the singleton (None/absent = keep)."""
    get_query_cache().configure(enabled=enabled, cache_dir=cache_dir)


def reset_query_cache() -> None:
    """Drop the in-process layers; a configured disk store survives."""
    if _cache is not None:
        _cache.reset()


def clear_query_cache_memos() -> None:
    """Drop term-id-keyed memos only (called with the solver's term-cache
    sweeps so interned DAGs can be collected)."""
    if _cache is not None:
        _cache.clear_memos()
    else:
        canon.clear_memos()
