"""In-process end-to-end service tests: real analyses over tiny
contracts with the host engine (frontier off, warmup off) so each case
stays in the tier-1 budget."""

import threading
from pathlib import Path

import pytest

from mythril_tpu.service import (
    AnalysisOptions,
    AnalysisService,
    ServiceConfig,
    canonical_codehash,
    issue_digest,
)

REPO = Path(__file__).resolve().parents[2]
KILL_SIMPLE_HEX = (
    REPO / "tests" / "testdata" / "inputs" / "kill_simple.bin-runtime"
).read_text().strip()
CLEAN_HEX = "0x60006000f3"  # PUSH1 0; PUSH1 0; RETURN — nothing to report

OPTS = AnalysisOptions(transaction_count=1, execution_timeout=30)


def _config(**overrides):
    base = dict(
        default_options=OPTS,
        max_batch_width=4,
        batch_window_s=0.05,
        frontier=False,
        probe=True,
        warmup=False,
    )
    base.update(overrides)
    return ServiceConfig(**base)


@pytest.fixture
def scoped_args():
    """The service arms the global flag object at start(); snapshot and
    restore it (plus the detector scope) so these tests do not leak
    configuration into the rest of the suite."""
    from mythril_tpu.facade.warm import reset_analysis_scope
    from mythril_tpu.support.support_args import args

    saved = dict(vars(args))
    yield
    vars(args).clear()
    vars(args).update(saved)
    # the service also re-armed the global query cache; point it back
    from mythril_tpu.querycache import configure as configure_query_cache

    configure_query_cache(
        enabled=getattr(args, "query_cache", True),
        cache_dir=getattr(args, "query_cache_dir", None),
    )
    reset_analysis_scope()


def test_submit_streams_issues_then_done(scoped_args):
    service = AnalysisService(_config()).start()
    try:
        _req, stream, deduped = service.submit(
            KILL_SIMPLE_HEX, name="kill", tier="interactive"
        )
        assert deduped is False
        events = list(stream.events(timeout=120))
        kinds = [k for k, _ in events]
        assert kinds[-1] == "done" and "issue" in kinds
        summary = events[-1][1]
        assert [i["swc_id"] for i in summary["issues"]] == ["106"]
        # streamed issues are exactly the authoritative set, earlier
        streamed = [p for k, p in events if k == "issue"]
        assert (
            sorted(issue_digest(i) for i in streamed)
            == sorted(issue_digest(i) for i in summary["issues"])
        )
        # the interactive tier's first evidence came from the host probe
        assert streamed[0].get("provisional") is True
    finally:
        assert service.stop(drain=True, timeout=30) is True


def test_clean_contract_reports_no_issues(scoped_args):
    service = AnalysisService(_config(probe=False)).start()
    try:
        _req, stream, _ = service.submit(CLEAN_HEX, name="clean")
        assert stream.issues(timeout=120) == []
    finally:
        service.stop(drain=True, timeout=30)


def test_duplicate_concurrent_submits_share_one_analysis(scoped_args):
    from mythril_tpu.observability.metrics import get_registry

    reg = get_registry()
    batches0 = reg.counter("service.batches", persistent=True).snapshot()
    dedup0 = reg.counter("service.dedup_hits", persistent=True).snapshot()

    # wide admission window so both submissions land in one flight
    service = AnalysisService(_config(batch_window_s=0.3)).start()
    results = {}
    lock = threading.Lock()

    def _client(cid):
        _req, stream, deduped = service.submit(KILL_SIMPLE_HEX, name=cid)
        summary = stream.result(timeout=120)
        with lock:
            results[cid] = (deduped, summary)

    try:
        threads = [
            threading.Thread(target=_client, args=(f"c{i}",)) for i in range(3)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert len(results) == 3
        digests = {
            cid: sorted(issue_digest(i) for i in summary["issues"])
            for cid, (_d, summary) in results.items()
        }
        assert len(set(map(tuple, digests.values()))) == 1
        # exactly one analysis ran; every other submission deduped
        assert (
            reg.counter("service.batches", persistent=True).snapshot()
            - batches0
        ) == 1
        assert (
            reg.counter("service.dedup_hits", persistent=True).snapshot()
            - dedup0
        ) == 2
    finally:
        service.stop(drain=True, timeout=30)


def test_per_request_isolation_on_tenant_failure(scoped_args, monkeypatch):
    """One tenant's failure reaches only that tenant; batchmates complete."""
    import mythril_tpu.analysis.cooperative as coop

    boom_hash = canonical_codehash(CLEAN_HEX)
    real = coop.run_cooperative_batch

    def _sabotaged(jobs, **kwargs):
        issues, errors, states = real(jobs, **kwargs)
        if any(name == boom_hash for name, _code in jobs):
            issues.pop(boom_hash, None)
            errors[boom_hash] = "injected tenant failure"
        return issues, errors, states

    monkeypatch.setattr(coop, "run_cooperative_batch", _sabotaged)

    service = AnalysisService(_config(probe=False, batch_window_s=0.3)).start()
    try:
        _r1, ok_stream, _ = service.submit(KILL_SIMPLE_HEX, name="ok")
        _r2, boom_stream, _ = service.submit(CLEAN_HEX, name="boom")
        with pytest.raises(RuntimeError, match="injected tenant failure"):
            boom_stream.result(timeout=120)
        # the co-batched healthy tenant is untouched by the failure
        assert [i["swc_id"] for i in ok_stream.issues(timeout=120)] == ["106"]

        # the failure is NOT cached: resubmitting analyzes afresh
        monkeypatch.setattr(coop, "run_cooperative_batch", real)
        _r3, retry_stream, deduped = service.submit(CLEAN_HEX, name="retry")
        assert deduped is False
        assert retry_stream.issues(timeout=120) == []
    finally:
        service.stop(drain=True, timeout=30)


def test_completed_result_replays_without_reanalysis(scoped_args):
    from mythril_tpu.observability.metrics import get_registry

    reg = get_registry()
    service = AnalysisService(_config(probe=False)).start()
    try:
        _r1, first, _ = service.submit(KILL_SIMPLE_HEX, name="first")
        first_issues = first.issues(timeout=120)

        batches0 = reg.counter("service.batches", persistent=True).snapshot()
        replay0 = reg.counter("service.replay_hits", persistent=True).snapshot()
        _r2, second, deduped = service.submit(KILL_SIMPLE_HEX, name="second")
        assert deduped is True
        assert second.issues(timeout=10) == first_issues
        assert (
            reg.counter("service.batches", persistent=True).snapshot()
            == batches0
        )
        assert (
            reg.counter("service.replay_hits", persistent=True).snapshot()
            - replay0
        ) == 1
    finally:
        service.stop(drain=True, timeout=30)


def test_stop_drains_and_rejects_new_submissions(scoped_args):
    service = AnalysisService(_config(probe=False)).start()
    _req, stream, _ = service.submit(KILL_SIMPLE_HEX, name="inflight")
    assert service.stop(drain=True, timeout=120) is True
    # the in-flight request still got its full result during the drain
    assert [i["swc_id"] for i in stream.issues(timeout=1)] == ["106"]
    with pytest.raises(RuntimeError, match="not accepting"):
        service.submit(KILL_SIMPLE_HEX, name="late")


def test_cache_root_pins_both_caches(scoped_args, tmp_path):
    root = tmp_path / "svc-cache"
    service = AnalysisService(
        _config(probe=False, cache_root=str(root))
    ).start()
    try:
        _req, stream, _ = service.submit(KILL_SIMPLE_HEX, name="kill")
        stream.result(timeout=120)
    finally:
        service.stop(drain=True, timeout=30)
    from mythril_tpu.support.support_args import args

    assert args.query_cache_dir == str(root / "querycache")
    assert args.compile_cache_dir == str(root / "xla")
    # the query cache persisted solved queries under the pinned root
    assert (root / "querycache").is_dir()


def test_wait_warm_and_stats(scoped_args):
    service = AnalysisService(_config(warmup=True)).start()
    try:
        assert service.wait_warm(timeout=120) is True
        stats = service.stats()
        assert "service.requests" in stats
        assert stats["service.queue_depth"] == 0
    finally:
        service.stop(drain=True, timeout=30)
