"""Public SMT API — the single seam the rest of the framework talks through.

API parity with the reference's Z3 wrapper layer (mythril/laser/smt/__init__.py:1-29,
bitvec.py, bool.py, array.py, function.py, bitvec_helper.py:30-240): the same
class names, helper names and annotation (taint) propagation semantics, but the
backing representation is this framework's own hash-consed term IR
(mythril_tpu/smt/terms.py) instead of z3 ExprRefs, and solving is routed to the
TPU probe + native CDCL stack instead of Z3 (mythril_tpu/smt/solver.py).

Annotations: every operator result carries the union of its operands'
annotation sets (reference: mythril/laser/smt/expression.py:10, bitvec.py:72) —
this is the taint channel the detection modules rely on.
"""

from __future__ import annotations

from typing import Iterable, List, Optional, Set, Union

from mythril_tpu.smt import terms
from mythril_tpu.smt.terms import Term


class Expression:
    """Base wrapper: a term plus a set of annotations (taint labels)."""

    __slots__ = ("raw", "annotations")

    def __init__(self, raw: Term, annotations: Optional[Iterable] = None):
        self.raw = raw
        self.annotations: Set = set(annotations) if annotations else set()

    def annotate(self, annotation) -> None:
        self.annotations.add(annotation)

    def get_annotations(self, annotation_type: type) -> List:
        return [a for a in self.annotations if isinstance(a, annotation_type)]

    def __hash__(self):
        return hash(self.raw)

    def __repr__(self):
        return repr(self.raw)


def _union(*exprs) -> Set:
    out: Set = set()
    for e in exprs:
        if isinstance(e, Expression):
            out |= e.annotations
    return out


class Bool(Expression):
    @property
    def is_true(self) -> bool:
        return self.raw.op == "const" and self.raw.aux is True

    @property
    def is_false(self) -> bool:
        return self.raw.op == "const" and self.raw.aux is False

    @property
    def value(self) -> Optional[bool]:
        return bool(self.raw.aux) if self.raw.op == "const" else None

    def __and__(self, other: "Bool") -> "Bool":
        return And(self, other)

    def __or__(self, other: "Bool") -> "Bool":
        return Or(self, other)

    def __invert__(self) -> "Bool":
        return Not(self)

    def __eq__(self, other):  # type: ignore[override]
        if not isinstance(other, Bool):
            return NotImplemented
        return Bool(terms.iff(self.raw, other.raw), _union(self, other))

    def __ne__(self, other):  # type: ignore[override]
        if not isinstance(other, Bool):
            return NotImplemented
        return Bool(terms.lxor(self.raw, other.raw), _union(self, other))

    def __hash__(self):
        return hash(self.raw)

    def __bool__(self):
        # Matches z3-python ergonomics closely enough: concrete bools collapse.
        if self.raw.op == "const":
            return bool(self.raw.aux)
        raise TypeError("symbolic Bool has no concrete truth value")

    def substitute(self, mapping) -> "Bool":
        raw_map = {k.raw: v.raw for k, v in mapping.items()}
        return Bool(terms.substitute(self.raw, raw_map), set(self.annotations))


class BitVec(Expression):
    """256-bit-centric bitvector wrapper with full operator overloading.

    Width-mismatched equality pads the narrower side with zeros, mirroring the
    reference's 512-bit sha3-operand special case (mythril/laser/smt/bitvec.py:16-22).
    """

    def size(self) -> int:
        return self.raw.width

    @property
    def symbolic(self) -> bool:
        return not self.raw.is_const

    @property
    def value(self) -> Optional[int]:
        return self.raw.value if self.raw.is_const else None

    # -- arithmetic ---------------------------------------------------------
    def __add__(self, other):
        other = _coerce(other, self.size())
        return BitVec(terms.add(self.raw, other.raw), _union(self, other))

    __radd__ = __add__

    def __sub__(self, other):
        other = _coerce(other, self.size())
        return BitVec(terms.sub(self.raw, other.raw), _union(self, other))

    def __rsub__(self, other):
        other = _coerce(other, self.size())
        return BitVec(terms.sub(other.raw, self.raw), _union(self, other))

    def __mul__(self, other):
        other = _coerce(other, self.size())
        return BitVec(terms.mul(self.raw, other.raw), _union(self, other))

    __rmul__ = __mul__

    def __truediv__(self, other):
        """Signed division (z3 ``/`` semantics, as in the reference)."""
        other = _coerce(other, self.size())
        return BitVec(terms.sdiv(self.raw, other.raw), _union(self, other))

    def __mod__(self, other):
        """Signed remainder (z3 ``%`` is srem on bitvecs)."""
        other = _coerce(other, self.size())
        return BitVec(terms.srem(self.raw, other.raw), _union(self, other))

    def __and__(self, other):
        other = _coerce(other, self.size())
        return BitVec(terms.band(self.raw, other.raw), _union(self, other))

    __rand__ = __and__

    def __or__(self, other):
        other = _coerce(other, self.size())
        return BitVec(terms.bor(self.raw, other.raw), _union(self, other))

    __ror__ = __or__

    def __xor__(self, other):
        other = _coerce(other, self.size())
        return BitVec(terms.bxor(self.raw, other.raw), _union(self, other))

    __rxor__ = __xor__

    def __invert__(self):
        return BitVec(terms.bnot(self.raw), set(self.annotations))

    def __neg__(self):
        return BitVec(terms.neg(self.raw), set(self.annotations))

    def __lshift__(self, other):
        other = _coerce(other, self.size())
        return BitVec(terms.shl(self.raw, other.raw), _union(self, other))

    def __rshift__(self, other):
        """Arithmetic shift right (z3 ``>>``); use LShR for logical."""
        other = _coerce(other, self.size())
        return BitVec(terms.ashr(self.raw, other.raw), _union(self, other))

    # -- comparisons (signed, like z3 python) -------------------------------
    def __lt__(self, other) -> Bool:
        other = _coerce(other, self.size())
        return Bool(terms.slt(self.raw, other.raw), _union(self, other))

    def __gt__(self, other) -> Bool:
        other = _coerce(other, self.size())
        return Bool(terms.sgt(self.raw, other.raw), _union(self, other))

    def __le__(self, other) -> Bool:
        other = _coerce(other, self.size())
        return Bool(terms.sle(self.raw, other.raw), _union(self, other))

    def __ge__(self, other) -> Bool:
        other = _coerce(other, self.size())
        return Bool(terms.sge(self.raw, other.raw), _union(self, other))

    def __eq__(self, other) -> Bool:  # type: ignore[override]
        if other is None:
            return Bool(terms.false())
        other = _coerce(other, self.size())
        a, b = _pad_pair(self.raw, other.raw)
        return Bool(terms.eq(a, b), _union(self, other))

    def __ne__(self, other) -> Bool:  # type: ignore[override]
        if other is None:
            return Bool(terms.true())
        other = _coerce(other, self.size())
        a, b = _pad_pair(self.raw, other.raw)
        return Bool(terms.ne(a, b), _union(self, other))

    def __hash__(self):
        return hash(self.raw)


def _coerce(x, width: int) -> BitVec:
    if isinstance(x, BitVec):
        return x
    if isinstance(x, int):
        return BitVec(terms.const(x, width))
    raise TypeError(f"cannot coerce {type(x)} to BitVec")


def _pad_pair(a: Term, b: Term):
    if a.width == b.width:
        return a, b
    if a.width < b.width:
        a = terms.zext(a, b.width - a.width)
    else:
        b = terms.zext(b, a.width - b.width)
    return a, b


class BitVecFunc(BitVec):
    """Kept for API parity; hash applications are real ``keccak`` terms here."""


class BaseArray:
    pass


class Array(BaseArray):
    """Named symbolic array store (reference smt/array.py:45)."""

    def __init__(self, name: str, domain: int, value_range: int, raw: Optional[Term] = None):
        self.raw = raw if raw is not None else terms.array_var(name, domain, value_range)
        self.domain = domain
        self.range = value_range

    def __getitem__(self, item: BitVec) -> BitVec:
        return BitVec(terms.select(self.raw, item.raw), set(item.annotations))

    def __setitem__(self, key: BitVec, value) -> None:
        value = _coerce(value, self.range)
        self.raw = terms.store(self.raw, key.raw, value.raw)


class K(BaseArray):
    """Constant-default array (reference smt/array.py:60)."""

    def __init__(self, domain: int, value_range: int, value: Union[int, BitVec]):
        value = _coerce(value, value_range)
        self.raw = terms.const_array(domain, value_range, value.raw)
        self.domain = domain
        self.range = value_range

    def __getitem__(self, item: BitVec) -> BitVec:
        return BitVec(terms.select(self.raw, item.raw), set(item.annotations))

    def __setitem__(self, key: BitVec, value) -> None:
        value = _coerce(value, self.range)
        self.raw = terms.store(self.raw, key.raw, value.raw)


class Function:
    """N-ary uninterpreted function (reference smt/function.py:7)."""

    def __init__(self, name: str, domain: List[int], value_range: int):
        self.name = name
        self.domain = domain
        self.range = value_range

    def __call__(self, *args: BitVec) -> BitVec:
        anns = _union(*args)
        return BitVec(
            terms.apply_func(self.name, self.range, *[a.raw for a in args]), anns
        )


# ---------------------------------------------------------------------------
# Helper functions (reference bitvec_helper.py / bool.py surface)
# ---------------------------------------------------------------------------


def If(cond, a, b):
    if isinstance(cond, bool):
        cond = Bool(terms.boolval(cond))
    if isinstance(a, int) and isinstance(b, BitVec):
        a = _coerce(a, b.size())
    if isinstance(b, int) and isinstance(a, BitVec):
        b = _coerce(b, a.size())
    anns = _union(cond, a, b)
    if isinstance(a, Bool):
        return Bool(terms.ite(cond.raw, a.raw, b.raw), anns)
    return BitVec(terms.ite(cond.raw, a.raw, b.raw), anns)


def UGT(a: BitVec, b) -> Bool:
    b = _coerce(b, a.size())
    return Bool(terms.ugt(a.raw, b.raw), _union(a, b))


def UGE(a: BitVec, b) -> Bool:
    b = _coerce(b, a.size())
    return Bool(terms.uge(a.raw, b.raw), _union(a, b))


def ULT(a: BitVec, b) -> Bool:
    b = _coerce(b, a.size())
    return Bool(terms.ult(a.raw, b.raw), _union(a, b))


def ULE(a: BitVec, b) -> Bool:
    b = _coerce(b, a.size())
    return Bool(terms.ule(a.raw, b.raw), _union(a, b))


def SLT(a: BitVec, b) -> Bool:
    b = _coerce(b, a.size())
    return Bool(terms.slt(a.raw, b.raw), _union(a, b))


def SGT(a: BitVec, b) -> Bool:
    b = _coerce(b, a.size())
    return Bool(terms.sgt(a.raw, b.raw), _union(a, b))


def Concat(*args) -> BitVec:
    if len(args) == 1 and isinstance(args[0], list):
        args = tuple(args[0])
    anns = _union(*args)
    return BitVec(terms.concat(*[a.raw for a in args]), anns)


def Extract(high: int, low: int, bv: BitVec) -> BitVec:
    return BitVec(terms.extract(high, low, bv.raw), set(bv.annotations))


def UDiv(a: BitVec, b) -> BitVec:
    b = _coerce(b, a.size())
    return BitVec(terms.udiv(a.raw, b.raw), _union(a, b))


def URem(a: BitVec, b) -> BitVec:
    b = _coerce(b, a.size())
    return BitVec(terms.urem(a.raw, b.raw), _union(a, b))


def SRem(a: BitVec, b) -> BitVec:
    b = _coerce(b, a.size())
    return BitVec(terms.srem(a.raw, b.raw), _union(a, b))


def SDiv(a: BitVec, b) -> BitVec:
    b = _coerce(b, a.size())
    return BitVec(terms.sdiv(a.raw, b.raw), _union(a, b))


def LShR(a: BitVec, b) -> BitVec:
    b = _coerce(b, a.size())
    return BitVec(terms.lshr(a.raw, b.raw), _union(a, b))


def Exp(a: BitVec, b) -> BitVec:
    b = _coerce(b, a.size())
    return BitVec(terms.bvexp(a.raw, b.raw), _union(a, b))


def Keccak(data: BitVec) -> BitVec:
    return BitVec(terms.keccak(data.raw), set(data.annotations))


def Sum(*args: BitVec) -> BitVec:
    out = args[0]
    for a in args[1:]:
        out = out + a
    return out


def ZeroExt(extra: int, a: BitVec) -> BitVec:
    return BitVec(terms.zext(a.raw, extra), set(a.annotations))


def SignExt(extra: int, a: BitVec) -> BitVec:
    return BitVec(terms.sext(a.raw, extra), set(a.annotations))


def And(*args: Bool) -> Bool:
    return Bool(terms.land(*[a.raw for a in args]), _union(*args))


def Or(*args: Bool) -> Bool:
    return Bool(terms.lor(*[a.raw for a in args]), _union(*args))


def Not(a: Bool) -> Bool:
    return Bool(terms.lnot(a.raw), set(a.annotations))


def Xor(a: Bool, b: Bool) -> Bool:
    return Bool(terms.lxor(a.raw, b.raw), _union(a, b))


def Implies(a: Bool, b: Bool) -> Bool:
    return Bool(terms.implies(a.raw, b.raw), _union(a, b))


def is_true(a: Bool) -> bool:
    return a.is_true


def is_false(a: Bool) -> bool:
    return a.is_false


def simplify(e):
    """Terms fold eagerly at construction, so simplify is (almost) the identity.

    Kept for reference API parity (mythril/laser/smt/expression.py:63); callers
    rely on it to canonicalize memory/storage indices, which hash-consing
    already guarantees.
    """
    return e


# Overflow predicates (reference bitvec_helper.py:196-227)


def BVAddNoOverflow(a: BitVec, b, signed: bool) -> Bool:
    b = _coerce(b, a.size())
    w = a.size()
    ax, bx = (terms.sext(a.raw, 1), terms.sext(b.raw, 1)) if signed else (
        terms.zext(a.raw, 1),
        terms.zext(b.raw, 1),
    )
    s = terms.add(ax, bx)
    if signed:
        # overflow iff the (w+1)-bit sum is not representable in w bits
        lo = terms.const((1 << (w + 1)) - (1 << (w - 1)), w + 1)  # -2^(w-1)
        hi = terms.const((1 << (w - 1)) - 1, w + 1)
        ok = terms.land(terms.sle(lo, s), terms.sle(s, hi))
    else:
        ok = terms.ule(s, terms.const((1 << w) - 1, w + 1))
    return Bool(ok, _union(a, b))


def BVSubNoUnderflow(a: BitVec, b, signed: bool) -> Bool:
    b = _coerce(b, a.size())
    w = a.size()
    if signed:
        ax, bx = terms.sext(a.raw, 1), terms.sext(b.raw, 1)
        d = terms.sub(ax, bx)
        lo = terms.const((1 << (w + 1)) - (1 << (w - 1)), w + 1)
        hi = terms.const((1 << (w - 1)) - 1, w + 1)
        ok = terms.land(terms.sle(lo, d), terms.sle(d, hi))
    else:
        ok = terms.uge(a.raw, b.raw)
    return Bool(ok, _union(a, b))


def BVMulNoOverflow(a: BitVec, b, signed: bool) -> Bool:
    b = _coerce(b, a.size())
    w = a.size()
    if signed:
        ax, bx = terms.sext(a.raw, w), terms.sext(b.raw, w)
        p = terms.mul(ax, bx)
        lo = terms.const((1 << (2 * w)) - (1 << (w - 1)), 2 * w)
        hi = terms.const((1 << (w - 1)) - 1, 2 * w)
        ok = terms.land(terms.sle(lo, p), terms.sle(p, hi))
    else:
        ax, bx = terms.zext(a.raw, w), terms.zext(b.raw, w)
        p = terms.mul(ax, bx)
        ok = terms.ule(p, terms.const((1 << w) - 1, 2 * w))
    return Bool(ok, _union(a, b))


# ---------------------------------------------------------------------------
# Symbol factory (reference smt/__init__.py:37-154)
# ---------------------------------------------------------------------------


class SymbolFactory:
    @staticmethod
    def BitVecVal(value: int, size: int, annotations=None) -> BitVec:
        return BitVec(terms.const(value, size), annotations)

    @staticmethod
    def BitVecSym(name: str, size: int, annotations=None) -> BitVec:
        return BitVec(terms.var(name, size), annotations)

    @staticmethod
    def BoolVal(value: bool, annotations=None) -> Bool:
        return Bool(terms.boolval(value), annotations)

    @staticmethod
    def BoolSym(name: str, annotations=None) -> Bool:
        return Bool(terms.bool_var(name), annotations)


symbol_factory = SymbolFactory()

from mythril_tpu.smt.solver import (  # noqa: E402  (re-export, reference smt/__init__ parity)
    Model,
    Optimize,
    Solver,
    SolverStatistics,
    SAT,
    UNKNOWN,
    UNSAT,
)
