"""Cross-contract static call graph over every loaded code object.

Each analyzed contract registers its code hash, on-chain address (when
known) and per-function summaries; edges are drawn wherever a call
site's constant-folded target address matches another registered
contract.  Unresolved targets stay as dangling edges (callee ``None``)
so multi-contract scenario tooling can see "this contract calls out,
we don't know where" as a fact distinct from "no external calls".

The graph is process-wide observe-only state (like the report views in
:mod:`report`): nothing prunes or gates on it, it feeds `myth static`,
``meta.staticpass`` and the ROADMAP's multi-contract scenario work.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional


def _norm_address(address) -> Optional[int]:
    """Contract address as an int, or None when symbolic/unknown."""
    if address is None:
        return None
    if isinstance(address, int):
        return address
    try:
        s = str(address).strip()
        return int(s, 16) if s.lower().startswith("0x") else int(s)
    except (ValueError, TypeError):
        return None


class StaticCallGraph:
    """Registry of code objects + resolved constant-target call edges."""

    def __init__(self):
        self._lock = threading.Lock()
        self._nodes: Dict[str, dict] = {}  # code_hash -> node dict
        self._by_address: Dict[int, str] = {}  # address -> code_hash

    def register(self, code_hash: str, name: str = "?",
                 address=None, function_map=None) -> None:
        addr = _norm_address(address)
        with self._lock:
            node = self._nodes.setdefault(code_hash, {
                "code_hash": code_hash,
                "name": name,
                "address": None,
                "calls": [],  # raw call sites, resolved lazily in edges()
            })
            if name != "?" and node["name"] in ("?", ""):
                node["name"] = name
            if addr is not None:
                node["address"] = f"0x{addr:040x}"
                self._by_address[addr] = code_hash
            if function_map is not None:
                calls = []
                for fn in function_map.functions:
                    for c in fn.calls:
                        calls.append({
                            "function": fn.name,
                            "selector": (
                                f"0x{fn.selector:08x}"
                                if fn.selector is not None else None
                            ),
                            "addr": c.addr,
                            "opcode": c.opcode,
                            "to": list(c.to) if c.to is not None else None,
                            "value": list(c.value) if c.value is not None else None,
                        })
                node["calls"] = calls

    def edges(self) -> List[dict]:
        """One edge per (call site, constant target); targets that match
        a registered address resolve to that callee's code hash."""
        with self._lock:
            out: List[dict] = []
            for ch, node in self._nodes.items():
                for c in node["calls"]:
                    targets = c["to"] if c["to"] is not None else [None]
                    for tgt in targets:
                        out.append({
                            "caller": ch,
                            "caller_function": c["function"],
                            "caller_selector": c["selector"],
                            "site_addr": c["addr"],
                            "opcode": c["opcode"],
                            "target_address": (
                                f"0x{tgt:040x}" if tgt is not None else None
                            ),
                            "callee": self._by_address.get(tgt),
                        })
            return out

    def to_dict(self) -> dict:
        edges = self.edges()
        with self._lock:
            nodes = [
                {
                    "code_hash": n["code_hash"],
                    "name": n["name"],
                    "address": n["address"],
                    "n_call_sites": len(n["calls"]),
                }
                for n in self._nodes.values()
            ]
        return {
            "nodes": nodes,
            "edges": edges,
            "resolved_edges": sum(1 for e in edges if e["callee"] is not None),
        }

    def reset(self) -> None:
        with self._lock:
            self._nodes.clear()
            self._by_address.clear()


_GRAPH = StaticCallGraph()


def get_callgraph() -> StaticCallGraph:
    return _GRAPH
