"""Pure steering planner for coverage-guided adaptive exploration.

The planner is the deliberative half of the adaptive controller (the
reactive half — actuation at engine/pipeline sync points — lives in
:mod:`mythril_tpu.adaptive.controller`).  It consumes the observability
stack's raw products:

* per-codehash coverage bitmaps + static reachability masks
  (:meth:`ExplorationLedger.bitmaps`),
* termination attribution (:data:`TERM_CLASSES` counts),
* solver-hotspot labels (``exploration.solver_hotspot_s``),
* the static pass's ranked ``interesting_points``,

and emits a :class:`SteeringPlan`:

* **weights** — per-codehash frontier slot-budget shares biased toward
  uncovered REACHABLE edges (saturated and plateaued codes decay to an
  epsilon floor, never to zero, so no code is starved outright),
* **requeue** — parked ``budget_exhausted`` path tokens worth
  resurrecting when arena slots free,
* **flip_targets** — uncovered JUMPI edges ranked by the static pass's
  ``interesting_points`` priorities, for targeted concolic flips,
* **plateaued** — per-codehash diminishing-returns verdicts (coverage
  delta below epsilon over a sliding window).

Everything here is pure numpy over plain inputs — no engine state, no
registry, no locks — mirroring ``pipeline.plan_rebalance`` /
``choose_free_slot``: the policy is unit-testable on its own and the
actuation sites stay mechanical.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, List, Mapping, Optional, Sequence, Tuple

import numpy as np

__all__ = [
    "EPS_WEIGHT",
    "PLATEAU_EPSILON",
    "PLATEAU_WINDOW",
    "SteeringPlan",
    "uncovered_reachable",
    "steer_weights",
    "requeue_candidates",
    "rank_flip_targets",
    "plateau_verdict",
    "build_plan",
]

#: Weight floor per codehash: a saturated or plateaued code keeps at
#: least this share (pre-normalization) so it is deprioritized, never
#: starved — a late-widening contract can still earn slots back.
EPS_WEIGHT = 0.05

#: Coverage-percent delta (reachable denominator) below which a sliding
#: window counts as a plateau.
PLATEAU_EPSILON = 0.5

#: Sliding-window length (plan ticks) for the plateau verdict.
PLATEAU_WINDOW = 4

#: Damping strength for solver-hotspot wall: a code that ate ALL the
#: observed solver seconds has its weight divided by (1 + this).
_HOTSPOT_DAMP = 0.5


@dataclass(frozen=True)
class SteeringPlan:
    """One planner emission.  All maps are keyed by FULL codehash."""

    #: per-codehash slot-budget shares; values sum to 1.0 when non-empty
    weights: Dict[str, float] = field(default_factory=dict)
    #: parked-path tokens (opaque to the planner) to resurrect, in order
    requeue: Tuple[Any, ...] = ()
    #: per-codehash uncovered-JUMPI addrs, highest priority first
    flip_targets: Dict[str, Tuple[int, ...]] = field(default_factory=dict)
    #: per-codehash diminishing-returns verdict
    plateaued: Dict[str, bool] = field(default_factory=dict)
    #: per-codehash uncovered reachable-edge counts (the bias signal)
    uncovered_edges: Dict[str, int] = field(default_factory=dict)

    def weight(self, code_hash: str) -> float:
        """Share for one code; unknown codes get the mean share (new
        code is neither favored nor starved until it reports coverage)."""
        if code_hash in self.weights:
            return self.weights[code_hash]
        if not self.weights:
            return 1.0
        return 1.0 / len(self.weights)


def uncovered_reachable(bitmap: Mapping[str, Any]
                        ) -> Tuple[np.ndarray, np.ndarray, int]:
    """(uncovered_taken_idx, uncovered_fall_idx, uncovered_instr_count)
    for one :meth:`ExplorationLedger.bitmaps` entry.

    An edge is "uncovered reachable" when the static mask marks it live
    and the executed plane has not seen it.  With no registered masks
    every JUMPI site whose instruction WAS reached counts — the dynamic
    frontier itself proved the branch point reachable."""
    instr = np.asarray(bitmap["instr"], bool)
    taken = np.asarray(bitmap["edge_taken"], bool)
    fall = np.asarray(bitmap["edge_fall"], bool)
    reach_taken = bitmap.get("reach_taken")
    reach_fall = bitmap.get("reach_fall")
    reach_instr = bitmap.get("reach_instr")
    if reach_taken is None or reach_fall is None:
        # no oracle: branch sites we reached but whose edges we did not
        # exhaust (taken|fall seen marks a JUMPI site)
        sites = taken | fall
        un_taken = np.flatnonzero(sites & ~taken)
        un_fall = np.flatnonzero(sites & ~fall)
        n_un_instr = 0
    else:
        un_taken = np.flatnonzero(np.asarray(reach_taken, bool) & ~taken)
        un_fall = np.flatnonzero(np.asarray(reach_fall, bool) & ~fall)
        n_un_instr = (
            int((np.asarray(reach_instr, bool) & ~instr).sum())
            if reach_instr is not None else 0
        )
    return un_taken, un_fall, n_un_instr


def steer_weights(uncovered: Mapping[str, int],
                  plateaued: Optional[Mapping[str, bool]] = None,
                  hotspot_s: Optional[Mapping[str, float]] = None,
                  eps: float = EPS_WEIGHT) -> Dict[str, float]:
    """Per-codehash slot-budget shares.

    Raw mass is the uncovered reachable-edge count (+1 so brand-new codes
    with zero observed edges still attract compute), damped by the code's
    share of observed solver wall (a hotspot code pays for its queries),
    floored at ``eps`` and collapsed TO the floor for plateaued codes,
    then normalized to a valid distribution.  Deterministic: equal inputs
    give equal weights, and iteration order never matters."""
    keys = sorted(uncovered)
    if not keys:
        return {}
    plateaued = plateaued or {}
    hotspot_s = hotspot_s or {}
    total_hot = sum(max(float(v), 0.0) for v in hotspot_s.values())
    mass = np.empty(len(keys), np.float64)
    for i, k in enumerate(keys):
        m = float(max(int(uncovered[k]), 0) + 1)
        if total_hot > 0:
            share = max(float(hotspot_s.get(k, 0.0)), 0.0) / total_hot
            m /= 1.0 + _HOTSPOT_DAMP * share
        if plateaued.get(k) or uncovered[k] <= 0:
            m = 0.0
        mass[i] = m
    # epsilon floor relative to the mean mass keeps the floor meaningful
    # whatever the edge-count scale (10 edges or 10k)
    floor = eps * max(float(mass.mean()), 1.0)
    mass = np.maximum(mass, floor)
    mass /= mass.sum()
    return {k: float(mass[i]) for i, k in enumerate(keys)}


def requeue_candidates(parked: Sequence[Tuple[Any, str]],
                       live: Iterable[Any],
                       limit: int = 16) -> List[Any]:
    """Parked-path tokens to resurrect when arena slots free.

    ``parked`` is ``[(token, reason), ...]`` in park order; only
    ``budget_exhausted`` parks qualify (every other class is a verdict,
    not a resource accident), a token currently LIVE is never named
    (exactly-once: a resurrected path must not run twice), and FIFO
    order is preserved so resurrection replays the original exploration
    order.  Duplicate tokens are named once."""
    live_set = set(live)
    out: List[Any] = []
    seen = set()
    for token, reason in parked:
        if len(out) >= max(int(limit), 0):
            break
        if reason != "budget_exhausted":
            continue
        if token in live_set or token in seen:
            continue
        seen.add(token)
        out.append(token)
    return out


def rank_flip_targets(un_taken: np.ndarray, un_fall: np.ndarray,
                      interesting_points: Sequence[Mapping[str, Any]] = (),
                      limit: int = 32) -> Tuple[int, ...]:
    """Uncovered-JUMPI addrs ranked for concolic flipping.

    Each uncovered edge's JUMPI addr scores by the highest-priority
    static ``interesting_point`` at or after it (the point the untaken
    branch guards); addrs with no downstream point score 0.  Sort is
    score-descending, then addr-ascending — fully deterministic."""
    addrs = np.union1d(np.asarray(un_taken, np.int64),
                       np.asarray(un_fall, np.int64))
    if addrs.size == 0:
        return ()
    pts = sorted(
        (int(p.get("addr", -1)), float(p.get("score", 0)))
        for p in interesting_points
        if int(p.get("addr", -1)) >= 0
    )
    pt_addrs = np.asarray([a for a, _ in pts], np.int64)
    pt_scores = np.asarray([s for _, s in pts], np.float64)
    scores = np.zeros(addrs.size, np.float64)
    if pt_addrs.size:
        for i, a in enumerate(addrs):
            j = int(np.searchsorted(pt_addrs, a))
            if j < pt_addrs.shape[0]:
                scores[i] = float(pt_scores[j:].max())
    order = np.lexsort((addrs, -scores))
    return tuple(int(a) for a in addrs[order][:max(int(limit), 0)])


def plateau_verdict(history: Sequence[float],
                    epsilon: float = PLATEAU_EPSILON,
                    window: int = PLATEAU_WINDOW) -> bool:
    """True when coverage gained less than ``epsilon`` percentage points
    over the last ``window`` plan ticks.  Short histories are never a
    plateau (the code has not had its chance yet), and the verdict is
    monotone in growth: appending a sample that lifts the window's total
    gain to ``epsilon`` or more always clears it."""
    if window <= 0 or len(history) <= window:
        return False
    return (float(history[-1]) - float(history[-1 - window])) < epsilon


def build_plan(bitmaps: Mapping[str, Mapping[str, Any]],
               history: Optional[Mapping[str, Sequence[float]]] = None,
               parked: Sequence[Tuple[Any, str]] = (),
               live: Iterable[Any] = (),
               points: Optional[Mapping[str, Sequence[Mapping[str, Any]]]]
               = None,
               hotspot_s: Optional[Mapping[str, float]] = None,
               epsilon: float = PLATEAU_EPSILON,
               window: int = PLATEAU_WINDOW,
               requeue_limit: int = 16,
               flip_limit: int = 32) -> SteeringPlan:
    """Compose one :class:`SteeringPlan` from ledger-shaped inputs.

    ``bitmaps`` is :meth:`ExplorationLedger.bitmaps` output; ``history``
    maps codehash → recent reachable-coverage percentages (controller-
    maintained); ``parked`` / ``live`` feed :func:`requeue_candidates`;
    ``points`` maps codehash → static ``interesting_points``; and
    ``hotspot_s`` maps codehash → attributed solver seconds."""
    history = history or {}
    points = points or {}
    uncovered: Dict[str, int] = {}
    plateaued: Dict[str, bool] = {}
    flips: Dict[str, Tuple[int, ...]] = {}
    for h, bm in bitmaps.items():
        un_taken, un_fall, n_un_instr = uncovered_reachable(bm)
        uncovered[h] = int(un_taken.size + un_fall.size) + (
            # edge-less codes (no JUMPI) steer on uncovered instructions
            n_un_instr if not bm.get("jumpis") else 0
        )
        plateaued[h] = plateau_verdict(history.get(h, ()), epsilon, window)
        targets = rank_flip_targets(
            un_taken, un_fall, points.get(h, ()), flip_limit
        )
        if targets:
            flips[h] = targets
    return SteeringPlan(
        weights=steer_weights(uncovered, plateaued, hotspot_s),
        requeue=tuple(requeue_candidates(parked, live, requeue_limit)),
        flip_targets=flips,
        plateaued=plateaued,
        uncovered_edges=uncovered,
    )
