"""STATICCALL callee frames on the device frontier (ROADMAP item 4).

Static frames used to be excluded from eligibility entirely, so every
STATICCALL-heavy view function host-stepped.  The per-path ``static`` flag
lifts the exclusion: state-mutating ops (SSTORE/LOG/SELFDESTRUCT) halt the
path as a terminal whose E_TERMINAL replay re-executes the op on the host
carrier — whose StateTransition raises the real WriteProtection
(mythril_tpu/core/instructions.py:114-117, reference
mythril/laser/ethereum/instructions.py StateTransition.check_gas wrapper).
"""

import pathlib
import sys

from collections import namedtuple

import jax
import numpy as np
import pytest

from mythril_tpu.frontier import ops as O
from mythril_tpu.frontier.arena import HostArena
from mythril_tpu.frontier.code import CodeTables, stacked_device_tables
from mythril_tpu.frontier.state import Caps, empty_state
from mythril_tpu.frontier.step import ArenaDev, CfgScalars, CodeDev, cached_segment

sys.path.insert(0, str(pathlib.Path(__file__).parents[2]))

Ins = namedtuple("Ins", "opcode address arg_int")

# PUSH1 1; PUSH1 0; SSTORE; STOP
WRITE_PROGRAM = [
    Ins("PUSH1", 0, 1),
    Ins("PUSH1", 2, 0),
    Ins("SSTORE", 4, None),
    Ins("STOP", 5, None),
]

CAPS = Caps(B=2, K=8)


def _run_write_program(static: int):
    arena = HostArena(CAPS.ARENA)
    row_zero = arena.const_row(0, 256)
    row_one = arena.const_row(1, 256)
    tables = CodeTables(WRITE_PROGRAM, arena)
    instr_cap, addr_cap, loops_cap = tables.size_bucket()
    segment = cached_segment(CAPS, 1, instr_cap, addr_cap, loops_cap)
    code_dev = CodeDev(*[
        jax.device_put(a)
        for a in stacked_device_tables([tables], (1, instr_cap, addr_cap, loops_cap))
    ])
    cfg = CfgScalars(
        max_depth=np.int32(128),
        loop_bound=np.int32(0),
        row_zero=np.int32(row_zero),
        row_one=np.int32(row_one),
        sel_mode=np.int32(0),
    )
    st = empty_state(CAPS, loops_cap)
    st.seed[0] = 0
    st.halt[0] = O.H_RUNNING
    st.static[0] = static
    # storage array row for ctx (SSTORE rewrites it)
    from mythril_tpu.smt import terms as T

    st.ctx[0] = arena.var_row(T.array_var("storage_t", 256, 256))
    dev_arena = ArenaDev(*[jax.device_put(a) for a in arena.device_arrays()])
    visited = jax.device_put(np.zeros((3, 1, instr_cap), bool))
    out_state, _a, _l, n_exec, _m, _v = segment(
        st, dev_arena, arena.length, visited, code_dev, cfg
    )
    return np.array(out_state.halt)[0], np.array(out_state.pc)[0], int(n_exec)


def test_static_flag_halts_sstore_as_terminal():
    halt, pc, _ = _run_write_program(static=1)
    assert halt == O.H_INVALID
    assert pc == 2  # still AT the SSTORE: the replay re-executes it on host


def test_nonstatic_sstore_completes():
    halt, _pc, n = _run_write_program(static=0)
    assert halt == O.H_STOP
    assert n == 4


# ---------------------------------------------------------------------------
# end-to-end: STATICCALL into a view function with a tx.origin check
# ---------------------------------------------------------------------------


def _staticcall_contract() -> bytes:
    """fn outer (byte 0x01): STATICCALLs fn view; SSTOREs the success flag.
    fn view (byte 0x02): JUMPI on ORIGIN==CALLER (SWC-115 inside the static
    frame); the taken branch attempts SSTORE (write-protected when called
    via outer), the fall-through returns 1."""
    from bench_contracts import Asm

    a = Asm()
    a.push(0).op("CALLDATALOAD").push(0xF8).op("SHR")
    a.op("DUP1").push(0x01).op("EQ").jumpi("outer")
    a.op("DUP1").push(0x02).op("EQ").jumpi("view")
    a.revert()

    a.label("outer")
    # memory[0] = selector byte for view (0x02 << 248)
    a.push(0x02).push(248).op("SHL").push(0).op("MSTORE")
    # staticcall(gas, address(this), 0, 1, 32, 32)
    a.push(32).push(32).push(1).push(0)
    a.op("ADDRESS")
    a.push(50000)
    a.op("STATICCALL")
    a.push(0).op("SSTORE")
    a.op("STOP")

    a.label("view")
    a.op("ORIGIN", "CALLER", "EQ").jumpi("view_write")
    a.push(1).push(0).op("MSTORE").push(32).push(0).op("RETURN")
    a.label("view_write")
    # write attempt inside the static frame: dies with WriteProtection
    a.push(7).push(1).op("SSTORE")
    a.op("STOP")
    return a.assemble()


def _analyze(code: bytes, frontier: bool):
    from mythril_tpu.analysis.security import fire_lasers, reset_callback_modules
    from mythril_tpu.analysis.symbolic import SymExecWrapper
    from mythril_tpu.support.support_args import args as global_args

    reset_callback_modules()
    from mythril_tpu.analysis.module.loader import ModuleLoader

    for m in ModuleLoader().get_detection_modules():
        if hasattr(m, "cache"):
            m.cache.clear()
    old = (global_args.frontier, global_args.frontier_force)
    global_args.frontier = frontier
    global_args.frontier_force = frontier
    try:
        sym = SymExecWrapper(
            code,
            address=0x0901D12E,
            strategy="bfs",
            transaction_count=1,
            execution_timeout=60,
            modules=["TxOrigin"],
        )
        return fire_lasers(sym, white_list=["TxOrigin"])
    finally:
        global_args.frontier, global_args.frontier_force = old


def keys(issues):
    return sorted((i.swc_id, i.address, i.function) for i in issues)


def test_staticcall_view_frame_host_parity():
    from mythril_tpu.frontier.stats import FrontierStatistics

    code = _staticcall_contract()
    host = _analyze(code, frontier=False)
    FrontierStatistics().reset()
    dev = _analyze(code, frontier=True)
    stats = FrontierStatistics().as_dict()
    assert keys(host) == keys(dev), (
        f"static-frame issues diverged: host={keys(host)} dev={keys(dev)}"
    )
    # the ORIGIN JUMPI inside the view function must be reported (the
    # direct-entry path at least; the static path reports the same key)
    assert any(i.swc_id == "115" for i in dev), "view-frame SWC-115 lost"
    assert stats["device_instructions"] > 0, "frontier never engaged"
