"""Tier-2 exact solving: serialize term DAGs to the native CDCL bit-blaster.

Role (see mythril_tpu/smt/solver.py): the probe answers most queries; this
tier supplies what probing cannot — exact UNSAT verdicts (stronger pruning
with zero recall loss) and models for hard SAT instances.  The reference
delegates the same questions to Z3 (mythril/laser/smt/solver/solver.py:51-66).

Abstractions applied before blasting, all sound for UNSAT (they only ever
ADD behaviors):
  * ``select`` over ``store``/``ite``/``const_array`` chains is rewritten
    into mux chains (same rewrite the device lowering performs);
  * base-array ``select``s, ``keccak``s and uninterpreted ``apply``s become
    fresh variables with Ackermann congruence constraints
    (equal arguments => equal results);
  * ``bvexp`` expands by square-and-multiply for constant exponents /
    power-of-two bases and is rejected otherwise.
SAT answers are therefore *candidates*: the caller validates the
reconstructed model with the exact concrete evaluator before trusting it
(solver.py does this), so keccak's abstraction can never produce a wrong SAT,
and UNSAT of the abstraction implies UNSAT of the original formula.

Keccak is additionally refined by CEGAR (the lazy analogue of the eager
hash axioms the reference installs via keccak_function_manager,
mythril/laser/ethereum/function_managers/keccak_function_manager.py): when
a candidate model assigns a keccak site a value different from the REAL
hash of its concretely-evaluated input, ``input == v => output ==
keccak(v)`` is asserted and the formula re-solved — so queries whose
verdict depends on hash semantics (hash-distinctness UNSAT proofs, models
routing through storage slots) converge to exact answers instead of
burning their budget on host-validation failures.
"""

from __future__ import annotations

import ctypes
import logging
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from mythril_tpu.ops.keccak import keccak256_int
from mythril_tpu.smt import terms
from mythril_tpu.smt.concrete_eval import ArrayValue, Assignment, evaluate
from mythril_tpu.smt.terms import Term

log = logging.getLogger(__name__)

SAT, UNSAT, UNKNOWN = "sat", "unsat", "unknown"

(
    OP_CONST, OP_VAR, OP_EQ, OP_AND, OP_OR, OP_NOT, OP_XOR, OP_ITE,
    OP_ADD, OP_SUB, OP_MUL, OP_UDIV, OP_UREM, OP_SDIV, OP_SREM,
    OP_BAND, OP_BOR, OP_BXOR, OP_BNOT, OP_NEG, OP_SHL, OP_LSHR, OP_ASHR,
    OP_CONCAT, OP_EXTRACT, OP_ZEXT, OP_SEXT, OP_ULT, OP_ULE, OP_SLT, OP_SLE,
) = range(31)

_BINOP = {
    "bvadd": OP_ADD, "bvsub": OP_SUB, "bvmul": OP_MUL, "bvudiv": OP_UDIV,
    "bvurem": OP_UREM, "bvsdiv": OP_SDIV, "bvsrem": OP_SREM,
    "bvand": OP_BAND, "bvor": OP_BOR, "bvxor": OP_BXOR,
    "bvshl": OP_SHL, "bvlshr": OP_LSHR, "bvashr": OP_ASHR,
    "ult": OP_ULT, "ule": OP_ULE, "slt": OP_SLT, "sle": OP_SLE,
    "xor": OP_XOR,
}

_MAX_NODES = 200_000


class Unsupported(Exception):
    """DAG contains structure the native tier cannot express exactly."""


_lib = None
_lib_tried = False


def _load():
    global _lib, _lib_tried
    if _lib_tried:
        return _lib
    _lib_tried = True
    from mythril_tpu.native.build import library_path

    path = library_path()
    if path is None:
        return None
    try:
        lib = ctypes.CDLL(str(path))
        lib.bb_solve.restype = ctypes.c_int32
        lib.bb_solve.argtypes = [
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int64,
            ctypes.c_double,
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64,
        ]
        lib.bb_open.restype = ctypes.c_void_p
        lib.bb_open.argtypes = [
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int64,
        ]
        lib.bb_solve_assume.restype = ctypes.c_int32
        lib.bb_solve_assume.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
            ctypes.c_double,
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64,
        ]
        lib.bb_close.restype = None
        lib.bb_close.argtypes = [ctypes.c_void_p]
        lib.bb_extend.restype = ctypes.c_int32
        lib.bb_extend.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int64,
        ]
        _lib = lib
    except OSError as e:
        log.warning("native library failed to load: %s", e)
    return _lib


def available() -> bool:
    return _load() is not None


# ---------------------------------------------------------------------------
# Serialization
# ---------------------------------------------------------------------------


class _Tape:
    def __init__(self):
        self.records: List[Tuple[int, int, int, int, int, int, int]] = []
        self.consts = bytearray()
        # original Term -> tape node id
        self.node_of: Dict[int, int] = {}
        # fresh var bookkeeping, in tape order: (kind, payload)
        #   ("scalar", term) | ("select", array_term, idx_term)
        #   | ("keccak", input_term) | ("apply", term)
        self.var_meta: List[tuple] = []
        # Ackermann groups
        self.selects: Dict[int, List[Tuple[int, int, Term]]] = {}  # arr tid -> [(idx node, var node, idx term)]
        self.keccaks: List[Tuple[int, int, Term]] = []  # (input node, var node, input term)
        self.applies: Dict[tuple, List[Tuple[List[int], int]]] = {}
        self.roots: List[int] = []

    def emit(self, op, width, a0=-1, a1=-1, a2=-1, x0=0, x1=0) -> int:
        self.records.append((op, width, a0, a1, a2, x0, x1))
        if len(self.records) > _MAX_NODES:
            raise Unsupported("tape too large")
        return len(self.records) - 1

    def const(self, value: int, width: int) -> int:
        nbytes = (width + 7) // 8
        off = len(self.consts)
        self.consts += int(value).to_bytes(nbytes, "little")
        return self.emit(OP_CONST, width, x0=off, x1=nbytes)

    def fresh(self, width: int, meta: tuple) -> int:
        node = self.emit(OP_VAR, width)
        self.var_meta.append(meta)
        return node


def _width(t: Term) -> int:
    return 1 if t.sort is terms.BOOL else t.width


def _lower_select(tape: _Tape, arr: Term, idx_node: int, idx_term: Term) -> int:
    """select(arr, idx) -> tape node, flattening store/ite chains."""
    rng_w = arr.sort[2]
    if arr.op == "store":
        base, s_idx, s_val = arr.args
        below = _lower_select(tape, base, idx_node, idx_term)
        hit = tape.emit(OP_EQ, 1, _node(tape, s_idx), idx_node)
        return tape.emit(OP_ITE, rng_w, hit, _node(tape, s_val), below)
    if arr.op == "ite":
        c, a, b = arr.args
        then = _lower_select(tape, a, idx_node, idx_term)
        els = _lower_select(tape, b, idx_node, idx_term)
        return tape.emit(OP_ITE, rng_w, _node(tape, c), then, els)
    if arr.op == "const_array":
        return _node(tape, arr.args[0])
    if arr.op == "array_var":
        var = tape.fresh(rng_w, ("select", arr, idx_term))
        tape.selects.setdefault(arr.tid, []).append((idx_node, var, idx_term))
        return var
    raise Unsupported(f"array op {arr.op}")


def _node(tape: _Tape, t: Term) -> int:
    return tape.node_of[t.tid]


def _serialize_node(tape: _Tape, t: Term) -> Optional[int]:
    op, a = t.op, t.args
    if op in ("array_var", "const_array", "store"):
        return None  # handled structurally at their select sites
    if op == "ite" and terms.is_array_sort(t.sort):
        return None  # consumed by select flattening
    w = _width(t)
    if op == "const":
        if t.sort is terms.BOOL:
            return tape.const(1 if t.aux else 0, 1)
        return tape.const(t.aux, w)
    if op == "var":
        return tape.fresh(w, ("scalar", t))
    if op == "select":
        return _lower_select(tape, a[0], _node(tape, a[1]), a[1])
    if op == "eq":
        if terms.is_array_sort(a[0].sort):
            raise Unsupported("array equality")
        return tape.emit(OP_EQ, 1, _node(tape, a[0]), _node(tape, a[1]))
    if op in ("and", "or"):
        code = OP_AND if op == "and" else OP_OR
        node = _node(tape, a[0])
        for x in a[1:]:
            node = tape.emit(code, 1, node, _node(tape, x))
        return node
    if op == "not":
        return tape.emit(OP_NOT, 1, _node(tape, a[0]))
    if op == "ite":
        return tape.emit(
            OP_ITE, w, _node(tape, a[0]), _node(tape, a[1]), _node(tape, a[2])
        )
    if op == "bvnot":
        return tape.emit(OP_BNOT, w, _node(tape, a[0]))
    if op == "bvneg":
        return tape.emit(OP_NEG, w, _node(tape, a[0]))
    if op == "concat":
        return tape.emit(OP_CONCAT, w, _node(tape, a[0]), _node(tape, a[1]))
    if op == "extract":
        hi, lo = t.aux
        return tape.emit(OP_EXTRACT, w, _node(tape, a[0]), x0=hi, x1=lo)
    if op == "zext":
        return tape.emit(OP_ZEXT, w, _node(tape, a[0]))
    if op == "sext":
        return tape.emit(OP_SEXT, w, _node(tape, a[0]))
    if op == "bvexp":
        return _serialize_exp(tape, t)
    if op == "keccak":
        var = tape.fresh(256, ("keccak", a[0]))
        tape.keccaks.append((_node(tape, a[0]), var, a[0]))
        return var
    if op == "apply":
        var = tape.fresh(w, ("apply", t))
        key = (t.aux, len(a))
        tape.applies.setdefault(key, []).append(
            ([_node(tape, x) for x in a], var)
        )
        return var
    code = _BINOP.get(op)
    if code is not None:
        return tape.emit(code, w, _node(tape, a[0]), _node(tape, a[1]))
    raise Unsupported(f"op {op}")


def _serialize_exp(tape: _Tape, t: Term) -> int:
    base, expo = t.args
    w = t.width
    if expo.is_const:
        e = expo.value
        if e > 64:
            raise Unsupported("huge constant exponent")
        result = tape.const(1, w)
        b = _node(tape, base)
        for bit in reversed(range(max(1, e.bit_length()))):
            result = tape.emit(OP_MUL, w, result, result)
            if (e >> bit) & 1:
                result = tape.emit(OP_MUL, w, result, b)
        return result
    if base.is_const and base.value != 0 and (base.value & (base.value - 1)) == 0:
        # (2^k)^e == 1 << (k*e), but k*e must be computed WITHOUT wrapping:
        # guard on e < ceil(w/k) (above which the true result is 0); inside
        # the guard k*e < w so the w-bit multiply is exact.
        k = base.value.bit_length() - 1
        if k == 0:  # base == 1
            return tape.const(1, w)
        bound = (w + k - 1) // k
        e_node = _node(tape, expo)
        e_small = tape.emit(OP_ULT, 1, e_node, tape.const(bound, w))
        shift = (
            tape.emit(OP_MUL, w, tape.const(k, w), e_node)
            if k != 1
            else e_node
        )
        shifted = tape.emit(OP_SHL, w, tape.const(1, w), shift)
        return tape.emit(OP_ITE, w, e_small, shifted, tape.const(0, w))
    raise Unsupported("bvexp with symbolic base and exponent")


def _add_congruence(tape: _Tape, pairs: List[Tuple[List[int], int]]):
    """For every pair of sites: args equal => results equal."""
    for i in range(len(pairs)):
        for j in range(i + 1, len(pairs)):
            _add_congruence_pair(tape, pairs[i], pairs[j])


def _add_congruence_pair(
    tape: _Tape, a: Tuple[List[int], int], b: Tuple[List[int], int]
):
    args_i, var_i = a
    args_j, var_j = b
    eqs = [tape.emit(OP_EQ, 1, x, y) for x, y in zip(args_i, args_j)]
    all_eq = eqs[0]
    for e in eqs[1:]:
        all_eq = tape.emit(OP_AND, 1, all_eq, e)
    out_eq = tape.emit(OP_EQ, 1, var_i, var_j)
    na = tape.emit(OP_NOT, 1, all_eq)
    tape.roots.append(tape.emit(OP_OR, 1, na, out_eq))


def _add_keccak_value(tape: _Tape, site: int, inp_val: int, true_hash: int):
    """Assert ``input == inp_val => output == keccak(inp_val)`` for one
    keccak site — a tautology of the real hash function (sound to add), and
    the lazy analogue of Z3's eager hash-value axioms: only the input values
    an actual model proposes ever get their hash pinned."""
    inp_node, var_node, inp_term = tape.keccaks[site]
    eq_in = tape.emit(OP_EQ, 1, inp_node, tape.const(inp_val, _width(inp_term)))
    eq_out = tape.emit(OP_EQ, 1, var_node, tape.const(true_hash, 256))
    tape.roots.append(
        tape.emit(OP_OR, 1, tape.emit(OP_NOT, 1, eq_in), eq_out)
    )


def _norm_idx(t: Term) -> Tuple[Optional[int], int]:
    """(base term id, constant offset) so provably-distinct indices can skip
    congruence: word reads are 32 selects at ``base + j`` — all C(32,2)
    pairwise constraints are identically true and need no clauses."""
    if t.is_const:
        return (None, t.value)
    if t.op == "bvadd":
        a, b = t.args
        if a.is_const:
            return (b.tid, a.value)
        if b.is_const:
            return (a.tid, b.value)
    return (t.tid, 0)


def _provably_distinct(t1: Term, t2: Term) -> bool:
    b1, o1 = _norm_idx(t1)
    b2, o2 = _norm_idx(t2)
    return b1 == b2 and o1 != o2


def _add_select_congruence(tape: _Tape) -> None:
    """Eager pairwise congruence for base-array selects, skipping pairs
    whose indices can never collide (same symbolic base, different constant
    offset — the dominant case for byte-addressed calldata/memory words)."""
    for sites in tape.selects.values():
        for i in range(len(sites)):
            for j in range(i + 1, len(sites)):
                idx_i, var_i, t_i = sites[i]
                idx_j, var_j, t_j = sites[j]
                if _provably_distinct(t_i, t_j):
                    continue
                _add_congruence_pair(tape, ([idx_i], var_i), ([idx_j], var_j))


def serialize(
    conjuncts: Sequence[Term],
    extra: Sequence[Term] = (),
    lazy_selects: bool = False,
) -> _Tape:
    """Serialize ``conjuncts`` as roots; ``extra`` terms (e.g. optimization
    objectives) are included in the DAG walk without being asserted.

    ``lazy_selects``: emit NO select-congruence constraints.  Dropping them
    only ADDS behaviors, so UNSAT stays sound; SAT models may violate
    congruence and must be refined (see ``solve``'s CEGAR loop).  Engine
    queries carry hundreds of select sites whose eager O(n^2) pairs blow
    the clause budget — refinement typically needs a handful of pairs."""
    tape = _Tape()
    for t in terms.topo_order(list(conjuncts) + list(extra)):
        node = _serialize_node(tape, t)
        if node is not None:
            tape.node_of[t.tid] = node
    tape.roots.extend(_node(tape, c) for c in conjuncts)
    if not lazy_selects:
        _add_select_congruence(tape)
    if tape.keccaks:
        _add_congruence(tape, [([inp], var) for inp, var, _ in tape.keccaks])
    for sites in tape.applies.values():
        _add_congruence(tape, sites)
    return tape


# ---------------------------------------------------------------------------
# Model reconstruction
# ---------------------------------------------------------------------------


def _rebuild_assignment(
    tape: _Tape, model: bytes
) -> Tuple[Assignment, List[Tuple[int, int, int]], List[Tuple[int, int, int]]]:
    """Parse packed VAR bits, then resolve array/UF sites in topo order.

    Tape order IS topo order of the original DAG, so by the time a select's
    value is installed every sub-select inside its index expression has
    already been written into the ArrayValue backing — concrete evaluation
    of the index under the partial assignment is exact.

    Returns (assignment, violations, kec_mismatches):

    * ``violations`` lists select-site pairs ``(arr_tid, site_i, site_j)``
      that read the SAME concrete index but were assigned DIFFERENT values
      — possible only under lazy congruence
      (``serialize(..., lazy_selects=True)``); the CEGAR loop in ``solve``
      asserts exactly those pairs and re-solves.
    * ``kec_mismatches`` lists keccak sites ``(site, input_value, true_hash)``
      whose input evaluates concretely under the assignment but whose model
      value differs from the REAL keccak256 of that input.  The CEGAR loop
      asserts ``input == value => output == keccak(value)`` — a fact of the
      actual hash function, so soundness is untouched — and re-solves; the
      refined model then carries real hash values (and hash-distinctness of
      distinct concrete inputs follows for free), closing the queries whose
      verdict depends on hash semantics instead of burning their budget on
      host-validation failures.
    """
    values: List[int] = []
    off = 0
    for op, width, *_ in tape.records:
        if op != OP_VAR:
            continue
        nbytes = (width + 7) // 8
        values.append(int.from_bytes(model[off : off + nbytes], "little"))
        off += nbytes
    asg = Assignment()
    deferred = []  # (kind, payload, value) resolved in tape order
    for (meta, value) in zip(tape.var_meta, values):
        kind = meta[0]
        if kind == "scalar":
            t = meta[1]
            asg.scalars[t] = bool(value) if t.sort is terms.BOOL else value
        else:
            deferred.append((meta, value))
    violations: List[Tuple[int, int, int]] = []
    kec_mismatches: List[Tuple[int, int, int]] = []
    site_no: Dict[int, int] = {}
    kec_site = 0
    writer: Dict[Tuple[int, int], Tuple[int, int]] = {}
    for meta, value in deferred:
        kind = meta[0]
        if kind == "select":
            _, arr, idx_term = meta
            si = site_no.get(arr.tid, 0)
            site_no[arr.tid] = si + 1
            idx_val = evaluate([idx_term], asg)[idx_term]
            prev = writer.get((arr.tid, idx_val))
            if prev is not None:
                if prev[1] != value:
                    violations.append((arr.tid, prev[0], si))
                continue  # first writer's value stands
            writer[(arr.tid, idx_val)] = (si, value)
            asg.arrays.setdefault(arr, ArrayValue()).backing[idx_val] = value
        elif kind == "apply":
            t = meta[1]
            arg_vals = tuple(evaluate([x], asg)[x] for x in t.args)
            asg.ufs[(t.aux, arg_vals)] = value
        elif kind == "keccak":
            # NOT installed in asg — validation recomputes real hashes.
            # Instead, compare the model's value against the true hash of
            # the concretely-evaluated input (evaluate() resolves nested
            # keccaks to their REAL hashes, so chained sites converge in
            # one refinement round each).
            si, kec_site = kec_site, kec_site + 1
            inp = meta[1]
            try:
                inp_val = evaluate([inp], asg)[inp]
            except NotImplementedError:
                continue
            true_hash = keccak256_int(inp_val, _width(inp) // 8)
            if value != true_hash:
                kec_mismatches.append((si, inp_val, true_hash))
    return asg, violations, kec_mismatches


# ---------------------------------------------------------------------------
# Entry point
# ---------------------------------------------------------------------------


def _run_solver(lib, tape: _Tape, timeout_s: float) -> Tuple[int, bytes]:
    rec = np.asarray(tape.records, dtype=np.int32).reshape(-1)
    consts = np.frombuffer(bytes(tape.consts) or b"\x00", dtype=np.uint8)
    roots = np.asarray(tape.roots, dtype=np.int32)
    model_size = sum(
        (w + 7) // 8 for op, w, *_ in tape.records if op == OP_VAR
    )
    model = np.zeros(max(1, model_size), dtype=np.uint8)
    status = lib.bb_solve(
        rec.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        len(tape.records),
        consts.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        len(consts),
        roots.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        len(roots),
        float(timeout_s),
        model.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        len(model),
    )
    return status, model.tobytes()


# Refinement rounds are cheap (a re-blast costs well under a second at
# engine query sizes) and byte-addressed aliasing chains legitimately need
# several: a wrapped-pointer UNSAT proof on the BECToken shape converges in
# 5 rounds / ~4s where eager congruence exceeded the clause budget outright.
_CEGAR_ROUNDS = 12

# Keccak value refinement is value-ENUMERATING: a query whose hash demand
# no input can meet proposes a fresh input every round, so it must be
# bounded separately (distinctness proofs and chained hashes converge in
# 1-2 rounds; past this cap the answer degrades to UNKNOWN exactly as the
# pre-CEGAR code did after one round).
_KECCAK_ROUNDS = 3


def _model_validates(conjuncts: Sequence[Term], asg: Assignment) -> bool:
    """Evaluate the conjunction under the model with REAL keccak semantics.

    A model whose keccak CNF values are fake but whose real-hash evaluation
    still satisfies every conjunct is a perfectly good model (the formula
    never observed the fake values) — returning it immediately keeps the
    pre-CEGAR fast path; refinement only runs when hash semantics actually
    bite."""
    try:
        vals = evaluate(list(conjuncts), asg)
        return all(vals[c] for c in conjuncts)
    except Exception:
        return False


def solve(
    conjuncts: Sequence[Term], timeout_s: float
) -> Tuple[str, Optional[Assignment]]:
    """Exact solve; returns (status, assignment-or-None).

    Select congruence is LAZY (CEGAR): the first blast asserts none of the
    O(n^2) select-congruence pairs (sound for UNSAT — dropping constraints
    only adds behaviors); when a SAT model assigns different values to two
    selects whose indices evaluate equal, exactly those pairs are asserted
    and the formula re-solved.  Engine-scale queries carry hundreds of
    select sites; eager congruence used to exceed the clause budget, while
    refinement virtually always needs zero or a handful of pairs.

    SAT models are reconstructed but NOT validated here — the caller owns
    validation (mythril_tpu/smt/solver.py re-checks with concrete_eval).
    """
    import time as _time

    lib = _load()
    if lib is None or timeout_s <= 0:
        return UNKNOWN, None
    deadline = _time.perf_counter() + timeout_s
    refine: List[Tuple[int, int, int]] = []
    kec_refine: List[Tuple[int, int, int]] = []
    kec_done: set = set()
    kec_rounds = 0
    try:
        # one serialization: the tape is append-only, so refinement rounds
        # just add congruence pairs to the same records/roots
        tape = serialize(conjuncts, lazy_selects=True)
    except Unsupported as e:
        log.debug("native tier: %s", e)
        return UNKNOWN, None
    for _round in range(_CEGAR_ROUNDS):
        try:
            for arr_tid, i, j in refine:
                sites = tape.selects.get(arr_tid)
                if sites is None or i >= len(sites) or j >= len(sites):
                    continue
                idx_i, var_i, _ = sites[i]
                idx_j, var_j, _ = sites[j]
                _add_congruence_pair(tape, ([idx_i], var_i), ([idx_j], var_j))
            for site, inp_val, true_hash in kec_refine:
                _add_keccak_value(tape, site, inp_val, true_hash)
        except Unsupported as e:
            # tape cap reached mid-refinement: degrade instead of raising
            # into the engine query (the session path does the same)
            log.debug("refinement hit tape cap: %s", e)
            return UNKNOWN, None
        refine, kec_refine = [], []
        remaining = deadline - _time.perf_counter()
        if remaining <= 0:
            return UNKNOWN, None
        status, model = _run_solver(lib, tape, remaining)
        if status == 0:
            return UNSAT, None
        if status != 1:
            return UNKNOWN, None
        try:
            asg, violations, kec_mm = _rebuild_assignment(tape, model)
        except Exception as e:  # reconstruction must never crash the solver
            log.debug("native model reconstruction failed: %s", e)
            return UNKNOWN, None
        # an already-asserted (site, input) pair cannot recur with a wrong
        # value in a model of the CNF; the guard protects the loop anyway
        kec_mm = [
            m for m in kec_mm if (m[0], m[1]) not in kec_done
        ]
        if not violations and not kec_mm:
            return SAT, asg
        if not violations and kec_mm and _model_validates(conjuncts, asg):
            return SAT, asg  # fake hash values were never observed
        # the keccak cap counts only PURE keccak rounds: a round that also
        # refines select congruence is productive regardless of whether the
        # model proposed a fresh hash input alongside
        if kec_mm and not violations:
            kec_rounds += 1
            if kec_rounds > _KECCAK_ROUNDS:
                return UNKNOWN, None
        # violated pairs are by construction not yet asserted (an asserted
        # pair cannot be violated by a model of the CNF)
        refine = violations
        kec_refine = kec_mm
        kec_done.update((m[0], m[1]) for m in kec_mm)
    return UNKNOWN, None


# ---------------------------------------------------------------------------
# Incremental session: bound refinement for Optimize
# ---------------------------------------------------------------------------


class OptimizeSession:
    """Blast a conjunction ONCE and answer many objective-bound queries.

    For each objective the tape gains a fresh bound vector ``M`` plus enable
    booleans wired as ``en_le => obj <= M``, ``en_ge => M <= obj``,
    ``en_eq => obj == M``; a query assumes one enable literal and M's bits.
    The CDCL state (learned clauses, activity, phases) persists across
    queries, so the Optimize binary search pays circuit construction once
    instead of once per bound — the z3-incremental-optimize analogue the
    reference gets from ``z3.Optimize`` (mythril/analysis/solver.py:216-256).

    ``guarded`` terms are additionally compiled behind per-term enable
    literals (``en_i => guarded[i]``): one blast serves a whole family of
    sibling queries that differ by one conjunct each — the transaction-end
    issue-confirmation gate, where every parked issue shares the full path
    prefix (analysis/potential_issues.py).

    UNSAT answers are exact (abstractions only add behaviors, see module
    docstring); SAT models must be validated by the caller exactly like
    ``solve``'s.
    """

    def __init__(
        self,
        conjuncts: Sequence[Term],
        objectives: Sequence[Term] = (),
        guarded: Sequence[Term] = (),
    ):
        lib = _load()
        if lib is None:
            raise Unsupported("native library unavailable")
        # select congruence is lazy here too: engine-scale conjunctions
        # (wide-mul overflow encodings + hundreds of select sites) exceed
        # the clause budget eagerly; violated pairs are appended to the
        # LIVE session via bb_extend, keeping all learned clauses
        tape = serialize(
            conjuncts,
            extra=list(objectives) + list(guarded),
            lazy_selects=True,
        )
        self._conjuncts = list(conjuncts)
        self._objectives = list(objectives)
        self._guarded = list(guarded)
        self._controls = []  # per objective: (m_node, width, {op: en_node})
        for i, obj in enumerate(objectives):
            w = obj.width
            obj_node = tape.node_of[obj.tid]
            m_var = terms.var(f"__optimize_bound_{i}", w)
            m_node = tape.fresh(w, ("scalar", m_var))
            ens = {}
            for op_name, cmp_node in (
                ("le", tape.emit(OP_ULE, 1, obj_node, m_node)),
                ("ge", tape.emit(OP_ULE, 1, m_node, obj_node)),
                ("eq", tape.emit(OP_EQ, 1, obj_node, m_node)),
            ):
                en_var = terms.var(f"__optimize_en_{op_name}_{i}", 1)
                en_node = tape.fresh(1, ("scalar", en_var))
                not_en = tape.emit(OP_NOT, 1, en_node)
                tape.roots.append(tape.emit(OP_OR, 1, not_en, cmp_node))
                ens[op_name] = en_node
            self._controls.append((m_node, w, ens))
        self._guards = []  # per guarded term: its enable node
        for i, g in enumerate(guarded):
            g_node = tape.node_of[g.tid]
            en_var = terms.var(f"__guard_en_{i}", 1)
            en_node = tape.fresh(1, ("scalar", en_var))
            not_en = tape.emit(OP_NOT, 1, en_node)
            tape.roots.append(tape.emit(OP_OR, 1, not_en, g_node))
            self._guards.append(en_node)
        self._tape = tape
        rec = np.asarray(tape.records, dtype=np.int32).reshape(-1)
        consts = np.frombuffer(bytes(tape.consts) or b"\x00", dtype=np.uint8)
        roots = np.asarray(tape.roots, dtype=np.int32)
        self._model_size = sum(
            (w + 7) // 8 for op, w, *_ in tape.records if op == OP_VAR
        )
        self._lib = lib
        self._handle = lib.bb_open(
            rec.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            len(tape.records),
            consts.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            len(consts),
            roots.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            len(roots),
        )
        if not self._handle:
            raise Unsupported("session open failed")

    def solve(
        self,
        bounds: Sequence[Tuple[int, str, int]],
        timeout_s: float,
        enable: Sequence[int] = (),
    ) -> Tuple[str, Optional[Assignment]]:
        """Solve under objective bounds [(obj_index, 'le'|'ge'|'eq', value)]
        and with the given guarded terms enabled (indices into ``guarded``).

        Congruence-violating models trigger in-place refinement (violated
        pairs appended to the live session via bb_extend) and a re-solve
        within the same timeout.  Returns (status, assignment-or-None); SAT
        models are congruence-clean but otherwise unvalidated (caller
        validates with the exact evaluator, as for ``solve``)."""
        import time as _time

        if self._handle is None:
            return UNKNOWN, None
        deadline = _time.perf_counter() + timeout_s
        kec_done: set = set()
        kec_rounds = 0
        for _round in range(_CEGAR_ROUNDS):
            remaining = deadline - _time.perf_counter()
            if remaining <= 0:
                return UNKNOWN, None
            status, asg, violations, kec_mm = self._solve_once(
                bounds, remaining, enable
            )
            kec_mm = [m for m in kec_mm if (m[0], m[1]) not in kec_done]
            if status != SAT or (not violations and not kec_mm):
                return status, asg
            if (
                not violations
                and kec_mm
                and self._query_validates(asg, bounds, enable)
            ):
                return SAT, asg  # fake hash values were never observed
            if kec_mm and not violations:  # pure keccak rounds only
                kec_rounds += 1
                if kec_rounds > _KECCAK_ROUNDS:
                    return UNKNOWN, None
            kec_done.update((m[0], m[1]) for m in kec_mm)
            ext = self._extend_refinements(violations, kec_mm)
            if ext == 0:
                return UNSAT, None  # refinement constraints closed the formula
            if ext != 1:
                return UNKNOWN, None
        return UNKNOWN, None

    def _query_validates(self, asg, bounds, enable) -> bool:
        """Real-keccak validation of THIS query: base conjuncts, the enabled
        guarded terms, and the assumed objective bounds must all hold."""
        checks = list(self._conjuncts) + [self._guarded[i] for i in enable]
        if not _model_validates(checks, asg):
            return False
        try:
            for idx, op_name, value in bounds:
                obj = self._objectives[idx]
                got = evaluate([obj], asg)[obj]
                if op_name == "le" and not got <= value:
                    return False
                if op_name == "ge" and not got >= value:
                    return False
                if op_name == "eq" and got != value:
                    return False
        except Exception:
            return False
        return True

    def _solve_once(
        self,
        bounds: Sequence[Tuple[int, str, int]],
        timeout_s: float,
        enable: Sequence[int],
    ):
        assume: List[int] = []
        for gi in enable:
            assume.append((self._guards[gi] << 16) | 1)
        for idx, op_name, value in bounds:
            m_node, w, ens = self._controls[idx]
            assume.append((ens[op_name] << 16) | 1)
            for bit in range(w):
                assume.append(
                    (m_node << 16) | (bit << 1) | ((value >> bit) & 1)
                )
        arr = np.asarray(assume, dtype=np.int64)
        model = np.zeros(max(1, self._model_size), dtype=np.uint8)
        status = self._lib.bb_solve_assume(
            self._handle,
            arr.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            len(arr),
            float(timeout_s),
            model.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            len(model),
        )
        if status == 0:
            return UNSAT, None, (), ()
        if status != 1:
            return UNKNOWN, None, (), ()
        try:
            asg, violations, kec_mm = _rebuild_assignment(
                self._tape, model.tobytes()
            )
            return SAT, asg, violations, kec_mm
        except Exception as e:
            log.debug("session model reconstruction failed: %s", e)
            return UNKNOWN, None, (), ()

    def _extend_refinements(self, violations, kec_mm=()) -> int:
        """Append refinement constraints (select-congruence pairs and/or
        keccak value implications) to the live native session.  The tape is
        append-only; only the delta records and delta roots cross the
        boundary (const offsets are absolute into the full consts buffer,
        which is re-passed).  Returns the bb_extend status: 1 ok, 0 formula
        now unsat, -1 unusable."""
        rec_mark = len(self._tape.records)
        root_mark = len(self._tape.roots)
        try:
            for arr_tid, i, j in violations:
                sites = self._tape.selects.get(arr_tid)
                if not sites or i >= len(sites) or j >= len(sites):
                    continue
                idx_i, var_i, _ = sites[i]
                idx_j, var_j, _ = sites[j]
                _add_congruence_pair(
                    self._tape, ([idx_i], var_i), ([idx_j], var_j)
                )
            for site, inp_val, true_hash in kec_mm:
                _add_keccak_value(self._tape, site, inp_val, true_hash)
        except Unsupported as e:
            # tape cap reached mid-refinement: the callers treat -1 as
            # UNKNOWN and degrade; an exception here would abort the whole
            # transaction-end issue sweep
            log.debug("session refinement hit tape cap: %s", e)
            return -1
        n_new = len(self._tape.records) - rec_mark
        new_roots = self._tape.roots[root_mark:]
        if n_new == 0 and not new_roots:
            return -1
        delta = np.asarray(
            self._tape.records[rec_mark:], dtype=np.int32
        ).reshape(-1)
        consts = np.frombuffer(
            bytes(self._tape.consts) or b"\x00", dtype=np.uint8
        )
        roots = np.asarray(new_roots, dtype=np.int32)
        return self._lib.bb_extend(
            self._handle,
            delta.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            n_new,
            consts.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            len(consts),
            roots.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            len(roots),
        )

    def close(self) -> None:
        if self._handle is not None:
            self._lib.bb_close(self._handle)
            self._handle = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):  # pragma: no cover - GC safety net
        try:
            self.close()
        except Exception:
            pass
