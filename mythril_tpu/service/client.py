"""Thin client for the analysis service (``myth submit``).

One TCP connection per submission: write the request line, then iterate
the event lines the daemon streams back.  ``submit_stream`` yields each
event dict as it arrives (issues the moment they confirm); ``submit``
collects and returns the terminal summary.
"""

from __future__ import annotations

import json
import socket
from typing import Any, Dict, Iterator, List, Optional, Sequence

__all__ = ["ServiceClient"]


class ServiceClient:
    def __init__(self, host: str = "127.0.0.1", port: int = 7344,
                 timeout: Optional[float] = 300.0):
        self.host = host
        self.port = port
        self.timeout = timeout

    # -- plumbing ------------------------------------------------------

    def _roundtrip(self, msg: Dict[str, Any]) -> Iterator[Dict[str, Any]]:
        with socket.create_connection(
            (self.host, self.port), timeout=self.timeout
        ) as sock:
            sock.sendall((json.dumps(msg) + "\n").encode())
            with sock.makefile("r", encoding="utf-8") as rf:
                for line in rf:
                    line = line.strip()
                    if line:
                        yield json.loads(line)

    # -- API -----------------------------------------------------------

    def ping(self) -> bool:
        for event in self._roundtrip({"op": "ping"}):
            return event.get("event") == "pong"
        return False

    def stats(self) -> Dict[str, Any]:
        for event in self._roundtrip({"op": "stats"}):
            return event
        return {}

    def metrics(self) -> str:
        """The daemon's registry in Prometheus text exposition format."""
        for event in self._roundtrip({"op": "metrics"}):
            return event.get("text", "")
        return ""

    def submit_stream(
        self,
        code: str,
        name: Optional[str] = None,
        tier: str = "batch",
        transaction_count: Optional[int] = None,
        modules: Optional[Sequence[str]] = None,
        strategy: Optional[str] = None,
        execution_timeout: Optional[int] = None,
        tenant: Optional[str] = None,
    ) -> Iterator[Dict[str, Any]]:
        """Yield event dicts: ``accepted``, ``issue``*, ``done``/``error``."""
        msg: Dict[str, Any] = {"op": "submit", "code": code, "tier": tier}
        if name:
            msg["name"] = name
        if tenant:
            msg["tenant"] = tenant
        if transaction_count is not None:
            msg["transaction_count"] = transaction_count
        if modules:
            msg["modules"] = list(modules)
        if strategy:
            msg["strategy"] = strategy
        if execution_timeout is not None:
            msg["execution_timeout"] = execution_timeout
        terminal = False
        for event in self._roundtrip(msg):
            yield event
            if event.get("event") in ("done", "error"):
                terminal = True
                break
        if not terminal:
            raise ConnectionError(
                "server closed the stream before a terminal event"
            )

    def submit(self, code: str, **kwargs) -> Dict[str, Any]:
        """Blocking submit; returns the ``done`` summary.

        The summary's ``issues`` list is authoritative; ``streamed``
        carries the incrementally received issue events (a superset
        check for the determinism tests).  Raises ``RuntimeError`` on a
        per-request analysis failure.
        """
        streamed: List[Dict[str, Any]] = []
        accepted: Dict[str, Any] = {}
        for event in self.submit_stream(code, **kwargs):
            kind = event.get("event")
            if kind == "accepted":
                accepted = event
            elif kind == "issue":
                streamed.append(event)
            elif kind == "error":
                raise RuntimeError(f"analysis failed: {event.get('error')}")
            elif kind == "done":
                out = dict(event)
                out["streamed"] = streamed
                out["request_id"] = accepted.get("request_id")
                out["deduped"] = accepted.get("deduped", False)
                return out
        raise ConnectionError("stream ended without terminal event")
