"""Taint bits over the arena row graph: device-evaluated detector sources.

Host taint is annotation objects on smt wrappers, installed by detector
post-hooks on taint-source opcodes (reference
mythril/analysis/module/modules/dependence_on_origin.py:60-66: ORIGIN's
result is annotated, a JUMPI whose condition carries the annotation raises
the issue).  On the device frontier, every value is an arena row and every
row records the rows it was computed from — the ref graph IS an exact
dataflow (taint) relation, computed for free by the segment.  So a
taint-source hook needs NO device event and NO host replay: the engine
seeds the source's env row with a taint bit (`HostArena.add_taint`), and
the walker, when decoding any row at a sink (a JUMPI condition, a CALL
argument), unions in the annotations synthesized from the taint bits
reachable in the row's dependency closure — the same reachability the
host's operator-level annotation unions compute.

A detection module opts in by declaring ``taint_source_hooks`` (see
analysis/module/base.py): a mapping from hooked opcode to the taint bit
that reproduces its post-hook's only effect.  When EVERY hook on an opcode
is so declared, the engine drops the opcode from the evented set entirely
(frontier/engine._hook_info) — unlike ``concrete_nop_hooks``, which still
events on symbolic operands, a taint-source opcode never ships an event.

The registry below maps bits to annotation factories (used by the walker
to synthesize instances) and matchers (used by the mid-frame encoder to
map a host wrapper's annotations back to bits when a host-stepped state
re-enters the device).  Modules register at import; unregistered bits
synthesize nothing, so seeding is harmless when a module is disabled.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Tuple

TAINT_ORIGIN = 1 << 0
TAINT_TIMESTAMP = 1 << 1
TAINT_NUMBER = 1 << 2
TAINT_COINBASE = 1 << 3
TAINT_GASLIMIT = 1 << 4
TAINT_BLOCKHASH = 1 << 5

from mythril_tpu.frontier.code import (
    CTX_COINBASE, CTX_GASLIMIT, CTX_NUMBER, CTX_ORIGIN, CTX_TIMESTAMP,
)

# THE table tying each seedable bit to the env ctx slot whose row carries
# it: engine._seed_ctx iterates this to seed, and ``suppressible`` guards
# event suppression with it — one source of truth, so a bit cannot be
# declared suppressible without also being seeded.  The row at each slot
# must be DEDICATED (arena.fresh_var_row), never interned — see
# _seed_ctx's no_fold/aliasing comments.  BLOCKHASH is deliberately
# absent: it parks on device, so its host hooks always run.
ENV_SOURCE_SLOTS = {
    TAINT_ORIGIN: CTX_ORIGIN,
    TAINT_TIMESTAMP: CTX_TIMESTAMP,
    TAINT_NUMBER: CTX_NUMBER,
    TAINT_COINBASE: CTX_COINBASE,
    TAINT_GASLIMIT: CTX_GASLIMIT,
}
SEEDED_BITS = frozenset(ENV_SOURCE_SLOTS)

# bit -> the opcode whose result carries it.  The static pre-analysis
# (mythril_tpu/staticpass) keys its may_reach relation on this table:
# a bit's flow starts at every reachable instruction of its source
# opcode.  BLOCKHASH appears here even though it is not device-seeded
# (it parks): static reachability covers host-installed annotations too.
SOURCE_OPCODES = {
    TAINT_ORIGIN: "ORIGIN",
    TAINT_TIMESTAMP: "TIMESTAMP",
    TAINT_NUMBER: "NUMBER",
    TAINT_COINBASE: "COINBASE",
    TAINT_GASLIMIT: "GASLIMIT",
    TAINT_BLOCKHASH: "BLOCKHASH",
}


def suppressible(bit: int) -> bool:
    """True when dropping a source hook's device events is safe: the engine
    seeds the bit (ENV_SOURCE_SLOTS) and a registered factory can
    synthesize the annotation."""
    return bit in SEEDED_BITS and bit in _factories

# bit -> () -> annotation instance (singletons: annotations are inspected
# by isinstance / attribute only, never mutated per-site)
_factories: Dict[int, Callable[[], object]] = {}
_singletons: Dict[int, object] = {}
# (bit, annotation -> bool): reverse mapping for host->device re-entry
_matchers: List[Tuple[int, Callable[[object], bool]]] = []


def register(bit: int, factory: Callable[[], object],
             matcher: Callable[[object], bool]) -> None:
    """Bind a taint bit to its annotation class.

    Idempotent for the SAME factory object (module re-imports).  A
    different factory on an already-bound bit raises: two detectors
    sharing one bit would synthesize the wrong annotation class at every
    sink, and the static pass keys its reachability on these bits — the
    invariant must be enforced, not assumed.
    """
    if bit <= 0 or (bit & (bit - 1)):
        raise ValueError(f"taint bit must be a single set bit, got {bit:#x}")
    if bit in _factories:
        if _factories[bit] is factory:
            return
        raise ValueError(
            f"taint bit {bit:#x} already registered with a different factory"
        )
    _factories[bit] = factory
    _matchers.append((bit, matcher))


def annotations_for_mask(mask: int) -> Tuple[object, ...]:
    """Synthesized annotation instances for a row taint mask, in ascending
    bit order (deterministic: the first predictable-op annotation names the
    operation in the issue text, so the order must not depend on dict or
    scheduling state)."""
    if not mask:
        return ()
    out = []
    for bit in sorted(_factories):
        if mask & bit:
            inst = _singletons.get(bit)
            if inst is None:
                inst = _singletons[bit] = _factories[bit]()
            out.append(inst)
    return tuple(out)


def mask_for_annotations(annotations) -> int:
    """Taint bits equivalent to a host wrapper's annotations (mid-frame
    device re-entry: a host-installed annotation must survive as a bit on
    the encoded row or the sink check would miss it)."""
    mask = 0
    for a in annotations:
        for bit, match in _matchers:
            if match(a):
                mask |= bit
                break
    return mask
