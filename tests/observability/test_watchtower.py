"""Watchtower SLO engine: evaluation states, breach edges, captures, config."""

import json

import pytest

from mythril_tpu.observability.metrics import get_registry, prometheus_text
from mythril_tpu.observability.watchtower import (
    STATUS_BREACH,
    STATUS_OK,
    Objective,
    Watchtower,
    default_objectives,
    load_slo_file,
)


@pytest.fixture(autouse=True)
def _clean_slo_metrics():
    reg = get_registry()
    yield
    reg.reset(include_persistent=True, prefix="slo.")


def _hist_value(bc, mn=None, mx=None):
    return {"c": sum(bc), "s": 0.0, "mn": mn, "mx": mx, "bc": list(bc)}


class FakeSource:
    """Scripted (values, bounds) snapshots for deterministic ticks."""

    def __init__(self, bounds=None):
        self.values = {}
        self.bounds = bounds or {}

    def __call__(self):
        return dict(self.values), dict(self.bounds)


def _tower(tmp_path, objectives, source, **kw):
    return Watchtower(
        str(tmp_path), objectives=objectives, interval_s=1.0,
        source=source, **kw)


def test_quantile_objective_ok_then_breach(tmp_path):
    src = FakeSource(bounds={"service.ttfe_s": (0.1, 1.0, 10.0)})
    obj = Objective("ttfe_p95", "quantile", "service.ttfe_s", target=1.0,
                    fast_window_s=10, slow_window_s=30)
    wt = _tower(tmp_path, [obj], src)
    try:
        evals = wt.tick(now=0.0)
        assert evals["ttfe_p95"]["state"] == "no_data"

        src.values["service.ttfe_s"] = _hist_value([3, 0, 0, 0], mx=0.05)
        evals = wt.tick(now=1.0)
        assert evals["ttfe_p95"]["state"] == "ok"
        assert evals["ttfe_p95"]["status"] == STATUS_OK

        src.values["service.ttfe_s"] = _hist_value([3, 0, 4, 0], mx=8.0)
        evals = wt.tick(now=2.0)
        # fast window violates and the slow window agrees (same data):
        # a breach, not a warn
        assert evals["ttfe_p95"]["state"] == "breach"
        assert evals["ttfe_p95"]["status"] == STATUS_BREACH
        assert evals["ttfe_p95"]["value"] > 1.0
    finally:
        wt.stop()


def test_ratio_objective_min_count_gate(tmp_path):
    src = FakeSource()
    obj = Objective("error_rate", "ratio", "service.request_errors",
                    denominator="service.requests", target=0.05,
                    min_count=5, fast_window_s=10, slow_window_s=30)
    wt = _tower(tmp_path, [obj], src)
    try:
        src.values = {"service.requests": 2, "service.request_errors": 2}
        evals = wt.tick(now=0.0)
        # denominator below min_count: no data, NOT a 100% error rate
        assert evals["error_rate"]["state"] == "no_data"

        src.values = {"service.requests": 10, "service.request_errors": 2}
        evals = wt.tick(now=1.0)
        assert evals["error_rate"]["state"] == "breach"
        assert evals["error_rate"]["value"] == pytest.approx(0.2)

        src.values = {"service.requests": 200, "service.request_errors": 2}
        evals = wt.tick(now=2.0)
        assert evals["error_rate"]["state"] == "ok"
    finally:
        wt.stop()


def test_gauge_floor_objective(tmp_path):
    src = FakeSource()
    obj = Objective("worker_liveness", "gauge", "service.workers",
                    target=2.0, op=">=")
    wt = _tower(tmp_path, [obj], src)
    try:
        src.values = {"service.workers": 2}
        assert wt.tick(now=0.0)["worker_liveness"]["state"] == "ok"
        src.values = {"service.workers": 1}
        assert wt.tick(now=1.0)["worker_liveness"]["state"] == "breach"
    finally:
        wt.stop()


def test_breach_edge_counts_once_and_recovers(tmp_path):
    reg = get_registry()
    src = FakeSource()
    obj = Objective("liveness", "gauge", "service.workers",
                    target=2.0, op=">=")
    wt = _tower(tmp_path, [obj], src)
    try:
        base = reg.counter("slo.breaches_total", persistent=True).value
        src.values = {"service.workers": 1}
        wt.tick(now=0.0)
        wt.tick(now=1.0)
        wt.tick(now=2.0)
        # three breaching ticks = ONE breach edge
        assert reg.counter("slo.breaches_total",
                           persistent=True).value == base + 1
        src.values = {"service.workers": 2}
        wt.tick(now=3.0)
        assert wt.health()["ok"] is True
        src.values = {"service.workers": 0}
        wt.tick(now=4.0)
        # a fresh ok->breach edge counts again
        assert reg.counter("slo.breaches_total",
                           persistent=True).value == base + 2
        assert dict(reg.labeled_counter(
            "slo.breaches", persistent=True))["liveness"] == 2
    finally:
        wt.stop()


def test_capture_fires_on_breach_with_cooldown(tmp_path):
    src = FakeSource()
    fired = []

    def hook(objective, evaluation):
        fired.append(objective.name)
        return {"bundle": f"/tmp/{objective.name}.json"}

    obj = Objective("liveness", "gauge", "service.workers",
                    target=2.0, op=">=")
    wt = _tower(tmp_path, [obj], src, capture=hook,
                capture_cooldown_s=10.0)
    try:
        src.values = {"service.workers": 1}
        wt.tick(now=1000.0)
        wt.tick(now=1005.0)  # inside cooldown: no second capture
        wt.tick(now=1011.0)  # past cooldown while still breaching: fires
        assert fired == ["liveness", "liveness"]
        caps = list(wt.captures)
        assert caps[0]["objective"] == "liveness"
        assert caps[0]["bundle"].endswith("liveness.json")
    finally:
        wt.stop()


def test_capture_exception_does_not_kill_tick(tmp_path):
    src = FakeSource()

    def hook(objective, evaluation):
        raise RuntimeError("capture backend down")

    obj = Objective("liveness", "gauge", "service.workers",
                    target=2.0, op=">=")
    wt = _tower(tmp_path, [obj], src, capture=hook)
    try:
        src.values = {"service.workers": 0}
        evals = wt.tick(now=0.0)
        assert evals["liveness"]["state"] == "breach"
        assert wt.health()["breaches_total"] >= 1
    finally:
        wt.stop()


def test_health_and_status_line(tmp_path):
    src = FakeSource()
    obj = Objective("liveness", "gauge", "service.workers",
                    target=2.0, op=">=")
    wt = _tower(tmp_path, [obj], src)
    try:
        src.values = {"service.workers": 2}
        wt.tick(now=0.0)
        h = wt.health()
        assert h["enabled"] and h["ok"] and h["breaching"] == []
        assert "slo: ok (1 objective" in wt.status_line()
        src.values = {"service.workers": 0}
        wt.tick(now=1.0)
        assert wt.status_line().startswith("SLO BREACH: liveness")
        # prometheus rendering: per-objective label from the dict gauge
        text = prometheus_text()
        assert 'slo_status{objective="liveness"} 2' in text
    finally:
        wt.stop()


def test_background_thread_ticks(tmp_path):
    import time

    src = FakeSource()
    src.values = {"service.workers": 1}
    wt = Watchtower(str(tmp_path), objectives=[], interval_s=0.05,
                    source=src)
    wt.start()
    try:
        deadline = time.time() + 5.0
        while wt.ticks < 2 and time.time() < deadline:
            time.sleep(0.02)
        assert wt.ticks >= 2
        assert wt.overhead_pct() >= 0.0
    finally:
        wt.stop()
    assert not wt.running


def test_default_objectives_worker_liveness_gated():
    names = {o.name for o in default_objectives(workers=1)}
    assert "ttfe_p95" in names and "error_rate" in names
    assert "worker_liveness" not in names
    pool = {o.name for o in default_objectives(workers=4)}
    assert "worker_liveness" in pool
    liveness = next(o for o in default_objectives(workers=4)
                    if o.name == "worker_liveness")
    assert liveness.target == 4.0 and liveness.op == ">="


# -- --slo FILE parsing ---------------------------------------------------


def test_load_slo_file_json_and_options(tmp_path):
    path = tmp_path / "slo.json"
    path.write_text(json.dumps({
        "interval_s": 2.5,
        "capture": {"profile": False},
        "objectives": [
            {"name": "ttfe_p95", "kind": "quantile",
             "metric": "service.ttfe_s", "target": 2.0, "q": 0.95},
        ],
    }))
    objectives, options = load_slo_file(str(path))
    assert len(objectives) == 1
    assert objectives[0].name == "ttfe_p95"
    assert objectives[0].q == 0.95
    assert options["interval_s"] == 2.5
    assert options["capture"] == {"profile": False}


def test_load_slo_file_yaml(tmp_path):
    pytest.importorskip("yaml")
    path = tmp_path / "slo.yaml"
    path.write_text(
        "interval_s: 1.0\n"
        "objectives:\n"
        "  - name: error_rate\n"
        "    kind: ratio\n"
        "    metric: service.request_errors\n"
        "    denominator: service.requests\n"
        "    target: 0.05\n"
    )
    objectives, options = load_slo_file(str(path))
    assert objectives[0].kind == "ratio"
    assert objectives[0].denominator == "service.requests"


@pytest.mark.parametrize("doc,msg", [
    ([], "mapping"),
    ({"objectives": []}, "required"),
    ({"objectives": [{"name": "x", "kind": "quantile",
                      "metric": "m", "target": 1, "bogus": 2}]},
     "unknown keys"),
    ({"objectives": [{"name": "x", "kind": "quantile"}]}, "missing"),
    ({"objectives": [{"name": "x", "kind": "nope",
                      "metric": "m", "target": 1}]}, "bad kind"),
])
def test_load_slo_file_rejects_bad_config(tmp_path, doc, msg):
    path = tmp_path / "slo.json"
    path.write_text(json.dumps(doc))
    with pytest.raises(ValueError, match=msg):
        load_slo_file(str(path))
