"""Kill-rate surfacing: ``myth top`` line and report ``meta.prefilter``."""

from mythril_tpu import absdomain
from mythril_tpu.observability import get_registry
from mythril_tpu.service.top import format_top
from mythril_tpu.smt import terms


def test_format_top_renders_prefilter_line():
    stats = {
        "service.queue_depth": 0,
        "prefilter": {"evaluated": 40, "killed": 10, "kill_rate": 0.25},
    }
    out = format_top(stats)
    assert "prefilter: 40 evaluated  10 killed  (25% kill rate)" in out


def test_format_top_omits_prefilter_line_when_idle():
    assert "prefilter" not in format_top({"service.queue_depth": 0})


def test_report_meta_prefilter_rollup():
    from mythril_tpu.analysis.report import _prefilter_meta

    absdomain.reset_state()
    get_registry().reset(prefix="prefilter.")
    x = terms.var("pfsurf_x", 256)
    assert absdomain.refute([terms.eq(x, terms.const(1, 256)),
                             terms.eq(x, terms.const(2, 256))])
    assert not absdomain.refute([terms.ult(x, terms.const(10, 256))])
    meta = _prefilter_meta()
    assert meta == {"evaluated": 2, "killed": 1, "fallthrough": 0,
                    "kill_rate": 0.5}
    absdomain.reset_state()
