"""Coverage-guided adaptive exploration (the observe→steer loop).

PRs 13–18 built the observability stack — the exploration ledger's
coverage bitmaps and termination taxonomy, the static reachable-edge
oracle, solver-hotspot attribution — but nothing acted on any of it.
This package closes the loop:

* :mod:`.plan` — the pure planner: ledger snapshots in, a
  :class:`~mythril_tpu.adaptive.plan.SteeringPlan` out (slot-budget
  weights biased at uncovered reachable edges, a requeue list for
  ``budget_exhausted`` parks, ranked concolic flip targets, per-code
  plateau verdicts).
* :mod:`.controller` — the process-wide actuation state: the throttled
  plan cache, the deterministic deficit scheduler the frontier consults
  at sync points, the ``--coverage-target`` stop verdict, and the
  ``adaptive.*`` counters.

``--no-adaptive`` disables every actuation site; the steering is a
strict scheduling optimization, so the issue set is bit-identical either
way (bench ``--adaptive-compare`` asserts it).
"""

from mythril_tpu.adaptive.controller import (
    AdaptiveController,
    get_adaptive_controller,
)
from mythril_tpu.adaptive.plan import (
    SteeringPlan,
    build_plan,
    plateau_verdict,
    rank_flip_targets,
    requeue_candidates,
    steer_weights,
    uncovered_reachable,
)

__all__ = [
    "AdaptiveController",
    "SteeringPlan",
    "build_plan",
    "get_adaptive_controller",
    "plateau_verdict",
    "rank_flip_targets",
    "requeue_candidates",
    "steer_weights",
    "uncovered_reachable",
]
