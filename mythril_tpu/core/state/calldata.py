"""Calldata models: concrete, symbolic, and their list-backed variants.

Reference parity: mythril/laser/ethereum/state/calldata.py (4 models:
ConcreteCalldata :113, BasicConcreteCalldata :160, SymbolicCalldata :206,
BasicSymbolicCalldata :257).  ``concrete(model)`` reifies actual attack bytes
from a solver model for exploit reports (reference :233-246).
"""

from __future__ import annotations

from typing import List, Optional, Union

from mythril_tpu.smt import Array, BitVec, If, K, symbol_factory
from mythril_tpu.smt.concrete_eval import evaluate
from mythril_tpu.smt.solver import Model


class BaseCalldata:
    def __init__(self, tx_id):
        self.tx_id = tx_id

    @property
    def calldatasize(self) -> BitVec:
        return self.size if isinstance(self.size, BitVec) else symbol_factory.BitVecVal(
            self.size, 256
        )

    @property
    def size(self):
        raise NotImplementedError

    def get_word_at(self, offset: Union[int, BitVec]) -> BitVec:
        """32-byte big-endian word starting at byte ``offset``."""
        if isinstance(offset, int):
            offset = symbol_factory.BitVecVal(offset, 256)
        from mythril_tpu.smt import Concat

        return Concat(*[self._load(offset + i) for i in range(32)])

    def __getitem__(self, item) -> BitVec:
        if isinstance(item, slice):
            start = item.start or 0
            stop = item.stop
            from mythril_tpu.smt import Concat

            parts = [self._load(start + i) for i in range(stop - start)]
            return Concat(*parts) if len(parts) > 1 else parts[0]
        return self._load(item)

    def _load(self, item) -> BitVec:
        raise NotImplementedError

    def concrete(self, model: Optional[Model]) -> List[int]:
        raise NotImplementedError


class ConcreteCalldata(BaseCalldata):
    """Fixed bytes backed by a constant array (reads fold to constants)."""

    def __init__(self, tx_id, calldata: List[int]):
        super().__init__(tx_id)
        self._calldata = list(calldata)
        arr = K(256, 8, 0)
        for i, b in enumerate(self._calldata):
            arr[symbol_factory.BitVecVal(i, 256)] = symbol_factory.BitVecVal(b, 8)
        self._array = arr

    @property
    def size(self) -> int:
        return len(self._calldata)

    def _load(self, item) -> BitVec:
        if isinstance(item, int):
            item = symbol_factory.BitVecVal(item, 256)
        return self._array[item]

    def concrete(self, model=None) -> List[int]:
        return list(self._calldata)


class BasicConcreteCalldata(BaseCalldata):
    """Plain-list calldata; symbolic reads become an ITE chain."""

    def __init__(self, tx_id, calldata: List[int]):
        super().__init__(tx_id)
        self._calldata = list(calldata)

    @property
    def size(self) -> int:
        return len(self._calldata)

    def _load(self, item) -> BitVec:
        if isinstance(item, int):
            if 0 <= item < len(self._calldata):
                return symbol_factory.BitVecVal(self._calldata[item], 8)
            return symbol_factory.BitVecVal(0, 8)
        value = symbol_factory.BitVecVal(0, 8)
        for i in range(len(self._calldata) - 1, -1, -1):
            value = If(
                item == symbol_factory.BitVecVal(i, 256),
                symbol_factory.BitVecVal(self._calldata[i], 8),
                value,
            )
        return value

    def concrete(self, model=None) -> List[int]:
        return list(self._calldata)


class SymbolicCalldata(BaseCalldata):
    """Fully symbolic: array variable + size symbol; OOB reads are zero."""

    def __init__(self, tx_id):
        super().__init__(tx_id)
        self._size = symbol_factory.BitVecSym(f"{tx_id}_calldatasize", 256)
        self._array = Array(f"{tx_id}_calldata", 256, 8)

    @property
    def size(self) -> BitVec:
        return self._size

    def _load(self, item) -> BitVec:
        if isinstance(item, int):
            item = symbol_factory.BitVecVal(item, 256)
        from mythril_tpu.smt import ULT

        return If(ULT(item, self._size), self._array[item], symbol_factory.BitVecVal(0, 8))

    def concrete(self, model: Model) -> List[int]:
        size = model.eval(self._size)
        size = min(int(size), 5000)  # cap mirrors reference's sanity bound
        return [int(model.eval(self._load(i))) for i in range(size)]


class BasicSymbolicCalldata(BaseCalldata):
    """Symbolic calldata tracking each read (index, value) pair."""

    def __init__(self, tx_id):
        super().__init__(tx_id)
        self._size = symbol_factory.BitVecSym(f"{tx_id}_calldatasize", 256)
        self._reads: List = []

    @property
    def size(self) -> BitVec:
        return self._size

    def _load(self, item) -> BitVec:
        if isinstance(item, int):
            item = symbol_factory.BitVecVal(item, 256)
        sym = symbol_factory.BitVecSym(f"{self.tx_id}_calldata[{item.raw.tid}]", 8)
        for idx, val in self._reads:
            sym = If(item == idx, val, sym)
        self._reads.append((item, sym))
        return sym

    def concrete(self, model: Model) -> List[int]:
        size = min(int(model.eval(self._size)), 5000)
        out = [0] * size
        for idx, val in self._reads:
            i = int(model.eval(idx))
            if i < size:
                out[i] = int(model.eval(val))
        return out
