"""``myth top`` — a live operator view of a running analysis daemon.

Polls the daemon's ``stats`` verb and renders a refreshing terminal
table: admission depths, cache hit rates, per-phase latency percentiles
(the request-scoped telemetry histograms), per-tenant totals, and the
in-flight request list with each request's current phase and age.

``format_top`` is a pure function over one stats payload so tests can
assert the rendering against a canned dict; ``run_top`` owns the
connection/refresh loop.
"""

from __future__ import annotations

import sys
import time
from typing import Any, Dict, Optional, TextIO

from mythril_tpu.service.client import ServiceClient

__all__ = ["format_health", "format_top", "run_top"]

# ANSI: clear screen + home.  Only emitted between refreshes, never in
# --once mode, so piped output stays clean.
_CLEAR = "\x1b[2J\x1b[H"

_PHASE_ORDER = ("queue_wait", "batch_wait", "execute", "stream",
                "ttfe", "probe")


def _ms(v: Any) -> str:
    if not isinstance(v, (int, float)):
        return "-"
    return f"{v * 1000:.1f}ms" if v < 1.0 else f"{v:.2f}s"


def format_top(stats: Dict[str, Any], address: Optional[str] = None) -> str:
    """Render one stats payload as the ``myth top`` screen."""
    lines = []
    title = "mythril-tpu service"
    if address:
        title += f" @ {address}"
    scope = stats.get("scope")
    if scope:
        title += f"  [{scope}]"
    lines.append(title)
    health = stats.get("health")
    if health and health.get("enabled"):
        breaching = health.get("breaching") or []
        if breaching:
            lines.append("!! SLO BREACH: " + ", ".join(breaching)
                         + f"  (breaches_total {health.get('breaches_total', 0)})")
        else:
            n = len(health.get("objectives") or [])
            line = f"slo: ok ({n} objective{'s' if n != 1 else ''}"
            warning = health.get("warning") or []
            if warning:
                line += f", warn: {', '.join(warning)}"
            if health.get("breaches_total"):
                line += f", breaches_total {health['breaches_total']}"
            lines.append(line + ")")
    hb = stats.get("heartbeat") or {}
    if hb.get("sources_dropped"):
        lines.append("WARN heartbeat: dropped sources "
                     + ", ".join(hb["sources_dropped"])
                     + " (repeated sampling errors)")
    cache = stats.get("cache") or {}
    lines.append(
        "queue {q}  inflight {i}  cached {c}  |  requests {r}  "
        "batches {b}  errors {e}  |  dedup {d:.0%}  replay {p:.0%}".format(
            q=stats.get("service.queue_depth", 0),
            i=stats.get("service.inflight", 0),
            c=stats.get("service.result_cache", 0),
            r=stats.get("service.requests", 0),
            b=stats.get("service.batches", 0),
            e=stats.get("service.request_errors", 0),
            d=cache.get("dedup_hit_rate", 0.0),
            p=cache.get("replay_hit_rate", 0.0),
        )
    )

    device = stats.get("device") or {}
    if device.get("compile_wall_s") or device.get("recompiles"):
        cache_d = device.get("cache") or {}
        line = "device: compile {c:.1f}s  cache {h}h/{m}m  recompiles {r}".format(
            c=device.get("compile_wall_s", 0.0),
            h=cache_d.get("hits", 0), m=cache_d.get("misses", 0),
            r=device.get("recompiles", 0),
        )
        if device.get("shape_churn"):
            line += f"  shape-churn {device['shape_churn']}"
        hbm = device.get("hbm_bytes") or {}
        if hbm:
            line += "  hbm {:.1f}MB".format(
                max(hbm.values()) / 1e6 if isinstance(hbm, dict) else 0.0
            )
        lines.append(line)

    workers = stats.get("workers") or []
    if workers:
        states = [w.get("state", "?") for w in workers]
        restarts = stats.get("service.worker_restarts", 0) or 0
        summary = (
            f"workers {len(workers)}  idle {states.count('idle')}  "
            f"busy {states.count('busy')}"
        )
        if restarts:
            summary += f"  restarts {restarts}"
        shed = stats.get("service.shed_total", 0) or 0
        quota = stats.get("service.quota_rejections", 0) or 0
        if shed or quota:
            summary += f"  |  shed {shed}  quota-rejected {quota}"
        lines.append(summary)
        if not (len(workers) == 1 and workers[0].get("state") == "inline"):
            lines.append(f"{'worker':<8}{'pid':>8} {'state':<10}"
                         f"{'batches':>9}{'restarts':>10}{'age':>9}"
                         f"{'exec p50':>10}{'kill%':>7}"
                         f"{'compile':>9}{'rcmp':>6}  rids")
            for w in workers:
                exec_p50 = ((w.get("phase_s") or {}).get("execute")
                            or {}).get("p50_s")
                pf = w.get("prefilter") or {}
                kill = (f"{pf['kill_rate'] * 100:.0f}%"
                        if pf.get("evaluated") else "-")
                dev = w.get("device") or {}
                compile_s = (_ms(dev["compile_s"])
                             if dev.get("compile_s") else "-")
                rcmp = (str(dev.get("recompiles", 0))
                        if dev else "-")
                rids = ",".join(w.get("active_rids") or []) or "-"
                lines.append(
                    f"w{w.get('id', '?'):<7}{str(w.get('pid', '-')):>8} "
                    f"{w.get('state', '?'):<10}{w.get('batches', 0):>9}"
                    f"{w.get('restarts', 0):>10}"
                    f"{_ms(w.get('age_s')) if w.get('age_s') else '-':>9}"
                    f"{_ms(exec_p50):>10}{kill:>7}"
                    f"{compile_s:>9}{rcmp:>6}  {rids}"
                )

    frontier = stats.get("frontier") or {}
    if frontier.get("bucket_classes") or frontier.get("page_faults"):
        line = (
            "frontier: {c} bucket classes  pad-waste {w:.1f}%"
            " (single-bucket {s:.1f}%)".format(
                c=frontier.get("bucket_classes", 0),
                w=frontier.get("pad_waste_pct", 0.0),
                s=frontier.get("pad_waste_single_bucket_pct", 0.0),
            )
        )
        if frontier.get("page_faults") or frontier.get("page_repacks"):
            line += "  |  paging: {f} faults  {r} repacks  {p:.0f}% resident".format(
                f=frontier.get("page_faults", 0),
                r=frontier.get("page_repacks", 0),
                p=frontier.get("page_resident_pct", 100.0),
            )
        lines.append(line)

    prefilter = stats.get("prefilter") or {}
    if prefilter.get("evaluated"):
        lines.append(
            "prefilter: {e} evaluated  {k} killed  ({r:.0%} kill rate)".format(
                e=prefilter.get("evaluated", 0),
                k=prefilter.get("killed", 0),
                r=prefilter.get("kill_rate", 0.0),
            )
        )

    devsolver = stats.get("devsolver") or {}
    if devsolver.get("admitted"):
        line = (
            "devsolver: {a} admitted  {s} sat  {u} unsat  {n} unknown  "
            "({r:.0%} decide rate)".format(
                a=devsolver.get("admitted", 0),
                s=devsolver.get("decided_sat", 0),
                u=devsolver.get("decided_unsat", 0),
                n=devsolver.get("unknown", 0),
                r=devsolver.get("decide_rate", 0.0),
            )
        )
        bad = devsolver.get("model_validation_failures", 0)
        if bad:
            line += f"  bad-models {bad}"
        lines.append(line)

    exploration = stats.get("exploration") or {}
    if exploration.get("terminated_total"):
        terminated = exploration.get("terminated") or {}
        cov = exploration.get("coverage_pct") or {}
        cov_reach = exploration.get("coverage_pct_reachable") or {}
        # compact class breakdown: only nonzero classes, largest first
        classes = "  ".join(
            f"{cls}={n}" for cls, n in
            sorted(terminated.items(), key=lambda kv: -kv[1]) if n
        )
        cov_txt = ""
        if cov:
            vals = list(cov.values())
            cov_txt = "  cov(avg) {:.1f}% raw".format(sum(vals) / len(vals))
            if cov_reach:
                rvals = list(cov_reach.values())
                cov_txt += " / {:.1f}% reachable".format(
                    sum(rvals) / len(rvals)
                )
            cov_txt += f" over {len(vals)} contracts"
        lines.append(
            "exploration: {t} paths terminated{c}".format(
                t=exploration.get("terminated_total", 0), c=cov_txt
            )
        )
        if classes:
            lines.append("  " + classes)

    adaptive = stats.get("adaptive") or {}
    if adaptive.get("plans"):
        line = (
            "adaptive: {p} plans  {s} resteered  {q} requeued  "
            "flips {h}/{f}".format(
                p=adaptive.get("plans", 0),
                s=adaptive.get("resteered_slots", 0),
                q=adaptive.get("requeued_paths", 0),
                h=adaptive.get("flips_hit", 0),
                f=adaptive.get("flips_planned", 0),
            )
        )
        if adaptive.get("plateau_stops"):
            line += f"  plateau-stops {adaptive['plateau_stops']}"
        stop = adaptive.get("coverage_stop") or {}
        if stop:
            line += "  last-stop {r}@{c:.1f}%".format(
                r=stop.get("reason", "?"),
                c=float(stop.get("coverage_pct_reachable") or 0.0),
            )
        lines.append(line)

    staticpass = stats.get("staticpass") or {}
    disabled = staticpass.get("gate_disabled") or {}
    if disabled:
        reasons = "  ".join(
            f"{r}={n}" for r, n in
            sorted(disabled.items(), key=lambda kv: -kv[1]) if n
        )
        lines.append(
            "WARN staticpass: gate self-disabled (nothing pruned)  "
            + reasons
        )

    phases = stats.get("phases") or {}
    if any((phases.get(p) or {}).get("count") for p in _PHASE_ORDER):
        lines.append("")
        lines.append(f"{'phase':<12}{'count':>7}{'avg':>10}{'p50':>10}"
                     f"{'p95':>10}{'p99':>10}")
        for p in _PHASE_ORDER:
            row = phases.get(p) or {}
            if not row.get("count"):
                continue
            lines.append(
                f"{p:<12}{row['count']:>7}{_ms(row.get('avg')):>10}"
                f"{_ms(row.get('p50')):>10}{_ms(row.get('p95')):>10}"
                f"{_ms(row.get('p99')):>10}"
            )

    tenants = stats.get("tenants") or {}
    if tenants:
        lines.append("")
        lines.append(f"{'tenant':<16}{'requests':>9}{'issues':>8}"
                     f"{'dedup':>7}{'compute_s':>11}")
        for tenant, row in sorted(tenants.items()):
            lines.append(
                f"{tenant:<16}{row.get('requests', 0):>9}"
                f"{row.get('issues', 0):>8}{row.get('dedup_hits', 0):>7}"
                f"{row.get('compute_s', 0.0):>11.3f}"
            )

    inflight = stats.get("inflight_requests") or []
    lines.append("")
    lines.append(f"in flight: {len(inflight)}")
    for req in inflight[:32]:
        lines.append(
            f"  {req.get('request_id', '?'):<10}"
            f"{(req.get('tenant') or '-'):<14}"
            f"{req.get('tier', '?'):<13}{req.get('phase', '?'):<12}"
            f"{_ms(req.get('age_s'))}"
        )
    if len(inflight) > 32:
        lines.append(f"  ... and {len(inflight) - 32} more")
    return "\n".join(lines)


_STATE_MARK = {"ok": "ok    ", "warn": "WARN  ", "breach": "BREACH",
               "no_data": "-     "}


def _fmt_value(v: Any, kind: str) -> str:
    if v is None:
        return "-"
    if kind == "ratio":
        return f"{v:.1%}"
    if kind == "quantile":
        return _ms(v)
    return f"{v:g}"


def format_health(health: Dict[str, Any],
                  address: Optional[str] = None) -> str:
    """Render one ``health`` payload as the ``myth health`` report.

    Pure over the payload (tests assert against canned dicts), mirroring
    ``format_top``.
    """
    if not health.get("enabled"):
        return "watchtower: disabled (daemon runs without --slo/watchtower)"
    lines = []
    title = "watchtower"
    if address:
        title += f" @ {address}"
    objectives = health.get("objectives") or []
    breaching = health.get("breaching") or []
    verdict = "BREACH" if breaching else "ok"
    n = len(objectives)
    title += (f": {verdict}  ({n} objective{'s' if n != 1 else ''}, "
              f"breaches_total {health.get('breaches_total', 0)}, "
              f"tick {health.get('interval_s', 0):g}s, "
              f"overhead {health.get('overhead_pct', 0):g}%)")
    lines.append(title)
    for e in objectives:
        kind = e.get("kind", "")
        win = ""
        if kind in ("quantile", "ratio"):
            win = (f"  [fast {e.get('fast_window_s', 0):g}s"
                   f"/slow {e.get('slow_window_s', 0):g}s"
                   f", n={e.get('window_count', 0)}]")
        lines.append(
            f"  {_STATE_MARK.get(e.get('state'), '?     ')} "
            f"{e.get('name', '?'):<22}"
            f"{_fmt_value(e.get('value'), kind):>10}  "
            f"{e.get('op', '?')} {_fmt_value(e.get('target'), kind)}"
            f"{win}"
        )
    for cap in health.get("captures") or []:
        lines.append(
            f"  capture: {cap.get('objective', '?')}"
            + (f"  bundle {cap['bundle']}" if cap.get("bundle") else "")
            + (f"  profile worker {cap['profile_worker']}"
               if "profile_worker" in cap else "")
        )
    return "\n".join(lines)


def run_top(
    host: str = "127.0.0.1",
    port: int = 7344,
    interval: float = 2.0,
    once: bool = False,
    iterations: Optional[int] = None,
    out: Optional[TextIO] = None,
) -> int:
    """Poll ``host:port`` and render until interrupted; returns exit code."""
    client = ServiceClient(host, port, timeout=10.0)
    out = out or sys.stdout
    n = 0
    while True:
        try:
            stats = client.stats()
        except OSError as exc:
            print(f"cannot reach analysis service at {host}:{port}: {exc}",
                  file=sys.stderr)
            return 1
        if not once and n:
            out.write(_CLEAR)
        out.write(format_top(stats, address=f"{host}:{port}") + "\n")
        out.flush()
        n += 1
        if once or (iterations is not None and n >= iterations):
            return 0
        try:
            time.sleep(interval)
        except KeyboardInterrupt:
            return 0
