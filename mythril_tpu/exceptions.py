"""Exception hierarchy (reference parity: mythril/exceptions.py:4-44)."""


class MythrilBaseException(Exception):
    """Base for all framework exceptions."""


class CompilerError(MythrilBaseException):
    """solc invocation failed."""


class UnsatError(MythrilBaseException):
    """Constraint set has no model (or none could be found in budget)."""


class NoContractFoundError(MythrilBaseException):
    """Input file contained no contract."""


class CriticalError(MythrilBaseException):
    """User-facing fatal error (bad args, unreachable RPC, ...)."""


class AddressNotFoundError(MythrilBaseException):
    """Function address not found in disassembly."""


class DetectorNotFoundError(MythrilBaseException):
    """Unknown detection module name."""


class IllegalArgumentError(ValueError, MythrilBaseException):
    """Bad argument to an API entry point."""
