"""Unit tests for the actuation-facing adaptive controller.

Each test gets its own :class:`AdaptiveController` with an injected
:class:`MetricsRegistry`, so nothing here touches the process-wide
singleton or the global registry — the tier-1 invariant the bench
``--adaptive-compare`` mode asserts end-to-end."""

from typing import Optional

import pytest

from mythril_tpu.adaptive.controller import AdaptiveController
from mythril_tpu.adaptive.plan import PLATEAU_WINDOW, SteeringPlan
from mythril_tpu.observability.metrics import MetricsRegistry
from mythril_tpu.support.support_args import args

H1, H2, H3 = "a" * 64, "b" * 64, "c" * 64


class _StubLedger:
    """Minimal ExplorationLedger stand-in the controller plans from."""

    def __init__(self, bitmaps=None, pct: Optional[float] = None,
                 per_code_pct=None):
        self._bitmaps = bitmaps or {}
        self._pct = pct
        self._per_code = per_code_pct or {}

    def bitmaps(self):
        return dict(self._bitmaps)

    def coverage_pct_reachable(self, code_hash=None):
        if code_hash is not None:
            return self._per_code.get(code_hash, self._pct)
        return self._pct

    def solver_hotspots(self, top=64):
        return []


def _bitmap(n=8, jumpi=3):
    import numpy as np

    taken = np.zeros(n, bool)
    taken[jumpi] = True  # fall edge uncovered -> steering mass
    return {
        "instr": np.ones(n, bool), "edge_taken": taken,
        "edge_fall": np.zeros(n, bool), "jumpis": [jumpi], "total": n,
    }


@pytest.fixture
def ctrl(monkeypatch):
    c = AdaptiveController(registry=MetricsRegistry())
    monkeypatch.setattr(args, "adaptive", True)
    monkeypatch.setattr(args, "coverage_target", None)
    monkeypatch.setattr(
        AdaptiveController, "_ledger", lambda self: _StubLedger()
    )
    return c


def _install_plan(ctrl, weights):
    import time

    ctrl._plan = SteeringPlan(weights=weights)
    ctrl._plan_at = time.monotonic()


class TestPickSeed:
    def test_fifo_when_disabled(self, ctrl, monkeypatch):
        _install_plan(ctrl, {H1: 0.1, H2: 0.9})
        monkeypatch.setattr(args, "adaptive", False)
        assert ctrl.pick_seed([H1, H2, H2]) == 0
        assert ctrl.meta()["resteered_slots"] == 0

    def test_fifo_single_code(self, ctrl):
        _install_plan(ctrl, {H1: 1.0})
        assert ctrl.pick_seed([H1, H1, H1]) == 0

    def test_fifo_without_plan(self, ctrl):
        assert ctrl.pick_seed([H1, H2]) == 0

    def test_deficit_converges_on_weights(self, ctrl):
        """Granted shares track the plan's weights without randomness:
        a 3:1 weight split grants ~3x the slots over a long queue."""
        _install_plan(ctrl, {H1: 0.75, H2: 0.25})
        grants = {H1: 0, H2: 0}
        for _ in range(100):
            queue = [H1, H2]
            pos = ctrl.pick_seed(queue)
            grants[queue[pos]] += 1
        assert grants[H1] == pytest.approx(75, abs=2)
        assert grants[H2] == pytest.approx(25, abs=2)

    def test_resteered_counted_only_off_fifo(self, ctrl):
        _install_plan(ctrl, {H1: 0.05, H2: 0.95})
        pos = ctrl.pick_seed([H1, H2])
        assert pos == 1  # H2's deficit dominates
        assert ctrl.meta()["resteered_slots"] == 1
        # H2 now granted; next pick is FIFO-compatible -> no new count
        pos2 = ctrl.pick_seed([H2, H1])
        assert ctrl.meta()["resteered_slots"] == 1 + (1 if pos2 else 0)

    def test_deterministic(self, ctrl):
        _install_plan(ctrl, {H1: 0.4, H2: 0.35, H3: 0.25})
        seq1 = [ctrl.pick_seed([H1, H2, H3]) for _ in range(30)]
        ctrl.reset_scope()
        _install_plan(ctrl, {H1: 0.4, H2: 0.35, H3: 0.25})
        seq2 = [ctrl.pick_seed([H1, H2, H3]) for _ in range(30)]
        assert seq1 == seq2


class TestPlanning:
    def test_plan_builds_from_ledger_and_counts(self, ctrl, monkeypatch):
        monkeypatch.setattr(
            AdaptiveController, "_ledger",
            lambda self: _StubLedger(
                bitmaps={H1: _bitmap(), H2: _bitmap()},
                per_code_pct={H1: 40.0, H2: 60.0},
            ),
        )
        plan = ctrl.plan(force=True)
        assert set(plan.weights) == {H1, H2}
        assert ctrl.meta()["plans"] == 1
        # history ticked for the plateau verdict
        assert ctrl._history[H1] == [40.0]

    def test_plan_throttled(self, ctrl, monkeypatch):
        monkeypatch.setattr(
            AdaptiveController, "_ledger",
            lambda self: _StubLedger(bitmaps={H1: _bitmap()}),
        )
        ctrl.plan(force=True)
        ctrl.plan()  # inside the min interval: cached, no second build
        assert ctrl.meta()["plans"] == 1
        ctrl.plan(force=True)
        assert ctrl.meta()["plans"] == 2

    def test_throttled_plan_still_reevaluates_requeue(self, ctrl,
                                                      monkeypatch):
        monkeypatch.setattr(
            AdaptiveController, "_ledger",
            lambda self: _StubLedger(bitmaps={H1: _bitmap()}),
        )
        ctrl.plan(force=True)
        plan = ctrl.plan(parked=[("tok", "budget_exhausted")])
        assert plan.requeue == ("tok",)
        assert ctrl.meta()["plans"] == 1

    def test_select_requeue_counts(self, ctrl, monkeypatch):
        monkeypatch.setattr(
            AdaptiveController, "_ledger",
            lambda self: _StubLedger(bitmaps={H1: _bitmap()}),
        )
        picked = ctrl.select_requeue(
            [("t1", "budget_exhausted"), ("t2", "verdict")], live=()
        )
        assert picked == ["t1"]
        assert ctrl.meta()["requeued_paths"] == 1

    def test_select_requeue_disabled(self, ctrl, monkeypatch):
        monkeypatch.setattr(args, "adaptive", False)
        assert ctrl.select_requeue([("t1", "budget_exhausted")]) == []


class TestFlipTargets:
    def test_prefix_match(self, ctrl):
        import time

        ctrl._plan = SteeringPlan(flip_targets={H1: (7, 3)})
        ctrl._plan_at = time.monotonic()
        assert ctrl.flip_targets_for(H1) == (7, 3)
        assert ctrl.flip_targets_for(H1[:10]) == (7, 3)
        assert ctrl.flip_targets_for(H2) == ()

    def test_count_flips(self, ctrl):
        ctrl.count_flips(planned=3, hit=2)
        m = ctrl.meta()
        assert m["flips_planned"] == 3 and m["flips_hit"] == 2


class TestCoverageStop:
    def test_no_target_no_stop(self, ctrl):
        assert ctrl.coverage_stop() is None
        assert ctrl.stop_state() is None

    def test_target_reached_latches(self, ctrl, monkeypatch):
        monkeypatch.setattr(
            AdaptiveController, "_ledger",
            lambda self: _StubLedger(bitmaps={H1: _bitmap()}, pct=92.5),
        )
        assert ctrl.coverage_stop(target=90.0) == "target"
        stop = ctrl.stop_state()
        assert stop["coverage_target_met"] is True
        assert stop["coverage_pct_reachable"] == 92.5
        assert stop["reason"] == "target"
        # latched: a second verdict does not re-stamp
        assert ctrl.coverage_stop(target=90.0) == "target"
        assert ctrl.meta()["coverage_stop"] == stop

    def test_below_target_keeps_exploring(self, ctrl, monkeypatch):
        monkeypatch.setattr(
            AdaptiveController, "_ledger",
            lambda self: _StubLedger(bitmaps={H1: _bitmap()}, pct=10.0),
        )
        assert ctrl.coverage_stop(target=90.0) is None

    def test_all_codes_plateau_stops(self, ctrl, monkeypatch):
        monkeypatch.setattr(
            AdaptiveController, "_ledger",
            lambda self: _StubLedger(
                bitmaps={H1: _bitmap()}, pct=50.0,
                per_code_pct={H1: 50.0},
            ),
        )
        for _ in range(PLATEAU_WINDOW + 2):  # flat history -> plateau
            ctrl.plan(force=True)
        assert ctrl.coverage_stop(target=90.0) == "plateau"
        assert ctrl.meta()["plateau_stops"] == 1

    def test_disabled_never_stops(self, ctrl, monkeypatch):
        monkeypatch.setattr(args, "adaptive", False)
        monkeypatch.setattr(
            AdaptiveController, "_ledger",
            lambda self: _StubLedger(bitmaps={H1: _bitmap()}, pct=99.0),
        )
        assert ctrl.coverage_stop(target=50.0) is None


class TestLifecycle:
    def test_reset_scope(self, ctrl, monkeypatch):
        monkeypatch.setattr(
            AdaptiveController, "_ledger",
            lambda self: _StubLedger(bitmaps={H1: _bitmap()}, pct=99.0),
        )
        ctrl.plan(force=True)
        ctrl.pick_seed([H1, H2])
        ctrl.coverage_stop(target=50.0)
        ctrl.reset_scope()
        assert ctrl.current_plan() is None
        assert ctrl.stop_state() is None
        assert ctrl._history == {} and ctrl._granted == {}
        # counters survive reset (scope is per-analysis, metrics are not)
        assert ctrl.meta()["plans"] == 1

    def test_register_points_bounded(self, ctrl):
        from mythril_tpu.adaptive.controller import _MAX_POINT_CODES

        for i in range(_MAX_POINT_CODES + 1):
            ctrl.register_points("%064x" % i, [{"addr": 1, "score": 1.0}])
        assert len(ctrl._points) <= _MAX_POINT_CODES

    def test_meta_shape(self, ctrl):
        m = ctrl.meta()
        assert m["enabled"] is True
        for k in ("plans", "resteered_slots", "requeued_paths",
                  "flips_planned", "flips_hit", "plateau_stops"):
            assert m[k] == 0
