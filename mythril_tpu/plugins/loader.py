"""Plugin loader singleton (reference parity: laser/plugin/loader.py:12-75)."""

from __future__ import annotations

import logging
from typing import Dict, List, Optional

from mythril_tpu.plugins.interface import LaserPlugin, PluginBuilder
from mythril_tpu.support.support_utils import Singleton

log = logging.getLogger(__name__)


class LaserPluginLoader(metaclass=Singleton):
    def __init__(self):
        self.laser_plugin_builders: Dict[str, PluginBuilder] = {}
        self.plugin_args: Dict[str, Dict] = {}

    def load(self, builder: PluginBuilder) -> None:
        if builder.name in self.laser_plugin_builders:
            log.warning("plugin %s already loaded; skipping", builder.name)
            return
        self.laser_plugin_builders[builder.name] = builder

    def add_args(self, plugin_name: str, **kwargs) -> None:
        self.plugin_args[plugin_name] = kwargs

    def is_enabled(self, plugin_name: str) -> bool:
        builder = self.laser_plugin_builders.get(plugin_name)
        return builder is not None and builder.enabled

    def enable(self, plugin_name: str) -> None:
        if plugin_name in self.laser_plugin_builders:
            self.laser_plugin_builders[plugin_name].enabled = True

    def disable(self, plugin_name: str) -> None:
        if plugin_name in self.laser_plugin_builders:
            self.laser_plugin_builders[plugin_name].enabled = False

    def instrument_virtual_machine(self, symbolic_vm, with_plugins: Optional[List[str]] = None):
        for name, builder in self.laser_plugin_builders.items():
            if not builder.enabled:
                continue
            if with_plugins is not None and name not in with_plugins:
                continue
            plugin = builder(**self.plugin_args.get(name, {}))
            plugin.initialize(symbolic_vm)
            log.debug("instrumented vm with plugin %s", name)
