"""Lazy select congruence + CEGAR refinement in the native tier.

``solve`` blasts NO select-congruence pairs up front (sound for UNSAT),
detects violated pairs during model reconstruction, and asserts exactly
those; ``OptimizeSession`` refines its LIVE session via ``bb_extend``
(learned clauses retained).  These tests pin the soundness contract: UNSAT
answers exact, SAT models congruence-clean.
"""

import pytest

from mythril_tpu.native import bitblast
from mythril_tpu.smt import terms
from mythril_tpu.smt.concrete_eval import evaluate

pytestmark = pytest.mark.skipif(
    not bitblast.available(), reason="native library unavailable"
)


def arr(name):
    return terms.array_var(name, 256, 8)


def c(v, w=256):
    return terms.const(v, w)


def test_congruence_unsat_needs_refinement():
    """select(a, i) != select(a, j) with i == j is UNSAT, but only via the
    congruence pairs the lazy blast omits — the CEGAR loop must find it."""
    a = arr("cg1")
    i, j = terms.var("i1", 256), terms.var("j1", 256)
    conj = [
        terms.eq(i, j),
        terms.lnot(
            terms.eq(terms.select(a, i), terms.select(a, j))
        ),
    ]
    status, _ = bitblast.solve(conj, timeout_s=30)
    assert status == bitblast.UNSAT


def test_congruence_sat_model_consistent():
    """Distinct indices allow distinct values; the model must be exact."""
    a = arr("cg2")
    s0 = terms.select(a, c(0))
    s1 = terms.select(a, c(1))
    conj = [
        terms.eq(s0, c(7, 8)),
        terms.eq(s1, c(9, 8)),
    ]
    status, asg = bitblast.solve(conj, timeout_s=30)
    assert status == bitblast.SAT
    vals = evaluate(conj, asg)
    assert all(vals[x] for x in conj)


def test_computed_index_aliasing_unsat():
    """select(a, x + 1) pinned to two different values via an alias of the
    index term — UNSAT only through refinement on computed indices."""
    a = arr("cg3")
    x = terms.var("x3", 256)
    idx1 = terms.add(x, c(1))
    idx2 = terms.add(c(1), x)  # same term after canonical fold, or an alias
    conj = [
        terms.eq(terms.select(a, idx1), c(1, 8)),
        terms.eq(terms.select(a, idx2), c(2, 8)),
    ]
    status, _ = bitblast.solve(conj, timeout_s=30)
    assert status == bitblast.UNSAT


def test_session_refines_in_place():
    """OptimizeSession with guarded conjuncts over aliasing selects must
    answer UNSAT for the aliased guard and SAT for the compatible one,
    from ONE session (bb_extend keeps the handle alive)."""
    a = arr("cg4")
    i, j = terms.var("i4", 256), terms.var("j4", 256)
    path = [terms.eq(i, j)]
    g_bad = terms.lnot(terms.eq(terms.select(a, i), terms.select(a, j)))
    g_ok = terms.eq(terms.select(a, i), c(5, 8))
    with bitblast.OptimizeSession(path, guarded=[g_bad, g_ok]) as sess:
        st_bad, _ = sess.solve([], 30, enable=[0])
        assert st_bad == bitblast.UNSAT
        st_ok, asg = sess.solve([], 30, enable=[1])
        assert st_ok == bitblast.SAT
        vals = evaluate(path + [g_ok], asg)
        assert all(vals[x] for x in path + [g_ok])


def test_session_bound_queries_after_refinement():
    """Objective bound refinement still works after congruence extension."""
    a = arr("cg5")
    i = terms.var("i5", 256)
    obj = terms.zext(terms.select(a, i), 248)  # 256-bit objective
    path = [terms.ule(c(3), obj)]
    with bitblast.OptimizeSession(path, objectives=[obj]) as sess:
        st, asg = sess.solve([], 30)
        assert st == bitblast.SAT
        # minimize: is obj <= 3 reachable?  (yes: exactly 3)
        st2, asg2 = sess.solve([(0, "le", 3)], 30)
        assert st2 == bitblast.SAT
        assert evaluate([obj], asg2)[obj] == 3
        # obj <= 2 contradicts the path
        st3, _ = sess.solve([(0, "le", 2)], 30)
        assert st3 == bitblast.UNSAT
