"""On-chain mode with a mocked JSON-RPC node.

Reference test role: tests/rpc_test.py (live node) + the mocked-DynLoader
world-state test (tests/laser/state/world_state_account_exist_load_test.py).
No network exists here, so a fake transport answers the JSON-RPC payloads:
the full ``analyze -a <addr>`` path, ``read-storage`` slot math, and
mid-execution dynamic loads are all covered end-to-end against it.
"""

from __future__ import annotations

import io
import json

import pytest

from mythril_tpu.frontend.rpc import EthJsonRpc, RPCError
from mythril_tpu.support.loader import DynLoader

# kill() dispatcher + CALLER SELFDESTRUCT (the standard vulnerable fixture)
KILL_RUNTIME = "60003560e01c6341c0e1b51460145760006000fd5b33ff"
ADDR = "0x2222222222222222222222222222222222222222"


class FakeNode:
    """Answers JSON-RPC calls; records every (method, params) it sees."""

    def __init__(self):
        self.calls = []
        self.storage = {0: "0x" + "00" * 31 + "2a"}
        self.code = {ADDR.lower(): "0x" + KILL_RUNTIME}

    def handle(self, payload: dict):
        method = payload["method"]
        params = payload.get("params", [])
        self.calls.append((method, params))
        if method == "eth_getCode":
            return self.code.get(params[0].lower(), "0x")
        if method == "eth_getStorageAt":
            slot = int(params[1], 16)
            return self.storage.get(slot, "0x" + "00" * 32)
        if method == "eth_getBalance":
            return hex(10**18)
        if method == "eth_blockNumber":
            return "0x10"
        if method == "eth_coinbase":
            return "0x" + "c0" * 20
        if method == "eth_getBlockByNumber":
            return {"number": params[0], "extraData": "0x11bb"}
        if method == "eth_getTransactionCount":
            return "0x5"
        raise ValueError(f"unexpected method {method}")


@pytest.fixture()
def node(monkeypatch):
    fake = FakeNode()

    def fake_urlopen(req, timeout=10):
        payload = json.loads(req.data.decode())
        result = fake.handle(payload)
        body = json.dumps({"jsonrpc": "2.0", "id": payload["id"], "result": result})

        class _Resp(io.BytesIO):
            def __enter__(self):
                return self

            def __exit__(self, *a):
                return False

        return _Resp(body.encode())

    monkeypatch.setattr("mythril_tpu.frontend.rpc._urlreq.urlopen", fake_urlopen)
    return fake


def test_client_methods_roundtrip(node):
    client = EthJsonRpc("localhost", 8545)
    assert client.eth_blockNumber() == 16
    assert client.eth_getBalance(ADDR) == 10**18
    assert client.eth_coinbase() == "0x" + "c0" * 20
    assert client.eth_getBlockByNumber(0)["extraData"] == "0x11bb"
    assert client.eth_getTransactionCount(ADDR) == 5
    assert client.eth_getCode(ADDR) == "0x" + KILL_RUNTIME
    assert node.calls[0] == ("eth_blockNumber", [])
    client.close()


def test_client_error_surfaces(monkeypatch):
    def failing_urlopen(req, timeout=10):
        raise OSError("connection refused")

    monkeypatch.setattr("mythril_tpu.frontend.rpc._urlreq.urlopen", failing_urlopen)
    client = EthJsonRpc("localhost", 8545)
    with pytest.raises(RPCError):
        client.eth_blockNumber()


def test_dynloader_caches_reads(node):
    loader = DynLoader(EthJsonRpc("localhost", 8545), active=True)
    v1 = loader.read_storage(ADDR, 0)
    v2 = loader.read_storage(ADDR, 0)
    assert int(v1, 16) == 0x2A and v1 == v2
    storage_calls = [c for c in node.calls if c[0] == "eth_getStorageAt"]
    assert len(storage_calls) == 1, "second read must come from the lru cache"
    code = loader.dynld(ADDR)
    loader.dynld(ADDR)
    assert code is not None and code.bytecode.hex() == KILL_RUNTIME
    code_calls = [c for c in node.calls if c[0] == "eth_getCode"]
    assert len(code_calls) == 1


def test_analyze_address_end_to_end(node):
    """The `myth analyze -a <addr>` path: code fetched over RPC, analyzed,
    and the selfdestruct found — with on-chain storage available mid-run."""
    from mythril_tpu.analysis.security import reset_callback_modules
    from mythril_tpu.analysis.module.loader import ModuleLoader
    from mythril_tpu.facade.mythril_analyzer import AnalyzerArgs, MythrilAnalyzer
    from mythril_tpu.facade.mythril_disassembler import MythrilDisassembler

    reset_callback_modules()
    for m in ModuleLoader().get_detection_modules():
        m.cache.clear()
    disassembler = MythrilDisassembler(eth=EthJsonRpc("localhost", 8545))
    address, contract = disassembler.load_from_address(ADDR)
    assert address == ADDR
    assert contract.code == KILL_RUNTIME
    analyzer = MythrilAnalyzer(
        disassembler,
        AnalyzerArgs(
            strategy="dfs",
            transaction_count=1,
            execution_timeout=60,
            modules=["AccidentallyKillable"],
        ),
        address=ADDR,
    )
    report = analyzer.fire_lasers(modules=["AccidentallyKillable"])
    assert len(report.issues) == 1
    issue = list(report.issues.values())[0]
    assert issue.swc_id == "106"


def test_read_storage_slot_and_mapping(node):
    from mythril_tpu.facade.mythril_disassembler import MythrilDisassembler

    disassembler = MythrilDisassembler(eth=EthJsonRpc("localhost", 8545))
    out = disassembler.get_state_variable_from_storage(ADDR, ["0", "2"])
    assert out.splitlines()[0].startswith("0:")
    assert "2a" in out.splitlines()[0]
    # mapping slot math: keccak(key . position)
    out = disassembler.get_state_variable_from_storage(ADDR, ["mapping", "1", "5"])
    line = out.splitlines()[0]
    slot = int(line.split(":")[0], 16)
    from mythril_tpu.support.support_utils import keccak256

    expected = int.from_bytes(
        keccak256((5).to_bytes(32, "big") + (1).to_bytes(32, "big")), "big"
    )
    assert slot == expected


def test_world_state_account_lazy_load(node):
    """Mid-execution account load through the DynLoader (reference
    world_state_account_exist_load_test with a mocked loader)."""
    from mythril_tpu.core.state.world_state import WorldState
    from mythril_tpu.smt import symbol_factory

    loader = DynLoader(EthJsonRpc("localhost", 8545), active=True)
    ws = WorldState(transaction_sequence=[])
    account = ws.accounts_exist_or_load(ADDR, loader)
    assert account.code is not None
    assert account.code.bytecode.hex() == KILL_RUNTIME
