"""Accounts and storage.

Reference parity: mythril/laser/ethereum/state/account.py (Storage :18-99 with
symbolic-array/concrete-K split + lazy on-chain loads, Account :101-223).
"""

from __future__ import annotations

from typing import Dict, Optional

from mythril_tpu.smt import Array, BitVec, K, symbol_factory
from mythril_tpu.support.support_args import args


class Storage:
    """Contract storage: an SMT array plus bookkeeping for reports/pruners.

    ``concrete=True`` (creation txs) starts from an all-zero K array;
    otherwise a named symbolic array (unknown pre-state).  ``printable_storage``
    mirrors writes/reads for report rendering; ``storage_keys_loaded`` guards
    repeated on-chain loads via the dynamic loader.
    """

    def __init__(self, concrete: bool = False, address: Optional[BitVec] = None, dynamic_loader=None):
        self.concrete = concrete and not args.unconstrained_storage
        self.address = address
        self.dynld = dynamic_loader
        addr_tag = (
            hex(address.value) if address is not None and address.value is not None else "sym"
        )
        if self.concrete:
            self._array = K(256, 256, 0)
        else:
            self._array = Array(f"Storage[{addr_tag}]", 256, 256)
        self.printable_storage: Dict[BitVec, BitVec] = {}
        self.storage_keys_loaded: set = set()

    def __getitem__(self, item: BitVec) -> BitVec:
        if (
            self.dynld is not None
            and getattr(self.dynld, "active", False)
            and item.value is not None
            and item.value not in self.storage_keys_loaded
            and self.address is not None
            and self.address.value
        ):
            try:
                value = int(
                    self.dynld.read_storage(f"0x{self.address.value:040x}", item.value), 16
                )
                self.storage_keys_loaded.add(item.value)
                self[item] = symbol_factory.BitVecVal(value, 256)
            except ValueError:
                pass
        return self._array[item]

    def __setitem__(self, key: BitVec, value) -> None:
        if isinstance(value, int):
            value = symbol_factory.BitVecVal(value, 256)
        self.printable_storage[key] = value
        self._array[key] = value

    def __copy__(self) -> "Storage":
        out = Storage.__new__(Storage)
        out.concrete = self.concrete
        out.address = self.address
        out.dynld = self.dynld
        if isinstance(self._array, Array):
            cloned = Array.__new__(Array)
            cloned.raw = self._array.raw
            cloned.domain = self._array.domain
            cloned.range = self._array.range
        else:
            cloned = K.__new__(K)
            cloned.raw = self._array.raw
            cloned.domain = self._array.domain
            cloned.range = self._array.range
        out._array = cloned
        out.printable_storage = dict(self.printable_storage)
        out.storage_keys_loaded = set(self.storage_keys_loaded)
        return out


class Account:
    """An on-chain account: code, nonce, balance closure, storage."""

    def __init__(
        self,
        address,
        code=None,
        contract_name: Optional[str] = None,
        balances: Optional[Array] = None,
        concrete_storage: bool = False,
        dynamic_loader=None,
        nonce: int = 0,
    ):
        if isinstance(address, int):
            address = symbol_factory.BitVecVal(address, 256)
        elif isinstance(address, str):
            address = symbol_factory.BitVecVal(int(address, 16), 256)
        self.address = address
        self.code = code  # Disassembly (may be None for EOA)
        self.contract_name = contract_name or "Unknown"
        self.nonce = nonce
        self.deleted = False
        self.storage = Storage(
            concrete=concrete_storage, address=address, dynamic_loader=dynamic_loader
        )
        # balance reads/writes go through the world state's shared array
        self._balances = balances

    def set_balance(self, balance) -> None:
        if isinstance(balance, int):
            balance = symbol_factory.BitVecVal(balance, 256)
        assert self._balances is not None
        self._balances[self.address] = balance

    def add_balance(self, balance) -> None:
        assert self._balances is not None
        self._balances[self.address] = self._balances[self.address] + balance

    @property
    def balance(self):
        return lambda: self._balances[self.address]

    def set_balances(self, balances: Array) -> None:
        self._balances = balances

    @property
    def serialised_code(self) -> str:
        if self.code is None:
            return ""
        return "0x" + self.code.bytecode.hex()

    def as_dict(self) -> Dict:
        return {
            "nonce": self.nonce,
            "code": self.serialised_code,
            "balance": repr(self.balance()),
            "storage": {repr(k): repr(v) for k, v in self.storage.printable_storage.items()},
        }

    def __copy__(self) -> "Account":
        import copy as _copy

        out = Account.__new__(Account)
        out.address = self.address
        out.code = self.code  # immutable Disassembly shared
        out.contract_name = self.contract_name
        out.nonce = self.nonce
        out.deleted = self.deleted
        out.storage = _copy.copy(self.storage)
        out._balances = self._balances
        return out

    def __str__(self):
        return f"Account(address={self.address}, name={self.contract_name})"
