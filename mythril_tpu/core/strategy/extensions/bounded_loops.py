"""Loop-bound strategy extension: skip states that loop past the bound.

Reference parity: mythril/laser/ethereum/strategy/extensions/bounded_loops.py:27-143
— per-state JUMPDEST trace annotation, repeating-suffix detection via rolling
hash, creation txs get max(8, bound).
"""

from __future__ import annotations

import logging
from typing import List

from mythril_tpu.core.state.annotation import StateAnnotation
from mythril_tpu.core.state.global_state import GlobalState
from mythril_tpu.core.strategy.basic import BasicSearchStrategy
from mythril_tpu.core.transaction.transaction_models import ContractCreationTransaction

log = logging.getLogger(__name__)


class JumpdestCountAnnotation(StateAnnotation):
    """Trace of (source, destination) jump pairs along this path."""

    def __init__(self):
        self._reached_count = {}
        self.trace: List[int] = []

    def __copy__(self):
        out = JumpdestCountAnnotation()
        out._reached_count = dict(self._reached_count)
        out.trace = list(self.trace)
        return out

    @property
    def persist_over_calls(self) -> bool:
        return False


class BoundedLoopsStrategy(BasicSearchStrategy):
    """Wraps another strategy; drops states whose loop count exceeds the bound."""

    def __init__(self, super_strategy: BasicSearchStrategy, loop_bound: int = 3, **kwargs):
        self.super_strategy = super_strategy
        self.bound = loop_bound
        super().__init__(super_strategy.work_list, super_strategy.max_depth)

    @staticmethod
    def calculate_hash(i: int, j: int, trace: List[int]) -> int:
        """Order-independent hash of trace window [i, j) (reference :50)."""
        key = 0
        size = 0
        for itr in range(i, j):
            key |= trace[itr] << ((itr - i) % 64)
            size += 1
        return key

    @staticmethod
    def count_key(trace: List[int], key: int, start: int, size: int) -> int:
        """Count consecutive repetitions of the suffix cycle (reference :60-83)."""
        count = 1
        i = start
        while i >= 0:
            if BoundedLoopsStrategy.calculate_hash(i, i + size, trace) != key:
                break
            count += 1
            i -= size
        return count

    @staticmethod
    def get_loop_count(trace: List[int]) -> int:
        """Longest-suffix-cycle repetition count (reference :85-103)."""
        found = False
        for i in range(len(trace) - 3, 0, -1):
            if trace[i] == trace[-2] and trace[i + 1] == trace[-1]:
                found = True
                break
        if found:
            key = BoundedLoopsStrategy.calculate_hash(i + 1, len(trace) - 1, trace)
            size = len(trace) - i - 2
            if size == 0:
                return 0
            return BoundedLoopsStrategy.count_key(trace, key, i + 1 - size, size)
        return 0

    def get_strategic_global_state(self) -> GlobalState:
        while True:
            state = self.super_strategy.get_strategic_global_state()
            annotations = state.get_annotations(JumpdestCountAnnotation)
            if not annotations:
                annotation = JumpdestCountAnnotation()
                state.annotate(annotation)
            else:
                annotation = annotations[0]

            cur_instr = state.get_current_instruction()
            annotation.trace.append(cur_instr["address"])

            if len(annotation.trace) < 4:
                return state
            # only bother with analysis at loop heads
            count = self.get_loop_count(annotation.trace)
            is_creation = isinstance(
                state.current_transaction, ContractCreationTransaction
            )
            bound = max(8, self.bound) if is_creation else self.bound
            if count > bound:
                log.debug(
                    "loop bound %d exceeded at address %d; skipping state",
                    bound,
                    cur_instr["address"],
                )
                if not self.work_list:
                    raise StopIteration
                continue
            return state

    def run_check(self) -> bool:
        return self.super_strategy.run_check()
