"""Serializable statespace export for --statespace-json.

Reference parity: mythril/analysis/traceexplore.py:44-164.
"""

from __future__ import annotations

import re
from typing import Dict

colors = [
    {"border": "#26996f", "background": "#2f7e5b"},
    {"border": "#9e42b3", "background": "#842899"},
    {"border": "#b82323", "background": "#991d1d"},
    {"border": "#4753bf", "background": "#3b46a1"},
]


def get_serializable_statespace(statespace) -> Dict:
    nodes = []
    edges = []

    color_map = {}
    i = 0
    for key in statespace.nodes:
        node = statespace.nodes[key]
        code = node.contract_name
        if code not in color_map:
            color_map[code] = colors[i % len(colors)]
            i += 1

    for key in statespace.nodes:
        node = statespace.nodes[key]
        code = node.contract_name
        instructions = []
        for state in node.states:
            instr = state.get_current_instruction()
            instructions.append(
                {
                    "address": instr["address"],
                    "opcode": instr["opcode"],
                    "argument": instr.get("argument"),
                }
            )
        nodes.append(
            {
                "id": str(node.uid),
                "func": node.function_name,
                "label": f"{node.function_name} {node.uid}",
                "code": code,
                "truncated": False,
                "instructions": instructions,
                "color": color_map.get(code, colors[0]),
            }
        )

    for edge in statespace.edges:
        condition = "" if edge.condition is None else re.sub(r"\s+", " ", repr(edge.condition))
        edges.append(
            {
                "from": str(edge.node_from),
                "to": str(edge.node_to),
                "arrows": "to",
                "label": condition[:200],
                "smooth": {"type": "cubicBezier"},
            }
        )

    return {"nodes": nodes, "edges": edges}
