"""Multi-selector seeding is a pure partition: recall must not change.

seed_message_call under args.multi_selector_seeding splits each symbolic
tx into one seed per function-table entry plus a complement seed.  The
union of the partition is the single-seed state space, so any analysis
must find exactly the same issues either way.
"""

import pytest

from bench import KILLBILLY, KILLBILLY_CREATION
from mythril_tpu.analysis.security import fire_lasers, reset_callback_modules
from mythril_tpu.analysis.symbolic import SymExecWrapper
from mythril_tpu.frontend.evmcontract import EVMContract
from mythril_tpu.support.support_args import args as global_args


def _analyze(multi_selector: bool):
    reset_callback_modules()
    from mythril_tpu.analysis.module.loader import ModuleLoader

    for m in ModuleLoader().get_detection_modules():
        m.cache.clear()
    old = global_args.multi_selector_seeding
    global_args.multi_selector_seeding = multi_selector
    try:
        contract = EVMContract(
            code=KILLBILLY, creation_code=KILLBILLY_CREATION, name="KillBilly"
        )
        sym = SymExecWrapper(
            contract,
            address=0x0901D12E,
            strategy="bfs",
            transaction_count=3,
            execution_timeout=120,
            modules=["AccidentallyKillable"],
        )
        issues = fire_lasers(sym, white_list=["AccidentallyKillable"])
    finally:
        global_args.multi_selector_seeding = old
    return sorted((i.swc_id, i.address) for i in issues)


def test_multi_selector_seeding_recall_parity():
    single = _analyze(False)
    partitioned = _analyze(True)
    assert single, "killbilly exploit not found at all"
    assert single == partitioned, (
        f"selector partition changed recall: {single} vs {partitioned}"
    )
