"""Span tracer: nesting/ordering, Chrome-trace schema, exporters, overhead."""

import json
import threading

import pytest

from mythril_tpu.observability.tracer import Tracer, get_tracer, traced


@pytest.fixture
def tracer():
    t = Tracer(capacity=1000)
    t.enabled = True
    return t


def test_disabled_tracer_records_nothing():
    t = Tracer()
    with t.span("x", cat="test"):
        pass
    assert len(t) == 0


def test_span_nesting_and_ordering(tracer):
    with tracer.span("outer", cat="test"):
        with tracer.span("inner_a", cat="test"):
            pass
        with tracer.span("inner_b", cat="test"):
            pass

    spans = tracer.spans()
    # spans are recorded on exit: children close before the parent
    assert [s["name"] for s in spans] == ["inner_a", "inner_b", "outer"]
    by_name = {s["name"]: s for s in spans}
    outer, a, b = by_name["outer"], by_name["inner_a"], by_name["inner_b"]
    # containment: both children start and end inside the parent interval
    for child in (a, b):
        assert outer["ts"] <= child["ts"]
        assert child["ts"] + child["dur"] <= outer["ts"] + outer["dur"] + 1e-9
    # ordering: inner_a completed before inner_b started
    assert a["ts"] + a["dur"] <= b["ts"] + 1e-9


def test_span_args_and_set(tracer):
    with tracer.span("q", cat="test", n=3) as sp:
        sp.set(status="sat")
    (span,) = tracer.spans()
    assert span["args"] == {"n": 3, "status": "sat"}


def test_chrome_trace_schema(tracer, tmp_path):
    with tracer.span("parent", cat="test", k=1):
        with tracer.span("child", cat="test"):
            pass
    path = tmp_path / "trace.json"
    tracer.export_chrome_trace(str(path))

    doc = json.loads(path.read_text())
    # the trace_event JSON *object* format Perfetto/chrome://tracing load
    assert isinstance(doc["traceEvents"], list)
    meta = [ev for ev in doc["traceEvents"] if ev["ph"] == "M"]
    slices = [ev for ev in doc["traceEvents"] if ev["ph"] == "X"]
    assert len(slices) == 2
    # metadata names the process and every track that recorded anything
    assert any(
        ev["name"] == "process_name" and ev["args"]["name"] == "mythril-tpu"
        for ev in meta
    )
    named_tids = {ev["tid"] for ev in meta if ev["name"] == "thread_name"}
    for ev in slices:
        assert ev["tid"] in named_tids
        assert isinstance(ev["name"], str)
        assert isinstance(ev["cat"], str)
        # timestamps/durations in microseconds, non-negative
        assert isinstance(ev["ts"], (int, float)) and ev["ts"] >= 0
        assert isinstance(ev["dur"], (int, float)) and ev["dur"] >= 0
        assert isinstance(ev["pid"], int)
        assert isinstance(ev["tid"], int)


def test_jsonl_export(tracer, tmp_path):
    with tracer.span("one", cat="test"):
        pass
    tracer.instant("mark", cat="test")
    path = tmp_path / "trace.jsonl"
    tracer.export_jsonl(str(path))
    lines = [json.loads(line) for line in path.read_text().splitlines()]
    assert [rec["name"] for rec in lines] == ["one", "mark"]
    assert lines[1]["dur"] == 0.0


def test_ring_buffer_bounded_and_counts_drops():
    t = Tracer(capacity=10)
    t.enabled = True
    for i in range(25):
        with t.span(f"s{i}", cat="test"):
            pass
    assert len(t) == 10
    assert t.dropped == 15
    assert t.chrome_trace()["otherData"]["dropped_spans"] == 15
    # the newest spans survive
    assert t.spans()[-1]["name"] == "s24"


def test_thread_safety_all_spans_recorded():
    t = Tracer(capacity=10_000)
    t.enabled = True

    def worker(tid):
        for i in range(100):
            with t.span(f"w{tid}", cat="test"):
                pass

    threads = [threading.Thread(target=worker, args=(k,)) for k in range(8)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    assert len(t) == 800
    # every span carries its recording thread's ident (idents may be
    # reused once a thread exits, so only presence is asserted)
    assert all(s["tid"] for s in t.spans())


def test_traced_decorator():
    t = get_tracer()
    t.reset()
    t.enabled = True
    try:
        @traced("deco.fn", cat="test")
        def fn(x):
            return x * 2

        assert fn(21) == 42
        assert [s["name"] for s in t.spans()] == ["deco.fn"]
    finally:
        t.enabled = False
        t.reset()


def test_reset_clears_and_rebases_origin(tracer):
    with tracer.span("a", cat="test"):
        pass
    tracer.reset()
    assert len(tracer) == 0
    with tracer.span("b", cat="test"):
        pass
    (span,) = tracer.spans()
    # origin was rebased: the new span starts near zero
    assert span["ts"] < 60.0


# -- flight-deck additions: flows, counters, named tracks, drop marker ------


def test_flow_events_link_dispatch_to_harvest(tracer):
    fid = tracer.new_flow_id()
    with tracer.span("dispatch", cat="device"):
        tracer.flow("s", fid, "flow.segment", cat="device")
    with tracer.span("pull", cat="device"):
        tracer.flow("t", fid, "flow.segment", cat="device")
    with tracer.span("harvest", cat="frontier"):
        tracer.flow("f", fid, "flow.segment", cat="device")

    doc = tracer.chrome_trace()
    flows = [ev for ev in doc["traceEvents"] if ev["ph"] in ("s", "t", "f")]
    assert [ev["ph"] for ev in flows] == ["s", "t", "f"]
    # all three endpoints share the id and arrive in wall-clock order
    assert {ev["id"] for ev in flows} == {fid}
    assert flows[0]["ts"] <= flows[1]["ts"] <= flows[2]["ts"]
    # the terminator binds to its ENCLOSING slice, not the next one
    assert flows[2]["bp"] == "e"


def test_new_flow_ids_are_unique(tracer):
    ids = [tracer.new_flow_id() for _ in range(100)]
    assert len(set(ids)) == 100


def test_counter_events_on_registered_track(tracer):
    tid = tracer.register_track("heartbeat")
    assert tid >= 1_000_000_000  # never collides with an OS thread ident
    tracer.counter(
        "pipeline.pool_queue_depth", {"value": 3}, tid=tid
    )
    doc = tracer.chrome_trace()
    (c,) = [ev for ev in doc["traceEvents"] if ev["ph"] == "C"]
    assert c["tid"] == tid
    assert c["args"] == {"value": 3}
    # the synthetic track is named via thread_name metadata
    assert any(
        ev["ph"] == "M"
        and ev["name"] == "thread_name"
        and ev["tid"] == tid
        and ev["args"]["name"] == "heartbeat"
        for ev in doc["traceEvents"]
    )


def test_thread_name_captured_lazily():
    t = Tracer(capacity=100)
    t.enabled = True

    def work():
        with t.span("named", cat="test"):
            pass

    th = threading.Thread(target=work, name="mythril-feas-0")
    th.start()
    th.join()
    assert "mythril-feas-0" in t.thread_names().values()
    doc = t.chrome_trace()
    assert any(
        ev["ph"] == "M" and ev["args"]["name"] == "mythril-feas-0"
        for ev in doc["traceEvents"]
    )


def test_dropped_marker_instant_visible_only_when_truncated():
    t = Tracer(capacity=5)
    t.enabled = True
    for i in range(3):
        with t.span(f"s{i}", cat="test"):
            pass
    doc = t.chrome_trace()
    assert not [e for e in doc["traceEvents"] if e["name"].startswith("tracer.dropped")]

    for i in range(10):
        with t.span(f"t{i}", cat="test"):
            pass
    doc = t.chrome_trace()
    (marker,) = [
        e for e in doc["traceEvents"] if e["name"].startswith("tracer.dropped")
    ]
    assert marker["ph"] == "i" and marker["s"] == "g"  # full-height line
    assert marker["args"]["dropped_spans"] == t.dropped > 0
    # the marker sits at the end of the visible timeline
    assert marker["ts"] == max(
        e["ts"] for e in doc["traceEvents"] if "ts" in e
    )


# -- writer storms: _record and the readers must survive 8-way hammering ----

N_STORM_THREADS = 8
N_STORM_ITER = 500


def _storm(worker, n_threads=N_STORM_THREADS):
    barrier = threading.Barrier(n_threads)

    def run(k):
        barrier.wait()  # maximize interleaving
        worker(k)

    threads = [
        threading.Thread(target=run, args=(k,)) for k in range(n_threads)
    ]
    for th in threads:
        th.start()
    for th in threads:
        th.join()


def test_writer_storm_exact_counts_no_drops():
    t = Tracer(capacity=N_STORM_THREADS * N_STORM_ITER * 3)
    t.enabled = True

    def worker(k):
        for i in range(N_STORM_ITER):
            with t.span(f"w{k}", cat="test", i=i):
                pass
            fid = t.new_flow_id()
            t.flow("s", fid, "flow.storm", cat="test")
            t.flow("f", fid, "flow.storm", cat="test")

    _storm(worker)
    assert len(t) == N_STORM_THREADS * N_STORM_ITER * 3
    assert t.dropped == 0
    # every flow id saw exactly one s and one f
    flows = [s for s in t.spans() if s.get("ph") in ("s", "f")]
    by_id = {}
    for s in flows:
        by_id.setdefault(s["flow_id"], []).append(s["ph"])
    assert all(sorted(phs) == ["f", "s"] for phs in by_id.values())


def test_writer_storm_eviction_accounting_is_exact():
    cap = 64
    t = Tracer(capacity=cap)
    t.enabled = True

    def worker(k):
        for i in range(N_STORM_ITER):
            with t.span(f"w{k}", cat="test"):
                pass

    _storm(worker)
    total = N_STORM_THREADS * N_STORM_ITER
    assert len(t) == cap
    assert t.dropped == total - cap
    assert t.chrome_trace()["otherData"]["dropped_spans"] == total - cap


def test_writer_storm_with_concurrent_readers():
    """summary()/spans()/chrome_trace() race 8 writers without corruption."""
    t = Tracer(capacity=4096)
    t.enabled = True
    stop = threading.Event()
    reader_errors = []

    def read_loop():
        try:
            while not stop.is_set():
                s = t.summary()
                assert 0 <= s["spans"] <= t.capacity
                for rec in t.spans():
                    assert isinstance(rec["name"], str)
                json.dumps(t.chrome_trace())  # full export must serialize
        except Exception as exc:  # pragma: no cover - failure path
            reader_errors.append(exc)

    readers = [threading.Thread(target=read_loop) for _ in range(2)]
    for r in readers:
        r.start()

    def worker(k):
        for i in range(N_STORM_ITER):
            with t.span(f"w{k}", cat="test"):
                pass
            t.counter(f"c{k}", {"value": i})

    try:
        _storm(worker)
    finally:
        stop.set()
        for r in readers:
            r.join()
    assert not reader_errors
    assert t.summary()["spans"] + t.dropped == N_STORM_THREADS * N_STORM_ITER * 2
