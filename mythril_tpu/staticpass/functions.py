"""Function recovery: selector-dispatch idiom + per-function summaries.

Solc emits a dispatcher prologue that compares the first four calldata
bytes against each public selector (``DUP1 PUSH4 sel EQ PUSH2 dest
JUMPI`` ladders, optionally split by ``GT``/``LT`` binary search in
large contracts) with a ``CALLDATASIZE`` guard routing short calldata
to the fallback/receive tail.  :func:`recover_functions` walks that
prologue over the (refined) CFG and partitions the code into
per-function regions keyed by 4-byte selector.

Recovery is ADVISORY, never load-bearing for soundness: anything that
does not match — hand-written dispatchers, unusual ladder orderings,
non-solc code — degrades to "one function: the whole contract", and no
consumer prunes work based on function boundaries.  Issue sets are
bit-identical whether recovery succeeds or degrades.

Per-function summaries re-walk each region with the converged abstract
stacks from :mod:`interproc`, capturing storage read/write key sets,
external-call sites with constant-folded target/value, CALLER-guard
facts, SELFDESTRUCT/DELEGATECALL reachability and unchecked call
returns — the facts detection modules and the interesting-point ranking
consume.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Set, Tuple

from mythril_tpu.staticpass.cfg import E_FALL
from mythril_tpu.staticpass.interproc import _peek, walk_block

_LADDER_BLOCK_CAP = 256  # dispatcher prologue blocks examined at most
_KEY_SET_CAP = 64  # distinct constant storage keys kept per function

_CALL_OPS = frozenset({"CALL", "CALLCODE", "DELEGATECALL", "STATICCALL"})
# stack position (1 = top) of the target address per call opcode
_CALL_TO_POS = {"CALL": 2, "CALLCODE": 2, "DELEGATECALL": 2, "STATICCALL": 2}
_CALL_VALUE_POS = {"CALL": 3, "CALLCODE": 3}


@dataclass(frozen=True)
class CallSite:
    """One external-call instruction with constant-folded operands."""

    instr: int
    addr: int
    opcode: str
    to: Optional[Tuple[int, ...]]  # constant targets, None = unknown
    value: Optional[Tuple[int, ...]]  # constant wei values, None = unknown/NA
    unchecked: bool  # return value immediately POPped


@dataclass(frozen=True)
class StaticFunction:
    """Summary of one recovered function region."""

    selector: Optional[int]  # None for fallback / whole-contract
    name: str  # "0x01020304" | "fallback" | "contract"
    entry_block: int
    entry_addr: int
    n_blocks: int
    storage_reads: Tuple[int, ...]
    storage_writes: Tuple[int, ...]
    reads_unknown: bool  # some SLOAD key did not fold to constants
    writes_unknown: bool
    calls: Tuple[CallSite, ...]
    caller_guarded: bool  # a CALLER comparison gates a branch in-region
    has_selfdestruct: bool
    has_delegatecall: bool
    selfdestruct_addrs: Tuple[int, ...]
    writes_after_call: bool  # an SSTORE is CFG-reachable from a call site


@dataclass(frozen=True)
class FunctionMap:
    dispatch_recovered: bool
    fallback_addr: Optional[int]
    functions: Tuple[StaticFunction, ...]


def _fall_succ(flow, b: int) -> Optional[int]:
    for nb, kind in zip(flow.succ[b], flow.succ_kind[b]):
        if kind == E_FALL:
            return nb
    return None


def _classify_dispatch_block(flow, b: int):
    """('eq', (selector, target_block)) | ('split', target_block) |
    ('size', target_block) | ('stop', None)."""
    t = flow.tables
    s = int(flow.block_start[b])
    last = int(flow.block_end[b]) - 1
    if not t.is_jumpi[last]:
        return "stop", None
    tgt = int(flow.static_target[last])
    if tgt < 0:
        return "stop", None
    tgt_block = int(flow.block_id[tgt])
    for i in range(s, last):
        nm = t.names[i]
        if nm.startswith("PUSH") and t.arg[i] is not None \
                and 0 <= t.arg[i] <= 0xFFFFFFFF:
            # selector compare: the pushed constant is consumed by an EQ
            # before any other push intervenes
            for j in range(i + 1, min(i + 4, last + 1)):
                if t.names[j] == "EQ":
                    return "eq", (int(t.arg[i]), tgt_block)
                if t.names[j].startswith("PUSH"):
                    break
    names = set(t.names[s:last])
    if "CALLDATASIZE" in names and names & {"LT", "GT", "ISZERO"}:
        return "size", tgt_block
    if names & {"LT", "GT"}:
        return "split", tgt_block
    return "stop", None


def _region_of(flow, entry_block: int) -> Set[int]:
    """Forward closure from the entry block over the (refined) edges.
    Regions of different functions may overlap (shared internal helper
    code) — fine, summaries are over-approximate."""
    seen = {entry_block}
    stack = [entry_block]
    while stack:
        b = stack.pop()
        for nb in flow.succ[b]:
            if nb not in seen:
                seen.add(nb)
                stack.append(nb)
    return seen


def _entry_stack(flow, b: int):
    es = getattr(flow, "entry_stack", None)
    return es(b) if es is not None else []


def _vals(v, cap: int = 8) -> Optional[Tuple[int, ...]]:
    return tuple(sorted(v))[:cap] if v is not None else None


def _summarize_region(
    flow, selector: Optional[int], name: str, entry_block: int,
    region: Set[int], instr_reach,
) -> StaticFunction:
    t = flow.tables
    acc: Dict[str, object] = {
        "reads": set(), "writes": set(),
        "reads_unknown": False, "writes_unknown": False,
    }
    calls: List[CallSite] = []
    call_blocks: List[int] = []
    sd_addrs: List[int] = []
    caller_guarded = False
    has_dc = False

    for b in sorted(region):
        s, e = int(flow.block_start[b]), int(flow.block_end[b])
        last = e - 1
        block_names = set(t.names[s:e])
        if "CALLER" in block_names and ("EQ" in block_names or "XOR" in block_names) \
                and t.is_jumpi[last]:
            caller_guarded = True

        def observe(i, stk, _b=b):
            if instr_reach is not None and i < len(instr_reach) \
                    and not instr_reach[i]:
                return
            nm = t.names[i]
            if nm == "SLOAD" or nm == "SSTORE":
                which = "reads" if nm == "SLOAD" else "writes"
                key = _peek(stk, 1)
                keys: Set[int] = acc[which]  # type: ignore[assignment]
                if key is None or len(keys) >= _KEY_SET_CAP:
                    acc[which + "_unknown"] = True
                else:
                    keys.update(key)
            elif nm in _CALL_OPS:
                to = _peek(stk, _CALL_TO_POS[nm])
                value = _peek(stk, _CALL_VALUE_POS[nm]) if nm in _CALL_VALUE_POS else None
                calls.append(CallSite(
                    instr=i, addr=int(t.addr[i]), opcode=nm,
                    to=_vals(to), value=_vals(value),
                    unchecked=(i + 1 < t.n and t.names[i + 1] == "POP"),
                ))
                call_blocks.append(_b)
            elif nm == "SELFDESTRUCT":
                sd_addrs.append(int(t.addr[i]))

        walk_block(t, _entry_stack(flow, b), s, e, observe)
        if "DELEGATECALL" in block_names:
            has_dc = True

    # writes-after-external-call: any SSTORE in the forward closure of a
    # call-site block (the reentrancy-shaped ordering detectors care about)
    writes_after_call = False
    if call_blocks:
        seen = set(call_blocks)
        stack = list(call_blocks)
        while stack and not writes_after_call:
            b = stack.pop()
            s, e = int(flow.block_start[b]), int(flow.block_end[b])
            if "SSTORE" in t.names[s:e]:
                writes_after_call = True
                break
            for nb in flow.succ[b]:
                if nb not in seen:
                    seen.add(nb)
                    stack.append(nb)

    return StaticFunction(
        selector=selector,
        name=name,
        entry_block=entry_block,
        entry_addr=int(t.addr[int(flow.block_start[entry_block])]),
        n_blocks=len(region),
        storage_reads=tuple(sorted(acc["reads"]))[:_KEY_SET_CAP],  # type: ignore[arg-type]
        storage_writes=tuple(sorted(acc["writes"]))[:_KEY_SET_CAP],  # type: ignore[arg-type]
        reads_unknown=bool(acc["reads_unknown"]),
        writes_unknown=bool(acc["writes_unknown"]),
        calls=tuple(calls),
        caller_guarded=caller_guarded,
        has_selfdestruct=bool(sd_addrs),
        has_delegatecall=has_dc,
        selfdestruct_addrs=tuple(sd_addrs),
        writes_after_call=writes_after_call,
    )


def recover_functions(flow, instr_reach=None) -> FunctionMap:
    """Recover the selector dispatch and summarize each function region.
    Degrades to one whole-contract function when the prologue does not
    match the idiom (or the contract genuinely has no dispatcher)."""
    if flow.n_blocks == 0:
        return FunctionMap(False, None, ())
    entries: List[Tuple[int, int]] = []  # (selector, entry_block)
    fallback_block: Optional[int] = None
    queue = [0]
    seen: Set[int] = set()
    while queue and len(seen) < _LADDER_BLOCK_CAP:
        b = queue.pop()
        if b in seen:
            continue
        seen.add(b)
        kind, info = _classify_dispatch_block(flow, b)
        if kind == "eq":
            sel, tgt = info
            entries.append((sel, tgt))
            nb = _fall_succ(flow, b)
            if nb is not None:
                queue.append(nb)
        elif kind == "split":
            queue.append(info)
            nb = _fall_succ(flow, b)
            if nb is not None:
                queue.append(nb)
        elif kind == "size":
            if fallback_block is None:
                fallback_block = info
            nb = _fall_succ(flow, b)
            if nb is not None:
                queue.append(nb)
        else:
            if fallback_block is None and entries:
                fallback_block = b

    if not entries:
        # no ladder recognized: one function spanning the whole contract
        region = _region_of(flow, 0)
        fn = _summarize_region(flow, None, "contract", 0, region, instr_reach)
        return FunctionMap(False, None, (fn,))

    # dedupe selectors keeping the first (dispatch order) occurrence
    by_sel: Dict[int, int] = {}
    for sel, tgt in entries:
        by_sel.setdefault(sel, tgt)

    functions: List[StaticFunction] = []
    for sel, entry_block in by_sel.items():
        region = _region_of(flow, entry_block)
        functions.append(_summarize_region(
            flow, sel, f"0x{sel:08x}", entry_block, region, instr_reach
        ))
    fallback_addr = None
    if fallback_block is not None:
        region = _region_of(flow, fallback_block)
        fb = _summarize_region(
            flow, None, "fallback", fallback_block, region, instr_reach
        )
        functions.append(fb)
        fallback_addr = fb.entry_addr
    return FunctionMap(True, fallback_addr, tuple(functions))


# ranked interesting points (export schema: kind/score/function/selector/addr)
_POINT_SCORES = {
    "unauthenticated_selfdestruct": 100,
    "unauthenticated_delegatecall": 90,
    "write_after_external_call": 70,
    "unchecked_call_return": 40,
}


def interesting_points(fmap: FunctionMap) -> List[dict]:
    """Ranked program points worth symbolic attention, highest first.
    Purely advisory: consumed by `myth static`, meta.staticpass and the
    future coverage-guided controller — never by the pruning gates."""
    points: List[dict] = []

    def add(kind: str, fn: StaticFunction, addr: Optional[int]) -> None:
        points.append({
            "kind": kind,
            "score": _POINT_SCORES[kind],
            "function": fn.name,
            "selector": f"0x{fn.selector:08x}" if fn.selector is not None else None,
            "addr": addr,
        })

    for fn in fmap.functions:
        if fn.has_selfdestruct and not fn.caller_guarded:
            add("unauthenticated_selfdestruct", fn,
                fn.selfdestruct_addrs[0] if fn.selfdestruct_addrs else None)
        if fn.has_delegatecall and not fn.caller_guarded:
            dc = next((c for c in fn.calls if c.opcode == "DELEGATECALL"), None)
            add("unauthenticated_delegatecall", fn, dc.addr if dc else None)
        if fn.writes_after_call:
            add("write_after_external_call", fn,
                fn.calls[0].addr if fn.calls else None)
        for c in fn.calls:
            if c.unchecked:
                add("unchecked_call_return", fn, c.addr)
    points.sort(key=lambda p: (-p["score"], p["addr"] if p["addr"] is not None else 1 << 62))
    return points
