"""Detection-module import must stay jax-free.

Detectors import frontier.taint (bit registry) at load time; the frontier
package's engine->step->jax chain must only load when a FrontierEngine is
actually constructed (svm.py's deliberately-lazy import and its graceful
degradation path depend on this).
"""

import os
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[2]

PROBE = (
    "import sys; "
    "assert 'jax' not in sys.modules, 'jax preloaded at startup'; "
    "import mythril_tpu.analysis.module.loader as L; "
    "mods = L.ModuleLoader().get_detection_modules(); "
    "assert len(mods) == 14, len(mods); "
    "sys.exit(1 if 'jax' in sys.modules else 0)"
)


def test_detector_import_stays_jax_free():
    # a clean PYTHONPATH: the TPU environment's sitecustomize (axon site
    # dir) preloads jax at interpreter startup, which would mask what the
    # detector imports actually pull
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("PYTHONPATH", "PYTHONSTARTUP")
    }
    env["PYTHONPATH"] = str(REPO)
    proc = subprocess.run(
        [sys.executable, "-c", PROBE],
        cwd=str(REPO),
        env=env,
        capture_output=True,
    )
    assert proc.returncode == 0, proc.stderr.decode()
