"""Span tracer: nesting/ordering, Chrome-trace schema, exporters, overhead."""

import json
import threading

import pytest

from mythril_tpu.observability.tracer import Tracer, get_tracer, traced


@pytest.fixture
def tracer():
    t = Tracer(capacity=1000)
    t.enabled = True
    return t


def test_disabled_tracer_records_nothing():
    t = Tracer()
    with t.span("x", cat="test"):
        pass
    assert len(t) == 0


def test_span_nesting_and_ordering(tracer):
    with tracer.span("outer", cat="test"):
        with tracer.span("inner_a", cat="test"):
            pass
        with tracer.span("inner_b", cat="test"):
            pass

    spans = tracer.spans()
    # spans are recorded on exit: children close before the parent
    assert [s["name"] for s in spans] == ["inner_a", "inner_b", "outer"]
    by_name = {s["name"]: s for s in spans}
    outer, a, b = by_name["outer"], by_name["inner_a"], by_name["inner_b"]
    # containment: both children start and end inside the parent interval
    for child in (a, b):
        assert outer["ts"] <= child["ts"]
        assert child["ts"] + child["dur"] <= outer["ts"] + outer["dur"] + 1e-9
    # ordering: inner_a completed before inner_b started
    assert a["ts"] + a["dur"] <= b["ts"] + 1e-9


def test_span_args_and_set(tracer):
    with tracer.span("q", cat="test", n=3) as sp:
        sp.set(status="sat")
    (span,) = tracer.spans()
    assert span["args"] == {"n": 3, "status": "sat"}


def test_chrome_trace_schema(tracer, tmp_path):
    with tracer.span("parent", cat="test", k=1):
        with tracer.span("child", cat="test"):
            pass
    path = tmp_path / "trace.json"
    tracer.export_chrome_trace(str(path))

    doc = json.loads(path.read_text())
    # the trace_event JSON *object* format Perfetto/chrome://tracing load
    assert isinstance(doc["traceEvents"], list)
    assert len(doc["traceEvents"]) == 2
    for ev in doc["traceEvents"]:
        assert ev["ph"] == "X"  # complete events
        assert isinstance(ev["name"], str)
        assert isinstance(ev["cat"], str)
        # timestamps/durations in microseconds, non-negative
        assert isinstance(ev["ts"], (int, float)) and ev["ts"] >= 0
        assert isinstance(ev["dur"], (int, float)) and ev["dur"] >= 0
        assert isinstance(ev["pid"], int)
        assert isinstance(ev["tid"], int)


def test_jsonl_export(tracer, tmp_path):
    with tracer.span("one", cat="test"):
        pass
    tracer.instant("mark", cat="test")
    path = tmp_path / "trace.jsonl"
    tracer.export_jsonl(str(path))
    lines = [json.loads(line) for line in path.read_text().splitlines()]
    assert [rec["name"] for rec in lines] == ["one", "mark"]
    assert lines[1]["dur"] == 0.0


def test_ring_buffer_bounded_and_counts_drops():
    t = Tracer(capacity=10)
    t.enabled = True
    for i in range(25):
        with t.span(f"s{i}", cat="test"):
            pass
    assert len(t) == 10
    assert t.dropped == 15
    assert t.chrome_trace()["otherData"]["dropped_spans"] == 15
    # the newest spans survive
    assert t.spans()[-1]["name"] == "s24"


def test_thread_safety_all_spans_recorded():
    t = Tracer(capacity=10_000)
    t.enabled = True

    def worker(tid):
        for i in range(100):
            with t.span(f"w{tid}", cat="test"):
                pass

    threads = [threading.Thread(target=worker, args=(k,)) for k in range(8)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    assert len(t) == 800
    # every span carries its recording thread's ident (idents may be
    # reused once a thread exits, so only presence is asserted)
    assert all(s["tid"] for s in t.spans())


def test_traced_decorator():
    t = get_tracer()
    t.reset()
    t.enabled = True
    try:
        @traced("deco.fn", cat="test")
        def fn(x):
            return x * 2

        assert fn(21) == 42
        assert [s["name"] for s in t.spans()] == ["deco.fn"]
    finally:
        t.enabled = False
        t.reset()


def test_reset_clears_and_rebases_origin(tracer):
    with tracer.span("a", cat="test"):
        pass
    tracer.reset()
    assert len(tracer) == 0
    with tracer.span("b", cat="test"):
        pass
    (span,) = tracer.spans()
    # origin was rebased: the new span starts near zero
    assert span["ts"] < 60.0
