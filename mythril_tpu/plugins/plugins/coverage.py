"""Instruction-coverage plugin + coverage-driven search strategy.

Reference parity: mythril/laser/plugin/plugins/coverage/coverage_plugin.py:47-101
and coverage_strategy.py:6-41.
"""

from __future__ import annotations

import logging
from typing import Dict, List, Tuple

from mythril_tpu.core.state.global_state import GlobalState
from mythril_tpu.core.strategy.basic import BasicSearchStrategy
from mythril_tpu.plugins.interface import LaserPlugin, PluginBuilder

log = logging.getLogger(__name__)


class InstructionCoverage(LaserPlugin):
    """Tracks a per-bytecode coverage bitmap via the execute_state hook."""

    def __init__(self):
        self.coverage: Dict[str, Tuple[int, List[bool]]] = {}
        self.tx_id = 0

    def initialize(self, symbolic_vm) -> None:
        self.coverage = {}
        self.tx_id = 0
        # expose the instance: the device frontier merges its visited-pc
        # bitmap here (it executes instructions without execute_state hooks)
        symbolic_vm.coverage_plugin = self

        def execute_state_hook(global_state: GlobalState):
            code = global_state.environment.code.bytecode.hex()
            if code not in self.coverage:
                total = len(global_state.environment.code.instruction_list)
                self.coverage[code] = (total, [False] * max(total, 1))
            self.coverage[code][1][
                min(global_state.mstate.pc, len(self.coverage[code][1]) - 1)
            ] = True

        def stop_sym_exec_hook():
            for code, (total, seen) in self.coverage.items():
                covered = sum(seen)
                pct = 100.0 * covered / total if total else 0.0
                log.info(
                    "Achieved %.2f%% coverage for code: %s...",
                    pct,
                    code[:40],
                )

        def start_sym_trans_hook():
            self.tx_id += 1

        symbolic_vm.register_laser_hooks("execute_state", execute_state_hook)
        symbolic_vm.register_laser_hooks("stop_sym_exec", stop_sym_exec_hook)
        symbolic_vm.register_laser_hooks("start_sym_trans", start_sym_trans_hook)

    def record_visited(self, code_hex: str, total: int, indices) -> None:
        """Merge externally-observed instruction indices (the device frontier
        executes without per-instruction hooks).  Device execution is
        speculative — forks later proven UNSAT still mark their pcs — so
        frontier coverage may read slightly above strict sat-reachable
        coverage, matching its states-executed accounting."""
        entry = self.coverage.setdefault(code_hex, (total, [False] * max(total, 1)))
        seen = entry[1]
        for i in indices:
            if 0 <= int(i) < len(seen):
                seen[int(i)] = True

    def get_coverage(self) -> Dict[str, float]:
        return {
            code: (100.0 * sum(seen) / total if total else 0.0)
            for code, (total, seen) in self.coverage.items()
        }


class CoverageStrategy(BasicSearchStrategy):
    """Prefer states whose pc is not yet covered (reference coverage_strategy.py)."""

    def __init__(self, super_strategy: BasicSearchStrategy, coverage_plugin: InstructionCoverage):
        self.super_strategy = super_strategy
        self.coverage_plugin = coverage_plugin
        super().__init__(super_strategy.work_list, super_strategy.max_depth)

    def get_strategic_global_state(self) -> GlobalState:
        for i, state in enumerate(self.work_list):
            if not self._is_covered(state):
                return self.work_list.pop(i)
        return self.super_strategy.get_strategic_global_state()

    def _is_covered(self, global_state: GlobalState) -> bool:
        code = global_state.environment.code.bytecode.hex()
        if code not in self.coverage_plugin.coverage:
            return False
        _, seen = self.coverage_plugin.coverage[code]
        pc = min(global_state.mstate.pc, len(seen) - 1)
        return seen[pc]


class CoveragePluginBuilder(PluginBuilder):
    name = "coverage"

    def __call__(self, *args, **kwargs) -> LaserPlugin:
        return InstructionCoverage()
