"""Pipelined frontier loop: overlap device segments with host harvest/solve.

The synchronous loop in engine._run alternates strictly — dispatch a
segment, block pulling its results, harvest on the host, repeat — so the
device idles for the whole harvest (measured at 66-69% of iteration wall on
the reentrance/bectoken workloads).  This module keeps ONE segment in
flight at all times:

  * dispatch N+1 is CHAINED onto dispatch N's un-materialized device
    outputs (step.chain_dispatch) before the host ever blocks on N, so the
    device starts segment N+1 the moment N retires while the host is still
    pulling/harvesting N;
  * host mutations from harvest N-1 (freed slots, resumed pending forks,
    fresh seed injections) ride dispatch N+1 as a per-slot correction mask
    merged on device — one packed upload, same cost the synchronous loop
    pays for its full push;
  * per-record feasibility checks (engine._prune_running) move into a
    bounded background pool: running paths continue SPECULATIVELY while the
    solver works, and an UNSAT verdict rolls the path (and any descendants
    it forked meanwhile) back at the next harvest.  Pruning is a
    performance optimization, not a soundness gate — issues are confirmed
    by their own solver queries at detection time — so late rollback keeps
    the issue set identical (args.sparse_pruning already disables the
    sweep entirely).

Correction protocol (the part that makes chaining sound):

  * every host write to a slot is uploaded EXACTLY ONCE.  corrections from
    harvest j ride dispatch j+2 (the first dispatch issued after harvest
    j), so ``active_at[slot]`` records that dispatch index;
  * until the pull of segment ``active_at[slot]`` the device's view of the
    slot is stale, so each pull carries the slot's row forward from the
    previous host mirror (pull_harvest builds a fresh mirror every
    segment).  Carried slots get ``ev_len = 0``: their device events were
    already drained at the harvest that mutated them, and re-draining the
    stale buffer would duplicate events;
  * a slot whose correction exposed it FREE becomes device-owned the
    moment a chained dispatch consumes the mask: every later chained
    segment may grant a fork into it, so the host never re-injects into it
    until a sync point (no dispatch in flight) resets ownership.  Fork
    grants into freed slots whose parent was meanwhile killed show up as
    occupied device slots with no host record — the orphan scan clears
    them and schedules the clear as a correction.

Sync points (the only places the pipeline intentionally drains): the first
microbenched dispatch, host arena appends for spill re-injection (an
in-flight segment appends device rows at the same indices), reclaiming
device-owned free slots for a backed-up seed queue, and the final drain —
an in-flight segment is always pulled and harvested before the loop exits,
never discarded.
"""

from __future__ import annotations

import logging
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional

import numpy as np

from mythril_tpu.frontier import ops as O
from mythril_tpu.frontier.records import PathRecord
from mythril_tpu.frontier.state import FrontierState, clear_slot
from mythril_tpu.frontier.stats import FrontierStatistics
from mythril_tpu.observability import deviceplane as _devplane
from mythril_tpu.observability import flightrecorder as _frec
from mythril_tpu.observability import tracer as _otrace
from mythril_tpu.observability.heartbeat import get_heartbeat
from mythril_tpu.observability.metrics import get_registry as _get_metrics
from mythril_tpu.support.support_args import args
from mythril_tpu.support.time_handler import time_handler

log = logging.getLogger(__name__)


def _pc(name: str):
    return _get_metrics().counter("pipeline." + name)


def plan_rebalance(live: np.ndarray, free: np.ndarray, n_shards: int,
                   max_moves: int = 4) -> List[int]:
    """Slots to SPILL so live paths spread across path-shards.

    ``live``/``free`` are [B] bool masks (running record present / host-
    reclaimable).  Returns source slots, hottest shard first — the spill/
    re-inject machinery parks them and re-injects into the coolest shards'
    free slots (``choose_free_slot``).  A move is planned only while the
    hottest shard holds at least 2 more live paths than the coolest AND the
    coolest shards have free slots to receive them, so a balanced (or
    fully packed) pod plans nothing.  Pure numpy, unit-testable."""
    B = live.shape[0]
    if n_shards <= 1 or B % n_shards:
        return []
    sz = B // n_shards
    live_by = live.reshape(n_shards, sz).sum(axis=1)
    free_by = free.reshape(n_shards, sz).sum(axis=1).astype(np.int64)
    moves: List[int] = []
    spilled = np.zeros(B, bool)
    while len(moves) < max_moves:
        hot = int(np.argmax(live_by))
        order = np.argsort(live_by, kind="stable")
        cold = next((int(s) for s in order if free_by[s] > 0), None)
        if cold is None or live_by[hot] - live_by[cold] < 2:
            break
        # spill the hot shard's LAST live slot (latest-injected first, so
        # long-running early paths keep their device residency)
        block = np.flatnonzero(live[hot * sz:(hot + 1) * sz]
                               & ~spilled[hot * sz:(hot + 1) * sz])
        if block.size == 0:
            break
        src = hot * sz + int(block[-1])
        spilled[src] = True
        moves.append(src)
        live_by[hot] -= 1
        live_by[cold] += 1
        free_by[cold] -= 1
    return moves


def choose_free_slot(free: np.ndarray, live: np.ndarray,
                     n_shards: int) -> Optional[int]:
    """First free slot on the least-loaded shard (ties to the lowest shard
    index; slot order within a shard).  With one shard this is exactly the
    pre-pod first-free scan, so single-device injection order — and hence
    the parity baseline — is unchanged."""
    idx = np.flatnonzero(free)
    if idx.size == 0:
        return None
    B = free.shape[0]
    if n_shards <= 1 or B % n_shards:
        return int(idx[0])
    sz = B // n_shards
    live_by = live.reshape(n_shards, sz).sum(axis=1)
    for shard in np.argsort(live_by, kind="stable"):
        block = np.flatnonzero(free[shard * sz:(shard + 1) * sz])
        if block.size:
            return int(shard) * sz + int(block[0])
    return None


class CorrectionLedger:
    """Exactly-once correction bookkeeping for chained dispatches.

    Tracks, per slot, the index of the first segment output that reflects
    the host's latest write (``active_at``), the pending upload mask, and
    device ownership of host-freed slots.  Kept free of engine state so the
    protocol is unit-testable on its own."""

    def __init__(self, n_slots: int):
        self.corr_mask = np.zeros(n_slots, bool)
        self.active_at = np.full(n_slots, -1, np.int64)
        self.device_owned = np.zeros(n_slots, bool)
        self.next_dispatch = 0  # index of the next dispatch to be issued
        self.pulled = -1  # index of the last pulled segment

    def touch(self, slot: int) -> None:
        """Host mutated ``slot``: upload it with the next dispatch."""
        self.corr_mask[slot] = True
        self.active_at[slot] = self.next_dispatch
        _pc("corrected_slots").inc()

    def consume(self, host_seed: np.ndarray) -> np.ndarray:
        """A dispatch is consuming the pending mask: return it (copy) and
        mark host-freed slots device-owned (the device may fork-grant into
        them from this dispatch on)."""
        mask = self.corr_mask.copy()
        self.device_owned |= mask & (host_seed < 0)
        self.corr_mask[:] = False
        self.next_dispatch += 1
        return mask

    def consume_all(self) -> None:
        """A FULL push is being dispatched: every slot becomes device
        authoritative at this dispatch's output."""
        self.corr_mask[:] = False
        self.active_at[:] = self.next_dispatch
        self.next_dispatch += 1

    def on_pull(self) -> np.ndarray:
        """A segment was pulled; returns the slots whose host value is
        newer than this output (to carry forward from the old mirror)."""
        self.pulled += 1
        return np.nonzero(self.active_at > self.pulled)[0]

    def carry_forward(self, new_st: FrontierState, prev_st: FrontierState
                      ) -> int:
        slots = self.on_pull()
        for slot in slots:
            s = int(slot)
            for name, dst, src in zip(new_st._fields, new_st, prev_st):
                if name == "events":
                    continue
                dst[s] = src[s]
            # host-authoritative slots have no undrained device events
            new_st.ev_len[s] = 0
        return len(slots)

    def release_owned(self) -> None:
        """Sync point (no dispatch in flight anywhere): nothing can grant
        into host-freed slots anymore, the host may reclaim them."""
        self.device_owned[:] = False


class FeasibilityPool:
    """Background solver pool for speculative feasibility checks.

    Raws are decoded on the MAIN thread (the walker/arena decode path is
    not thread-safe); workers only run check_satisfiable_batch, which is
    query-cache-aware through the solver fast path.  In-flight queries are
    deduplicated by the fast path's own canonical key (the frozenset of
    constraint term ids), so identical lineages pending at the same time
    solve once.  Actual solves are serialized under one lock: the solver's
    memo caches are shared, and the win is moving the solve OFF the
    dispatch critical path, not parallel solving."""

    def __init__(self, workers: int):
        self._executor = ThreadPoolExecutor(
            max_workers=max(1, int(workers)),
            thread_name_prefix="mythril-feas",
        )
        self._solver_lock = threading.Lock()
        self._lock = threading.Lock()
        self._inflight: Dict[frozenset, list] = {}
        self._done: list = []

    def submit(self, slot: int, rec, n_cons: int, raws, key: frozenset,
               sid: int = -1, verdict: Optional[bool] = None,
               point: str = "") -> None:
        """Queue a feasibility check.  ``verdict=False`` means the abstract
        pre-filter already PROVED the query UNSAT: no worker runs, and the
        verdict is published to EVERY waiter deduplicated under ``key`` —
        including ones already in flight, so concurrent identical lineages
        never fall through to an exact solve the pre-filter refuted.

        ``point`` is the program-point label ("codehash:0xPC") of the JUMPI
        being checked: solver wall time accrues to it in the exploration
        ledger's solver_hotspot histogram, and a kill verdict's *why*
        ("prefilter" / "unsat" / "unknown") rides the done-queue so
        apply_verdicts can stamp the killed paths' termination class."""
        if verdict is False:
            with self._lock:
                waiters = self._inflight.get(key)
                if waiters is not None:
                    waiters.append((slot, rec, n_cons))
                    _pc("pool_inflight_dedup").inc()
                else:
                    self._inflight[key] = [(slot, rec, n_cons)]
                # drain() tolerates a second (key, ok, why) entry for a
                # query a worker also finishes: the later pop finds nothing
                self._done.append((key, False, "prefilter"))
            _pc("pool_prefilter_kills").inc()
            return
        with self._lock:
            waiters = self._inflight.get(key)
            if waiters is not None:
                waiters.append((slot, rec, n_cons))
                _pc("pool_inflight_dedup").inc()
                return
            self._inflight[key] = [(slot, rec, n_cons)]
        _pc("pool_submitted").inc()
        # queue depth is a heartbeat-sampled gauge (pending()); publishing
        # it here on every mutation left whatever the last submit saw,
        # which read stale between sync points
        tracer = _otrace.get_tracer()
        fid = None
        if tracer.enabled:
            # flow arrow: harvest slice (caller's thread) -> worker span
            fid = tracer.new_flow_id()
            tracer.flow("s", fid, "flow.feasibility", cat="solver")
        self._executor.submit(self._work, key, raws, sid, fid, point)

    def _work(self, key: frozenset, raws, sid: int = -1,
              fid: Optional[int] = None, point: str = "") -> None:
        from mythril_tpu.observability.exploration import (
            get_exploration_ledger,
        )
        from mythril_tpu.smt.solver import check_satisfiable_batch

        with _otrace.span("pipeline.feasibility", cat="solver", segment=sid):
            if fid is not None:
                _otrace.get_tracer().flow("f", fid, "flow.feasibility",
                                          cat="solver")
            statuses: list = []
            t0 = time.perf_counter()
            try:
                from mythril_tpu.devsolver.admission import point_context

                with self._solver_lock, point_context(point):
                    ok = bool(check_satisfiable_batch(
                        [raws], statuses_out=statuses)[0])
            except Exception as e:  # pragma: no cover - defensive
                log.debug("background feasibility check failed: %s", e)
                ok = True  # sound: the path just keeps running
            if point:
                get_exploration_ledger().record_solver_time(
                    point, time.perf_counter() - t0)
        why = statuses[0] if statuses else ("sat" if ok else "unsat")
        with self._lock:
            self._done.append((key, ok, why))

    def drain(self) -> list:
        """Verdicts that landed since the last drain as
        (slot, rec, n_cons, ok, why) tuples."""
        out = []
        with self._lock:
            done, self._done = self._done, []
            for key, ok, why in done:
                for item in self._inflight.pop(key, ()):
                    out.append((*item, ok, why))
        return out

    def pending(self) -> int:
        with self._lock:
            return len(self._inflight)

    def shutdown(self) -> None:
        self._executor.shutdown(wait=True)
        # apply nothing: whatever verdicts are still queued are dropped
        # with the run (speculation is sound without them)


class PipelinedRunner:
    """Drives engine._run's segment loop in pipelined (chained) form.

    Constructed by engine._run with the run's prepared state; mutates the
    shared mirrors/records in place and reports the loop outcome via
    attributes (executed, max_live, slow_bailed, width_verdict_valid,
    visited, arena_len)."""

    def __init__(self, engine, *, st, records, walker, arena, ev_seen,
                 seeds, seed_lasers, lasers, ctxs, seed_code_idx, mid_enc,
                 seed_queue, statics, beam, tables, table_code, table_idx,
                 segment, code_dev, cfg, dev_arena, arena_len, visited,
                 deadline, program_key, program_warm, mesh=None,
                 push_fn=None, table_hash=None, repack_fn=None):
        self.engine = engine
        self.caps = engine.caps
        self.st = st
        self.records = records
        self.walker = walker
        self.arena = arena
        self.ev_seen = ev_seen
        self.seeds = seeds
        self.seed_lasers = seed_lasers
        self.lasers = lasers
        self.ctxs = ctxs
        self.seed_code_idx = seed_code_idx
        self.mid_enc = mid_enc
        self.seed_queue = seed_queue
        self.statics = statics
        self.beam = beam
        self.tables = tables
        self.table_code = table_code
        self.table_idx = table_idx
        self.segment = segment
        self.code_dev = code_dev
        self.cfg = cfg
        self.dev_arena = dev_arena
        self.arena_len = arena_len
        self.visited = visited
        self.deadline = deadline
        self.program_key = program_key
        self.program_warm = program_warm
        # packed-code paging: engine callback that folds pending window
        # moves into fresh same-shape tables.  Called ONLY at sync points
        # (no dispatch in flight), the one place swapping code_dev cannot
        # race a chained dispatch that already captured the old tables.
        self.repack_fn = repack_fn

        # pod composition: with a mesh the slot batch is path-sharded and
        # every chained dispatch is one SPMD program.  push_fn is the
        # engine's path-sharded push (push_state otherwise); the mask
        # sharding places correction masks exactly like the state rows, so
        # correction merges stay shard-local.
        self.mesh = mesh
        self.push_fn = push_fn
        self.n_shards = 1
        self.mask_sharding = None
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            from mythril_tpu.parallel.mesh import PATH_AXIS

            self.n_shards = int(mesh.shape[PATH_AXIS])
            self.mask_sharding = NamedSharding(
                mesh, PartitionSpec(PATH_AXIS)
            )
            _get_metrics().gauge("pipeline.mesh_shards").set(self.n_shards)
        self._rebalance_backoff = 0

        self.table_hash = table_hash or [
            "?" for _ in range(len(table_code))
        ]

        self.ledger = CorrectionLedger(self.caps.B)
        self.pool = FeasibilityPool(args.solver_workers)
        self.reinject_q: List[tuple] = []
        # adaptive park pool: re-runnable spills the reinject queue could
        # not hold.  The controller's plan names which to resurrect when
        # arena slots free; anything still pooled at run end flushes back
        # to its host work list (exactly-once: a pooled carrier is never
        # simultaneously on a work list or in a slot)
        self.adaptive_parked: List[tuple] = []

        self.executed = 0
        self.max_live = 0
        self.slow_bailed = False
        self.width_verdict_valid = True

        # flight-deck correlation: every dispatch (full or chained) gets a
        # monotonic segment id that its pull, harvest, replay and
        # feasibility spans all carry, plus a flow id linking the dispatch
        # slice to the host work it produced (s at dispatch, t at pull,
        # f at harvest)
        self.seg_uid = -1
        self.current_sid = -1  # sid of the segment being harvested
        self._seg_flow: Dict[int, int] = {}
        self._last_dispatch_sid = -1

    def _begin_dispatch(self) -> int:
        self.seg_uid += 1
        sid = self.seg_uid
        self._last_dispatch_sid = sid
        tracer = _otrace.get_tracer()
        if tracer.enabled:
            self._seg_flow[sid] = tracer.new_flow_id()
        return sid

    # -- heartbeat source ----------------------------------------------

    def _heartbeat_sample(self) -> dict:
        """Queue depths for the heartbeat sampler.  Runs on the sampler
        thread against concurrently-mutated state: values are snapshots,
        and the sampler tolerates a transient race throwing."""
        B = self.caps.B
        live, free = self._slot_masks()
        sample = {
            "pipeline.pool_queue_depth": self.pool.pending(),
            "pipeline.ledger_pending_corrections": int(
                self.ledger.corr_mask.sum()
            ),
            "pipeline.reinject_queue_depth": len(self.reinject_q),
            "pipeline.seed_queue_depth": len(self.seed_queue),
            "frontier.arena_occupancy": int(self.arena.length),
            "frontier.live_paths": int(live.sum()),
        }
        n_sh = self.n_shards
        if n_sh >= 1 and B % max(n_sh, 1) == 0:
            sz = B // n_sh
            sample["pipeline.free_slots_by_shard"] = {
                f"shard{i}": int(free[i * sz:(i + 1) * sz].sum())
                for i in range(n_sh)
            }
            sample["pipeline.live_slots_by_shard"] = {
                f"shard{i}": int(live[i * sz:(i + 1) * sz].sum())
                for i in range(n_sh)
            }
        return sample

    # -- walker park sink: catch re-runnable spills ---------------------

    def _park_sink(self, laser, rec, carrier, snap) -> bool:
        """Batch-full spills are perfectly re-runnable device states; queue
        them for re-injection at the next sync point instead of bouncing
        them to the host work list.  Semantic parks (the device provably
        cannot execute the instruction) always go to the host."""
        if snap.get("semantic_park"):
            return False
        from mythril_tpu.frontier.engine import _mid_eligible

        if len(self.reinject_q) >= 2 * self.caps.B:
            # queue full: these spills are exactly the "budget" parks the
            # adaptive plan resurrects when slots free — pool them
            # (bounded) instead of bouncing to the host work list
            if self._adaptive_enabled() and \
                    len(self.adaptive_parked) < 4 * self.caps.B \
                    and _mid_eligible(carrier):
                self.adaptive_parked.append((laser, carrier))
                _pc("adaptive_parked").inc()
                return True
            return False
        if not _mid_eligible(carrier):
            return False
        self.reinject_q.append((laser, carrier))
        _pc("reinject_queued").inc()
        return True

    # -- speculative verdicts ------------------------------------------

    def apply_verdicts(self) -> None:
        from mythril_tpu.observability.exploration import (
            VERDICT_CLASS,
            get_exploration_ledger,
        )

        st, records = self.st, self.records
        led = get_exploration_ledger()
        for slot, rec, n_cons, ok, why in self.pool.drain():
            if ok:
                if records[slot] is rec:
                    rec._pruned_at = max(rec._pruned_at, n_cons)
                continue
            # UNSAT: roll back the speculatively-running path and every
            # descendant it forked while the verdict was pending.  A path
            # that already finished replayed its events, but its issues
            # (if any) fail their own confirmation query — soundness does
            # not depend on this rollback, only slot recycling does.
            cls = VERDICT_CLASS.get(why, "solver_unsat")
            for s in range(self.caps.B):
                r = records[s]
                node = r
                while node is not None and node is not rec:
                    node = node.parent
                if node is rec and r is not None:
                    records[s] = None
                    clear_slot(st, s)
                    self.ev_seen[s] = 0
                    self.ledger.touch(s)
                    _pc("pool_unsat_rollbacks").inc()
                    if r.term_class is None:
                        r.term_class = cls
                        led.stamp(cls)

    def clear_orphans(self) -> None:
        """Device-occupied slots with no host record are descendants of
        paths killed while a segment was in flight: the fork event that
        would have created their record was skipped (dead parent)."""
        st, records = self.st, self.records
        for slot in range(self.caps.B):
            if records[slot] is not None:
                continue
            if self.ledger.active_at[slot] > self.ledger.pulled:
                continue  # host-authoritative row, host knows it is free
            if int(st.seed[slot]) >= 0:
                clear_slot(st, slot)
                self.ev_seen[slot] = 0
                self.ledger.touch(slot)
                _pc("orphan_rollbacks").inc()

    # -- refill ---------------------------------------------------------

    def _slot_masks(self):
        """([B] live, [B] free) numpy masks of the host's current view:
        live = running record present, free = host-reclaimable."""
        B = self.caps.B
        rec = np.fromiter(
            (self.records[s] is not None for s in range(B)), bool, B
        )
        seed = np.asarray(self.st.seed)
        live = rec & (np.asarray(self.st.halt) == O.H_RUNNING) & (seed >= 0)
        free = ~rec & ~self.ledger.device_owned & (seed < 0)
        return live, free

    def _free_slot(self) -> Optional[int]:
        """Next injection target.  Single-device: first free slot (the
        pre-pod scan).  Mesh: a free slot on the least-loaded shard, so
        injections spread over the pod instead of packing shard 0."""
        live, free = self._slot_masks()
        return choose_free_slot(free, live, self.n_shards)

    def refill(self) -> None:
        """Queued seeds into host-reclaimable free slots.  Unlike the
        synchronous loop, beam scores of LIVE slots are not refreshed:
        uploading onto a device-advanced slot would clobber it.  Seed
        order follows the adaptive plan's deficit scheduler (FIFO — the
        parity baseline — with one code or --no-adaptive)."""
        from mythril_tpu.frontier.engine import (
            _adaptive_pick,
            _beam_importance,
        )

        eng = self.engine
        while self.seed_queue:
            slot = self._free_slot()
            if slot is None:
                break
            si = self.seed_queue.pop(
                _adaptive_pick(self.seed_queue, self.seed_code_idx,
                               self.table_hash)
            )
            eng._inject(self.st, slot, si, self.ctxs[si],
                        self.seed_code_idx[si],
                        _beam_importance(self.seeds[si]) if self.beam else 0,
                        static=self.statics[si])
            if self.mid_enc[si] is not None:
                with _otrace.span("frontier.mid_inject", cat="frontier",
                                  seed=si):
                    eng._apply_mid(self.st, slot, self.mid_enc[si])
                FrontierStatistics().mid_injections += 1
            self.records[slot] = PathRecord(seed_idx=si)
            self.ev_seen[slot] = 0
            self.ledger.touch(slot)

    # -- adaptive steering ---------------------------------------------

    @staticmethod
    def _adaptive_enabled() -> bool:
        return bool(getattr(args, "adaptive", True))

    def _adaptive_requeue(self) -> None:
        """Resurrect pooled spills when arena slots free (sync point
        only: the moved carriers ride the ordinary ``_reinject`` path, so
        arena appends and ledger touches stay inside the existing
        exactly-once protocol).  The plan picks which parked paths earn
        their slot back; the rest stay pooled."""
        if not self.adaptive_parked or not self._adaptive_enabled():
            return
        live, free = self._slot_masks()
        room = int(free.sum()) - len(self.reinject_q) - len(self.seed_queue)
        if room <= 0:
            return
        try:
            from mythril_tpu.adaptive import get_adaptive_controller

            parked = [
                (id(carrier), "budget_exhausted")
                for _, carrier in self.adaptive_parked
            ]
            picked = set(get_adaptive_controller().select_requeue(
                parked, live=(), limit=room
            ))
        except Exception:  # steering must never break a dispatch
            log.debug("adaptive requeue failed", exc_info=True)
            return
        if not picked:
            return
        keep: List[tuple] = []
        cap = 2 * self.caps.B
        for laser, carrier in self.adaptive_parked:
            if id(carrier) in picked and len(self.reinject_q) < cap:
                self.reinject_q.append((laser, carrier))
            else:
                keep.append((laser, carrier))
        self.adaptive_parked = keep

    def _adaptive_coverage_stop(self) -> bool:
        from mythril_tpu.frontier.engine import _adaptive_coverage_stop

        return _adaptive_coverage_stop()

    # -- sync-point spill re-injection ---------------------------------

    def _reinject(self) -> bool:
        """Encode queued spills back into free slots.  ONLY at a sync
        point: seed-context/mid encoding appends host arena rows, which an
        in-flight segment would race at the same indices.  Returns True
        when device arena rows were appended (the next dispatch must use
        the refreshed arena)."""
        from mythril_tpu.frontier.engine import _beam_importance
        from mythril_tpu.frontier.step import push_arena_rows

        eng, arena = self.engine, self.arena
        old_len = arena.length
        q, self.reinject_q = self.reinject_q, []
        for laser, carrier in q:
            slot = self._free_slot()
            ci = self.table_idx.get((id(laser), id(carrier.environment.code)))
            if slot is None or ci is None:
                laser.work_list.append(carrier)
                continue
            try:
                si = len(self.seeds)
                ctx = eng._seed_ctx(arena, carrier, si)
                enc = eng._encode_mid(arena, carrier)
            except MemoryError:
                laser.work_list.append(carrier)
                continue
            if enc is None:
                # stamp like a bounced seed so _mid_eligible stops
                # re-offering the state at this pc
                carrier._frontier_park_pc = carrier.mstate.pc
                laser.work_list.append(carrier)
                continue
            self.walker.add_seed(laser, self.tables[ci], carrier)
            self.ctxs.append(ctx)
            self.seed_code_idx.append(ci)
            self.mid_enc.append(enc)
            self.statics.append(
                1 if getattr(carrier.environment, "static", False) else 0
            )
            eng._inject(self.st, slot, si, ctx, ci,
                        _beam_importance(carrier) if self.beam else 0,
                        static=self.statics[-1])
            eng._apply_mid(self.st, slot, enc)
            FrontierStatistics().mid_injections += 1
            self.records[slot] = PathRecord(seed_idx=si)
            self.ev_seen[slot] = 0
            self.ledger.touch(slot)
            _pc("reinjected").inc()
        if arena.length > old_len:
            self.dev_arena = push_arena_rows(
                self.dev_arena, arena, old_len, arena.length
            )
            self.arena_len = arena.length
            return True
        return False

    def _rebalance(self) -> bool:
        """Sync-point live-slot rebalance across path-shards.

        Spills the hottest shard's youngest live paths through the ordinary
        batch-full park flow — snapshot, forced ``H_PARK``, walker replay +
        commit — so they land in ``reinject_q`` via the park sink and are
        re-injected (same sync point) into the coolest shards' free slots.
        Every spill and re-injection goes through ``ledger.touch``, so the
        exactly-once correction protocol is preserved.  Returns True when
        any slot moved."""
        from mythril_tpu.frontier.records import snapshot_slot

        live, free = self._slot_masks()
        moves = plan_rebalance(live, free, self.n_shards)
        if not moves:
            return False
        stats = FrontierStatistics()
        for src in moves:
            rec = self.records[src]
            rec.final = snapshot_slot(self.st, src)
            rec.final["halt"] = O.H_PARK
            stats.device_paths += 1
            stats.record_bulk_park("rebalance")
            try:
                self.walker.replay(rec)
                self.walker.commit(rec)
            except Exception as e:  # pragma: no cover - diagnostics
                log.warning(
                    "frontier rebalance failed on a path: %s", e,
                    exc_info=True,
                )
            self.records[src] = None
            clear_slot(self.st, src)
            self.ev_seen[src] = 0
            self.ledger.touch(src)
            _pc("rebalanced_slots").inc()
        return True

    def _flush_reinject_queue(self) -> None:
        for laser, carrier in self.reinject_q:
            laser.work_list.append(carrier)
        self.reinject_q = []
        self._flush_adaptive_pool()

    def _flush_adaptive_pool(self) -> None:
        for laser, carrier in self.adaptive_parked:
            laser.work_list.append(carrier)
        self.adaptive_parked = []

    # -- the loop -------------------------------------------------------

    def _ramped_cfg(self):
        caps = self.caps
        return self.cfg._replace(
            k_limit=np.int32(
                min(caps.K, 96 << min(FrontierStatistics().segments, 4))
            )
        )

    def _dispatch_full(self):
        """Full push of the host mirror (dispatch 0 and sync points)."""
        from mythril_tpu.frontier.step import push_state

        sid = self._begin_dispatch()
        cfg = self._ramped_cfg()
        with _otrace.span("frontier.dispatch", cat="device", segment=sid,
                          full=True, shards=self.n_shards):
            self._emit_dispatch_flow(sid)
            st_dev = (self.push_fn or push_state)(self.st)
            self.ledger.consume_all()
            # every free slot is exposed to the device again
            for slot in range(self.caps.B):
                self.ledger.device_owned[slot] = self.records[slot] is None
            full_args = (st_dev, self.dev_arena, self.arena_len,
                         self.visited, self.code_dev, cfg)
            out = self.segment(*full_args)
        return out, full_args

    def _chain(self, inflight, arena_override=None):
        from mythril_tpu.frontier.step import chain_dispatch

        sid = self._begin_dispatch()
        cfg = self._ramped_cfg()
        with _otrace.span("frontier.dispatch", cat="device", segment=sid,
                          chained=True, shards=self.n_shards):
            self._emit_dispatch_flow(sid)
            mask = self.ledger.consume(self.st.seed)
            out = chain_dispatch(self.segment, inflight, self.st, mask,
                                 self.code_dev, cfg,
                                 arena_override=arena_override,
                                 push_fn=self.push_fn,
                                 mask_sharding=self.mask_sharding,
                                 segment_id=sid)
        _pc("segments_pipelined").inc()
        return out

    def _emit_dispatch_flow(self, sid: int) -> None:
        fid = self._seg_flow.get(sid)
        if fid is not None:
            _otrace.get_tracer().flow("s", fid, "flow.segment", cat="device")

    def run(self) -> None:
        from mythril_tpu.frontier import engine as _eng
        from mythril_tpu.frontier.step import pull_harvest

        eng, caps = self.engine, self.caps
        stats = FrontierStatistics()
        reg = _get_metrics()
        self.walker.park_sink = self._park_sink
        narrow_harvests = 0
        run_segments = 0
        stop: Optional[str] = None
        # microbench timings are single-device figures; skip it on a mesh
        # (the synchronous loop applies the same gate)
        micro_pending = bool(args.frontier_microbench and not stats.microbench
                             and self.mesh is None)

        hb = get_heartbeat()
        hb.register("pipeline", self._heartbeat_sample)
        hb_started = False
        if not hb.running:
            # CLI runs with --heartbeat-out arm the sampler up front; any
            # other pipelined run (facade embedding, bench) starts it here
            # so pool/ledger depth gauges are sampled, not set-on-mutation
            hb.start(period_s=getattr(args, "heartbeat_interval", 0.5),
                     out_path=getattr(args, "heartbeat_out", None))
            hb_started = True

        # device plane: tag this thread's dispatches/pulls (and any XLA
        # compile they trigger) with the program's bucket shape for the
        # duration of the run; restored in the finally below
        _devplane.install()
        _bucket_tag = _devplane.bucket_tag(self.program_key[1])
        _dscope = _devplane.dispatch_scope(_bucket_tag)
        _dscope.__enter__()

        t0 = time.perf_counter()
        inflight, full_args = self._dispatch_full()
        inflight_sid = self._last_dispatch_sid
        dispatch_wall = time.perf_counter() - t0
        prev_st = self.st
        # while any dispatch is in flight the device owns the arena append
        # indices; host encode paths must not race them (arena.freeze)
        self.arena.freeze()
        watch = _frec.activity()
        watch.__enter__()
        try:
            while True:
                deadline_hit = (time.perf_counter() > self.deadline
                                or time_handler.time_remaining() <= 0)
                # chain the next dispatch BEFORE blocking on the current
                # one, unless this iteration must end at a sync point
                free_owned = bool(
                    (self.ledger.device_owned
                     & np.fromiter((self.records[s] is None
                                    for s in range(caps.B)), bool, caps.B)
                     ).any()
                )
                want_sync = bool(
                    micro_pending or self.reinject_q
                    or (self.seed_queue and free_owned)
                    or (self.adaptive_parked and free_owned)
                )
                if (not want_sync and self.n_shards > 1
                        and stop is None and not deadline_hit):
                    # pod imbalance: force a sync point so _rebalance can
                    # spill/re-inject; backoff avoids syncing every segment
                    # when the imbalance is not fixable (e.g. no free slots)
                    if self._rebalance_backoff > 0:
                        self._rebalance_backoff -= 1
                    else:
                        live_m, free_m = self._slot_masks()
                        if plan_rebalance(live_m, free_m, self.n_shards):
                            want_sync = True
                            _pc("rebalance_syncs").inc()
                nxt = None
                nxt_sid = -1
                nxt_wall = 0.0
                if stop is None and not deadline_hit and not want_sync:
                    t_d = time.perf_counter()
                    nxt = self._chain(inflight)
                    nxt_sid = self._last_dispatch_sid
                    nxt_wall = time.perf_counter() - t_d

                # ---- pull: the pipeline's only blocking point
                (out_state, out_arena, out_len, n_exec, seg_ml,
                 out_visited) = inflight
                t_pull = time.perf_counter()
                _req_tags = getattr(self.engine, "request_tags", None)
                with _otrace.span(
                    "frontier.segment", cat="device", segment=inflight_sid,
                    warm=self.program_warm, pipelined=True,
                    **({"requests": ",".join(_req_tags)} if _req_tags else {}),
                ), _otrace.device_annotation("frontier.segment"):
                    _fid = self._seg_flow.get(inflight_sid)
                    if _fid is not None:
                        _otrace.get_tracer().flow(
                            "t", _fid, "flow.segment", cat="device"
                        )
                    self.engine._fire_request_flows()
                    # steady state (next dispatch chained): delta pull —
                    # the [B] scalar plane + dirty rows/events only; a sync
                    # point follows otherwise and _dispatch_full pushes the
                    # whole mirror, so pull everything
                    new_st, arena_len_new, n_exec_host, seg_ml_host = (
                        pull_harvest(
                            out_state, out_len, n_exec, seg_ml,
                            prev=prev_st if nxt is not None else None,
                            shards=self.n_shards,
                        )
                    )
                bubble = time.perf_counter() - t_pull
                self.max_live = max(self.max_live, seg_ml_host)
                self.arena.pull_from_device(out_arena, arena_len_new)
                self.arena_len = arena_len_new
                self.dev_arena = out_arena
                self.visited = out_visited
                self.executed += n_exec_host
                stats.device_instructions += n_exec_host
                stats.segments += 1
                # host-visible device cost of this segment: its dispatch
                # call plus the time the host actually waited on it — the
                # harvest that overlapped it is NOT device time
                seg_equiv = dispatch_wall + bubble
                stats.segment_s += seg_equiv
                reg.observe("frontier.segment_wall_s", seg_equiv)
                _devplane.observe_segment(seg_equiv, _bucket_tag)
                reg.counter("pipeline.bubble_s").inc(bubble)
                if nxt is not None:
                    reg.counter("pipeline.overlap_segments").inc()
                _eng._WARM_PROGRAMS.add(self.program_key)
                # the executable is compiled and persistently cached now:
                # harvest its cost/memory analysis once, off-thread
                _devplane.harvest_analysis(
                    self.segment, lambda: full_args, _bucket_tag
                )

                if micro_pending and n_exec_host > 0:
                    t_mb = time.perf_counter()
                    eng._run_microbench(
                        self.segment, full_args, n_exec_host, new_st
                    )
                    self.deadline += time.perf_counter() - t_mb
                    micro_pending = False

                # ---- harvest (overlaps the in-flight nxt segment)
                carried = self.ledger.carry_forward(new_st, prev_st)
                if carried:
                    _pc("carried_slots").inc(carried)
                self.st = new_st
                prev_st = new_st
                if nxt is None:
                    self.ledger.release_owned()
                _frec.beat()  # a segment retired: push the watchdog out
                t_har = time.perf_counter()
                self.apply_verdicts()
                self.current_sid = inflight_sid
                with _otrace.span("frontier.harvest", cat="frontier",
                                  segment=inflight_sid):
                    _fid = self._seg_flow.pop(inflight_sid, None)
                    if _fid is not None:
                        _otrace.get_tracer().flow(
                            "f", _fid, "flow.segment", cat="device"
                        )
                    eng._harvest(self.st, self.records, self.walker,
                                 self.ev_seen, pipe=self)
                self.clear_orphans()
                for slot in range(caps.B):
                    if self.records[slot] is not None:
                        self.ledger.device_owned[slot] = False
                self.ev_seen.fill(0)
                har_only = time.perf_counter() - t_har
                stats.harvest_s += har_only
                reg.observe("frontier.harvest_wall_s", har_only)
                if nxt is not None:
                    reg.counter("pipeline.overlap_s").inc(har_only)

                # ---- slow-bail accounting on the host-visible wall
                bail_now = False
                if ((run_segments > 0 or self.program_warm)
                        and not args.frontier_force):
                    host_rates = [
                        r for r in (
                            getattr(laser, "host_step_rate", lambda: None)()
                            for laser in self.lasers
                        ) if r
                    ]
                    bail_rate = (
                        _eng._SLOW_BAIL_HOST_FACTOR * min(host_rates)
                        if host_rates else _eng._SLOW_BAIL_FLOOR
                    )
                    code_keys = [_eng._code_key(c) for c in self.table_code]
                    seg_rate = n_exec_host / max(seg_equiv, 1e-6)
                    if seg_rate < bail_rate:
                        counts = [
                            _eng._SLOW_SEGMENTS.get(k, 0) + 1
                            for k in code_keys
                        ]
                        for k, c in zip(code_keys, counts):
                            _eng._SLOW_SEGMENTS[k] = c
                        if (max(counts) >= _eng._SLOW_BAIL_SEGMENTS
                                or seg_rate
                                < _eng._SLOW_BAIL_DECISIVE * bail_rate):
                            log.info(
                                "frontier: %d instructions in %.2fs (below "
                                "%.0f/s); host engine takes over",
                                n_exec_host, seg_equiv, bail_rate,
                            )
                            bail_now = True
                    else:
                        for k in code_keys:
                            _eng._SLOW_SEGMENTS.pop(k, None)
                run_segments += 1

                if stop is None:
                    self.refill()
                live = int(((self.st.halt == O.H_RUNNING)
                            & (self.st.seed >= 0)).sum())
                self.max_live = max(self.max_live, live)

                # ---- exit decisions (first verdict wins; a later drain
                # iteration must not overwrite it)
                if stop is None:
                    if deadline_hit:
                        log.info(
                            "frontier: execution timeout; parking live paths"
                        )
                        stop = "timeout"
                    elif bail_now:
                        stop = "slow-bail"
                    elif self._adaptive_coverage_stop():
                        log.info(
                            "frontier: coverage target reached; "
                            "parking live paths"
                        )
                        stop = "coverage-target"
                    elif (live == 0 and not self.seed_queue
                          and not self.reinject_q
                          and not self.adaptive_parked):
                        stop = "done"
                    elif (self.arena_len + max(live, 1) * caps.R * 4
                          >= caps.ARENA):
                        # double the synchronous margin: up to two segments
                        # of appends can be in flight before the next check
                        log.warning(
                            "frontier: arena nearly full; parking live paths"
                        )
                        stop = "arena-full"
                    elif live < caps.MIN_LIVE:
                        narrow_harvests += 1
                        if narrow_harvests >= caps.NARROW_BAIL:
                            log.info(
                                "frontier: only %d live paths after %d "
                                "segments; host engine takes over",
                                live, narrow_harvests,
                            )
                            stop = "narrow-bail"
                    else:
                        narrow_harvests = 0

                if nxt is not None:
                    inflight = nxt
                    inflight_sid = nxt_sid
                    dispatch_wall = nxt_wall
                    continue
                # sync point: no dispatch in flight anywhere
                if stop is not None:
                    break
                self.ledger.release_owned()
                self.arena.thaw()
                if self.n_shards > 1:
                    moved = self._rebalance()
                    self._rebalance_backoff = 0 if moved else 2
                self._adaptive_requeue()
                if (self.adaptive_parked and not self.reinject_q
                        and not self.seed_queue):
                    live_now, _ = self._slot_masks()
                    if not live_now.any():
                        # nothing else runs and the plan declined the
                        # pooled spills: hand them to the host engine
                        # rather than spin on empty segments
                        self._flush_adaptive_pool()
                if self.repack_fn is not None:
                    # fold pending page-window moves in BEFORE re-injection
                    # so faulted carriers resume against tables whose
                    # resident window now covers their pc
                    new_cd = self.repack_fn()
                    if new_cd is not None:
                        self.code_dev = new_cd
                if self.reinject_q:
                    self._reinject()
                self.refill()
                t0 = time.perf_counter()
                inflight, full_args = self._dispatch_full()
                inflight_sid = self._last_dispatch_sid
                dispatch_wall = time.perf_counter() - t0
                self.arena.freeze()
        finally:
            _dscope.__exit__(None, None, None)
            watch.__exit__(None, None, None)
            self.arena.thaw()
            self.walker.park_sink = None
            self._flush_reinject_queue()
            self.pool.shutdown()
            # an abandoned dispatch (exception before its pull) would leave
            # a started flow with no finish; close it so every "s" in the
            # export has its "f"
            if self._seg_flow:
                tracer = _otrace.get_tracer()
                for sid, fid in self._seg_flow.items():
                    with tracer.span("frontier.dispatch.abandoned",
                                     cat="device", segment=sid):
                        tracer.flow("f", fid, "flow.segment", cat="device")
                self._seg_flow.clear()
            hb.unregister("pipeline")
            if hb_started:
                hb.stop()
            overlap = reg.counter("pipeline.overlap_s").value
            total_har = overlap + reg.counter("pipeline.bubble_s").value
            if total_har > 0:
                reg.gauge("pipeline.overlap_ratio").set(
                    round(overlap / total_har, 4)
                )
            # the microbench ran at a sync point (full pull), so its
            # bytes_pulled estimate is the full-state figure; overwrite it
            # with the measured steady-state delta-pull average
            mb = stats.microbench
            pulls = reg.counter("pipeline.delta_pulls").value
            if mb and pulls:
                mb = dict(mb)
                mb["bytes_pulled_meta_per_segment"] = int(
                    reg.counter("pipeline.delta_pull_bytes").value / pulls
                )
                mb["delta_pull_segments"] = int(pulls)
                stats.microbench = mb

        if stop == "slow-bail":
            self.slow_bailed = True
        if stop in ("timeout", "slow-bail", "arena-full", "coverage-target"):
            self.width_verdict_valid = False
        live = int(((self.st.halt == O.H_RUNNING)
                    & (self.st.seed >= 0)).sum())
        if stop != "done" or live > 0:
            eng._park_all(self.st, self.records, self.walker,
                          reason=stop or "drain")
