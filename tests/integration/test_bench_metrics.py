"""Bench metric helpers: time-to-full-recall semantics."""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).parents[2]))


class _Issue:
    def __init__(self, swc_id, t):
        self.swc_id = swc_id
        self.discovery_time = t


def test_ttfr_is_max_over_contracts_of_earliest_match(monkeypatch):
    import bench
    from mythril_tpu.analysis.report import StartTime

    base = StartTime().global_start_time
    t0 = base  # rebase to zero
    monkeypatch.setattr(
        bench, "CORPUS_RECALL", {"a": "106", "b": "101"}
    )
    per_name = {
        "a": [_Issue("106", 5.0), _Issue("106", 9.0)],   # earliest 5
        "b": [_Issue("110", 1.0), _Issue("101", 7.0)],   # earliest match 7
    }
    assert abs(bench._ttfr(per_name, t0) - 7.0) < 1e-6


def test_ttfr_nan_when_recall_incomplete(monkeypatch):
    import bench

    monkeypatch.setattr(bench, "CORPUS_RECALL", {"a": "106", "b": "101"})
    per_name = {"a": [_Issue("106", 5.0)], "b": [_Issue("110", 1.0)]}
    out = bench._ttfr(per_name, 0.0)
    assert out != out  # NaN


def test_ttfr_skips_other_shards(monkeypatch):
    import bench

    monkeypatch.setattr(bench, "CORPUS_RECALL", {"a": "106", "b": "101"})
    per_name = {"a": [_Issue("106", 3.0)]}  # "b" on another shard
    from mythril_tpu.analysis.report import StartTime

    base = StartTime().global_start_time
    assert abs(bench._ttfr(per_name, base) - 3.0) < 1e-6
