"""Deferred issues: modules park constraints, the engine solves once per tx end.

Reference parity: mythril/analysis/potential_issues.py:82-126 — modules create
PotentialIssue records (no model yet) on a state annotation;
check_potential_issues solves each at transaction end, converting the solvable
ones into confirmed Issues with concrete transaction sequences.  The
annotation's search_importance (10 x #issues) steers beam search (:61-62).
"""

from __future__ import annotations

import logging
from functools import lru_cache
from typing import List

from mythril_tpu.analysis.report import Issue
from mythril_tpu.analysis.solver import get_transaction_sequence
from mythril_tpu.core.state.annotation import StateAnnotation
from mythril_tpu.core.state.global_state import GlobalState
from mythril_tpu.exceptions import UnsatError

log = logging.getLogger(__name__)


class PotentialIssue:
    def __init__(
        self,
        contract: str,
        function_name: str,
        address: int,
        swc_id: str,
        title: str,
        bytecode,
        detector,
        severity: str = "Medium",
        description_head: str = "",
        description_tail: str = "",
        constraints=None,
    ):
        self.contract = contract
        self.function_name = function_name
        self.address = address
        self.swc_id = swc_id
        self.title = title
        self.bytecode = bytecode
        self.severity = severity
        self.description_head = description_head
        self.description_tail = description_tail
        self.detector = detector
        self.constraints = constraints or []


class PotentialIssuesAnnotation(StateAnnotation):
    def __init__(self):
        self.potential_issues: List[PotentialIssue] = []

    @property
    def search_importance(self) -> int:
        return 10 * len(self.potential_issues)

    def __copy__(self):
        # shared across forks on purpose: issues park once per program point
        return self


def get_potential_issues_annotation(global_state: GlobalState) -> PotentialIssuesAnnotation:
    for annotation in global_state.get_annotations(PotentialIssuesAnnotation):
        return annotation
    annotation = PotentialIssuesAnnotation()
    global_state.annotate(annotation)
    return annotation


def check_potential_issues(global_state: GlobalState) -> None:
    """Called by the engine at outermost transaction end (svm counterpart of
    reference svm.py:423).

    The sat/unsat GATE over all parked issues runs as ONE batched sweep
    first (the sets share the whole path prefix — union model replay and
    merged dispatch resolve most), so the per-issue exploit synthesis
    (model + input minimization) is paid only for the satisfiable ones."""
    from mythril_tpu.support.time_handler import time_handler

    annotation = get_potential_issues_annotation(global_state)
    if time_handler.time_remaining() <= 0:
        # budget exhausted: leave everything parked.  Confirmation solving
        # runs inside harvest/walker replay, which the engine's per-
        # iteration deadline checks cannot interrupt — without this guard a
        # single wide harvest full of terminal paths overran the execution
        # timeout by minutes of session blasting (bectoken: 501s wall on a
        # 120s budget).  Partial-result discipline: issues confirmed before
        # the deadline are already in detector.issues.
        return
    # the detector's (address, bytecode-hash) cache is the reference's
    # dedup discipline (module/base.py:70-95, checked at analyze time);
    # multiple paths park the same program point before the first
    # confirmation lands, so re-check here — each duplicate skipped is a
    # full exploit-synthesis solve saved
    pending: List[PotentialIssue] = []
    for p in annotation.potential_issues:
        key = (p.address, get_bytecode_hash(p.bytecode))
        if key in p.detector.cache:
            continue
        pending.append(p)
    unsolved: List[PotentialIssue] = []
    gate, session, enable_map = _gate_issues(global_state, pending)
    try:
        for idx, (potential_issue, feasible) in enumerate(zip(pending, gate)):
            if time_handler.time_remaining() <= 0:
                # deadline landed mid-sweep: everything not yet confirmed
                # stays parked (same partial-result discipline as the
                # entry guard)
                unsolved.append(potential_issue)
                continue
            if not feasible:
                # an UNKNOWN here degrades exactly like a failed solve
                # below: the issue stays parked, retried at a later tx end
                unsolved.append(potential_issue)
                continue
            key = (
                potential_issue.address,
                get_bytecode_hash(potential_issue.bytecode),
            )
            if key in potential_issue.detector.cache:
                continue  # confirmed earlier in this same sweep
            # confirmation pipelining: gate members answer their exploit
            # synthesis (initial solve + every minimization bound query)
            # under assumptions on the gate's live session — the path
            # condition is blasted ONCE per tx-end sweep, not once per
            # issue (the round-4 double payment; cf. reference
            # analysis/solver.py:51-101, one Optimize per issue)
            gi = enable_map.get(idx) if session is not None else None
            try:
                transaction_sequence = get_transaction_sequence(
                    global_state,
                    global_state.world_state.constraints
                    + potential_issue.constraints,
                    session=session if gi is not None else None,
                    session_enable=(gi,) if gi is not None else (),
                )
            except UnsatError:
                unsolved.append(potential_issue)
                continue
            potential_issue.detector.cache.add(
                (
                    potential_issue.address,
                    get_bytecode_hash(potential_issue.bytecode),
                )
            )
            potential_issue.detector.issues.append(
                Issue(
                    contract=potential_issue.contract,
                    function_name=potential_issue.function_name,
                    address=potential_issue.address,
                    title=potential_issue.title,
                    bytecode=potential_issue.bytecode,
                    swc_id=potential_issue.swc_id,
                    gas_used=(
                        global_state.mstate.min_gas_used,
                        global_state.mstate.max_gas_used,
                    ),
                    description_head=potential_issue.description_head,
                    description_tail=potential_issue.description_tail,
                    severity=potential_issue.severity,
                    transaction_sequence=transaction_sequence,
                )
            )
    finally:
        if session is not None:
            session.close()
    annotation.potential_issues = unsolved


@lru_cache(maxsize=512)
def _code_hash_memo(bytecode) -> str:
    from mythril_tpu.support.support_utils import get_code_hash

    return get_code_hash(bytecode)


def get_bytecode_hash(bytecode) -> str:
    # every tx-end sweep keys each parked issue by this hash; keccak over
    # the full runtime bytecode is far too expensive to recompute per issue
    if bytecode is None:
        return ""
    return _code_hash_memo(
        bytecode if isinstance(bytecode, (str, bytes)) else str(bytecode)
    )


def _gate_issues(global_state: GlobalState, issues: List[PotentialIssue]):
    """sat/unsat gate over all parked issues at FULL solver budget.

    All issues at one transaction end share the whole path prefix, so the
    gate blasts ``path ∪ sanity bounds ∪ all issue constraints`` ONCE into
    an incremental CDCL session with per-issue enable literals and answers
    each issue as a solve-under-assumptions (learned clauses shared).
    Exact UNSATs skip the expensive exploit synthesis; SAT models are
    validated exactly; anything undecidable here (UNKNOWN, unsupported
    structure, wide-mul overflow encodings, no native library) passes
    through True to the full per-issue solve — the gate can only SAVE
    work, never lose recall beyond what the full solve itself would.

    Returns ``(gate, session, enable_map)``: the session is the LIVE
    blasted formula (or None), built with the exploit-synthesis sanity
    bounds in its base and the minimization objectives registered in
    get_transaction_sequence's exact order, so each feasible member's
    confirmation runs on it under assumptions instead of re-blasting.
    The CALLER owns (and must close) the returned session."""
    gate = [True] * len(issues)
    if len(issues) < 2:
        # a lone issue keeps the classic path: its confirmation solve
        # builds (at most) one session itself, and the cheap tiers may
        # answer it with no blast at all
        return gate, None, {}
    from mythril_tpu.native import bitblast
    from mythril_tpu.smt.concrete_eval import evaluate
    from mythril_tpu.smt.solver import SolverStatistics
    from mythril_tpu.support.support_args import args
    from mythril_tpu.support.time_handler import time_handler

    if not bitblast.available():
        return gate, None, {}
    from mythril_tpu.analysis.solver import _set_minimisation_constraints
    from mythril_tpu.core.state.constraints import Constraints

    path_raws = list(global_state.world_state.constraints.get_all_raw())
    # the confirmation solve operates under calldata-size/callvalue sanity
    # bounds and minimizes (calldatasize, callvalue) per transaction
    # (analysis/solver.py) — bake BOTH into the shared session so bound
    # queries are pure assumptions.  Gating under the same sanity bounds is
    # consistent: an issue satisfiable only beyond them would fail its full
    # confirmation solve anyway (which always adds them).
    sanity, minimize = _set_minimisation_constraints(
        global_state.world_state.transaction_sequence,
        Constraints(),
        [],
        5000,
        global_state.world_state,
    )
    sanity_raws = [c.raw if hasattr(c, "raw") else c for c in sanity]
    objective_raws = [m.raw if hasattr(m, "raw") else m for m in minimize]
    path_raws = path_raws + sanity_raws
    issue_raws = [
        [c.raw if hasattr(c, "raw") else c for c in p.constraints]
        for p in issues
    ]
    # one enable-guarded conjunct per issue (land folds multi-term lists)
    from mythril_tpu.smt import terms as T

    # wide-mul overflow encodings included: the session blasts select
    # congruence lazily (bb_extend refinement), so the Dadda 512-bit
    # multiply no longer exceeds the clause budget — SWC-101 confirmations,
    # the most expensive class, now share the gate like everything else.
    # Should the full blast STILL overflow a budget, retry without the
    # wide-mul members rather than losing the gate for every issue.
    def _wide_mul(t) -> bool:
        return any(
            x.op == "bvmul" and T.is_bv_sort(x.sort) and x.width > 256
            for x in T.topo_order([t])
        )

    folded_all = [
        T.land(*raws) if raws else T.boolval(True) for raws in issue_raws
    ]
    attempts = [list(range(len(folded_all)))]
    narrow = [i for i in attempts[0] if not _wide_mul(folded_all[i])]
    if len(narrow) < len(folded_all):
        attempts.append(narrow)
    session = None
    members: List[int] = []
    for candidate_members in attempts:
        if len(candidate_members) < 2:
            return gate, None, {}
        try:
            session = bitblast.OptimizeSession(
                path_raws,
                objectives=objective_raws,
                guarded=[folded_all[i] for i in candidate_members],
            )
            members = candidate_members
            break
        except bitblast.Unsupported:
            continue
    if session is None:
        return gate, None, {}
    guarded = [folded_all[i] for i in members]
    enable_map = {i: gi for gi, i in enumerate(members)}
    try:
        for gi, i in enumerate(members):
            if time_handler.time_remaining() <= 0:
                break  # deadline mid-gate: the rest pass through True
            # the OVERALL analysis deadline is re-read per query: one hard
            # issue must not spend the whole remaining budget N times over
            budget_s = max(0.05, min(
                args.solver_timeout / 1000.0,
                max(time_handler.time_remaining(), 0) / 2,
            ))
            SolverStatistics().cdcl_calls += 1
            status, asg = session.solve([], budget_s, enable=[gi])
            if status == bitblast.UNSAT:
                gate[i] = False
            elif status == bitblast.SAT and asg is not None:
                # exact validation, as for every native SAT model; a valid
                # model is remembered so the full solve's replay tier hits
                conj = path_raws + [guarded[gi]]
                try:
                    vals = evaluate(conj, asg)
                    if all(vals[c] for c in conj):
                        from mythril_tpu.smt.solver import remember_model

                        remember_model(conj, asg)
                except Exception:
                    pass  # full solve decides from scratch
    except Exception:
        session.close()
        raise
    return gate, session, enable_map
