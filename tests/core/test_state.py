"""State-model unit tests (reference parity: tests/laser/state/)."""

import pytest

from mythril_tpu.core.evm_exceptions import StackOverflowException, StackUnderflowException
from mythril_tpu.core.state.calldata import (
    BasicConcreteCalldata,
    BasicSymbolicCalldata,
    ConcreteCalldata,
    SymbolicCalldata,
)
from mythril_tpu.core.state.machine_state import MachineStack, MachineState
from mythril_tpu.core.state.memory import Memory
from mythril_tpu.core.state.world_state import WorldState
from mythril_tpu.smt import symbol_factory
from mythril_tpu.smt.solver import Solver, SAT


def val(v, w=256):
    return symbol_factory.BitVecVal(v, w)


class TestMachineStack:
    def test_overflow(self):
        stack = MachineStack()
        for i in range(1024):
            stack.append(i)
        with pytest.raises(StackOverflowException):
            stack.append(1)

    def test_underflow(self):
        with pytest.raises(StackUnderflowException):
            MachineStack().pop()


class TestMemory:
    def test_word_roundtrip(self):
        mem = Memory()
        mem.write_word_at(val(0), val(0xDEADBEEF))
        assert mem.get_word_at(val(0)).value == 0xDEADBEEF

    def test_byte_level(self):
        mem = Memory()
        mem.write_word_at(val(0), val(0x0102030405060708 << (8 * 24)))
        assert mem.get_byte(val(0)).value == 0x01
        assert mem.get_byte(val(7)).value == 0x08
        assert mem.get_byte(val(31)).value == 0

    def test_symbolic_index(self):
        mem = Memory()
        idx = symbol_factory.BitVecSym("idx", 256)
        mem.set_byte(idx, val(0xAB, 8))
        assert mem.get_byte(idx).value == 0xAB  # same term -> same cell

    def test_copy_isolation(self):
        mem = Memory()
        mem.set_byte(val(0), val(1, 8))
        mem2 = mem.copy()
        mem2.set_byte(val(0), val(2, 8))
        assert mem.get_byte(val(0)).value == 1
        assert mem2.get_byte(val(0)).value == 2


class TestMachineState:
    def test_memory_gas(self):
        ms = MachineState(gas_limit=100000)
        ms.mem_extend(0, 32)
        assert ms.min_gas_used == 3
        ms.mem_extend(0, 32)  # no growth, no charge
        assert ms.min_gas_used == 3
        ms.mem_extend(32, 32)
        assert ms.min_gas_used == 6


class TestCalldata:
    def test_concrete_models_agree(self):
        data = [0xAB, 0x12, 0x58, 0x50]
        for cls in (ConcreteCalldata, BasicConcreteCalldata):
            cd = cls("1", data)
            assert cd[0].value == 0xAB
            assert cd.calldatasize.value == 4
            assert cd.concrete(None) == data
            word = cd.get_word_at(0)
            assert word.value == int.from_bytes(bytes(data) + bytes(28), "big")

    def test_symbolic_calldata_constrainable(self):
        cd = SymbolicCalldata("2")
        s = Solver()
        s.add(cd[0] == symbol_factory.BitVecVal(0xFE, 8))
        s.add(cd.calldatasize == val(4))
        assert s.check() == SAT
        concrete = cd.concrete(s.model())
        assert concrete[0] == 0xFE
        assert len(concrete) == 4

    def test_basic_symbolic_read_tracking(self):
        cd = BasicSymbolicCalldata("3")
        b0 = cd[0]
        s = Solver()
        s.add(b0 == symbol_factory.BitVecVal(0x7F, 8))
        s.add(cd.calldatasize == val(1))
        assert s.check() == SAT
        assert cd.concrete(s.model()) == [0x7F]


class TestWorldState:
    def test_account_auto_create(self):
        ws = WorldState()
        acct = ws[val(0x1234)]
        assert acct.address.value == 0x1234

    def test_balance_transfer_symbolic(self):
        ws = WorldState()
        a = ws.create_account(balance=100, address=0xA)
        b = ws.create_account(balance=0, address=0xB)
        ws.balances[val(0xB)] = ws.balances[val(0xB)] + val(40)
        ws.balances[val(0xA)] = ws.balances[val(0xA)] - val(40)
        s = Solver()
        s.add(ws.balances[val(0xB)] == val(40))
        s.add(ws.balances[val(0xA)] == val(60))
        assert s.check() == SAT

    def test_copy_forks_storage(self):
        import copy

        ws = WorldState()
        acct = ws.create_account(balance=0, address=0xA, concrete_storage=True)
        acct.storage[val(1)] = val(111)
        ws2 = copy.copy(ws)
        ws2.accounts[0xA].storage[val(1)] = val(222)
        assert ws.accounts[0xA].storage[val(1)].value == 111
        assert ws2.accounts[0xA].storage[val(1)].value == 222
