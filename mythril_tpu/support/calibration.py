"""Dispatch-RTT calibration: scale device break-evens to the actual link.

The frontier/probe break-even constants were hand-tuned on a ~100ms-RTT
tunneled TPU (ROADMAP round-3 note): ``device_probe_threshold`` (the
DAG-size x candidates product above which a probe dispatch beats host
evaluation) and the narrow-gate static-JUMPI floor both encode that link
latency.  On an untunneled chip the round trip is ~50x cheaper and the same
constants under-sell the device; on a slower link they over-dispatch.

This module measures the real dispatch round trip ONCE (tiny jitted add,
median of three timed runs after a warmup) the first time a device decision
is taken, and rescales the defaults linearly in RTT around the tuned
anchor.  User-overridden values are left alone.  The measurement is
reported in the jsonv2 meta (``mythril_execution_info.calibration``).
"""

from __future__ import annotations

import logging
import os
import time
from typing import Dict, Optional

log = logging.getLogger(__name__)

# the link the hand-tuned constants were measured on
_ANCHOR_RTT_MS = 100.0
_ANCHOR_PROBE_THRESHOLD = 600_000
_ANCHOR_MIN_STATIC_JUMPIS = 8
# observed-width admission gate at the anchor link: 24 live paths was the
# empirical floor below which segment fixed costs beat the host on the
# ~100ms tunnel (round-5 width study: the 0.3-0.7x rows peak at width
# 5-12, the winning rows at 40+); on a local-RTT chip this scales down to
# the engine default of 8
_ANCHOR_MIN_SEED_WIDTH = 24

_state: Dict = {"done": False, "rtt_ms": None, "applied": {}}


def measure_dispatch_rtt_ms() -> Optional[float]:
    """Median round trip of a tiny device dispatch, in milliseconds.

    Returns None when no accelerator platform is configured (never
    initializes a backend just to measure it)."""
    platforms = os.environ.get("JAX_PLATFORMS", "")
    if not platforms.startswith(("tpu", "axon")):
        return None
    try:
        import jax
        import numpy as np

        f = jax.jit(lambda x: x + 1)

        def roundtrip():
            # a FRESH host upload and a FORCED host readback: on tunneled
            # backends, block_until_ready() alone completes on the local
            # async completion signal (~0.05 ms measured against a ~120 ms
            # link) and would mis-scale every break-even ~50x toward
            # over-dispatching
            y = f(jax.device_put(np.zeros((8,), np.int32)))
            np.asarray(y)

        roundtrip()  # compile + first-transfer setup outside the timed runs
        samples = []
        for _ in range(3):
            t0 = time.perf_counter()
            roundtrip()
            samples.append((time.perf_counter() - t0) * 1000.0)
        samples.sort()
        return samples[1]
    except Exception as e:  # pragma: no cover - device-env dependent
        log.debug("RTT calibration failed: %s", e)
        return None


def calibrate() -> Dict:
    """Measure once and rescale un-overridden break-evens; idempotent.

    Returns the telemetry dict (empty when calibration did not run)."""
    if _state["done"]:
        return _state["applied"]
    _state["done"] = True
    rtt = measure_dispatch_rtt_ms()
    _state["rtt_ms"] = rtt
    if rtt is None:
        return {}
    from mythril_tpu.frontier import engine as frontier_engine
    from mythril_tpu.support.support_args import args

    scale = rtt / _ANCHOR_RTT_MS
    applied: Dict = {"dispatch_rtt_ms": round(rtt, 2)}
    if args.device_probe_threshold == _ANCHOR_PROBE_THRESHOLD:
        new_threshold = int(
            min(5_000_000, max(20_000, _ANCHOR_PROBE_THRESHOLD * scale))
        )
        args.device_probe_threshold = new_threshold
        applied["device_probe_threshold"] = new_threshold
    if frontier_engine._MIN_STATIC_JUMPIS == _ANCHOR_MIN_STATIC_JUMPIS:
        new_jumpis = int(min(16, max(2, round(_ANCHOR_MIN_STATIC_JUMPIS * scale))))
        frontier_engine._MIN_STATIC_JUMPIS = new_jumpis
        applied["min_static_jumpis"] = new_jumpis
    if frontier_engine._MIN_SEED_WIDTH == 8:  # engine default, un-overridden
        new_width = int(min(64, max(8, round(_ANCHOR_MIN_SEED_WIDTH * scale))))
        frontier_engine._MIN_SEED_WIDTH = new_width
        applied["min_seed_width"] = new_width
    _state["applied"] = applied
    log.info("device calibration: %s", applied)
    return applied


def telemetry() -> Dict:
    """Calibration info for report meta (without forcing a measurement).

    Empty both when calibration never ran AND when it ran without an
    accelerator (rtt None) — a ``{"dispatch_rtt_ms": null}`` block would be
    noise every consumer has to null-check."""
    if not _state["done"] or _state["rtt_ms"] is None:
        return {}
    out = {"dispatch_rtt_ms": _state["rtt_ms"]}
    out.update({k: v for k, v in _state["applied"].items() if k != "dispatch_rtt_ms"})
    return out
