"""JSON export of the static pass (--staticpass-report).

Blocks and edges are serialized through the same ``core/cfg.py``
Node/Edge structures the dynamic engine uses, so downstream tooling
consumes one CFG schema for both.
"""

from __future__ import annotations

import json
from typing import List

from mythril_tpu.core.cfg import Edge, JumpType, Node
from mythril_tpu.staticpass.summary import StaticSummary

# unresolved-jump fans (edges to every JUMPDEST) can be quadratic; the
# JSON export caps them and says so rather than ballooning the artifact
_MAX_EDGES = 4096

_EDGE_TYPE = {
    "jump": JumpType.UNCONDITIONAL,
    "fall": JumpType.CONDITIONAL,
    "dyn": JumpType.UNCONDITIONAL,
}

_VIEWS: List = []  # GateView per analyzed contract, in analysis order


def record_view(view) -> None:
    _VIEWS.append(view)


def reset_views() -> None:
    del _VIEWS[:]


def summary_to_dict(summary: StaticSummary) -> dict:
    from mythril_tpu.frontier import taint

    nodes = []
    for b in range(summary.n_blocks):
        node = Node(
            contract_name="static",
            start_addr=int(summary.block_addrs[b]),
            function_name=f"block_{b}",
        )
        d = node.get_dict()
        d["reachable"] = bool(summary.instr_reachable[summary.block_starts[b]])
        nodes.append(d)
    edges = []
    for frm, to, kind in summary.edges[:_MAX_EDGES]:
        e = Edge(frm, to, edge_type=_EDGE_TYPE.get(kind, JumpType.UNCONDITIONAL))
        d = e.as_dict()
        d["kind"] = kind
        edges.append(d)
    bit_names = {bit: name for bit, name in taint.SOURCE_OPCODES.items()}
    return {
        "is_creation": summary.is_creation,
        "code_size": summary.code_size,
        "instructions": summary.n_instructions,
        "blocks": summary.n_blocks,
        "reachable_blocks": summary.n_reachable_blocks,
        "jumps_resolved": summary.n_resolved_jumps,
        "underflow_blocks": summary.underflow_blocks,
        "unreachable_bytes": summary.unreachable_bytes,
        "unreachable_spans": [list(s) for s in summary.unreachable_spans],
        "nodes": nodes,
        "edges": edges,
        "edges_truncated": len(summary.edges) > _MAX_EDGES,
        "may_reach": {
            f"{bit_names.get(bit, bit)}": sorted(ops)
            for bit, ops in sorted(summary.may_reach.items())
        },
        "escalated_sources": sorted(
            bit_names.get(bit, str(bit)) for bit in summary.escalated_bits
        ),
        "wall_s": round(summary.wall_s, 6),
    }


def report_dict() -> dict:
    """Everything recorded since process start, one entry per contract."""
    return {
        "contracts": [
            {
                "name": view.contract_name,
                "modules_skipped": view.skipped_modules,
                "codes": [summary_to_dict(s) for s in view.summaries],
            }
            for view in _VIEWS
        ]
    }


def export_report(path: str) -> None:
    with open(path, "w") as f:
        json.dump(report_dict(), f, indent=2, sort_keys=True)
