"""The struct-of-arrays frontier state: B paths as dense tensors.

This is the device-resident replacement for the host work list of
``GlobalState`` objects (SURVEY.md §7.1; reference mythril/laser/ethereum/
svm.py:67 ``work_list``).  Every per-path field is a fixed-capacity array so
the whole batch is one XLA-friendly pytree; stack words, memory words and
storage entries hold *arena row indices* (see arena.py), never Python
objects.  The host keeps a numpy mirror between device segments: uploads at
segment start, downloads at harvest.

Caps overflow never loses a path: any overflow parks the path (H_PARK) and
the host engine continues it from the reconstructed state.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import NamedTuple

import numpy as np

from mythril_tpu.frontier import ops as O
from mythril_tpu.frontier.code import CTX_W


@dataclass(frozen=True)
class Caps:
    B: int = 64  # frontier width (paths)
    STK: int = 48  # stack slots tracked (EVM limit is 1024; overflow parks)
    MEM: int = 48  # word-granular memory entries
    STO: int = 32  # storage assoc entries (concrete-fold cache)
    CON: int = 96  # device-added path constraints
    EVT: int = 576  # events per path PER SEGMENT (buffers are drained at
    # every harvest and rebuilt empty; solc code is MSTORE/JUMPI-dense and
    # every one is an event; mid-instruction overflow parks the path, a
    # fork-site overflow just pends until the next segment).  Sized ~1.5x K
    # so a long segment cannot starve an event-dense path.
    R: int = 4  # arena rows reserved per path per step
    K: int = 384  # max steps per device segment: over a tunneled link every
    # harvest costs a full round trip, so segments run as long as the event
    # buffers allow (the while_loop still exits early when all paths halt)
    ARENA: int = 1 << 17
    # adaptive bail-out: if fewer than MIN_LIVE paths stay live for
    # NARROW_BAIL consecutive harvests, park everything to the host engine
    # (device segments only pay off when the batch is wide)
    MIN_LIVE: int = 8
    NARROW_BAIL: int = 3


class FrontierState(NamedTuple):
    """One leading [B] dim on everything; see Caps for trailing dims."""

    pc: np.ndarray  # [B] i32 instruction index
    halt: np.ndarray  # [B] i32 ops.H_*; free slots marked by seed < 0
    seed: np.ndarray  # [B] i32 seed index, -1 = free slot
    code_id: np.ndarray  # [B] i32 index into the stacked CodeDev tables —
    # paths from DIFFERENT contracts share one segment (multi-code batching)
    steps: np.ndarray  # [B] i32 instructions this path executed on device
    # (per-laser total_states attribution; reset on fork-copy)
    score: np.ndarray  # [B] i32 beam importance (sum of the seed's
    # annotation search_importance; inherited on fork — annotations are
    # SHARED across forks, potential_issues.py __copy__): the SEL_BEAM
    # fork-grant ranks by it under slot scarcity
    stack: np.ndarray  # [B, STK] i32 arena rows
    stack_len: np.ndarray  # [B] i32
    mem_addr: np.ndarray  # [B, MEM] i32 byte address, -1 = empty
    mem_val: np.ndarray  # [B, MEM] i32 arena rows
    mem_len: np.ndarray  # [B] i32
    mem_size: np.ndarray  # [B] i32 ceil32 active memory size (msize/gas)
    sto_key: np.ndarray  # [B, STO] i32 arena rows
    sto_val: np.ndarray  # [B, STO] i32 arena rows
    sto_len: np.ndarray  # [B] i32
    ctx: np.ndarray  # [B, CTX_W] i32 env/context arena rows
    cons: np.ndarray  # [B, CON] i32 bool arena rows
    cons_len: np.ndarray  # [B] i32
    events: np.ndarray  # [B, EVT, EV_W] i32
    ev_len: np.ndarray  # [B] i32
    gas_min: np.ndarray  # [B] i32
    gas_max: np.ndarray  # [B] i32
    depth: np.ndarray  # [B] i32 control-flow transfers (max_depth cap)
    loops: np.ndarray  # [B, n_loops] i32 per-JUMPDEST visit counts
    static: np.ndarray  # [B] i32 STATICCALL write protection: state-mutating
    # ops (SSTORE/LOG/SELFDESTRUCT) halt the path as a terminal whose replay
    # raises the host WriteProtection (instructions.py StateTransition)


def empty_state(caps: Caps, n_loops: int) -> FrontierState:
    B = caps.B
    return FrontierState(
        pc=np.zeros(B, np.int32),
        halt=np.full(B, O.H_STOP, np.int32),
        seed=np.full(B, -1, np.int32),
        code_id=np.zeros(B, np.int32),
        steps=np.zeros(B, np.int32),
        score=np.zeros(B, np.int32),
        stack=np.full((B, caps.STK), -1, np.int32),
        stack_len=np.zeros(B, np.int32),
        mem_addr=np.full((B, caps.MEM), -1, np.int32),
        mem_val=np.full((B, caps.MEM), -1, np.int32),
        mem_len=np.zeros(B, np.int32),
        mem_size=np.zeros(B, np.int32),
        sto_key=np.full((B, caps.STO), -1, np.int32),
        sto_val=np.full((B, caps.STO), -1, np.int32),
        sto_len=np.zeros(B, np.int32),
        ctx=np.full((B, CTX_W), -1, np.int32),
        cons=np.full((B, caps.CON), -1, np.int32),
        cons_len=np.zeros(B, np.int32),
        events=np.full((B, caps.EVT, O.EV_W), -1, np.int32),
        ev_len=np.zeros(B, np.int32),
        gas_min=np.zeros(B, np.int32),
        gas_max=np.zeros(B, np.int32),
        depth=np.zeros(B, np.int32),
        loops=np.zeros((B, n_loops), np.int32),
        static=np.zeros(B, np.int32),
    )


def clear_slot(st: FrontierState, i: int) -> None:
    """Host-side: free slot ``i`` in the numpy mirror (after harvest)."""
    st.seed[i] = -1
    st.halt[i] = O.H_STOP
    st.code_id[i] = 0
    st.steps[i] = 0
    st.score[i] = 0
    st.stack_len[i] = 0
    st.stack[i] = -1
    st.mem_len[i] = 0
    st.mem_addr[i] = -1
    st.mem_val[i] = -1
    st.mem_size[i] = 0
    st.sto_len[i] = 0
    st.sto_key[i] = -1
    st.sto_val[i] = -1
    st.cons_len[i] = 0
    st.cons[i] = -1
    st.ev_len[i] = 0
    st.events[i] = -1
    st.gas_min[i] = 0
    st.gas_max[i] = 0
    st.depth[i] = 0
    st.loops[i] = 0
    st.pc[i] = 0
    st.static[i] = 0
