"""Exploration ledger: analysis-quality observability for the frontier.

The operational plane (request telemetry, fleet fabric) says how fast the
service runs; this module says how *well* it explored.  Three channels,
one process-wide ledger:

* **Coverage** — per-contract instruction and JUMPI branch-edge bitmaps.
  The device frontier marks a three-plane ``[3, C, I]`` bool array per
  step (plane 0 = instruction executed, plane 1 = taken edge, plane 2 =
  fall-through edge); ``engine._merge_coverage`` folds the host readback
  into this ledger, and the host-side :class:`InstructionCoverage` plugin
  contributes the pcs the walker/host engine executed.  Edge coverage is
  quoted against ``2 * |JUMPI|`` resolvable edges per contract.

* **Termination attribution** — every path that stops exploring is
  stamped with exactly ONE of :data:`TERM_CLASSES`.  ``stamp`` increments
  the per-class labeled counter and the total counter together, so the
  partition invariant (sum over classes == total terminated) holds by
  construction and is asserted in tests, bench rows, and the CI smoke.

* **Solver hotspots** — feasibility-solve wall time attributed to the
  program point (codehash-tagged pc) whose query burned the budget, as a
  pair of labeled series (``solver_hotspot_s`` / ``solver_hotspot_n``)
  that render as a labeled histogram in Prometheus exposition.

Everything lands in the metrics registry under ``exploration.*`` so the
PR-13 fleet publisher exports worker-labeled ``fleet_exploration_*``
series with no extra wiring; bitmaps (not registry-shaped) live on the
ledger itself and reset with the analysis scope.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Iterable, List, Optional

import numpy as np

__all__ = [
    "TERM_CLASSES",
    "VERDICT_CLASS",
    "ExplorationLedger",
    "exploration_meta",
    "get_exploration_ledger",
]

#: The termination taxonomy.  Exactly one class per terminated path:
#:   completed      — ran to a terminal halt (STOP/RETURN/REVERT/
#:                    SELFDESTRUCT/INVALID) or the host replay ended it
#:   prefilter_killed — the abstract interval/known-bits pass proved the
#:                    path condition UNSAT before any exact solve
#:   solver_unsat   — an exact solver verdict killed the path
#:   solver_timeout_unknown — the solver answered UNKNOWN at budget and
#:                    the engine's unknown-as-unsat policy pruned it
#:   staticpass_pruned — a plugin/static gate (PluginSkipState) dropped
#:                    the path pre-execution, subtree included
#:   loop_bound     — the device loop detector hit --loop-bound
#:   budget_exhausted — max-depth halt or the execution timeout parked
#:                    the path with no host budget left to resume it
#:   shed           — the service admission plane refused the request
TERM_CLASSES = (
    "completed",
    "prefilter_killed",
    "solver_unsat",
    "solver_timeout_unknown",
    "staticpass_pruned",
    "loop_bound",
    "budget_exhausted",
    "shed",
)

#: Solver batch statuses (check_satisfiable_batch ``statuses_out``) to
#: termination classes, for kill attribution at the prune/verdict points.
#: Every status a tier can emit MUST be mapped here explicitly — the
#: lookup sites default to "solver_unsat", so a missing entry silently
#: misattributes terminations (tests/devsolver/test_integration.py keeps
#: this table in sync with the statuses solver.py can emit).
VERDICT_CLASS = {
    "unsat": "solver_unsat",
    "unknown": "solver_timeout_unknown",
    "prefilter": "prefilter_killed",
    # the device SAT tier's UNSAT is an exact solver verdict — it differs
    # from "prefilter" (abstraction) in mechanism, not in exactness
    "devsolver": "solver_unsat",
}

# visited-array plane indices (frontier/step.py writes these on device)
PLANE_INSTR = 0
PLANE_EDGE_TAKEN = 1
PLANE_EDGE_FALL = 2
N_PLANES = 3

# labeled-series cardinality guard: distinct program-point labels beyond
# this fold into "other" so a pathological contract cannot balloon the
# registry (or the fleet wire format)
_MAX_HOTSPOT_LABELS = 256


class _CodeCoverage:
    __slots__ = ("total", "jumpis", "instr", "edge_taken", "edge_fall",
                 "reach_instr", "reach_taken", "reach_fall")

    def __init__(self, total: int, jumpis: int):
        self.total = max(int(total), 0)
        self.jumpis = max(int(jumpis), 0)
        n = max(self.total, 1)
        self.instr = np.zeros(n, bool)
        self.edge_taken = np.zeros(n, bool)
        self.edge_fall = np.zeros(n, bool)
        # static reachability masks (the staticpass reachable-edge
        # oracle); None until a summary is registered — then the
        # *_reachable variants fall back to the raw denominators
        self.reach_instr: Optional[np.ndarray] = None
        self.reach_taken: Optional[np.ndarray] = None
        self.reach_fall: Optional[np.ndarray] = None

    def set_static(self, instr_mask, taken_mask, fall_mask) -> None:
        """Install the static reachability masks, aligned to this
        entry's instruction space (truncate/pad as needed)."""
        def fit(mask):
            m = np.zeros(self.instr.shape[0], bool)
            src = np.asarray(mask, bool)
            n = min(m.shape[0], src.shape[0])
            m[:n] = src[:n]
            return m

        self.reach_instr = fit(instr_mask)
        self.reach_taken = fit(taken_mask)
        self.reach_fall = fit(fall_mask)

    def _reach_counts(self):
        """(reachable_instructions, reachable_edges) with the executed
        bits unioned in, so executed ⊆ reachable holds by construction
        and the reachable percentages can never dip below the raw ones
        even if a registered mask is misaligned."""
        if self.reach_instr is None:
            return None, None
        r_instr = int((self.reach_instr | self.instr).sum()) \
            if self.total else 0
        r_edges = int((self.reach_taken | self.edge_taken).sum()) \
            + int((self.reach_fall | self.edge_fall).sum())
        return min(r_instr, self.total), min(r_edges, 2 * self.jumpis)

    def as_dict(self) -> Dict[str, Any]:
        seen = int(self.instr.sum())
        taken = int(self.edge_taken.sum())
        fall = int(self.edge_fall.sum())
        edges_total = 2 * self.jumpis
        instr_pct = round(100.0 * seen / self.total, 2) if self.total else 0.0
        edge_pct = round(100.0 * (taken + fall) / edges_total, 2) \
            if edges_total else None
        r_instr, r_edges = self._reach_counts()
        instr_pct_reach = (
            round(100.0 * seen / r_instr, 2)
            if r_instr else instr_pct
        )
        edge_pct_reach = (
            round(100.0 * (taken + fall) / r_edges, 2)
            if r_edges else edge_pct
        )
        return {
            "instructions_total": self.total,
            "instructions_seen": seen,
            "instructions_reachable": r_instr,
            "instruction_pct": instr_pct,
            "instruction_pct_raw": instr_pct,
            "instruction_pct_reachable": instr_pct_reach,
            "jumpis": self.jumpis,
            "edges_total": edges_total,
            "edges_seen": taken + fall,
            "edges_reachable": r_edges,
            "edge_taken_seen": taken,
            "edge_fall_seen": fall,
            "edge_pct": edge_pct,
            "edge_pct_raw": edge_pct,
            "edge_pct_reachable": edge_pct_reach,
        }


class ExplorationLedger:
    """Process-wide exploration accounting (one per worker process).

    Counter-shaped channels live in the metrics registry (named under
    ``exploration.*`` — scoped like the ``prefilter.*`` counters, swept by
    ``reset_analysis_metrics``); the coverage bitmaps live here and are
    swept by the same scope reset through :func:`reset_scope`.
    """

    def __init__(self, registry=None):
        self._lock = threading.Lock()
        self._codes: Dict[str, _CodeCoverage] = {}
        self._registry = registry

    # -- registry handles ----------------------------------------------

    def _reg(self):
        if self._registry is not None:
            return self._registry
        from mythril_tpu.observability.metrics import get_registry

        return get_registry()

    def _terminated_counter(self):
        return self._reg().labeled_counter(
            "exploration.terminated", label_name="class"
        )

    # -- coverage -------------------------------------------------------

    def _entry(self, code_hash: str, total: int, jumpis: int = -1
               ) -> _CodeCoverage:
        entry = self._codes.get(code_hash)
        if entry is None:
            entry = _CodeCoverage(total, max(jumpis, 0))
            self._codes[code_hash] = entry
        elif jumpis >= 0 and entry.jumpis == 0:
            entry.jumpis = int(jumpis)
        return entry

    def record_device_planes(self, code_hash: str, total: int, jumpis: int,
                             planes: np.ndarray) -> None:
        """Fold a device-harvested ``[3, >=total]`` bool plane stack for
        one contract into the ledger (union; planes are cumulative)."""
        planes = np.asarray(planes, bool)
        with self._lock:
            entry = self._entry(code_hash, total, jumpis)
            n = min(entry.instr.shape[0], planes.shape[1])
            entry.instr[:n] |= planes[PLANE_INSTR, :n]
            entry.edge_taken[:n] |= planes[PLANE_EDGE_TAKEN, :n]
            entry.edge_fall[:n] |= planes[PLANE_EDGE_FALL, :n]
        self._publish_gauge()

    def record_instr(self, code_hash: str, total: int,
                     indices: Iterable[int]) -> None:
        """Fold host-observed instruction indices (the coverage plugin's
        bitmap: walker replay + host-engine stepping) into the ledger.
        Out-of-range indices count into ``exploration.pc_overflow``."""
        overflow = 0
        with self._lock:
            entry = self._entry(code_hash, total)
            limit = entry.instr.shape[0]
            for i in indices:
                i = int(i)
                if 0 <= i < limit:
                    entry.instr[i] = True
                else:
                    overflow += 1
        if overflow:
            self.record_pc_overflow(overflow)
        self._publish_gauge()

    def register_static(self, code_hash: str, instr_mask,
                        taken_mask, fall_mask) -> None:
        """Install the static pass's reachability masks for one code
        (the reachable-edge oracle): `coverage_pct_reachable` quotes
        coverage against the statically reachable denominator instead
        of all decoded instructions (padding, metadata, dead code)."""
        with self._lock:
            entry = self._entry(code_hash, len(np.asarray(instr_mask)))
            entry.set_static(instr_mask, taken_mask, fall_mask)
        self._publish_gauge()

    def record_pc_overflow(self, n: int = 1) -> None:
        """An out-of-range pc was observed (and dropped, not clamped)."""
        self._reg().counter("exploration.pc_overflow").inc(n)

    @property
    def pc_overflow(self) -> int:
        return int(self._reg().counter("exploration.pc_overflow").value)

    def coverage(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            return {h: c.as_dict() for h, c in self._codes.items()}

    def coverage_pct(self, code_hash: Optional[str] = None
                     ) -> Optional[float]:
        """Raw instruction coverage percent (denominator = every decoded
        instruction): one contract, or the aggregate weighted by
        instruction counts when ``code_hash`` is None."""
        with self._lock:
            if code_hash is not None:
                entry = self._codes.get(code_hash)
                if entry is None or not entry.total:
                    return None
                return round(100.0 * int(entry.instr.sum()) / entry.total, 2)
            total = sum(c.total for c in self._codes.values())
            if not total:
                return None
            seen = sum(int(c.instr.sum()) for c in self._codes.values())
            return round(100.0 * seen / total, 2)

    def coverage_pct_reachable(self, code_hash: Optional[str] = None
                               ) -> Optional[float]:
        """Instruction coverage percent over the STATICALLY REACHABLE
        denominator.  Codes with no registered static masks contribute
        their raw denominator, so this is always ≥ `coverage_pct` and
        degrades to it when the static pass is off."""
        with self._lock:
            if code_hash is not None:
                entry = self._codes.get(code_hash)
                if entry is None or not entry.total:
                    return None
                r_instr, _ = entry._reach_counts()
                denom = r_instr if r_instr else entry.total
                return round(100.0 * int(entry.instr.sum()) / denom, 2)
            total = seen = 0
            for c in self._codes.values():
                if not c.total:
                    continue
                r_instr, _ = c._reach_counts()
                total += r_instr if r_instr else c.total
                seen += int(c.instr.sum())
            if not total:
                return None
            return round(100.0 * seen / total, 2)

    def _publish_gauge(self) -> None:
        """Per-codehash instruction coverage as dict-valued gauges —
        ``prometheus_text`` renders dict gauges as labeled samples, so the
        percentages reach Prometheus / ``--metrics-out`` directly.  Both
        denominators are published: raw (all decoded instructions) and
        statically reachable (the staticpass oracle)."""
        with self._lock:
            raw = {}
            reach = {}
            for h, c in self._codes.items():
                if not c.total:
                    continue
                seen = int(c.instr.sum())
                raw[h[:10]] = round(100.0 * seen / c.total, 2)
                r_instr, _ = c._reach_counts()
                denom = r_instr if r_instr else c.total
                reach[h[:10]] = round(100.0 * seen / denom, 2)
        self._reg().gauge("exploration.coverage_pct", default={}).set(raw)
        self._reg().gauge(
            "exploration.coverage_pct_reachable", default={}
        ).set(reach)

    # -- termination attribution ---------------------------------------

    def stamp(self, term_class: str, n: int = 1) -> None:
        """Record ``n`` paths terminating with ``term_class``.  The class
        counter and the total increment together, so the partition
        invariant cannot drift."""
        if term_class not in TERM_CLASSES:
            raise ValueError(f"unknown termination class {term_class!r}")
        self._terminated_counter().inc(term_class, n)
        self._reg().counter("exploration.terminated_total").inc(n)

    def terminated(self) -> Dict[str, int]:
        snap = self._terminated_counter().snapshot()
        return {cls: int(snap.get(cls, 0)) for cls in TERM_CLASSES}

    def terminated_total(self) -> int:
        return int(self._reg().counter("exploration.terminated_total").value)

    # -- solver hotspots -----------------------------------------------

    def record_solver_time(self, label: str, seconds: float) -> None:
        """Attribute feasibility-solve wall time to a program point."""
        if seconds < 0:
            return
        reg = self._reg()
        s = reg.labeled_counter("exploration.solver_hotspot_s",
                                label_name="point")
        if label not in s and len(s) >= _MAX_HOTSPOT_LABELS:
            label = "other"
        s.inc(label, round(float(seconds), 6))
        reg.labeled_counter("exploration.solver_hotspot_n",
                            label_name="point").inc(label)

    def solver_hotspots(self, top: int = 10) -> List[Dict[str, Any]]:
        reg = self._reg()
        secs = reg.labeled_counter("exploration.solver_hotspot_s",
                                   label_name="point").snapshot()
        counts = reg.labeled_counter("exploration.solver_hotspot_n",
                                     label_name="point").snapshot()
        ranked = sorted(secs.items(), key=lambda kv: -kv[1])[:max(top, 0)]
        return [
            {
                "point": label,
                "solver_s": round(float(sec), 4),
                "queries": int(counts.get(label, 0)),
            }
            for label, sec in ranked
        ]

    # -- snapshots ------------------------------------------------------

    def meta(self) -> Dict[str, Any]:
        """The ``meta.exploration`` block for jsonv2 reports and bench."""
        terminated = self.terminated()
        total = self.terminated_total()
        return {
            "coverage_pct": self.coverage_pct(),
            "coverage_pct_raw": self.coverage_pct(),
            "coverage_pct_reachable": self.coverage_pct_reachable(),
            "coverage": self.coverage(),
            "terminated": terminated,
            "terminated_total": total,
            "partition_ok": sum(terminated.values()) == total,
            "solver_hotspots": self.solver_hotspots(),
            "pc_overflow": int(
                self._reg().counter("exploration.pc_overflow").value
            ),
        }

    def snapshot(self) -> Dict[str, Any]:
        """The ``--coverage-out`` artifact: meta plus raw bitmaps (as
        index lists, JSON-serializable)."""
        out = self.meta()
        with self._lock:
            out["bitmaps"] = {
                h: {
                    "instr": np.flatnonzero(c.instr).tolist(),
                    "edge_taken": np.flatnonzero(c.edge_taken).tolist(),
                    "edge_fall": np.flatnonzero(c.edge_fall).tolist(),
                }
                for h, c in self._codes.items()
            }
        return out

    def bitmaps(self) -> Dict[str, Dict[str, Any]]:
        """Per-codehash coverage/reachability arrays, COPIED out under the
        lock — the adaptive planner's raw input.  Each entry carries the
        executed planes, the static reachability masks (or None when no
        summary was registered), and the denominators; callers own the
        copies and may mutate them freely."""
        with self._lock:
            return {
                h: {
                    "total": c.total,
                    "jumpis": c.jumpis,
                    "instr": c.instr.copy(),
                    "edge_taken": c.edge_taken.copy(),
                    "edge_fall": c.edge_fall.copy(),
                    "reach_instr": None if c.reach_instr is None
                    else c.reach_instr.copy(),
                    "reach_taken": None if c.reach_taken is None
                    else c.reach_taken.copy(),
                    "reach_fall": None if c.reach_fall is None
                    else c.reach_fall.copy(),
                }
                for h, c in self._codes.items()
            }

    def reset_scope(self) -> None:
        """Per-analysis sweep (the registry counters reset separately via
        ``reset_analysis_metrics``; this clears the bitmap side)."""
        with self._lock:
            self._codes.clear()


_ledger: Optional[ExplorationLedger] = None
_ledger_lock = threading.Lock()


def get_exploration_ledger() -> ExplorationLedger:
    global _ledger
    if _ledger is None:
        with _ledger_lock:
            if _ledger is None:
                _ledger = ExplorationLedger()
    return _ledger


def exploration_meta() -> Dict[str, Any]:
    """Module-level accessor mirroring ``observability_meta()``."""
    return get_exploration_ledger().meta()
