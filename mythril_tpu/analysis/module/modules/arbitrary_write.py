"""ArbitraryStorage: write to an attacker-controlled storage slot (SWC-124).

Reference parity: mythril/analysis/module/modules/arbitrary_write.py:1-78.
"""

from __future__ import annotations

from mythril_tpu.analysis.module.base import DetectionModule, EntryPoint
from mythril_tpu.analysis.potential_issues import (
    PotentialIssue,
    get_potential_issues_annotation,
)
from mythril_tpu.analysis.swc_data import WRITE_TO_ARBITRARY_STORAGE
from mythril_tpu.core.state.global_state import GlobalState
from mythril_tpu.smt import symbol_factory

DESCRIPTION = """
Search for any writes to an arbitrary storage slot.
"""


class ArbitraryStorage(DetectionModule):
    name = "Caller can write to arbitrary storage locations"
    swc_id = WRITE_TO_ARBITRARY_STORAGE
    description = DESCRIPTION
    entry_point = EntryPoint.CALLBACK
    pre_hooks = ["SSTORE"]
    # staticpass: a write-to-arbitrary-slot issue needs an SSTORE
    static_required_ops = frozenset({"SSTORE"})

    def _execute(self, state: GlobalState) -> None:
        if self._cache_key(state) in self.cache:
            return None
        self._analyze_state(state)
        return None

    def _analyze_state(self, state: GlobalState) -> None:
        write_slot = state.mstate.stack[-1]
        if write_slot.value is not None:
            return
        # can the slot index be forced to an arbitrary magic value?
        constraints = [
            write_slot == symbol_factory.BitVecVal(324345425435, 256)
        ]
        potential_issue = PotentialIssue(
            contract=state.environment.active_account.contract_name,
            function_name=state.node.function_name if state.node else "unknown",
            address=state.get_current_instruction()["address"],
            swc_id=WRITE_TO_ARBITRARY_STORAGE,
            title="Write to an arbitrary storage location",
            severity="High",
            bytecode=state.environment.code.bytecode,
            description_head="The caller can write to arbitrary storage locations.",
            description_tail=(
                "It is possible to write to arbitrary storage locations. By "
                "modifying the values of storage variables, attackers may bypass "
                "security controls or manipulate the business logic of the smart "
                "contract."
            ),
            detector=self,
            constraints=constraints,
        )
        get_potential_issues_annotation(state).potential_issues.append(potential_issue)


detector = ArbitraryStorage
