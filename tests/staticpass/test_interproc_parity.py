"""Interprocedural refinement over-approximation contract: issue sets
are bit-identical with the interproc layer on and off (the base static
pass stays enabled in both runs), and the reachable coverage
denominator never reports below the raw one."""

import bench
from mythril_tpu.frontend.evmcontract import EVMContract
from mythril_tpu.observability import get_registry
from mythril_tpu.observability.exploration import get_exploration_ledger
from mythril_tpu.staticpass import clear_cache, reset_views
from mythril_tpu.support.support_args import args


def _run(interproc_on: bool):
    prev = (args.staticpass, args.staticpass_interproc)
    args.staticpass = True
    args.staticpass_interproc = interproc_on
    try:
        bench._clear_caches()
        clear_cache()
        reset_views()
        get_registry().reset(prefix="staticpass.")
        contract = EVMContract(
            code=bench.KILLBILLY,
            creation_code=bench.KILLBILLY_CREATION,
            name="KillBilly",
        )
        _, issues = bench._analyze(
            contract, 0x0901D12E, 2, modules=None, timeout=300
        )
        return sorted((i.swc_id, i.address, i.title) for i in issues)
    finally:
        args.staticpass, args.staticpass_interproc = prev


def test_issue_sets_identical_and_coverage_monotone():
    on_issues = _run(True)
    # with interproc on, every ledger entry must satisfy the defensive
    # guarantee: reachable coverage >= raw coverage
    for code_hash, d in get_exploration_ledger().coverage().items():
        assert d["instruction_pct_reachable"] >= d["instruction_pct_raw"], code_hash
    off_issues = _run(False)
    assert on_issues == off_issues
    # the recall issue itself must be present in both
    assert any(swc == "106" for swc, _, _ in on_issues)
