"""Dispatch-RTT calibration: scaling math, idempotence, override respect."""

import mythril_tpu.support.calibration as cal
from mythril_tpu.frontier import engine as frontier_engine
from mythril_tpu.support.support_args import args


def _fresh_state():
    cal._state.clear()
    cal._state.update({"done": False, "rtt_ms": None, "applied": {}})


def _with_rtt(monkeypatch, rtt):
    monkeypatch.setattr(cal, "measure_dispatch_rtt_ms", lambda: rtt)


def test_no_platform_is_noop(monkeypatch):
    _fresh_state()
    _with_rtt(monkeypatch, None)
    assert cal.calibrate() == {}
    assert cal.telemetry() == {}


def test_fast_link_lowers_breakevens(monkeypatch):
    _fresh_state()
    _with_rtt(monkeypatch, 2.0)  # local chip: ~2ms round trip
    old_thresh = args.device_probe_threshold
    old_jumpis = frontier_engine._MIN_STATIC_JUMPIS
    old_width = frontier_engine._MIN_SEED_WIDTH
    try:
        applied = cal.calibrate()
        assert applied["dispatch_rtt_ms"] == 2.0
        # 600k * (2/100) = 12k, floored at 20k
        assert applied["device_probe_threshold"] == 20_000
        assert applied["min_static_jumpis"] == 2
        assert args.device_probe_threshold == 20_000
        assert frontier_engine._MIN_STATIC_JUMPIS == 2
        # 24 * (2/100) rounds to 0, floored at the engine default of 8
        assert frontier_engine._MIN_SEED_WIDTH == 8
    finally:
        args.device_probe_threshold = old_thresh
        frontier_engine._MIN_STATIC_JUMPIS = old_jumpis
        frontier_engine._MIN_SEED_WIDTH = old_width
        _fresh_state()


def test_anchor_link_keeps_defaults(monkeypatch):
    _fresh_state()
    _with_rtt(monkeypatch, 100.0)
    old_thresh = args.device_probe_threshold
    old_jumpis = frontier_engine._MIN_STATIC_JUMPIS
    old_width = frontier_engine._MIN_SEED_WIDTH
    try:
        applied = cal.calibrate()
        assert applied.get("device_probe_threshold") == 600_000
        assert applied.get("min_static_jumpis") == 8
        assert applied.get("min_seed_width") == 24
    finally:
        args.device_probe_threshold = old_thresh
        frontier_engine._MIN_STATIC_JUMPIS = old_jumpis
        frontier_engine._MIN_SEED_WIDTH = old_width
        _fresh_state()


def test_user_override_untouched(monkeypatch):
    _fresh_state()
    _with_rtt(monkeypatch, 2.0)
    old_thresh = args.device_probe_threshold
    old_jumpis = frontier_engine._MIN_STATIC_JUMPIS
    old_width = frontier_engine._MIN_SEED_WIDTH
    args.device_probe_threshold = 123_456  # user-set: must not be rescaled
    try:
        applied = cal.calibrate()
        assert "device_probe_threshold" not in applied
        assert args.device_probe_threshold == 123_456
    finally:
        args.device_probe_threshold = old_thresh
        frontier_engine._MIN_STATIC_JUMPIS = old_jumpis
        frontier_engine._MIN_SEED_WIDTH = old_width
        _fresh_state()


def test_idempotent(monkeypatch):
    _fresh_state()
    calls = []

    def fake():
        calls.append(1)
        return 50.0

    monkeypatch.setattr(cal, "measure_dispatch_rtt_ms", fake)
    old_thresh = args.device_probe_threshold
    old_jumpis = frontier_engine._MIN_STATIC_JUMPIS
    old_width = frontier_engine._MIN_SEED_WIDTH
    try:
        first = cal.calibrate()
        second = cal.calibrate()
        assert first == second
        assert len(calls) == 1
    finally:
        args.device_probe_threshold = old_thresh
        frontier_engine._MIN_STATIC_JUMPIS = old_jumpis
        frontier_engine._MIN_SEED_WIDTH = old_width
        _fresh_state()
