"""Renaming-invariant canonicalization of constraint sets.

Extends the node encoding of :mod:`mythril_tpu.smt.serialize` with a
variable-anonymized form so that two queries differing only in the NAMES of
their free symbols hash identically.  The engine re-derives the same
structural constraints under fresh symbol names on every run (``caller_2``,
``calldata_KillBilly_3`` ... carry per-run instance counters), so a plain
content hash of the serialized DAG would never hit across runs.

The canonical form of a conjunct set:

1.  Per conjunct, serialize the DAG in deterministic traversal order with
    ``var``/``array_var`` aux (the name) blanked but sorts kept — the
    *shape*.  The variable leaves encountered during that traversal are
    recorded in order (the *occurrence list*).
2.  Sort the conjuncts by shape digest (stable, so same-shape conjuncts
    keep their input order).
3.  Scan the sorted occurrence lists and assign each distinct variable a
    canonical index at first occurrence.  The query encoding is the sorted
    list of ``(shape, occurrence-index-pattern)`` pairs; its sha256 is the
    query hash.

The encoding is a complete invariant: the term set is reconstructible from
it up to variable names, so hash equality implies alpha-equivalence and a
cached UNSAT verdict transfers soundly.  SAT models are stored keyed by
canonical index and re-validated against the new query before being served,
so exactness never rests on the hash alone.

Per-conjunct *named* digests (shape + the actual variable names) are also
produced: the unsat-core subsumption tier must key cores by those, because
a core's meaning depends on WHICH variables its conjuncts share — renaming
each conjunct independently would conflate ``{x>5, x<3}`` (unsat) with
``{x>5, y<3}`` (sat).
"""

from __future__ import annotations

import hashlib
import json
from typing import Dict, List, Optional, Sequence, Tuple

from mythril_tpu.smt import terms
from mythril_tpu.smt.concrete_eval import ArrayValue, Assignment
from mythril_tpu.smt.serialize import _encode_aux, _encode_sort
from mythril_tpu.smt.terms import Term

# per-conjunct fingerprints keyed by interned term id.  Bounded; cleared via
# clear_memos() whenever the solver's term-referencing caches are cleared,
# so a hypothetical intern-table reset can never serve a stale tid mapping.
_FP_MEMO: Dict[int, Tuple[str, Tuple[Term, ...], str]] = {}
_FP_MEMO_CAP = 65536


def digest(blob: str) -> str:
    return hashlib.sha256(blob.encode()).hexdigest()


def clear_memos() -> None:
    _FP_MEMO.clear()


def conjunct_fingerprint(t: Term) -> Tuple[str, Tuple[Term, ...], str]:
    """``(shape, occurrences, named)`` for one conjunct.

    ``shape``: digest of the DAG with variable names anonymized.
    ``occurrences``: the variable leaves in serialization order (shared
    leaves appear once, at their first-visit position — the DAG dedup is
    part of the shape, so ``x+x`` and ``x+y`` differ structurally).
    ``named``: digest additionally committing to the actual names, the key
    the core-subsumption tier matches on.
    """
    hit = _FP_MEMO.get(t.tid)
    if hit is not None:
        return hit
    order = terms.topo_order([t])
    index = {n.tid: i for i, n in enumerate(order)}
    nodes = []
    occurrences: List[Term] = []
    for n in order:
        if n.op in ("var", "array_var"):
            occurrences.append(n)
            aux = None  # identity is restored by the query-level numbering
        else:
            aux = _encode_aux(n.aux)
        nodes.append(
            [n.op, _encode_sort(n.sort), aux, [index[a.tid] for a in n.args]]
        )
    shape = digest(json.dumps(nodes, separators=(",", ":")))
    named = digest(shape + "|" + json.dumps([v.aux for v in occurrences]))
    if len(_FP_MEMO) >= _FP_MEMO_CAP:
        _FP_MEMO.clear()
    out = (shape, tuple(occurrences), named)
    _FP_MEMO[t.tid] = out
    return out


class QueryFingerprint:
    """Canonical identity of one conjunct set.

    ``qhash``: renaming-invariant content hash of the whole set.
    ``var_order``: THIS query's variable terms by canonical index — the
    mapping a cached model's canonical-index values are rebuilt through.
    ``conj_hashes``: the name-preserving per-conjunct digests, the set the
    core-subsumption tier tests cached cores against.
    """

    __slots__ = ("qhash", "var_order", "conj_hashes")

    def __init__(self, qhash: str, var_order: Tuple[Term, ...],
                 conj_hashes: frozenset):
        self.qhash = qhash
        self.var_order = var_order
        self.conj_hashes = conj_hashes


def fingerprint(conjuncts: Sequence[Term]) -> QueryFingerprint:
    fps = [conjunct_fingerprint(c) for c in conjuncts]
    order = sorted(range(len(conjuncts)), key=lambda i: fps[i][0])
    var_index: Dict[int, int] = {}
    var_order: List[Term] = []
    enc = []
    for i in order:
        shape, occurrences, _named = fps[i]
        pattern = []
        for v in occurrences:
            j = var_index.get(v.tid)
            if j is None:
                j = len(var_order)
                var_index[v.tid] = j
                var_order.append(v)
            pattern.append(j)
        enc.append([shape, pattern])
    qhash = digest(json.dumps(enc, separators=(",", ":")))
    return QueryFingerprint(
        qhash, tuple(var_order), frozenset(f[2] for f in fps)
    )


# ---------------------------------------------------------------------------
# Model (de)serialization.  Entries carry BOTH keys per variable: the
# canonical index (exact-hit rebuild onto an alpha-renamed query) and the
# (name, sort) pair (cross-query model-reuse probing).
# ---------------------------------------------------------------------------


def _sort_key(enc):
    return tuple(enc) if isinstance(enc, list) else enc


def dump_model(asg: Assignment, var_index: Dict[int, int]) -> Optional[dict]:
    """JSON-able form of a validated model; None when it cannot be cached
    faithfully (uninterpreted-function entries have no stable cross-run
    key).  Variables outside ``var_index`` are dropped — recycled models
    carry assignments for unrelated queries' symbols, which cannot affect
    this query's evaluation."""
    if asg.ufs:
        return None
    scalars = []
    for t, v in asg.scalars.items():
        ci = var_index.get(t.tid)
        if ci is None:
            continue
        scalars.append(
            [ci, t.aux, _encode_sort(t.sort),
             bool(v) if t.sort is terms.BOOL else int(v)]
        )
    arrays = []
    for t, av in asg.arrays.items():
        ci = var_index.get(t.tid)
        if ci is None:
            continue
        arrays.append(
            [ci, t.aux, _encode_sort(t.sort), {
                "backing": {str(k): int(v) for k, v in av.backing.items()},
                "default": int(av.default),
                "salt": int(av.salt),
                "range_bits": int(av.range_bits),
            }]
        )
    return {"scalars": scalars, "arrays": arrays}


def _load_array(data: dict) -> ArrayValue:
    return ArrayValue(
        {int(k): int(v) for k, v in data.get("backing", {}).items()},
        int(data.get("default", 0)),
        int(data.get("salt", 0)),
        int(data.get("range_bits", 0)),
    )


def load_model(data: dict, var_order: Sequence[Term]) -> Optional[Assignment]:
    """Rebuild a cached model onto ``var_order`` (canonical index -> this
    query's variable).  None on any index/sort mismatch — the caller then
    treats the entry as a miss."""
    scalars: Dict[Term, object] = {}
    arrays: Dict[Term, ArrayValue] = {}
    try:
        for ci, _name, sort_enc, v in data.get("scalars", ()):
            if ci >= len(var_order):
                return None
            t = var_order[ci]
            if _sort_key(_encode_sort(t.sort)) != _sort_key(sort_enc):
                return None
            scalars[t] = bool(v) if t.sort is terms.BOOL else int(v)
        for ci, _name, sort_enc, av in data.get("arrays", ()):
            if ci >= len(var_order):
                return None
            t = var_order[ci]
            if _sort_key(_encode_sort(t.sort)) != _sort_key(sort_enc):
                return None
            arrays[t] = _load_array(av)
    except (TypeError, ValueError, KeyError):
        return None
    return Assignment(scalars, arrays)


def model_on_query(data: dict, query_vars: Sequence[Term]) -> Optional[Assignment]:
    """Materialize a cached model onto a DIFFERENT query's variables by
    (name, sort) matching.  Unmatched query variables keep the Assignment
    completion default (0 / empty array); extra cached entries are ignored.
    The result is only a CANDIDATE — the caller must validate it with
    concrete_eval.evaluate before answering SAT."""
    scalars_by_name: Dict[tuple, object] = {}
    arrays_by_name: Dict[tuple, dict] = {}
    try:
        for _ci, name, sort_enc, v in data.get("scalars", ()):
            scalars_by_name[(name, _sort_key(sort_enc))] = v
        for _ci, name, sort_enc, av in data.get("arrays", ()):
            arrays_by_name[(name, _sort_key(sort_enc))] = av
    except (TypeError, ValueError):
        return None
    scalars: Dict[Term, object] = {}
    arrays: Dict[Term, ArrayValue] = {}
    matched = False
    for t in query_vars:
        key = (t.aux, _sort_key(_encode_sort(t.sort)))
        if t.op == "var":
            v = scalars_by_name.get(key)
            if v is not None:
                scalars[t] = bool(v) if t.sort is terms.BOOL else int(v)
                matched = True
        elif t.op == "array_var":
            av = arrays_by_name.get(key)
            if av is not None:
                try:
                    arrays[t] = _load_array(av)
                except (TypeError, ValueError):
                    return None
                matched = True
    if not matched:
        return None
    return Assignment(scalars, arrays)
