"""Metrics history ring: delta encoding, rotation, restart seams, windows."""

import json
import os

import pytest

from mythril_tpu.observability.history import (
    HistoryReader,
    MetricsHistory,
    counter_window,
    encode_registry,
    histogram_window,
    window_percentile,
)
from mythril_tpu.observability.metrics import MetricsRegistry


@pytest.fixture
def reg():
    return MetricsRegistry()


def _hist(tmp_path, reg, **kw):
    return MetricsHistory(str(tmp_path), registry=reg, **kw)


def _lines(path):
    return [json.loads(l) for l in open(path) if l.strip()]


def test_roundtrip_counter_gauge_histogram(tmp_path, reg):
    reg.counter("service.requests").inc(3)
    reg.gauge("service.workers").set(2)
    h = reg.histogram("service.ttfe_s", buckets=(0.1, 1.0, 10.0))
    h.observe(0.05)
    h.observe(5.0)
    hist = _hist(tmp_path, reg)
    t, values = hist.record(t=100.0)
    hist.close()
    assert values["service.requests"] == 3
    assert values["service.workers"] == 2
    assert values["service.ttfe_s"]["c"] == 2

    reader = HistoryReader(str(tmp_path))
    samples = list(reader.samples())
    assert len(samples) == 1
    rt, rvals = samples[0]
    assert rt == 100.0
    assert rvals == values
    # bucket boundaries replay from the full line's hb map
    assert reader.bucket_bounds["service.ttfe_s"] == (0.1, 1.0, 10.0)


def test_delta_lines_carry_only_changes(tmp_path, reg):
    c = reg.counter("service.requests")
    reg.gauge("service.workers").set(1)
    c.inc()
    hist = _hist(tmp_path, reg)
    hist.record(t=1.0)
    hist.record(t=2.0)  # nothing changed: no line at all
    c.inc()
    hist.record(t=3.0)  # only the counter changed
    hist.close()

    (path,) = [p for _, p in
               [(0, os.path.join(str(tmp_path), "seg-00000000.jsonl"))]]
    lines = _lines(path)
    assert len(lines) == 2  # full + one delta; the quiet tick wrote nothing
    assert lines[0]["full"] == 1
    assert lines[1]["m"] == {"service.requests": 2}

    # the reader still reconstructs the unchanged gauge at every tick
    reader = HistoryReader(str(tmp_path))
    series = reader.series("service.workers")
    assert [v for _, v in series] == [1, 1]


def test_zero_counters_omitted_gauge_zero_kept(reg):
    reg.counter("service.nothing")  # zero: absent means zero
    reg.gauge("service.workers").set(0)  # zero gauge is a statement
    values, _bounds = encode_registry(reg)
    assert "service.nothing" not in values
    assert values["service.workers"] == 0


def test_prefix_filter(reg):
    reg.counter("service.requests").inc()
    reg.counter("frontier.segments").inc()
    values, _ = encode_registry(reg)
    assert "service.requests" in values
    assert "frontier.segments" not in values


def test_rotation_and_ring_prune(tmp_path, reg):
    c = reg.counter("service.requests")
    hist = _hist(tmp_path, reg, max_segment_bytes=1, max_segments=3)
    for i in range(8):
        c.inc()
        hist.record(t=float(i))
    hist.close()
    names = sorted(n for n in os.listdir(str(tmp_path))
                   if n.startswith("seg-"))
    # every tick rotated (1-byte budget); only the newest 3 survive
    assert len(names) <= 3
    assert names[-1] > names[0]
    # each surviving segment leads with a full snapshot: independently
    # readable, so the pruned prefix costs nothing
    for n in names:
        assert _lines(os.path.join(str(tmp_path), n))[0].get("full") == 1


def test_restart_continues_sequence(tmp_path, reg):
    c = reg.counter("service.requests")
    c.inc()
    h1 = _hist(tmp_path, reg)
    h1.record(t=1.0)
    h1.close()

    c.inc(5)
    h2 = _hist(tmp_path, reg)
    h2.record(t=2.0)
    h2.close()
    names = sorted(n for n in os.listdir(str(tmp_path))
                   if n.startswith("seg-"))
    assert names == ["seg-00000000.jsonl", "seg-00000001.jsonl"]

    reader = HistoryReader(str(tmp_path))
    series = reader.series("service.requests")
    assert [v for _, v in series] == [1, 6]


def test_reader_tolerates_torn_tail_line(tmp_path, reg):
    reg.counter("service.requests").inc()
    hist = _hist(tmp_path, reg)
    hist.record(t=1.0)
    hist.close()
    path = os.path.join(str(tmp_path), "seg-00000000.jsonl")
    with open(path, "a") as f:
        f.write('{"t": 2.0, "m": {"service.requ')  # crashed writer
    reader = HistoryReader(str(tmp_path))
    assert len(list(reader.samples())) == 1


def test_since_until_filters(tmp_path, reg):
    c = reg.counter("service.requests")
    hist = _hist(tmp_path, reg)
    for i in range(5):
        c.inc()
        hist.record(t=float(i))
    hist.close()
    reader = HistoryReader(str(tmp_path))
    ts = [t for t, _ in reader.samples(since=1.0, until=3.0)]
    assert ts == [1.0, 2.0, 3.0]
    assert reader.latest()[0] == 4.0
    segs = reader.segments()
    assert segs[0]["lines"] == 5
    assert segs[0]["t_first"] == 0.0 and segs[0]["t_last"] == 4.0


# -- windowed evaluation --------------------------------------------------


def _hist_sample(c, bc, s=0.0, mn=None, mx=None):
    return {"service.lat_s": {"c": c, "s": s, "mn": mn, "mx": mx,
                              "bc": list(bc)}}


def test_counter_window_delta_and_seam(tmp_path):
    samples = [
        (0.0, {"service.requests": 10}),
        (5.0, {"service.requests": 14}),
        (10.0, {"service.requests": 3}),  # restart seam: counter fell
    ]
    assert counter_window(samples, "service.requests", 0.0, 5.0) == 4.0
    # a negative delta means a restart crossed the window: the end value
    # ("everything since the restart") is the conservative reading
    assert counter_window(samples, "service.requests", 0.0, 10.0) == 3.0
    assert counter_window(samples, "service.missing", 0.0, 10.0) == 0.0


def test_histogram_window_delta_and_percentile():
    bounds = {"service.lat_s": (0.1, 1.0, 10.0)}
    samples = [
        (0.0, _hist_sample(2, [2, 0, 0, 0], mn=0.01, mx=0.05)),
        (60.0, _hist_sample(6, [2, 0, 4, 0], mn=0.01, mx=8.0)),
    ]
    win = histogram_window(samples, "service.lat_s", 0.0, 60.0)
    # the two old sub-0.1s observations are outside the window
    assert win["bc"] == [0, 0, 4, 0] and win["count"] == 4
    est, n = window_percentile(
        samples, "service.lat_s", 0.95, 0.0, 60.0, bounds)
    assert n == 4
    # all windowed mass in the (1.0, 10.0] bucket, clamped by mx=8.0
    assert 1.0 <= est <= 8.0


def test_window_percentile_respects_min_count():
    bounds = {"service.lat_s": (0.1, 1.0)}
    samples = [(0.0, _hist_sample(1, [1, 0, 0]))]
    est, n = window_percentile(
        samples, "service.lat_s", 0.95, -60.0, 0.0, bounds, min_count=5)
    assert est is None and n == 1


def test_window_percentile_over_reader_replay(tmp_path, reg):
    """The on-disk delta replay feeds the same window math as the tail."""
    h = reg.histogram("service.lat_s", buckets=(0.1, 1.0, 10.0))
    hist = _hist(tmp_path, reg)
    h.observe(0.05)
    hist.record(t=0.0)
    for _ in range(4):
        h.observe(5.0)
    hist.record(t=60.0)
    hist.close()
    reader = HistoryReader(str(tmp_path))
    samples = list(reader.samples())
    est, n = window_percentile(
        samples, "service.lat_s", 0.95, 0.0, 60.0, reader.bucket_bounds)
    assert n == 4
    assert 1.0 <= est <= 10.0
