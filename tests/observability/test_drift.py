"""Drift doctor: ranked attribution over bench pairs and history windows."""

import json
from pathlib import Path

import pytest

from mythril_tpu.observability.drift import (
    attribute,
    diff_history_windows,
    diff_tables,
    format_drift,
    load_bench_table,
)

_REPO = Path(__file__).resolve().parents[2]


def _row(production=100.0, baseline=50.0, **over):
    row = {
        "unit": "states/sec",
        "baseline": baseline,
        "production": production,
        "speedup": round(production / baseline, 3),
        "reps": 3,
        "spread": {"production": [production * 0.95, production * 1.05]},
        "ttfe_s": {"baseline": 2.0, "production": 1.0},
        "harvest_share_pct": 20.0,
        "harvest_phase_s": {
            "ingest": 0.1, "solver": 1.0, "replay": 0.5, "commit": 0.1,
        },
        "device_residency_pct": 80.0,
    }
    row.update(over)
    return row


def test_ranked_regression_tops_synthetic_pair():
    prior = {"fast": _row(), "slow": _row()}
    current = {
        "fast": _row(),
        # halve the rate, double solver wall: both should rank, rate first
        "slow": _row(production=50.0,
                     harvest_phase_s={"ingest": 0.1, "solver": 2.0,
                                      "replay": 0.5, "commit": 0.1}),
    }
    report = diff_tables(prior, current, "A.json", "B.json")
    assert report["mode"] == "bench"
    assert report["workloads_compared"] == ["fast", "slow"]
    top = report["ranked"][0]
    assert top["workload"] == "slow"
    assert top["direction"] == "regressed"
    # headline names the violator
    assert "slow" in report["headline"]
    metrics = {f["metric"] for f in report["ranked"]}
    assert "harvest_phase_s.solver" in metrics


def test_regression_outranks_equal_improvement():
    # +50% coverage vs -50% coverage, same weight: the regression wins
    prior = {"up": _row(exploration={"coverage_pct": 40.0}),
             "down": _row(exploration={"coverage_pct": 40.0})}
    current = {"up": _row(exploration={"coverage_pct": 60.0}),
               "down": _row(exploration={"coverage_pct": 20.0})}
    report = diff_tables(prior, current)
    cov = [f for f in report["ranked"]
           if f["metric"] == "exploration.coverage_pct"]
    assert [f["workload"] for f in cov] == ["down", "up"]
    assert cov[0]["direction"] == "regressed"
    assert cov[1]["direction"] == "improved"
    assert cov[0]["score"] > cov[1]["score"]


def test_movement_below_noise_floor_is_dropped():
    prior = {"w": _row(production=100.0)}
    current = {"w": _row(production=101.0)}  # +1% < 2% floor
    report = diff_tables(prior, current)
    assert not any(f["metric"] == "production_rate"
                   for f in report["ranked"])
    empty = diff_tables({"w": _row()}, {"w": _row()})
    assert empty["ranked"] == []
    assert empty["headline"] == "drift: no metric moved beyond noise"


def test_relative_movement_is_capped():
    # 0.001 -> 10: a 10000x transition must not drown everything; the
    # rel is clipped to +300%
    prior = {"w": _row(harvest_share_pct=0.001)}
    current = {"w": _row(harvest_share_pct=10.0)}
    report = diff_tables(prior, current)
    f = next(f for f in report["ranked"]
             if f["metric"] == "harvest_share_pct")
    assert f["rel_pct"] == 300.0


def test_torn_inputs_are_data_not_errors():
    prior = {"gone": _row(), "shared": _row(),
             "broken": "not-a-row"}
    current = {"shared": {"production": "NaN-ish", "baseline": None},
               "new": _row()}
    report = diff_tables(prior, current)
    assert report["only_in_prior"] == ["broken", "gone"]
    assert report["only_in_current"] == ["new"]
    # the shared row's non-numeric values are skipped, not fatal
    assert all(f["workload"] == "shared" or False
               for f in report["ranked"]) or report["ranked"] == []
    # wholly non-dict inputs degrade to an empty comparison
    assert diff_tables(None, [1, 2])["workloads_compared"] == []


def test_attribute_filters_by_workload():
    prior = {"a": _row(), "b": _row()}
    current = {"a": _row(production=20.0), "b": _row(production=99.0)}
    report = diff_tables(prior, current)
    assert "a" in attribute(report, workload="a")
    line_b = attribute(report, workload="b")
    assert "b" in line_b or line_b.startswith("drift: no metric")
    assert attribute(report, workload="nope").startswith(
        "drift: no metric moved")


def test_format_drift_renders_ranked_table():
    prior = {"w": _row()}
    current = {"w": _row(production=10.0)}
    text = format_drift(diff_tables(prior, current, "old", "new"), limit=3)
    assert "drift report  old -> new" in text
    assert "production_rate" in text
    assert "REGRESSED" in text
    assert text.strip().endswith(attribute(diff_tables(prior, current)))


def test_load_bench_table_all_formats(tmp_path):
    table = {"w": _row()}
    snap = tmp_path / "snap.json"
    snap.write_text(json.dumps({"workloads": table, "metric": "x"}))
    assert load_bench_table(str(snap)) == table

    wrapper = tmp_path / "wrapper.json"
    wrapper.write_text(json.dumps({"rc": 0, "parsed": {"workloads": table}}))
    assert load_bench_table(str(wrapper)) == table

    # torn tail: last parseable snapshot line wins
    torn = tmp_path / "torn.json"
    torn.write_text(json.dumps({
        "rc": 124, "parsed": None,
        "tail": "garbage\n" + json.dumps({"workloads": table})
        + "\n{\"workloads\": {truncated",
    }))
    assert load_bench_table(str(torn)) == table

    assert load_bench_table(str(tmp_path / "missing.json")) == {}
    bad = tmp_path / "bad.json"
    bad.write_text("not json at all")
    assert load_bench_table(str(bad)) == {}


def test_history_window_mode_ranks_counter_acceleration():
    # counter: 1/s in the prior window, 5/s in the recent one;
    # histogram: avg 10ms -> 40ms; labeled map: flat
    samples = []
    total = 0.0
    hist_c, hist_s = 0, 0.0
    for t in range(0, 121, 10):
        rate = 1.0 if t <= 60 else 5.0
        total += rate * 10
        hist_c += 10
        hist_s += (0.01 if t <= 60 else 0.04) * 10
        samples.append((float(t), {
            "service.requests": total,
            "frontier.segment_device_s": {
                "c": hist_c, "s": round(hist_s, 4), "mn": 0.001,
                "mx": 0.1, "bc": [hist_c, 0, 0],
            },
            "device.cache_hits_by_bucket": {"1x2x3x4": 7},
        }))
    report = diff_history_windows(samples, window_s=60.0)
    assert report["mode"] == "history"
    by_metric = {f["metric"]: f for f in report["ranked"]}
    assert by_metric["service.requests"]["direction"] == "moved"
    assert by_metric["service.requests"]["current"] > \
        by_metric["service.requests"]["prior"]
    assert "frontier.segment_device_s.avg_s" in by_metric
    # the flat labeled map did not move
    assert "device.cache_hits_by_bucket.total" not in by_metric
    assert report["headline"].startswith("drift: most-moved")


def test_history_window_mode_empty():
    report = diff_history_windows([], window_s=60.0)
    assert report["ranked"] == []
    assert report["headline"] == "drift: history is empty"


@pytest.mark.skipif(
    not ((_REPO / "BENCH_r13.json").exists()
         and (_REPO / "BENCH_r15.json").exists()),
    reason="repo bench artifacts not present",
)
def test_repo_artifacts_r13_vs_r15_name_bectoken():
    """The acceptance drill: the r13 -> r15 pair must attribute movement
    to bectoken_batch (the workload the r15 table visibly lost)."""
    prior = load_bench_table(str(_REPO / "BENCH_r13.json"))
    current = load_bench_table(str(_REPO / "BENCH_r15.json"))
    assert prior and current
    report = diff_tables(prior, current, "BENCH_r13", "BENCH_r15")
    top5 = [f["workload"] for f in report["ranked"][:5]]
    assert "bectoken_batch" in top5
