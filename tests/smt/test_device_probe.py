"""Solver integration with the batched JAX lowering (forced on CPU).

``args.probe_backend = "jax"`` routes candidate evaluation through
mythril_tpu/ops/lowering.py; results must be identical in kind to the host
path (a validated model), including graceful fallback for unlowerable DAGs.
"""

import pytest

from mythril_tpu.smt import terms
from mythril_tpu.smt.concrete_eval import evaluate
from mythril_tpu.smt.solver import SAT, solve_conjunction
from mythril_tpu.support.support_args import args as global_args


@pytest.fixture
def jax_backend():
    prev = global_args.probe_backend
    global_args.probe_backend = "jax"
    yield
    global_args.probe_backend = prev


def test_device_probe_finds_model(jax_backend):
    x = terms.var("x", 256)
    y = terms.var("y", 256)
    conjuncts = [
        terms.eq(terms.add(x, y), terms.const(1000, 256)),
        terms.ult(x, terms.const(10, 256)),
        terms.ugt(y, terms.const(100, 256)),
    ]
    status, asg = solve_conjunction(conjuncts)
    assert status == SAT
    vals = evaluate(conjuncts, asg)
    assert all(vals[c] for c in conjuncts)


def test_device_probe_selector_style_constraints(jax_backend):
    # the realistic hot query: function-selector match + caller alternation
    calldata = terms.array_var("calldata", 256, 8)
    word = terms.concat(
        *[terms.select(calldata, terms.const(i, 256)) for i in range(4)]
    )
    caller = terms.var("caller", 256)
    conjuncts = [
        terms.eq(word, terms.const(0x41C0E1B5, 32)),
        terms.lor(
            terms.eq(caller, terms.const(0xDEADBEEF, 256)),
            terms.eq(caller, terms.const(0xAFFE, 256)),
        ),
    ]
    status, asg = solve_conjunction(conjuncts)
    assert status == SAT
    vals = evaluate(conjuncts, asg)
    assert all(vals[c] for c in conjuncts)


def test_device_probe_falls_back_on_uf(jax_backend):
    # 'apply' nodes cannot lower; the host path must still answer
    x = terms.var("x", 256)
    f = terms.apply_func("oracle", 256, x)
    conjuncts = [terms.eq(f, terms.const(0, 256)), terms.ult(x, terms.const(5, 256))]
    status, asg = solve_conjunction(conjuncts)
    assert status == SAT
