"""Plugin control-flow signals (reference parity: laser/plugin/signals.py:1-27)."""


class PluginSignal(Exception):
    pass


class PluginSkipState(PluginSignal):
    """Raised inside a state hook: drop this state from the work list."""


class PluginSkipWorldState(PluginSignal):
    """Raised inside a world-state hook: do not reseed from this world state."""
