"""Cross-process completed-result LRU under ``--cache-root``.

The admission controller's in-memory replay log dies with its process
and is invisible to anything else sharing the cache root.  This store
persists each completed flight's event log (the same replay-then-live
event list a late subscriber gets) as one small JSON file keyed by
``(codehash, options_key)`` — so a dedup hit survives worker affinity,
daemon restarts, and multiple daemons sharing one ``--cache-root``
(exactly like the SMT query cache and XLA compile cache beside it).

Concurrency: writes are atomic (tmp + ``os.replace``), reads tolerate
missing/garbled files (a torn concurrent eviction reads as a miss), and
LRU pressure is by mtime — ``get`` touches the file, eviction removes
the oldest.  No cross-process lock is needed: the worst race re-analyzes
one contract, it never corrupts a result.

Only ``done``-terminated logs are stored, mirroring the in-memory
policy: a tenant-scoped failure must not poison later submissions.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
from typing import Any, List, Optional, Tuple

log = logging.getLogger(__name__)

__all__ = ["ResultStore"]


class ResultStore:
    def __init__(self, root: str, max_entries: int = 1024):
        self.root = os.path.abspath(os.path.expanduser(root))
        self.max_entries = max_entries
        os.makedirs(self.root, exist_ok=True)

    def _path(self, key: Tuple) -> str:
        digest = hashlib.sha256(repr(key).encode()).hexdigest()[:40]
        return os.path.join(self.root, f"{digest}.json")

    def get(self, key: Tuple) -> Optional[List[Tuple[str, Any]]]:
        """Replay log for ``key``, or None.  Touches the entry (LRU)."""
        path = self._path(key)
        try:
            with open(path, "r", encoding="utf-8") as f:
                doc = json.load(f)
            events = [(str(k), p) for k, p in doc["events"]]
        except (OSError, ValueError, KeyError, TypeError):
            return None
        if not events or events[-1][0] != "done":
            return None
        try:
            os.utime(path)
        except OSError:
            pass
        return events

    def put(self, key: Tuple, events: List[Tuple[str, Any]]) -> bool:
        """Persist a completed replay log; returns False on skip/error."""
        if not events or events[-1][0] != "done":
            return False
        path = self._path(key)
        tmp = f"{path}.tmp.{os.getpid()}"
        try:
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(
                    {"key": repr(key),
                     "events": [[k, p] for k, p in events]},
                    f, default=repr,
                )
            os.replace(tmp, path)
        except (OSError, ValueError):
            log.debug("result store put failed for %r", key, exc_info=True)
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return False
        self._evict()
        return True

    def _evict(self) -> None:
        try:
            entries = [
                os.path.join(self.root, n)
                for n in os.listdir(self.root)
                if n.endswith(".json")
            ]
            if len(entries) <= self.max_entries:
                return
            entries.sort(key=lambda p: os.path.getmtime(p))
            for path in entries[: len(entries) - self.max_entries]:
                os.unlink(path)
        except OSError:
            pass  # concurrent eviction; next put retries

    def __len__(self) -> int:
        try:
            return sum(
                1 for n in os.listdir(self.root) if n.endswith(".json")
            )
        except OSError:
            return 0
