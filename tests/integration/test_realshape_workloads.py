"""Real exploit shapes beyond BECToken: etherstore reentrancy and rubixi
ownership takeover, host/frontier differential (bench_contracts.py;
reference shapes /root/reference/solidity_examples/etherstore.sol and
rubixi.sol)."""

import pathlib
import sys

import pytest

sys.path.insert(0, str(pathlib.Path(__file__).parents[2]))
from bench_contracts import etherstore_like, rubixi_like  # noqa: E402
from mythril_tpu.analysis.security import fire_lasers, reset_callback_modules
from mythril_tpu.analysis.symbolic import SymExecWrapper
from mythril_tpu.frontier.stats import FrontierStatistics
from mythril_tpu.support.support_args import args as global_args


def _analyze(code: bytes, frontier: bool, modules, timeout=90):
    reset_callback_modules()
    from mythril_tpu.analysis.module.loader import ModuleLoader

    for m in ModuleLoader().get_detection_modules():
        if hasattr(m, "cache"):
            m.cache.clear()
    old = (global_args.frontier, global_args.frontier_force)
    global_args.frontier = frontier
    global_args.frontier_force = frontier
    try:
        sym = SymExecWrapper(
            code,
            address=0x0901D12E,
            strategy="bfs",
            transaction_count=2,
            execution_timeout=timeout,
            modules=modules,
        )
        return fire_lasers(sym, white_list=modules)
    finally:
        global_args.frontier, global_args.frontier_force = old


def keys(issues):
    return sorted({(i.swc_id, i.address) for i in issues})


@pytest.mark.parametrize("frontier", [False, True])
def test_etherstore_reentrancy_found(frontier):
    """The withdrawFunds CALL-to-caller before the balance decrement must
    be flagged SWC-107 (external call to user address / state change after
    external call)."""
    FrontierStatistics().reset()
    issues = _analyze(
        etherstore_like(), frontier,
        ["ExternalCalls", "StateChangeAfterCall"],
    )
    assert any(i.swc_id == "107" for i in issues), (
        f"reentrancy window not flagged: {keys(issues)}"
    )
    if frontier:
        assert FrontierStatistics().device_instructions > 0


@pytest.mark.parametrize("frontier", [False, True])
def test_rubixi_ownership_drain_found(frontier):
    """dynamicPyramid (tx1) then collectAllFees (tx2) drains fees to the
    attacker: SWC-105 unprotected ether withdrawal."""
    FrontierStatistics().reset()
    issues = _analyze(rubixi_like(), frontier, ["EtherThief"])
    assert any(i.swc_id == "105" for i in issues), (
        f"ownership-takeover drain not flagged: {keys(issues)}"
    )
    if frontier:
        assert FrontierStatistics().device_instructions > 0


def test_frontier_host_parity_on_real_shapes():
    for code, modules in (
        (etherstore_like(), ["ExternalCalls", "StateChangeAfterCall"]),
        (rubixi_like(), ["EtherThief"]),
    ):
        host = _analyze(code, False, modules)
        dev = _analyze(code, True, modules)
        assert keys(host) == keys(dev), (
            f"host={keys(host)} dev={keys(dev)}"
        )
