"""Batched device-resident frontier interpreter.

The north-star architecture (SURVEY.md §7.1): instead of stepping one
host-Python ``GlobalState`` at a time (reference
mythril/laser/ethereum/svm.py:261-304), the work list becomes a fixed-width
struct-of-arrays batch of machine states held on the TPU.  One jitted segment
program steps every live path in lockstep for K instructions per dispatch —
opcode dispatch via ``lax.switch``, 256-bit words as 16-bit-limb tensors,
symbolic values as indices into a device-resident term arena, JUMPI forks as
masked in-batch duplication — and the host only sees the batch at segment
boundaries to harvest finished paths, fire detector hooks, and refill slots.

Module map:
  * ``ops``     — arena/term op codes + handler family codes (shared constants)
  * ``arena``   — host mirror of the device term arena; encode/decode vs
                  the host term IR (mythril_tpu/smt/terms.py)
  * ``code``    — per-instruction dispatch tables compiled from bytecode
  * ``state``   — the SoA frontier state pytree + host mirrors
  * ``step``    — the jitted K-step segment program
  * ``records`` — host-side path lineage (fork tree) bookkeeping
  * ``walker``  — carrier reconstruction: replays device events through host
                  GlobalStates so detection modules see identical states
  * ``engine``  — orchestration + LaserEVM integration
"""

__all__ = ["FrontierEngine"]


def __getattr__(name: str):
    # lazy: detection modules import frontier.taint (jax-free) at load time;
    # an eager engine import here would pull step -> jax into every detector
    # load and defeat svm.py's deliberately-lazy FrontierEngine import and
    # its graceful degradation when jax is unavailable
    if name == "FrontierEngine":
        from mythril_tpu.frontier.engine import FrontierEngine

        return FrontierEngine
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
