"""Process-wide metrics registry: named counters, gauges, histograms.

This registry absorbs the mutable-attribute telemetry that used to live
in three disconnected singletons (``FrontierStatistics``,
``SolverStatistics``, the ``InstructionProfiler`` plugin).  Those
classes remain as thin facades whose attributes are properties backed by
registry metrics, so call sites like ``stats.segments += 1`` and tests
that assign ``stats.unknown_as_unsat = 0`` keep working unchanged.

Scopes
------
Metrics default to the *analysis* scope and are cleared by
``MetricsRegistry.reset()`` at the start of each analysis.  Metrics
created with ``persistent=True`` survive that sweep — the frontier's
per-code slow/narrow-segment verdicts use this, mirroring the
deliberately process-persistent ``_SLOW_CODES`` / ``_NARROW_CODES``
dicts in ``frontier/engine.py`` (a code that degenerated once must not
be re-probed by the very next analysis in the same process).

Thread-safety: ``Counter.inc``, ``Histogram.observe`` and
``LabeledCounter.inc`` are real read-modify-write cycles, and the
pipelined frontier's feasibility pool mutates solver/querycache counters
from worker threads — so all three take a shared module-level mutation
lock (one uncontended lock acquire per increment; the hot paths increment
at segment/query granularity, not per instruction).  Plain ``+=`` on a
``LabeledCounter`` item and facade property writes remain main-thread
constructs.  Registry *registration* is separately lock-protected because
worker threads may create metrics concurrently.
"""

from __future__ import annotations

import bisect
import collections
import re
import threading
from typing import Any, Dict, List, Optional, Tuple, Union

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "LabeledCounter",
    "MetricsRegistry",
    "get_registry",
    "percentile_from_buckets",
    "prometheus_text",
]

Number = Union[int, float]

# shared by every metric's mutators: increments are read-modify-write and
# must be atomic across the feasibility-pool worker threads
_MUTATION_LOCK = threading.Lock()


class Counter:
    """Monotonic-by-convention accumulator; ``set()`` exists for facades.

    ``initial`` fixes the numeric type: a counter created with ``0.0``
    resets to float zero, keeping facade report output (``round(x, 3)``)
    type-stable with the pre-registry singletons.
    """

    __slots__ = ("name", "persistent", "value", "gen", "_initial")

    def __init__(self, name: str, persistent: bool = False, initial: Number = 0):
        self.name = name
        self.persistent = persistent
        self._initial = initial
        self.value: Number = initial
        # reset generation: bumped by every reset() so delta consumers
        # (observability/fleet.py) can tell "swept back to zero" from
        # "never moved" without guessing from the value
        self.gen = 0

    def inc(self, n: Number = 1) -> None:
        with _MUTATION_LOCK:
            self.value += n

    def set(self, v: Number) -> None:
        self.value = v

    def reset(self) -> None:
        self.value = self._initial
        self.gen += 1

    def snapshot(self) -> Number:
        return self.value


class Gauge:
    """Last-write-wins value; may hold any JSON-serializable object."""

    __slots__ = ("name", "persistent", "value", "gen", "_default",
                 "label_name")

    def __init__(self, name: str, persistent: bool = False, default: Any = 0,
                 label_name: str = "key"):
        self.name = name
        self.persistent = persistent
        self._default = default
        self.value: Any = _copy_default(default)
        # label key used when a dict-valued gauge is rendered to the
        # Prometheus text format ({objective="ttfe_p95"} reads better
        # than {key="ttfe_p95"} for the watchtower's status gauge)
        self.label_name = label_name
        self.gen = 0

    def set(self, v: Any) -> None:
        self.value = v

    def reset(self) -> None:
        self.value = _copy_default(self._default)
        self.gen += 1

    def snapshot(self) -> Any:
        return self.value


def _copy_default(default: Any) -> Any:
    # mutable defaults (microbench dict) must not be shared across resets
    return default.copy() if isinstance(default, (dict, list)) else default


class LabeledCounter(collections.Counter):
    """A ``collections.Counter`` registered as one metric.

    Subclassing keeps facade call sites like
    ``stats.parks_by_opcode[op] += 1`` and ``.most_common()`` intact.
    """

    def __init__(self, name: str, persistent: bool = False,
                 label_name: str = "label"):
        super().__init__()
        self.name = name
        self.persistent = persistent
        # Prometheus label key used by the text exposition ({tenant="x"}
        # reads better than {label="x"} for the service's per-tenant
        # counters); keys stay plain strings everywhere else.
        self.label_name = label_name
        self.gen = 0

    def inc(self, label: str, n: Number = 1) -> None:
        """Thread-safe increment (``c[label] += n`` is not atomic)."""
        with _MUTATION_LOCK:
            self[label] = self.get(label, 0) + n

    def reset(self) -> None:
        self.clear()
        self.gen += 1

    def snapshot(self) -> Dict[str, Number]:
        return dict(self.most_common())


# Power-of-two-ish duration buckets (seconds): 100µs .. ~100s.
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0,
)


def percentile_from_buckets(
    buckets: Tuple[float, ...],
    bucket_counts: List[int],
    q: float,
    lo_obs: Optional[float] = None,
    hi_obs: Optional[float] = None,
) -> Optional[float]:
    """Estimate the ``q``-quantile (0..1) from a bucket layout.

    ``bucket_counts`` is one count per bucket plus the +Inf overflow slot
    (``Histogram`` layout).  Linear interpolation inside the covering
    bucket, clamped to ``[lo_obs, hi_obs]`` when observed extremes are
    known.  Shared by ``Histogram.percentile`` (live registry) and the
    watchtower's windowed evaluation over history bucket deltas, where
    only counts — not extremes — survive delta encoding.  Returns
    ``None`` when the counts are empty.
    """
    count = sum(bucket_counts)
    if not count:
        return None
    target = max(0.0, min(1.0, q)) * count
    cum = 0
    for i, c in enumerate(bucket_counts):
        if not c:
            continue
        if cum + c >= target:
            lo = buckets[i - 1] if i > 0 else 0.0
            if i < len(buckets):
                hi = buckets[i]
            elif hi_obs is not None:
                hi = hi_obs
            else:
                hi = buckets[-1]
            frac = (target - cum) / c
            est = lo + (hi - lo) * max(0.0, min(1.0, frac))
            if lo_obs is not None:
                est = max(est, lo_obs)
            if hi_obs is not None:
                est = min(est, hi_obs)
            return est
        cum += c
    return hi_obs if hi_obs is not None else buckets[-1]


class Histogram:
    """Fixed-bucket histogram with count/sum/min/max.

    ``bucket_counts[i]`` counts observations ``<= buckets[i]``; the final
    slot is the +Inf overflow bucket (Prometheus-style cumulative-free
    layout — each observation lands in exactly one slot).
    """

    __slots__ = (
        "name", "persistent", "buckets", "bucket_counts",
        "count", "sum", "min", "max", "gen",
    )

    def __init__(
        self,
        name: str,
        buckets: Tuple[float, ...] = DEFAULT_BUCKETS,
        persistent: bool = False,
    ):
        self.name = name
        self.persistent = persistent
        self.buckets: Tuple[float, ...] = tuple(buckets)
        self.bucket_counts: List[int] = [0] * (len(self.buckets) + 1)
        self.count = 0
        self.sum = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None
        self.gen = 0

    def observe(self, v: float) -> None:
        with _MUTATION_LOCK:
            self.bucket_counts[bisect.bisect_left(self.buckets, v)] += 1
            self.count += 1
            self.sum += v
            if self.min is None or v < self.min:
                self.min = v
            if self.max is None or v > self.max:
                self.max = v

    def reset(self) -> None:
        self.bucket_counts = [0] * (len(self.buckets) + 1)
        self.count = 0
        self.sum = 0.0
        self.min = None
        self.max = None
        self.gen += 1

    def percentile(self, q: float) -> Optional[float]:
        """Estimate the ``q``-quantile (0..1) from the bucket layout.

        Linear interpolation inside the covering bucket, clamped to the
        observed ``[min, max]`` — exact at the extremes, bucket-resolution
        in between (the same estimate ``histogram_quantile`` makes).
        Returns ``None`` when nothing has been observed.
        """
        with _MUTATION_LOCK:
            if not self.count:
                return None
            counts = list(self.bucket_counts)
            lo_obs, hi_obs = self.min, self.max
        return percentile_from_buckets(self.buckets, counts, q,
                                       lo_obs=lo_obs, hi_obs=hi_obs)

    def snapshot(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "count": self.count,
            "sum": round(self.sum, 6),
        }
        if self.count:
            out["min"] = round(self.min, 6)
            out["max"] = round(self.max, 6)
            out["avg"] = round(self.sum / self.count, 6)
            # only non-empty buckets, keyed by upper bound ("+Inf" last)
            nonzero = {}
            for i, c in enumerate(self.bucket_counts):
                if c:
                    le = "+Inf" if i == len(self.buckets) else repr(self.buckets[i])
                    nonzero[le] = c
            out["buckets_le"] = nonzero
        return out


class MetricsRegistry:
    """Name -> metric map with get-or-create accessors and scoped reset."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, Any] = {}

    def _get_or_create(self, name: str, factory, kind) -> Any:
        m = self._metrics.get(name)
        if m is None:
            with self._lock:
                m = self._metrics.get(name)
                if m is None:
                    m = factory()
                    self._metrics[name] = m
        if not isinstance(m, kind):
            raise TypeError(
                f"metric {name!r} already registered as {type(m).__name__}"
            )
        return m

    def counter(
        self, name: str, persistent: bool = False, initial: Number = 0
    ) -> Counter:
        return self._get_or_create(
            name, lambda: Counter(name, persistent, initial), Counter
        )

    def gauge(self, name: str, persistent: bool = False, default: Any = 0,
              label_name: str = "key") -> Gauge:
        return self._get_or_create(
            name, lambda: Gauge(name, persistent, default, label_name), Gauge
        )

    def labeled_counter(self, name: str, persistent: bool = False,
                        label_name: str = "label") -> LabeledCounter:
        return self._get_or_create(
            name, lambda: LabeledCounter(name, persistent, label_name),
            LabeledCounter,
        )

    def histogram(
        self,
        name: str,
        buckets: Tuple[float, ...] = DEFAULT_BUCKETS,
        persistent: bool = False,
    ) -> Histogram:
        return self._get_or_create(
            name, lambda: Histogram(name, buckets, persistent), Histogram
        )

    def observe(self, name: str, v: float) -> None:
        """Shorthand: record ``v`` into histogram ``name``."""
        self.histogram(name).observe(v)

    def reset(self, include_persistent: bool = False, prefix: str = "") -> None:
        """Zero analysis-scoped metrics; keep ``persistent=True`` ones
        unless ``include_persistent`` is set.  ``prefix`` restricts the
        sweep to one namespace (e.g. ``"frontier."``)."""
        with self._lock:
            metrics = [
                m for name, m in self._metrics.items()
                if name.startswith(prefix)
            ]
        for m in metrics:
            if include_persistent or not m.persistent:
                m.reset()

    def snapshot(self, prefix: str = "") -> Dict[str, Any]:
        """JSON-serializable view of every metric (optionally filtered)."""
        with self._lock:
            items = sorted(self._metrics.items())
        return {
            name: m.snapshot()
            for name, m in items
            if name.startswith(prefix)
        }


_registry = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return _registry


# -- Prometheus text exposition (format 0.0.4) ---------------------------

_PROM_BAD_CHARS = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str) -> str:
    """Registry names use dots; Prometheus metric names cannot."""
    n = _PROM_BAD_CHARS.sub("_", name)
    return "_" + n if n and n[0].isdigit() else n


def _prom_label_value(v: Any) -> str:
    return str(v).replace("\\", r"\\").replace('"', r'\"').replace("\n", r"\n")


def _prom_number(v: Number) -> str:
    if isinstance(v, float) and v == int(v) and abs(v) < 1e15:
        return repr(v)
    return repr(v) if isinstance(v, float) else str(v)


def prometheus_text(registry: Optional[MetricsRegistry] = None) -> str:
    """Render the registry in the Prometheus text exposition format.

    Counters and gauges become single samples; dict-valued gauges (the
    heartbeat's per-shard depth maps) and labeled counters become one
    labeled sample per key; histograms emit the standard *cumulative*
    ``_bucket{le=...}`` series plus ``_sum``/``_count``.  Non-numeric
    gauge payloads are skipped — the format has no place for them.
    The analysis service serves this under the ``metrics`` verb.
    """
    reg = registry or get_registry()
    with reg._lock:
        items = sorted(reg._metrics.items())
    lines: List[str] = []
    for name, m in items:
        pname = _prom_name(name)
        if isinstance(m, Histogram):
            with _MUTATION_LOCK:
                counts = list(m.bucket_counts)
                count, total = m.count, m.sum
            lines.append(f"# TYPE {pname} histogram")
            cum = 0
            for i, c in enumerate(counts):
                cum += c
                le = ("+Inf" if i == len(m.buckets)
                      else _prom_number(float(m.buckets[i])))
                lines.append(f'{pname}_bucket{{le="{le}"}} {cum}')
            lines.append(f"{pname}_sum {_prom_number(float(total))}")
            lines.append(f"{pname}_count {count}")
        elif isinstance(m, LabeledCounter):
            # the label *name* is interpolated into the exposition verbatim,
            # so it must be a legal Prometheus label identifier too
            lkey = _prom_name(m.label_name or "label")
            lines.append(f"# TYPE {pname} counter")
            for label, v in sorted(m.snapshot().items()):
                if isinstance(v, (int, float)):
                    lines.append(
                        f'{pname}{{{lkey}="{_prom_label_value(label)}"}}'
                        f" {_prom_number(v)}"
                    )
        elif isinstance(m, Counter):
            lines.append(f"# TYPE {pname} counter")
            lines.append(f"{pname} {_prom_number(m.value)}")
        elif isinstance(m, Gauge):
            v = m.value
            if isinstance(v, dict):
                numeric = {k: x for k, x in v.items()
                           if isinstance(x, (int, float))}
                if not numeric:
                    continue
                lkey = _prom_name(m.label_name or "key")
                lines.append(f"# TYPE {pname} gauge")
                for k, x in sorted(numeric.items()):
                    lines.append(
                        f'{pname}{{{lkey}="{_prom_label_value(k)}"}}'
                        f" {_prom_number(x)}"
                    )
            elif isinstance(v, (int, float)) and not isinstance(v, bool):
                lines.append(f"# TYPE {pname} gauge")
                lines.append(f"{pname} {_prom_number(v)}")
    return "\n".join(lines) + "\n"
