"""The BECToken batchTransfer workload contract (bench_contracts.py).

Checks the hand-assembled runtime reproduces the CVE-2018-10299 semantics:
the unchecked ``cnt * _value`` multiply is flagged (SWC-101) while the
SafeMath-checked moves are not, and the frontier run matches the host run.
"""

import pathlib
import sys

import pytest

sys.path.insert(0, str(pathlib.Path(__file__).parents[2]))
from bench_contracts import (  # noqa: E402
    SEL_BATCH_TRANSFER,
    SEL_TRANSFER,
    bectoken_like,
)
from mythril_tpu.analysis.security import fire_lasers, reset_callback_modules
from mythril_tpu.analysis.symbolic import SymExecWrapper
from mythril_tpu.support.support_args import args as global_args


def _analyze(frontier: bool):
    reset_callback_modules()
    from mythril_tpu.analysis.module.loader import ModuleLoader

    for m in ModuleLoader().get_detection_modules():
        if hasattr(m, "cache"):
            m.cache.clear()
    old = (global_args.frontier, global_args.frontier_force)
    global_args.frontier = frontier
    global_args.frontier_force = frontier
    try:
        sym = SymExecWrapper(
            bectoken_like(),
            address=0x0901D12E,
            strategy="bfs",
            transaction_count=2,
            execution_timeout=120,
            modules=["IntegerArithmetics"],
        )
        return fire_lasers(sym, white_list=["IntegerArithmetics"])
    finally:
        global_args.frontier, global_args.frontier_force = old


def _dispatches(issue, sel: int) -> bool:
    steps = (issue.transaction_sequence or {}).get("steps", [])
    if not steps:
        return False
    data = steps[-1]["input"][2:]
    return data[:8].lower() == f"{sel:08x}"


@pytest.mark.parametrize("frontier", [False, True])
def test_batch_transfer_overflow_found(frontier):
    issues = _analyze(frontier)
    overflow = [i for i in issues if i.swc_id == "101"]
    assert overflow, "batchTransfer cnt*value overflow not found"
    # the exploit transaction must dispatch to batchTransfer — the checked
    # SafeMath paths (transfer) must not be flagged
    assert any(_dispatches(i, SEL_BATCH_TRANSFER) for i in overflow), (
        "SWC-101 not attributed to batchTransfer"
    )
    assert not any(_dispatches(i, SEL_TRANSFER) for i in overflow), (
        "SafeMath-checked transfer() wrongly flagged"
    )
