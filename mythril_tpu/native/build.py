"""Lazy in-tree build of the native library (g++ -O2 -shared -fPIC).

No pybind11 in this environment, so the boundary is a C ABI loaded with
ctypes.  The .so is cached next to the sources and rebuilt whenever a source
file is newer; concurrent builds are serialized with an exclusive lock so
parallel pytest workers don't race the compiler.
"""

from __future__ import annotations

import fcntl
import logging
import os
import shutil
import subprocess
from pathlib import Path
from typing import Optional

log = logging.getLogger(__name__)

_SRC_DIR = Path(__file__).parent / "src"
_LIB_PATH = Path(__file__).parent / "_libmythril_native.so"
_SOURCES = ["bitblast.cpp", "keccak.cpp"]


def library_path() -> Optional[Path]:
    """Path to the built library, building it if needed; None if impossible."""
    sources = [_SRC_DIR / s for s in _SOURCES if (_SRC_DIR / s).exists()]
    if not sources:
        return None
    if _LIB_PATH.exists() and all(
        _LIB_PATH.stat().st_mtime >= s.stat().st_mtime for s in sources
    ):
        return _LIB_PATH
    gxx = shutil.which("g++") or shutil.which("c++")
    if gxx is None:
        log.debug("no C++ compiler on PATH; native tier disabled")
        return None
    lock_path = _LIB_PATH.with_suffix(".lock")
    try:
        with open(lock_path, "w") as lock:
            fcntl.flock(lock, fcntl.LOCK_EX)
            if _LIB_PATH.exists() and all(
                _LIB_PATH.stat().st_mtime >= s.stat().st_mtime for s in sources
            ):
                return _LIB_PATH
            tmp = _LIB_PATH.with_suffix(".so.tmp")
            cmd = [
                gxx,
                "-O2",
                "-std=c++17",
                "-shared",
                "-fPIC",
                "-o",
                str(tmp),
                *[str(s) for s in sources],
            ]
            proc = subprocess.run(cmd, capture_output=True, text=True, timeout=300)
            if proc.returncode != 0:
                log.warning("native build failed:\n%s", proc.stderr[-2000:])
                return None
            os.replace(tmp, _LIB_PATH)
            return _LIB_PATH
    except Exception as e:  # hung compiler, lock failure, ... — callers
        # treat library_path()/available() as non-throwing and fall back
        log.debug("native build unavailable: %s", e)
        return None
