"""Static bytecode pre-analysis (once per contract, before any execution).

Three vectorized passes over the decoded instruction stream — the same
flat tables ``frontier/code.py`` builds its device dispatch from:

1. **CFG recovery** (:mod:`cfg`): basic blocks, static resolution of
   PUSH-then-JUMP/JUMPI targets via a bounded abstract constant stack,
   reachability from entry, unreachable-code spans.
2. **Abstract stack height** (:mod:`stackheight`): per-block max-entry-
   height fixpoint; a statically guaranteed underflow marks the rest of
   the block (and its edges) dead.
3. **Static taint reachability** (:mod:`taintflow`): per
   ``frontier/taint.py`` source bit, the set of opcodes its value may
   influence (``may_reach``), with global-channel escalation for flows
   the CFG cannot order (storage, calls, creation returns).

Everything is OVER-approximate: a may_reach miss or a reachable
instruction marked dead is impossible by construction, so issue sets are
identical with and without the pass (asserted in tests and by
``bench.py --staticpass-compare``).  Consumers:

* ``analysis/module/loader.py`` skips statically irrelevant detectors,
* ``analysis/symbolic.py`` never registers their hooks (hooks elided),
* ``frontier/engine.py`` / ``frontier/code.py`` clear event bits on
  unreachable instructions, skip their loop slots, and export statically
  resolved jump targets,
* ``--staticpass-report`` dumps the CFG/taint summary as JSON, and the
  ``staticpass.*`` counters flow through the observability registry into
  report meta, ``--metrics-out`` and bench JSON.

``--no-staticpass`` (args.staticpass = False) disables all of it.
"""

from mythril_tpu.staticpass.gate import (  # noqa: F401
    GateView,
    filter_modules,
    gate_view_for_contract,
    module_relevant,
)
from mythril_tpu.staticpass.report import (  # noqa: F401
    export_report,
    report_dict,
    reset_views,
)
from mythril_tpu.staticpass.summary import (  # noqa: F401
    StaticSummary,
    clear_cache,
    record_summary_metrics,
    summarize,
    summary_for_code,
)
