"""Multi-host corpus sharding (mythril_tpu/parallel/corpus.py)."""

import os

from mythril_tpu.parallel import run_corpus, shard_corpus, shard_identity


def test_round_robin_partition_is_exact():
    items = [f"c{i}" for i in range(10)]
    shards = [shard_corpus(items, index=i, count=3) for i in range(3)]
    # disjoint and complete
    flat = [x for s in shards for x in s]
    assert sorted(flat) == sorted(items)
    assert len(set(flat)) == len(items)
    # round-robin spreads the head evenly
    assert shards[0][0] == "c0" and shards[1][0] == "c1" and shards[2][0] == "c2"


def test_single_shard_returns_all():
    assert shard_corpus([1, 2, 3], index=0, count=1) == [1, 2, 3]


def test_identity_env_override(monkeypatch):
    monkeypatch.setenv("MYTHRIL_TPU_SHARD", "2")
    monkeypatch.setenv("MYTHRIL_TPU_NUM_SHARDS", "5")
    assert shard_identity() == (2, 5)


def test_run_corpus_isolates_failures():
    def analyze(path):
        if path == "bad":
            raise RuntimeError("boom")
        return f"ok:{path}"

    results = dict(run_corpus(["a", "bad", "b"], analyze, index=0, count=1))
    assert results["a"] == "ok:a"
    assert results["b"] == "ok:b"
    assert isinstance(results["bad"], RuntimeError)
