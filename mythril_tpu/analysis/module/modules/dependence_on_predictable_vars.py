"""PredictableVariables: control flow depends on predictable block values
(SWC-116 timestamp/number, SWC-120 weak randomness from blockhash/coinbase).

Reference parity: mythril/analysis/module/modules/dependence_on_predictable_vars.py:1-195.
"""

from __future__ import annotations

from typing import List, Optional

from mythril_tpu.analysis.module.base import DetectionModule, EntryPoint
from mythril_tpu.analysis.report import Issue
from mythril_tpu.analysis.solver import get_transaction_sequence
from mythril_tpu.analysis.swc_data import TIMESTAMP_DEPENDENCE, WEAK_RANDOMNESS
from mythril_tpu.core.state.global_state import GlobalState
from mythril_tpu.exceptions import UnsatError
from mythril_tpu.frontier import taint

DESCRIPTION = (
    "Check whether important control flow decisions are influenced by block.coinbase, "
    "block.gaslimit, block.timestamp or block.number."
)

PREDICTABLE_OPS = ["COINBASE", "GASLIMIT", "TIMESTAMP", "NUMBER"]


class PredictableValueAnnotation:
    def __init__(self, operation: str, add_constraints=None):
        self.operation = operation
        self.add_constraints = add_constraints or []


class PredictablePathAnnotation:
    def __init__(self, operation: str, location: int):
        self.operation = operation
        self.location = location


# one taint bit per predictable source: the operation name feeds both the
# issue text and the SWC id split (coinbase/blockhash -> weak randomness),
# so the bit must round-trip to the exact operation
_TAINT_OPS = {
    "TIMESTAMP": (taint.TAINT_TIMESTAMP, "block.timestamp"),
    "NUMBER": (taint.TAINT_NUMBER, "block.number"),
    "COINBASE": (taint.TAINT_COINBASE, "block.coinbase"),
    "GASLIMIT": (taint.TAINT_GASLIMIT, "block.gaslimit"),
    "BLOCKHASH": (taint.TAINT_BLOCKHASH, "blockhash"),
}

for _bit, _op in _TAINT_OPS.values():
    taint.register(
        _bit,
        (lambda op: lambda: PredictableValueAnnotation(op))(_op),
        (lambda op: lambda a: isinstance(a, PredictableValueAnnotation)
         and a.operation == op)(_op),
    )


class PredictableVariables(DetectionModule):
    name = "Control flow depends on a predictable environment variable"
    swc_id = f"{TIMESTAMP_DEPENDENCE}.{WEAK_RANDOMNESS}"
    description = DESCRIPTION
    entry_point = EntryPoint.CALLBACK
    pre_hooks = ["JUMPI", "BLOCKHASH"]
    post_hooks = ["BLOCKHASH"] + PREDICTABLE_OPS
    # the post-hooks on the four block-attribute pushes only annotate the
    # result; seeded taint bits on their env rows reproduce that, so the
    # device ships no events for them.  BLOCKHASH stays undeclared: it has
    # a pre-hook too and parks on device anyway.
    taint_source_hooks = {
        op: _TAINT_OPS[op][0] for op in PREDICTABLE_OPS
    }
    # staticpass: issues only exist where a predictable value (BLOCKHASH
    # included — its host hook annotates too) may influence a JUMPI
    static_required_ops = frozenset(_TAINT_OPS)
    static_taint_sources = {op: bit for op, (bit, _) in _TAINT_OPS.items()}
    static_taint_sinks = frozenset({"JUMPI"})

    def _execute(self, state: GlobalState) -> Optional[List[Issue]]:
        if self._cache_key(state) in self.cache:
            return None
        return self._analyze_state(state)

    def _analyze_state(self, state: GlobalState) -> List[Issue]:
        opcode = state.get_current_instruction()["opcode"]

        if opcode != "JUMPI":
            # post hook on a predictable-value op: taint its result
            if state.mstate.stack:
                op = {
                    "COINBASE": "block.coinbase",
                    "GASLIMIT": "block.gaslimit",
                    "TIMESTAMP": "block.timestamp",
                    "NUMBER": "block.number",
                    "BLOCKHASH": "blockhash",
                }.get(opcode, opcode.lower())
                state.mstate.stack[-1].annotate(PredictableValueAnnotation(op))
            return []

        condition = state.mstate.stack[-2]
        annotations = [
            a for a in condition.annotations if isinstance(a, PredictableValueAnnotation)
        ]
        if not annotations:
            return []
        try:
            transaction_sequence = get_transaction_sequence(
                state, state.world_state.constraints.get_all_constraints()
            )
        except UnsatError:
            return []
        # one issue per distinct tainting operation, in sorted order: the
        # reference loops over every annotation on the condition
        # (dependence_on_predictable_vars.py:74-110); sorting makes the set
        # identical whether annotations arrived in host insertion order or
        # were synthesized from device taint bits in ascending-bit order
        # (frontier/taint.annotations_for_mask)
        operations = sorted({a.operation for a in annotations})
        issues = []
        for operation in operations:
            swc_id = (
                WEAK_RANDOMNESS
                if operation in ("block.coinbase", "blockhash")
                else TIMESTAMP_DEPENDENCE
            )
            issues.append(
                Issue(
                    contract=state.environment.active_account.contract_name,
                    function_name=state.node.function_name if state.node else "unknown",
                    address=state.get_current_instruction()["address"],
                    swc_id=swc_id,
                    title="Dependence on predictable environment variable",
                    severity="Low",
                    bytecode=state.environment.code.bytecode,
                    description_head=f"A control flow decision is made based on {operation}.",
                    description_tail=(
                        f"The {operation} environment variable is used to determine a "
                        "control flow decision. Note that the values of variables like "
                        "coinbase, gaslimit, block number and timestamp are predictable "
                        "and can be manipulated by a malicious miner. Also keep in mind "
                        "that attackers know hashes of earlier blocks. Don't use any of "
                        "those environment variables as sources of randomness and be "
                        "aware that use of these variables introduces a certain level "
                        "of trust into miners."
                    ),
                    gas_used=(state.mstate.min_gas_used, state.mstate.max_gas_used),
                    transaction_sequence=transaction_sequence,
                )
            )
        return issues


detector = PredictableVariables
