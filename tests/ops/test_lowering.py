"""Differential test: JAX lowering must agree bit-exactly with concrete_eval.

This is the contract that keeps the device probe path sound: any model the
batched evaluator accepts is re-validated on host, but the filter itself must
be exact or satisfiable candidates would be discarded.
"""

import random

import numpy as np
import pytest

from mythril_tpu.ops import lowering
from mythril_tpu.smt import terms
from mythril_tpu.smt.concrete_eval import ArrayValue, Assignment, evaluate


def _random_assignments(bv_vars, array_vars, rng, n):
    out = []
    for _ in range(n):
        asg = Assignment()
        for v in bv_vars:
            choice = rng.random()
            if choice < 0.25:
                asg.scalars[v] = rng.randint(0, 5)
            elif choice < 0.5:
                asg.scalars[v] = terms.mask(-rng.randint(1, 5), v.width)
            else:
                asg.scalars[v] = rng.getrandbits(v.width)
        for av in array_vars:
            backing = {
                rng.getrandbits(av.sort[1]) % 64: rng.getrandbits(av.sort[2])
                for _ in range(rng.randint(0, 4))
            }
            asg.arrays[av] = ArrayValue(backing, default=rng.getrandbits(8))
        out.append(asg)
    return out


def _check(conjuncts, assignments):
    compiled = lowering.compile_conjunction(conjuncts)
    got = compiled.evaluate_batch(assignments)
    for b, asg in enumerate(assignments):
        vals = evaluate(conjuncts, asg)
        want = [bool(vals[c]) for c in conjuncts]
        assert list(got[b]) == want, f"candidate {b}: {list(got[b])} != {want}"


def test_arithmetic_and_compare_ops():
    rng = random.Random(7)
    x = terms.var("x", 256)
    y = terms.var("y", 256)
    z = terms.var("z", 64)
    conjuncts = [
        terms.eq(terms.add(x, y), terms.const(100, 256)),
        terms.ult(terms.mul(x, terms.const(3, 256)), y),
        terms.eq(terms.udiv(x, y), terms.const(2, 256)),
        terms.eq(terms.sdiv(x, y), terms.const(2, 256)),
        terms.eq(terms.urem(x, terms.const(7, 256)), terms.const(3, 256)),
        terms.eq(terms.srem(x, y), terms.sub(x, y)),
        terms.slt(x, y),
        terms.sle(terms.neg(x), y),
        terms.ule(x, terms.bnot(y)),
        terms.eq(terms.band(x, y), terms.bor(x, y)),
        terms.eq(terms.bxor(x, y), terms.const(0xFF, 256)),
        terms.eq(terms.zext(z, 192), x),
        terms.eq(terms.sext(z, 192), y),
        terms.eq(terms.bvexp(x, terms.const(3, 256)), y),
    ]
    _check(conjuncts, _random_assignments([x, y, z], [], rng, 33))


def test_shift_concat_extract_ops():
    rng = random.Random(11)
    x = terms.var("x", 256)
    s = terms.var("s", 256)
    lo = terms.var("lo", 128)
    conjuncts = [
        terms.eq(terms.shl(x, s), terms.lshr(x, s)),
        terms.eq(terms.ashr(x, s), terms.const(0, 256)),
        terms.eq(terms.extract(31, 0, x), terms.const(0xAB, 32)),
        terms.eq(terms.concat2(terms.extract(255, 128, x), lo), x),
        terms.eq(terms.shl(x, terms.const(300, 256)), terms.const(0, 256)),
    ]
    # include boundary shift amounts explicitly
    asgs = _random_assignments([x, s, lo], [], rng, 17)
    for amt in (0, 1, 15, 16, 255, 256, 257, 1 << 200):
        a = Assignment()
        a.scalars[x] = rng.getrandbits(256)
        a.scalars[s] = amt
        a.scalars[lo] = rng.getrandbits(128)
        asgs.append(a)
    _check(conjuncts, asgs)


def test_bool_ops_and_ite():
    rng = random.Random(13)
    x = terms.var("x", 256)
    y = terms.var("y", 256)
    p = terms.bool_var("p")
    q = terms.bool_var("q")
    conjuncts = [
        terms.land(p, terms.lnot(q)),
        terms.lor(terms.eq(x, y), p),
        terms.lxor(p, q),
        terms.eq(
            terms.ite(p, x, y), terms.ite(q, terms.const(1, 256), terms.const(2, 256))
        ),
        terms.iff(p, terms.ult(x, y)),
    ]
    asgs = _random_assignments([x, y], [], rng, 16)
    for i, a in enumerate(asgs):
        a.scalars[p] = bool(i & 1)
        a.scalars[q] = bool(i & 2)
    _check(conjuncts, asgs)


def test_array_select_store_chains():
    rng = random.Random(17)
    arr = terms.array_var("storage", 256, 256)
    i = terms.var("i", 256)
    v = terms.var("v", 256)
    stored = terms.store(arr, terms.const(5, 256), v)
    stored2 = terms.store(stored, i, terms.const(77, 256))
    conjuncts = [
        terms.eq(terms.select(stored2, terms.const(5, 256)), v),
        terms.eq(terms.select(stored2, i), terms.const(77, 256)),
        terms.eq(terms.select(arr, i), terms.const(0, 256)),
        terms.eq(
            terms.select(terms.const_array(256, 256, terms.const(9, 256)), i),
            terms.const(9, 256),
        ),
    ]
    asgs = _random_assignments([i, v], [arr], rng, 25)
    # force some collisions i == 5
    for a in asgs[::3]:
        a.scalars[i] = 5
    _check(conjuncts, asgs)


def test_keccak_lowering():
    rng = random.Random(19)
    x = terms.var("x", 256)
    h = terms.keccak(x)
    conjuncts = [terms.eq(terms.extract(15, 0, h), terms.const(0x1234, 16))]
    _check(conjuncts, _random_assignments([x], [], rng, 6))


def test_apply_raises_unsupported():
    x = terms.var("x", 256)
    f = terms.apply_func("power", 256, x)
    with pytest.raises(lowering.LoweringUnsupported):
        lowering.compile_conjunction([terms.eq(f, x)])


def test_compile_cache_returns_same_object():
    x = terms.var("x", 256)
    c = [terms.ult(x, terms.const(10, 256))]
    assert lowering.compile_cached(c) is lowering.compile_cached(c)
