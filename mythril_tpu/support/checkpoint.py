"""Frontier checkpoint/resume between transactions.

The reference has no checkpoint/restart (SURVEY.md §5.4) — its closest
analogue is the ``open_states`` world-state snapshot list carried between
transactions (reference svm.py:306-315).  This module makes that snapshot
durable: after each symbolic transaction the surviving open world states are
serialized (accounts, storage/balance term DAGs, path constraints, and the
transaction records exploit reporting needs) so an interrupted multi-
transaction analysis resumes at the last completed transaction boundary
instead of restarting.  The same format is the DCN shipping unit for
multi-host corpus sharding.

Scope notes: state annotations (pruner bookkeeping) are intentionally NOT
persisted — they are performance hints, and resuming without them is sound
(pruners rebuild their caches); dynamic-loader bindings are re-attached by
the resuming process.
"""

from __future__ import annotations

import json
import os
from typing import List, Optional, Tuple

from mythril_tpu.smt import Array, Bool, symbol_factory
from mythril_tpu.smt.serialize import dump_terms, load_terms

FORMAT_VERSION = 1


# ---------------------------------------------------------------------------
# World-state <-> dict
# ---------------------------------------------------------------------------


def _dump_world_state(ws) -> dict:
    """Collect every term the state depends on into ONE dump (shared DAG)."""
    roots = [ws.balances.raw, ws.starting_balances.raw]
    constraint_base = len(roots)
    roots.extend(c.raw if hasattr(c, "raw") else c for c in ws.constraints)

    accounts = []
    for addr, acct in ws.accounts.items():
        accounts.append(
            {
                "address": addr,
                "nonce": acct.nonce,
                "contract_name": acct.contract_name,
                "code": acct.code.bytecode.hex() if acct.code is not None else None,
                "storage_concrete": acct.storage.concrete,
                "storage_root": len(roots),
            }
        )
        roots.append(acct.storage._array.raw)

    txs = []
    for tx in ws.transaction_sequence:
        txs.append(_dump_transaction(tx, roots))

    return {
        "terms": dump_terms(roots),
        "n_constraints": len(ws.constraints),
        "constraint_base": constraint_base,
        "accounts": accounts,
        "transactions": txs,
    }


def _dump_transaction(tx, roots: List) -> dict:
    from mythril_tpu.core.state.calldata import ConcreteCalldata
    from mythril_tpu.core.transaction.transaction_models import (
        ContractCreationTransaction,
    )

    def term_ref(wrapped) -> int:
        roots.append(wrapped.raw if hasattr(wrapped, "raw") else wrapped)
        return len(roots) - 1

    record = {
        "kind": (
            "creation" if isinstance(tx, ContractCreationTransaction) else "call"
        ),
        "id": tx.id,
        "gas_limit": tx.gas_limit if isinstance(tx.gas_limit, int) else None,
        "origin": term_ref(tx.origin),
        "caller": term_ref(tx.caller),
        "gas_price": term_ref(tx.gas_price),
        "call_value": term_ref(tx.call_value),
        "static": tx.static,
        "callee_address": (
            tx.callee_account.address.value
            if tx.callee_account is not None
            and tx.callee_account.address.value is not None
            else None
        ),
        "code": tx.code.bytecode.hex() if getattr(tx, "code", None) else None,
    }
    if isinstance(tx.call_data, ConcreteCalldata):
        record["calldata"] = list(tx.call_data.concrete(None))
    else:
        record["calldata"] = None  # symbolic: rebuilt from the tx id
    return record


def _load_world_state(data: dict, dynamic_loader=None):
    from mythril_tpu.core.state.account import Account, Storage
    from mythril_tpu.core.state.world_state import WorldState

    roots = load_terms(data["terms"])
    ws = WorldState()
    ws.balances.raw = roots[0]
    ws.starting_balances.raw = roots[1]
    base = data["constraint_base"]
    for i in range(data["n_constraints"]):
        ws.constraints.append(Bool(roots[base + i]))

    from mythril_tpu.frontend.disassembler import Disassembly

    for rec in data["accounts"]:
        acct = Account(
            rec["address"],
            code=Disassembly(rec["code"]) if rec["code"] else None,
            contract_name=rec["contract_name"],
            balances=ws.balances,
            concrete_storage=False,
            dynamic_loader=dynamic_loader,
            nonce=rec["nonce"],
        )
        acct.storage.concrete = rec["storage_concrete"]
        acct.storage._array.raw = roots[rec["storage_root"]]
        ws.put_account(acct)

    ws.transaction_sequence = [
        _load_transaction(rec, ws, roots) for rec in data["transactions"]
    ]
    return ws


def _load_transaction(rec: dict, ws, roots):
    from mythril_tpu.core.state.calldata import ConcreteCalldata, SymbolicCalldata
    from mythril_tpu.core.transaction.transaction_models import (
        ContractCreationTransaction,
        MessageCallTransaction,
        tx_id_manager,
    )

    tx_id_manager.ensure_above(rec["id"])
    from mythril_tpu.frontend.disassembler import Disassembly
    from mythril_tpu.smt import BitVec

    def term_at(i: int) -> BitVec:
        return BitVec(roots[i])

    callee = ws[rec["callee_address"]] if rec["callee_address"] is not None else None
    calldata = (
        ConcreteCalldata(rec["id"], rec["calldata"])
        if rec["calldata"] is not None
        else SymbolicCalldata(rec["id"])
    )
    cls = (
        ContractCreationTransaction if rec["kind"] == "creation" else MessageCallTransaction
    )
    tx = cls.__new__(cls)
    tx.world_state = ws
    tx.id = rec["id"]
    tx.gas_limit = rec["gas_limit"] if rec["gas_limit"] is not None else 8_000_000
    tx.origin = term_at(rec["origin"])
    tx.caller = term_at(rec["caller"])
    tx.gas_price = term_at(rec["gas_price"])
    tx.base_fee = symbol_factory.BitVecSym(f"{tx.id}_basefee", 256)
    tx.call_value = term_at(rec["call_value"])
    tx.static = rec["static"]
    tx.callee_account = callee
    tx.call_data = calldata
    tx.code = Disassembly(rec["code"]) if rec["code"] else None
    tx.return_data = None
    if rec["kind"] == "creation":
        # exploit reporting reconstructs the pre-state from here
        # (analysis/solver.py); the initial creation's pre-state is empty
        from mythril_tpu.core.state.world_state import WorldState

        tx.prev_world_state = WorldState()
    return tx


# ---------------------------------------------------------------------------
# Checkpoint files
# ---------------------------------------------------------------------------


def save_checkpoint(
    path: str,
    completed_transactions: int,
    open_states: List,
    target_address: Optional[int] = None,
    shard: int = 0,
) -> None:
    """Atomically write one frontier snapshot."""
    payload = {
        "version": FORMAT_VERSION,
        "shard": shard,
        "completed_transactions": completed_transactions,
        "target_address": target_address,
        "open_states": [_dump_world_state(ws) for ws in open_states],
    }
    tmp = f"{path}.tmp"
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(tmp, "w") as fh:
        json.dump(payload, fh)
    os.replace(tmp, path)


def load_checkpoint(
    path: str, dynamic_loader=None
) -> Tuple[int, List, Optional[int]]:
    """Read a snapshot -> (completed txs, open world states, target addr)."""
    with open(path) as fh:
        payload = json.load(fh)
    if payload.get("version") != FORMAT_VERSION:
        raise ValueError(
            f"unsupported checkpoint version {payload.get('version')}"
        )
    states = [
        _load_world_state(d, dynamic_loader) for d in payload["open_states"]
    ]
    return payload["completed_transactions"], states, payload.get("target_address")
