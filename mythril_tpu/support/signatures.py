"""4-byte function-selector -> signature database.

Reference parity: mythril/support/signatures.py:15-80 — sqlite-backed, with a
built-in seed table of common signatures; the optional 4byte.directory online
lookup is gated off (zero-egress environment) but the hook is kept.
"""

from __future__ import annotations

import os
import sqlite3
import threading
from typing import List, Optional

from mythril_tpu.ops.keccak import keccak256

_COMMON_SIGNATURES = [
    "transfer(address,uint256)",
    "transferFrom(address,address,uint256)",
    "approve(address,uint256)",
    "balanceOf(address)",
    "totalSupply()",
    "allowance(address,address)",
    "owner()",
    "name()",
    "symbol()",
    "decimals()",
    "mint(address,uint256)",
    "burn(uint256)",
    "withdraw()",
    "withdraw(uint256)",
    "deposit()",
    "kill()",
    "killbilly()",
    "selfdestruct()",
    "destroy()",
    "close()",
    "fallback()",
    "owner_changed(address)",
    "setOwner(address)",
    "transferOwnership(address)",
    "pause()",
    "unpause()",
    "batchTransfer(address[],uint256)",
    "collectAllocations()",
    "allocate(address,uint256)",
    "depositFunds()",
    "withdrawFunds(uint256)",
]


def selector_of(signature: str) -> str:
    return "0x" + keccak256(signature.encode()).hex()[:8]


class SignatureDB:
    """Thread-safe sqlite selector DB with in-memory fallback."""

    _lock = threading.RLock()
    _instance = None

    def __new__(cls, enable_online_lookup: bool = False, path: Optional[str] = None):
        with cls._lock:
            if cls._instance is None:
                inst = super().__new__(cls)
                inst._init(enable_online_lookup, path)
                cls._instance = inst
            return cls._instance

    def _init(self, enable_online_lookup: bool, path: Optional[str]):
        self.enable_online_lookup = enable_online_lookup
        self.path = path or os.path.join(
            os.path.expanduser("~"), ".mythril_tpu", "signatures.db"
        )
        os.makedirs(os.path.dirname(self.path), exist_ok=True)
        self.conn = sqlite3.connect(self.path, check_same_thread=False)
        self.conn.execute(
            "CREATE TABLE IF NOT EXISTS signatures "
            "(byte_sig VARCHAR(10), text_sig VARCHAR(255), "
            "PRIMARY KEY (byte_sig, text_sig))"
        )
        for sig in _COMMON_SIGNATURES:
            self.add(selector_of(sig), sig)
        self.conn.commit()

    def add(self, byte_sig: str, text_sig: str) -> None:
        with SignatureDB._lock:
            self.conn.execute(
                "INSERT OR IGNORE INTO signatures (byte_sig, text_sig) VALUES (?, ?)",
                (byte_sig, text_sig),
            )

    def get(self, byte_sig: str) -> List[str]:
        with SignatureDB._lock:
            rows = self.conn.execute(
                "SELECT text_sig FROM signatures WHERE byte_sig = ?", (byte_sig,)
            ).fetchall()
        return [r[0] for r in rows]

    def import_solidity_file(self, file_path: str) -> None:
        """Harvest ``function x(...)`` signatures from a .sol source file."""
        import re

        with open(file_path) as f:
            src = f.read()
        for m in re.finditer(r"function\s+(\w+)\s*\(([^)]*)\)", src):
            name, params = m.group(1), m.group(2)
            types = []
            for p in params.split(","):
                p = p.strip()
                if not p:
                    continue
                t = p.split()[0]
                t = {"uint": "uint256", "int": "int256", "byte": "bytes1"}.get(t, t)
                types.append(t)
            sig = f"{name}({','.join(types)})"
            self.add(selector_of(sig), sig)
        self.conn.commit()
