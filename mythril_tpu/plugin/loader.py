"""Routing loader for discovered plugins.

Reference parity: mythril/plugin/loader.py:21-90 — validates the plugin type
and dispatches to the matching subsystem: detection modules go to the
analysis ModuleLoader, engine plugins to the laser LaserPluginLoader.
"""

from __future__ import annotations

import logging
from typing import Dict, List

from mythril_tpu.analysis.module.base import DetectionModule
from mythril_tpu.analysis.module.loader import ModuleLoader
from mythril_tpu.plugin.discovery import PluginDiscovery
from mythril_tpu.plugin.interface import MythrilLaserPlugin, MythrilPlugin
from mythril_tpu.plugins.loader import LaserPluginLoader
from mythril_tpu.support.support_utils import Singleton

log = logging.getLogger(__name__)


class UnsupportedPluginType(Exception):
    """Raised when a discovered plugin matches no loadable interface."""


class MythrilPluginLoader(metaclass=Singleton):
    """Loads discovered plugins into the right subsystem."""

    def __init__(self):
        self.loaded_plugins: List[MythrilPlugin] = []
        self.plugin_args: Dict[str, Dict] = {}
        self._load_default_enabled()

    def set_args(self, plugin_name: str, **kwargs) -> None:
        self.plugin_args[plugin_name] = kwargs

    def load(self, plugin: MythrilPlugin) -> None:
        if not isinstance(plugin, MythrilPlugin):
            raise ValueError("passed plugin is not a MythrilPlugin")
        log.info("loading plugin: %s", plugin)
        if isinstance(plugin, DetectionModule):
            ModuleLoader().register_module(plugin)
        elif isinstance(plugin, MythrilLaserPlugin):
            LaserPluginLoader().load(plugin)
        else:
            raise UnsupportedPluginType(
                f"plugin type of {plugin!r} is not supported"
            )
        self.loaded_plugins.append(plugin)

    def _load_default_enabled(self) -> None:
        for name in PluginDiscovery().get_plugins(default_enabled=True):
            try:
                plugin = PluginDiscovery().build_plugin(
                    name, self.plugin_args.get(name, {})
                )
                self.load(plugin)
            except Exception as e:
                log.warning("could not load plugin %s: %s", name, e)
