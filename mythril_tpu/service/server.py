"""JSON-lines TCP front end for the analysis service (``myth serve``).

Deliberately thin: one request line in, a stream of event lines out —
the protocol mirrors the in-process ``ResultStream`` one-to-one so the
daemon, not the transport, owns ordering and isolation.

Protocol (UTF-8, one JSON object per line):

    -> {"op": "submit", "code": "<hex>", "name": "...", "tier": "batch"}
    <- {"event": "accepted", "request_id": "...", "deduped": false}
    <- {"event": "issue", "swc_id": "106", ...}          (0..n, as they confirm)
    <- {"event": "done", "issues": [...], "elapsed_s": 1.2}
  or <- {"event": "error", "error": "..."}

    -> {"op": "ping"}    <- {"event": "pong"}
    -> {"op": "stats"}   <- {"event": "stats", ...counters...}
    -> {"op": "metrics"} <- {"event": "metrics", "text": "<prometheus>"}
    -> {"op": "profile", "worker": 0, "duration_s": 1.0}
    <- {"event": "profile", "ok": true, "dir": "...", "worker": 0}

``metrics`` concatenates the daemon-local registry with the fleet's
worker-labeled series (``fleet_*{worker="N"}`` plus unlabeled rollups)
when a worker pool is running, so one scrape is pool-wide truth.
``profile`` opens a windowed ``jax.profiler`` capture inside the chosen
worker (the daemon process in inline mode) and blocks until the window
closes; the capture directory lands under ``--cache-root``.

``submit`` also accepts an optional ``"tenant"`` label for per-tenant
accounting and ``"detach": true`` — the handler then answers with the
``accepted`` line only and returns the connection, instead of holding a
handler thread open for the whole analysis.  A detached client follows
up over fresh connections with the long-poll op:

    -> {"op": "poll", "request_id": "...", "cursor": 0, "wait_s": 10}
    <- {"event": "poll", "events": [{"kind": ..., "payload": ...}],
        "cursor": 3, "closed": false}

which blocks server-side at most ``wait_s`` for the first event past
``cursor`` — an idle subscriber holds no worker and no thread between
polls.  A submission refused by scheduling policy (tenant quota, load
shed) answers ``{"event": "error", "error": ..., "rejected":
"quota"|"shed"}`` immediately.

``metrics`` returns the full registry in the Prometheus text exposition
format (``content_type`` names the version) so one sidecar bridge can
serve it over HTTP unmodified.

``run_server`` installs SIGTERM/SIGINT handlers that stop accepting,
drain every in-flight request (subscribers still receive their streamed
issues and terminal events), then exit — the graceful-shutdown contract
a deployment's rolling restart relies on.
"""

from __future__ import annotations

import json
import logging
import signal
import socket
import socketserver
import threading
from typing import Optional, Tuple

from mythril_tpu.service.daemon import AnalysisService, ServiceConfig
from mythril_tpu.service.request import AnalysisOptions

log = logging.getLogger(__name__)

__all__ = ["AnalysisServer", "run_server"]

#: bound on one request line (code is hex: 2 chars/byte; EVM contracts
#: cap at 24KiB runtime, so 1MiB is generous headroom for options)
MAX_LINE = 1 << 20


class _Handler(socketserver.StreamRequestHandler):
    def handle(self) -> None:
        service: AnalysisService = self.server.service  # type: ignore[attr-defined]
        try:
            line = self.rfile.readline(MAX_LINE)
            if not line:
                return
            try:
                msg = json.loads(line)
            except ValueError:
                self._send({"event": "error", "error": "malformed JSON"})
                return
            op = msg.get("op")
            if op == "ping":
                self._send({"event": "pong"})
            elif op == "stats":
                self._send({"event": "stats", **service.stats()})
            elif op == "health":
                self._send({"event": "health", **service.health()})
            elif op == "metrics":
                from mythril_tpu.observability.metrics import prometheus_text

                # daemon-local registry first, then the fleet rollup of
                # worker-labeled series (empty string in inline mode)
                self._send({
                    "event": "metrics",
                    "content_type": "text/plain; version=0.0.4",
                    "text": prometheus_text()
                    + service.fleet_prometheus_text(),
                })
            elif op == "profile":
                self._send({
                    "event": "profile",
                    **service.profile(
                        worker_id=int(msg.get("worker", 0)),
                        duration_s=float(msg.get("duration_s", 1.0)),
                    ),
                })
            elif op == "submit":
                self._submit(service, msg)
            elif op == "poll":
                self._poll(service, msg)
            else:
                self._send({"event": "error", "error": f"unknown op {op!r}"})
        except (BrokenPipeError, ConnectionResetError):
            pass  # client went away; the flight finishes for other subscribers

    def _submit(self, service: AnalysisService, msg: dict) -> None:
        try:
            options = None
            if any(k in msg for k in (
                "transaction_count", "modules", "strategy",
                "execution_timeout", "coverage_target",
            )):
                base = service.config.default_options
                raw_target = msg.get("coverage_target", base.coverage_target)
                options = AnalysisOptions(
                    transaction_count=int(
                        msg.get("transaction_count", base.transaction_count)
                    ),
                    modules=tuple(msg["modules"]) if msg.get("modules")
                    else base.modules,
                    strategy=msg.get("strategy", base.strategy),
                    execution_timeout=int(
                        msg.get("execution_timeout", base.execution_timeout)
                    ),
                    coverage_target=float(raw_target)
                    if raw_target is not None else None,
                )
            request, stream, deduped = service.submit(
                msg.get("code", ""),
                name=msg.get("name"),
                tier=msg.get("tier", "batch"),
                options=options,
                tenant=msg.get("tenant"),
            )
        except (ValueError, RuntimeError) as exc:
            err = {"event": "error", "error": str(exc)}
            kind = getattr(exc, "kind", None)
            if kind is not None:  # AdmissionRejected: quota | shed
                err["rejected"] = kind
            self._send(err)
            return
        self._send({
            "event": "accepted",
            "request_id": request.request_id,
            "codehash": request.codehash,
            "deduped": deduped,
        })
        if msg.get("detach"):
            return  # client follows up via {"op": "poll"}
        for kind, payload in stream.events():
            if kind == "issue":
                self._send({"event": "issue", **payload})
            elif kind == "error":
                self._send({"event": "error", "error": payload})
            else:
                self._send({"event": "done", **payload})

    def _poll(self, service: AnalysisService, msg: dict) -> None:
        try:
            out = service.poll(
                str(msg.get("request_id", "")),
                cursor=int(msg.get("cursor", 0)),
                wait_s=float(msg.get("wait_s", 0.0)),
            )
        except KeyError as exc:
            self._send({"event": "error", "error": str(exc)})
            return
        self._send({
            "event": "poll",
            "events": [
                {"kind": kind, "payload": payload}
                for kind, payload in out["events"]
            ],
            "cursor": out["cursor"],
            "closed": out["closed"],
        })

    def _send(self, obj: dict) -> None:
        self.wfile.write((json.dumps(obj) + "\n").encode())
        self.wfile.flush()


class _TCPServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True  # handler threads must not block process exit


class AnalysisServer:
    """Socket server + service lifecycle, embeddable in tests."""

    def __init__(
        self,
        config: Optional[ServiceConfig] = None,
        host: str = "127.0.0.1",
        port: int = 0,
    ):
        self.service = AnalysisService(config)
        self._tcp = _TCPServer((host, port), _Handler)
        self._tcp.service = self.service  # type: ignore[attr-defined]
        self._serve_thread: Optional[threading.Thread] = None

    @property
    def address(self) -> Tuple[str, int]:
        return self._tcp.server_address[:2]

    def start(self) -> "AnalysisServer":
        self.service.start()
        self._serve_thread = threading.Thread(
            target=self._tcp.serve_forever, name="service-accept", daemon=True
        )
        self._serve_thread.start()
        log.info("analysis service listening on %s:%d", *self.address)
        return self

    def stop(self, drain: bool = True, timeout: Optional[float] = None) -> bool:
        """Stop accepting, drain in-flight work, close the socket."""
        drained = self.service.stop(drain=drain, timeout=timeout)
        self._tcp.shutdown()
        self._tcp.server_close()
        t = self._serve_thread
        if t is not None and t.is_alive():
            t.join(timeout=5.0)
        self._serve_thread = None
        return drained


def run_server(
    config: Optional[ServiceConfig] = None,
    host: str = "127.0.0.1",
    port: int = 7344,
    drain_timeout: Optional[float] = None,
) -> int:
    """Blocking entry point for ``myth serve``; returns an exit code.

    SIGTERM/SIGINT trigger a graceful drain: no new submissions, every
    in-flight flight runs to its terminal event, then the socket closes.
    """
    server = AnalysisServer(config, host=host, port=port).start()
    stop = threading.Event()

    def _on_signal(signum, _frame):
        log.info("signal %d: draining analysis service", signum)
        stop.set()

    prev = {
        sig: signal.signal(sig, _on_signal)
        for sig in (signal.SIGTERM, signal.SIGINT)
    }
    try:
        print(f"analysis service listening on {server.address[0]}:"
              f"{server.address[1]}", flush=True)
        stop.wait()
        drained = server.stop(drain=True, timeout=drain_timeout)
        return 0 if drained else 1
    finally:
        for sig, handler in prev.items():
            signal.signal(sig, handler)


def wait_for_server(host: str, port: int, timeout: float = 30.0) -> bool:
    """Poll until the server accepts connections (CI smoke helper)."""
    import time as _time

    deadline = _time.time() + timeout
    while _time.time() < deadline:
        try:
            with socket.create_connection((host, port), timeout=1.0):
                return True
        except OSError:
            _time.sleep(0.1)
    return False
