"""Host-side path lineage: the fork tree behind the device batch.

Slots in the device batch are recycled, so the host keeps one ``PathRecord``
per logical path: its parent link (which event in the parent's stream forked
it), its own accumulated event rows, and — once the path halts — a snapshot
of its final device state.  This is the host half of the fork bookkeeping the
reference does implicitly with Python object identity
(mythril/laser/ethereum/svm.py:296 work_list of forked GlobalStates).
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np


class PathRecord:
    __slots__ = (
        "seed_idx",
        "parent",
        "fork_event_idx",
        "events",
        "final",
        "dead",
        "carrier",
        "carrier_pos",
        "children_by_event",
        "_pruned_at",
        "_submitted_at",
        "steps_seen",
        "_replay_err",
        "term_class",
    )

    def __init__(self, seed_idx: int, parent: Optional["PathRecord"] = None,
                 fork_event_idx: int = -1):
        self.seed_idx = seed_idx
        self.parent = parent
        self.fork_event_idx = fork_event_idx
        self.events: List[np.ndarray] = []
        self.final: Optional[dict] = None  # device-state snapshot at halt
        self.dead = False  # killed by a PluginSkipState / dead branch
        self.carrier = None  # host GlobalState advanced to carrier_pos
        self.carrier_pos = 0  # events processed so far
        self.children_by_event: Dict[int, "PathRecord"] = {}
        self._pruned_at = 0  # constraint count last proven satisfiable
        self._submitted_at = 0  # constraint count last sent to the pool
        self.steps_seen = 0  # device step count already attributed
        self._replay_err = None  # exception captured by a replay worker
        # exploration-ledger termination class, stamped exactly once when
        # the path stops exploring (observability/exploration.TERM_CLASSES);
        # None while the path lives or when it continues host-side
        self.term_class: Optional[str] = None


def snapshot_slot(st, slot: int) -> dict:
    """Copy the per-slot device state (numpy mirror) for final processing."""
    # carrier storage/constraints are rebuilt from event replay (code.py
    # _ALWAYS_EVENT); memory is NOT — most MSTOREs ship no event, so the
    # word table rides the snapshot and walker._restore_memory writes it
    # into the carrier before the terminal replay / park resume
    mem_len = int(st.mem_len[slot])
    return {
        "halt": int(st.halt[slot]),
        "pc": int(st.pc[slot]),
        "stack": st.stack[slot, : int(st.stack_len[slot])].copy(),
        "gas_min": int(st.gas_min[slot]),
        "gas_max": int(st.gas_max[slot]),
        "depth": int(st.depth[slot]),
        "mem_size": int(st.mem_size[slot]),
        "mem": list(
            zip(
                st.mem_addr[slot, :mem_len].tolist(),
                st.mem_val[slot, :mem_len].tolist(),
            )
        ),
    }
