"""Device-mesh parallelism for the probe solver and frontier search.

The reference is a single-threaded CPU tool (SURVEY.md §2.8); its only
parallelism is Z3-internal.  Here, scaling is an explicit subsystem built the
TPU way: a 2-D ``jax.sharding.Mesh`` over which the probe workload is SPMD —
independent frontier paths shard over the ``path`` axis (data parallelism)
and the candidate-assignment batch of each path shards over the ``cand``
axis; XLA inserts the ICI collectives for the cross-device score reductions.
"""

from mythril_tpu.parallel.corpus import run_corpus, shard_corpus, shard_identity
from mythril_tpu.parallel.mesh import (
    CAND_AXIS,
    PATH_AXIS,
    make_frontier_mesh,
    shard_frontier_inputs,
    shard_probe_args,
)
from mythril_tpu.parallel.probe import (
    evaluate_batch_sharded,
    frontier_step,
    pack_frontier,
)

__all__ = [
    "run_corpus",
    "shard_corpus",
    "shard_identity",
    "CAND_AXIS",
    "PATH_AXIS",
    "make_frontier_mesh",
    "shard_frontier_inputs",
    "shard_probe_args",
    "evaluate_batch_sharded",
    "frontier_step",
    "pack_frontier",
]
