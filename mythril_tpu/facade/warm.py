"""Warm-process facade: reusable flag propagation + per-request scoping.

``MythrilAnalyzer`` was written for one-shot processes: its constructor
copies CLI args into the global flag object, arms the caches, and the
process exits after one report.  The analysis service needs exactly that
propagation WITHOUT constructing an analyzer per request — the process
stays warm and only the per-request telemetry/detector scope resets
between batches.  This module is the shared half:

* ``apply_analyzer_args`` — the flag-propagation block, factored out of
  ``MythrilAnalyzer.__init__`` so the daemon and the one-shot facade run
  the identical configuration path (including ``--cache-root``
  derivation and cache arming).
* ``resolve_cache_root`` — one directory pins both persistent caches.
* ``reset_analysis_scope`` — the scope sweep that makes each service
  batch behave like a fresh process: non-persistent metrics, detector
  issue lists, and the process-wide (address, bytecode_hash) detection
  caches are cleared; the SMT query cache, interned terms, and compiled
  XLA programs deliberately stay warm (their reuse is sound by
  construction — validated hits only).
* ``WorkerContext`` — the explicit owner of one worker's engine-global
  lifecycle.  The engine keeps genuinely process-global state (the flag
  singleton, the ``module/base`` issue sink, ``smt/terms`` interning),
  which is what confined the daemon to a single worker thread; this
  class names that state and scopes every touch of it, so a worker —
  the daemon's in-process thread or a pool worker *process* — is "the
  thing that owns a WorkerContext".  Process isolation then makes N
  contexts coexist: one per worker process, none shared.
"""

from __future__ import annotations

import contextlib
import os
from typing import Any, Dict, Optional, Tuple

from mythril_tpu.support.support_args import args

__all__ = [
    "WorkerContext",
    "apply_analyzer_args",
    "reset_analysis_scope",
    "resolve_cache_root",
]


def resolve_cache_root(cache_root: Optional[str]) -> Tuple[Optional[str], Optional[str]]:
    """Map ``--cache-root DIR`` to ``(query_cache_dir, compile_cache_dir)``.

    One directory configures all service persistence: the SMT query
    cache lands under ``DIR/querycache`` and the XLA compilation cache
    under ``DIR/xla``.  Explicit ``--query-cache-dir`` /
    ``--compile-cache-dir`` flags win over the derived paths.
    """
    if not cache_root:
        return None, None
    root = os.path.abspath(os.path.expanduser(cache_root))
    return os.path.join(root, "querycache"), os.path.join(root, "xla")


def apply_analyzer_args(cmd_args) -> None:
    """Propagate facade args onto the global flag object and arm caches.

    Mirrors the reference's copy-into-singleton pattern
    (mythril/mythril/mythril_analyzer.py:63-70); shared by the one-shot
    ``MythrilAnalyzer`` and the long-lived ``service.AnalysisService``
    so both configure the engine identically.
    """
    args.solver_timeout = cmd_args.solver_timeout
    args.execution_timeout = cmd_args.execution_timeout
    args.create_timeout = cmd_args.create_timeout
    args.max_depth = cmd_args.max_depth
    args.call_depth_limit = cmd_args.call_depth_limit
    args.loop_bound = cmd_args.loop_bound
    args.transaction_count = cmd_args.transaction_count
    args.unconstrained_storage = cmd_args.unconstrained_storage
    args.sparse_pruning = cmd_args.sparse_pruning
    args.parallel_solving = cmd_args.parallel_solving
    args.solver_log = cmd_args.solver_log
    args.enable_iprof = cmd_args.enable_iprof
    args.benchmark_path = getattr(cmd_args, "benchmark_path", None)
    args.checkpoint_path = getattr(cmd_args, "checkpoint_file", None)
    args.resume_from = getattr(cmd_args, "resume_from", None)
    args.probe_backend = getattr(cmd_args, "probe_backend", "auto")
    if args.probe_backend == "cdcl":
        # forced-exact mode without the native solver would answer every
        # query UNKNOWN and silently prune the whole state space
        from mythril_tpu.native import bitblast

        if not bitblast.available():
            raise RuntimeError(
                "--probe-backend cdcl requires the native CDCL solver "
                "(mythril_tpu/native); it is not available in this build"
            )
    args.frontier = getattr(cmd_args, "frontier", False)
    args.frontier_width = getattr(cmd_args, "frontier_width", 64)
    args.frontier_force = getattr(cmd_args, "frontier_force", False)
    args.query_cache = getattr(cmd_args, "query_cache", True)
    args.staticpass = getattr(cmd_args, "staticpass", True)
    args.staticpass_interproc = getattr(
        cmd_args, "staticpass_interproc", True
    )
    args.code_paging = getattr(cmd_args, "code_paging", True)
    args.code_page_budget = getattr(cmd_args, "code_page_budget", 2048)
    args.pipeline = getattr(cmd_args, "pipeline", True)
    args.prefilter = getattr(cmd_args, "prefilter", True)
    args.devsolver = getattr(cmd_args, "devsolver", True)
    args.devsolver_bit_budget = getattr(cmd_args, "devsolver_bit_budget", 64)
    args.devsolver_iters = getattr(cmd_args, "devsolver_iters", 2048)
    from mythril_tpu import devsolver as _devsolver

    _devsolver.configure(bit_budget=args.devsolver_bit_budget,
                         iters=args.devsolver_iters)
    args.frontier_mesh = getattr(cmd_args, "frontier_mesh", True)
    args.adaptive = getattr(cmd_args, "adaptive", True)
    args.coverage_target = getattr(cmd_args, "coverage_target", None)
    args.solver_workers = getattr(cmd_args, "solver_workers", 2)
    args.harvest_workers = getattr(cmd_args, "harvest_workers", 4)
    args.heartbeat_out = getattr(cmd_args, "heartbeat_out", None)
    args.heartbeat_interval = getattr(cmd_args, "heartbeat_interval", 0.5)
    args.flight_recorder = getattr(cmd_args, "flight_recorder", None)
    args.history_dir = getattr(cmd_args, "history_dir", None)
    args.watchdog_deadline = getattr(cmd_args, "watchdog_deadline", None)
    # --cache-root pins both persistent caches under one directory;
    # explicit per-cache flags win over the derived paths
    args.cache_root = getattr(cmd_args, "cache_root", None)
    derived_qc, derived_xla = resolve_cache_root(args.cache_root)
    args.query_cache_dir = (
        getattr(cmd_args, "query_cache_dir", None) or derived_qc
    )
    args.compile_cache_dir = (
        getattr(cmd_args, "compile_cache_dir", None) or derived_xla
    )
    from mythril_tpu.querycache import configure as _configure_query_cache

    _configure_query_cache(
        enabled=args.query_cache, cache_dir=args.query_cache_dir
    )
    if args.compile_cache_dir:
        from mythril_tpu import enable_persistent_compilation_cache

        enable_persistent_compilation_cache(args.compile_cache_dir)


class WorkerContext:
    """Explicit per-worker handle on the engine's process-global state.

    One worker (the daemon's inline thread, or one pool worker process)
    constructs exactly one context and routes every engine-global touch
    through it: flag-object configuration (``configure``), the per-batch
    scope sweep (``reset_scope``), issue-sink installation
    (``sink_scope``), the host-probe flag flip (``probe_scope``) and
    abstract-pre-filter accounting (``prefilter_delta``).  Nothing here
    is thread-safe by design — the context IS the single-ownership
    contract the old implicit globals only implied.
    """

    def __init__(self, analyzer_args):
        #: the AnalyzerArgs-shaped namespace this worker was armed with
        self.analyzer_args = analyzer_args
        self.configured = False

    def configure(self) -> "WorkerContext":
        """Arm the global flag object + caches for this worker process."""
        apply_analyzer_args(self.analyzer_args)
        self.configured = True
        return self

    def reset_scope(self) -> None:
        """Per-batch sweep: next analysis behaves like a fresh process."""
        reset_analysis_scope()

    def sink_scope(self, sink):
        """Install an issue sink for the scope of one analysis."""
        from mythril_tpu.analysis.module.base import issue_sink_scope

        return issue_sink_scope(sink)

    @contextlib.contextmanager
    def probe_scope(self):
        """Host-first probe configuration: frontier off, host probe
        backend — restored on exit.  (The probe's tighter execution
        timeout travels as an explicit ``run_cooperative_batch``
        argument, not through the flag object.)"""
        saved = (args.frontier, args.probe_backend)
        args.frontier = False
        args.probe_backend = "host"
        try:
            yield
        finally:
            args.frontier, args.probe_backend = saved

    @contextlib.contextmanager
    def prefilter_delta(self, out: Dict[str, int]):
        """Measure this scope's abstract pre-filter activity into ``out``
        (keys ``evaluated``/``killed``) — the scoped counters reset per
        batch, so callers that outlive the batch need the delta."""
        from mythril_tpu.observability.metrics import get_registry

        reg = get_registry()
        e0 = reg.counter("prefilter.evaluated").value
        k0 = reg.counter("prefilter.killed").value
        try:
            yield out
        finally:
            out["evaluated"] = out.get("evaluated", 0) + max(
                reg.counter("prefilter.evaluated").value - e0, 0
            )
            out["killed"] = out.get("killed", 0) + max(
                reg.counter("prefilter.killed").value - k0, 0
            )

    @contextlib.contextmanager
    def devsolver_delta(self, out: Dict[str, int]):
        """Measure this scope's device-SAT-tier activity into ``out``
        (keys ``admitted``/``decided_sat``/``decided_unsat``/``unknown``/
        ``model_validation_failures``) — scoped counters reset per batch,
        same contract as ``prefilter_delta``."""
        from mythril_tpu.observability.metrics import get_registry

        reg = get_registry()
        names = ("admitted", "decided_sat", "decided_unsat", "unknown",
                 "model_validation_failures")
        base = {n: reg.counter("devsolver." + n).value for n in names}
        try:
            yield out
        finally:
            for n in names:
                out[n] = out.get(n, 0) + max(
                    reg.counter("devsolver." + n).value - base[n], 0
                )

    @contextlib.contextmanager
    def exploration_delta(self, out: Dict[str, Any]):
        """Measure this scope's exploration-ledger activity into ``out``:
        per-class terminated-path deltas (``terminated`` dict +
        ``terminated_total``), ``pc_overflow``, and the scope-end
        per-contract ``coverage_pct``.  Like ``prefilter_delta``, the
        ledger resets per analysis scope, so callers that outlive the
        batch (the daemon's persistent mirrors, pool-worker done
        payloads) need the delta."""
        from mythril_tpu.observability.exploration import (
            get_exploration_ledger,
        )

        led = get_exploration_ledger()
        t0 = led.terminated()
        o0 = led.pc_overflow
        try:
            yield out
        finally:
            t1 = led.terminated()
            term = out.setdefault("terminated", {})
            for cls, n in t1.items():
                d = max(n - t0.get(cls, 0), 0)
                if d:
                    term[cls] = term.get(cls, 0) + d
            out["terminated_total"] = sum(term.values())
            out["pc_overflow"] = out.get("pc_overflow", 0) + max(
                led.pc_overflow - o0, 0
            )
            # coverage is a level, not a flow: report the scope-end view
            # (keyed by codehash so the daemon can attribute per request)
            cov = led.coverage()
            out["coverage_pct"] = {
                h: c["instruction_pct"] for h, c in cov.items()
            }
            out["coverage_pct_reachable"] = {
                h: c["instruction_pct_reachable"] for h, c in cov.items()
            }

    @contextlib.contextmanager
    def adaptive_delta(self, out: Dict[str, Any]):
        """Measure this scope's adaptive-controller activity into ``out``
        (keys ``plans``/``resteered_slots``/``requeued_paths``/
        ``flips_planned``/``flips_hit``/``plateau_stops``, plus the
        scope-end ``coverage_stop`` verdict when --coverage-target
        latched one) — scoped counters reset per batch, same contract as
        ``prefilter_delta``."""
        from mythril_tpu.observability.metrics import get_registry

        reg = get_registry()
        names = ("plans", "resteered_slots", "requeued_paths",
                 "flips_planned", "flips_hit", "plateau_stops")
        base = {n: reg.counter("adaptive." + n).value for n in names}
        try:
            yield out
        finally:
            for n in names:
                out[n] = out.get(n, 0) + max(
                    reg.counter("adaptive." + n).value - base[n], 0
                )
            from mythril_tpu.adaptive import get_adaptive_controller

            stop = get_adaptive_controller().stop_state()
            if stop:
                out["coverage_stop"] = stop

    def stats(self) -> Dict[str, Any]:
        """Worker-local engine-global sizes (heartbeat payload)."""
        from mythril_tpu.smt.terms import intern_table_size

        return {"interned_terms": intern_table_size()}


def reset_analysis_scope() -> None:
    """Make the next analysis in this process behave like a fresh one.

    Clears per-analysis telemetry (non-persistent metrics, which resets
    the FrontierStatistics/SolverStatistics facades), detector issue
    lists, and the process-wide (address, bytecode_hash) detection
    caches — without the caches sweep a daemon batch would silently
    suppress re-detection of anything a previous batch already flagged.
    Deliberately does NOT drop the SMT query cache, the interned-term
    tables, or compiled XLA programs: keeping those warm across requests
    is the service's entire point, and their reuse is validated-sound.
    """
    from mythril_tpu.analysis.module.loader import ModuleLoader
    from mythril_tpu.analysis.security import reset_callback_modules
    from mythril_tpu.observability import reset_analysis_metrics

    reset_analysis_metrics()
    reset_callback_modules()
    for module in ModuleLoader().get_detection_modules():
        module.cache.clear()
