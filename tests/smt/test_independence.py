"""Independence splitting (solver.independence_split + solve integration).

Reference parity: tests/laser/smt/independece_solver_test.py — bucketing by
shared variables, and joint-model correctness of the merged result.
"""

import pytest

from mythril_tpu.smt import terms
from mythril_tpu.smt.concrete_eval import evaluate
from mythril_tpu.smt.solver import (
    SAT,
    clear_model_cache,
    independence_split,
    solve_conjunction,
)


@pytest.fixture(autouse=True)
def _fresh():
    clear_model_cache()
    yield
    clear_model_cache()


def test_disjoint_variables_split():
    a, b = terms.var("ia", 256), terms.var("ib", 256)
    c, d = terms.var("ic", 256), terms.var("id", 256)
    conj = [
        terms.ult(a, b),
        terms.eq(c, terms.const(5, 256)),
        terms.ult(terms.const(1, 256), d),
    ]
    buckets = independence_split(conj)
    assert [len(x) for x in buckets] == [1, 1, 1]


def test_shared_variable_joins_buckets():
    a, b, c = terms.var("ja", 256), terms.var("jb", 256), terms.var("jc", 256)
    conj = [
        terms.ult(a, b),       # {a, b}
        terms.ult(b, c),       # {b, c} -> joins the first
        terms.eq(terms.var("jd", 256), terms.const(0, 256)),  # {d} separate
    ]
    buckets = independence_split(conj)
    assert sorted(len(x) for x in buckets) == [1, 2]


def test_transitive_chain_single_bucket():
    vs = [terms.var(f"ch{i}", 32) for i in range(5)]
    conj = [terms.ult(vs[i], vs[i + 1]) for i in range(4)]
    assert len(independence_split(conj)) == 1


def test_uninterpreted_functions_block_splitting():
    x, y = terms.var("ux", 256), terms.var("uy", 256)
    conj = [
        terms.eq(terms.apply_func("g", 256, x), terms.const(1, 256)),
        terms.eq(terms.apply_func("g", 256, y), terms.const(2, 256)),
    ]
    assert len(independence_split(conj)) == 1


def test_solve_merges_bucket_models():
    a, b = terms.var("ma", 256), terms.var("mb", 256)
    c = terms.var("mc", 64)
    conj = [
        terms.eq(terms.add(a, b), terms.const(1000, 256)),
        terms.ult(a, terms.const(10, 256)),
        terms.eq(terms.mul(c, terms.const(3, 64)), terms.const(21, 64)),
    ]
    status, asg = solve_conjunction(conj)
    assert status == SAT
    vals = evaluate(conj, asg)
    assert all(vals[x] for x in conj)
    assert asg.scalars[c] == 7


def test_unsat_bucket_fails_whole_query():
    a = terms.var("na", 256)
    b = terms.var("nb", 8)
    conj = [
        terms.ult(a, terms.const(100, 256)),
        # parity contradiction, decided exactly by the native tier
        terms.eq(terms.mul(b, terms.const(2, 8)), terms.const(1, 8)),
    ]
    status, _ = solve_conjunction(conj)
    assert status != SAT
