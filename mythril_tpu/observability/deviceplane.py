"""Device telemetry plane: see inside XLA from the host-side registry.

Every telemetry plane built so far (tracer, flight recorder, fleet,
watchtower, history) observes the *host* — the device frontier was a
black box: XLA compile wall, per-bucket segment cost, HBM footprint and
recompile churn were invisible, so a perf drift diagnosis meant a human
eyeballing two BENCH_*.json files.  This module turns the device side
into ordinary registry metrics:

* ``install()`` registers ``jax.monitoring`` listeners.  JAX emits
  duration events around tracing/lowering/backend-compile
  (``/jax/core/compile/*_duration``) and plain events for persistent
  compilation-cache hits/misses — the listeners fold them into
  ``device.*`` counters/histograms, attributed to the **dispatching
  bucket shape** via a thread-local dispatch scope (compile happens on
  the thread that dispatches, including the floored-bucket precompile
  daemon thread).
* ``dispatch_scope(bucket)`` tags the calling thread with the bucket
  shape ``(code_cap, instr_cap, addr_cap, loops_cap)`` so compile
  events, device-wall stamps and pull stamps land in per-bucket series.
* ``observe_segment(seconds)`` / ``observe_pull(seconds)`` stamp the
  device-visible wall around the frontier's existing blocking points
  (engine sync loop, pipeline bubble, packed harvest pull) into
  ``frontier.segment_device_s`` / ``frontier.pull_device_s`` histograms
  plus per-bucket ``..._sum{bucket=…}`` / ``..._count{bucket=…}``
  labeled series (the registry has no labeled-histogram kind; a
  sum/count pair per label is the standard Prometheus degradation).
* ``harvest_analysis(fn, args_thunk, tag)`` runs the AOT
  ``fn.lower(*args).compile()`` path once per executable in a daemon
  thread and publishes ``Compiled.cost_analysis()`` /
  ``memory_analysis()`` into ``device.flops_per_segment{bucket=…}`` and
  ``device.hbm_bytes{bucket=…}`` gauges.  Both analyses may return
  ``None``, partial dicts, or raise outright on CPU backends — absence
  degrades to ``device.analysis_unavailable{reason=…}`` counters, never
  a crash and never a zero that reads as "free".

All ``device.*`` metrics are ``persistent=True`` (process-scoped, like
``compilecache.*``): compile/recompile history must survive the
per-analysis registry sweep, and consumers (bench, fleet deltas) read
them as before/after deltas.  Because they are ordinary registry
metrics, the PR-13 fleet fabric ships them per worker with no extra
wiring — pooled runs get ``fleet_device_*{worker=…}`` series for free.

Overhead: the listeners fire per *compile* (rare) and the stamps cost
two counter increments plus one histogram observe per *segment*
(segments are 0.1–10 s).  Time spent inside the plane is self-measured
into ``device.plane_overhead_s`` so ``device_meta()`` can report
overhead as a fraction of the observed segment wall.
"""

from __future__ import annotations

import os
import threading
import time
from contextlib import contextmanager
from typing import Any, Callable, Dict, Iterator, Optional, Sequence, Tuple

from mythril_tpu.observability.metrics import get_registry

__all__ = [
    "bucket_tag",
    "current_bucket",
    "device_meta",
    "dispatch_scope",
    "harvest_analysis",
    "heartbeat_source",
    "install",
    "install_deviceplane",
    "installed",
    "observe_pull",
    "observe_segment",
    "reset_for_tests",
]

# JAX-emitted monitoring event names (jax._src.dispatch / compiler).
_EV_BACKEND_COMPILE = "/jax/core/compile/backend_compile_duration"
_EV_TRACE = "/jax/core/compile/jaxpr_trace_duration"
_EV_LOWER = "/jax/core/compile/jaxpr_to_mlir_module_duration"
_EV_CACHE_HIT = "/jax/compilation_cache/cache_hits"
_EV_CACHE_MISS = "/jax/compilation_cache/cache_misses"

_UNTAGGED = "untagged"

_install_lock = threading.Lock()
_installed = False

# dispatch attribution is per-thread: XLA compiles on the thread that
# dispatches (the engine main thread, or the floored-bucket precompile
# daemon thread), so a thread-local scope is exact, not heuristic
_ctx = threading.local()

# process-scoped attribution state (guarded by _install_lock):
# tag -> dispatch-scope session id of its last compile burst.  One
# dispatch (scope entry) triggers SEVERAL backend-compile events — the
# segment program plus jax's auxiliary executables — so recompiles are
# counted per *session*, not per event: a compile burst for an
# already-compiled tag in a LATER session means XLA compiled again for
# a program we thought was warm.
_compiled_tags: Dict[str, int] = {}
_session_seq = [0]
# program tags whose cost/memory analysis has been harvested (or is
# in flight) — the AOT lower/compile must run once per executable
_analyzed_tags: set = set()


def bucket_tag(bucket: Sequence[int]) -> str:
    """Canonical label for a size bucket: ``"CCxICxACxLC"``."""
    return "x".join(str(int(b)) for b in bucket)


def current_bucket() -> Optional[str]:
    """Bucket tag of the innermost active dispatch scope, if any."""
    return getattr(_ctx, "bucket", None)


@contextmanager
def dispatch_scope(bucket) -> Iterator[None]:
    """Tag the calling thread with the dispatching bucket shape.

    ``bucket`` is either the 4-tuple ``(code_cap, instr_cap, addr_cap,
    loops_cap)`` or an already-formatted tag string.  Scopes nest; the
    innermost wins (the opening natural-bucket dispatch nests inside the
    floored run's scope).
    """
    tag = bucket if isinstance(bucket, str) else bucket_tag(bucket)
    prev = getattr(_ctx, "bucket", None)
    prev_session = getattr(_ctx, "session", 0)
    with _install_lock:
        _session_seq[0] += 1
        _ctx.session = _session_seq[0]
    _ctx.bucket = tag
    try:
        yield
    finally:
        _ctx.bucket = prev
        _ctx.session = prev_session


def _overhead(t0: float) -> None:
    get_registry().counter("device.plane_overhead_s", persistent=True,
                           initial=0.0).inc(time.perf_counter() - t0)


# -- jax.monitoring listeners ---------------------------------------------


def _on_duration(event: str, duration_secs: float, **kwargs) -> None:
    if not event.startswith("/jax/core/compile/"):
        return
    t0 = time.perf_counter()
    reg = get_registry()
    tag = current_bucket() or _UNTAGGED
    if event == _EV_BACKEND_COMPILE:
        reg.observe("device.compile_wall_s", duration_secs)
        reg.counter("device.compile_wall_s_total", persistent=True,
                    initial=0.0).inc(duration_secs)
        reg.labeled_counter("device.compile_wall_s_by_bucket",
                            persistent=True,
                            label_name="bucket").inc(tag, duration_secs)
        session = getattr(_ctx, "session", 0)
        with _install_lock:
            prev_session = _compiled_tags.get(tag)
            _compiled_tags[tag] = session
            n_shapes = len(_compiled_tags)
        if prev_session is None:
            reg.counter("device.shapes_compiled_total",
                        persistent=True).inc()
            if n_shapes > 1:
                # every distinct shape beyond the first is churn: a
                # stream of fresh shapes (bucket floor misconfigured,
                # tables not stacking) shows up as a churn ramp the
                # watchtower can alarm on
                reg.counter("device.shape_churn_total",
                            persistent=True).inc()
        elif prev_session != session:
            # same shape compiling again in a later dispatch: XLA threw
            # away (or never kept) an executable we already paid for.
            # Counted once per dispatch session, not per event burst.
            reg.counter("device.recompiles_total", persistent=True).inc()
            reg.labeled_counter("device.recompiles_by_bucket",
                                persistent=True,
                                label_name="bucket").inc(tag)
    elif event == _EV_TRACE:
        reg.observe("device.trace_wall_s", duration_secs)
        reg.counter("device.trace_wall_s_total", persistent=True,
                    initial=0.0).inc(duration_secs)
    elif event == _EV_LOWER:
        reg.observe("device.lower_wall_s", duration_secs)
        reg.counter("device.lower_wall_s_total", persistent=True,
                    initial=0.0).inc(duration_secs)
    _overhead(t0)


def _on_event(event: str, **kwargs) -> None:
    if not event.startswith("/jax/compilation_cache/"):
        return
    reg = get_registry()
    tag = current_bucket() or _UNTAGGED
    if event == _EV_CACHE_HIT:
        reg.counter("device.cache_hits", persistent=True).inc()
        reg.labeled_counter("device.cache_hits_by_bucket", persistent=True,
                            label_name="bucket").inc(tag)
    elif event == _EV_CACHE_MISS:
        reg.counter("device.cache_misses", persistent=True).inc()
        reg.labeled_counter("device.cache_misses_by_bucket", persistent=True,
                            label_name="bucket").inc(tag)


def install() -> bool:
    """Register the monitoring listeners and heartbeat source (idempotent).

    Returns True when the plane is active.  Safe without jax (the plane
    simply stays disabled) and safe to call from every dispatch path —
    the first caller wins, the rest are no-ops.
    """
    global _installed
    if _installed:
        return True
    if os.environ.get("MYTHRIL_DEVICEPLANE", "1") in ("0", "false", "off"):
        return False
    with _install_lock:
        if _installed:
            return True
        try:
            from jax import monitoring as _mon
        except Exception:  # pragma: no cover - jax is baked into the image
            return False
        _mon.register_event_duration_secs_listener(_on_duration)
        _mon.register_event_listener(_on_event)
        try:
            from mythril_tpu.observability.heartbeat import get_heartbeat
            get_heartbeat().register("device", heartbeat_source)
        except Exception:  # pragma: no cover - heartbeat optional
            pass
        _installed = True
    return True


def installed() -> bool:
    return _installed


# package-level re-export name ("install" is too generic outside the
# deviceplane namespace)
install_deviceplane = install


# -- device-wall stamps ----------------------------------------------------


def _stamp(base: str, seconds: float, tag: Optional[str]) -> None:
    t0 = time.perf_counter()
    reg = get_registry()
    tag = tag or current_bucket() or _UNTAGGED
    reg.observe(base, float(seconds))
    # no labeled-histogram kind exists; a per-bucket sum/count pair is
    # the standard exposition (avg-by-bucket in one PromQL division)
    reg.labeled_counter(base + "_sum", persistent=True,
                        label_name="bucket").inc(tag, float(seconds))
    reg.labeled_counter(base + "_count", persistent=True,
                        label_name="bucket").inc(tag)
    _overhead(t0)


def observe_segment(seconds: float, tag: Optional[str] = None) -> None:
    """Record one segment's device-visible wall (dispatch + host wait)."""
    _stamp("frontier.segment_device_s", seconds, tag)


def observe_pull(seconds: float, tag: Optional[str] = None) -> None:
    """Record one blocking device->host harvest pull."""
    _stamp("frontier.pull_device_s", seconds, tag)


# -- cost / memory analysis harvest ---------------------------------------


def _analysis_unavailable(reason: str) -> None:
    get_registry().labeled_counter(
        "device.analysis_unavailable", persistent=True, label_name="reason"
    ).inc(reason)


def _set_bucket_gauge(name: str, tag: str, value: float) -> None:
    g = get_registry().gauge(name, persistent=True, default={},
                             label_name="bucket")
    cur = g.value if isinstance(g.value, dict) else {}
    nxt = dict(cur)
    nxt[tag] = value
    g.set(nxt)


def _first_dict(obj: Any) -> Optional[Dict[str, Any]]:
    """cost_analysis() has returned a dict, a list of per-computation
    dicts, or None across jax versions — normalize to one dict."""
    if isinstance(obj, dict):
        return obj
    if isinstance(obj, (list, tuple)) and obj and isinstance(obj[0], dict):
        return obj[0]
    return None


def _harvest_worker(fn, args_thunk: Callable[[], Tuple], tag: str) -> None:
    reg = get_registry()
    t0 = time.perf_counter()
    try:
        # runs after the live dispatch compiled + persistently cached the
        # program, so this compile is a cache read, not a second compile;
        # scope it so any event it emits still attributes to the bucket
        with dispatch_scope(tag):
            compiled = fn.lower(*args_thunk()).compile()
    except Exception:
        # AOT path itself unavailable (donation mismatch, backend quirk):
        # degrade, never crash the run that scheduled us
        _analysis_unavailable("lower_compile:error")
        return
    finally:
        reg.observe("device.analysis_harvest_s", time.perf_counter() - t0)

    try:
        cost = _first_dict(compiled.cost_analysis())
    except Exception:
        cost = None
        _analysis_unavailable("cost_analysis:error")
    if cost is None:
        _analysis_unavailable("cost_analysis:none")
    else:
        flops = cost.get("flops")
        if isinstance(flops, (int, float)) and flops > 0:
            _set_bucket_gauge("device.flops_per_segment", tag, float(flops))
        else:
            _analysis_unavailable("cost_analysis:no_flops")
        touched = cost.get("bytes accessed")
        if isinstance(touched, (int, float)) and touched > 0:
            _set_bucket_gauge("device.bytes_accessed_per_segment", tag,
                              float(touched))

    try:
        mem = compiled.memory_analysis()
    except Exception:
        mem = None
        _analysis_unavailable("memory_analysis:error")
    if mem is None:
        _analysis_unavailable("memory_analysis:none")
    else:
        hbm = 0.0
        for attr in ("argument_size_in_bytes", "output_size_in_bytes",
                     "temp_size_in_bytes"):
            v = getattr(mem, attr, None)
            if isinstance(v, (int, float)) and v > 0:
                hbm += float(v)
        if hbm > 0:
            _set_bucket_gauge("device.hbm_bytes", tag, hbm)
        else:
            # a CPU backend's memory_analysis object reports zeros —
            # absence must not read as a free program
            _analysis_unavailable("memory_analysis:empty")


def harvest_analysis(fn, args_thunk: Callable[[], Tuple], tag: str) -> bool:
    """Harvest ``cost_analysis``/``memory_analysis`` once per executable.

    ``fn`` is the jitted segment, ``args_thunk`` builds the example
    arguments lazily on the worker thread (keeps the dispatch path
    free).  Runs AFTER the first real dispatch of the program so the
    AOT re-compile is served by the persistent XLA compilation cache
    rather than racing the live compile.  Idempotent per ``tag``.
    """
    if os.environ.get("MYTHRIL_DEVICE_ANALYSIS", "1") in ("0", "false",
                                                          "off"):
        return False
    with _install_lock:
        if tag in _analyzed_tags:
            return False
        _analyzed_tags.add(tag)
    threading.Thread(
        target=_harvest_worker, args=(fn, args_thunk, tag),
        name="mythril-device-analysis", daemon=True,
    ).start()
    return True


# -- surfaces --------------------------------------------------------------


def _counter_value(reg, name: str) -> float:
    m = reg._metrics.get(name)
    return m.value if m is not None and hasattr(m, "value") else 0


def _gauge_dict(reg, name: str) -> Dict[str, Any]:
    m = reg._metrics.get(name)
    v = getattr(m, "value", None)
    return dict(v) if isinstance(v, dict) else {}


def _labeled_dict(reg, name: str) -> Dict[str, Any]:
    m = reg._metrics.get(name)
    return dict(m) if m is not None and isinstance(m, dict) else {}


def device_meta() -> Dict[str, Any]:
    """The ``meta.device`` block for jsonv2 reports / daemon stats.

    Pure read of the registry — safe without install() (everything
    reads zero/absent) and cheap enough for every report.
    """
    reg = get_registry()
    out: Dict[str, Any] = {"enabled": _installed}
    try:
        import jax
        out["backend"] = jax.default_backend()
    except Exception:  # pragma: no cover
        out["backend"] = None
    out["compile_wall_s"] = round(
        float(_counter_value(reg, "device.compile_wall_s_total")), 3)
    hist = reg._metrics.get("device.compile_wall_s")
    out["compiles"] = getattr(hist, "count", 0)
    out["recompiles"] = int(_counter_value(reg, "device.recompiles_total"))
    out["shape_churn"] = int(_counter_value(reg, "device.shape_churn_total"))
    out["cache"] = {
        "hits": int(_counter_value(reg, "device.cache_hits")),
        "misses": int(_counter_value(reg, "device.cache_misses")),
    }
    by_bucket = _labeled_dict(reg, "device.compile_wall_s_by_bucket")
    out["compile_wall_s_by_bucket"] = {
        k: round(float(v), 3) for k, v in sorted(by_bucket.items())
    }
    flops = _gauge_dict(reg, "device.flops_per_segment")
    if flops:
        out["flops_per_segment"] = flops
    hbm = _gauge_dict(reg, "device.hbm_bytes")
    if hbm:
        out["hbm_bytes"] = hbm
    seg = reg._metrics.get("frontier.segment_device_s")
    if seg is not None and getattr(seg, "count", 0):
        out["segment_device_s"] = {
            "count": seg.count,
            "sum": round(seg.sum, 3),
            "p50": round(seg.percentile(0.5) or 0.0, 6),
            "p95": round(seg.percentile(0.95) or 0.0, 6),
        }
    unavailable = _labeled_dict(reg, "device.analysis_unavailable")
    if unavailable:
        out["analysis_unavailable"] = dict(sorted(unavailable.items()))
    overhead = float(_counter_value(reg, "device.plane_overhead_s"))
    wall = getattr(seg, "sum", 0.0) or 0.0
    out["overhead_pct"] = round(100.0 * overhead / wall, 4) if wall else 0.0
    return out


def heartbeat_source() -> Dict[str, Any]:
    """Heartbeat gauges: compile wall / recompiles / churn trajectory."""
    reg = get_registry()
    return {
        "heartbeat.device_compile_s": round(
            float(_counter_value(reg, "device.compile_wall_s_total")), 3),
        "heartbeat.device_recompiles": int(
            _counter_value(reg, "device.recompiles_total")),
        "heartbeat.device_shape_churn": int(
            _counter_value(reg, "device.shape_churn_total")),
    }


def reset_for_tests() -> None:
    """Forget attribution state (compiled shapes, harvested programs).

    Tests only — the listeners stay registered; registry metrics are
    reset separately via ``MetricsRegistry.reset``.
    """
    with _install_lock:
        _compiled_tags.clear()
        _analyzed_tags.clear()
