"""CFG recovery: blocks, static jump resolution, reachability, dead spans."""

import numpy as np
import pytest

from mythril_tpu.frontend.disassembler import Disassembly
from mythril_tpu.staticpass.cfg import E_DYN, E_FALL, E_JUMP, StaticCFG
from mythril_tpu.staticpass.summary import summarize
from mythril_tpu.staticpass.tables import InstrTables


def _cfg(hexcode: str) -> StaticCFG:
    return StaticCFG(InstrTables(Disassembly(bytes.fromhex(hexcode)).instruction_list))


def _summary(hexcode: str):
    code = bytes.fromhex(hexcode)
    return summarize(Disassembly(code).instruction_list, code_size=len(code))


def test_single_block_no_edges():
    # PUSH1 0; PUSH1 0; REVERT
    cfg = _cfg("60006000fd")
    assert cfg.n_blocks == 1
    assert cfg.edge_list() == []


def test_resolved_jump_and_dead_pad():
    # PUSH1 4; JUMP; INVALID; JUMPDEST; STOP
    cfg = _cfg("600456fe5b00")
    assert cfg.n_blocks == 3  # [PUSH,JUMP] [INVALID] [JUMPDEST,STOP]
    assert cfg.n_resolved == 1
    assert (0, 2, E_JUMP) in cfg.edge_list()
    # the INVALID pad gets no incoming edge
    assert not any(to == 1 for _, to, _k in cfg.edge_list())
    reach = cfg.reachable_blocks()
    assert list(reach) == [True, False, True]


def test_unreachable_span_bytes():
    s = _summary("600456fe5b00")
    assert s.n_resolved_jumps == 1
    assert s.unreachable_bytes == 1  # just the INVALID pad byte
    assert s.unreachable_spans == [(3, 4)]
    # static_target exported per instruction: the JUMP (index 1) resolves
    # to the JUMPDEST's instruction index (3)
    assert s.static_target[1] == 3


def test_unresolved_jump_overapproximates_to_all_jumpdests():
    # PUSH1 0; CALLDATALOAD; JUMP; JUMPDEST; STOP; JUMPDEST; STOP
    cfg = _cfg("60003556" + "5b00" + "5b00")
    dyn = [(b, to) for b, to, k in cfg.edge_list() if k == E_DYN]
    # both JUMPDEST blocks receive a dyn edge from the jump block
    assert sorted(to for _, to in dyn) == sorted(cfg.jumpdest_blocks)
    assert cfg.n_resolved == 0
    assert cfg.reachable_blocks().all()


def test_resolved_invalid_target_halts():
    # PUSH1 3; JUMP; STOP  -- target 3 is STOP, not a JUMPDEST: the VM
    # halts at the jump, so nothing downstream is reachable
    cfg = _cfg("60035600")
    assert cfg.edge_list() == []
    assert list(cfg.reachable_blocks()) == [True, False]


def test_jumpi_keeps_fallthrough():
    # PUSH1 1; PUSH1 7; JUMPI; STOP; INVALID; JUMPDEST(7); STOP
    cfg = _cfg("6001600757" + "00" + "fe" + "5b00")
    kinds = {(b, to): k for b, to, k in cfg.edge_list()}
    jumpi_block = 0
    assert kinds[(jumpi_block, 3)] == E_JUMP  # JUMPDEST block
    assert kinds[(jumpi_block, 1)] == E_FALL  # STOP block
    reach = cfg.reachable_blocks()
    assert reach[1] and reach[3] and not reach[2]


def test_constant_folding_resolves_computed_target():
    # PUSH1 2; PUSH1 4; ADD; JUMP; JUMPDEST; STOP  -- target = 2 + 4 = 6
    cfg = _cfg("600260040156" + "5b00")
    assert cfg.n_resolved == 1
    assert (0, 1, E_JUMP) in cfg.edge_list()


def test_implicit_trailing_stop_is_a_block():
    # code falling off the end: disassembler appends nothing, but the
    # final PUSH block simply has no successor beyond the last instr
    cfg = _cfg("6000")
    assert cfg.n_blocks == 1
    assert cfg.edge_list() == []


def test_summary_is_deterministic():
    a = _summary("600456fe5b00")
    b = _summary("600456fe5b00")
    assert np.array_equal(a.instr_reachable, b.instr_reachable)
    assert a.reachable_opcodes == b.reachable_opcodes
    assert a.edges == b.edges


@pytest.mark.parametrize("hexcode", ["", "00", "5b", "fe"])
def test_degenerate_codes_do_not_crash(hexcode):
    s = _summary(hexcode)
    assert s.n_blocks in (0, 1)
