"""The 9 EVM precompiled contracts, concretely executed on host.

Reference parity: mythril/laser/ethereum/natives.py:76-253.  The reference
leans on native wheels (coincurve/py_ecc/blake2b-py); none exist in this
environment, so the math is carried in-repo: secp256k1 recovery and bn128
group ops in pure modular arithmetic, RIPEMD-160 from spec (OpenSSL 3 often
drops it), blake2b F from EIP-152.  Symbolic input raises
NativeContractException; the caller degrades to fresh symbols
(reference call.py:241-250).  bn128 *pairing* is the one op still deferred
(raises NativeContractException → safely over-approximated).
"""

from __future__ import annotations

import hashlib
from typing import List

from mythril_tpu.ops.keccak import keccak256


class NativeContractException(Exception):
    """Input not fully concrete, or unsupported — degrade to symbols."""


def _concrete_bytes(data: List) -> bytes:
    out = bytearray()
    for b in data:
        if isinstance(b, int):
            out.append(b)
        elif getattr(b, "value", None) is not None:
            out.append(b.value)
        else:
            raise NativeContractException("symbolic byte in native call input")
    return bytes(out)


def _word(data: bytes, i: int) -> int:
    return int.from_bytes(data[32 * i : 32 * (i + 1)].ljust(32, b"\x00"), "big")


# ---------------------------------------------------------------------------
# secp256k1 (for ecrecover)
# ---------------------------------------------------------------------------

_P = 2**256 - 2**32 - 977
_N = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141
_GX = 0x79BE667EF9DCBBAC55A06295CE870B07029BFCDB2DCE28D959F2815B16F81798
_GY = 0x483ADA7726A3C4655DA4FBFC0E1108A8FD17B448A68554199C47D08FFB10D4B8


def _inv_mod(a: int, m: int) -> int:
    return pow(a, -1, m)


def _ec_add_jac(p1, p2, p):
    """Affine point addition on y^2 = x^3 + ax + b over F_p (a irrelevant here
    since we never add a point to itself via this path without doubling)."""
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2:
        if (y1 + y2) % p == 0:
            return None
        # doubling (secp256k1/bn128 both have a=0)
        lam = (3 * x1 * x1) * _inv_mod(2 * y1, p) % p
    else:
        lam = (y2 - y1) * _inv_mod(x2 - x1, p) % p
    x3 = (lam * lam - x1 - x2) % p
    y3 = (lam * (x1 - x3) - y1) % p
    return (x3, y3)


def _ec_mul_point(point, scalar: int, p: int):
    result = None
    addend = point
    while scalar:
        if scalar & 1:
            result = _ec_add_jac(result, addend, p)
        addend = _ec_add_jac(addend, addend, p)
        scalar >>= 1
    return result


def ecrecover_address(msg_hash: bytes, v: int, r: int, s: int) -> bytes:
    """Recover the signer address; b'' on any failure (EVM returns empty)."""
    if v not in (27, 28):
        return b""
    if not (0 < r < _N and 0 < s < _N):
        return b""
    x = r
    if x >= _P:
        return b""
    # lift x to a curve point
    y_sq = (pow(x, 3, _P) + 7) % _P
    y = pow(y_sq, (_P + 1) // 4, _P)
    if (y * y) % _P != y_sq:
        return b""
    if (y % 2) != ((v - 27) % 2):
        y = _P - y
    R = (x, y)
    e = int.from_bytes(msg_hash, "big") % _N
    r_inv = _inv_mod(r, _N)
    u1 = (-e * r_inv) % _N
    u2 = (s * r_inv) % _N
    q = _ec_add_jac(
        _ec_mul_point((_GX, _GY), u1, _P), _ec_mul_point(R, u2, _P), _P
    )
    if q is None:
        return b""
    qx, qy = q
    pub = qx.to_bytes(32, "big") + qy.to_bytes(32, "big")
    return keccak256(pub)[12:]


def ecrecover(data: List) -> List[int]:
    data_bytes = _concrete_bytes(data).ljust(128, b"\x00")
    msg_hash = data_bytes[0:32]
    v = _word(data_bytes, 1)
    r = _word(data_bytes, 2)
    s = _word(data_bytes, 3)
    try:
        addr = ecrecover_address(msg_hash, v, r, s)
    except Exception:  # noqa: BLE001 — any math failure = empty result
        return []
    if not addr:
        return []
    return list(addr.rjust(32, b"\x00"))


# ---------------------------------------------------------------------------
# sha256 / ripemd160 / identity / modexp
# ---------------------------------------------------------------------------


def sha256(data: List) -> List[int]:
    return list(hashlib.sha256(_concrete_bytes(data)).digest())


def _ripemd160_py(data: bytes) -> bytes:
    """Pure-python RIPEMD-160 (spec implementation; OpenSSL 3 drops it)."""
    import struct

    def rol(x, n):
        return ((x << n) | (x >> (32 - n))) & 0xFFFFFFFF

    K1 = [0x00000000, 0x5A827999, 0x6ED9EBA1, 0x8F1BBCDC, 0xA953FD4E]
    K2 = [0x50A28BE6, 0x5C4DD124, 0x6D703EF3, 0x7A6D76E9, 0x00000000]
    R1 = [
        0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15,
        7, 4, 13, 1, 10, 6, 15, 3, 12, 0, 9, 5, 2, 14, 11, 8,
        3, 10, 14, 4, 9, 15, 8, 1, 2, 7, 0, 6, 13, 11, 5, 12,
        1, 9, 11, 10, 0, 8, 12, 4, 13, 3, 7, 15, 14, 5, 6, 2,
        4, 0, 5, 9, 7, 12, 2, 10, 14, 1, 3, 8, 11, 6, 15, 13,
    ]
    R2 = [
        5, 14, 7, 0, 9, 2, 11, 4, 13, 6, 15, 8, 1, 10, 3, 12,
        6, 11, 3, 7, 0, 13, 5, 10, 14, 15, 8, 12, 4, 9, 1, 2,
        15, 5, 1, 3, 7, 14, 6, 9, 11, 8, 12, 2, 10, 0, 4, 13,
        8, 6, 4, 1, 3, 11, 15, 0, 5, 12, 2, 13, 9, 7, 10, 14,
        12, 15, 10, 4, 1, 5, 8, 7, 6, 2, 13, 14, 0, 3, 9, 11,
    ]
    S1 = [
        11, 14, 15, 12, 5, 8, 7, 9, 11, 13, 14, 15, 6, 7, 9, 8,
        7, 6, 8, 13, 11, 9, 7, 15, 7, 12, 15, 9, 11, 7, 13, 12,
        11, 13, 6, 7, 14, 9, 13, 15, 14, 8, 13, 6, 5, 12, 7, 5,
        11, 12, 14, 15, 14, 15, 9, 8, 9, 14, 5, 6, 8, 6, 5, 12,
        9, 15, 5, 11, 6, 8, 13, 12, 5, 12, 13, 14, 11, 8, 5, 6,
    ]
    S2 = [
        8, 9, 9, 11, 13, 15, 15, 5, 7, 7, 8, 11, 14, 14, 12, 6,
        9, 13, 15, 7, 12, 8, 9, 11, 7, 7, 12, 7, 6, 15, 13, 11,
        9, 7, 15, 11, 8, 6, 6, 14, 12, 13, 5, 14, 13, 13, 7, 5,
        15, 5, 8, 11, 14, 14, 6, 14, 6, 9, 12, 9, 12, 5, 15, 8,
        8, 5, 12, 9, 12, 5, 14, 6, 8, 13, 6, 5, 15, 13, 11, 11,
    ]

    def f(j, x, y, z):
        if j < 16:
            return x ^ y ^ z
        if j < 32:
            return (x & y) | (~x & z)
        if j < 48:
            return (x | ~z) ^ y
        if j < 64:
            return (x & z) | (y & ~z)
        return x ^ (y | ~z)

    msg = bytearray(data)
    ml = len(data) * 8
    msg.append(0x80)
    while len(msg) % 64 != 56:
        msg.append(0)
    msg += struct.pack("<Q", ml)

    h = [0x67452301, 0xEFCDAB89, 0x98BADCFE, 0x10325476, 0xC3D2E1F0]
    for block_start in range(0, len(msg), 64):
        x = list(struct.unpack("<16L", bytes(msg[block_start : block_start + 64])))
        al, bl, cl, dl, el = h
        ar, br, cr, dr, er = h
        for j in range(80):
            t = (
                rol((al + f(j, bl, cl, dl) + x[R1[j]] + K1[j // 16]) & 0xFFFFFFFF, S1[j])
                + el
            ) & 0xFFFFFFFF
            al, el, dl, cl, bl = el, dl, rol(cl, 10), bl, t
            t = (
                rol(
                    (ar + f(79 - j, br, cr, dr) + x[R2[j]] + K2[j // 16]) & 0xFFFFFFFF,
                    S2[j],
                )
                + er
            ) & 0xFFFFFFFF
            ar, er, dr, cr, br = er, dr, rol(cr, 10), br, t
        t = (h[1] + cl + dr) & 0xFFFFFFFF
        h[1] = (h[2] + dl + er) & 0xFFFFFFFF
        h[2] = (h[3] + el + ar) & 0xFFFFFFFF
        h[3] = (h[4] + al + br) & 0xFFFFFFFF
        h[4] = (h[0] + bl + cr) & 0xFFFFFFFF
        h[0] = t
    return struct.pack("<5L", *h)


def ripemd160(data: List) -> List[int]:
    raw = _concrete_bytes(data)
    try:
        digest = hashlib.new("ripemd160", raw).digest()
    except ValueError:
        digest = _ripemd160_py(raw)
    return list(digest.rjust(32, b"\x00"))


def identity(data: List) -> List[int]:
    return [b if isinstance(b, int) else b for b in data]


def mod_exp(data: List) -> List[int]:
    raw = _concrete_bytes(data)
    base_len = _word(raw, 0)
    exp_len = _word(raw, 1)
    mod_len = _word(raw, 2)
    if base_len > 4096 or exp_len > 4096 or mod_len > 4096:
        raise NativeContractException("modexp operand too large")
    off = 96
    base = int.from_bytes(raw[off : off + base_len].ljust(base_len, b"\x00"), "big")
    off += base_len
    exp = int.from_bytes(raw[off : off + exp_len].ljust(exp_len, b"\x00"), "big")
    off += exp_len
    mod = int.from_bytes(raw[off : off + mod_len].ljust(mod_len, b"\x00"), "big")
    if mod == 0:
        return [0] * mod_len
    result = pow(base, exp, mod)
    return list(result.to_bytes(mod_len, "big"))


# ---------------------------------------------------------------------------
# alt_bn128 group ops
# ---------------------------------------------------------------------------

_BN_P = 21888242871839275222246405745257275088696311157297823662689037894645226208583


def _bn_on_curve(pt):
    if pt is None:
        return True
    x, y = pt
    return (y * y - x * x * x - 3) % _BN_P == 0


def _bn_decode(x: int, y: int):
    if x == 0 and y == 0:
        return None
    if x >= _BN_P or y >= _BN_P:
        raise NativeContractException("bn128 coordinate out of field")
    pt = (x, y)
    if not _bn_on_curve(pt):
        raise NativeContractException("point not on bn128 curve")
    return pt


def _bn_encode(pt) -> List[int]:
    if pt is None:
        return [0] * 64
    x, y = pt
    return list(x.to_bytes(32, "big") + y.to_bytes(32, "big"))


def ec_add(data: List) -> List[int]:
    raw = _concrete_bytes(data).ljust(128, b"\x00")
    p1 = _bn_decode(_word(raw, 0), _word(raw, 1))
    p2 = _bn_decode(_word(raw, 2), _word(raw, 3))
    return _bn_encode(_ec_add_jac(p1, p2, _BN_P))


def ec_mul(data: List) -> List[int]:
    raw = _concrete_bytes(data).ljust(96, b"\x00")
    p1 = _bn_decode(_word(raw, 0), _word(raw, 1))
    scalar = _word(raw, 2)
    if p1 is None:
        return _bn_encode(None)
    return _bn_encode(_ec_mul_point(p1, scalar, _BN_P))


def ec_pair(data: List) -> List[int]:
    """bn128 pairing check — deferred: over-approximated as symbolic.

    The full Fp12-tower Miller loop is not yet carried in-repo; raising
    NativeContractException makes the caller treat the output as fresh
    symbols, which is sound for detection purposes.
    """
    raise NativeContractException("bn128 pairing not implemented")


# ---------------------------------------------------------------------------
# blake2b F compression (EIP-152)
# ---------------------------------------------------------------------------

_BLAKE2B_IV = [
    0x6A09E667F3BCC908, 0xBB67AE8584CAA73B, 0x3C6EF372FE94F82B, 0xA54FF53A5F1D36F1,
    0x510E527FADE682D1, 0x9B05688C2B3E6C1F, 0x1F83D9ABFB41BD6B, 0x5BE0CD19137E2179,
]

_BLAKE2B_SIGMA = [
    [0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15],
    [14, 10, 4, 8, 9, 15, 13, 6, 1, 12, 0, 2, 11, 7, 5, 3],
    [11, 8, 12, 0, 5, 2, 15, 13, 10, 14, 3, 6, 7, 1, 9, 4],
    [7, 9, 3, 1, 13, 12, 11, 14, 2, 6, 5, 10, 4, 0, 15, 8],
    [9, 0, 5, 7, 2, 4, 10, 15, 14, 1, 11, 12, 6, 8, 3, 13],
    [2, 12, 6, 10, 0, 11, 8, 3, 4, 13, 7, 5, 15, 14, 1, 9],
    [12, 5, 1, 15, 14, 13, 4, 10, 0, 7, 6, 3, 9, 2, 8, 11],
    [13, 11, 7, 14, 12, 1, 3, 9, 5, 0, 15, 4, 8, 6, 2, 10],
    [6, 15, 14, 9, 11, 3, 0, 8, 12, 2, 13, 7, 1, 4, 10, 5],
    [10, 2, 8, 4, 7, 6, 1, 5, 15, 11, 9, 14, 3, 12, 13, 0],
]

_M64 = (1 << 64) - 1


def _ror64(x, n):
    return ((x >> n) | (x << (64 - n))) & _M64


def _blake2b_g(v, a, b, c, d, x, y):
    v[a] = (v[a] + v[b] + x) & _M64
    v[d] = _ror64(v[d] ^ v[a], 32)
    v[c] = (v[c] + v[d]) & _M64
    v[b] = _ror64(v[b] ^ v[c], 24)
    v[a] = (v[a] + v[b] + y) & _M64
    v[d] = _ror64(v[d] ^ v[a], 16)
    v[c] = (v[c] + v[d]) & _M64
    v[b] = _ror64(v[b] ^ v[c], 63)


def blake2b_fcompress(data: List) -> List[int]:
    raw = _concrete_bytes(data)
    if len(raw) != 213:
        raise NativeContractException("blake2b F input must be 213 bytes")
    rounds = int.from_bytes(raw[0:4], "big")
    if rounds > 0xFFFFFF:
        raise NativeContractException("blake2b round count too large")
    h = [int.from_bytes(raw[4 + 8 * i : 12 + 8 * i], "little") for i in range(8)]
    m = [int.from_bytes(raw[68 + 8 * i : 76 + 8 * i], "little") for i in range(16)]
    t0 = int.from_bytes(raw[196:204], "little")
    t1 = int.from_bytes(raw[204:212], "little")
    final = raw[212]
    if final not in (0, 1):
        raise NativeContractException("blake2b final flag must be 0/1")

    v = h[:] + _BLAKE2B_IV[:]
    v[12] ^= t0
    v[13] ^= t1
    if final:
        v[14] ^= _M64
    for r in range(rounds):
        s = _BLAKE2B_SIGMA[r % 10]
        _blake2b_g(v, 0, 4, 8, 12, m[s[0]], m[s[1]])
        _blake2b_g(v, 1, 5, 9, 13, m[s[2]], m[s[3]])
        _blake2b_g(v, 2, 6, 10, 14, m[s[4]], m[s[5]])
        _blake2b_g(v, 3, 7, 11, 15, m[s[6]], m[s[7]])
        _blake2b_g(v, 0, 5, 10, 15, m[s[8]], m[s[9]])
        _blake2b_g(v, 1, 6, 11, 12, m[s[10]], m[s[11]])
        _blake2b_g(v, 2, 7, 8, 13, m[s[12]], m[s[13]])
        _blake2b_g(v, 3, 4, 9, 14, m[s[14]], m[s[15]])
    out = bytearray()
    for i in range(8):
        out += ((h[i] ^ v[i] ^ v[i + 8]) & _M64).to_bytes(8, "little")
    return list(out)


PRECOMPILE_FUNCTIONS = [
    ecrecover,
    sha256,
    ripemd160,
    identity,
    mod_exp,
    ec_add,
    ec_mul,
    ec_pair,
    blake2b_fcompress,
]
PRECOMPILE_NAMES = [
    "ecrecover",
    "sha256",
    "ripemd160",
    "identity",
    "mod_exp",
    "ec_add",
    "ec_mul",
    "ec_pair",
    "blake2b_fcompress",
]


def native_contracts(address: int, data: List) -> List[int]:
    """Dispatch by precompile address 1..9 (reference natives.py:253-282)."""
    if not (1 <= address <= len(PRECOMPILE_FUNCTIONS)):
        raise NativeContractException(f"no precompile at address {address}")
    return PRECOMPILE_FUNCTIONS[address - 1](data)
