"""Batched keccak-256 on device — exact concrete hashing for the probe solver.

The reference cannot hash symbolically, so it axiomatizes keccak as an
uninterpreted function with disjoint-interval range constraints
(mythril/laser/ethereum/function_managers/keccak_function_manager.py:26-34).
This framework instead evaluates ``keccak`` terms *concretely* for every
candidate assignment, on device, in batch — hashing thousands of candidate
preimages per dispatch.  Exactness beats axioms: a probe hit is a real model
with real hash values, so no post-hoc ``_replace_with_actual_sha`` step
(reference: mythril/analysis/solver.py:128-164) is ever needed.

Representation: 64-bit keccak lanes as four 16-bit limbs in uint32
(``[..., 25, 4]`` state), matching ``mythril_tpu/ops/bitvec.py`` — no 64-bit
integers anywhere, so the same arithmetic is valid inside Pallas TPU kernels.
Differentially tested against the host implementation
(mythril_tpu/ops/keccak.py) in tests/ops/test_keccak_jax.py.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from mythril_tpu.ops.bitvec import LIMB_BITS, LIMB_MASK, nlimbs
from mythril_tpu.ops.keccak import _RC, _ROT

RATE_BYTES = 136  # 1088-bit rate for keccak-256

# Round constants as [24, 4] little-endian 16-bit limbs.
_RC_LIMBS = np.array(
    [[(rc >> (16 * i)) & LIMB_MASK for i in range(4)] for rc in _RC], np.uint32
)

# Static lane shuffles for one round, flattened over lane index i = x + 5*y.
# rho+pi: output lane dst = y + 5*((2x+3y)%5) takes input lane x+5y rotated
# by _ROT[x][y]; chi: out[i] = b[i] ^ (~b[i+1 (mod x)] & b[i+2 (mod x)]).
_PI_SRC = np.zeros(25, np.int32)
_PI_ROT = np.zeros(25, np.int32)
for _x in range(5):
    for _y in range(5):
        _PI_SRC[_y + 5 * ((2 * _x + 3 * _y) % 5)] = _x + 5 * _y
        _PI_ROT[_y + 5 * ((2 * _x + 3 * _y) % 5)] = _ROT[_x][_y] % 64
_CHI1 = np.array([(i % 5 + 1) % 5 + 5 * (i // 5) for i in range(25)], np.int32)
_CHI2 = np.array([(i % 5 + 2) % 5 + 5 * (i // 5) for i in range(25)], np.int32)
_MOD5 = np.arange(25, dtype=np.int32) % 5
_XM1 = np.array([(x + 4) % 5 for x in range(5)], np.int32)
_XP1 = np.array([(x + 1) % 5 for x in range(5)], np.int32)
# Per-lane limb gather for the rho rotations: new[j] = old[(j - q) % 4].
_ROT_Q, _ROT_S = _PI_ROT // LIMB_BITS, _PI_ROT % LIMB_BITS
_ROT_JIDX = (np.arange(4)[None, :] - _ROT_Q[:, None]) % 4  # [25, 4]


def _rotl64(lane: jnp.ndarray, r: int) -> jnp.ndarray:
    """Rotate a [..., 4]-limb 64-bit lane left by a static amount."""
    r %= 64
    q, s = divmod(r, LIMB_BITS)
    rolled = jnp.roll(lane, q, axis=-1)
    if s == 0:
        return rolled
    prev = jnp.roll(rolled, 1, axis=-1)
    return ((rolled << s) | (prev >> (LIMB_BITS - s))) & LIMB_MASK


def _rho_rotate(lanes: jnp.ndarray) -> jnp.ndarray:
    """Rotate each of the 25 [..., 25, 4] lanes by its static rho amount.

    Limb rotation is a static gather; the sub-limb shift uses the limb one
    below (limbs are < 2^16, so ``prev >> 16`` is 0 exactly when s == 0)."""
    jidx = jnp.broadcast_to(jnp.asarray(_ROT_JIDX), lanes.shape)
    rolled = jnp.take_along_axis(lanes, jidx, axis=-1)
    prev = jnp.take_along_axis(lanes, (jidx - 1) % 4, axis=-1)
    s = jnp.asarray(_ROT_S[:, None].astype(np.uint32))
    return ((rolled << s) | (prev >> (LIMB_BITS - s))) & LIMB_MASK


def _round(state: jnp.ndarray, rc: jnp.ndarray) -> jnp.ndarray:
    """One keccak-f round on the [..., 25, 4] state (lane index = x + 5*y)."""
    s5 = state.reshape(state.shape[:-2] + (5, 5, 4))  # [..., y, x, limb]
    c = s5[..., 0, :, :]
    for y in range(1, 5):
        c = c ^ s5[..., y, :, :]
    d = jnp.take(c, _XM1, axis=-2) ^ _rotl64(jnp.take(c, _XP1, axis=-2), 1)
    a = state ^ jnp.take(d, _MOD5, axis=-2)
    b = _rho_rotate(jnp.take(a, _PI_SRC, axis=-2))
    chi = b ^ (
        (jnp.take(b, _CHI1, axis=-2) ^ LIMB_MASK) & jnp.take(b, _CHI2, axis=-2)
    )
    return chi.at[..., 0, :].set(chi[..., 0, :] ^ rc)


def keccak_f1600(state: jnp.ndarray) -> jnp.ndarray:
    """Full 24-round permutation of the [..., 25, 4] state.

    On TPU (or with ``args.keccak_backend = "pallas"``) this dispatches to the
    hand-scheduled Pallas kernel (mythril_tpu/ops/keccak_pallas.py); the
    portable path runs the rounds under ``lax.scan`` so the compiled graph
    holds ONE round body — a fully unrolled version takes minutes of XLA
    compile time."""
    from mythril_tpu.support.support_args import args

    backend = getattr(args, "keccak_backend", "auto")
    if backend == "pallas" or (
        backend == "auto" and jax.default_backend() == "tpu"
    ):
        from mythril_tpu.ops import keccak_pallas

        return keccak_pallas.keccak_f1600(state)
    out, _ = jax.lax.scan(
        lambda st, rc: (_round(st, rc), None), state, jnp.asarray(_RC_LIMBS)
    )
    return out


def _gather_bytes(data: jnp.ndarray, width: int) -> list:
    """Big-endian byte string of a [..., L]-limb bitvector, as a list of
    [...]-shaped uint32 byte tensors (static index shuffle)."""
    n = width // 8
    out = []
    for j in range(n):  # j = 0 is the most significant byte
        k = n - 1 - j  # numeric little-endian byte index
        limb = data[..., k // 2]
        out.append((limb >> (8 * (k % 2))) & 0xFF)
    return out


def keccak256(data: jnp.ndarray, width: int) -> jnp.ndarray:
    """keccak-256 of the big-endian byte serialization of a bitvector.

    ``data``: [..., nlimbs(width)] uint32; ``width`` must be a multiple of 8
    (the term layer guarantees byte-width hash inputs).  Returns [..., 16]
    limbs (a 256-bit word)."""
    assert width % 8 == 0, "keccak input must be byte-aligned"
    msg = _gather_bytes(data, width)
    n = len(msg)
    zero = jnp.zeros(jnp.shape(data)[:-1], jnp.uint32)
    msg = [jnp.broadcast_to(b, zero.shape).astype(jnp.uint32) for b in msg]

    # keccak (pre-NIST) padding: 0x01 ... 0x80 within the last rate block
    nblocks = n // RATE_BYTES + 1
    padded = msg + [zero] * (nblocks * RATE_BYTES - n)
    padded[n] = padded[n] | 0x01
    padded[nblocks * RATE_BYTES - 1] = padded[nblocks * RATE_BYTES - 1] | 0x80

    state = jnp.zeros(zero.shape + (25, 4), jnp.uint32)
    for blk in range(nblocks):
        block = padded[blk * RATE_BYTES : (blk + 1) * RATE_BYTES]
        # absorb: XOR 17 lanes (8 bytes each, little-endian within the lane)
        lanes = []
        for t in range(17):
            limbs = [
                block[8 * t + 2 * u] | (block[8 * t + 2 * u + 1] << 8)
                for u in range(4)
            ]
            lanes.append(jnp.stack(limbs, axis=-1))
        absorb = jnp.stack(lanes, axis=-2)  # [..., 17, 4]
        state = state.at[..., :17, :].set(state[..., :17, :] ^ absorb)
        state = keccak_f1600(state)

    # squeeze 32 bytes = lanes 0..3; output word is big-endian bytes
    out_bytes = []  # big-endian byte list, most significant first
    for t in range(4):
        for u in range(8):  # byte u of lane t, little-endian in the lane
            out_bytes.append((state[..., t, u // 2] >> (8 * (u % 2))) & 0xFF)
    # out_bytes[0] is the FIRST digest byte = most significant of the word
    limbs = []
    for i in range(16):  # little-endian 16-bit limbs of the 256-bit word
        b_lo = out_bytes[31 - 2 * i]
        b_hi = out_bytes[31 - (2 * i + 1)]
        limbs.append(b_lo | (b_hi << 8))
    return jnp.stack(limbs, axis=-1)
