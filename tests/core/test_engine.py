"""End-to-end engine tests: symbolic execution over real (hand-assembled) bytecode."""

import pytest

from mythril_tpu.core.svm import LaserEVM
from mythril_tpu.core.state.world_state import WorldState
from mythril_tpu.core.transaction.symbolic import ACTORS
from mythril_tpu.frontend.disassembler import Disassembly
from mythril_tpu.support.model import get_model
from mythril_tpu.exceptions import UnsatError

# kill() dispatcher: selector 0x41c0e1b5 -> SELFDESTRUCT(caller); else REVERT
KILL_CODE = "60003560e01c6341c0e1b51460145760006000fd5b33ff"

# storage counter: any call does SSTORE(0, SLOAD(0)+1) then STOP
COUNTER_CODE = "60005460010160005500"


def run_contract(code_hex, tx_count=1, hooks=None):
    ws = WorldState()
    acct = ws.create_account(
        balance=0, address=0x0901D12E, code=Disassembly(bytes.fromhex(code_hex))
    )
    acct.contract_name = "Test"
    laser = LaserEVM(transaction_count=tx_count, execution_timeout=60)
    if hooks:
        for kind, hook_dict in hooks.items():
            laser.register_hooks(kind, hook_dict)
    laser.sym_exec(world_state=ws, target_address=acct.address.value)
    return laser


def test_selfdestruct_path_reached_with_model():
    captured = []
    run_contract(
        KILL_CODE, hooks={"pre": {"SELFDESTRUCT": [lambda gs: captured.append(gs)]}}
    )
    assert len(captured) == 1
    gs = captured[0]
    model = get_model(
        gs.world_state.constraints + [gs.environment.sender == ACTORS.attacker]
    )
    calldata = gs.current_transaction.call_data.concrete(model)
    assert bytes(calldata[:4]).hex() == "41c0e1b5"
    assert model.eval(gs.environment.sender) == ACTORS.attacker.value


def test_revert_path_produces_no_open_state():
    laser = run_contract(KILL_CODE)
    # one open state from the selfdestruct (non-revert) terminal only
    assert len(laser.open_states) == 1


def test_counter_increments_across_transactions():
    laser = run_contract(COUNTER_CODE, tx_count=2)
    # every tx STOPs -> one open state per tx round
    assert len(laser.open_states) == 1
    ws = laser.open_states[0]
    storage = ws.accounts[0x0901D12E].storage
    from mythril_tpu.smt import symbol_factory
    from mythril_tpu.smt.solver import Solver, SAT

    s = Solver()
    # after 2 txs on fresh storage the slot should be able to equal start+2;
    # storage starts symbolic, so check write structure: last write = read+1
    value = storage[symbol_factory.BitVecVal(0, 256)]
    assert value.symbolic

    sat_check = Solver()
    sat_check.add(ws.constraints)
    assert sat_check.check() == SAT


def test_unreachable_branch_prunes():
    # PUSH1 0 PUSH1 7 JUMPI -> taken branch is statically impossible
    code = "600060075700005b00"
    laser = run_contract(code)
    # execution must finish without error and produce the fallthrough STOP state
    assert len(laser.open_states) >= 1


def test_total_states_counted():
    laser = run_contract(KILL_CODE)
    assert laser.total_states > 5
