"""Conformance suite: official Ethereum VMTests replayed concretely.

This is the backend-independent oracle recommended by SURVEY.md §4 item 1
(reference harness: tests/laser/evm_testsuite/evm_test.py:1-210): each fixture
describes a concrete pre-state, one concrete message call, and the expected
post-state.  We build a concrete ``WorldState`` from ``pre``, replay the call
through the symbolic engine via the concolic transaction driver, then assert

  (a) the engine's gas lower bound does not exceed the actual gas consumption
      recorded in the fixture, and min <= max (same fidelity the reference
      harness asserts: max_gas_used is an over-approximating bound used for
      OOG detection, not an exact upper bound, so only min is oracle-checked),
  (b) the post-state accounts (nonce, code, storage) match exactly,
  (c) fixtures with no ``post`` section (OOG / error cases) leave zero
      surviving open world states.

Fixture sources, in priority order:
  1. ``$VMTESTS_DIR`` if set,
  2. the official fixture tree mounted read-only with the reference at
     /root/reference/tests/laser/evm_testsuite/VMTests (538 fixtures),
  3. the small in-repo sample set under tests/testdata/vmtests (always run,
     so the suite is never empty on machines without the reference mount).
"""

from __future__ import annotations

import json
import os
import time
from pathlib import Path
from typing import List, Tuple

import pytest

REFERENCE_FIXTURES = Path("/root/reference/tests/laser/evm_testsuite/VMTests")
LOCAL_FIXTURES = Path(__file__).parent.parent / "testdata" / "vmtests"

CATEGORIES = [
    "vmArithmeticTest",
    "vmBitwiseLogicOperation",
    "vmEnvironmentalInfo",
    "vmPushDupSwapTest",
    "vmTests",
    "vmSha3Test",
    "vmSystemOperations",
    "vmRandomTest",
    "vmIOandFlowOperations",
]

# Concrete block-env fixtures (BlockNumberDynamicJump*) and exact-gas
# fixtures (gas0/gas1) replay via the env overrides + concrete-gas mode;
# LOG memory expansion and the stack-limit loops replay directly (the
# harness raises max_depth — concrete replays are naturally bounded).
SKIP = {
    # OOG-at-exact-SSTORE-cost cases: need the full refund ledger
    # (15000-per-clear, capped at half) to place the OOG point; the
    # reference also shelves these ("tests_to_resolve", evm_test.py:53)
    "jumpTo1InstructionafterJump",
    "sstore_load_2",
}


def _iter_fixture_files() -> List[Path]:
    env_dir = os.environ.get("VMTESTS_DIR")
    roots = []
    if env_dir:
        roots.append(Path(env_dir))
    elif REFERENCE_FIXTURES.is_dir():
        roots.append(REFERENCE_FIXTURES)
    roots.append(LOCAL_FIXTURES)

    files: List[Path] = []
    for root in roots:
        for category in CATEGORIES:
            cat_dir = root / category
            if cat_dir.is_dir():
                files.extend(sorted(cat_dir.glob("*.json")))
    return files


def load_cases() -> List[Tuple[str, dict]]:
    cases = []
    seen = set()
    for path in _iter_fixture_files():
        with path.open() as fh:
            top = json.load(fh)
        for name, data in top.items():
            if name in seen:
                continue
            seen.add(name)
            cases.append((name, data))
    return cases


CASES = load_cases()


@pytest.mark.conformance
@pytest.mark.parametrize("name, data", CASES, ids=[c[0] for c in CASES])
def test_vmtest(name: str, data: dict) -> None:
    if name in SKIP:
        pytest.skip("feature class out of scope (see module docstring)")

    from mythril_tpu.core.state.account import Account
    from mythril_tpu.core.state.world_state import WorldState
    from mythril_tpu.core.svm import LaserEVM
    from mythril_tpu.core.transaction.concolic import execute_message_call
    from mythril_tpu.frontend.disassembler import Disassembly
    from mythril_tpu.smt import symbol_factory
    from mythril_tpu.support.support_args import args
    from mythril_tpu.support.time_handler import time_handler

    pre = data["pre"]
    action = data["exec"]
    env = data.get("env", {})
    post = data.get("post", {})
    gas_before = int(action["gas"], 16)
    gas_after = data.get("gas")
    gas_used = gas_before - int(gas_after, 16) if gas_after is not None else None

    args.unconstrained_storage = False
    world_state = WorldState()
    for address, details in pre.items():
        account = Account(address, concrete_storage=True)
        account.code = Disassembly(details["code"])
        account.nonce = int(details["nonce"], 16)
        for key, value in details["storage"].items():
            account.storage[symbol_factory.BitVecVal(int(key, 16), 256)] = (
                symbol_factory.BitVecVal(int(value, 16), 256)
            )
        world_state.put_account(account)
        account.set_balance(int(details["balance"], 16))

    time_handler.start_execution(10000)
    # stack-limit fixtures loop ~1020 times (thousands of control transfers);
    # concrete replays terminate on their own, so the symbolic depth cap
    # must not cut them short
    laser_evm = LaserEVM(max_depth=100_000)
    laser_evm.open_states = [world_state]
    laser_evm.time = time.time()

    # concrete block parameters from the fixture's env section
    block_env = {}
    env_map = {
        "currentNumber": "block_number",
        "currentTimestamp": "timestamp",
        "currentCoinbase": "coinbase",
        "currentDifficulty": "difficulty",
        "currentGasLimit": "block_gaslimit",
    }
    for fixture_key, attr in env_map.items():
        if fixture_key in env:
            block_env[attr] = symbol_factory.BitVecVal(
                int(env[fixture_key], 16), 256
            )

    try:
        # deterministic replay: GAS pushes exact remaining gas (reference
        # skiplists gas0/gas1; the env overrides replay BlockNumber* too).
        # Set inside the try so the process-wide flag can never leak.
        args.concrete_gas = True
        final_states = execute_message_call(
            laser_evm,
            callee_address=symbol_factory.BitVecVal(int(action["address"], 16), 256),
            caller_address=symbol_factory.BitVecVal(int(action["caller"], 16), 256),
            origin_address=symbol_factory.BitVecVal(int(action["origin"], 16), 256),
            code=action["code"][2:],
            gas_limit=gas_before,
            data=list(bytes.fromhex(action["data"][2:])),
            gas_price=int(action["gasPrice"], 16),
            value=int(action["value"], 16),
            track_gas=True,
            block_env=block_env,
        )
    finally:
        args.concrete_gas = False

    block_gas_limit = int(env.get("currentGasLimit", "0x7fffffffffffffff"), 16)
    if gas_used is not None and gas_used < block_gas_limit:
        # actual gas must fall within some surviving path's [min, max] bounds
        # (reference evm_test.py:155-163 asserts both ends)
        bounds = [(s.mstate.min_gas_used, s.mstate.max_gas_used) for s in final_states]
        assert all(lo <= hi for lo, hi in bounds)
        assert any(lo <= gas_used <= hi for lo, hi in bounds), (
            f"gas {gas_used} outside all bounds {bounds}"
        )

    if post == {}:
        assert len(laser_evm.open_states) == 0
        return

    assert len(laser_evm.open_states) == 1
    result_state = laser_evm.open_states[0]
    for address, details in post.items():
        account = result_state[symbol_factory.BitVecVal(int(address, 16), 256)]
        assert account.nonce == int(details["nonce"], 16)
        code_bytes = account.code.bytecode if account.code is not None else b""
        assert code_bytes == bytes.fromhex(details["code"][2:])
        for index, value in details["storage"].items():
            expected = int(value, 16)
            actual = account.storage[symbol_factory.BitVecVal(int(index, 16), 256)]
            actual_val = getattr(actual, "value", actual)
            if actual_val is True:
                actual_val = 1
            elif actual_val is False:
                actual_val = 0
            assert actual_val == expected, f"storage[{index}]"
