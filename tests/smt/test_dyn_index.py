"""Computed-index array reads (ABI dynamic-array head indirection).

Z3's array theory gives the reference this for free
(mythril/laser/smt/array.py:45-72 Select over a symbolic index); this stack
resolves it with dynamic select hints installed against the partial
assignment (smt/solver.py _apply_dyn_hints) plus pointer-word pre-seeding,
and exactly in the CDCL tier via Ackermann congruence.  The motivating shape
is solc's ``address[]`` calldata layout — ``cnt = calldataload(4 +
calldataload(4))`` — from BECToken's batchTransfer
(solidity_examples/BECToken.sol:257-268, CVE-2018-10299).
"""

from mythril_tpu.core.state.calldata import SymbolicCalldata
from mythril_tpu.smt import (
    BVMulNoOverflow, Not, Solver, ULE, UGE, symbol_factory,
    SAT, UNSAT,
)
from mythril_tpu.smt.solver import SolverStatistics


def val(v, w=256):
    return symbol_factory.BitVecVal(v, w)


def _abi_words():
    """off / cnt / value terms in the true dynamic-array layout."""
    cd = SymbolicCalldata("1")
    off = cd.get_word_at(4)
    cnt = cd.get_word_at(val(4) + off)
    value = cd.get_word_at(36)
    return cd, off, cnt, value


def test_one_level_indirection_probe_hit():
    """The probe (not the CDCL fallback) must solve the true ABI shape."""
    cd, off, cnt, value = _abi_words()
    s = Solver()
    s.add(UGE(cnt, val(1)))
    s.add(ULE(cnt, val(20)))
    s.add(UGE(value, val(1)))
    s.add(Not(BVMulNoOverflow(cnt, value, signed=False)))
    stats = SolverStatistics()
    hits_before = stats.probe_hits
    assert s.check() == SAT
    assert stats.probe_hits == hits_before + 1, "expected a probe hit, not CDCL"
    m = s.model()
    cnt_v, value_v = int(m.eval(cnt)), int(m.eval(value))
    assert 1 <= cnt_v <= 20
    assert cnt_v * value_v >= 1 << 256, "product must wrap"
    # the reified exploit calldata must be compact (ABI-shaped, not junk)
    data = cd.concrete(m)
    assert len(data) <= 512


def test_indirect_read_equals_direct_head_value():
    """cnt read through the pointer must match a directly pinned word."""
    cd, off, cnt, _ = _abi_words()
    s = Solver()
    s.add(cnt == val(0xDEAD))
    s.add(UGE(off, val(32)))  # keep the data region off the head
    assert s.check() == SAT
    m = s.model()
    assert int(m.eval(cnt)) == 0xDEAD


def test_wide_mul_unsat_exact():
    """Bounded factors cannot overflow: the CDCL tier must prove UNSAT."""
    cnt = symbol_factory.BitVecSym("cnt", 256)
    value = symbol_factory.BitVecSym("value", 256)
    s = Solver()
    s.add(UGE(cnt, val(1)))
    s.add(ULE(cnt, val(20)))
    s.add(ULE(value, val(1 << 200)))
    s.add(Not(BVMulNoOverflow(cnt, value, signed=False)))
    assert s.check() == UNSAT


def test_overflow_raise_with_range_pinned_factor():
    """cnt is range-pinned small: the product raise must pick the minimal
    cofactor split (cnt=2-ish, value~2^255), not a blunt 2^128 split."""
    cnt = symbol_factory.BitVecSym("cnt2", 256)
    value = symbol_factory.BitVecSym("value2", 256)
    s = Solver()
    s.add(UGE(cnt, val(2)))
    s.add(ULE(cnt, val(3)))
    s.add(Not(BVMulNoOverflow(cnt, value, signed=False)))
    assert s.check() == SAT
    m = s.model()
    cnt_v, value_v = int(m.eval(cnt)), int(m.eval(value))
    assert 2 <= cnt_v <= 3
    assert cnt_v * value_v >= 1 << 256


def test_guard_no_poison_size_raised():
    """``idx < size`` guards must be satisfied by raising size, not by
    zeroing the computed index through the pointer word."""
    cd, off, cnt, _ = _abi_words()
    s = Solver()
    s.add(cnt == val(7))
    assert s.check() == SAT
    m = s.model()
    size_v = int(m.eval(cd.calldatasize))
    off_v = int(m.eval(off))
    # data region must genuinely sit inside calldata
    assert size_v >= 4 + off_v + 32
