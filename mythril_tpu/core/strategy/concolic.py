"""Concolic strategy: follow a recorded trace, flip chosen branches.

Reference parity: mythril/laser/ethereum/strategy/concolic.py:21-133 — the
strategy walks states along a recorded (pc, tx_id) trace; at each requested
JUMPI address it negates the last path constraint and solves for inputs that
flip the branch; halts when every requested branch has been flipped.
"""

from __future__ import annotations

import logging
from typing import Dict, List, Tuple

from mythril_tpu.core.state.annotation import StateAnnotation
from mythril_tpu.core.state.global_state import GlobalState
from mythril_tpu.core.strategy.basic import CriterionSearchStrategy
from mythril_tpu.exceptions import UnsatError
from mythril_tpu.smt import Not

log = logging.getLogger(__name__)


class TraceAnnotation(StateAnnotation):
    """Cumulative (pc, tx_id) trace of this path (reference :21)."""

    def __init__(self, trace=None):
        self.trace: List[Tuple[int, str]] = trace or []

    @property
    def persist_over_calls(self) -> bool:
        return True

    def __copy__(self):
        return TraceAnnotation(list(self.trace))


class ConcolicStrategy(CriterionSearchStrategy):
    def __init__(self, work_list, max_depth, trace, flip_branch_addresses):
        super().__init__(work_list, max_depth)
        self.trace: List[Tuple[int, str]] = trace
        self.flip_branch_addresses: List[int] = flip_branch_addresses
        self.results: Dict[int, Dict] = {}

    def check_completion_criterion(self) -> None:
        if len(self.flip_branch_addresses) == len(self.results):
            self.set_criterion_satisfied()

    def get_strategic_global_state(self) -> GlobalState:
        while self.work_list:
            state = self.work_list.pop()
            annotations = state.get_annotations(TraceAnnotation)
            annotation = annotations[0] if annotations else TraceAnnotation()
            if not annotations:
                state.annotate(annotation)

            instr = state.get_current_instruction()
            tx = state.current_transaction
            annotation.trace.append((instr["address"], tx.id if tx else "?"))

            # does this state still follow the recorded trace?
            if annotation.trace != self.trace[: len(annotation.trace)]:
                # deviated: if the deviation point is a requested flip, solve it
                deviation_addr = annotation.trace[-2][0] if len(annotation.trace) >= 2 else None
                if (
                    deviation_addr in self.flip_branch_addresses
                    and deviation_addr not in self.results
                ):
                    self._solve_flip(state, deviation_addr)
                continue
            return state
        raise StopIteration

    def _solve_flip(self, state: GlobalState, address: int) -> None:
        from mythril_tpu.analysis.solver import get_transaction_sequence

        try:
            self.results[address] = get_transaction_sequence(
                state, state.world_state.constraints
            )
            log.info("flipped branch at %d", address)
        except UnsatError:
            log.info("branch at %d cannot be flipped", address)
            self.results[address] = {}
        self.check_completion_criterion()
