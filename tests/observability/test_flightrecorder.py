"""Flight recorder: dump bundles, excepthook chaining, watchdog scoping."""

import json
import sys
import time

import pytest

from mythril_tpu.observability import flightrecorder as frec
from mythril_tpu.observability.flightrecorder import FlightRecorder
from mythril_tpu.observability.tracer import get_tracer


@pytest.fixture(autouse=True)
def _clean_module_state():
    yield
    frec.disarm_flight_recorder()


def test_dump_writes_bundle_with_spans_and_stacks(tmp_path):
    tracer = get_tracer()
    tracer.reset()
    tracer.enabled = True
    try:
        with tracer.span("pre-crash", cat="test"):
            pass
        rec = FlightRecorder(str(tmp_path))
        path = rec.dump("manual", extra={"note": "hello"})
        bundle = json.loads(open(path).read())
        assert bundle["reason"] == "manual"
        assert bundle["note"] == "hello"
        assert bundle["seq"] == 1
        assert any(s["name"] == "pre-crash" for s in bundle["spans_tail"])
        # every live thread has a stack tail; this one is among them
        assert any("MainThread" in k for k in bundle["threads"])
        assert rec.bundles == [path]
        # no stray .tmp left behind (atomic replace)
        assert not list(tmp_path.glob("*.tmp"))
    finally:
        tracer.enabled = False
        tracer.reset()


def test_dump_includes_heartbeat_tail(tmp_path):
    from mythril_tpu.observability.heartbeat import get_heartbeat

    hb = get_heartbeat()
    hb.reset()
    hb.register("t", lambda: {"test.fr.depth": 4})
    hb.sample_now()
    try:
        rec = FlightRecorder(str(tmp_path))
        bundle = json.loads(open(rec.dump("manual")).read())
        assert bundle["heartbeat_tail"][-1]["test.fr.depth"] == 4
    finally:
        hb.reset()
        from mythril_tpu.observability.metrics import get_registry

        get_registry().reset(prefix="test.fr.")


def test_excepthook_chains_and_dumps(tmp_path):
    seen = []
    prev = sys.excepthook
    sys.excepthook = lambda *a: seen.append(a)
    try:
        rec = frec.arm_flight_recorder(str(tmp_path))
        try:
            raise ValueError("boom")
        except ValueError:
            sys.excepthook(*sys.exc_info())
        assert len(rec.bundles) == 1
        bundle = json.loads(open(rec.bundles[0]).read())
        assert bundle["reason"] == "exception"
        assert "ValueError: boom" in bundle["exception"]
        # the pre-existing hook still ran after the dump
        assert len(seen) == 1
        frec.disarm_flight_recorder()
        # disarm restores the chained hook
        assert sys.excepthook not in (rec._on_exception,)
    finally:
        sys.excepthook = prev


def test_watchdog_fires_once_inside_activity_window(tmp_path):
    rec = frec.arm_flight_recorder(str(tmp_path), watchdog_deadline_s=0.1)
    deadline = time.time() + 5.0
    with frec.activity():
        while not rec.bundles and time.time() < deadline:
            time.sleep(0.02)
        # one stall -> exactly one bundle, even if we keep stalling
        time.sleep(0.3)
    assert len(rec.bundles) == 1
    bundle = json.loads(open(rec.bundles[0]).read())
    assert bundle["reason"] == "watchdog"
    assert bundle["idle_s"] >= 0.1  # fires when idle >= deadline


def test_watchdog_silent_outside_activity_and_with_beats(tmp_path):
    rec = frec.arm_flight_recorder(str(tmp_path), watchdog_deadline_s=0.1)
    # idle (no activity window): never fires
    time.sleep(0.3)
    assert rec.bundles == []
    # active but beating: never fires
    with frec.activity():
        for _ in range(6):
            time.sleep(0.05)
            frec.beat()
    assert rec.bundles == []


def test_module_helpers_are_noops_when_disarmed():
    frec.disarm_flight_recorder()
    assert frec.get_flight_recorder() is None
    frec.beat()  # must not raise
    with frec.activity():
        pass


def test_rearm_replaces_recorder(tmp_path):
    a = frec.arm_flight_recorder(str(tmp_path / "a"))
    b = frec.arm_flight_recorder(str(tmp_path / "b"))
    assert frec.get_flight_recorder() is b
    assert not a._armed


def test_dump_includes_registered_context_sources(tmp_path):
    frec.register_flight_context("t.ctx", lambda: {"k": 1})
    frec.register_flight_context("t.bad", lambda: 1 / 0)
    try:
        rec = FlightRecorder(str(tmp_path))
        bundle = json.loads(open(rec.dump("manual")).read())
        assert bundle["context"]["t.ctx"] == {"k": 1}
        # one broken source never takes the bundle down with it
        assert "ZeroDivisionError" in bundle["context"]["t.bad"]["error"]
    finally:
        frec.unregister_flight_context("t.ctx")
        frec.unregister_flight_context("t.bad")
    # sources survive re-arms but honor unregistration
    bundle = json.loads(open(FlightRecorder(str(tmp_path)).dump("again")).read())
    assert "t.ctx" not in bundle.get("context", {})
