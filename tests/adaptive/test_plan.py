"""Property tests for the pure steering planner.

``hypothesis`` is not part of the toolchain, so each property runs over a
seeded ``numpy.random.default_rng`` sweep — deterministic, wide enough to
exercise the edge cases the ISSUE contract names:

* ``steer_weights`` always emits a valid probability distribution,
* ``requeue_candidates`` never names a live (or duplicate) token,
* ``plateau_verdict`` is monotone under coverage growth.
"""

import numpy as np
import pytest

from mythril_tpu.adaptive.plan import (
    EPS_WEIGHT,
    PLATEAU_EPSILON,
    PLATEAU_WINDOW,
    SteeringPlan,
    build_plan,
    plateau_verdict,
    rank_flip_targets,
    requeue_candidates,
    steer_weights,
    uncovered_reachable,
)

RNG = np.random.default_rng(0xC0FFEE)


def _random_uncovered(rng, n_codes):
    return {
        "%040x" % rng.integers(0, 1 << 62): int(rng.integers(-2, 500))
        for _ in range(n_codes)
    }


class TestSteerWeights:
    def test_empty(self):
        assert steer_weights({}) == {}

    def test_valid_distribution_randomized(self):
        """Weights are a valid distribution for ANY input mix: strictly
        positive (epsilon floor, no starvation) and summing to 1."""
        for trial in range(200):
            n = int(RNG.integers(1, 12))
            uncovered = _random_uncovered(RNG, n)
            plateaued = {k: bool(RNG.integers(0, 2)) for k in uncovered}
            hotspots = {
                k: float(RNG.uniform(0, 30))
                for k in uncovered if RNG.integers(0, 2)
            }
            w = steer_weights(uncovered, plateaued, hotspots)
            assert set(w) == set(uncovered)
            vals = np.asarray(list(w.values()))
            assert (vals > 0).all(), f"starved a code at trial {trial}: {w}"
            assert vals.sum() == pytest.approx(1.0, abs=1e-9)

    def test_deterministic_and_order_invariant(self):
        uncovered = _random_uncovered(RNG, 6)
        plateaued = {k: i % 2 == 0 for i, k in enumerate(uncovered)}
        w1 = steer_weights(uncovered, plateaued)
        w2 = steer_weights(
            dict(reversed(list(uncovered.items()))), plateaued
        )
        assert w1 == w2

    def test_uncovered_mass_attracts_weight(self):
        w = steer_weights({"a" * 40: 100, "b" * 40: 1})
        assert w["a" * 40] > w["b" * 40]

    def test_plateaued_code_decays_to_floor(self):
        """A plateaued code never out-weighs any non-plateaued code,
        whatever its uncovered mass — but keeps a positive share."""
        for _ in range(50):
            uncovered = _random_uncovered(RNG, 5)
            keys = sorted(uncovered)
            flat = keys[0]
            uncovered[flat] = 10_000  # huge mass, then flat-lined
            w = steer_weights(uncovered, {flat: True})
            assert w[flat] > 0
            assert all(w[flat] <= w[k] + 1e-12 for k in keys[1:])

    def test_saturated_code_decays_to_floor(self):
        w = steer_weights({"a" * 40: 0, "b" * 40: 50})
        assert 0 < w["a" * 40] < w["b" * 40]

    def test_hotspot_damping(self):
        """Equal uncovered mass: the code eating the solver wall yields."""
        hot, cold = "a" * 40, "b" * 40
        w = steer_weights({hot: 50, cold: 50}, hotspot_s={hot: 10.0})
        assert w[hot] < w[cold]

    def test_floor_scales_with_eps(self):
        uncovered = {"a" * 40: 0, "b" * 40: 1000}
        lo = steer_weights(uncovered, eps=0.01)["a" * 40]
        hi = steer_weights(uncovered, eps=0.25)["a" * 40]
        assert lo < hi
        assert EPS_WEIGHT == pytest.approx(0.05)


class TestRequeueCandidates:
    def test_never_names_live_tokens_randomized(self):
        """Exactly-once: whatever the park log looks like, a token that
        is currently live in an arena slot is never resurrected."""
        reasons = ("budget_exhausted", "verdict", "loop_bound", "pruned")
        for _ in range(200):
            n = int(RNG.integers(0, 40))
            parked = [
                (int(RNG.integers(0, 20)),
                 reasons[int(RNG.integers(0, len(reasons)))])
                for _ in range(n)
            ]
            live = {int(t) for t in RNG.integers(0, 20, size=6)}
            out = requeue_candidates(parked, live,
                                     limit=int(RNG.integers(0, 10)))
            assert not (set(out) & live)
            assert len(out) == len(set(out))  # no duplicates
            assert all(
                any(t == tok and r == "budget_exhausted"
                    for t, r in parked)
                for tok in out
            )

    def test_fifo_order_and_limit(self):
        parked = [(i, "budget_exhausted") for i in range(10)]
        assert requeue_candidates(parked, (), limit=4) == [0, 1, 2, 3]
        assert requeue_candidates(parked, {0, 2}, limit=4) == [1, 3, 4, 5]

    def test_only_budget_exhausted_qualifies(self):
        parked = [(1, "verdict"), (2, "budget_exhausted"), (3, "pruned")]
        assert requeue_candidates(parked, ()) == [2]


class TestPlateauVerdict:
    def test_short_history_never_plateaus(self):
        for n in range(PLATEAU_WINDOW + 1):
            assert plateau_verdict([50.0] * n) is False

    def test_flat_history_plateaus(self):
        assert plateau_verdict([50.0] * (PLATEAU_WINDOW + 2)) is True

    def test_monotone_under_coverage_growth_randomized(self):
        """The ISSUE contract: the verdict is monotone in the window's
        total gain — appending a sample that lifts the gain to epsilon
        or more ALWAYS clears a standing plateau."""
        for _ in range(200):
            n = int(RNG.integers(PLATEAU_WINDOW + 1, PLATEAU_WINDOW + 12))
            # non-decreasing coverage history (coverage never regresses)
            hist = list(np.cumsum(RNG.uniform(0, 0.2, size=n)))
            verdict = plateau_verdict(hist)
            gain = hist[-1] - hist[-1 - PLATEAU_WINDOW]
            assert verdict == (gain < PLATEAU_EPSILON)
            if verdict:
                # growth >= epsilon within the window clears it (the
                # 1e-9 absorbs float cancellation in x + eps - x)
                lifted = hist + [hist[-1 - PLATEAU_WINDOW + 1]
                                 + PLATEAU_EPSILON + 1e-9]
                assert plateau_verdict(lifted) is False

    def test_growth_keeps_exploring(self):
        hist = [10.0, 20.0, 30.0, 40.0, 50.0, 60.0]
        assert plateau_verdict(hist) is False

    def test_window_zero_disables(self):
        assert plateau_verdict([1.0] * 50, window=0) is False


class TestUncoveredReachable:
    def test_no_oracle_uses_seen_branch_sites(self):
        taken = np.zeros(8, bool)
        fall = np.zeros(8, bool)
        taken[3] = True  # JUMPI at 3: taken seen, fall not
        fall[5] = taken[5] = True  # JUMPI at 5: exhausted
        un_taken, un_fall, n_instr = uncovered_reachable({
            "instr": np.ones(8, bool), "edge_taken": taken,
            "edge_fall": fall,
        })
        assert list(un_taken) == []
        assert list(un_fall) == [3]
        assert n_instr == 0

    def test_oracle_masks_bound_the_frontier(self):
        instr = np.zeros(8, bool)
        instr[:4] = True
        reach = np.ones(8, bool)
        un_taken, un_fall, n_instr = uncovered_reachable({
            "instr": instr,
            "edge_taken": np.zeros(8, bool),
            "edge_fall": np.zeros(8, bool),
            "reach_taken": np.array([0, 0, 1, 0, 0, 0, 0, 0], bool),
            "reach_fall": np.array([0, 0, 1, 0, 0, 0, 0, 0], bool),
            "reach_instr": reach,
        })
        assert list(un_taken) == [2]
        assert list(un_fall) == [2]
        assert n_instr == 4  # 8 reachable, 4 executed


class TestRankFlipTargets:
    def test_empty(self):
        assert rank_flip_targets(np.array([]), np.array([])) == ()

    def test_score_then_addr_deterministic(self):
        pts = [{"addr": 30, "score": 5.0}, {"addr": 100, "score": 1.0}]
        un = np.array([10, 40, 90])
        # 10 and 40... 10 sees max(5,1)=5, 40 sees 1? no: points at/after
        # 10 -> {30:5, 100:1} max 5; after 40 -> {100:1}; after 90 -> 1
        out = rank_flip_targets(un, np.array([]), pts)
        assert out == (10, 40, 90)
        # determinism across repeated calls
        assert out == rank_flip_targets(un, np.array([]), pts)

    def test_limit(self):
        un = np.arange(100)
        out = rank_flip_targets(un, np.array([]), limit=7)
        assert len(out) == 7


class TestBuildPlan:
    def _bitmap(self, n=8, jumpis=(3,)):
        taken = np.zeros(n, bool)
        fall = np.zeros(n, bool)
        for j in jumpis:
            taken[j] = True  # taken seen, fall uncovered
        return {
            "instr": np.ones(n, bool), "edge_taken": taken,
            "edge_fall": fall, "jumpis": list(jumpis), "total": n,
        }

    def test_composes_all_products(self):
        h1, h2 = "a" * 40, "b" * 40
        plan = build_plan(
            {h1: self._bitmap(), h2: self._bitmap(jumpis=(2, 5))},
            history={h1: [50.0] * (PLATEAU_WINDOW + 2)},
            parked=[("tok1", "budget_exhausted"), ("tok2", "verdict")],
            live=(),
            points={h1: ({"addr": 6, "score": 3.0},)},
        )
        assert isinstance(plan, SteeringPlan)
        assert set(plan.weights) == {h1, h2}
        assert plan.plateaued[h1] is True and plan.plateaued[h2] is False
        assert plan.weights[h2] > plan.weights[h1]
        assert plan.requeue == ("tok1",)
        assert plan.flip_targets[h1] == (3,)
        assert plan.uncovered_edges == {h1: 1, h2: 2}

    def test_weight_accessor_defaults(self):
        plan = SteeringPlan()
        assert plan.weight("anything") == 1.0
        plan = build_plan({"a" * 40: self._bitmap(),
                           "b" * 40: self._bitmap()})
        assert plan.weight("unknown") == pytest.approx(0.5)
