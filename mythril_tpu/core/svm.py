"""LaserEVM: the work-list symbolic execution engine.

Reference parity: mythril/laser/ethereum/svm.py:42-739 — strategy-driven main
loop (:261-304), per-instruction execution with plugin/module hooks (:336-449),
nested-call frame management via transaction signals (:451-504), CFG
bookkeeping (:506-532), the 9 laser hook types + per-opcode pre/post hooks
(:100-133, 596-739), and the multi-transaction loop with open-world-state
reseeding (:208-245).
"""

from __future__ import annotations

import copy as _copy
import logging
import time
from collections import defaultdict
from typing import Callable, Dict, List, Optional, Tuple

from mythril_tpu.core.cfg import Edge, JumpType, Node, NodeFlags
from mythril_tpu.core.evm_exceptions import StackUnderflowException, VmException
from mythril_tpu.core.instructions import Instruction
from mythril_tpu.core.state.global_state import GlobalState
from mythril_tpu.core.state.world_state import WorldState
from mythril_tpu.core.strategy.basic import BasicSearchStrategy, DepthFirstSearchStrategy
from mythril_tpu.core.transaction.transaction_models import (
    ContractCreationTransaction,
    TransactionEndSignal,
    TransactionStartSignal,
)
from mythril_tpu.plugins.signals import PluginSkipState, PluginSkipWorldState
from mythril_tpu.support.opcodes import OPCODES, stack_inputs
from mythril_tpu.support.support_args import args
from mythril_tpu.support.time_handler import time_handler

log = logging.getLogger(__name__)

# host steps executed before the production frontier's first drain attempt
# (multiple of the drain cadence 8): enough samples for host_step_rate, so
# the engine's throughput bail starts informed instead of blind
_FRONTIER_WARMUP_STEPS = 24

LASER_HOOK_TYPES = (
    "start_sym_exec",
    "stop_sym_exec",
    "start_sym_trans",
    "stop_sym_trans",
    "start_exec",
    "stop_exec",
    "execute_state",
    "add_world_state",
    "transaction_start",
    "transaction_end",
)


class LaserEVM:
    def __init__(
        self,
        dynamic_loader=None,
        max_depth: int = 128,
        execution_timeout: Optional[int] = None,
        create_timeout: Optional[int] = None,
        strategy=DepthFirstSearchStrategy,
        transaction_count: int = 2,
        requires_statespace: bool = True,
        iprof=None,
    ):
        self.dynamic_loader = dynamic_loader
        self.open_states: List[WorldState] = []
        self.total_states = 0
        # host stepping telemetry (exec loop): wall and count of host-side
        # execute_state calls, consumed by the frontier's throughput bail
        self._host_steps = 0
        self._host_step_secs = 0.0

        self.work_list: List[GlobalState] = []
        self.strategy: BasicSearchStrategy = strategy(self.work_list, max_depth)
        self.max_depth = max_depth
        self.transaction_count = transaction_count
        self.execution_timeout = execution_timeout or args.execution_timeout
        self.create_timeout = create_timeout if create_timeout is not None else args.create_timeout

        self.requires_statespace = requires_statespace
        self.nodes: Dict[int, Node] = {}
        self.edges: List[Edge] = []

        self.time: Optional[float] = None
        self.executed_transactions = False

        # frontier checkpointing (SURVEY.md §5.4): snapshot path + number of
        # transactions a resumed run has already completed
        self.checkpoint_path: Optional[str] = None
        self.resume_offset: int = 0

        # hook registries
        self._hooks: Dict[str, List[Callable]] = {t: [] for t in LASER_HOOK_TYPES}
        self._pre_hooks: Dict[str, List[Callable]] = defaultdict(list)
        self._post_hooks: Dict[str, List[Callable]] = defaultdict(list)
        self.instr_pre_hook: Dict[str, List[Callable]] = defaultdict(list)
        self.instr_post_hook: Dict[str, List[Callable]] = defaultdict(list)

        self.iprof = iprof
        self.executed_instruction_count = 0

    def host_step_rate(self) -> Optional[float]:
        """Measured host stepping rate (states/s) on this workload, or None
        until enough samples exist to be meaningful."""
        if self._host_steps < _FRONTIER_WARMUP_STEPS or self._host_step_secs <= 0:
            return None
        return self._host_steps / self._host_step_secs

    # ------------------------------------------------------------------
    # hook registration (reference svm.py:596-739)
    # ------------------------------------------------------------------

    def register_laser_hooks(self, hook_type: str, hook: Callable) -> None:
        if hook_type not in LASER_HOOK_TYPES:
            raise ValueError(f"unknown laser hook type {hook_type}")
        self._hooks[hook_type].append(hook)

    def register_hooks(self, hook_type: str, hook_dict: Dict[str, List[Callable]]) -> None:
        """Register detection-module hooks keyed by opcode name."""
        target = self._pre_hooks if hook_type == "pre" else self._post_hooks
        for op, funcs in hook_dict.items():
            target[op].extend(funcs)

    def register_instr_hooks(self, hook_type: str, opcode: Optional[str], hook: Callable) -> None:
        """Instruction-level hooks; opcode None means every opcode."""
        registry = self.instr_pre_hook if hook_type == "pre" else self.instr_post_hook
        registry["*" if opcode is None else opcode].append(hook)

    def _fire(self, hook_type: str, *hook_args) -> None:
        for hook in self._hooks[hook_type]:
            hook(*hook_args)

    def extend_strategy(self, extension, **kwargs) -> None:
        self.strategy = extension(self.strategy, **kwargs)

    # ------------------------------------------------------------------
    # top-level entry points (reference svm.py:139-245)
    # ------------------------------------------------------------------

    def sym_exec(
        self,
        world_state: Optional[WorldState] = None,
        target_address: Optional[int] = None,
        creation_code: Optional[bytes] = None,
        contract_name: Optional[str] = None,
    ) -> None:
        from mythril_tpu.core.transaction import symbolic as sym_tx

        pre_configured = world_state is not None and target_address is not None
        self._fire("start_sym_exec")
        time_handler.start_execution(self.execution_timeout)
        self.time = time.time()

        if pre_configured:
            self.open_states = [world_state]
            self._execute_transactions(target_address)
        else:
            assert creation_code is not None
            created = sym_tx.execute_contract_creation(
                self, creation_code, contract_name or "MAIN"
            )
            log.info(
                "finished creation; %d open states, created address %s",
                len(self.open_states),
                created.address,
            )
            if created.address.value is not None:
                self._execute_transactions(created.address.value)

        self._fire("stop_sym_exec")

    def resume(
        self, open_states: List[WorldState], completed_transactions: int, address: int
    ) -> None:
        """Continue from a checkpointed frontier: same start/stop framing as
        ``sym_exec`` but seeded with restored open states and skipping the
        transactions a previous run already completed."""
        self._fire("start_sym_exec")
        time_handler.start_execution(self.execution_timeout)
        self.time = time.time()
        self.open_states = open_states
        self.resume_offset = completed_transactions
        self._execute_transactions(address)
        self._fire("stop_sym_exec")

    def _execute_transactions(self, address: int) -> None:
        """Symbolic-tx loop: each round reseeds from surviving open states.

        When ``checkpoint_path`` is set, the surviving frontier is snapshot
        to disk after every completed transaction (the recovery story the
        reference lacks, SURVEY.md §5.4); ``resume_offset`` counts
        transactions already completed by a resumed run.
        """
        from mythril_tpu.core.transaction import symbolic as sym_tx

        self.executed_transactions = True
        for i in range(self.resume_offset, self.transaction_count):
            if not self.open_states:
                break
            # prune unreachable open states before the next round (batched:
            # one device sweep over all open world states)
            if not args.sparse_pruning:
                from mythril_tpu.smt.solver import check_satisfiable_batch

                flags = check_satisfiable_batch(
                    [s.constraints.get_all_raw() for s in self.open_states]
                )
                self.open_states = [
                    s for s, ok in zip(self.open_states, flags) if ok
                ]
            if not self.open_states:
                break
            log.info(
                "starting message call transaction %d; %d open states",
                i,
                len(self.open_states),
            )
            self._fire("start_sym_trans")
            sym_tx.execute_message_call(self, address)
            self._fire("stop_sym_trans")
            if self.checkpoint_path:
                from mythril_tpu.support.checkpoint import save_checkpoint

                try:
                    save_checkpoint(
                        self.checkpoint_path,
                        i + 1,
                        self.open_states,
                        target_address=address,
                    )
                except Exception as e:  # snapshots are best-effort
                    log.warning("checkpoint write failed: %s", e)

    # ------------------------------------------------------------------
    # main loop (reference svm.py:261-304)
    # ------------------------------------------------------------------

    def exec(self, create: bool = False, track_gas: bool = False) -> Optional[List[GlobalState]]:
        final_states: List[GlobalState] = []
        self._fire("start_exec")
        if args.frontier and args.frontier_force and not create and not track_gas:
            # forced mode (tests, explicit override): engage the device
            # before any host stepping.  The production path defers the
            # first drain past a short host warmup (loop below) so the
            # engine's throughput bail compares segment rates against the
            # MEASURED host stepping rate instead of a blind floor.
            try:
                from mythril_tpu.frontier import FrontierEngine

                FrontierEngine(self).drain_work_list()
            except Exception as e:  # graceful degradation, never lose a run
                log.warning(
                    "frontier engine failed; host engine continues: %s",
                    e, exc_info=True,
                )
        start = time.perf_counter()
        deadline = (
            start + self.create_timeout
            if create and self.create_timeout
            else start + self.execution_timeout
        )
        frontier_live = args.frontier and not create and not track_gas
        frontier_enabled = frontier_live  # config verdict, never re-armed
        rearm_width = 0  # work-list width that re-arms a zero-drain disable
        pending_seeds = 0  # fresh frames added since the last drain attempt
        iteration = 0
        first_drain_attempted = False
        zero_drains = 0  # consecutive drain attempts that executed nothing
        for global_state in self.strategy:
            if time.perf_counter() > deadline or time_handler.time_remaining() <= 0:
                log.info("%s timeout reached; halting exec loop", "create" if create else "execution")
                break
            # --coverage-target: the request contract ends exploration at
            # the bar (or on an all-codes plateau); checked every 16 host
            # steps so the ledger scan stays off the per-step critical path
            if (args.coverage_target and not create and iteration % 16 == 0
                    and self._coverage_target_stop()):
                log.info("coverage target reached; halting exec loop")
                break
            t_step = time.perf_counter()
            new_states, op_code = self.execute_state(global_state)
            if self.requires_statespace:
                self.manage_cfg(op_code, new_states)
            if not args.sparse_pruning:
                new_states = self._prune_unsatisfiable(new_states)
            # host stepping pace (states/s over the FULL iteration,
            # including sibling pruning — the true wall cost of advancing
            # one state on the host): the frontier's mid-run throughput
            # bail compares device segment rates against it — the host's
            # own pace on a workload spans 5..900 states/s, so no fixed
            # floor can stand in for it
            self._host_step_secs += time.perf_counter() - t_step
            self._host_steps += 1
            self.work_list.extend(new_states)
            self.total_states += len(new_states)
            if track_gas and not new_states:
                final_states.append(global_state)
            # nested frontier segments (SURVEY.md §7.4 item 4): inner
            # message-call frames pushed by the CALL-family handlers are
            # fresh pc=0 seeds, and mid-frame states (resumed callers,
            # earlier spills) re-enter via the engine's mid-frame encoder —
            # periodically hand them to the device (the engine's own width
            # gate decides whether a drain pays)
            iteration += 1
            pending_seeds += len(new_states)
            # a zero-drain disable fires early (iterations ~24-40), when
            # work lists are still narrow; a contract whose fanout widens
            # later must get the device back.  Re-arm when the work list
            # clearly outgrows the width that was being rejected, doubling
            # the threshold each time so flapping decays geometrically.
            if (
                frontier_enabled
                and not frontier_live
                and rearm_width
                and len(self.work_list) >= rearm_width
            ):
                frontier_live = True
                zero_drains = 0
                rearm_width *= 2
            # attempt a drain only once enough seeds accumulated to clear
            # the engine's own width gate — a handful would bail there
            # anyway, and every attempt rescans the work list.  The FIRST
            # attempt waits until host_step_rate is measurable (production
            # mode) so the engine's throughput bail starts informed — the
            # samples persist on the laser, so only the first transaction
            # of an analysis ever pays the warmup; explorations shorter
            # than it are trivially host-fast and never engage the device.
            if frontier_live and iteration % 8 == 0 and (
                pending_seeds >= 8
                or (not first_drain_attempted and self.work_list)
            ) and (
                args.frontier_force or self.host_step_rate() is not None
            ):
                first_drain_attempted = True
                pending_seeds = 0
                try:
                    from mythril_tpu.frontier import FrontierEngine

                    executed = FrontierEngine(self).drain_work_list()
                    # three consecutive no-op attempts mean the engine's
                    # gates (width / verdict memos) reject this workload:
                    # stop paying the per-attempt work-list rescan for the
                    # rest of this transaction
                    zero_drains = zero_drains + 1 if executed == 0 else 0
                    if zero_drains >= 3:
                        frontier_live = False
                        # never shrink below the last re-arm threshold, or
                        # a work list oscillating around it would flap the
                        # device on/off at a constant width forever
                        rearm_width = max(
                            2 * len(self.work_list), 32, rearm_width
                        )
                except Exception as e:  # graceful degradation
                    log.warning(
                        "nested frontier drain failed; host continues: %s", e,
                        exc_info=True,
                    )
        self._fire("stop_exec")
        return final_states if track_gas else None

    def _coverage_target_stop(self) -> bool:
        """True when the adaptive controller's --coverage-target verdict
        says exploration is over (bar reached or plateau)."""
        try:
            # the instruction-coverage plugin only lands its bitmap in
            # the exploration ledger at stop_sym_exec; the verdict needs
            # the LIVE view, so flush the in-memory planes first
            plugin = getattr(self, "coverage_plugin", None)
            if plugin is not None and getattr(plugin, "coverage", None):
                from mythril_tpu.observability.exploration import (
                    get_exploration_ledger,
                )
                from mythril_tpu.support.support_utils import get_code_hash

                led = get_exploration_ledger()
                for code, (total, seen) in plugin.coverage.items():
                    led.record_instr(
                        get_code_hash(code), total,
                        [i for i, hit in enumerate(seen) if hit],
                    )
            from mythril_tpu.adaptive import get_adaptive_controller

            return get_adaptive_controller().coverage_stop() is not None
        except Exception:  # the contract must never break a run
            log.debug("coverage-target check failed", exc_info=True)
            return False

    @staticmethod
    def _prune_unsatisfiable(states: List[GlobalState]) -> List[GlobalState]:
        """Drop successors with unsatisfiable path conditions.

        Multiple successors (JUMPI siblings) are decided in ONE batched
        solver sweep — on device backends that is a single tape-VM dispatch
        for the whole fork instead of one per state (SURVEY.md §7: the
        pruner as a batched masked reduction over the frontier).
        """
        if not states:
            return states
        if len(states) == 1:
            return states if states[0].world_state.constraints.is_possible else []
        from mythril_tpu.smt.solver import check_satisfiable_batch

        flags = check_satisfiable_batch(
            [s.world_state.constraints.get_all_raw() for s in states]
        )
        return [s for s, ok in zip(states, flags) if ok]

    # ------------------------------------------------------------------
    # single-instruction execution (reference svm.py:336-449)
    # ------------------------------------------------------------------

    def execute_state(
        self, global_state: GlobalState
    ) -> Tuple[List[GlobalState], Optional[str]]:
        instructions = global_state.environment.code.instruction_list
        try:
            instruction = instructions[global_state.mstate.pc]
            op_code = instruction.opcode
        except IndexError:
            # implicit STOP off the end of code
            self._add_world_state(global_state)
            return [], None
        global_state.op_code = op_code

        # required stack elements check (reference svm.py:351-357); an arity
        # miss is an exceptional halt, not an engine error
        if op_code in OPCODES and len(global_state.mstate.stack) < stack_inputs(op_code):
            return (
                self._handle_vm_exception(
                    global_state, op_code, f"not enough stack elements for {op_code}"
                ),
                op_code,
            )

        try:
            self._fire("execute_state", global_state)
        except PluginSkipState:
            return [], None

        # detection-module pre hooks
        for hook in self._pre_hooks[op_code]:
            try:
                hook(global_state)
            except PluginSkipState:
                return [], None

        self.executed_instruction_count += 1
        try:
            inst = Instruction(
                op_code,
                self.dynamic_loader,
                pre_hooks=self.instr_pre_hook[op_code] + self.instr_pre_hook["*"],
                post_hooks=self.instr_post_hook[op_code] + self.instr_post_hook["*"],
            )
            new_global_states = inst.evaluate(global_state)

        except VmException as error:
            log.debug("VM exception at pc %d: %s", global_state.mstate.pc, error)
            new_global_states = self._handle_vm_exception(global_state, op_code, str(error))

        except TransactionStartSignal as start_signal:
            self._fire("transaction_start", start_signal.global_state, start_signal.transaction)
            new_global_state = start_signal.transaction.initial_global_state()
            new_global_state.transaction_stack = list(
                start_signal.global_state.transaction_stack
            ) + [(start_signal.transaction, start_signal.global_state)]
            new_global_state.node = global_state.node
            new_global_state.mstate.depth = global_state.mstate.depth
            return [new_global_state], op_code

        except TransactionEndSignal as end_signal:
            transaction, return_global_state = end_signal.global_state.transaction_stack[-1]
            self._fire("transaction_end", end_signal.global_state, transaction, return_global_state, end_signal.revert)
            if return_global_state is None:
                # outermost frame
                if (
                    not isinstance(transaction, ContractCreationTransaction)
                    or transaction.return_data is not None
                ) and not end_signal.revert:
                    end_signal.global_state.world_state.node = global_state.node
                    self._check_potential_issues(end_signal.global_state)
                    self._add_world_state(end_signal.global_state)
                new_global_states = []
            else:
                new_global_states = self._end_message_call(
                    _copy.copy(return_global_state),
                    end_signal.global_state,
                    revert_changes=end_signal.revert,
                    return_data=transaction.return_data,
                    ended_transaction=transaction,
                )

        # detection-module post hooks
        if self._post_hooks[op_code]:
            kept = []
            for new_state in new_global_states:
                skip = False
                for hook in self._post_hooks[op_code]:
                    try:
                        hook(new_state)
                    except PluginSkipState:
                        skip = True
                        break
                if not skip:
                    kept.append(new_state)
            new_global_states = kept

        # depth counts control-flow transfers (JUMP/JUMPI bump it in their
        # handlers, reference instructions.py:1552,1603,1628) — NOT every
        # instruction, or max_depth=128 would cap runs at 128 opcodes
        return new_global_states, op_code

    def _handle_vm_exception(
        self, global_state: GlobalState, op_code: str, error: str
    ) -> List[GlobalState]:
        """Unwind the tx stack on exceptional halt (reference svm.py:317-334)."""
        transaction, return_global_state = global_state.transaction_stack[-1]
        if return_global_state is None:
            return []
        return self._end_message_call(
            _copy.copy(return_global_state),
            global_state,
            revert_changes=True,
            return_data=None,
            ended_transaction=transaction,
        )

    def _end_message_call(
        self,
        return_global_state: GlobalState,
        global_state: GlobalState,
        revert_changes: bool = False,
        return_data=None,
        ended_transaction=None,
    ) -> List[GlobalState]:
        """Resume the caller frame after a child tx (reference svm.py:451-504)."""
        if not revert_changes:
            # adopt the child's world (storage/balances) and its constraints
            return_global_state.world_state = global_state.world_state
            addr = return_global_state.environment.active_account.address.value
            if addr is not None and addr in global_state.world_state.accounts:
                return_global_state.environment.active_account = (
                    global_state.world_state.accounts[addr]
                )
        else:
            # reverted: state rolls back, path constraints remain
            for constraint in global_state.world_state.constraints[
                len(return_global_state.world_state.constraints) :
            ]:
                return_global_state.world_state.constraints.append(constraint)

        # child's gas is spent either way
        return_global_state.mstate.min_gas_used += global_state.mstate.min_gas_used
        return_global_state.mstate.max_gas_used += global_state.mstate.max_gas_used

        return_global_state.last_return_data = return_data
        if ended_transaction is not None:
            return_global_state.call_output_location = (
                getattr(ended_transaction, "memory_out_offset", None),
                getattr(ended_transaction, "memory_out_size", None),
            )

        # resume via the <op>_post handler of the call instruction
        op_code = return_global_state.environment.code.instruction_list[
            return_global_state.mstate.pc
        ].opcode
        try:
            new_states = Instruction(op_code, self.dynamic_loader).evaluate(
                return_global_state, post=True
            )
        except VmException:
            new_states = []
        return new_states

    def _check_potential_issues(self, global_state: GlobalState) -> None:
        """Solve deferred issues at tx end (reference svm.py:423)."""
        try:
            from mythril_tpu.analysis.potential_issues import check_potential_issues

            check_potential_issues(global_state)
        except ImportError:
            pass

    def _add_world_state(self, global_state: GlobalState) -> None:
        """Archive a surviving world state as a seed for the next tx."""
        try:
            self._fire("add_world_state", global_state)
        except (PluginSkipState, PluginSkipWorldState):
            return
        self.open_states.append(global_state.world_state)

    # ------------------------------------------------------------------
    # CFG bookkeeping (reference svm.py:506-532)
    # ------------------------------------------------------------------

    def manage_cfg(self, opcode: Optional[str], new_states: List[GlobalState]) -> None:
        if opcode is None:
            return
        if opcode == "JUMP":
            for state in new_states:
                self._new_node_state(state)
        elif opcode == "JUMPI":
            for state in new_states:
                condition = (
                    state.world_state.constraints[-1]
                    if state.world_state.constraints
                    else None
                )
                self._new_node_state(state, JumpType.CONDITIONAL, condition)
        elif opcode in ("CALL", "CALLCODE", "DELEGATECALL", "STATICCALL", "CREATE", "CREATE2"):
            for state in new_states:
                self._new_node_state(state, JumpType.CALL)
        elif opcode in ("RETURN", "STOP"):
            for state in new_states:
                self._new_node_state(state, JumpType.RETURN)
        for state in new_states:
            if state.node is not None:
                state.node.states.append(state)

    def _new_node_state(self, state: GlobalState, edge_type=JumpType.UNCONDITIONAL, condition=None) -> None:
        if not self.requires_statespace:
            return
        old_node = state.node
        new_node = Node(state.environment.active_account.contract_name)
        new_node.start_addr = state.get_current_instruction()["address"]
        self.nodes[new_node.uid] = new_node
        if old_node is not None:
            self.edges.append(
                Edge(old_node.uid, new_node.uid, edge_type=edge_type, condition=condition)
            )
        state.node = new_node
        new_node.constraints = state.world_state.constraints.copy()
        # function-entry naming
        address = new_node.start_addr
        env = state.environment
        if env.code is not None and address in env.code.address_to_function_name:
            new_node.flags |= NodeFlags.FUNC_ENTRY
            new_node.function_name = env.code.address_to_function_name[address]
        elif old_node is not None:
            new_node.function_name = old_node.function_name
