"""Transaction models and the engine's control-transfer signals.

Reference parity: mythril/laser/ethereum/transaction/transaction_models.py:
signals (:35-54), BaseTransaction (:57), MessageCallTransaction (:159),
ContractCreationTransaction (:194), TxIdManager (:20-32).  Control transfer
between call frames is exception-driven in the worklist engine — a deliberate
parity choice: the host orchestrates frames; device kernels only ever see
single-frame segments.
"""

from __future__ import annotations

import copy as _copy
from typing import Optional

from mythril_tpu.core.state.account import Account
from mythril_tpu.core.state.calldata import (
    BaseCalldata,
    ConcreteCalldata,
    SymbolicCalldata,
)
from mythril_tpu.core.state.constraints import Constraints
from mythril_tpu.core.state.environment import Environment
from mythril_tpu.core.state.global_state import GlobalState
from mythril_tpu.core.state.machine_state import MachineState
from mythril_tpu.core.state.world_state import WorldState
from mythril_tpu.smt import BitVec, UGE, symbol_factory


class TxIdManager:
    """Monotone transaction-id source (reference :20-32)."""

    _instance = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
            cls._instance._next = 0
        return cls._instance

    def get_next_tx_id(self) -> str:
        self._next += 1
        return str(self._next)

    def restart_counter(self) -> None:
        self._next = 0

    def ensure_above(self, used_id: str) -> None:
        """Advance past an id restored from a checkpoint: new transactions
        must never reuse a restored id (symbols are named by tx id and
        interned, so a collision aliases variables across transactions)."""
        try:
            self._next = max(self._next, int(used_id))
        except ValueError:
            pass


tx_id_manager = TxIdManager()


class TransactionStartSignal(Exception):
    """Raised by CALL-family handlers to push a new frame."""

    def __init__(self, transaction, op_code: str, global_state: GlobalState):
        self.transaction = transaction
        self.op_code = op_code
        self.global_state = global_state


class TransactionEndSignal(Exception):
    """Raised by terminal handlers (STOP/RETURN/REVERT/SELFDESTRUCT)."""

    def __init__(self, global_state: GlobalState, revert: bool = False):
        self.global_state = global_state
        self.revert = revert


class BaseTransaction:
    def __init__(
        self,
        world_state: WorldState,
        callee_account: Optional[Account] = None,
        caller: Optional[BitVec] = None,
        call_data: Optional[BaseCalldata] = None,
        identifier: Optional[str] = None,
        gas_price=None,
        gas_limit: int = 8_000_000,
        origin=None,
        code=None,
        call_value=None,
        init_call_data: bool = True,
        static: bool = False,
        base_fee=None,
        block_env: Optional[dict] = None,
    ):
        self.world_state = world_state
        self.id = identifier or tx_id_manager.get_next_tx_id()
        self.gas_limit = gas_limit
        self.gas_price = (
            gas_price
            if gas_price is not None
            else symbol_factory.BitVecSym(f"{self.id}_gasprice", 256)
        )
        self.base_fee = (
            base_fee
            if base_fee is not None
            else symbol_factory.BitVecSym(f"{self.id}_basefee", 256)
        )
        self.origin = (
            origin if origin is not None else symbol_factory.BitVecSym(f"{self.id}_origin", 256)
        )
        self.code = code
        self.caller = caller
        self.callee_account = callee_account
        if call_data is None and init_call_data:
            # symbolic, not empty-concrete (reference transaction_models.py:
            # 103-104): creation transactions read constructor arguments
            # through the calldata model (codesize_/codecopy_ route reads
            # past the code end there), so the default must be able to
            # carry symbolic argument bytes
            call_data = SymbolicCalldata(self.id)
        self.call_data = call_data
        self.call_value = (
            call_value
            if call_value is not None
            else symbol_factory.BitVecSym(f"{self.id}_callvalue", 256)
        )
        self.static = static
        self.return_data = None
        # optional concrete block parameters (Environment attribute -> BitVec)
        # applied to every Environment this tx spawns; used by fixture replay
        self.block_env = block_env

    def _apply_block_env(self, environment) -> None:
        for attr, value in (self.block_env or {}).items():
            setattr(environment, attr, value)

    def initial_global_state_from_environment(self, environment, active_function):
        """Seed a GlobalState for this tx + the sender-balance constraint."""
        from mythril_tpu.core.state.machine_state import MachineState

        global_state = GlobalState(
            self.world_state,
            environment,
            machine_state=MachineState(gas_limit=self.gas_limit),
        )
        global_state.environment.active_function_name = active_function
        sender = environment.sender
        value = environment.callvalue
        # sender must afford the transfer (reference :120-145)
        global_state.world_state.constraints.append(
            UGE(global_state.world_state.balances[sender], value)
        )
        global_state.world_state.balances[sender] -= value
        global_state.world_state.balances[environment.active_account.address] += value
        return global_state

    def __str__(self):
        addr = (
            self.callee_account.address
            if self.callee_account is not None
            else "<creating>"
        )
        return f"{type(self).__name__} {self.id} to {addr}"


class MessageCallTransaction(BaseTransaction):
    """A symbolic or concrete message call (reference :159)."""

    def initial_global_state(self) -> GlobalState:
        environment = Environment(
            self.callee_account,
            self.caller,
            self.call_data,
            self.gas_price,
            self.call_value,
            self.origin,
            code=self.code or self.callee_account.code,
            basefee=self.base_fee,
            static=self.static,
        )
        self._apply_block_env(environment)
        return super().initial_global_state_from_environment(
            environment, active_function="fallback"
        )

    def end(self, global_state: GlobalState, return_data=None, revert: bool = False):
        self.return_data = return_data
        raise TransactionEndSignal(global_state, revert)


class ContractCreationTransaction(BaseTransaction):
    """Creation tx: executes init code, assigns runtime code on RETURN.

    Reference :194-271 — snapshots ``prev_world_state`` for exploit-report
    initial-state reconstruction, and ``end()`` installs the returned runtime
    bytecode into the created account.
    """

    def __init__(self, *args, contract_name=None, **kwargs):
        # snapshot the pre-state before the account is created
        world_state = kwargs.get("world_state") if "world_state" in kwargs else args[0]
        self.prev_world_state = _copy.copy(world_state)
        super().__init__(*args, **kwargs)
        self.contract_name = contract_name or "unknown_contract"
        if self.callee_account is None:
            self.callee_account = self.world_state.create_account(
                0, concrete_storage=True
            )
        self.callee_account.contract_name = self.contract_name

    def initial_global_state(self) -> GlobalState:
        environment = Environment(
            self.callee_account,
            self.caller,
            self.call_data,
            self.gas_price,
            self.call_value,
            self.origin,
            code=self.code,
            basefee=self.base_fee,
        )
        self._apply_block_env(environment)
        return super().initial_global_state_from_environment(
            environment, active_function="constructor"
        )

    def end(self, global_state: GlobalState, return_data=None, revert: bool = False):
        from mythril_tpu.frontend.disassembler import Disassembly

        if not revert and return_data is not None:
            if not isinstance(return_data, (bytes, bytearray)):
                # runtime code with SYMBOLIC bytes: solc >= 0.8 writes
                # immutable values into PUSH operands of the returned code
                # before RETURN, and a constructor-argument-derived
                # immutable is symbolic.  DELIBERATE DEVIATION from the
                # reference (ROADMAP.md "Known deviations"): Mythril keeps
                # such entries symbolic — its Disassembly accepts BitVec
                # operand bytes (reference transaction_models.py:249-253),
                # so message-call analysis can still constrain the
                # immutable's value through the PUSHed symbol.  This build
                # concretizes the symbolic operand bytes to ZERO and
                # deploys.  The code STRUCTURE (opcodes, jump targets) is
                # identical, but any issue whose trigger depends on the
                # actual immutable value (e.g. an owner-address immutable
                # gating a selfdestruct) can be missed or mis-confirmed —
                # a recall risk accepted to keep deployed code fully
                # concrete for the device frontier's packed code buffers.
                return_data = bytes(
                    (b.value or 0) if hasattr(b, "value") else int(b)
                    for b in return_data
                )
            global_state.environment.active_account.code = Disassembly(bytes(return_data))
            self.return_data = global_state.environment.active_account.address
        elif not revert:
            self.return_data = None
        raise TransactionEndSignal(global_state, revert)
