"""VM-level exception family (reference parity: laser/ethereum/evm_exceptions.py:1-43)."""


class VmException(Exception):
    """Base for exceptional halts inside the symbolic VM."""


class StackUnderflowException(IndexError, VmException):
    pass


class StackOverflowException(VmException):
    pass


class InvalidJumpDestination(VmException):
    pass


class InvalidInstruction(VmException):
    pass


class OutOfGasException(VmException):
    pass


class WriteProtection(VmException):
    """State modification attempted inside STATICCALL context."""
