"""Differential fuzz for the device SAT tier.

Three obligations over seeded random *narrow* conditions (small widths,
few variables — the tier's admission fragment):

1. **Host/device bit-identity** — the numpy driver and the jitted
   ``lax.while_loop`` twin produce byte-identical status AND assignment
   planes for the same packed CNF.
2. **SAT soundness** — every SAT verdict carries a model that satisfies
   the ORIGINAL conjunction under ``concrete_eval`` (the facade
   validates internally; the test re-validates independently).
3. **UNSAT soundness** — on rows narrow enough to brute-force, an UNSAT
   verdict is checked against exhaustive enumeration of the free
   variables (an exact oracle with no Z3 dependency).

Rows with a known model additionally assert the tier never reports
them UNSAT (the absdomain fuzz's no-false-UNSAT contract).
"""

import random

import numpy as np
import pytest

from mythril_tpu import devsolver
from mythril_tpu.devsolver import blaster, device, kernel
from mythril_tpu.native.bitblast import Unsupported
from mythril_tpu.smt import concrete_eval, terms
from mythril_tpu.smt.concrete_eval import Assignment

_WIDTHS = (4, 8)

_BIN = [terms.add, terms.sub, terms.band, terms.bor, terms.bxor]
_UN = [terms.bnot, terms.neg]
_CMP = [terms.eq, terms.ult, terms.ule, terms.slt, terms.sle]


def _gen_pool(rng: random.Random, tag: str, n_vars: int = 2):
    by_width = {}
    asg_scalars = {}
    for w in _WIDTHS:
        leaves = []
        for i in range(n_vars):
            v = terms.var(f"dfz_{tag}_{w}_{i}", w)
            asg_scalars[v] = rng.getrandbits(w)
            leaves.append(v)
        leaves.append(terms.const(rng.getrandbits(w), w))
        by_width[w] = leaves

    for _ in range(25):
        w = rng.choice(_WIDTHS)
        pool = by_width[w]
        kind = rng.random()
        if kind < 0.5:
            t = rng.choice(_BIN)(rng.choice(pool), rng.choice(pool))
        elif kind < 0.6:
            t = rng.choice(_UN)(rng.choice(pool))
        elif kind < 0.7:
            # const shifts / const multiply stay in the narrow fragment
            c = terms.const(rng.randrange(0, w + 2), w)
            t = rng.choice([terms.shl, terms.lshr, terms.ashr])(
                rng.choice(pool), c)
        elif kind < 0.78:
            t = terms.mul(rng.choice(pool),
                          terms.const(rng.randrange(0, 8), w))
        elif kind < 0.86 and w == 4:
            t = (terms.zext if rng.random() < 0.5 else terms.sext)(
                rng.choice(pool), 4)
            by_width[8].append(t)
            continue
        elif kind < 0.94:
            src_w = rng.choice([x for x in _WIDTHS if x >= w])
            hi = rng.randrange(w - 1, src_w)
            t = terms.extract(hi, hi - w + 1, rng.choice(by_width[src_w]))
        else:
            c = rng.choice(_CMP)(rng.choice(pool), rng.choice(pool))
            t = terms.ite(c, rng.choice(pool), rng.choice(pool))
        pool.append(t)
    return by_width, Assignment(scalars=asg_scalars)


def _true_conjuncts(rng, by_width, asg, n):
    out = []
    flat = [t for pool in by_width.values() for t in pool]
    while len(out) < n:
        a, b = rng.choice(flat), rng.choice(flat)
        if a.width != b.width:
            continue
        c = rng.choice(_CMP)(a, b)
        if c.op == "const":
            out.append(c if c.aux else terms.lnot(c))
            continue
        v = concrete_eval.evaluate_one(c, asg)
        out.append(c if v else terms.lnot(c))
    return out


def _random_conjuncts(rng, by_width, n):
    """Unoriented comparisons — UNSAT rows arise naturally."""
    out = []
    flat = [t for pool in by_width.values() for t in pool]
    while len(out) < n:
        a, b = rng.choice(flat), rng.choice(flat)
        if a.width != b.width:
            continue
        c = rng.choice(_CMP)(a, b)
        if c.op == "const":
            continue
        out.append(c if rng.random() < 0.5 else terms.lnot(c))
    return out


def _brute_force_sat(conjuncts) -> bool:
    """Exhaustive oracle over the free bit-vector variables."""
    fv = sorted(
        (v for v in terms.free_vars(conjuncts) if terms.is_bv_sort(v.sort)),
        key=lambda v: v.tid,
    )
    total_bits = sum(v.width for v in fv)
    assert total_bits <= 16, f"row too wide to brute force: {total_bits}"
    for combo in range(1 << total_bits):
        asg = Assignment()
        off = 0
        for v in fv:
            asg.scalars[v] = (combo >> off) & ((1 << v.width) - 1)
            off += v.width
        try:
            vals = concrete_eval.evaluate(list(conjuncts), asg)
        except Exception:
            continue
        if all(vals[c] for c in conjuncts):
            return True
    return False


@pytest.mark.parametrize("seed", range(25))
def test_host_device_bit_identical(seed):
    rng = random.Random(0xD5D0 + seed)
    by_width, asg = _gen_pool(rng, f"hd{seed}")
    rows = [_true_conjuncts(rng, by_width, asg, rng.randrange(1, 4))
            for _ in range(2)]
    rows += [_random_conjuncts(rng, by_width, rng.randrange(1, 4))
             for _ in range(2)]

    blasted = []
    for row in rows:
        try:
            b = blaster.blast(row)
        except Unsupported:
            continue
        if b.verdict is None:
            blasted.append(b)
    if not blasted:
        pytest.skip("every row folded or fell through for this seed")

    plane = kernel.pack_plane(
        [(b.clauses, b.dec_vars) for b in blasted],
        max(b.n_vars for b in blasted),
    )
    sh, ah = kernel.run_host(plane, 1024)
    sd, ad = device.run_device(plane, 1024)
    assert np.array_equal(sh, sd), f"seed {seed}: status diverged"
    assert np.array_equal(ah, ad), f"seed {seed}: assignment diverged"


@pytest.mark.parametrize("seed", range(25))
def test_sat_models_validate_and_no_false_unsat(seed):
    rng = random.Random(0x5A7 + seed)
    by_width, asg = _gen_pool(rng, f"sat{seed}")
    row = _true_conjuncts(rng, by_width, asg, rng.randrange(1, 5))
    devsolver.reset_state()
    status, model = devsolver.decide(row)
    # the row is TRUE under asg, so UNSAT would be a soundness bug
    assert status != "unsat", (
        f"seed {seed}: devsolver refuted a conjunction with a model"
    )
    if status == "sat":
        vals = concrete_eval.evaluate(list(row), model)
        assert all(vals[c] for c in row), (
            f"seed {seed}: returned model does not satisfy the conjunction"
        )


@pytest.mark.parametrize("seed", range(25))
def test_unsat_verdicts_against_brute_force(seed):
    # 4-bit only, 3 vars -> at most 12 free bits: exhaustively checkable
    rng = random.Random(0xB40 + seed)
    v = [terms.var(f"dbf_{seed}_{i}", 4) for i in range(3)]
    pool = v + [terms.const(rng.getrandbits(4), 4) for _ in range(2)]
    for _ in range(10):
        pool.append(rng.choice(_BIN)(rng.choice(pool), rng.choice(pool)))
    row = []
    while len(row) < rng.randrange(2, 5):
        a, b = rng.choice(pool), rng.choice(pool)
        c = rng.choice(_CMP)(a, b)
        if c.op == "const":
            continue
        row.append(c if rng.random() < 0.5 else terms.lnot(c))

    devsolver.reset_state()
    status, model = devsolver.decide(row)
    truth = _brute_force_sat(row)
    if status == "unsat":
        assert not truth, (
            f"seed {seed}: devsolver UNSAT but brute force found a model"
        )
    elif status == "sat":
        assert truth, f"seed {seed}: devsolver SAT on an UNSAT row"
        vals = concrete_eval.evaluate(list(row), model)
        assert all(vals[c] for c in row)
    # unknown is always allowed


def test_decided_fraction_is_nonzero():
    """The admission fragment is not vacuous: across the fuzz corpus a
    healthy fraction of narrow rows are DECIDED, not just attempted."""
    rng = random.Random(0xC0FFEE)
    decided = total = 0
    for seed in range(20):
        by_width, asg = _gen_pool(rng, f"fr{seed}")
        row = _true_conjuncts(rng, by_width, asg, 2)
        devsolver.reset_state()
        status, _ = devsolver.decide(row)
        total += 1
        decided += status in ("sat", "unsat")
    assert decided > total // 2, f"only {decided}/{total} rows decided"
