"""Differential recall: host probe vs production hybrid vs forced-exact CDCL.

VERDICT.md round-1 weak spot #2: the probe treats "no model found in budget"
as unsat, which can silently prune feasible paths.  This suite measures that
completeness boundary: the same contract corpus analyzed under three solver
configurations must produce identical issue sets, and the
``unknown_as_unsat`` counter (SolverStatistics) must stay at zero — i.e.
every prune decision was backed by an exact verdict or a concrete model.
"""

import pytest

from mythril_tpu.analysis.security import fire_lasers, reset_callback_modules
from mythril_tpu.analysis.symbolic import SymExecWrapper
from mythril_tpu.smt.solver import SolverStatistics, clear_model_cache
from mythril_tpu.support.model import _get_model_cached
from mythril_tpu.support.support_args import args as global_args

# dispatcher prelude: selector(kill()=0x41c0e1b5) -> JUMPDEST at 0x14=20
DISPATCH = "60003560e01c6341c0e1b5146014576000" + "6000fd" + "5b"

# the corpus: 12 small contracts spanning the detector surface
CORPUS = {
    "selfdestruct": DISPATCH + "33ff",
    "invalid": DISPATCH + "fe",
    "tx_origin": DISPATCH + "323314601b5700" "5b00",
    "overflow_sstore": DISPATCH + "600435" "6001" "01" "6000" "55" "00",
    "timestamp": DISPATCH + "426064" "11" "601c57" "00" "5b00",
    "clean_store": "602a60005500",
    "ether_send": DISPATCH + "6000" "6000" "6000" "6000" "6064" "33" "61ffff" "f1" "00",
    "double_send": DISPATCH
    + ("6000" "6000" "6000" "6000" "6000" "33" "61ffff" "f1" "50") * 2
    + "00",
    "gated_kill": DISPATCH + "600054" "6001" "14" "601f" "57" "6000" "6000" "fd" "5b" "33ff",
    "callvalue_branch": DISPATCH + "34" "6019" "57" "00" "5b" "33ff",
    "underflow_sub": DISPATCH + "600435" "6001" "90" "03" "6000" "55" "00",
    "caller_check": DISPATCH + "3373aaaaaaaaaaaaaaaaaaaaaaaaaaaaaaaaaaaaaaaa14601f5760006000fd5b33ff",
}


def _analyze(code_hex: str, backend: str):
    reset_callback_modules()
    from mythril_tpu.analysis.module.loader import ModuleLoader

    for m in ModuleLoader().get_detection_modules():
        m.cache.clear()
    clear_model_cache()
    _get_model_cached.cache_clear()
    old = global_args.probe_backend
    global_args.probe_backend = backend
    try:
        sym = SymExecWrapper(
            bytes.fromhex(code_hex),
            address=0x0901D12E,
            strategy="dfs",
            transaction_count=2,
            execution_timeout=120,
        )
        issues = fire_lasers(sym)
    finally:
        global_args.probe_backend = old
    return sorted((i.swc_id, i.address, i.title) for i in issues)


@pytest.mark.parametrize("name", sorted(CORPUS))
def test_recall_matches_across_solver_modes(name):
    from mythril_tpu.native import bitblast

    if not bitblast.available():
        pytest.skip("native CDCL solver unavailable; forced-exact mode "
                    "cannot run (environmental, not a recall regression)")
    code = CORPUS[name]
    stats = SolverStatistics()
    stats.unknown_as_unsat = 0
    host = _analyze(code, "host")
    assert stats.unknown_as_unsat == 0, (
        f"{name}: host probe pruned on UNKNOWN {stats.unknown_as_unsat} times"
    )
    cdcl = _analyze(code, "cdcl")
    assert host == cdcl, f"{name}: host probe recall differs from exact CDCL"
    auto = _analyze(code, "auto")
    assert host == auto, f"{name}: production hybrid recall differs from host"
