"""Wall-clock deadline shared by engine and solver.

Reference parity: mythril/laser/ethereum/time_handler.py:5-18 — the remaining
execution time clamps per-query solver timeouts (mythril/support/model.py:27-30).
"""

from __future__ import annotations

import time
from typing import Optional

from mythril_tpu.support.support_utils import Singleton


class TimeHandler(metaclass=Singleton):
    def __init__(self):
        self._start_time: Optional[float] = None
        self._execution_time: Optional[float] = None

    def start_execution(self, execution_time_seconds: float) -> None:
        self._start_time = time.time()
        self._execution_time = execution_time_seconds

    def time_remaining(self) -> float:
        """Seconds left; very large if never started."""
        if self._start_time is None:
            return 10**9
        return self._execution_time - (time.time() - self._start_time)


time_handler = TimeHandler()
