"""Process-wide frontier telemetry: where device execution stops and why.

The frontier is a fast path that degrades to the host engine by *parking*
paths (engine.py); which opcodes force the parks is exactly the data that
prioritizes widening device coverage, and how much of a run stayed
device-resident is the number that explains the measured speedup.  Counters
land in the report meta next to the solver statistics (reference parity:
engine telemetry via ExecutionInfo, mythril/analysis/report.py:319-320).

Since the observability subsystem landed this class is a thin facade:
every attribute is a property backed by a named metric in
``mythril_tpu.observability.metrics`` (prefix ``frontier.``), so the
``stats.segments += 1`` call sites and the ``as_dict()`` report shape
are unchanged while the same numbers flow into ``--metrics-out`` /
``meta.observability`` snapshots.
"""

from __future__ import annotations

from mythril_tpu.observability.metrics import get_registry
from mythril_tpu.support.support_utils import Singleton

_PREFIX = "frontier."


def _counter_prop(attr: str, doc: str = ""):
    name = _PREFIX + attr

    def fget(self):
        return get_registry().counter(name).value

    def fset(self, v):
        get_registry().counter(name).set(v)

    return property(fget, fset, doc=doc)


class FrontierStatistics(metaclass=Singleton):
    """Facade over the ``frontier.*`` metrics in the global registry."""

    device_instructions = _counter_prop(
        "device_instructions", "instructions executed on device")
    device_paths = _counter_prop(
        "device_paths", "paths that ran (fully or partly) on device")
    segments = _counter_prop("segments", "device segment dispatches")
    segment_s = _counter_prop(
        "segment_s", "wall time in segment dispatch + state pull")
    harvest_s = _counter_prop("harvest_s", "wall time in host-side harvest")
    mesh_devices = _counter_prop(
        "mesh_devices", ">0: segments ran path-sharded over a mesh")
    mid_injections = _counter_prop(
        "mid_injections", "mid-frame states re-entered on device")
    mid_encode_failures = _counter_prop(
        "mid_encode_failures", "mid-frame seeds bounced at encoding")
    semantic_parks = _counter_prop(
        "semantic_parks", "paths pinned host-side until stepped past")
    page_faults = _counter_prop(
        "page_faults", "paths that jumped off their code's resident window")
    page_repacks = _counter_prop(
        "page_repacks", "sync-point window moves folded into fresh tables")

    def __init__(self) -> None:
        self._materialize()

    @property
    def parks_by_opcode(self):
        """opcode name -> paths parked on it"""
        return get_registry().labeled_counter(_PREFIX + "parks_by_opcode")

    @property
    def parks_by_reason(self):
        """timeout/arena/narrow/batch-full"""
        return get_registry().labeled_counter(_PREFIX + "parks_by_reason")

    @property
    def microbench(self) -> dict:
        # device-only efficiency numbers (engine._run_microbench): pure
        # segment compute time via chained re-dispatch subtraction, so the
        # per-chip story is measurable independent of the host<->device link
        return get_registry().gauge(_PREFIX + "microbench", default={}).value

    @microbench.setter
    def microbench(self, v: dict) -> None:
        get_registry().gauge(_PREFIX + "microbench", default={}).set(v)

    def _materialize(self) -> None:
        """Force-create the backing metrics so snapshots always carry the
        full frontier block even before the first increment."""
        reg = get_registry()
        for attr in (
            "device_instructions", "device_paths", "segments",
            "mesh_devices", "mid_injections", "mid_encode_failures",
            "semantic_parks", "page_faults", "page_repacks",
        ):
            reg.counter(_PREFIX + attr)
        # float-typed wall-time accumulators (report emits 0.0, not 0)
        reg.counter(_PREFIX + "segment_s", initial=0.0)
        reg.counter(_PREFIX + "harvest_s", initial=0.0)
        # the harvest_wall_s aggregate split per phase (harvest.py), plus
        # the background floored-bucket compile — force-created so every
        # snapshot carries the full attribution block
        for phase in ("ingest", "solver", "replay", "commit"):
            reg.histogram(_PREFIX + "harvest.%s_s" % phase)
        reg.histogram(_PREFIX + "bucket_compile_s")
        reg.labeled_counter(_PREFIX + "parks_by_opcode")
        reg.labeled_counter(_PREFIX + "parks_by_reason")
        reg.gauge(_PREFIX + "microbench", default={})

    def reset(self) -> None:
        """Zero the frontier-scoped metrics.

        Note this deliberately does NOT touch the persistent-scope
        verdict metrics (``frontier.slow_code_verdicts`` etc.) that
        mirror engine.py's process-lifetime slow-segment bookkeeping.
        """
        get_registry().reset(prefix=_PREFIX)

    def record_park(self, opcode: str) -> None:
        self.parks_by_opcode[opcode] += 1
        self.parks_by_reason["opcode"] += 1

    def record_bulk_park(self, reason: str, n: int = 1) -> None:
        if n:
            self.parks_by_reason[reason] += n

    def as_dict(self) -> dict:
        return {
            "device_instructions": self.device_instructions,
            "device_paths": self.device_paths,
            "segments": self.segments,
            "mesh_devices": self.mesh_devices,
            "segment_s": round(self.segment_s, 3),
            "harvest_s": round(self.harvest_s, 3),
            "mid_injections": self.mid_injections,
            "mid_encode_failures": self.mid_encode_failures,
            "semantic_parks": self.semantic_parks,
            # page_{faults,repacks} intentionally absent: as_dict is the
            # seed-compatible facade shape (pinned byte-for-byte by
            # tests/observability/test_facades.py); paging telemetry lives
            # in the registry snapshot / meta.frontier instead
            "parks_by_opcode": dict(self.parks_by_opcode.most_common()),
            "parks_by_reason": dict(self.parks_by_reason.most_common()),
            **({"microbench": self.microbench} if self.microbench else {}),
        }
