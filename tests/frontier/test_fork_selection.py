"""Strategy-scored fork grants: the batched form of the host search
strategies (SURVEY.md §7.2 item 5).

With more JUMPI forks requested than free batch slots, the segment grants
by the configured selection mode — deepest-first (DFS flavor),
shallowest-first (BFS flavor), uncovered-target-first (coverage) — instead
of arbitrary slot order.  Denied parents pend pristine (H_PENDING_FORK), so
no path is lost either way; the mode only decides WHO gets the scarce slot.
"""

from collections import namedtuple

import jax
import numpy as np
import pytest

from mythril_tpu.frontier import ops as O
from mythril_tpu.frontier import step as step_mod
from mythril_tpu.frontier.arena import HostArena
from mythril_tpu.frontier.code import CodeTables, stacked_device_tables
from mythril_tpu.frontier.state import Caps, empty_state
from mythril_tpu.frontier.step import ArenaDev, CfgScalars, CodeDev, cached_segment
from mythril_tpu.smt import terms as T

Ins = namedtuple("Ins", "opcode address arg_int")

# one JUMPI; fall-through STOP; valid JUMPDEST target; STOP
PROGRAM = [
    Ins("JUMPI", 0, None),
    Ins("STOP", 1, None),
    Ins("JUMPDEST", 2, None),
    Ins("STOP", 3, None),
]

CAPS = Caps(B=4, K=1)
DEPTHS = (5, 9, 1)  # slots 0..2; slot 3 free


def _run_one_step(sel_mode: int, scores=(0, 0, 0)):
    arena = HostArena(CAPS.ARENA)
    row_zero = arena.const_row(0, 256)
    row_one = arena.const_row(1, 256)
    dest_row = arena.const_row(2, 256)  # byte address of the JUMPDEST
    cond_rows = [arena.var_row(T.var(f"c{i}", 256)) for i in range(3)]

    tables = CodeTables(PROGRAM, arena)
    instr_cap, addr_cap, loops_cap = tables.size_bucket()
    segment = cached_segment(CAPS, 1, instr_cap, addr_cap, loops_cap)
    code_dev = CodeDev(*[
        jax.device_put(a)
        for a in stacked_device_tables([tables], (1, instr_cap, addr_cap, loops_cap))
    ])
    cfg = CfgScalars(
        max_depth=np.int32(128),
        loop_bound=np.int32(0),
        row_zero=np.int32(row_zero),
        row_one=np.int32(row_one),
        sel_mode=np.int32(sel_mode),
    )

    st = empty_state(CAPS, loops_cap)
    for slot, depth in enumerate(DEPTHS):
        st.seed[slot] = 0
        st.halt[slot] = O.H_RUNNING
        st.pc[slot] = 0
        # stack top (popped first) is the jump dest, then the condition word
        st.stack[slot, 0] = cond_rows[slot]
        st.stack[slot, 1] = dest_row
        st.stack_len[slot] = 2
        st.depth[slot] = depth
        st.score[slot] = scores[slot]

    dev_arena = ArenaDev(*[jax.device_put(a) for a in arena.device_arrays()])
    visited = jax.device_put(np.zeros((3, 1, instr_cap), bool))
    out_state, _arena, _alen, n_exec, _ml, _visited = segment(
        st, dev_arena, arena.length, visited, code_dev, cfg
    )
    assert int(n_exec) == 3
    return np.array(out_state.halt), np.array(out_state.seed)


@pytest.mark.parametrize(
    "sel_mode,winner",
    [
        (step_mod.SEL_NONE, 0),  # slot order
        (step_mod.SEL_DEEP, 1),  # depth 9
        (step_mod.SEL_SHALLOW, 2),  # depth 1
    ],
)
def test_scarce_fork_grant_follows_selection_mode(sel_mode, winner):
    halt, seed = _run_one_step(sel_mode)
    # exactly one fork granted into the single free slot (3)
    assert seed[3] == 0 and halt[3] == O.H_RUNNING
    for slot in range(3):
        if slot == winner:
            # granted parent took the fall-through and keeps running
            assert halt[slot] == O.H_RUNNING
        else:
            # denied parents pend pristine for the next segment/harvest
            assert halt[slot] == O.H_PENDING_FORK


def test_beam_mode_grants_highest_importance():
    """SEL_BEAM ranks fork wanters by the state's beam score column (the
    batched ``BeamSearch.beam_priority``, strategy/basic.py:86-87): the
    parent carrying the most potential-issue importance wins the scarce
    slot even when a rival is deeper."""
    halt, seed = _run_one_step(step_mod.SEL_BEAM, scores=(3, 7, 50))
    assert seed[3] == 0 and halt[3] == O.H_RUNNING
    assert halt[2] == O.H_RUNNING  # score 50 granted
    assert halt[0] == O.H_PENDING_FORK
    assert halt[1] == O.H_PENDING_FORK


def test_coverage_mode_prefers_uncovered_target():
    """SEL_COVERAGE grants the fork whose taken branch lands on code no
    path has visited yet, even when a rival parent is deeper."""
    program = [
        Ins("JUMPI", 0, None),
        Ins("STOP", 1, None),
        Ins("JUMPDEST", 2, None),
        Ins("STOP", 3, None),
        Ins("JUMPDEST", 4, None),
        Ins("STOP", 5, None),
    ]
    arena = HostArena(CAPS.ARENA)
    row_zero = arena.const_row(0, 256)
    row_one = arena.const_row(1, 256)
    dest_covered = arena.const_row(2, 256)
    dest_fresh = arena.const_row(4, 256)
    cond_rows = [arena.var_row(T.var(f"k{i}", 256)) for i in range(2)]

    tables = CodeTables(program, arena)
    instr_cap, addr_cap, loops_cap = tables.size_bucket()
    segment = cached_segment(CAPS, 1, instr_cap, addr_cap, loops_cap)
    code_dev = CodeDev(*[
        jax.device_put(a)
        for a in stacked_device_tables([tables], (1, instr_cap, addr_cap, loops_cap))
    ])
    cfg = CfgScalars(
        max_depth=np.int32(128),
        loop_bound=np.int32(0),
        row_zero=np.int32(row_zero),
        row_one=np.int32(row_one),
        sel_mode=np.int32(step_mod.SEL_COVERAGE),
    )

    st = empty_state(CAPS, loops_cap)
    # slot 0: deeper, but targets already-covered code; slot 1: shallow,
    # targets fresh code; slots 2-3: one occupied non-forking, one free
    for slot, (dest, depth) in enumerate(
        [(dest_covered, 20), (dest_fresh, 2)]
    ):
        st.seed[slot] = 0
        st.halt[slot] = O.H_RUNNING
        st.pc[slot] = 0
        st.stack[slot, 0] = cond_rows[slot]
        st.stack[slot, 1] = dest
        st.stack_len[slot] = 2
        st.depth[slot] = depth
    st.seed[2] = 0
    st.halt[2] = O.H_RUNNING
    st.pc[2] = 1  # sits at STOP; occupies the slot this step

    visited = np.zeros((3, 1, instr_cap), bool)
    visited[0, 0, 2] = True  # the covered JUMPDEST (instruction plane)
    dev_arena = ArenaDev(*[jax.device_put(a) for a in arena.device_arrays()])
    out_state, _arena, _alen, _n, _ml, _v = segment(
        st, dev_arena, arena.length, visited, code_dev, cfg
    )
    halt = np.array(out_state.halt)
    assert halt[1] == O.H_RUNNING  # fresh-target parent granted
    assert halt[0] == O.H_PENDING_FORK  # covered-target parent denied
    assert np.array(out_state.seed)[3] == 0  # child landed in the free slot
