#!/usr/bin/env python
"""Flight-deck acceptance smoke: one pipelined frontier run, full deck armed.

Runs a small forced-frontier analysis (multi-shard when more than one
device is visible — CI forces 8 via XLA_FLAGS) with the span tracer,
heartbeat sampler, and flight recorder all on, exports the artifacts,
then VALIDATES them:

* the Chrome-trace JSON loads and is Perfetto-shaped (``traceEvents``);
* ``process_name``/``thread_name`` metadata names every track that
  recorded an event;
* every flow start ("s") has a matching finish ("f") with the same id,
  in wall-clock order — no dangling dispatch arrows;
* segment-id flow links exist (``flow.segment``) and the pipelined
  spans carry ``segment`` args;
* heartbeat counter tracks ("C" events) are present and the JSONL is
  parseable with monotonic ticks;
* a flight-recorder bundle can be dumped and loads back.

Exit status is nonzero on any violation.  Artifacts land in ``--out``
(default ``flightdeck-smoke/``) for CI to archive.

Usage::

    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        JAX_PLATFORMS=cpu python scripts/flightdeck_smoke.py --out DIR
"""

from __future__ import annotations

import json
import os
import pathlib
import sys

REPO = pathlib.Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

FAILURES: list = []


def check(ok: bool, what: str) -> None:
    tag = "ok" if ok else "FAIL"
    print(f"[flightdeck-smoke] {tag}: {what}")
    if not ok:
        FAILURES.append(what)


def run_analysis(out_dir: pathlib.Path) -> dict:
    from bench import KILLBILLY, KILLBILLY_CREATION, _clear_caches
    from mythril_tpu.frontend.evmcontract import EVMContract
    from mythril_tpu.frontier import engine as _eng
    from mythril_tpu.observability import get_registry, get_tracer
    from mythril_tpu.observability.flightrecorder import (
        arm_flight_recorder,
        disarm_flight_recorder,
        get_flight_recorder,
    )
    from mythril_tpu.observability.heartbeat import get_heartbeat
    from mythril_tpu.support.support_args import args

    tracer = get_tracer()
    tracer.reset()
    tracer.enabled = True
    hb = get_heartbeat()
    hb.reset()
    hb.start(period_s=0.05, out_path=str(out_dir / "heartbeat.jsonl"))
    arm_flight_recorder(str(out_dir / "flight"), watchdog_deadline_s=600.0)

    args.probe_backend = "auto"
    args.frontier = True
    args.frontier_force = True  # tiny contract: bypass the narrow gate
    args.frontier_width = 64
    args.pipeline = True
    args.frontier_mesh = True
    _eng._SLOW_CODES.clear()
    _eng._NARROW_CODES.clear()
    _clear_caches()

    from bench import _analyze

    try:
        _, issues = _analyze(
            EVMContract(code=KILLBILLY, creation_code=KILLBILLY_CREATION,
                        name="KillBilly"),
            0x0901D12E, 3, modules=["AccidentallyKillable"], timeout=300,
        )
        check(any(i.swc_id == "106" for i in issues),
              "recall: the killbilly selfdestruct was found")
        bundle_path = get_flight_recorder().dump("smoke")
        hb.sample_now()
    finally:
        hb.stop()
        disarm_flight_recorder()
        tracer.export_chrome_trace(str(out_dir / "trace.json"))
        tracer.export_jsonl(str(out_dir / "trace.jsonl"))
        (out_dir / "metrics.json").write_text(
            json.dumps(get_registry().snapshot(), indent=1)
        )
        tracer.enabled = False

    import jax

    return {"bundle": bundle_path, "n_devices": jax.device_count()}


def validate_trace(out_dir: pathlib.Path) -> None:
    doc = json.loads((out_dir / "trace.json").read_text())
    events = doc["traceEvents"]
    check(isinstance(events, list) and events, "trace.json loads, has events")

    meta = [e for e in events if e["ph"] == "M"]
    check(any(e["name"] == "process_name" for e in meta),
          "process_name metadata present")
    named = {e["tid"] for e in meta if e["name"] == "thread_name"}
    used = {e["tid"] for e in events if e["ph"] != "M"}
    check(used <= named, "every track that recorded an event is named")
    names = {
        e["args"]["name"] for e in meta if e["name"] == "thread_name"
    }
    check("heartbeat" in names, "heartbeat counter track is named")

    flows = [e for e in events if e["ph"] in ("s", "t", "f")]
    starts = {e["id"] for e in flows if e["ph"] == "s"}
    ends = {e["id"] for e in flows if e["ph"] == "f"}
    check(bool(starts), "flow events present")
    check(starts == ends, f"every flow start has a finish "
          f"(dangling: {sorted(starts ^ ends)[:5]})")
    by_id: dict = {}
    for e in flows:
        by_id.setdefault(e["id"], []).append(e)
    ordered = all(
        all(a["ts"] <= b["ts"] for a, b in zip(evs, evs[1:]))
        for evs in by_id.values()
    )
    check(ordered, "flow endpoints are in wall-clock order")
    check(any(e["name"] == "flow.segment" for e in flows),
          "segment-id dispatch->harvest flow links present")

    seg_spans = [
        e for e in events
        if e["ph"] == "X" and e["name"].startswith("frontier.")
        and (e.get("args") or {}).get("segment") is not None
    ]
    check(bool(seg_spans), "frontier spans carry segment ids")
    counters = [e for e in events if e["ph"] == "C"]
    check(bool(counters), "heartbeat counter events present")


def validate_heartbeat(out_dir: pathlib.Path) -> None:
    lines = [
        json.loads(l)
        for l in (out_dir / "heartbeat.jsonl").read_text().splitlines()
    ]
    check(bool(lines), "heartbeat JSONL has samples")
    ticks = [l["tick"] for l in lines]
    check(ticks == sorted(ticks), "heartbeat ticks are monotonic")
    check(any("pipeline.pool_queue_depth" in l for l in lines),
          "queue depths were sampled from the pipelined runner")


def validate_bundle(bundle_path: str) -> None:
    bundle = json.loads(open(bundle_path).read())
    check(bundle["reason"] == "smoke", "flight bundle loads")
    check(bool(bundle.get("threads")), "bundle has thread stacks")
    check("spans_tail" in bundle, "bundle has a span tail")


def validate_metrics(out_dir: pathlib.Path) -> None:
    snap = json.loads((out_dir / "metrics.json").read_text())
    check(isinstance(snap, dict) and snap, "metrics.json loads")
    check(snap.get("pipeline.segments_pipelined", 0) > 0,
          "the run actually pipelined segments")


def main() -> int:
    out = pathlib.Path(
        sys.argv[sys.argv.index("--out") + 1]
        if "--out" in sys.argv else "flightdeck-smoke"
    )
    out.mkdir(parents=True, exist_ok=True)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    info = run_analysis(out)
    print(f"[flightdeck-smoke] devices: {info['n_devices']}")
    validate_trace(out)
    validate_heartbeat(out)
    validate_bundle(info["bundle"])
    validate_metrics(out)

    if FAILURES:
        print(f"[flightdeck-smoke] {len(FAILURES)} FAILURES:", file=sys.stderr)
        for f in FAILURES:
            print(f"  - {f}", file=sys.stderr)
        return 1
    print("[flightdeck-smoke] all checks passed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
