"""CFG recovery: basic blocks, static jump resolution, reachability.

Jump targets are resolved by constant-folding a bounded abstract stack of
push constants *within each block* (the `PUSHn dest JUMP[I]` idiom that
dominates solc output, plus simple arithmetic folds the optimizer emits).
Anything unresolved is over-approximated with edges to EVERY JUMPDEST, so
static reachability can only over-count — the soundness contract every
consumer relies on (issue sets must be identical with the pass on or off).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from mythril_tpu.staticpass.tables import InstrTables

# abstract stack depth bound: values pushed below this are forgotten
# (reads past the known region return "unknown", never a wrong constant)
_ABS_STACK_CAP = 64

# edge kinds (report export maps these onto core.cfg.JumpType)
E_FALL = "fall"  # sequential flow / JUMPI false branch
E_JUMP = "jump"  # statically resolved JUMP/JUMPI target
E_DYN = "dyn"  # unresolved jump: over-approximated to all JUMPDESTs

_FOLD_BINOPS = {
    "ADD": lambda a, b: a + b,
    "SUB": lambda a, b: a - b,
    "MUL": lambda a, b: a * b,
    "AND": lambda a, b: a & b,
    "OR": lambda a, b: a | b,
    "XOR": lambda a, b: a ^ b,
    "SHL": lambda s, v: v << s if s < 512 else 0,
    "SHR": lambda s, v: v >> s if s < 512 else 0,
}
_U256 = (1 << 256) - 1


class StaticCFG:
    """Basic blocks over an InstrTables + successor lists per block."""

    def __init__(self, tables: InstrTables):
        t = self.tables = tables
        n = t.n

        # leaders: instr 0, every JUMPDEST, every instr after a block ender
        leader = np.zeros(n, bool)
        if n:
            leader[0] = True
            leader |= t.is_jumpdest
            ender = t.is_jump | t.is_jumpi | t.is_terminator
            leader[1:] |= ender[:-1]
        self.block_start = np.flatnonzero(leader).astype(np.int32)
        self.n_blocks = len(self.block_start)
        self.block_end = np.empty(self.n_blocks, np.int32)  # exclusive
        if self.n_blocks:
            self.block_end[:-1] = self.block_start[1:]
            self.block_end[-1] = n
        self.block_id = np.zeros(n, np.int32)
        for b in range(self.n_blocks):
            self.block_id[self.block_start[b]: self.block_end[b]] = b

        self.jumpdest_blocks: List[int] = [
            int(self.block_id[i]) for i in np.flatnonzero(t.is_jumpdest)
        ]
        # -1 or resolved target *instruction index* per JUMP/JUMPI
        self.static_target = np.full(n, -1, np.int32)
        self.n_resolved = 0
        # per block: successor block ids + edge kinds, parallel lists
        self.succ: List[List[int]] = [[] for _ in range(self.n_blocks)]
        self.succ_kind: List[List[str]] = [[] for _ in range(self.n_blocks)]
        self._build_edges()

    # -- abstract constant stack ---------------------------------------

    def _block_top_const(self, b: int) -> Optional[int]:
        """Constant on top of the abstract stack right before the block's
        final instruction (the would-be jump target), or None."""
        t = self.tables
        s, e = int(self.block_start[b]), int(self.block_end[b])
        stk: List[Optional[int]] = []
        for i in range(s, e - 1):
            name = t.names[i]
            if name.startswith("PUSH"):
                stk.append(t.arg[i] if t.arg[i] is not None else 0)
            elif name == "PC":
                stk.append(int(t.addr[i]))
            elif name.startswith("DUP"):
                k = int(name[3:])
                stk.append(stk[-k] if len(stk) >= k else None)
            elif name.startswith("SWAP"):
                k = int(name[4:])
                if len(stk) < k + 1:
                    stk[:0] = [None] * (k + 1 - len(stk))
                stk[-1], stk[-k - 1] = stk[-k - 1], stk[-1]
            elif name == "POP":
                if stk:
                    stk.pop()
            elif name in _FOLD_BINOPS and len(stk) >= 2 \
                    and stk[-1] is not None and stk[-2] is not None:
                a, bv = stk.pop(), stk.pop()
                stk.append(_FOLD_BINOPS[name](a, bv) & _U256)
            else:
                for _ in range(int(t.arity[i])):
                    if stk:
                        stk.pop()
                stk.extend([None] * int(t.pushes[i]))
            if len(stk) > _ABS_STACK_CAP:
                del stk[: len(stk) - _ABS_STACK_CAP]
        return stk[-1] if stk else None

    # -- edges ----------------------------------------------------------

    def _add_edge(self, b: int, to: int, kind: str) -> None:
        self.succ[b].append(to)
        self.succ_kind[b].append(kind)

    def _build_edges(self) -> None:
        t = self.tables
        for b in range(self.n_blocks):
            last = int(self.block_end[b]) - 1
            name = t.names[last]
            fall = b + 1 if b + 1 < self.n_blocks else None
            if t.is_terminator[last]:
                continue
            if not (t.is_jump[last] or t.is_jumpi[last]):
                if fall is not None:
                    self._add_edge(b, fall, E_FALL)
                continue
            target = self._block_top_const(b)
            if target is not None:
                dest = t.jumpdest_at_addr.get(int(target))
                if dest is not None:
                    self.static_target[last] = dest
                    self.n_resolved += 1
                    self._add_edge(b, int(self.block_id[dest]), E_JUMP)
                # resolved-but-invalid destination: the VM halts there,
                # so no jump edge at all
            else:
                for jb in self.jumpdest_blocks:
                    self._add_edge(b, jb, E_DYN)
            if t.is_jumpi[last] and fall is not None:
                self._add_edge(b, fall, E_FALL)

    # -- reachability ----------------------------------------------------

    def reachable_blocks(self, halting: Optional[np.ndarray] = None) -> np.ndarray:
        """Bool mask of blocks reachable from the entry block; a block
        flagged in ``halting`` is entered but contributes no successors
        (statically guaranteed underflow before its terminator)."""
        reach = np.zeros(self.n_blocks, bool)
        if not self.n_blocks:
            return reach
        stack = [0]
        reach[0] = True
        while stack:
            b = stack.pop()
            if halting is not None and halting[b]:
                continue
            for nb in self.succ[b]:
                if not reach[nb]:
                    reach[nb] = True
                    stack.append(nb)
        return reach

    def edge_list(self) -> List[Tuple[int, int, str]]:
        return [
            (b, to, kind)
            for b in range(self.n_blocks)
            for to, kind in zip(self.succ[b], self.succ_kind[b])
        ]
