"""Interprocedural value-set refinement: cross-block jump resolution,
constant-folded JUMPI pruning, the subset invariant, and the budget /
widening fallbacks that keep it over-approximate."""

import numpy as np
import pytest

from mythril_tpu.frontend.disassembler import Disassembly
from mythril_tpu.staticpass import interproc
from mythril_tpu.staticpass.cfg import E_JUMP, StaticCFG
from mythril_tpu.staticpass.interproc import (
    RefinedFlow,
    _fall_dead,
    _join_val,
    _taken_dead,
    refine,
)
from mythril_tpu.staticpass.tables import InstrTables


def _cfg(hexcode: str) -> StaticCFG:
    return StaticCFG(InstrTables(Disassembly(bytes.fromhex(hexcode)).instruction_list))


def _assert_subset(refined: RefinedFlow, cfg: StaticCFG) -> None:
    """Refinement may only REMOVE reachability, never add it."""
    base = np.asarray(cfg.reachable_blocks(), bool)
    ref = np.asarray(refined.reachable_blocks(), bool)
    assert not np.any(ref & ~base)


# ---------------------------------------------------------------------------
# abstract-value lattice
# ---------------------------------------------------------------------------


def test_join_val_unions_small_sets():
    assert _join_val(frozenset({1}), frozenset({2})) == frozenset({1, 2})


def test_join_val_top_absorbs():
    assert _join_val(None, frozenset({1})) is None
    assert _join_val(frozenset({1}), None) is None


def test_join_val_widens_past_cap():
    a = frozenset(range(interproc.VSET_CAP))
    assert _join_val(a, frozenset({10 ** 6})) is None


def test_jumpi_deadness_predicates():
    assert _taken_dead(frozenset({0}))
    assert not _taken_dead(frozenset({0, 1}))
    assert not _taken_dead(None)
    assert _fall_dead(frozenset({1}))
    assert not _fall_dead(frozenset({0, 1}))
    assert not _fall_dead(None)


# ---------------------------------------------------------------------------
# cross-block jump resolution
# ---------------------------------------------------------------------------

# PUSH1 8; PUSH1 5; JUMP; JUMPDEST; JUMP; INVALID; JUMPDEST; STOP
# The second JUMP's target (8) was pushed by the CALLER block, so the
# per-block constant fold cannot see it — only the interproc fixpoint can.
CROSS_BLOCK = "60086005565b56fe5b00"


def test_cross_block_constant_jump_resolves():
    cfg = _cfg(CROSS_BLOCK)
    refined = refine(cfg)
    assert refined is not None
    # the base CFG leaves the second JUMP as a dynamic fan
    base_dyn = [(f, t, k) for f, t, k in cfg.edge_list() if k != E_JUMP]
    assert base_dyn
    # refined: block 1 ([JUMPDEST@5, JUMP@6]) jumps only to block 3 (@8)
    succs = [(f, t, k) for f, t, k in refined.edge_list() if f == 1]
    assert succs == [(1, 3, E_JUMP)]
    assert refined.n_resolved >= 1
    _assert_subset(refined, cfg)


def test_cross_block_prunes_invalid_pad():
    cfg = _cfg(CROSS_BLOCK)
    refined = refine(cfg)
    reach = list(np.asarray(refined.reachable_blocks(), bool))
    # block 2 is the INVALID pad at addr 7 — nothing targets it
    assert reach[2] is np.False_ or not reach[2]
    assert reach[0] and reach[1] and reach[3]


def test_entry_stack_empty_for_unvisited_block():
    refined = refine(_cfg(CROSS_BLOCK))
    # the INVALID pad was never visited: its entry stack defaults to []
    assert refined.entry_stack(2) == []


# ---------------------------------------------------------------------------
# constant-folded JUMPI pruning
# ---------------------------------------------------------------------------


def test_constant_false_jumpi_kills_taken_edge():
    # PUSH1 0; PUSH1 6; JUMPI; STOP; JUMPDEST; STOP — cond is {0}
    cfg = _cfg("6000600657005b00")
    refined = refine(cfg)
    assert refined is not None
    reach = np.asarray(refined.reachable_blocks(), bool)
    # the JUMPDEST@6 block (last) is only reachable via the dead taken edge
    assert not reach[-1]
    _assert_subset(refined, cfg)


def test_constant_true_jumpi_kills_fall_edge():
    # PUSH1 1; PUSH1 6; JUMPI; STOP; JUMPDEST; STOP — cond is {1}
    cfg = _cfg("6001600657005b00")
    refined = refine(cfg)
    assert refined is not None
    reach = np.asarray(refined.reachable_blocks(), bool)
    # the fall-through STOP block (between JUMPI and JUMPDEST) is dead
    assert not reach[1]
    assert reach[-1]
    _assert_subset(refined, cfg)


def test_unknown_cond_keeps_both_edges():
    # CALLDATASIZE; PUSH1 5; JUMPI; STOP; JUMPDEST; STOP — cond is ⊤
    cfg = _cfg("36600557005b00")
    refined = refine(cfg)
    assert refined is not None
    reach = np.asarray(refined.reachable_blocks(), bool)
    assert reach.all()


# ---------------------------------------------------------------------------
# convergence and fallbacks
# ---------------------------------------------------------------------------


def test_loop_converges_via_widening():
    # PUSH1 0; JUMPDEST; PUSH1 1; ADD; PUSH1 2; JUMP — counter widens to ⊤
    refined = refine(_cfg("60005b600101600256"))
    assert refined is not None


def test_budget_exhaustion_falls_back(monkeypatch):
    monkeypatch.setattr(interproc, "_VISIT_BUDGET_MIN", 0)
    monkeypatch.setattr(interproc, "_VISIT_BUDGET_PER_BLOCK", 0)
    assert refine(_cfg(CROSS_BLOCK)) is None
