"""FrontierEngine: orchestrates device segments against the host engine.

Replaces the host work-list loop (reference mythril/laser/ethereum/
svm.py:261-304) for message-call transactions: eligible seed states are
packed into the device batch, the jitted segment program executes up to
``caps.K`` instructions per dispatch for the whole batch, and each harvest

  1. pulls the state mirror + new arena rows,
  2. threads new fork children into the host-side path records,
  3. replays completed paths' events through host GlobalStates (walker) —
     firing detector hooks, archiving open world states, and pushing parked
     paths onto ``laser.work_list`` for the host engine to continue,
  4. recycles freed slots for queued seeds / pending forks.

Multi-code batching: the dispatch tables are stacked per code identity and
every path carries a ``code_id``, so seeds from DIFFERENT contracts — a
corpus sweep driven by ``drain_lasers``, or several codes on one work list —
share a single wide segment.  The reference analyzes a corpus strictly
sequentially (mythril/mythril/mythril_analyzer.py:138-175, one contract at a
time); here the corpus IS the batch axis.

Anything the device cannot run (CALL family, creation txs, symbolic memory
addressing, cap overflows) degrades gracefully: the path is parked with its
exact machine state and the ordinary host engine picks it up — the frontier
is a fast path, never a semantics fork.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from mythril_tpu.frontier import ops as O
from mythril_tpu.frontier import taint
from mythril_tpu.frontier.arena import HostArena
from mythril_tpu.frontier.code import (
    CTX_ADDRESS,
    CTX_BALANCES,
    CTX_BASEFEE,
    CTX_CALLER,
    CTX_CALLVALUE,
    CTX_CDSIZE,
    CTX_CHAINID,
    CTX_COINBASE,
    CTX_DIFFICULTY,
    CTX_GASLIMIT,
    CTX_GASPRICE,
    CTX_NUMBER,
    CTX_ORIGIN,
    CTX_SEED,
    CTX_STORAGE,
    CTX_TIMESTAMP,
    CodeTables,
    bucket_classes,
    multi_size_bucket,
    pad_waste_pct,
    stacked_device_tables,
    visited_instr_cap,
)
from mythril_tpu.frontier.harvest import HarvestExecutor
from mythril_tpu.frontier.records import PathRecord, snapshot_slot
from mythril_tpu.frontier.state import Caps, FrontierState, clear_slot, empty_state
from mythril_tpu.frontier.stats import FrontierStatistics
from mythril_tpu.observability import deviceplane as _devplane
from mythril_tpu.observability import flightrecorder as _frec
from mythril_tpu.observability import tracer as _otrace
from mythril_tpu.observability.metrics import get_registry as _get_metrics
from mythril_tpu.frontier.step import (
    ArenaDev,
    CfgScalars,
    CodeDev,
    cached_segment,
    pull_harvest,
    push_state,
)
from mythril_tpu.frontier.walker import Walker
from mythril_tpu.support.support_args import args
from mythril_tpu.support.time_handler import time_handler

log = logging.getLogger(__name__)

# codes a frontier run proved dynamically NARROW (max live paths stayed
# under caps.MIN_LIVE): later narrow drains skip the device for them a
# priori.  A WIDE seed set still admits them — their width comes from many
# seeds, not fanout, and lanes amortize the dispatch regardless.
_NARROW_CODES: set = set()

# codes the throughput bail proved SLOWER THAN THE HOST at whatever width
# they actually reached: unlike the narrow verdict, a wide re-drain of the
# same code just re-pays the proven loss, so this memo outranks the width
# bypass (mixed batches with any unmarked code still go)
_SLOW_CODES: set = set()

# mid-run throughput bail: consecutive post-warmup segments whose
# (device instructions / SEGMENT-ONLY wall — dispatch + transfers, not
# harvest, which is replay/confirmation work the host path pays too) fall
# below the bail threshold hand the run to the host engine.  The only
# correct baseline is the HOST's measured stepping rate on THIS workload
# (laser.host_step_rate — it spans 5..900 states/s: heavy wide-mul term
# construction vs light dispatch code), compared at a 0.7 safety factor.
# Before enough host samples exist the floor below applies — LOW enough
# that slow-host workloads (bectoken segments measure ~230 instr/s against
# a 5 states/s host) are never bailed blind.  On an untunneled chip
# segment walls shrink ~50x and the bail becomes unreachable.
_SLOW_BAIL_FLOOR = 100.0
_SLOW_BAIL_HOST_FACTOR = 0.7
_SLOW_BAIL_SEGMENTS = 2
# a DECISIVE loss (segment rate under half the bail rate, i.e. the device
# is running at under 0.35x the slowest host alternative) bails after ONE
# warm segment: round 4's first-analysis numbers showed narrow real
# contracts losing 0.3-0.7x for two full segments before the counter
# tripped, and the first analysis is the case that matters
_SLOW_BAIL_DECISIVE = 0.5

# slow-segment counters persist ACROSS runs per code (short explorations
# split into several 1-2 segment runs, so a per-run counter never reaches
# the bail threshold); a fast segment resets its codes.  These dicts are
# deliberately process-lifetime state, NOT per-analysis telemetry: the
# observability registry mirrors the verdicts under persistent-scope
# metrics (frontier.slow_code_verdicts / frontier.narrow_code_verdicts)
# that survive the reset_analysis_metrics() sweep, exactly like the dicts.
_SLOW_SEGMENTS: Dict[object, int] = {}

# (caps, bucket) programs already dispatched once this process: their first
# segment paid any XLA compile, so later runs' first segments count toward
# the throughput bail
_WARM_PROGRAMS: set = set()

# static width hint: below this many JUMPIs across the seed codes a narrow
# seed set cannot fan out wide enough to amortize segment dispatches
_MIN_STATIC_JUMPIS = 8

# observed-width admission gate (calibration-scaled with the link RTT): a
# seed set narrower than this stays host-side even when statically branchy.
# Round 4's static-JUMPI-only gate admitted requires-style contracts
# (overflow/underflow: 10 JUMPIs but observed max work-list width 5-12 —
# every fork's other side reverts) which then lost 0.3-0.7x to segment
# fixed costs on the first analysis.  Genuinely wide workloads prove their
# width ON THE HOST within milliseconds (fork doubling), so demanding
# observed width costs them one drain interval, not a compile or a segment.
_MIN_SEED_WIDTH = 8

_jumpi_count_cache: Dict[object, int] = {}


def _code_key(code):
    bytecode = getattr(code, "bytecode", None)
    return hash(bytecode) if bytecode else id(code)


def _jumpi_count(code) -> int:
    # keyed by bytecode hash (NOT id(code): a freed Disassembly's id can be
    # recycled for a different contract), bounded against unbounded growth
    key = _code_key(code)
    got = _jumpi_count_cache.get(key)
    if got is None:
        got = sum(
            1 for ins in code.instruction_list if ins.opcode == "JUMPI"
        )
        if len(_jumpi_count_cache) >= 4096:
            _jumpi_count_cache.clear()
        _jumpi_count_cache[key] = got
    return got


_code_tag_cache: Dict[object, str] = {}


def _code_tag(code) -> str:
    """Short codehash prefix for solver-hotspot program-point labels."""
    key = _code_key(code)
    tag = _code_tag_cache.get(key)
    if tag is None:
        bytecode = getattr(code, "bytecode", b"") or b""
        if bytecode:
            from mythril_tpu.support.support_utils import get_code_hash

            tag = get_code_hash(bytecode.hex())[:10]
        else:
            tag = "?"
        if len(_code_tag_cache) >= 4096:
            _code_tag_cache.clear()
        _code_tag_cache[key] = tag
    return tag


_code_hash_cache: Dict[object, str] = {}


def _code_hash_full(code) -> str:
    """Full codehash — the exploration ledger's coverage key (and the
    adaptive planner's), so steering weights join coverage bitmaps
    without prefix games."""
    key = _code_key(code)
    h = _code_hash_cache.get(key)
    if h is None:
        bytecode = getattr(code, "bytecode", b"") or b""
        if bytecode:
            from mythril_tpu.support.support_utils import get_code_hash

            h = get_code_hash(bytecode.hex())
        else:
            h = "?"
        if len(_code_hash_cache) >= 4096:
            _code_hash_cache.clear()
        _code_hash_cache[key] = h
    return h


def _adaptive_pick(seed_queue: List[int], seed_code_idx: List[int],
                   table_hash: List[str]) -> int:
    """Queue position of the next seed to inject (0 = the FIFO order every
    pre-adaptive build used).  With >1 code queued and the controller
    enabled, the steering plan's deficit scheduler picks the code whose
    uncovered reachable edges earn the next slot."""
    if len(seed_queue) <= 1:
        return 0
    try:
        from mythril_tpu.adaptive import get_adaptive_controller

        ctrl = get_adaptive_controller()
        if not ctrl.enabled:
            return 0
        ctrl.plan()  # throttled refresh; cheap when recently built
        return ctrl.pick_seed(
            [table_hash[seed_code_idx[si]] for si in seed_queue]
        )
    except Exception:  # steering must never break a dispatch
        log.debug("adaptive seed pick failed", exc_info=True)
        return 0


def _adaptive_coverage_stop() -> bool:
    """True when the --coverage-target contract says stop exploring
    (bar reached or all-codes plateau)."""
    if not getattr(args, "coverage_target", None):
        return False
    try:
        from mythril_tpu.adaptive import get_adaptive_controller

        return get_adaptive_controller().coverage_stop() is not None
    except Exception:  # pragma: no cover - defensive
        log.debug("adaptive coverage check failed", exc_info=True)
        return False


def _strategy_chain(laser):
    """The active strategy and every strategy it wraps (extensions nest via
    ``super_strategy``), outermost first."""
    strategy = laser.strategy
    while strategy is not None:
        yield strategy
        strategy = getattr(strategy, "super_strategy", None)


def _is_concolic(laser) -> bool:
    """Concolic runs are excluded from the frontier: trace recording and the
    ConcolicStrategy depend on the host engine stepping every instruction."""
    from mythril_tpu.core.strategy.concolic import ConcolicStrategy

    return any(isinstance(s, ConcolicStrategy) for s in _strategy_chain(laser))


def _sel_mode(laser) -> int:
    """Map the active host search strategy onto the device fork-grant
    priority (step.SEL_*) — the batched form of the strategy's ordering
    (SURVEY.md §7.2 item 5).  Strategies with host-only scores (beam's
    annotation importance, random) keep slot order; their ordering applies
    when parked/spilled paths re-enter the host work list."""
    from mythril_tpu.core.strategy.basic import (
        BeamSearch,
        BreadthFirstSearchStrategy,
        DepthFirstSearchStrategy,
    )
    from mythril_tpu.frontier import step as step_mod
    from mythril_tpu.plugins.plugins.coverage import CoverageStrategy

    for strategy in _strategy_chain(laser):
        if isinstance(strategy, CoverageStrategy):
            return step_mod.SEL_COVERAGE
        if isinstance(strategy, BeamSearch):
            return step_mod.SEL_BEAM
        if isinstance(strategy, DepthFirstSearchStrategy):
            return step_mod.SEL_DEEP
        if isinstance(strategy, BreadthFirstSearchStrategy):
            return step_mod.SEL_SHALLOW
    return step_mod.SEL_NONE


def _beam_importance(gs) -> int:
    """The host beam score (strategy/basic.py BeamSearch.beam_priority):
    annotations are SHARED across a seed's fork tree, so this is exact for
    every device descendant of ``gs`` at segment time."""
    try:
        return int(sum(a.search_importance for a in gs._annotations))
    except Exception:
        return 0


def _frame_ok(gs) -> bool:
    from mythril_tpu.core.transaction.transaction_models import (
        MessageCallTransaction,
    )

    return (
        isinstance(gs.current_transaction, MessageCallTransaction)
        and gs.environment.code is not None
        and len(gs.environment.code.instruction_list) > 0
        # static (STATICCALL) frames are eligible: the per-path static flag
        # halts state-mutating ops as terminals whose replay raises the
        # host WriteProtection (step.py write-violation override)
    )


def _is_fresh(gs) -> bool:
    return gs.mstate.pc == 0 and not gs.mstate.stack


# leave headroom below the device caps: an injected state that starts near a
# cap would park within a few instructions and bounce host<->device
_MID_STACK_MAX = Caps.STK - 12
_MID_MEM_MAX = Caps.MEM - 12


def _mid_eligible(gs) -> bool:
    """Mid-frame states the device can RE-ENTER: pc > 0 with a bounded
    stack and concretely-addressed memory — resumed callers after inner
    calls, batch-full spills, timeout/arena bulk parks (reference engine
    continues ANY state, svm.py:261-304; round-3 frontier only admitted
    fresh frames so every park left the device permanently)."""
    if len(gs.mstate.stack) > _MID_STACK_MAX:
        return False
    if gs.mstate.pc >= len(gs.environment.code.instruction_list):
        return False
    if len(gs.mstate.memory) > _MID_MEM_MAX * 32:
        return False
    # memoized per (pc, #writes): a state is immutable while it waits on
    # the work list, and drains rescan the list every few instructions —
    # the O(M log M) walk must not repeat per scan
    memo_key = (gs.mstate.pc, len(gs.mstate.memory))
    cached = getattr(gs, "_frontier_mem_ok", None)
    if cached is not None and cached[0] == memo_key:
        return cached[1]
    addrs = gs.mstate.memory.concrete_addresses()
    ok = addrs is not None
    gs._frontier_mem_ok = (memo_key, ok)
    if not ok:
        # symbolic memory addressing blocks the device AT this pc: stamp so
        # the cheap top-level guard skips this state until the host engine
        # has advanced it (fresh copies drop the stamp)
        gs._frontier_park_pc = gs.mstate.pc
    return ok


def _eligible(gs) -> bool:
    """Seed states the device can take: fresh message-call frames (pc 0,
    empty stack) — including INNER call frames, which the nested-frontier
    drains in svm.exec rely on — plus re-entrant mid-frame states (see
    ``_mid_eligible``).

    States the device parked for a SEMANTIC reason carry
    ``_frontier_park_pc``; while still AT that pc they would re-park on
    the first device step (this covers fresh-looking pc=0 parks too)."""
    try:
        if getattr(gs, "_frontier_park_pc", None) == gs.mstate.pc:
            return False
        if not _frame_ok(gs):
            return False
        return _is_fresh(gs) or _mid_eligible(gs)
    except Exception:
        return False


def reset_isolation_gauges() -> None:
    """Clear the per-analysis bucket-isolation latch.

    ``frontier.bucket_classes`` and the pad-waste gauges are sticky
    within one analysis (a multi-class dispatch must survive later
    single-class tail rounds), so each fresh analysis zeroes them here
    before its first dispatch — otherwise a long-lived process (daemon,
    bench harness) would report the previous corpus's split."""
    reg = _get_metrics()
    for name in (
        "frontier.bucket_classes",
        "frontier.pad_waste_pct",
        "frontier.pad_waste_single_bucket_pct",
        "frontier.page_resident_pct",
    ):
        reg.gauge(name).set(0)


def _latch_resident_pct(pct: float) -> None:
    """Record the LOWEST residency observed this analysis: a fully
    resident class dispatched after a paged one must not mask the paged
    class's figure (gauges are last-write-wins)."""
    gauge = _get_metrics().gauge("frontier.page_resident_pct")
    current = float(gauge.value or 0.0)
    if current <= 0.0 or pct < current:
        gauge.set(pct)


def drain_lasers(
    lasers: List,
    caps: Optional[Caps] = None,
    bucket_floor: Optional[tuple] = None,
    tags: Optional[Sequence[str]] = None,
    flow_cb: Optional[Callable[[], None]] = None,
) -> int:
    """Run eligible seeds from EVERY laser's work list as one multi-code
    frontier batch (the cooperative corpus entry point).  Parked paths land
    back on their own laser's work list.  Returns #instructions executed.

    Lasers must share search configuration (max_depth / strategy family);
    heterogeneous groups run as separate batches.  ``bucket_floor`` pins a
    minimum (code_cap, instr_cap, addr_cap, loops_cap) so every round of a
    cooperative run reuses ONE compiled segment program even as the live
    code set shrinks (a smaller round must not trigger a fresh XLA compile
    mid-sweep).  ``tags`` (service request ids riding this batch) annotate
    every ``frontier.segment`` span so a shared wide device segment is
    attributable to the requests it serves.  ``flow_cb`` is invoked once,
    inside the first ``frontier.segment`` span actually dispatched — the
    service uses it to record per-request trace-flow endpoints there, so
    request span trees join the segment that served them (and no arrow
    dangles when a batch never reaches the device)."""
    groups: Dict[tuple, List[Tuple]] = {}
    for laser in lasers:
        if _is_concolic(laser):
            continue
        seeds = [s for s in laser.work_list if _eligible(s)]
        if not seeds:
            continue
        key = (laser.max_depth, _sel_mode(laser))
        groups.setdefault(key, []).extend((laser, s) for s in seeds)
    # a single corpus-wide floor covers the WHOLE corpus: applying it to a
    # small heterogeneous group would pad that group's device tables to the
    # full code axis (wasted HBM); with one group — the practical
    # cooperative case — the floor is exact.  PER-CLASS floors (a list,
    # from bucket_hint_classes) survive any grouping: each class picks the
    # smallest floor that covers it, so nothing over-pads.
    if len(groups) > 1 and not isinstance(bucket_floor, list):
        bucket_floor = None
    executed = 0
    for pairs in groups.values():
        engine = FrontierEngine(pairs[0][0], caps)
        if tags:
            engine.request_tags = tuple(tags)
        engine.request_flow_cb = flow_cb
        executed += engine._drain_pairs(pairs, bucket_floor=bucket_floor)
    return executed


class FrontierEngine:
    def __init__(self, laser, caps: Optional[Caps] = None):
        self.laser = laser
        self.caps = caps or Caps(B=args.frontier_width)
        # service request ids riding this engine's segments (set by
        # drain_lasers(tags=...)); stamped onto frontier.segment spans
        self.request_tags: Optional[tuple] = None
        # one-shot callback fired inside the first segment span actually
        # dispatched (drain_lasers(flow_cb=...)): the service records its
        # per-request trace-flow endpoints there
        self.request_flow_cb: Optional[Callable[[], None]] = None

    def _fire_request_flows(self) -> None:
        """Invoke the service's flow callback once, inside a segment span."""
        cb, self.request_flow_cb = self.request_flow_cb, None
        if cb is not None:
            try:
                cb()
            except Exception:  # telemetry must never break a dispatch
                log.debug("request flow callback failed", exc_info=True)

    # ------------------------------------------------------------------

    def drain_work_list(self) -> int:
        """Run every eligible work-list state on the device; parked paths
        land back on ``laser.work_list``.  Returns #states executed."""
        laser = self.laser
        if _is_concolic(laser):
            return 0
        seeds = [s for s in laser.work_list if _eligible(s)]
        if not seeds:
            return 0
        return self._drain_pairs([(laser, s) for s in seeds])

    def _drain_pairs(self, pairs: List[Tuple],
                     bucket_floor: Optional[tuple] = None) -> int:
        """Run (laser, seed) pairs as one batch; seeds are removed from
        their work lists and never lost (parked back on failure)."""
        if not self._device_worthwhile(pairs):
            return 0
        for laser, s in pairs:
            laser.work_list.remove(s)
        try:
            return self._run(pairs, bucket_floor=bucket_floor)
        except Exception:
            # never lose a seed: hand everything back to the host engines.
            # Paths a partial frontier run already completed re-run on host;
            # the per-(address, bytecode) issue cache absorbs duplicates.
            for laser, s in pairs:
                laser.work_list.append(s)
            raise

    def _device_worthwhile(self, pairs: List[Tuple]) -> bool:
        """A-priori narrow bail: segment dispatches only amortize over wide
        frontiers, so a seed set that cannot fan out stays host-side.  The
        admission evidence is OBSERVED width (the link-calibrated
        _MIN_SEED_WIDTH); a statically-branchy seed set that has already
        fanned out to half the gate is admitted early."""
        if args.frontier_force:
            return True
        # scale the break-evens to the measured link (no-op after first call)
        from mythril_tpu.support.calibration import calibrate

        calibrate()
        codes = {id(s.environment.code): s.environment.code for _, s in pairs}
        # the slow verdict outranks the width bypass (see _SLOW_CODES)
        if all(_code_key(c) in _SLOW_CODES for c in codes.values()):
            return False
        width_gate = max(self.caps.MIN_LIVE, _MIN_SEED_WIDTH)
        if len(pairs) >= width_gate:
            return True
        if all(
            _code_key(c) in _NARROW_CODES or _code_key(c) in _SLOW_CODES
            for c in codes.values()
        ):
            return False
        # early admission for provably-branchy code that is already halfway
        # to the width gate: fork doubling will cross it within one segment
        # (no MIN_LIVE floor here — at the default gate==MIN_LIVE this
        # clause is only reached when len(pairs) < MIN_LIVE, so flooring
        # would make it dead code)
        return (
            sum(_jumpi_count(c) for c in codes.values()) >= _MIN_STATIC_JUMPIS
            and len(pairs) >= max(2, width_gate // 2)
        )

    # ------------------------------------------------------------------

    @staticmethod
    def _hook_info(laser, summary=None) -> Tuple[set, set, set]:
        """(hooked, concrete-nop, value-gated) opcode sets for this laser.

        When a static summary for the code being packed is supplied
        (mythril_tpu/staticpass), two further elisions apply per code:
        opcodes with no statically reachable instruction leave the hooked
        set (their events could never fire), and an opcode whose EVERY
        hook belongs to a module with a statically dead declared taint
        flow is dropped too — safe because such modules only raise at
        their declared sinks, and those sinks (JUMPI) are _ALWAYS_EVENT
        ops whose events and host hook replays are unaffected by this
        set.

        An opcode is concrete-nop when EVERY hook on it (pre and post) is a
        bound method of a module that declares it in ``concrete_nop_hooks``
        — the device may then suppress its events for all-concrete operands
        (the hook provably does nothing there).

        An opcode is dropped from the hooked set ENTIRELY when every hook
        on it is a declared taint source (module ``taint_source_hooks``):
        its only effect — annotating the pushed value — is reproduced by
        the seeded taint bit on the source's env row plus the walker's
        row-graph closure (frontier/taint.py), so device executions need
        no event at all.

        The value-gated set (module ``value_gated_hooks``) marks opcodes
        whose events the device ships only when the value operand is
        CONCRETE with the solc panic selector in its top 32 bits (the
        MSTORE gate; the hook no-ops on symbolic values too)."""
        # defaultdict access creates empty entries; only real hooks count
        hooked = {
            op
            for reg in (laser._pre_hooks, laser._post_hooks)
            for op, funcs in reg.items()
            if op and funcs
        }
        conc_nop = set()
        for op in hooked:
            if all(
                op in getattr(getattr(hook, "__self__", None),
                              "concrete_nop_hooks", ())
                for reg in (laser._pre_hooks, laser._post_hooks)
                for hook in reg.get(op, [])
            ):
                conc_nop.add(op)
        def _declared_bit(hook, op):
            decl = getattr(getattr(hook, "__self__", None),
                           "taint_source_hooks", {})
            return decl.get(op) if hasattr(decl, "get") else None

        # drop only when every hook declares the op AND the declared bit is
        # actually seeded + registered (taint.suppressible) — an undeclared
        # or unseedable bit would silently disable the detector on device
        taint_src = {
            op
            for op in hooked
            if all(
                (bit := _declared_bit(hook, op)) is not None
                and taint.suppressible(bit)
                for reg in (laser._pre_hooks, laser._post_hooks)
                for hook in reg.get(op, [])
            )
        }
        val_gate = {
            op
            for op in hooked
            if all(
                op in getattr(getattr(hook, "__self__", None),
                              "value_gated_hooks", ())
                for reg in (laser._pre_hooks, laser._post_hooks)
                for hook in reg.get(op, [])
            )
        }
        hooked = hooked - taint_src
        if summary is not None:
            from mythril_tpu.staticpass import GateView, module_relevant

            view = GateView([summary])
            dropped = {
                op for op in hooked if op not in summary.reachable_opcodes
            }
            for op in hooked - dropped:
                owners = {
                    getattr(hook, "__self__", None)
                    for reg in (laser._pre_hooks, laser._post_hooks)
                    for hook in reg.get(op, [])
                }
                if owners and all(
                    m is not None
                    and getattr(m, "static_taint_sources", None)
                    and getattr(m, "static_taint_sinks", None)
                    and not module_relevant(m, view)
                    for m in owners
                ):
                    dropped.add(op)
            if dropped:
                from mythril_tpu.observability import get_registry

                get_registry().counter(
                    "staticpass.hooks_elided_device"
                ).inc(len(dropped))
                hooked -= dropped
        return hooked, conc_nop, val_gate

    def _seed_ctx(self, arena: HostArena, gs, seed_idx: int) -> np.ndarray:
        from mythril_tpu.smt import symbol_factory

        env = gs.environment
        ctx = np.full(16, -1, np.int32)
        ctx[CTX_CALLER] = arena.var_row(env.sender.raw)
        ctx[CTX_CALLVALUE] = arena.var_row(env.callvalue.raw)
        ctx[CTX_ADDRESS] = arena.var_row(env.address.raw)
        ctx[CTX_CDSIZE] = arena.var_row(env.calldata.calldatasize.raw)
        ctx[CTX_BALANCES] = arena.encode(gs.world_state.balances.raw)
        ctx[CTX_STORAGE] = arena.encode(
            env.active_account.storage._array.raw
        )
        ctx[CTX_GASPRICE] = arena.var_row(env.gasprice.raw)
        ctx[CTX_DIFFICULTY] = arena.var_row(
            gs.new_bitvec("block_difficulty", 256).raw
        )
        # taint-source slots use DEDICATED rows (arena.fresh_var_row): host
        # taint is per-USE, and origin aliases the sender term / gaslimit is
        # a constant a program literal could equal — tainting an interned
        # row would leak the bit to non-source uses of the same term
        from mythril_tpu.smt import terms as _T

        # no_fold: a device constant fold emits a REF-LESS row, which would
        # cut the tainted gaslimit constant out of the walker's closure —
        # the host annotation survives folding on the wrapper, so the
        # device must keep the dataflow edge (the branch forks symbolically
        # and the infeasible side dies at the sibling check's decode fold)
        ctx[CTX_GASLIMIT] = arena.fresh_var_row(
            _T.const(gs.mstate.gas_limit, 256), no_fold=True
        )
        ctx[CTX_ORIGIN] = arena.fresh_var_row(env.origin.raw)
        ctx[CTX_TIMESTAMP] = arena.fresh_var_row(
            symbol_factory.BitVecSym("timestamp", 256).raw
        )
        ctx[CTX_NUMBER] = arena.fresh_var_row(env.block_number.raw)
        ctx[CTX_COINBASE] = arena.fresh_var_row(
            gs.new_bitvec("coinbase", 256).raw
        )
        ctx[CTX_CHAINID] = arena.var_row(env.chainid.raw)
        ctx[CTX_BASEFEE] = arena.var_row(env.basefee.raw)
        ctx[CTX_SEED] = seed_idx
        # taint-source seeding: any row whose closure reaches one of these
        # source rows carries the bit — the device-side form of the
        # post-hook annotation.  ENV_SOURCE_SLOTS is the same table
        # taint.suppressible consults, so a suppressible bit is always
        # seeded here.
        for bit, slot in taint.ENV_SOURCE_SLOTS.items():
            arena.add_taint(ctx[slot], bit)
        return ctx

    def _inject(self, st: FrontierState, slot: int, seed_idx: int,
                ctx: np.ndarray, code_idx: int, score: int = 0,
                static: int = 0) -> None:
        clear_slot(st, slot)
        st.seed[slot] = seed_idx
        st.halt[slot] = O.H_RUNNING
        st.ctx[slot] = ctx
        st.code_id[slot] = code_idx
        st.score[slot] = score
        st.static[slot] = static

    def _encode_mid(self, arena: HostArena, gs) -> Optional[dict]:
        """Pack a mid-frame host state for device re-entry, or None.

        Stack words (symbolic included) become arena rows; concretely
        addressed memory is regrouped into the device's disjoint 32-byte
        word entries.  Loop counters start at zero — a re-entered path may
        re-run up to loop_bound extra iterations before the device bound
        trips (bounded, and the host bounded-loops strategy still applies
        to whatever parks back).  Gas starts at zero on device: the walker
        reports seed-relative totals via its per-seed gas_base."""
        I32_MAX = (1 << 31) - 1
        if arena.length > self.caps.ARENA * 9 // 10:
            # near-capacity: an encode raising halfway would strand its
            # already-appended rows (the arena has no rollback); the run is
            # about to park on arena pressure anyway
            return None
        try:
            # validate memory FIRST: stack encoding appends arena rows, and
            # rows for a seed bounced afterwards would leak into the shared
            # arena (pulling the arena-full park forward)
            addrs = gs.mstate.memory.concrete_addresses()
            if addrs is None:
                return None
            windows = []
            i, n = 0, len(addrs)
            while i < n:
                start = addrs[i]
                if i + 32 > n or addrs[i : i + 32] != list(
                    range(start, start + 32)
                ):
                    return None  # partial word: the entry model can't hold it
                if start + 32 > I32_MAX:
                    return None  # device addresses are i32
                windows.append(start)
                i += 32
            if len(windows) > _MID_MEM_MAX:
                return None
            pc = int(gs.mstate.pc)
            mem_size = int(getattr(gs.mstate, "memory_size", 0) or 0)
            depth = int(getattr(gs.mstate, "depth", 0) or 0)
            if max(pc, mem_size, depth) > I32_MAX:
                return None
            # host-installed taint annotations must survive re-entry as row
            # bits or the sink check would miss them (frontier/taint.py).
            # A TAINTED wrapper gets a DEDICATED opaque row: tainting the
            # interned/structural row would leak the bit to every other use
            # of the same term (origin aliases the sender term — the exact
            # false-SWC-115 fabrication fresh_var_row exists to prevent)
            def enc(wrapper) -> int:
                mask = taint.mask_for_annotations(
                    getattr(wrapper, "annotations", ())
                )
                if not mask:
                    return arena.encode(wrapper.raw)
                return arena.tainted_row(wrapper.raw, mask)

            mem_pairs = [
                (a, enc(gs.mstate.memory.get_word_at(a))) for a in windows
            ]
            stack_rows = [enc(v) for v in gs.mstate.stack]
            return {
                "pc": pc,
                "stack": stack_rows,
                "mem": mem_pairs,
                "mem_size": mem_size,
                "depth": depth,
            }
        except Exception as e:
            log.debug("mid-frame encode failed: %s", e)
            return None

    @staticmethod
    def _apply_mid(st: FrontierState, slot: int, enc: dict) -> None:
        st.pc[slot] = enc["pc"]
        for k, row in enumerate(enc["stack"]):
            st.stack[slot, k] = row
        st.stack_len[slot] = len(enc["stack"])
        for k, (addr, row) in enumerate(enc["mem"]):
            st.mem_addr[slot, k] = addr
            st.mem_val[slot, k] = row
        st.mem_len[slot] = len(enc["mem"])
        st.mem_size[slot] = enc["mem_size"]
        st.depth[slot] = enc["depth"]

    # ------------------------------------------------------------------
    # large-code frontier: per-class floors + packed-code paging
    # ------------------------------------------------------------------

    # faults per code before the engine stops repacking for it and pins
    # further faulting paths host-side (semantic park) — a window that
    # keeps missing is a code the host engine runs better
    _PAGE_FAULT_LIMIT = 8

    @staticmethod
    def _pick_floor(floors: List[tuple],
                    natural: tuple) -> Optional[tuple]:
        """Smallest per-class floor that covers ``natural`` in every
        dimension, or None.  A floor that only partially covers would
        produce a third bucket shape (elementwise max) that neither the
        sweep's floored program nor the natural program matches — a
        guaranteed mid-sweep recompile — so partial covers are skipped."""
        best = None
        for f in floors:
            if len(f) != len(natural):
                continue
            if all(fv >= nv for fv, nv in zip(f, natural)):
                if best is None or f[0] * f[1] < best[0] * best[1]:
                    best = f
        return best

    def _note_page_fault(self, code_idx: int, pc: int) -> bool:
        """Record a device page fault (harvest calls this).  Returns True
        when the window will be repacked to cover ``pc`` at the next sync
        point — the faulting path should then re-inject as an ordinary
        park carrier.  Returns False once the code exceeded the fault
        budget: the caller pins the path host-side instead."""
        _get_metrics().counter("frontier.page_faults").inc()
        counts = getattr(self, "_page_fault_counts", None)
        if counts is None or not getattr(self, "_page_tables", None):
            return False
        tables = self._page_tables
        if not (0 <= code_idx < len(tables)):
            return False
        counts[code_idx] = counts.get(code_idx, 0) + 1
        if counts[code_idx] > self._PAGE_FAULT_LIMIT:
            return False
        axis = self._page_bucket[1]
        full = tables[code_idx].fam.shape[0]
        if full <= axis:
            # not actually paged (stale pc past the code end): no repack
            return False
        # window start: a quarter-axis of context before the fault pc so
        # backward jumps inside the new span stay resident, clamped to
        # keep the window inside the code
        base = min(max(0, int(pc) - axis // 4), full - axis)
        self._page_pending[code_idx] = base
        return True

    def _maybe_repack(self):
        """Rebuild the device tables with pending window moves folded in
        (sync points only).  Same bucket, same shapes — the compiled
        segment program is untouched; only table CONTENT re-uploads.
        Returns the fresh CodeDev, or None when nothing is pending."""
        pending = getattr(self, "_page_pending", None)
        if not pending:
            return None
        for ci, base in pending.items():
            self._page_bases[ci] = base
        pending.clear()
        reg = _get_metrics()
        reg.counter("frontier.page_repacks").inc()
        tables, bucket = self._page_tables, self._page_bucket
        axis = bucket[1]
        full_rows = sum(t.fam.shape[0] for t in tables)
        _latch_resident_pct(round(
            100.0 * sum(min(t.fam.shape[0], axis) for t in tables)
            / max(full_rows, 1), 1,
        ))
        return CodeDev(*[
            self._page_placer(a)
            for a in stacked_device_tables(
                tables, bucket, page_bases=self._page_bases)
        ])

    # ------------------------------------------------------------------

    def _run(self, pairs: List[Tuple],
             bucket_floor: Optional[tuple] = None) -> int:
        caps = self.caps
        t_start = time.perf_counter()

        # mesh precondition lift: pad the slot batch up to a multiple of
        # the attached device count so the path axis always shards evenly
        # (the old `caps.B % n_dev == 0` gate silently fell back to a
        # single device).  The extra slots are ordinary dead slots (seed
        # -1, never injected into unless paths need them) — they cost only
        # their share of the packed transfers.
        if args.frontier_mesh:
            import dataclasses

            import jax

            n_dev = jax.device_count()
            if n_dev > 1 and caps.B % n_dev:
                pad = -caps.B % n_dev
                caps = dataclasses.replace(caps, B=caps.B + pad)
                self.caps = caps
                _get_metrics().counter("frontier.mesh_pad_slots").inc(pad)

        seed_lasers = [laser for laser, _ in pairs]
        seeds = [gs for _, gs in pairs]
        lasers: List = []
        for laser in seed_lasers:
            if laser not in lasers:
                lasers.append(laser)

        arena = HostArena(caps.ARENA)
        arena.seeds = seeds
        row_zero = arena.const_row(0, 256)
        row_one = arena.const_row(1, 256)

        # one stacked table entry per (laser, code) identity: hooks differ
        # per laser, so the same bytecode under two lasers gets two entries
        tables: List[CodeTables] = []
        table_laser: List = []
        table_code: List = []
        table_hash: List[str] = []
        table_idx: Dict[tuple, int] = {}
        seed_code_idx: List[int] = []
        for laser, gs in pairs:
            code = gs.environment.code
            key = (id(laser), id(code))
            ci = table_idx.get(key)
            if ci is None:
                ci = len(tables)
                table_idx[key] = ci
                # once-per-bytecode static pre-analysis (cached): prunes
                # events on statically unreachable instructions and feeds
                # the per-code hook elision below; None = pass disabled
                # or failed, packing proceeds exactly as before
                from mythril_tpu.staticpass import (
                    publish_reachability,
                    summary_for_code,
                )

                summary = summary_for_code(code)
                # register the reachable-edge oracle with the exploration
                # ledger so coverage is also quoted against the statically
                # reachable denominator (coverage_pct_reachable), and hand
                # the static interesting points to the adaptive controller
                # (flip-target ranking shares the oracle's codehash key)
                publish_reachability(code, summary)
                if summary is not None and getattr(
                        summary, "interesting_points", None):
                    try:
                        from mythril_tpu.adaptive import (
                            get_adaptive_controller,
                        )

                        get_adaptive_controller().register_points(
                            _code_hash_full(code),
                            summary.interesting_points,
                        )
                    except Exception:  # steering never breaks packing
                        log.debug("adaptive point registration failed",
                                  exc_info=True)
                hooked, conc_nop, val_gate = self._hook_info(laser, summary)
                tables.append(
                    CodeTables(
                        code.instruction_list,
                        arena,
                        hooked_opcodes=hooked,
                        code_size=len(getattr(code, "bytecode", b"") or b"")
                        or None,
                        conc_nop_opcodes=conc_nop,
                        value_gate_opcodes=val_gate,
                        static_summary=summary,
                    )
                )
                table_laser.append(laser)
                table_code.append(code)
                table_hash.append(_code_hash_full(code))
            seed_code_idx.append(ci)

        # ------------------------------------------------------------------
        # per-code bucket isolation (large-code frontier): cluster the
        # codes by their own size bucket and dispatch one batch per class,
        # so a creation-heavy outlier pays for its own padded axes instead
        # of inflating every small code's tables.  ``bucket_floor`` may be
        # a list of per-class floors (cooperative driver) — each class
        # picks the smallest floor that covers it.  --no-code-paging keeps
        # the legacy single corpus-wide bucket (the parity baseline).
        # ------------------------------------------------------------------
        floors: List[tuple] = []
        if isinstance(bucket_floor, list):
            floors = [tuple(f) for f in bucket_floor]
            bucket_floor = None
        elif bucket_floor is not None:
            floors = [tuple(bucket_floor)]
            bucket_floor = None
        if getattr(args, "code_paging", True):
            classes = bucket_classes(tables)
        else:
            classes = [(multi_size_bucket(tables), list(range(len(tables))))]
        if len(classes) > 1:
            reg = _get_metrics()
            # the counterfactual is the LEGACY single corpus-wide bucket —
            # no paging, so its instruction axis covers the largest code in
            # full (the r19 tail: one outlier inflating everyone's axes).
            # multi_size_bucket() under paging clamps at the residency
            # budget, which would understate the waste being recovered.
            single = multi_size_bucket(tables)
            single = (
                single[0],
                max(single[1], max(t.full_instr_cap() for t in tables)),
            ) + single[2:]
            single_waste = round(pad_waste_pct(tables, single), 2)
            waste_num = waste_den = 0.0
            for cls_bucket, members in classes:
                cells = cls_bucket[0] * cls_bucket[1]
                waste_num += pad_waste_pct(
                    [tables[i] for i in members], cls_bucket
                ) * cells
                waste_den += cells
                reg.gauge(
                    "frontier.bucket_class_occupancy.%d" % cls_bucket[1]
                ).set(round(100.0 * len(members) / cls_bucket[0], 1))
            total = 0
            for _cls_bucket, members in classes:
                member_set = set(members)
                sub_pairs = [
                    p for p, ci in zip(pairs, seed_code_idx)
                    if ci in member_set
                ]
                total += self._run(sub_pairs, bucket_floor=floors or None)
            # aggregate LAST: each single-class sub-run above writes its
            # own class's figure into the gauges, so the corpus-weighted
            # aggregate must land after the recursion to survive
            reg.gauge("frontier.pad_waste_pct").set(
                round(waste_num / max(waste_den, 1.0), 2)
            )
            reg.gauge("frontier.pad_waste_single_bucket_pct").set(
                single_waste
            )
            reg.gauge("frontier.bucket_classes").set(len(classes))
            return total

        natural_bucket = multi_size_bucket(tables)
        bucket = natural_bucket
        floor = self._pick_floor(floors, natural_bucket)
        if floor is not None:
            bucket = tuple(max(b, f) for b, f in zip(bucket, floor))
        code_cap, instr_cap, addr_cap, loops_cap = bucket
        # coverage planes are indexed by TRUE pc, so their axis covers the
        # whole code even when paged dispatch tables hold only a window
        visit_cap = max(instr_cap, visited_instr_cap(tables))
        _waste = round(pad_waste_pct(tables, bucket), 2)
        _reg = _get_metrics()
        # single class: the class bucket IS the corpus bucket, so the
        # counterfactual equals the actual.  A multi-class dispatch
        # earlier in this analysis latches bucket_classes >= 2 — its
        # corpus-weighted figures are the ones worth keeping, so a later
        # single-class round (e.g. a tail transaction touching one code)
        # must not clobber them.  reset_isolation_gauges() clears the
        # latch at analysis entry.  bucket_classes reads 0 when isolation
        # is off (--no-code-paging), 1 when on but the corpus is uniform.
        if int(_reg.gauge("frontier.bucket_classes").value or 0) <= 1:
            _reg.gauge("frontier.pad_waste_pct").set(_waste)
            _reg.gauge("frontier.pad_waste_single_bucket_pct").set(_waste)
            _reg.gauge("frontier.bucket_classes").set(
                1 if getattr(args, "code_paging", True) else 0
            )
        _reg.gauge(
            "frontier.bucket_class_occupancy.%d" % instr_cap
        ).set(round(100.0 * len(tables) / code_cap, 1))
        # packed-code paging state: per-table resident-window starts plus
        # pending faults the next sync-point repack folds in
        self._page_tables = tables
        self._page_bucket = bucket
        self._page_bases = [0] * len(tables)
        self._page_pending = {}
        self._page_fault_counts = {}
        paged_rows = sum(t.fam.shape[0] for t in tables)
        self._page_resident = sum(
            min(t.fam.shape[0], instr_cap) for t in tables
        )
        _latch_resident_pct(
            round(100.0 * self._page_resident / max(paged_rows, 1), 1)
        )
        program_key = (caps, bucket, visit_cap)
        program_warm = program_key in _WARM_PROGRAMS
        _devplane.install()
        with _otrace.span("frontier.compile", cat="frontier",
                          warm=program_warm, bucket=list(bucket)), \
                _devplane.dispatch_scope(bucket):
            # builds (or returns) the jitted program; the XLA compile
            # itself is paid inside the first dispatch's segment span
            # (warm=False marks it)
            segment = cached_segment(caps, *bucket)
        # marked warm only AFTER a segment actually dispatches (loop below):
        # a run that breaks before its first segment must not tag the still
        # uncompiled program as warm, or the NEXT run's compile-paying first
        # segment would count toward the slow-bail verdict
        import jax

        # tables are uploaded once and reused per segment; a page-fault
        # repack (window move) rebuilds them at the next sync point with
        # IDENTICAL shapes, so no recompile ever rides a repack
        code_dev = CodeDev(
            *[jax.device_put(a) for a in stacked_device_tables(
                tables, bucket, page_bases=self._page_bases)]
        )
        laser0 = lasers[0]
        cfg = CfgScalars(
            max_depth=np.int32(laser0.max_depth),
            loop_bound=np.int32(args.loop_bound or 0),
            row_zero=np.int32(row_zero),
            row_one=np.int32(row_one),
            sel_mode=np.int32(_sel_mode(laser0)),
            k_limit=np.int32(caps.K),
        )

        # seed contexts (also fills the arena with env rows)
        ctxs = [self._seed_ctx(arena, gs, i) for i, gs in enumerate(seeds)]

        # mid-frame seeds (resumed callers, earlier spills) are encoded up
        # front; any the encoder rejects bounce straight back to their host
        # work list (eligibility is a cheap pre-filter, the encoder decides)
        mid_enc: List[Optional[dict]] = []
        bounced = set()
        for i, gs in enumerate(seeds):
            if _is_fresh(gs):
                mid_enc.append(None)
                continue
            with _otrace.span("frontier.mid_encode", cat="frontier", seed=i):
                enc = self._encode_mid(arena, gs)
            mid_enc.append(enc)
            if enc is None:
                FrontierStatistics().mid_encode_failures += 1
                # stamp so _mid_eligible stops re-offering this state at
                # every drain while it sits at the same pc.  The work-list
                # re-append happens at the END of the run: _drain_pairs'
                # exception handler re-appends every pair, so appending here
                # would duplicate the state if the run later failed.
                gs._frontier_park_pc = gs.mstate.pc
                bounced.add(i)

        walker = Walker(seed_lasers, arena,
                        [tables[ci] for ci in seed_code_idx], seeds)
        st = empty_state(caps, loops_cap)
        records: Dict[int, Optional[PathRecord]] = {i: None for i in range(caps.B)}
        seed_queue = [i for i in range(len(seeds)) if i not in bounced]
        ev_seen = np.zeros(caps.B, np.int64)

        from mythril_tpu.frontier import step as step_mod

        beam = _sel_mode(laser0) == step_mod.SEL_BEAM

        statics = [
            1 if getattr(gs.environment, "static", False) else 0
            for gs in seeds
        ]

        # initial fill (adaptive: the steering plan's deficit scheduler
        # orders multi-code injection; FIFO — the parity baseline — with
        # one code, no plan, or --no-adaptive)
        for slot in range(caps.B):
            if not seed_queue:
                break
            si = seed_queue.pop(
                _adaptive_pick(seed_queue, seed_code_idx, table_hash)
            )
            self._inject(st, slot, si, ctxs[si], seed_code_idx[si],
                         _beam_importance(seeds[si]) if beam else 0,
                         static=statics[si])
            if mid_enc[si] is not None:
                self._apply_mid(st, slot, mid_enc[si])
                FrontierStatistics().mid_injections += 1
            records[slot] = PathRecord(seed_idx=si)
            ev_seen[slot] = 0

        # the arena stays device-resident across segments; the host pulls
        # only the newly appended row slices at each harvest
        dev_arena = ArenaDev(
            *[jax.device_put(a) for a in arena.device_arrays()]
        )
        arena_len = arena.length
        # [3, C, I] coverage planes: instruction / taken-edge / fall-edge
        # (see observability/exploration.py for the plane contract).  The
        # instruction axis is the FULL cap (true-pc indexed), independent
        # of the possibly-windowed dispatch-table axis
        visited = jax.device_put(np.zeros((3, code_cap, visit_cap), bool))

        # SPMD over the mesh path axis (SURVEY.md §5.8): with >1 attached
        # device the segment inputs are placed path-sharded (state) /
        # replicated (arena, tables, coverage) and GSPMD partitions the SAME
        # jitted program — the fork-grant prefix sum becomes the only
        # cross-shard collective
        mesh = None
        push_sharded = None
        n_dev = jax.device_count()
        if args.frontier_mesh and n_dev > 1:
            from mythril_tpu.parallel.mesh import (
                make_frontier_mesh,
                path_sharding,
            )

            if caps.B % n_dev:
                # caller-pinned caps the padding above could not touch
                # (checkpoint resume with a fixed width): run single-device,
                # but LOUDLY — the metric makes the fallback visible
                _get_metrics().counter("frontier.mesh_fallbacks").inc()
                log.warning(
                    "frontier: batch width %d not divisible by %d devices; "
                    "falling back to single-device execution",
                    caps.B, n_dev,
                )
            else:
                try:
                    mesh = make_frontier_mesh(path_size=n_dev)
                except Exception as e:  # pragma: no cover - defensive
                    _get_metrics().counter("frontier.mesh_fallbacks").inc()
                    log.warning(
                        "frontier: mesh construction failed (%s); "
                        "falling back to single-device execution", e,
                    )
        self._mesh_shards = n_dev if mesh is not None else 1
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            FrontierStatistics().mesh_devices = n_dev
            repl = NamedSharding(mesh, PartitionSpec())
            # read-mostly inputs placed replicated ONCE; segment outputs keep
            # their shardings, so no per-segment re-placement is needed
            dev_arena = jax.tree.map(
                lambda x: jax.device_put(x, repl), dev_arena
            )
            visited = jax.device_put(visited, repl)
            code_dev = jax.tree.map(
                lambda x: jax.device_put(x, repl), code_dev
            )

            def _path_sharding(x):
                return path_sharding(mesh, x)

            # event buffers start empty every segment: one constant sharded
            # pair reused for the whole run (nothing crosses the link)
            mesh_empty_events = jax.device_put(
                np.full_like(st.events, -1), _path_sharding(st.events)
            )
            mesh_empty_ev_len = jax.device_put(
                np.zeros_like(st.ev_len), _path_sharding(st.ev_len)
            )

            def push_sharded(state: FrontierState) -> FrontierState:
                """Host mirror -> path-sharded device state: each field ships
                straight from host numpy to its shards (no single-device
                staging hop; local-device transfers are cheap, unlike the
                tunnel case the packed push_state optimizes for)."""
                fields = {
                    name: jax.device_put(f, _path_sharding(f))
                    for name, f in zip(state._fields, state)
                    if name not in ("events", "ev_len")
                }
                fields["events"] = mesh_empty_events
                fields["ev_len"] = mesh_empty_ev_len
                return FrontierState(**fields)

        if mesh is not None:
            _repl = NamedSharding(mesh, PartitionSpec())
            self._page_placer = lambda a: jax.device_put(a, _repl)
        else:
            self._page_placer = jax.device_put
        executed = 0
        exec_timeout = min(
            laser.execution_timeout or args.execution_timeout
            for laser in lasers
        )
        deadline = t_start + exec_timeout
        narrow_harvests = 0
        max_live = 0
        run_segments = 0
        slow_bailed = False

        width_verdict_valid = True  # False when the run was cut short
        skip_loop = False

        # TTFE fix: a floored bucket shares one compiled program across a
        # cooperative corpus, but a COLD program then pays the big bucket's
        # XLA compile before the first event can harvest (ttfe_s regression
        # in BENCH_r05).  Run the OPENING dispatch at the program's natural
        # bucket — a small program that compiles fast — harvest it, and only
        # then enter the floored-bucket loop.  Time-to-first-event now rides
        # the small compile; the big compile amortizes over the rest.
        if mesh is None and bucket != natural_bucket and not program_warm:
            nat_cc, _nat_ic, _nat_ac, nat_lc = natural_bucket
            stats = FrontierStatistics()

            # pre-compile the floored big-bucket program in the background
            # while the opening natural-bucket segment runs: a dummy
            # dispatch on an all-empty state (every seed -1, so the segment
            # while_loop condition is false and zero steps execute) pays
            # exactly the XLA compile.  cached_segment only builds the
            # wrapper and jit.lower().compile() does not populate the
            # dispatch cache, so a real dispatch is the reliable warmup.
            # Inputs are shared with the live dispatch safely: the segment
            # donates nothing (_SEGMENT_DONATE_ARGNUMS is empty).
            def _precompile_floored():
                t0 = time.perf_counter()
                # the compile happens on THIS daemon thread: scope it so
                # the device plane attributes it to the floored bucket
                with _devplane.dispatch_scope(bucket):
                    try:
                        out = segment(
                            push_state(empty_state(caps, loops_cap)),
                            dev_arena, arena_len, visited, code_dev, cfg,
                        )
                        np.asarray(out[3])  # force completion
                    except Exception as e:  # pragma: no cover - diagnostics
                        log.debug("floored-bucket precompile failed: %s", e)
                _get_metrics().observe(
                    "frontier.bucket_compile_s", time.perf_counter() - t0
                )

            precompile = threading.Thread(
                target=_precompile_floored,
                name="mythril-bucket-precompile",
                daemon=True,
            )
            precompile.start()
            nat_segment = cached_segment(caps, *natural_bucket)
            nat_code_dev = CodeDev(*[
                jax.device_put(a)
                for a in stacked_device_tables(
                    tables, natural_bucket, page_bases=self._page_bases)
            ])
            # full-cap coverage axis (true-pc indexed), same as the floored
            # planes — the corner copy below is then a straight slice
            nat_visited = jax.device_put(
                np.zeros((3, nat_cc, visit_cap), bool)
            )
            cfg0 = cfg._replace(
                k_limit=np.int32(min(caps.K, 96 << min(stats.segments, 4)))
            )
            st_nat = st._replace(loops=st.loops[:, :nat_lc])
            t_seg = time.perf_counter()
            _fid0 = (_otrace.get_tracer().new_flow_id()
                     if _otrace.get_tracer().enabled else None)
            with _otrace.span(
                "frontier.segment", cat="device", segment=-1,
                warm=(caps, natural_bucket, visit_cap) in _WARM_PROGRAMS,
                opening=True,
                **(
                    {"requests": ",".join(self.request_tags)}
                    if self.request_tags else {}
                ),
            ), _otrace.device_annotation("frontier.segment"), \
                    _devplane.dispatch_scope(natural_bucket):
                if _fid0 is not None:
                    _otrace.get_tracer().flow("s", _fid0, "flow.segment",
                                              cat="device")
                self._fire_request_flows()
                out_state, dev_arena, out_len, n_exec, seg_ml, nat_visited = (
                    nat_segment(push_state(st_nat), dev_arena, arena_len,
                                nat_visited, nat_code_dev, cfg0)
                )
                st_p, arena_len, n_exec_host, seg_ml_host = pull_harvest(
                    out_state, out_len, n_exec, seg_ml
                )
            _frec.beat()
            max_live = max(max_live, seg_ml_host)
            arena.pull_from_device(dev_arena, arena_len)
            executed += n_exec_host
            stats.device_instructions += n_exec_host
            stats.segments += 1
            seg_only = time.perf_counter() - t_seg
            stats.segment_s += seg_only
            _get_metrics().observe("frontier.segment_wall_s", seg_only)
            _devplane.observe_segment(
                seg_only, _devplane.bucket_tag(natural_bucket)
            )
            _get_metrics().counter("frontier.opening_dispatches").inc()
            _WARM_PROGRAMS.add((caps, natural_bucket, visit_cap))
            _devplane.harvest_analysis(
                nat_segment,
                lambda st_nat=st_nat, dev_arena=dev_arena,
                arena_len=arena_len, nat_visited=nat_visited,
                nat_code_dev=nat_code_dev, cfg0=cfg0: (
                    push_state(st_nat), dev_arena, arena_len, nat_visited,
                    nat_code_dev, cfg0,
                ),
                _devplane.bucket_tag(natural_bucket),
            )
            st = st_p._replace(loops=np.ascontiguousarray(np.pad(
                st_p.loops, ((0, 0), (0, loops_cap - nat_lc))
            )))
            t_har = time.perf_counter()
            with _otrace.span("frontier.harvest", cat="frontier",
                              segment=-1):
                if _fid0 is not None:
                    _otrace.get_tracer().flow("f", _fid0, "flow.segment",
                                              cat="device")
                self._harvest(st, records, walker, ev_seen)
            ev_seen.fill(0)
            har_only = time.perf_counter() - t_har
            stats.harvest_s += har_only
            _get_metrics().observe("frontier.harvest_wall_s", har_only)
            # the opening coverage lives in the natural bucket's corner of
            # the floored bitmap (same code order, smaller caps)
            import jax.numpy as jnp

            visited = visited.at[:, :nat_cc, :].set(
                jnp.asarray(nat_visited)
            )
            live = int(((st.halt == O.H_RUNNING) & (st.seed >= 0)).sum())
            max_live = max(max_live, live)
            if live == 0 and not seed_queue:
                skip_loop = True  # nothing left for the floored program
            else:
                # the floored program dispatches next: join so its compile
                # time lands in bucket_compile_s and the first real segment
                # below measures dispatch, not compile
                precompile.join()

        if not skip_loop and args.pipeline:
            # pipeline and mesh COMPOSE: with a mesh the chained dispatches
            # run as one SPMD program over the path axis (push_fn places the
            # corrections with the exact shardings the in-flight outputs
            # carry, so GSPMD inserts no resharding between segments)
            from mythril_tpu.frontier.pipeline import PipelinedRunner

            runner = PipelinedRunner(
                self, st=st, records=records, walker=walker, arena=arena,
                ev_seen=ev_seen, seeds=seeds, seed_lasers=seed_lasers,
                lasers=lasers, ctxs=ctxs, seed_code_idx=seed_code_idx,
                mid_enc=mid_enc, seed_queue=seed_queue, statics=statics,
                beam=beam, tables=tables, table_code=table_code,
                table_hash=table_hash,
                table_idx=table_idx, segment=segment, code_dev=code_dev,
                cfg=cfg, dev_arena=dev_arena, arena_len=arena_len,
                visited=visited, deadline=deadline,
                program_key=program_key, program_warm=program_warm,
                mesh=mesh, push_fn=push_sharded,
                repack_fn=self._maybe_repack,
            )
            runner.run()
            st = runner.st
            executed = runner.executed + executed
            arena_len = runner.arena_len
            visited = runner.visited
            max_live = max(max_live, runner.max_live)
            slow_bailed = runner.slow_bailed
            width_verdict_valid = runner.width_verdict_valid
            skip_loop = True
        watch = _frec.activity() if not skip_loop else None
        if watch is not None:
            watch.__enter__()
        while not skip_loop:
            if time.perf_counter() > deadline or time_handler.time_remaining() <= 0:
                log.info("frontier: execution timeout; parking live paths")
                self._park_all(st, records, walker, reason="timeout")
                width_verdict_valid = False
                break

            stats = FrontierStatistics()
            t_seg = time.perf_counter()
            # step-limit ramp (dynamic scalar, no recompile): early segments
            # stay short so the first terminals harvest — and their exploits
            # confirm — quickly; later segments run long to amortize the
            # link round trip.  Keyed on the ANALYSIS-wide segment counter
            # (reset per contract by the facade/bench), not a per-drain
            # counter: periodic nested drains must not re-pay truncated
            # segments long after the first exploit confirmed.
            cfg = cfg._replace(
                k_limit=np.int32(min(caps.K, 96 << min(stats.segments, 4)))
            )
            st_dev = push_sharded(st) if mesh is not None else push_state(st)
            micro = (
                args.frontier_microbench
                and not stats.microbench
                and mesh is None
            )
            if micro:
                micro_args = (
                    st_dev, dev_arena, arena_len, visited, code_dev, cfg
                )
            _fid = (_otrace.get_tracer().new_flow_id()
                    if _otrace.get_tracer().enabled else None)
            with _otrace.span(
                "frontier.segment", cat="device",
                segment=run_segments, warm=program_warm,
                **(
                    {"requests": ",".join(self.request_tags)}
                    if self.request_tags else {}
                ),
            ), _otrace.device_annotation("frontier.segment"), \
                    _devplane.dispatch_scope(bucket):
                if _fid is not None:
                    _otrace.get_tracer().flow("s", _fid, "flow.segment",
                                              cat="device")
                self._fire_request_flows()
                out_state, dev_arena, out_len, n_exec, seg_max_live, visited = (
                    segment(st_dev, dev_arena, arena_len, visited, code_dev, cfg)
                )
                # pull state to host mirrors (writable: harvest mutates
                # slots): one packed meta transfer (scalars ride along) +
                # one bucket-capped events pull
                st, arena_len_new, n_exec_host, seg_ml_host = pull_harvest(
                    out_state, out_len, n_exec, seg_max_live
                )
            _frec.beat()
            max_live = max(max_live, seg_ml_host)
            arena.pull_from_device(dev_arena, arena_len_new)
            arena_len = arena_len_new
            executed += n_exec_host
            stats.device_instructions += n_exec_host
            stats.segments += 1
            seg_only = time.perf_counter() - t_seg
            if micro and n_exec_host > 0:
                t_mb = time.perf_counter()
                self._run_microbench(segment, micro_args, n_exec_host, st)
                # the microbench re-dispatches the segment 1+reps times by
                # design; that wall time is measurement overhead, not
                # exploration — compensate the execution deadline so a
                # microbenched run keeps the budget it was configured with
                deadline += time.perf_counter() - t_mb
            stats.segment_s += seg_only
            _get_metrics().observe("frontier.segment_wall_s", seg_only)
            _devplane.observe_segment(seg_only, _devplane.bucket_tag(bucket))
            _WARM_PROGRAMS.add(program_key)  # a segment really dispatched
            # compiled + persistently cached by the dispatch above: harvest
            # cost/memory analysis once per executable, off-thread
            _devplane.harvest_analysis(
                segment,
                lambda st_dev=st_dev, dev_arena=dev_arena,
                arena_len=arena_len, visited=visited, code_dev=code_dev,
                cfg=cfg: (
                    st_dev, dev_arena, arena_len, visited, code_dev, cfg
                ),
                _devplane.bucket_tag(bucket),
            )

            t_har = time.perf_counter()
            with _otrace.span("frontier.harvest", cat="frontier",
                              segment=run_segments):
                if _fid is not None:
                    _otrace.get_tracer().flow("f", _fid, "flow.segment",
                                              cat="device")
                self._harvest(st, records, walker, ev_seen)
            # events were fully drained into the path records, and the next
            # segment starts with EMPTY device buffers (push_state rebuilds
            # them; events never cross the link upward) — restart the
            # per-slot seen counters to match
            ev_seen.fill(0)
            # page-fault repack: the synchronous loop is all sync points —
            # fold pending window moves into fresh tables (same shapes, no
            # recompile) before the next dispatch
            new_code_dev = self._maybe_repack()
            if new_code_dev is not None:
                code_dev = new_code_dev
            har_only = time.perf_counter() - t_har
            stats.harvest_s += har_only
            _get_metrics().observe("frontier.harvest_wall_s", har_only)

            # mid-run throughput accounting — BEFORE the exit checks below,
            # so a run's final segment still counts (short explorations
            # split into 1-2 segment drains would otherwise never
            # accumulate a verdict): a run can stay live enough to dodge
            # the narrow bail yet execute fewer instructions per second
            # than the host engine steps (small programs over a high-RTT
            # link).  Measured on SEGMENT wall only (dispatch + transfers)
            # — harvest time is replay/confirmation work the host path
            # pays too.  A run's first segment counts only when the
            # program was already warm (else it may be paying the one-off
            # XLA compile); counters persist across runs per code.
            bail_now = False
            if (run_segments > 0 or program_warm) and not args.frontier_force:
                host_rates = [
                    r for r in (
                        getattr(laser, "host_step_rate", lambda: None)()
                        for laser in lasers
                    ) if r
                ]
                # min over lasers: a multi-code batch may pair a fast-host
                # contract with one whose host alternative is 100x slower
                # (bectoken-style wide-mul terms) — bailing the batch, and
                # blanket-marking its codes, must only happen when the
                # device underruns even the SLOWEST host alternative
                bail_rate = (
                    _SLOW_BAIL_HOST_FACTOR * min(host_rates)
                    if host_rates else _SLOW_BAIL_FLOOR
                )
                code_keys = [_code_key(c) for c in table_code]
                seg_rate = n_exec_host / max(seg_only, 1e-6)
                if seg_rate < bail_rate:
                    counts = [_SLOW_SEGMENTS.get(k, 0) + 1 for k in code_keys]
                    for k, c in zip(code_keys, counts):
                        _SLOW_SEGMENTS[k] = c
                    if (
                        max(counts) >= _SLOW_BAIL_SEGMENTS
                        or seg_rate < _SLOW_BAIL_DECISIVE * bail_rate
                    ):
                        log.info(
                            "frontier: %d instructions in %.2fs (below "
                            "%.0f/s); host engine takes over",
                            n_exec_host, seg_only, bail_rate,
                        )
                        bail_now = True
                else:
                    for k in code_keys:
                        _SLOW_SEGMENTS.pop(k, None)
            run_segments += 1
            if bail_now:
                # BEFORE the refill below: injecting queued seeds just to
                # park them straight back out would be a pure encode/park
                # round trip per free slot
                slow_bailed = True
                width_verdict_valid = False
                self._park_all(st, records, walker, reason="slow-bail")
                break

            # refill free slots with queued seeds; under beam search
            # also refresh live slots' scores (a seed's shared annotation
            # may have gained importance from sibling replays)
            for slot in range(caps.B):
                rec = records[slot]
                if rec is None and seed_queue:
                    si = seed_queue.pop(
                        _adaptive_pick(seed_queue, seed_code_idx, table_hash)
                    )
                    self._inject(st, slot, si, ctxs[si], seed_code_idx[si],
                                 _beam_importance(seeds[si]) if beam else 0,
                                 static=statics[si])
                    if mid_enc[si] is not None:
                        with _otrace.span("frontier.mid_inject",
                                          cat="frontier", seed=si):
                            self._apply_mid(st, slot, mid_enc[si])
                        FrontierStatistics().mid_injections += 1
                    records[slot] = PathRecord(seed_idx=si)
                    ev_seen[slot] = 0
                elif beam and rec is not None:
                    st.score[slot] = _beam_importance(seeds[rec.seed_idx])

            live = int(((st.halt == O.H_RUNNING) & (st.seed >= 0)).sum())
            max_live = max(max_live, live)
            if live == 0 and not seed_queue:
                break
            # --coverage-target: the request contract says stop at the
            # bar (or the all-codes plateau) — spending further segments
            # on saturated code is the waste this controller exists to cut
            if _adaptive_coverage_stop():
                log.info(
                    "frontier: coverage target reached; parking live paths"
                )
                self._park_all(st, records, walker, reason="coverage-target")
                width_verdict_valid = False
                break
            if arena_len + max(live, 1) * caps.R * 2 >= caps.ARENA:
                log.warning("frontier: arena nearly full; parking live paths")
                self._park_all(st, records, walker, reason="arena-full")
                width_verdict_valid = False
                break
            # adaptive bail-out: the device pays off only on wide frontiers
            # (the per-segment dispatch amortizes over live paths); a run
            # that stays narrow hands its paths to the host engine, which
            # steps small work lists faster than segment round trips
            if live < caps.MIN_LIVE:
                narrow_harvests += 1
                if narrow_harvests >= caps.NARROW_BAIL:
                    log.info(
                        "frontier: only %d live paths after %d segments; "
                        "host engine takes over", live, narrow_harvests,
                    )
                    self._park_all(st, records, walker, reason="narrow-bail")
                    break
            else:
                narrow_harvests = 0
        if watch is not None:
            watch.__exit__(None, None, None)

        if slow_bailed:
            # slow: proven slower than host stepping on this link (absolute
            # verdict) — but only for codes whose OWN slow-segment count
            # reached the bail threshold: a mixed batch bails on its worst
            # member's count, and blanket-marking would permanently disable
            # the device for codes that just joined the batch
            for code in table_code:
                key = _code_key(code)
                if _SLOW_SEGMENTS.get(key, 0) >= _SLOW_BAIL_SEGMENTS:
                    if key not in _SLOW_CODES:
                        _get_metrics().counter(
                            "frontier.slow_code_verdicts", persistent=True
                        ).inc()
                    _SLOW_CODES.add(key)
        elif max_live < caps.MIN_LIVE and width_verdict_valid:
            # narrow: stayed under MIN_LIVE (skipped for narrow drains,
            # still admitted by wide seed sets).  A run cut short by
            # timeout/arena pressure proves nothing and marks nothing.
            for code in table_code:
                if _code_key(code) not in _NARROW_CODES:
                    _get_metrics().counter(
                        "frontier.narrow_code_verdicts", persistent=True
                    ).inc()
                _NARROW_CODES.add(_code_key(code))

        visited_host = np.asarray(visited)
        for ci, (laser, code) in enumerate(zip(table_laser, table_code)):
            self._merge_coverage(visited_host[:, ci], tables[ci], code, laser)
        for i in bounced:
            seed_lasers[i].work_list.append(seeds[i])
        # seeds still queued when a break path ended the loop (slow-bail,
        # timeout, arena pressure) never occupied a slot: hand them back to
        # their host work lists or their paths would silently vanish
        for si in seed_queue:
            seed_lasers[si].work_list.append(seeds[si])
        return executed

    @staticmethod
    def _merge_coverage(visited: np.ndarray, tables, code, laser) -> None:
        """Device-executed coverage planes ``[3, I]`` into the coverage
        plugin's bitmap and the exploration ledger (the walker only
        replays hook events, so plugin-side coverage alone would
        underreport frontier runs; edge planes exist only here)."""
        bytecode = getattr(code, "bytecode", None)
        if not bytecode:
            return
        cov = getattr(laser, "coverage_plugin", None)
        if cov is not None:
            cov.record_visited(
                bytecode.hex(), tables.n,
                np.nonzero(visited[0, : tables.n])[0],
            )
        from mythril_tpu.observability.exploration import (
            get_exploration_ledger,
        )
        from mythril_tpu.support.support_utils import get_code_hash

        get_exploration_ledger().record_device_planes(
            get_code_hash(bytecode.hex()), tables.n, _jumpi_count(code),
            visited[:, : tables.n],
        )

    # ------------------------------------------------------------------

    def _harvest(self, st: FrontierState, records, walker: Walker,
                 ev_seen: np.ndarray, pipe=None) -> None:
        """``pipe`` is the PipelinedRunner when the pipelined loop drives
        this harvest: slot mutations are reported to its correction ledger
        (so they ride the next chained dispatch) and feasibility checks go
        to its background pool instead of blocking here.

        The phase work lives in frontier/harvest.py: vectorized event
        ingestion, the laser-affinity replay pool (args.harvest_workers;
        0 = serial), and the deterministic slot-order commit."""
        HarvestExecutor(self).harvest(st, records, walker, ev_seen, pipe)

    @staticmethod
    def _run_microbench(segment, micro_args, n_exec: int, st, reps: int = 4) -> None:
        """Pure device-compute time of one segment, link-independent.

        Over the axon tunnel neither wall timers nor block_until_ready see
        device time (the async signal completes locally, ~0.05 ms against a
        ~115 ms link).  Chained-dispatch subtraction cancels the link: one
        dispatch plus a forced host readback measures compute+RTT; ``reps``
        back-to-back dispatches on the SAME inputs (in-order device stream,
        no donation) measure reps*compute+RTT; the difference divided by
        reps-1 is the per-segment device compute alone.  Runs once per
        process on the first productive segment when args.frontier_microbench
        is set (bench.py's device_microbench block)."""
        t0 = time.perf_counter()
        out = segment(*micro_args)
        np.asarray(out[3])  # n_exec scalar readback forces a true sync
        t_one = time.perf_counter() - t0
        t0 = time.perf_counter()
        outs = [segment(*micro_args) for _ in range(reps)]
        np.asarray(outs[-1][3])
        t_many = time.perf_counter() - t0
        compute = max((t_many - t_one) / max(reps - 1, 1), 1e-9)
        # packed host->device push excludes events (rebuilt empty on
        # device); the packed pull rides the same layout + 2 scalars
        push_bytes = 4 * sum(
            int(np.prod(f.shape))
            for name, f in zip(st._fields, st)
            if name != "events"
        )
        FrontierStatistics().microbench = {
            "segment_compute_s": round(compute, 4),
            "instructions_per_s": round(n_exec / compute, 1),
            "n_exec_per_segment": int(n_exec),
            "dispatch_plus_link_s": round(t_one, 4),
            "bytes_pushed_per_segment": push_bytes,
            # the packed pull rides the same layout + the arena_len /
            # n_exec / max_live scalars (step.pull_harvest)
            "bytes_pulled_meta_per_segment": push_bytes + 12,
            "width": int(st.halt.shape[0]),
            "reps": reps,
        }

    def _lineage_constraint_rows(self, rec) -> List[int]:
        """Arena rows of the branch conditions appended along this path
        (parent prefixes up to each fork, then the record's own stream).
        Event decoding is shared with the walker (walker.fork_branch_row)."""
        from mythril_tpu.frontier.walker import fork_branch_row

        rows: List[int] = []
        chain = []
        node, upto = rec, len(rec.events)
        while node is not None:
            chain.append((node, upto))
            upto = node.fork_event_idx
            node = node.parent
        for level, (node, limit) in enumerate(reversed(chain)):
            for k in range(limit):
                ev = node.events[k]
                if int(ev[O.EV_KIND]) != O.E_FORK:
                    continue
                # this path continued past the event (fell through a granted
                # fork, or took the single decided branch)
                row = fork_branch_row(ev, taken=False)
                if row >= 0:
                    rows.append(row)
            # entering the next level means this node granted a fork the
            # child took: the child's side appended the taken condition
            if node is not rec:
                child = chain[len(chain) - 2 - level][0]
                row = fork_branch_row(
                    node.events[child.fork_event_idx], taken=True
                )
                if row >= 0:
                    rows.append(row)
        return rows

    def _lineage_mutated(self, rec, walker: Walker) -> bool:
        from mythril_tpu.plugins.plugins.mutation_pruner import MUTATOR_OPCODES

        mutators = frozenset(MUTATOR_OPCODES)
        names = walker.tables_for(rec).opcode_names
        node, upto = rec, len(rec.events)
        while node is not None:
            for k in range(upto):
                ev = node.events[k]
                if int(ev[O.EV_KIND]) != O.E_HOOK:
                    continue
                pc = int(ev[O.EV_PC])
                if pc < len(names) and names[pc] in mutators:
                    return True
            upto = node.fork_event_idx
            node = node.parent
        return False

    def _prefetch_mutation_checks(self, st: FrontierState, records,
                                  walker: Walker) -> None:
        from mythril_tpu.smt import UGT, symbol_factory
        from mythril_tpu.smt.solver import ProbeConfig, check_satisfiable_batch

        terminal = (O.H_STOP, O.H_RETURN, O.H_SELFDESTRUCT)
        queries, seen = [], set()
        for slot in range(self.caps.B):
            rec = records[slot]
            if rec is None or int(st.halt[slot]) not in terminal:
                continue
            if self._lineage_mutated(rec, walker):
                continue
            seed = walker.seeds[rec.seed_idx]
            value = seed.current_transaction.call_value
            try:
                raws = list(seed.world_state.constraints.get_all_raw())
                raws += [
                    walker.decode_wrapped(r, rec.seed_idx).raw
                    for r in self._lineage_constraint_rows(rec)
                ]
            except Exception as e:
                log.debug("mutation prefetch decode failed: %s", e)
                continue
            raws.append(
                UGT(value, symbol_factory.BitVecVal(0, 256)).raw
            )
            key = frozenset(t.tid for t in raws)
            if key not in seen:
                seen.add(key)
                queries.append(raws)
        if len(queries) >= 2:
            # the hook's exact budget (imported, so they cannot diverge); the
            # call's side effect is the solver memo the hook will hit
            from mythril_tpu.plugins.plugins.mutation_pruner import (
                MUTATION_PROBE_CONFIG,
            )
            from mythril_tpu.querycache import get_query_cache

            qc_hits = get_query_cache().hits_total()
            with _otrace.span(
                "frontier.mutation_prefetch", cat="frontier", n=len(queries)
            ) as sp:
                check_satisfiable_batch(
                    queries, ProbeConfig(**MUTATION_PROBE_CONFIG)
                )
                sp.set(
                    querycache_hits=get_query_cache().hits_total() - qc_hits
                )

    def _prune_running(self, st: FrontierState, records, walker: Walker,
                       ev_seen: np.ndarray, pipe=None) -> None:
        from mythril_tpu.smt.solver import check_satisfiable_batch

        todo = []
        for slot in range(self.caps.B):
            rec = records[slot]
            if rec is None or int(st.halt[slot]) != O.H_RUNNING:
                continue
            n_cons = int(st.cons_len[slot])
            if n_cons <= rec._pruned_at:
                continue
            if pipe is not None and n_cons <= rec._submitted_at:
                continue  # verdict for this lineage depth still pending
            seed = walker.seeds[rec.seed_idx]
            raws = list(seed.world_state.constraints.get_all_raw())
            try:
                raws += [
                    walker.decode_wrapped(int(r), rec.seed_idx).raw
                    for r in st.cons[slot, :n_cons]
                ]
            except Exception as e:
                # cannot prune this slot: treat as satisfiable (sound — the
                # path just keeps running) and don't re-decode every segment
                log.warning("prune decode failed on slot %d: %s", slot, e)
                rec._pruned_at = n_cons
                continue
            todo.append((slot, rec, n_cons, raws))
        if not todo:
            return
        if pipe is not None:
            # pipelined: the path keeps running SPECULATIVELY while the
            # pool solves in the background; an UNSAT verdict rolls it
            # back at a later harvest (pipeline.apply_verdicts).  The key
            # mirrors the solver fast path's canonical identity, so the
            # pool dedups in-flight twins and the worker hits the query
            # cache for everything already decided.
            # abstract pre-filter: one vectorized pass over the whole
            # batch of rows; a proven-UNSAT verdict skips the worker and
            # is published through the pool's normal done-queue so the
            # existing rollback machinery (apply_verdicts) kills the path
            kills = [False] * len(todo)
            if getattr(args, "prefilter", True):
                from mythril_tpu.absdomain import prefilter_batch

                kills = prefilter_batch([raws for _, _, _, raws in todo])
            for (slot, rec, n_cons, raws), killed in zip(todo, kills):
                rec._submitted_at = n_cons
                pipe.pool.submit(
                    slot, rec, n_cons, raws,
                    frozenset(t.tid for t in raws),
                    sid=getattr(pipe, "current_sid", -1),
                    verdict=False if killed else None,
                    point="%s:%#x" % (
                        _code_tag(walker.seeds[rec.seed_idx].environment.code),
                        int(st.pc[slot]),
                    ),
                )
            return
        # harvest feasibility is one of the query cache's three entry points
        # (ISSUE/querycache.rst): the batched check below takes the cache's
        # exact/core tiers per set inside _fast_path; the span records how
        # many of this sweep's decisions the cache absorbed
        from mythril_tpu.querycache import get_query_cache

        from mythril_tpu.observability.exploration import (
            VERDICT_CLASS,
            get_exploration_ledger,
        )

        qc_hits = get_query_cache().hits_total()
        statuses: List[str] = []
        t_solve = time.perf_counter()
        with _otrace.span(
            "frontier.prune_check", cat="frontier", n=len(todo)
        ) as sp:
            flags = check_satisfiable_batch(
                [raws for _, _, _, raws in todo], statuses_out=statuses
            )
            sp.set(querycache_hits=get_query_cache().hits_total() - qc_hits)
        # batched solve: attribute the sweep's wall evenly across the
        # program points it decided (a documented approximation — the
        # pipelined pool times each query exactly)
        share = (time.perf_counter() - t_solve) / len(todo)
        led = get_exploration_ledger()
        if len(statuses) < len(todo):  # defensive: fill missing statuses
            statuses = statuses + ["unsat"] * (len(todo) - len(statuses))
        for (slot, rec, n_cons, _), ok, status in zip(todo, flags, statuses):
            led.record_solver_time(
                "%s:%#x" % (
                    _code_tag(walker.seeds[rec.seed_idx].environment.code),
                    int(st.pc[slot]),
                ),
                share,
            )
            if ok:
                rec._pruned_at = n_cons
            else:
                rec.term_class = VERDICT_CLASS.get(status, "solver_unsat")
                led.stamp(rec.term_class)
                records[slot] = None
                clear_slot(st, slot)
                ev_seen[slot] = 0

    def _park_all(self, st: FrontierState, records, walker: Walker,
                  reason: str = "bulk") -> None:
        """Timeout/overflow: hand every live path back to the host engine."""
        stats = FrontierStatistics()
        if reason in ("timeout", "coverage-target"):
            # the execution budget is gone (or the coverage contract ended
            # the request): the host work list these paths land on will
            # never be drained, so they stop exploring HERE — other park
            # reasons (slow/narrow-bail, drain) genuinely continue
            # host-side and are stamped at their real end
            from mythril_tpu.observability.exploration import (
                get_exploration_ledger,
            )

            led = get_exploration_ledger()
            for slot in range(self.caps.B):
                rec = records[slot]
                if rec is not None and rec.term_class is None:
                    rec.term_class = "budget_exhausted"
                    led.stamp("budget_exhausted")
        for slot in range(self.caps.B):
            rec = records[slot]
            if rec is None:
                continue
            if int(st.halt[slot]) == O.H_RUNNING:
                st.halt[slot] = O.H_PARK
            rec.final = snapshot_slot(st, slot)
            if rec.final["halt"] in (O.H_PENDING_FORK, O.H_PAGE_FAULT):
                rec.final["halt"] = O.H_PARK
            stats.device_paths += 1
            stats.record_bulk_park(reason)
            try:
                walker.finish(rec)
            except Exception as e:  # pragma: no cover
                log.warning("frontier park failed: %s", e, exc_info=True)
            records[slot] = None
            clear_slot(st, slot)
