"""get_model: the one model-query entry point used across the framework.

Reference parity: mythril/support/model.py:15-63 — memoized over the constraint
tuple, applies the solver timeout clamped by remaining execution time, raises
UnsatError on unsat/unknown.  Here the query routes to the probe/CDCL stack
(mythril_tpu/smt/solver.py) instead of a z3 Optimize instance.
"""

from __future__ import annotations

import os
from functools import lru_cache
from typing import Optional, Sequence, Tuple

from mythril_tpu.exceptions import UnsatError
from mythril_tpu.smt.solver import Model, Optimize, ProbeConfig, SAT, UNSAT
from mythril_tpu.support.support_args import args
from mythril_tpu.support.time_handler import time_handler


def get_model(
    constraints,
    minimize=(),
    maximize=(),
    enforce_execution_time: bool = True,
    solver_timeout: Optional[int] = None,
) -> Model:
    """Solve ``constraints``; return a Model or raise UnsatError."""
    timeout = solver_timeout if solver_timeout is not None else args.solver_timeout
    if enforce_execution_time:
        timeout = min(timeout, int(max(time_handler.time_remaining(), 0) * 1000) // 2 + 1)
    if timeout <= 0:
        raise UnsatError("solver budget exhausted")

    raws = tuple(c.raw if hasattr(c, "raw") else c for c in constraints)
    min_raws = tuple(m.raw if hasattr(m, "raw") else m for m in minimize)
    max_raws = tuple(m.raw if hasattr(m, "raw") else m for m in maximize)
    return _get_model_cached(raws, min_raws, max_raws, timeout)


@lru_cache(maxsize=2**18)
def _get_model_cached(raws: tuple, min_raws: tuple, max_raws: tuple, timeout: int) -> Model:
    # lru_cache keyed by interned term tuples — the counterpart of the
    # reference's 2**23-entry cache over z3 constraint tuples.
    opt = Optimize(
        ProbeConfig(
            max_rounds=args.probe_rounds,
            candidates_per_round=args.probe_candidates,
            timeout_ms=timeout,
        )
    )
    opt.add(*raws)
    for m in min_raws:
        opt.minimize(m)
    for m in max_raws:
        opt.maximize(m)
    if args.solver_log:
        _dump_query(raws, args.solver_log)
    status = opt.check()
    if status != SAT:
        raise UnsatError(f"no model found ({status})")
    return opt.model()


_dump_counter = [0]


def _dump_query(raws, directory: str) -> None:
    """Dump the query term dump (the .ir analogue of --solver-log .smt2 files)."""
    os.makedirs(directory, exist_ok=True)
    _dump_counter[0] += 1
    path = os.path.join(directory, f"query_{_dump_counter[0]:06d}.ir")
    with open(path, "w") as f:
        for r in raws:
            f.write(repr(r) + "\n")
