"""Lower narrow path conditions from the bitblast tape to a 3-CNF plane.

Reuses ``native/bitblast.serialize`` wholesale (the same serialization
the abstract pre-filter packs, ``absdomain/tape.py``): the conjunction is
one append-only tape of word-level records, and this module re-lowers
each record to single-bit Tseitin gates with aggressive constant folding.
Every gate is binary, so no clause ever exceeds 3 literals — the search
kernel's clause plane is a fixed ``[C, 3]`` array.

Narrowing first: the same ``x == c`` / ``cnt <= 1`` range harvest the
pre-filter performs (``absdomain/tape._harvest``) pins the common-prefix
known bits of every harvested VAR node to constants *before* gate
construction.  The pins are implied by the asserted conjuncts, so adding
them preserves equisatisfiability (UNSAT stays exact), and they are what
makes engine queries "narrow": a 256-bit loop counter pinned to
``[0, 1]`` contributes one free bit, not 256.

Admission is structural and happens here: the decision set is the
narrowest VAR nodes whose unpinned bits fit ``bit_budget`` together;
wide incidental actors (an unconstrained sender riding along in a
module confirmation) stay as non-decision CNF variables.  Splitting
over a subset keeps UNSAT exact — a refutation exhausts conflicts, and
conflicts involve only implied assignments, so they hold for any value
of the undecided bits — while a search that runs out of decisions
lapses to UNKNOWN.  Queries whose narrowest var alone exceeds the
budget, or that blow the gate/clause caps, raise ``Unsupported`` and
fall through to the exact tiers — the blaster can reject, never
misdecide.

Soundness inventory (why a kernel UNSAT on this CNF proves the original
conjunction UNSAT): serialization abstractions only ADD behaviors
(fresh variables for base-array selects/keccak/apply, dropped select
congruence); narrowing pins are implied facts; the Tseitin lowering is
exact per record.  SAT is only ever a *candidate*: the caller rebuilds
the model through ``bitblast._rebuild_assignment`` and validates it with
``concrete_eval.evaluate`` against the ORIGINAL terms before trusting it.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from mythril_tpu.native import bitblast
from mythril_tpu.native.bitblast import (
    OP_ADD, OP_AND, OP_ASHR, OP_BAND, OP_BNOT, OP_BOR, OP_BXOR, OP_CONCAT,
    OP_CONST, OP_EQ, OP_EXTRACT, OP_ITE, OP_LSHR, OP_MUL, OP_NEG, OP_NOT,
    OP_OR, OP_SEXT, OP_SHL, OP_SLE, OP_SLT, OP_SUB, OP_ULE, OP_ULT, OP_VAR,
    OP_XOR, OP_ZEXT, Unsupported,
)
from mythril_tpu.smt import terms
from mythril_tpu.smt.terms import Term

__all__ = ["Blasted", "blast", "model_bytes"]

# tape records above this are not worth a host-side gate build: the
# pre-filter and exact tiers handle the wide tail
MAX_TAPE_NODES = 768

# a "bit" is either a Python bool (folded constant) or an int literal
# (2*var positive / 2*var+1 negated); variable 1 is the constant-TRUE
# anchor shared with the kernel plane
_TRUE, _FALSE = True, False


class Blasted:
    """One lowered query: 3-CNF clauses + model-readback bookkeeping."""

    __slots__ = ("clauses", "n_vars", "dec_vars", "tape", "var_bits",
                 "verdict", "free_bits", "projected", "truncated",
                 "abstracted")

    def __init__(self):
        self.clauses: List[List[int]] = []
        self.n_vars = 2  # vars 0/1 are the kernel's false/true anchors
        self.dec_vars: List[int] = []
        self.tape = None
        # per OP_VAR tape node, in tape order: list of bits, each either
        # ("c", 0/1) or ("v", cnf_var)
        self.var_bits: List[List[tuple]] = []
        self.verdict: Optional[str] = None  # "unsat" when decided here
        self.free_bits = 0
        self.projected = 0  # roots dropped by narrow-core projection
        self.truncated = 0  # subtrees cut at a summary pseudo-var
        # True when the tape carries select/keccak/UF sites: the tier
        # runs no CEGAR loop, so a SAT candidate violating lazy
        # congruence is expected fallthrough, not a soundness alarm
        self.abstracted = False


class _Builder:
    def __init__(self, var_cap: int, clause_cap: int):
        self.out = Blasted()
        self.var_cap = var_cap
        self.clause_cap = clause_cap
        self._memo: Dict[tuple, object] = {}

    # -- CNF primitives ------------------------------------------------

    def new_var(self) -> int:
        v = self.out.n_vars
        self.out.n_vars = v + 1
        if v >= self.var_cap:
            raise Unsupported("devsolver: CNF variable cap")
        return v

    def add(self, *lits: int) -> None:
        self.out.clauses.append(list(lits))
        if len(self.out.clauses) > self.clause_cap:
            raise Unsupported("devsolver: CNF clause cap")

    @staticmethod
    def neg(b):
        return (not b) if isinstance(b, bool) else b ^ 1

    def land(self, a, b):
        if isinstance(a, bool):
            return b if a else _FALSE
        if isinstance(b, bool):
            return a if b else _FALSE
        if a == b:
            return a
        if a == b ^ 1:
            return _FALSE
        key = ("and", min(a, b), max(a, b))
        hit = self._memo.get(key)
        if hit is not None:
            return hit
        z = 2 * self.new_var()
        self.add(z ^ 1, a)
        self.add(z ^ 1, b)
        self.add(a ^ 1, b ^ 1, z)
        self._memo[key] = z
        return z

    def lor(self, a, b):
        return self.neg(self.land(self.neg(a), self.neg(b)))

    def lxor(self, a, b):
        if isinstance(a, bool):
            return self.neg(b) if a else b
        if isinstance(b, bool):
            return self.neg(a) if b else a
        if a == b:
            return _FALSE
        if a == b ^ 1:
            return _TRUE
        key = ("xor", min(a & ~1, b & ~1), max(a & ~1, b & ~1),
               (a & 1) ^ (b & 1))
        hit = self._memo.get(key)
        if hit is not None:
            return hit
        z = 2 * self.new_var()
        self.add(z ^ 1, a, b)
        self.add(z ^ 1, a ^ 1, b ^ 1)
        self.add(z, a ^ 1, b)
        self.add(z, a, b ^ 1)
        self._memo[key] = z
        return z

    def lmux(self, c, a, b):
        """c ? a : b."""
        if isinstance(c, bool):
            return a if c else b
        if a == b:
            return a
        return self.lor(self.land(c, a), self.land(self.neg(c), b))

    def assert_true(self, b) -> None:
        if isinstance(b, bool):
            if not b:
                self.out.verdict = "unsat"
            return
        self.add(b)

    # -- word-level helpers (bit lists are little-endian) --------------

    def w_add(self, a: List, b: List, cin=_FALSE) -> Tuple[List, object]:
        out, c = [], cin
        for ai, bi in zip(a, b):
            x = self.lxor(ai, bi)
            out.append(self.lxor(x, c))
            c = self.lor(self.land(ai, bi), self.land(c, x))
        return out, c

    def w_not(self, a: List) -> List:
        return [self.neg(x) for x in a]

    def w_sub(self, a: List, b: List) -> Tuple[List, object]:
        # a - b == a + ~b + 1; carry-out == NOT borrow (1 means a >= b)
        return self.w_add(a, self.w_not(b), _TRUE)

    def w_ult(self, a: List, b: List):
        _diff, carry = self.w_sub(a, b)
        return self.neg(carry)

    def w_eq(self, a: List, b: List):
        acc = _TRUE
        for ai, bi in zip(a, b):
            acc = self.land(acc, self.neg(self.lxor(ai, bi)))
        return acc

    def w_slt(self, a: List, b: List):
        sa, sb = a[-1], b[-1]
        same = self.neg(self.lxor(sa, sb))
        return self.lor(self.land(sa, self.neg(sb)),
                        self.land(same, self.w_ult(a, b)))


def _const_int(bits: List) -> Optional[int]:
    """Concrete value of a bit vector iff every bit folded constant."""
    v = 0
    for i, b in enumerate(bits):
        if not isinstance(b, bool):
            return None
        if b:
            v |= 1 << i
    return v


def _harvest_pins(conjuncts: Sequence[Term], tape):
    """Range-harvest the conjuncts; return ``(var_pins, node_known)``.

    ``var_pins``: {OP_VAR tape node: (known_mask, known_value)} — the
    common-prefix bits every value in the harvested range shares, sunk
    onto the leaf variables.  ``node_known``: the same facts kept at
    the narrowed node itself (any op), for output-bit assertions and
    decision summaries when the sink dies early.  Raises
    ``tape_mod._RowRefuted`` via the caller when harvested ranges are
    contradictory (the row is UNSAT outright).

    Engine conditions rarely narrow a raw VAR: a calldata word is a
    CONCAT of 32 lazily-selected byte VARs, so a range pin on the word
    must be PUSHED DOWN through the structural ops (concat / extract /
    zext / sext) to reach the leaf VARs it actually constrains — that
    push-down is what turns a 256-bit ``x < 10`` word into 4 free bits
    instead of 256.
    """
    from mythril_tpu.absdomain import tape as tape_mod

    ranges: Dict[int, Tuple[int, int]] = {}
    widths: Dict[int, int] = {}

    def narrow(t: Term, lo: int, hi: int) -> None:
        node = tape.node_of.get(t.tid)
        if node is None:
            return
        w = t.width if terms.is_bv_sort(t.sort) else 1
        lo, hi = max(lo, 0), min(hi, (1 << w) - 1)
        cur = ranges.get(node)
        if cur is not None:
            lo, hi = max(lo, cur[0]), min(hi, cur[1])
        if lo > hi:
            raise tape_mod._RowRefuted
        ranges[node] = (lo, hi)
        widths[node] = w

    tape_mod._harvest_row(conjuncts, narrow)

    # node-level known bits for EVERY narrowed node — when the leaf
    # push-down below dies early (an ITE-guarded calldata byte), the
    # node itself still carries the fact as output-bit assertions and
    # as a decision summary
    node_known: Dict[int, Tuple[int, int]] = {}
    for node, (lo, hi) in ranges.items():
        w = widths[node]
        k = (lo ^ hi).bit_length()
        known = ((1 << w) - 1) & ~((1 << k) - 1)
        if known:
            node_known[node] = (known, lo & known)

    pins: Dict[int, Tuple[int, int]] = {}

    def pin(node: int, known: int, kv: int) -> None:
        """Sink a known-bits fact onto ``node``; recurse through the
        structural ops until it lands on VAR leaves (or dies trying —
        arithmetic ops don't distribute bitwise)."""
        if not known:
            return
        op, w, a0, a1, _a2, _x0, x1 = tape.records[node]
        if op == OP_VAR:
            pk, pv = pins.get(node, (0, 0))
            both = pk & known
            if (pv & both) != (kv & both):
                # two implied facts disagree on a shared bit: the
                # conjunction itself is contradictory
                raise tape_mod._RowRefuted
            pins[node] = (pk | known, pv | (kv & known))
        elif op == OP_CONCAT:
            w_hi = tape.records[a0][1]
            w_lo = w - w_hi
            lo_mask = (1 << w_lo) - 1
            pin(a1, known & lo_mask, kv & lo_mask)
            pin(a0, known >> w_lo, kv >> w_lo)
        elif op == OP_EXTRACT:
            pin(a0, known << x1, kv << x1)
        elif op == OP_ZEXT:
            wa = tape.records[a0][1]
            if (kv >> wa) & (known >> wa):
                raise tape_mod._RowRefuted  # zero-extension bit pinned 1
            pin(a0, known & ((1 << wa) - 1), kv & ((1 << wa) - 1))
        elif op == OP_SEXT:
            wa = tape.records[a0][1]
            lo_mask = (1 << wa) - 1
            k, v = known & lo_mask, kv & lo_mask
            hk = known >> wa  # high bits are all copies of the sign bit
            hv = (kv >> wa) & hk
            if hk:
                if hv and hv != hk:
                    raise tape_mod._RowRefuted  # copies disagree
                k |= 1 << (wa - 1)
                if hv:
                    v |= 1 << (wa - 1)
            pin(a0, k, v)

    for node, (known, kv) in node_known.items():
        pin(node, known, kv)
    return pins, node_known


def blast(conjuncts: Sequence[Term], bit_budget: int = 64,
          var_cap: int = 4096, clause_cap: int = 4096) -> Blasted:
    """Serialize + narrow + lower one conjunction to 3-CNF.

    Raises ``Unsupported`` for anything outside the narrow fragment; the
    returned object may carry ``verdict == "unsat"`` when narrowing or
    constant folding already refuted the query (no kernel run needed).
    """
    from mythril_tpu.absdomain import tape as tape_mod

    tape = bitblast.serialize(conjuncts, lazy_selects=True)
    if len(tape.records) > MAX_TAPE_NODES:
        raise Unsupported("devsolver: tape too large")

    bld = _Builder(var_cap, clause_cap)
    out = bld.out
    out.tape = tape
    out.abstracted = bool(tape.selects or tape.keccaks or tape.applies)

    try:
        pins, node_known = _harvest_pins(conjuncts, tape)
    except tape_mod._RowRefuted:
        out.verdict = "unsat"
        return out

    # admission pre-scan: PROJECT the conjunction onto its narrow core.
    # Engine queries mix narrow pinned words with wide incidental actors
    # (sender, call value, balance selects); a root whose free support
    # fits the decision budget is kept, the rest are dropped before any
    # gate is built.  Refuting a SUBSET of the asserted conjuncts
    # refutes the whole conjunction, so UNSAT stays exact; a kernel SAT
    # on the projection is only a candidate and is validated against
    # the ORIGINAL conjuncts by the caller.  Within the kept core the
    # kernel branches only over decision bits — conflicts involve
    # implied assignments alone, so exhausting them holds for any value
    # of the undecided bits, while running out of decisions lapses to
    # UNKNOWN.
    #
    # A decision SOURCE is either a free VAR leaf or a harvested
    # interior node (a calldata word whose bytes hide behind ITE size
    # guards): the node's unpinned OUTPUT bits summarize its whole
    # subtree, so a 256-bit ``x < 16`` word costs 4 decision bits even
    # when no leaf pin can land.
    n_free: Dict[int, int] = {}
    for node, (op, w, *_rest) in enumerate(tape.records):
        if op == OP_VAR:
            known, _kv = pins.get(node, (0, 0))
            n_free[node] = w - bin(known).count("1")
    out.free_bits = sum(n_free.values())

    def src_cost(src: Tuple[str, int]) -> int:
        kind, node = src
        if kind == "var":
            return n_free[node]
        known, _kv = node_known[node]
        return tape.records[node][1] - bin(known).count("1")

    support: List[frozenset] = []  # per record: decision sources
    for node, rec in enumerate(tape.records):
        op = rec[0]
        if op == OP_VAR:
            s = frozenset((("var", node),)) if n_free[node] else frozenset()
        else:
            s = frozenset()
            for a in rec[2:5]:
                if a >= 0:
                    s |= support[a]
        if node in node_known:
            # summarize ONLY undecidable subtrees: truncation severs
            # the node from its inputs, so a subtree that fits the
            # budget is worth keeping intact (its relations to sibling
            # terms are exactly what the kernel refutes)
            subtree = sum(src_cost(x) for x in s)
            if subtree > bit_budget and src_cost(("node", node)) < subtree:
                s = frozenset((("node", node),))
        support.append(s)

    chosen: set = set()
    kept: set = set()  # positions into tape.roots
    spent = 0
    costed = sorted(
        (sum(src_cost(x) for x in support[r]), i, r)
        for i, r in enumerate(tape.roots)
    )
    for _cost, i, r in costed:
        extra = sum(src_cost(x) for x in support[r] - chosen)
        if spent + extra > bit_budget:
            continue  # shared sources can make a later root affordable
        spent += extra
        chosen |= support[r]
        kept.add(i)
    if not kept:
        raise Unsupported("devsolver: no root fits decision budget %d"
                          % bit_budget)
    out.projected = len(tape.roots) - len(kept)
    decide_vars = {n for k, n in chosen if k == "var"}
    decide_summ = {n for k, n in chosen if k == "node"}

    # records reachable from a kept root, CUT at summary nodes: a
    # summarized subtree (the ITE size-guard comparators under a
    # calldata word) is replaced wholesale by a fresh pseudo-variable,
    # so none of its gates are built
    needed: set = set()
    stack = [tape.roots[i] for i in kept]
    while stack:
        n = stack.pop()
        if n in needed:
            continue
        needed.add(n)
        if n in decide_summ:
            continue
        for a in tape.records[n][2:5]:
            if a >= 0 and a not in needed:
                stack.append(a)

    consts = bytes(tape.consts)
    bits: List[List] = []
    for node, rec in enumerate(tape.records):
        op, w, a0, a1, a2, x0, x1 = rec
        if op == OP_CONST:
            v = int.from_bytes(consts[x0:x0 + x1], "little") & ((1 << w) - 1)
            nb = [bool((v >> i) & 1) for i in range(w)]
        elif op == OP_VAR:
            known, kv = pins.get(node, (0, 0))
            decide = node in decide_vars
            nb, refs = [], []
            for i in range(w):
                if (known >> i) & 1:
                    bit = bool((kv >> i) & 1)
                    refs.append(("c", 1 if bit else 0))
                else:
                    cv = bld.new_var()
                    if decide:
                        out.dec_vars.append(cv)
                    bit = 2 * cv
                    refs.append(("v", cv))
                nb.append(bit)
            out.var_bits.append(refs)
        elif node not in needed:
            nb = None  # only feeds dropped roots or a cut subtree
        elif node in decide_summ:
            # truncate: the node becomes a fresh pseudo-variable with
            # its harvested known bits pinned and the rest decided —
            # an abstraction that only ADDS behaviors, so a refutation
            # of the truncated formula refutes the original
            known, kv = node_known[node]
            nb = []
            for i in range(w):
                if (known >> i) & 1:
                    nb.append(bool((kv >> i) & 1))
                else:
                    cv = bld.new_var()
                    out.dec_vars.append(cv)
                    nb.append(2 * cv)
            out.truncated += 1
        else:
            nb = _lower(bld, op, w, x0, x1,
                        bits[a0] if a0 >= 0 else None,
                        bits[a1] if a1 >= 0 else None,
                        bits[a2] if a2 >= 0 else None)
            if node in node_known:
                # implied output-bit facts: assert the harvested known
                # bits directly on the gate outputs (units that drive
                # propagation); unpinned bits become decisions when
                # this node was chosen as a summary source
                known, kv = node_known[node]
                summ = node in decide_summ
                for i in range(w):
                    b = nb[i]
                    if (known >> i) & 1:
                        want = bool((kv >> i) & 1)
                        if isinstance(b, bool):
                            if b != want:
                                out.verdict = "unsat"
                                return out
                        else:
                            bld.add(b if want else b ^ 1)
                    elif summ and not isinstance(b, bool):
                        out.dec_vars.append(b >> 1)
        bits.append(nb)
    # summary sources can alias gate vars already decided elsewhere
    out.dec_vars = list(dict.fromkeys(out.dec_vars))

    for i, root in enumerate(tape.roots):
        if i not in kept:
            continue
        bld.assert_true(bits[root][0])
        if out.verdict is not None:
            return out
    return out


def _lower(bld: _Builder, op: int, w: int, x0: int, x1: int,
           A: Optional[List], B: Optional[List], C: Optional[List]
           ) -> List:
    """Tseitin-lower one tape record; raises Unsupported outside the
    narrow fragment (division, symbolic shifts, symbolic multiply)."""
    if op == OP_EQ:
        return [bld.w_eq(A, B)]
    if op == OP_AND:
        return [bld.land(A[0], B[0])]
    if op == OP_OR:
        return [bld.lor(A[0], B[0])]
    if op == OP_NOT:
        return [bld.neg(A[0])]
    if op == OP_XOR:
        return [bld.lxor(A[0], B[0])]
    if op == OP_ITE:
        return [bld.lmux(A[0], B[i], C[i]) for i in range(w)]
    if op == OP_ADD:
        return bld.w_add(A, B)[0]
    if op == OP_SUB:
        return bld.w_sub(A, B)[0]
    if op == OP_BAND:
        return [bld.land(a, b) for a, b in zip(A, B)]
    if op == OP_BOR:
        return [bld.lor(a, b) for a, b in zip(A, B)]
    if op == OP_BXOR:
        return [bld.lxor(a, b) for a, b in zip(A, B)]
    if op == OP_BNOT:
        return bld.w_not(A)
    if op == OP_NEG:
        return bld.w_add(bld.w_not(A), [_FALSE] * w, _TRUE)[0]
    if op == OP_MUL:
        ca, cb = _const_int(A), _const_int(B)
        if ca is None and cb is None:
            raise Unsupported("devsolver: symbolic multiply")
        k, v = (A, cb) if cb is not None else (B, ca)
        acc = [_FALSE] * w
        for i in range(w):
            if (v >> i) & 1:
                shifted = [_FALSE] * i + k[: w - i]
                acc = bld.w_add(acc, shifted)[0]
        return acc
    if op in (OP_SHL, OP_LSHR, OP_ASHR):
        s = _const_int(B)
        if s is None:
            raise Unsupported("devsolver: symbolic shift")
        if op == OP_SHL:
            return ([_FALSE] * s + A[: w - s]) if s < w else [_FALSE] * w
        if op == OP_LSHR:
            return (A[s:] + [_FALSE] * s) if s < w else [_FALSE] * w
        # ashr: matches concrete_eval (shift clamped to w-1, sign fill)
        s = min(s, w - 1)
        return A[s:] + [A[-1]] * s
    if op == OP_CONCAT:
        return B + A  # low part is B (width w - len(A)), high part A
    if op == OP_EXTRACT:
        return A[x1:x1 + w]
    if op == OP_ZEXT:
        return A + [_FALSE] * (w - len(A))
    if op == OP_SEXT:
        return A + [A[-1]] * (w - len(A))
    if op == OP_ULT:
        return [bld.w_ult(A, B)]
    if op == OP_ULE:
        return [bld.neg(bld.w_ult(B, A))]
    if op == OP_SLT:
        return [bld.w_slt(A, B)]
    if op == OP_SLE:
        return [bld.neg(bld.w_slt(B, A))]
    raise Unsupported("devsolver: op %d" % op)


def model_bytes(blasted: Blasted, assign_row) -> bytes:
    """Pack a kernel assignment into ``bitblast._rebuild_assignment``'s
    model wire format: per OP_VAR node in tape order, ``(w+7)//8``
    little-endian bytes.  Unassigned CNF variables read as 0 — any
    extension of an all-clauses-satisfied partial assignment is a model,
    and host validation is the final authority either way."""
    out = bytearray()
    for refs in blasted.var_bits:
        v = 0
        for i, (kind, payload) in enumerate(refs):
            if kind == "c":
                bit = payload
            else:
                bit = 1 if int(assign_row[payload]) == 1 else 0
            v |= bit << i
        out += v.to_bytes((len(refs) + 7) // 8, "little")
    return bytes(out)
