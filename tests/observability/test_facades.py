"""Facade parity: the registry-backed statistics singletons must be
indistinguishable from the pre-observability attribute-style originals,
and report meta must carry the new observability block."""

import json

import pytest

from mythril_tpu.frontier.stats import FrontierStatistics
from mythril_tpu.observability import (
    get_registry,
    get_tracer,
    reset_analysis_metrics,
)
from mythril_tpu.smt.solver import SolverStatistics


@pytest.fixture(autouse=True)
def _clean_stats():
    FrontierStatistics().reset()
    SolverStatistics().reset()
    yield
    FrontierStatistics().reset()
    SolverStatistics().reset()


# seed-identical as_dict for a freshly reset instance — byte-for-byte
_SEED_EMPTY = (
    '{"device_instructions": 0, "device_paths": 0, "segments": 0, '
    '"mesh_devices": 0, "segment_s": 0.0, "harvest_s": 0.0, '
    '"mid_injections": 0, "mid_encode_failures": 0, "semantic_parks": 0, '
    '"parks_by_opcode": {}, "parks_by_reason": {}}'
)


def test_frontier_as_dict_empty_is_byte_identical_to_seed():
    assert json.dumps(FrontierStatistics().as_dict()) == _SEED_EMPTY


def test_frontier_as_dict_populated_matches_seed_shape():
    stats = FrontierStatistics()
    stats.device_instructions += 1000
    stats.device_paths += 3
    stats.segments += 2
    stats.segment_s += 1.23456
    stats.harvest_s += 0.98765
    stats.mesh_devices = 8
    stats.mid_injections += 1
    stats.record_park("CALL")
    stats.record_park("CALL")
    stats.record_park("SHA3")
    stats.record_bulk_park("timeout", 5)
    stats.record_bulk_park("noop", 0)  # n=0 must not create a key
    stats.microbench = {"segment_compute_s": 0.1}
    assert json.dumps(stats.as_dict()) == (
        '{"device_instructions": 1000, "device_paths": 3, "segments": 2, '
        '"mesh_devices": 8, "segment_s": 1.235, "harvest_s": 0.988, '
        '"mid_injections": 1, "mid_encode_failures": 0, "semantic_parks": 0, '
        '"parks_by_opcode": {"CALL": 2, "SHA3": 1}, '
        '"parks_by_reason": {"timeout": 5, "opcode": 3}, '
        '"microbench": {"segment_compute_s": 0.1}}'
    )


def test_frontier_singleton_and_registry_share_state():
    FrontierStatistics().segments += 4
    assert FrontierStatistics().segments == 4
    assert get_registry().snapshot()["frontier.segments"] == 4


def test_solver_stats_attribute_assignment_and_repr():
    stats = SolverStatistics()
    stats.query_count += 2
    stats.solver_time += 0.5
    stats.probe_hits = 9  # direct assignment (test_recall_differential style)
    stats.unknown_as_unsat = 0
    assert SolverStatistics() is stats
    assert SolverStatistics().probe_hits == 9
    assert repr(stats) == (
        "Solver statistics: query count: 2, solver time: 0.500, "
        "probe hits: 9, cdcl calls: 0, unknown treated as unsat: 0"
    )


def test_solver_enabled_survives_reset():
    stats = SolverStatistics()
    stats.enabled = True
    stats.query_count += 5
    stats.reset()
    assert stats.enabled is True
    assert stats.query_count == 0


def test_reset_analysis_metrics_sweeps_both_facades_keeps_persistent():
    FrontierStatistics().segments += 3
    SolverStatistics().query_count += 7
    get_registry().counter("frontier.slow_code_verdicts", persistent=True).inc()
    reset_analysis_metrics()
    assert FrontierStatistics().segments == 0
    assert SolverStatistics().query_count == 0
    assert (
        get_registry().counter("frontier.slow_code_verdicts", persistent=True).value
        == 1
    )
    get_registry().reset(include_persistent=True)


def test_report_meta_observability_roundtrip_jsonv2():
    from mythril_tpu.analysis.report import Report
    from mythril_tpu.core.execution_info import SolverStatsInfo

    SolverStatistics().query_count += 11
    report = Report(execution_info=[SolverStatsInfo()])
    meta = json.loads(report.as_swc_standard_format())[0]["meta"]
    # legacy execution-info rollup is untouched
    assert meta["mythril_execution_info"]["solver_query_count"] == 11
    # new block: full metrics snapshot rides the same jsonv2 document
    metrics = meta["observability"]["metrics"]
    assert metrics["solver.query_count"] == 11
    assert "frontier.segments" in metrics


def test_report_meta_includes_trace_summary_when_tracing():
    from mythril_tpu.analysis.report import Report

    tracer = get_tracer()
    tracer.reset()
    tracer.enabled = True
    try:
        with tracer.span("unit.test", cat="test"):
            pass
        meta = json.loads(Report().as_swc_standard_format())[0]["meta"]
        trace = meta["observability"]["trace"]
        assert trace["enabled"] is True
        assert trace["spans"] == 1
    finally:
        tracer.enabled = False
        tracer.reset()
