"""Persistent SMT query cache: verdict memoization + cheap reuse tiers.

An in-process LRU (always on unless ``--no-query-cache``) layered over an
optional disk store (``--query-cache-dir``), keyed by the renaming-invariant
canonical hash from :mod:`mythril_tpu.querycache.canon`.  Lookup runs three
tiers, every one strictly cheaper than any solver dispatch:

exact
    The canonical hash indexes a stored verdict.  A SAT entry carries its
    model (canonical-index keyed); the model is rebuilt onto THIS query's
    variables and re-validated with ``concrete_eval.evaluate`` before being
    served, so a served SAT is sound by construction exactly like a probe
    hit.  A served UNSAT is sound because hash equality implies
    alpha-equivalence (canon.py's encoding is a complete invariant).
    UNKNOWN entries carry the budget they were produced under and are
    served only to requests with an equal-or-smaller budget — a larger
    budget must retry, exactly reproducing what cold solving would do.

core subsumption
    Minimized unsat cores are stored as sets of name-preserving conjunct
    hashes.  A cached core that is a SUBSET of the query's conjunct-hash
    set proves the query unsat (a conjunction containing an unsatisfiable
    subset is unsatisfiable; names must match, so shared-variable identity
    is preserved).

model reuse
    Recently cached SAT models are materialized onto the query's variables
    by (name, sort) and evaluated; a satisfying one answers SAT without
    solving — the cross-run analogue of the solver's in-process
    recent-model replay tier.

Every tier either re-validates on the live query or rests on an exact
argument, so cached answers are verdict-identical to cold solving.

Counters (``querycache.*``) live in the observability registry and flow
into jsonv2 report meta / ``--metrics-out`` like every other subsystem's.
"""

from __future__ import annotations

import logging
import threading
from collections import OrderedDict
from typing import Dict, FrozenSet, List, Optional, Sequence, Tuple

from mythril_tpu.observability import tracer as _otrace
from mythril_tpu.querycache import canon
from mythril_tpu.querycache.store import DiskStore
from mythril_tpu.smt.concrete_eval import Assignment, evaluate
from mythril_tpu.smt.terms import Term

log = logging.getLogger(__name__)

# mirror smt.solver's verdict strings without importing it (the solver
# imports this package at its hook sites; a module-level back-import would
# be a cycle)
SAT = "sat"
UNSAT = "unsat"
UNKNOWN = "unknown"

_UNSET = object()

_COUNTERS = (
    "querycache.lookups",
    "querycache.exact_hits",
    "querycache.model_hits",
    "querycache.core_hits",
    "querycache.unknown_hits",
    "querycache.unknown_retries",
    "querycache.misses",
    "querycache.stores",
    "querycache.disk_reads",
    "querycache.disk_writes",
    "querycache.validation_failures",
)

_HIT_COUNTERS = (
    "querycache.exact_hits",
    "querycache.model_hits",
    "querycache.core_hits",
    "querycache.unknown_hits",
)


def _registry():
    from mythril_tpu.observability.metrics import get_registry

    return get_registry()


def materialize_counters() -> None:
    """Force-create the querycache.* counters so registry snapshots (report
    meta, --metrics-out, bench) always carry the full block, zeroes
    included, even for runs where the cache never fired."""
    reg = _registry()
    for name in _COUNTERS:
        reg.counter(name)


class QueryCache:
    # models probed per lookup in the reuse tier (each miss is one host
    # DAG evaluation, same cost class as the solver's replay tier)
    MODEL_PROBE_LIMIT = 8
    # cores larger than this are not stored: a wide core almost never
    # recurs as a subset of a different query, and subset checks over the
    # member index stay O(small)
    CORE_SIZE_CAP = 12
    # greedy core minimization is attempted only below this set size
    # (quadratic interval-refutation walks)
    MINIMIZE_CAP = 16

    def __init__(self, max_entries: int = 4096, max_models: int = 64,
                 max_cores: int = 4096) -> None:
        self.enabled = True
        self.max_entries = max_entries
        self.max_models = max_models
        self.max_cores = max_cores
        # RLock: lookup can trigger a disk read that re-enters bookkeeping
        self._lock = threading.RLock()
        self._store: Optional[DiskStore] = None
        self._entries: "OrderedDict[str, dict]" = OrderedDict()
        self._models: "OrderedDict[str, dict]" = OrderedDict()
        self._cores: Dict[str, FrozenSet[str]] = {}
        # one representative member (min hash) -> core ids: a core can only
        # subsume a query that contains its representative, so lookup walks
        # the query's own hashes instead of every stored core
        self._core_members: Dict[str, List[str]] = {}
        self._fp_memo: Dict[frozenset, canon.QueryFingerprint] = {}

    # -- configuration ------------------------------------------------

    def configure(self, enabled=None, cache_dir=_UNSET) -> None:
        with self._lock:
            if enabled is not None:
                self.enabled = bool(enabled)
            if cache_dir is _UNSET:
                return
            if cache_dir is None:
                self._store = None
                return
            try:
                self._store = DiskStore(cache_dir)
            except OSError as e:
                log.warning("query cache dir %s unusable (%s); disk layer off",
                            cache_dir, e)
                self._store = None
                return
            for cid, hashes in self._store.load_cores(self.max_cores).items():
                self._add_core(cid, hashes, write=False)

    def reset(self) -> None:
        """Drop the in-process layers (bench cold runs / per-test isolation).
        The disk store survives and its cores are re-indexed, so a
        configured warm run hits only through disk."""
        with self._lock:
            self._entries.clear()
            self._models.clear()
            self._cores.clear()
            self._core_members.clear()
            self.clear_memos()
            if self._store is not None:
                for cid, hashes in self._store.load_cores(self.max_cores).items():
                    self._add_core(cid, hashes, write=False)

    def clear_memos(self) -> None:
        """Drop term-id-keyed memos only (they reference interned Terms;
        cleared alongside the solver's term caches so dropped DAGs can be
        collected and tids can never be served stale)."""
        self._fp_memo.clear()
        canon.clear_memos()

    # -- fingerprints --------------------------------------------------

    def _fingerprint(self, conj: Sequence[Term]) -> canon.QueryFingerprint:
        key = frozenset(c.tid for c in conj)
        fp = self._fp_memo.get(key)
        if fp is None:
            if len(self._fp_memo) >= 8192:
                self._fp_memo.clear()
            fp = canon.fingerprint(conj)
            self._fp_memo[key] = fp
        return fp

    # -- lookup ---------------------------------------------------------

    def lookup(
        self,
        conjuncts: Sequence[Term],
        budget_ms: Optional[int] = None,
        probe_models: bool = True,
    ) -> Optional[Tuple[str, Optional[Assignment]]]:
        """Decide the conjunction from cached knowledge, or None (miss).

        ``budget_ms``: the requesting query's solver budget — required for
        serving cached UNKNOWNs (None never serves them).  ``probe_models``
        gates the model-reuse tier for batched callers that replay models
        over a merged union themselves.
        """
        if not self.enabled or not conjuncts:
            return None
        reg = _registry()
        with self._lock:
            reg.counter("querycache.lookups").inc()
            fp = self._fingerprint(conjuncts)
            result, tier = self._lookup_locked(
                conjuncts, fp, budget_ms, probe_models, reg
            )
        if result is None:
            reg.counter("querycache.misses").inc()
            return None
        reg.counter("querycache." + tier).inc()
        if _otrace.get_tracer().enabled:
            with _otrace.span(
                "querycache.hit", cat="smt",
                tier=tier[:-1] if tier.endswith("s") else tier,
                status=result[0], conjuncts=len(conjuncts),
            ):
                pass
        return result

    def _lookup_locked(self, conjuncts, fp, budget_ms, probe_models, reg):
        entry = self._entries.get(fp.qhash)
        if entry is not None:
            self._entries.move_to_end(fp.qhash)
        elif self._store is not None:
            entry = self._store.read_entry(fp.qhash)
            if entry is not None:
                reg.counter("querycache.disk_reads").inc()
                self._remember_entry(fp.qhash, entry)
        if entry is not None:
            verdict = entry.get("verdict")
            if verdict == UNSAT:
                return (UNSAT, None), "exact_hits"
            if verdict == SAT:
                model = entry.get("model")
                asg = canon.load_model(model, fp.var_order) if model else None
                if asg is not None and self._validates(conjuncts, asg):
                    self._remember_model(fp.qhash, model)
                    return (SAT, asg), "exact_hits"
                # hash collisions are cryptographically negligible, but the
                # validation gate means even one could only cost a miss
                reg.counter("querycache.validation_failures").inc()
            elif verdict == UNKNOWN:
                cached_budget = entry.get("budget_ms")
                if (
                    budget_ms is not None
                    and cached_budget is not None
                    and int(budget_ms) <= int(cached_budget)
                ):
                    return (UNKNOWN, None), "unknown_hits"
                reg.counter("querycache.unknown_retries").inc()
        cid = self._subsuming_core(fp.conj_hashes)
        if cid is not None:
            return (UNSAT, None), "core_hits"
        if probe_models:
            for qhash in list(reversed(self._models))[: self.MODEL_PROBE_LIMIT]:
                if qhash == fp.qhash:
                    continue  # the exact tier already tried this one
                asg = canon.model_on_query(self._models[qhash], fp.var_order)
                if asg is not None and self._validates(conjuncts, asg):
                    return (SAT, asg), "model_hits"
        return None, None

    @staticmethod
    def _validates(conjuncts, asg) -> bool:
        try:
            vals = evaluate(conjuncts, asg)
        except Exception:
            return False
        return all(vals[c] for c in conjuncts)

    def _subsuming_core(self, conj_hashes: frozenset) -> Optional[str]:
        for h in conj_hashes:
            for cid in self._core_members.get(h, ()):
                if self._cores[cid] <= conj_hashes:
                    return cid
        return None

    # -- record ---------------------------------------------------------

    def record(
        self,
        conjuncts: Sequence[Term],
        status: str,
        asg: Optional[Assignment] = None,
        budget_ms: Optional[int] = None,
    ) -> None:
        """Persist a verdict.  Idempotent: re-recording a verdict that was
        itself served from the cache is a no-op, and a decided (SAT/UNSAT)
        verdict is never downgraded to UNKNOWN.  UNKNOWN entries keep the
        LARGEST budget they failed under."""
        if not self.enabled or not conjuncts:
            return
        if status not in (SAT, UNSAT, UNKNOWN):
            return
        reg = _registry()
        with self._lock:
            fp = self._fingerprint(conjuncts)
            existing = self._entries.get(fp.qhash)
            if status == UNKNOWN:
                budget = int(budget_ms or 0)
                if existing is not None:
                    if existing.get("verdict") != UNKNOWN:
                        return
                    if budget <= int(existing.get("budget_ms") or 0):
                        return
                entry = {"verdict": UNKNOWN, "budget_ms": budget}
            elif status == SAT:
                if existing is not None and existing.get("verdict") == SAT:
                    return
                if asg is None:
                    return
                var_index = {t.tid: i for i, t in enumerate(fp.var_order)}
                model = canon.dump_model(asg, var_index)
                if model is None:
                    # a SAT entry without a revalidatable model could never
                    # be served soundly — don't store one
                    return
                entry = {"verdict": SAT, "model": model}
                self._remember_model(fp.qhash, model)
            else:
                if existing is not None and existing.get("verdict") == UNSAT:
                    return
                entry = {"verdict": UNSAT}
                self._record_core(conjuncts)
            self._remember_entry(fp.qhash, entry)
            reg.counter("querycache.stores").inc()
            if self._store is not None and self._store.write_entry(fp.qhash, entry):
                reg.counter("querycache.disk_writes").inc()

    def _record_core(self, conjuncts: Sequence[Term]) -> None:
        core = self._minimize_core(list(conjuncts))
        if len(core) > self.CORE_SIZE_CAP:
            return
        hashes = frozenset(canon.conjunct_fingerprint(c)[2] for c in core)
        cid = canon.digest("|".join(sorted(hashes)))
        self._add_core(cid, hashes, write=True)

    def _minimize_core(self, core: List[Term]) -> List[Term]:
        """Greedy-drop minimization, justified conjunct by conjunct with the
        EXACT interval refuter (never the heuristic probe: every retained
        subset must itself be proven unsat, or the full recorded-UNSAT set
        is kept unminimized)."""
        if len(core) > self.MINIMIZE_CAP:
            return core
        from mythril_tpu.smt.intervals import refute

        try:
            if not refute(core):
                return core
            i = 0
            while i < len(core) and len(core) > 1:
                trial = core[:i] + core[i + 1:]
                if refute(trial):
                    core = trial
                else:
                    i += 1
        except Exception:
            pass
        return core

    def _add_core(self, cid: str, hashes: FrozenSet[str], write: bool) -> None:
        if not hashes or cid in self._cores or len(self._cores) >= self.max_cores:
            return
        self._cores[cid] = hashes
        self._core_members.setdefault(min(hashes), []).append(cid)
        if write and self._store is not None:
            self._store.write_core(cid, hashes)

    # -- bounded containers --------------------------------------------

    def _remember_entry(self, qhash: str, entry: dict) -> None:
        if qhash in self._entries:
            self._entries.move_to_end(qhash)
        self._entries[qhash] = entry
        while len(self._entries) > self.max_entries:
            self._entries.popitem(last=False)

    def _remember_model(self, qhash: str, model: dict) -> None:
        if qhash in self._models:
            self._models.move_to_end(qhash)
        self._models[qhash] = model
        while len(self._models) > self.max_models:
            self._models.popitem(last=False)

    # -- introspection --------------------------------------------------

    def hits_total(self) -> int:
        reg = _registry()
        return sum(reg.counter(name).value for name in _HIT_COUNTERS)

    def stats(self) -> dict:
        reg = _registry()
        out = {name.split(".", 1)[1]: reg.counter(name).value
               for name in _COUNTERS}
        out["entries"] = len(self._entries)
        out["cores"] = len(self._cores)
        out["disk"] = self._store is not None
        return out
