"""Differential tests: JAX limb algebra vs exact Python big-int semantics.

Oracle is plain Python int arithmetic with the same EVM conventions the host
evaluator uses (mythril_tpu/smt/concrete_eval.py): x/0 == 0, truncated signed
division, shifts saturating at the width.
"""

import random

import numpy as np
import pytest

from mythril_tpu.ops import bitvec as bb
from mythril_tpu.smt.terms import mask, to_signed

WIDTHS = [8, 16, 24, 160, 256]
random.seed(0xC0FFEE)


def _samples(width, n=24):
    edge = [0, 1, 2, (1 << width) - 1, 1 << (width - 1), (1 << (width - 1)) - 1]
    rnd = [random.getrandbits(width) for _ in range(n - len(edge))]
    small = [random.getrandbits(min(8, width)) for _ in range(4)]
    return [mask(v, width) for v in (edge + rnd + small)]


def _pairs(width):
    xs = _samples(width)
    ys = list(reversed(_samples(width)))
    return xs, ys


def _check_binop(fn_jax, fn_py, width):
    xs, ys = _pairs(width)
    a = bb.from_ints(xs, width)
    b = bb.from_ints(ys, width)
    got = bb.to_ints(fn_jax(a, b, width), width)
    want = [mask(fn_py(x, y), width) for x, y in zip(xs, ys)]
    assert got == want


@pytest.mark.parametrize("width", WIDTHS)
def test_roundtrip(width):
    xs = _samples(width)
    assert bb.to_ints(bb.from_ints(xs, width), width) == xs


@pytest.mark.parametrize("width", WIDTHS)
def test_add_sub_mul(width):
    _check_binop(bb.add, lambda x, y: x + y, width)
    _check_binop(bb.sub, lambda x, y: x - y, width)
    _check_binop(bb.mul, lambda x, y: x * y, width)


@pytest.mark.parametrize("width", WIDTHS)
def test_bitwise_neg(width):
    _check_binop(bb.and_, lambda x, y: x & y, width)
    _check_binop(bb.or_, lambda x, y: x | y, width)
    _check_binop(bb.xor, lambda x, y: x ^ y, width)
    xs = _samples(width)
    a = bb.from_ints(xs, width)
    assert bb.to_ints(bb.not_(a, width), width) == [mask(~x, width) for x in xs]
    assert bb.to_ints(bb.neg(a, width), width) == [mask(-x, width) for x in xs]


@pytest.mark.parametrize("width", WIDTHS)
def test_compares(width):
    xs, ys = _pairs(width)
    a, b = bb.from_ints(xs, width), bb.from_ints(ys, width)
    assert list(np.asarray(bb.eq(a, b))) == [x == y for x, y in zip(xs, ys)]
    assert list(np.asarray(bb.ult(a, b))) == [x < y for x, y in zip(xs, ys)]
    assert list(np.asarray(bb.ule(a, b))) == [x <= y for x, y in zip(xs, ys)]
    assert list(np.asarray(bb.slt(a, b, width))) == [
        to_signed(x, width) < to_signed(y, width) for x, y in zip(xs, ys)
    ]
    assert list(np.asarray(bb.sle(a, b, width))) == [
        to_signed(x, width) <= to_signed(y, width) for x, y in zip(xs, ys)
    ]


@pytest.mark.parametrize("width", [8, 24, 256])
def test_shifts(width):
    xs = _samples(width)
    shifts = [0, 1, 7, 15, 16, 17, width - 1, width, width + 3, 2 * width, 1 << 100]
    shifts = [mask(s, width) for s in shifts if s < (1 << width)] + [
        (1 << width) - 1
    ]
    for s in shifts:
        a = bb.from_ints(xs, width)
        sv = bb.from_ints([s] * len(xs), width)
        want_shl = [mask(x << s, width) if s < width else 0 for x in xs]
        want_lshr = [x >> s if s < width else 0 for x in xs]
        want_ashr = [
            mask(to_signed(x, width) >> min(s, width - 1), width) for x in xs
        ]
        assert bb.to_ints(bb.shl(a, sv, width), width) == want_shl, s
        assert bb.to_ints(bb.lshr(a, sv, width), width) == want_lshr, s
        assert bb.to_ints(bb.ashr(a, sv, width), width) == want_ashr, s


@pytest.mark.parametrize("width", [8, 64, 256])
def test_divmod(width):
    xs, ys = _pairs(width)
    ys = ys[:4] + [0, 1, 2] + ys[4:]
    xs = xs[:4] + [7, 9, (1 << width) - 3] + xs[4:]
    xs, ys = xs[: len(ys)], ys[: len(xs)]
    a, b = bb.from_ints(xs, width), bb.from_ints(ys, width)
    assert bb.to_ints(bb.udiv(a, b, width), width) == [
        0 if y == 0 else x // y for x, y in zip(xs, ys)
    ]
    assert bb.to_ints(bb.urem(a, b, width), width) == [
        0 if y == 0 else x % y for x, y in zip(xs, ys)
    ]

    def py_sdiv(x, y):
        if y == 0:
            return 0
        sx, sy = to_signed(x, width), to_signed(y, width)
        q = abs(sx) // abs(sy)
        return -q if (sx < 0) != (sy < 0) else q

    def py_srem(x, y):
        if y == 0:
            return 0
        sx, sy = to_signed(x, width), to_signed(y, width)
        r = abs(sx) % abs(sy)
        return -r if sx < 0 else r

    assert bb.to_ints(bb.sdiv(a, b, width), width) == [
        mask(py_sdiv(x, y), width) for x, y in zip(xs, ys)
    ]
    assert bb.to_ints(bb.srem(a, b, width), width) == [
        mask(py_srem(x, y), width) for x, y in zip(xs, ys)
    ]


@pytest.mark.parametrize("width", [8, 64, 256])
def test_exp(width):
    xs = [0, 1, 2, 3, 10, 255, (1 << width) - 1]
    es = [0, 1, 2, 3, 17, width, (1 << width) - 1]
    pairs = [(x, e) for x in xs for e in es]
    a = bb.from_ints([p[0] for p in pairs], width)
    e = bb.from_ints([p[1] for p in pairs], width)
    assert bb.to_ints(bb.bvexp(a, e, width), width) == [
        pow(x, ev, 1 << width) for x, ev in pairs
    ]


def test_resize_sext_extract_concat():
    xs = _samples(256, 12)
    a = bb.from_ints(xs, 256)
    # truncate & zero-extend
    assert bb.to_ints(bb.resize(a, 256, 64), 64) == [mask(x, 64) for x in xs]
    assert bb.to_ints(bb.resize(bb.from_ints(xs, 256), 256, 512), 512) == xs
    # sign extend 8 -> 256
    small = [0, 1, 0x7F, 0x80, 0xFF]
    s8 = bb.from_ints(small, 8)
    assert bb.to_ints(bb.sext_to(s8, 8, 256), 256) == [
        mask(to_signed(v, 8), 256) for v in small
    ]
    # extract arbitrary bit ranges
    for hi, lo in [(255, 0), (255, 248), (7, 0), (131, 4), (40, 33)]:
        w = hi - lo + 1
        assert bb.to_ints(bb.extract_bits(a, hi, lo, 256), w) == [
            (x >> lo) & ((1 << w) - 1) for x in xs
        ]
    # concat 256 ++ 256 = 512
    ys = list(reversed(xs))
    b = bb.from_ints(ys, 256)
    assert bb.to_ints(bb.concat_bits(a, b, 256, 256), 512) == [
        (x << 256) | y for x, y in zip(xs, ys)
    ]
    # concat with non-limb-aligned widths
    c = bb.from_ints([0x5], 3)
    d = bb.from_ints([0x1F], 5)
    assert bb.to_ints(bb.concat_bits(c, d, 3, 5), 8) == [(0x5 << 5) | 0x1F]


def test_mux_and_sign():
    xs = [0, 1, 1 << 255, (1 << 256) - 1]
    a = bb.from_ints(xs, 256)
    b = bb.from_ints(list(reversed(xs)), 256)
    cond = np.array([True, False, True, False])
    got = bb.to_ints(bb.mux(cond, a, b), 256)
    assert got == [xs[0], xs[2], xs[2], xs[0]]
    assert list(np.asarray(bb.sign_bit(a, 256))) == [0, 0, 1, 1]
