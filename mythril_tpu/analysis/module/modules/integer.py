"""IntegerArithmetics: overflow/underflow that reaches a sink (SWC-101).

Reference parity: mythril/analysis/module/modules/integer.py:1-350 — ADD/MUL/
SUB/EXP results are annotated with their overflow predicate; an issue is
raised only when an annotated (tainted) value reaches a sink (SSTORE / JUMPI /
CALL / RETURN) and both the overflow and the path are satisfiable at
transaction end.
"""

from __future__ import annotations

import logging
from typing import List, Optional

from mythril_tpu.analysis.module.base import DetectionModule, EntryPoint
from mythril_tpu.analysis.potential_issues import (
    PotentialIssue,
    get_potential_issues_annotation,
)
from mythril_tpu.analysis.swc_data import INTEGER_OVERFLOW_AND_UNDERFLOW
from mythril_tpu.core.state.global_state import GlobalState
from mythril_tpu.smt import (
    BitVec,
    Bool,
    BVAddNoOverflow,
    BVMulNoOverflow,
    BVSubNoUnderflow,
    Not,
)

log = logging.getLogger(__name__)

DESCRIPTION = """
Check for integer underflows.
For every SUB instruction, check if there's a possible state where op1 > op0.
For every ADD, MUL instruction, check if there's a possible state where op1 + op0 > 2^32 - 1.
"""


def _iroot_ceil(n: int, e: int) -> int:
    """Smallest b with b**e >= n (exact integer e-th root, rounded up)."""
    if e <= 1 or n <= 1:
        return n
    lo, hi = 1, 1 << (-(-n.bit_length() // e) + 1)
    while lo < hi:
        mid = (lo + hi) // 2
        if mid**e >= n:
            hi = mid
        else:
            lo = mid + 1
    return lo


# overflow bands for fully-symbolic EXP: (exponent, smallest overflowing base)
_EXP_BANDS = tuple(
    (k, _iroot_ceil(1 << 256, k))
    for k in (2, 3, 4, 6, 8, 11, 16, 22, 32, 43, 64, 86, 128, 172, 256)
)


class OverUnderflowAnnotation:
    """Attached to a result BitVec: remembers the violating predicate."""

    __slots__ = ("overflowing_state", "operator", "constraint")

    def __init__(self, overflowing_state: GlobalState, operator: str, constraint: Bool):
        self.overflowing_state = overflowing_state
        self.operator = operator
        self.constraint = constraint


class IntegerArithmetics(DetectionModule):
    name = "Integer overflow or underflow"
    swc_id = INTEGER_OVERFLOW_AND_UNDERFLOW
    description = DESCRIPTION
    entry_point = EntryPoint.CALLBACK
    pre_hooks = [
        "ADD",
        "MUL",
        "SUB",
        "EXP",
        "SSTORE",
        "JUMPI",
        "CALL",
        "RETURN",
    ]
    # _handle_add/mul/sub/exp return immediately when both operands are
    # concrete (a.value/b.value not None) — the device suppresses those
    # events (solc code is dominated by concrete pointer arithmetic)
    concrete_nop_hooks = frozenset({"ADD", "MUL", "SUB", "EXP"})
    # staticpass: the SSTORE/JUMPI/CALL/RETURN hooks only verify overflow
    # annotations installed by the arithmetic hooks
    static_required_ops = frozenset({"ADD", "MUL", "SUB", "EXP"})

    def _execute(self, state: GlobalState) -> None:
        opcode = state.get_current_instruction()["opcode"]
        if opcode in ("ADD", "MUL", "SUB", "EXP"):
            getattr(self, f"_handle_{opcode.lower()}")(state)
        else:
            getattr(self, f"_handle_sink_{opcode.lower()}")(state)
        return None

    # -- taint sources -----------------------------------------------------

    def _handle_add(self, state: GlobalState) -> None:
        a, b = state.mstate.stack[-1], state.mstate.stack[-2]
        if a.value is not None and b.value is not None:
            return
        annotation = OverUnderflowAnnotation(
            state, "addition", Not(BVAddNoOverflow(a, b, False))
        )
        # annotate the operand: the ADD handler's result unions operand
        # annotations, so the taint rides forward to any sink
        state.mstate.stack[-1].annotate(annotation)

    def _handle_mul(self, state: GlobalState) -> None:
        a, b = state.mstate.stack[-1], state.mstate.stack[-2]
        if a.value is not None and b.value is not None:
            return
        annotation = OverUnderflowAnnotation(
            state, "multiplication", Not(BVMulNoOverflow(a, b, False))
        )
        state.mstate.stack[-1].annotate(annotation)

    def _handle_sub(self, state: GlobalState) -> None:
        a, b = state.mstate.stack[-1], state.mstate.stack[-2]
        if a.value is not None and b.value is not None:
            return
        annotation = OverUnderflowAnnotation(
            state, "subtraction", Not(BVSubNoUnderflow(a, b, False))
        )
        state.mstate.stack[-1].annotate(annotation)

    def _handle_exp(self, state: GlobalState) -> None:
        base, exponent = state.mstate.stack[-1], state.mstate.stack[-2]
        if base.value is not None and exponent.value is not None:
            return
        constraint = self._exp_overflow_condition(base, exponent)
        if constraint is None:
            return
        annotation = OverUnderflowAnnotation(state, "exponentiation", constraint)
        state.mstate.stack[-1].annotate(annotation)

    @staticmethod
    def _exp_overflow_condition(base: BitVec, exponent: BitVec) -> Optional[Bool]:
        """base ** exponent >= 2^256, without a symbolic power term.

        One side concrete gives the exact threshold on the other; both
        symbolic uses a band cover: base >= 2^ceil(256/k) and exponent >= k
        implies overflow for any band k (sound; bands at ~sqrt(2) spacing
        keep the miss window small)."""
        from mythril_tpu.smt import And, Or, UGE, symbol_factory

        def bv(v: int) -> BitVec:
            return symbol_factory.BitVecVal(v, 256)

        if base.value is not None:
            b = base.value
            if b <= 1:
                return None
            e, power = 0, 1
            while power < (1 << 256):
                power *= b
                e += 1
            return UGE(exponent, bv(e))  # smallest e with b**e >= 2^256
        if exponent.value is not None:
            e = exponent.value
            if e == 0:
                return None
            if e == 1:
                return None  # base itself cannot exceed 2^256 - 1
            if e >= 256:
                return UGE(base, bv(2))
            # smallest b with b**e >= 2^256: integer e-th root of 2^256,
            # adjusted (2**ceil(256/e) overshoots whenever e does not divide
            # 256, silently missing a band of real overflows)
            thresh = _iroot_ceil(1 << 256, e)
            return UGE(base, bv(thresh))
        return Or(
            *[
                And(UGE(base, bv(thresh)), UGE(exponent, bv(k)))
                for k, thresh in _EXP_BANDS
            ]
        )

    # -- sinks --------------------------------------------------------------

    def _collect(self, value: BitVec) -> List[OverUnderflowAnnotation]:
        return [a for a in value.annotations if isinstance(a, OverUnderflowAnnotation)]

    def _handle_sink_sstore(self, state: GlobalState) -> None:
        value = state.mstate.stack[-2]
        self._register(state, self._collect(value))

    def _handle_sink_jumpi(self, state: GlobalState) -> None:
        condition = state.mstate.stack[-2]
        self._register(state, self._collect(condition))

    def _handle_sink_call(self, state: GlobalState) -> None:
        value = state.mstate.stack[-3]
        self._register(state, self._collect(value))

    def _handle_sink_return(self, state: GlobalState) -> None:
        offset = state.mstate.stack[-1]
        self._register(state, self._collect(offset))

    def _register(self, state: GlobalState, annotations: List[OverUnderflowAnnotation]) -> None:
        """Park EVERY overflow annotation riding the sink value.

        The reference collects all of them into a set and reports each
        satisfiable one (integer.py:211-259) — parking only the first would
        make WHICH site gets reported depend on annotation ordering, i.e.
        on scheduling (caught by the cooperative differential test)."""
        if not annotations:
            return
        if self._cache_key(state) in self.cache:
            return
        parked = get_potential_issues_annotation(state)

        def _ckey(constraints):
            return tuple(
                c.raw.tid if hasattr(c, "raw") else id(c) for c in constraints
            )

        # key includes the constraint identity: two parks of the same site
        # from different overflowing states carry DIFFERENT predicates, and
        # only one of them may be satisfiable — dropping by address alone
        # could park the unsatisfiable variant forever
        seen = {
            (p.address, p.title, _ckey(p.constraints))
            for p in parked.potential_issues
            if p.detector is self
        }
        for annotation in annotations:
            ostate = annotation.overflowing_state
            address = ostate.get_current_instruction()["address"]
            title = (
                "Integer Underflow"
                if annotation.operator == "subtraction"
                else "Integer Overflow"
            )
            key = (address, title, _ckey([annotation.constraint]))
            if key in seen:
                continue
            seen.add(key)
            potential_issue = PotentialIssue(
                contract=ostate.environment.active_account.contract_name,
                function_name=ostate.node.function_name if ostate.node else "unknown",
                address=address,
                swc_id=INTEGER_OVERFLOW_AND_UNDERFLOW,
                title=title,
                severity="High",
                bytecode=ostate.environment.code.bytecode,
                description_head=f"The arithmetic operator can {'underflow' if annotation.operator == 'subtraction' else 'overflow'}.",
                description_tail=(
                    "It is possible to cause an integer overflow or underflow in the "
                    "arithmetic operation. Prevent this by constraining inputs using "
                    "the require() statement or use the OpenZeppelin SafeMath library "
                    "for integer arithmetic operations. Refer to the transaction "
                    "sequence to see how the overflow can be triggered."
                ),
                detector=self,
                constraints=[annotation.constraint],
            )
            parked.potential_issues.append(potential_issue)


detector = IntegerArithmetics
