#!/usr/bin/env python
"""Watchtower acceptance smoke: the SLO breach drill, end to end.

Phase 1 (breach drill) — a real ``myth serve --workers 2`` daemon with a
tight SLO file and an injected 2s admission-side stall
(``BENCH_INJECT_ADMISSION_SLEEP=2``).  The TTFE objective must breach
within one fast window, and the breach must leave the full evidence
trail on disk:

* ``slo.breaches_total`` increments (Prometheus scrape);
* a flight-recorder bundle stamped with the objective, fanned out to
  every worker (linked worker bundles);
* a windowed profiler capture directory stamped ``slo-ttfe_p95-*``;
* ``myth health`` (the CLI subprocess) reports the breach and exits 1;
* the persistent history ring under ``--cache-root/history`` survives
  the daemon and replays through ``HistoryReader``.

Phase 2 (clean run) — the same daemon shape with the injection removed
and honest targets: health stays ok, zero breaches, ``myth health``
exits 0.  Guards against a watchtower that cries wolf.

Exit status is nonzero on any violation.  Artifacts land in ``--out``
(default ``watchtower-smoke/``) for CI to archive.

Usage::

    JAX_PLATFORMS=cpu python scripts/watchtower_smoke.py --out DIR
"""

from __future__ import annotations

import json
import os
import pathlib
import signal
import subprocess
import sys
import time

REPO = pathlib.Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

BREACH_PORT = 7395
CLEAN_PORT = 7394

FAILURES: list = []


def check(ok: bool, what: str) -> None:
    tag = "ok" if ok else "FAIL"
    print(f"[watchtower-smoke] {tag}: {what}", flush=True)
    if not ok:
        FAILURES.append(what)


def _kill_hex() -> str:
    return (REPO / "tests/testdata/inputs/kill_simple.bin-runtime") \
        .read_text().strip()


def _spawn_daemon(port: int, out: pathlib.Path, slo: pathlib.Path,
                  env_extra: dict, log_name: str) -> subprocess.Popen:
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env.update(env_extra)
    log = open(out / log_name, "w")
    return subprocess.Popen(
        [sys.executable, "-m", "mythril_tpu", "serve",
         "--port", str(port), "--no-frontier",
         "--workers", "2", "--batch-width", "1", "-t", "1",
         "--cache-root", str(out / "cache"),
         "--flight-recorder", str(out / "flight"),
         "--slo", str(slo)],
        cwd=str(REPO), env=env, stdout=log, stderr=log,
    )


def _stop_daemon(proc: subprocess.Popen, what: str,
                 expect_clean: bool = True) -> None:
    proc.send_signal(signal.SIGTERM)
    try:
        rc = proc.wait(timeout=120)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.wait(timeout=30)
        check(False, f"{what}: daemon drained on SIGTERM (hung, killed)")
        return
    if expect_clean:
        check(rc == 0, f"{what}: daemon drained cleanly on SIGTERM (rc={rc})")


def _myth_health(port: int) -> tuple:
    """Run the `myth health` CLI as a subprocess -> (rc, stdout)."""
    r = subprocess.run(
        [sys.executable, "-m", "mythril_tpu", "health",
         "--port", str(port)],
        cwd=str(REPO), capture_output=True, text=True, timeout=60,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    return r.returncode, r.stdout + r.stderr


def breach_drill(out: pathlib.Path) -> None:
    from mythril_tpu.service.client import ServiceClient
    from mythril_tpu.service.server import wait_for_server

    out.mkdir(parents=True, exist_ok=True)
    slo = out / "slo.json"
    # tight TTFE budget + short windows: the injected 2s stall must trip
    # the fast window on the first evaluation that sees a sample
    slo.write_text(json.dumps({
        "interval_s": 1.0,
        "capture": {"profile": True, "profile_duration_s": 0.3,
                    "cooldown_s": 5},
        "objectives": [
            {"name": "ttfe_p95", "kind": "quantile",
             "metric": "service.ttfe_s", "q": 0.95, "target": 0.5,
             "fast_window_s": 10, "slow_window_s": 30, "min_count": 1},
        ],
    }))
    proc = _spawn_daemon(BREACH_PORT, out, slo,
                         {"BENCH_INJECT_ADMISSION_SLEEP": "2"},
                         "serve.log")
    try:
        check(wait_for_server("127.0.0.1", BREACH_PORT, timeout=120),
              "breach daemon came up")
        client = ServiceClient("127.0.0.1", BREACH_PORT, timeout=300.0)
        # interactive tier: TTFE is the stalled submit + first finding
        rid = client.submit_detached(
            _kill_hex(), name="kill", tier="interactive"
        )["request_id"]
        client.wait(rid, timeout=300)

        health = {}
        deadline = time.time() + 90
        while time.time() < deadline:
            health = client.health()
            if health.get("enabled") and not health.get("ok"):
                break
            time.sleep(0.5)
        check(health.get("enabled") is True, "health verb: watchtower on")
        check(health.get("ok") is False,
              f"TTFE breached within the drill window ({health.get('breaching')})")
        check("ttfe_p95" in (health.get("breaching") or []),
              "the breaching objective is ttfe_p95")
        check(int(health.get("breaches_total") or 0) >= 1,
              "breaches_total incremented")

        # scrape: the breach counters and the per-objective status gauge
        text = client.metrics()
        check("slo_breaches_total" in text
              and any(l.startswith("slo_breaches_total")
                      and float(l.rsplit(" ", 1)[1]) >= 1
                      for l in text.splitlines()),
              "prometheus slo_breaches_total >= 1")
        check('slo_status{objective="ttfe_p95"} 2' in text,
              "prometheus slo_status gauge reports breach (2)")

        # the capture trail: give the fan-out + profile window a moment
        flight = out / "flight"
        profiles = out / "cache" / "profiles"
        daemon_b, worker_b, prof_dirs = [], [], []
        deadline = time.time() + 60
        while time.time() < deadline:
            names = (sorted(os.listdir(flight))
                     if flight.is_dir() else [])
            worker_b = [n for n in names if "-w0-" in n or "-w1-" in n]
            daemon_b = [n for n in names
                        if n not in worker_b and "slo.ttfe_p95" in n]
            prof_dirs = (sorted(p for p in os.listdir(profiles)
                                if p.startswith("slo-ttfe_p95-"))
                         if profiles.is_dir() else [])
            if daemon_b and len(worker_b) >= 2 and prof_dirs:
                break
            time.sleep(0.5)
        check(bool(daemon_b),
              f"flight bundle stamped with the objective ({daemon_b[:2]})")
        check(len(worker_b) >= 2,
              f"linked bundles fanned out to both workers ({worker_b[:4]})")
        if daemon_b:
            bundle = json.load(open(flight / daemon_b[0]))
            slo_block = bundle.get("slo") or {}
            check(slo_block.get("name") == "ttfe_p95",
                  "bundle carries the SLO evaluation")
        check(bool(prof_dirs),
              f"profiler capture stamped slo-ttfe_p95-* ({prof_dirs[:2]})")

        rc, text = _myth_health(BREACH_PORT)
        check(rc == 1, f"`myth health` exits 1 on breach (rc={rc})")
        check("ttfe_p95" in text, "`myth health` names the objective")
    finally:
        _stop_daemon(proc, "breach drill")
        sys.stdout.write((out / "serve.log").read_text()[-4000:])

    # the history ring outlives the daemon
    from mythril_tpu.observability.history import HistoryReader

    hist = out / "cache" / "history"
    check(hist.is_dir(), "history ring exists under --cache-root")
    if hist.is_dir():
        reader = HistoryReader(str(hist))
        segs = reader.segments()
        check(bool(segs), f"history has segments ({segs})")
        series = list(reader.series("service.requests"))
        check(bool(series), "service.requests replays from history")


def clean_run(out: pathlib.Path) -> None:
    from mythril_tpu.service.client import ServiceClient
    from mythril_tpu.service.server import wait_for_server

    out.mkdir(parents=True, exist_ok=True)
    slo = out / "slo.json"
    # honest CPU-CI targets: a clean daemon must hold these
    slo.write_text(json.dumps({
        "interval_s": 1.0,
        "capture": {"profile": False},
        "objectives": [
            {"name": "ttfe_p95", "kind": "quantile",
             "metric": "service.ttfe_s", "q": 0.95, "target": 30.0,
             "fast_window_s": 10, "slow_window_s": 30},
            {"name": "error_rate", "kind": "ratio",
             "metric": "service.request_errors",
             "denominator": "service.requests", "target": 0.05,
             "min_count": 2},
        ],
    }))
    proc = _spawn_daemon(CLEAN_PORT, out, slo, {}, "serve.log")
    try:
        check(wait_for_server("127.0.0.1", CLEAN_PORT, timeout=120),
              "clean daemon came up")
        client = ServiceClient("127.0.0.1", CLEAN_PORT, timeout=300.0)
        for i in range(3):
            rid = client.submit_detached(
                _kill_hex(), name=f"kill{i}", tier="interactive"
            )["request_id"]
            client.wait(rid, timeout=300)
        time.sleep(2.5)  # at least two evaluation ticks past the traffic
        health = client.health()
        check(health.get("enabled") is True, "clean: watchtower on")
        check(health.get("ok") is True,
              f"clean: no breach (breaching={health.get('breaching')})")
        check(int(health.get("breaches_total") or 0) == 0,
              "clean: zero breaches_total")
        overhead = float(health.get("overhead_pct") or 0.0)
        check(overhead < 2.0,
              f"clean: watchtower overhead {overhead:.3f}% < 2% budget")
        rc, _text = _myth_health(CLEAN_PORT)
        check(rc == 0, f"clean: `myth health` exits 0 (rc={rc})")
    finally:
        _stop_daemon(proc, "clean run")
        sys.stdout.write((out / "serve.log").read_text()[-4000:])


def main() -> int:
    out = pathlib.Path(
        sys.argv[sys.argv.index("--out") + 1]
        if "--out" in sys.argv else "watchtower-smoke"
    )
    out.mkdir(parents=True, exist_ok=True)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    breach_drill(out / "breach")
    clean_run(out / "clean")

    if FAILURES:
        print(f"[watchtower-smoke] {len(FAILURES)} FAILURES:",
              file=sys.stderr)
        for f in FAILURES:
            print(f"  - {f}", file=sys.stderr)
        return 1
    print("[watchtower-smoke] all checks passed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
