"""Detection-module integration tests over hand-assembled vulnerable bytecode.

Mirrors the reference's golden-output strategy (tests/integration_tests/
analysis_tests.py): run the full pipeline on known-vulnerable fixtures and
assert which detectors fire and what exploit inputs they produce.
"""

import pytest

from mythril_tpu.analysis.security import fire_lasers, reset_callback_modules
from mythril_tpu.analysis.symbolic import SymExecWrapper
from mythril_tpu.support.support_args import args as global_args


def analyze(code_hex: str, tx_count=1, modules=None):
    reset_callback_modules()
    # the (pc, bytecode-hash) issue cache persists across analyses in one
    # process (reference base.py:70-95); other suites analyze the same
    # fixtures, so clear it for order-independence
    from mythril_tpu.analysis.module.loader import ModuleLoader

    for m in ModuleLoader().get_detection_modules():
        m.cache.clear()
    sym = SymExecWrapper(
        bytes.fromhex(code_hex),
        address=0x0901D12E,
        strategy="dfs",
        transaction_count=tx_count,
        execution_timeout=60,
        modules=modules,
    )
    return fire_lasers(sym, white_list=modules)


# dispatcher prelude: selector(kill()=0x41c0e1b5) -> JUMPDEST at 0x14=20
# 0..14: PUSH1 00 CALLDATALOAD PUSH1 E0 SHR PUSH4 sel EQ PUSH1 dest JUMPI
# 15..19: PUSH1 00 PUSH1 00 REVERT
DISPATCH = "60003560e01c6341c0e1b5146014576000" + "6000fd" + "5b"


def test_unprotected_selfdestruct():
    issues = analyze(DISPATCH + "33ff", modules=["AccidentallyKillable"])
    assert len(issues) == 1
    issue = issues[0]
    assert issue.swc_id == "106"
    assert issue.severity == "High"
    assert issue.function == "kill()"
    step = issue.transaction_sequence["steps"][-1]
    assert step["input"].startswith("0x41c0e1b5")
    assert step["origin"] == "0xdeadbeefdeadbeefdeadbeefdeadbeefdeadbeef"


def test_ether_thief_and_external_call():
    # kill() body: CALL(gas=0xffff, to=CALLER, value=0x64, no args/ret) then STOP
    body = "6000" "6000" "6000" "6000" "6064" "33" "61ffff" "f1" "00"
    issues = analyze(DISPATCH + body)
    swc_ids = {i.swc_id for i in issues}
    assert "105" in swc_ids  # EtherThief: 100 wei > 0 paid
    assert "107" in swc_ids  # ExternalCalls: call to caller-supplied address


def test_exception_state_invalid_opcode():
    issues = analyze(DISPATCH + "fe", modules=["Exceptions"])
    assert len(issues) == 1
    assert issues[0].swc_id == "110"
    assert issues[0].title == "Exception State"


def test_tx_origin_dependence():
    # kill() body: ORIGIN CALLER EQ PUSH1 <dest> JUMPI STOP JUMPDEST STOP
    # dispatch block ends at byte 20 (JUMPDEST); body starts at 21
    # 21: ORIGIN(32) 22: CALLER(33) 23: EQ(14) 24-25: PUSH1 28+1=0x1d? compute:
    # bytes: 32 33 14 60 XX 57 00 5b 00 ; JUMPDEST at offset 21+6=27=0x1b
    body = "323314601b5700" "5b00"
    issues = analyze(DISPATCH + body, modules=["TxOrigin"])
    assert len(issues) == 1
    assert issues[0].swc_id == "115"


def test_integer_overflow_to_sstore_sink():
    # kill() body: CALLDATALOAD(4) + 1 -> SSTORE(0): overflow when arg = 2^256-1
    body = "600435" "6001" "01" "6000" "55" "00"
    issues = analyze(DISPATCH + body, modules=["IntegerArithmetics"])
    assert len(issues) >= 1
    assert issues[0].swc_id == "101"
    assert "Overflow" in issues[0].title


def test_timestamp_dependence():
    # kill() body: TIMESTAMP PUSH1 0x64 GT PUSH1 dest JUMPI STOP JUMPDEST STOP
    # bytes: 42 6064 11 60 XX 57 00 5b 00 ; body starts at 21; JUMPDEST at 21+7=28=0x1c
    body = "426064" "11" "601c57" "00" "5b00"
    issues = analyze(DISPATCH + body, modules=["PredictableVariables"])
    assert len(issues) == 1
    assert issues[0].swc_id in ("116", "120")


def test_clean_contract_no_issues():
    # store 42 at slot 0 and stop: nothing to report
    issues = analyze("602a60005500")
    assert issues == []


def test_multiple_sends():
    # two consecutive CALLs to caller then STOP
    one_call = "6000" "6000" "6000" "6000" "6000" "33" "61ffff" "f1" "50"
    body = one_call + one_call + "00"
    issues = analyze(DISPATCH + body, modules=["MultipleSends"])
    assert len(issues) == 1
    assert issues[0].swc_id == "113"
