"""Hash-consed bitvector/bool/array term IR — the framework's SMT core.

This replaces the reference's Z3 wrapper layer (mythril/laser/smt/*) with an
in-house intermediate representation designed for TPU lowering: every term is an
immutable, interned DAG node; concrete subterms constant-fold eagerly so purely
concrete execution never builds symbolic residue.  The same DAG has three
consumers:

  * the host big-int evaluator (``mythril_tpu/smt/concrete_eval.py``) — exact
    semantics, used for witness validation and differential testing;
  * the JAX lowering (``mythril_tpu/ops/lowering.py``) — batched evaluation of
    the DAG over many candidate assignments on TPU (the probe solver);
  * the C++ bit-blaster (``mythril_tpu/native/``) — exact sat/unsat.

Reference parity: the op surface mirrors mythril/laser/smt/bitvec_helper.py:30-240
and mythril/laser/smt/array.py, but keccak is a first-class operator (evaluated
concretely by every backend) instead of an uninterpreted function with interval
axioms (reference: mythril/laser/ethereum/function_managers/keccak_function_manager.py).
"""

from __future__ import annotations

import itertools
import threading
from typing import Dict, Iterable, Optional, Tuple, Union

# ---------------------------------------------------------------------------
# Sorts
# ---------------------------------------------------------------------------

BOOL = "bool"


def bv(width: int) -> Tuple[str, int]:
    return ("bv", width)


def array_sort(dom: int, rng: int) -> Tuple[str, int, int]:
    return ("arr", dom, rng)


def is_bv_sort(s) -> bool:
    return isinstance(s, tuple) and s[0] == "bv"


def is_array_sort(s) -> bool:
    return isinstance(s, tuple) and s[0] == "arr"


def mask(value: int, width: int) -> int:
    return value & ((1 << width) - 1)


def to_signed(value: int, width: int) -> int:
    value = mask(value, width)
    if value >= 1 << (width - 1):
        value -= 1 << width
    return value


# ---------------------------------------------------------------------------
# Term node
# ---------------------------------------------------------------------------

_term_counter = itertools.count()


class Term:
    """One interned node of the expression DAG.

    ``op``   operation name (see OPS below)
    ``sort`` BOOL | ("bv", w) | ("arr", dw, rw)
    ``args`` child terms
    ``aux``  non-term payload: constant value, variable name, (hi, lo), ...
    """

    __slots__ = ("op", "sort", "args", "aux", "tid", "_hashkey", "__weakref__")

    def __init__(self, op, sort, args, aux, hashkey):
        self.op = op
        self.sort = sort
        self.args = args
        self.aux = aux
        self.tid = next(_term_counter)
        self._hashkey = hashkey

    # Terms are interned: identity == structural equality.
    def __hash__(self):
        return hash(self._hashkey)

    def __eq__(self, other):
        return self is other

    @property
    def width(self) -> int:
        assert is_bv_sort(self.sort), f"not a bitvector: {self.op}"
        return self.sort[1]

    @property
    def is_const(self) -> bool:
        return self.op == "const"

    @property
    def value(self) -> int:
        assert self.op == "const"
        return self.aux

    def __repr__(self):
        if self.op == "const":
            if self.sort is BOOL:
                return "true" if self.aux else "false"
            return f"0x{self.aux:x}#{self.sort[1]}"
        if self.op in ("var", "array_var"):
            return f"{self.aux}"
        inner = " ".join(repr(a) for a in self.args)
        if self.aux is not None:
            return f"({self.op}[{self.aux}] {inner})"
        return f"({self.op} {inner})"


# Interning table.  Keyed by (op, sort, child tids, aux).
_INTERN: Dict[tuple, Term] = {}
# Interning must be race-free: equality is term identity (``self is
# other``), so two threads materializing the same key concurrently would
# mint two Terms with distinct tids and silently break every identity
# check and solver memo downstream.  Double-checked: the hit path stays
# lock-free (dict reads are atomic under the GIL), only a miss locks.
_INTERN_LOCK = threading.Lock()


def _mk(op, sort, args=(), aux=None) -> Term:
    if isinstance(sort, list):
        sort = tuple(sort)
    key = (op, sort, tuple(a.tid for a in args), aux)
    t = _INTERN.get(key)
    if t is None:
        with _INTERN_LOCK:
            t = _INTERN.get(key)
            if t is None:
                t = Term(op, sort, tuple(args), aux, key)
                _INTERN[key] = t
    return t


def intern_table_size() -> int:
    return len(_INTERN)


def clear_intern_table() -> None:
    """Drop all interned terms (tests / long-running corpus scans)."""
    _INTERN.clear()
    # also release the memoized walks so dropped terms can be collected
    _TOPO_CACHE.clear()


# ---------------------------------------------------------------------------
# Constructors: constants and variables
# ---------------------------------------------------------------------------


def const(value: int, width: int) -> Term:
    return _mk("const", bv(width), aux=mask(int(value), width))


def true() -> Term:
    return _mk("const", BOOL, aux=True)


def false() -> Term:
    return _mk("const", BOOL, aux=False)


def boolval(b: bool) -> Term:
    return true() if b else false()


def var(name: str, width: int) -> Term:
    return _mk("var", bv(width), aux=name)


def bool_var(name: str) -> Term:
    return _mk("var", BOOL, aux=name)


def array_var(name: str, dom: int, rng: int) -> Term:
    return _mk("array_var", array_sort(dom, rng), aux=name)


def const_array(dom: int, rng: int, default: Term) -> Term:
    """K combinator: array mapping every index to ``default``.

    Reference: mythril/laser/smt/array.py:60 (class K).
    """
    assert is_bv_sort(default.sort) and default.width == rng
    return _mk("const_array", array_sort(dom, rng), (default,))


# ---------------------------------------------------------------------------
# Bitvector operations (eager constant folding + light algebraic rewrites)
# ---------------------------------------------------------------------------


def _c2(a: Term, b: Term) -> bool:
    return a.op == "const" and b.op == "const"


def add(a: Term, b: Term) -> Term:
    w = a.width
    assert b.width == w
    if _c2(a, b):
        return const(a.value + b.value, w)
    if a.is_const and a.value == 0:
        return b
    if b.is_const and b.value == 0:
        return a
    # canonical order for commutative op: const on the left
    if b.is_const and not a.is_const:
        a, b = b, a
    return _mk("bvadd", bv(w), (a, b))


def sub(a: Term, b: Term) -> Term:
    w = a.width
    assert b.width == w
    if _c2(a, b):
        return const(a.value - b.value, w)
    if b.is_const and b.value == 0:
        return a
    if a is b:
        return const(0, w)
    return _mk("bvsub", bv(w), (a, b))


def mul(a: Term, b: Term) -> Term:
    w = a.width
    assert b.width == w
    if _c2(a, b):
        return const(a.value * b.value, w)
    for x, y in ((a, b), (b, a)):
        if x.is_const:
            if x.value == 0:
                return const(0, w)
            if x.value == 1:
                return y
    if b.is_const and not a.is_const:
        a, b = b, a
    return _mk("bvmul", bv(w), (a, b))


def udiv(a: Term, b: Term) -> Term:
    w = a.width
    if _c2(a, b):
        return const(0 if b.value == 0 else a.value // b.value, w)
    if b.is_const and b.value == 1:
        return a
    return _mk("bvudiv", bv(w), (a, b))


def sdiv(a: Term, b: Term) -> Term:
    w = a.width
    if _c2(a, b):
        if b.value == 0:
            return const(0, w)
        x, y = to_signed(a.value, w), to_signed(b.value, w)
        # EVM-style truncated division
        q = abs(x) // abs(y)
        if (x < 0) != (y < 0):
            q = -q
        return const(q, w)
    return _mk("bvsdiv", bv(w), (a, b))


def urem(a: Term, b: Term) -> Term:
    w = a.width
    if _c2(a, b):
        return const(0 if b.value == 0 else a.value % b.value, w)
    return _mk("bvurem", bv(w), (a, b))


def srem(a: Term, b: Term) -> Term:
    w = a.width
    if _c2(a, b):
        if b.value == 0:
            return const(0, w)
        x, y = to_signed(a.value, w), to_signed(b.value, w)
        r = abs(x) % abs(y)
        if x < 0:
            r = -r
        return const(r, w)
    return _mk("bvsrem", bv(w), (a, b))


def band(a: Term, b: Term) -> Term:
    w = a.width
    if _c2(a, b):
        return const(a.value & b.value, w)
    for x, y in ((a, b), (b, a)):
        if x.is_const:
            if x.value == 0:
                return const(0, w)
            if x.value == (1 << w) - 1:
                return y
    if a is b:
        return a
    if b.is_const and not a.is_const:
        a, b = b, a
    return _mk("bvand", bv(w), (a, b))


def bor(a: Term, b: Term) -> Term:
    w = a.width
    if _c2(a, b):
        return const(a.value | b.value, w)
    for x, y in ((a, b), (b, a)):
        if x.is_const:
            if x.value == 0:
                return y
            if x.value == (1 << w) - 1:
                return const((1 << w) - 1, w)
    if a is b:
        return a
    if b.is_const and not a.is_const:
        a, b = b, a
    return _mk("bvor", bv(w), (a, b))


def bxor(a: Term, b: Term) -> Term:
    w = a.width
    if _c2(a, b):
        return const(a.value ^ b.value, w)
    if a is b:
        return const(0, w)
    for x, y in ((a, b), (b, a)):
        if x.is_const and x.value == 0:
            return y
    if b.is_const and not a.is_const:
        a, b = b, a
    return _mk("bvxor", bv(w), (a, b))


def bnot(a: Term) -> Term:
    w = a.width
    if a.is_const:
        return const(~a.value, w)
    if a.op == "bvnot":
        return a.args[0]
    return _mk("bvnot", bv(w), (a,))


def neg(a: Term) -> Term:
    w = a.width
    if a.is_const:
        return const(-a.value, w)
    return _mk("bvneg", bv(w), (a,))


def shl(a: Term, b: Term) -> Term:
    w = a.width
    if _c2(a, b):
        return const(0 if b.value >= w else a.value << b.value, w)
    if b.is_const and b.value == 0:
        return a
    return _mk("bvshl", bv(w), (a, b))


def lshr(a: Term, b: Term) -> Term:
    w = a.width
    if _c2(a, b):
        return const(0 if b.value >= w else a.value >> b.value, w)
    if b.is_const and b.value == 0:
        return a
    return _mk("bvlshr", bv(w), (a, b))


def ashr(a: Term, b: Term) -> Term:
    w = a.width
    if _c2(a, b):
        x = to_signed(a.value, w)
        s = min(b.value, w - 1) if b.value >= 0 else w - 1
        return const(x >> s, w)
    if b.is_const and b.value == 0:
        return a
    return _mk("bvashr", bv(w), (a, b))


def bvexp(a: Term, b: Term) -> Term:
    """Modular exponentiation a**b mod 2^w.

    The reference models EXP with an uninterpreted ``Power`` function plus
    eagerly-asserted concrete axioms (exponent_function_manager.py:11-66); here
    it is a real operator every backend evaluates exactly.
    """
    w = a.width
    if _c2(a, b):
        return const(pow(a.value, b.value, 1 << w), w)
    if a.is_const and a.value == 1:
        return const(1, w)
    if b.is_const and b.value == 0:
        return const(1, w)
    if b.is_const and b.value == 1:
        return a
    return _mk("bvexp", bv(w), (a, b))


def concat2(a: Term, b: Term) -> Term:
    """a is the high part, b the low part (z3 convention)."""
    w = a.width + b.width
    if _c2(a, b):
        return const((a.value << b.width) | b.value, w)
    # Fuse adjacent extracts of the same base term
    if (
        a.op == "extract"
        and b.op == "extract"
        and a.args[0] is b.args[0]
        and a.aux[1] == b.aux[0] + 1
    ):
        return extract(a.aux[0], b.aux[1], a.args[0])
    return _mk("concat", bv(w), (a, b))


def concat(*parts: Term) -> Term:
    parts_l = list(parts)
    out = parts_l[0]
    for p in parts_l[1:]:
        out = concat2(out, p)
    return out


def extract(hi: int, lo: int, a: Term) -> Term:
    """Bits hi..lo inclusive (z3 argument order, reference bitvec_helper Extract)."""
    w = hi - lo + 1
    assert 0 <= lo <= hi < a.width, (hi, lo, a.width)
    if w == a.width:
        return a
    if a.is_const:
        return const(a.value >> lo, w)
    if a.op == "extract":
        return extract(a.aux[1] + hi, a.aux[1] + lo, a.args[0])
    if a.op == "concat":
        hi_part, lo_part = a.args
        if hi < lo_part.width:
            return extract(hi, lo, lo_part)
        if lo >= lo_part.width:
            return extract(hi - lo_part.width, lo - lo_part.width, hi_part)
    if a.op == "zext":
        inner = a.args[0]
        if hi < inner.width:
            return extract(hi, lo, inner)
        if lo >= inner.width:
            return const(0, w)
    return _mk("extract", bv(w), (a,), (hi, lo))


def zext(a: Term, extra: int) -> Term:
    if extra == 0:
        return a
    w = a.width + extra
    if a.is_const:
        return const(a.value, w)
    return _mk("zext", bv(w), (a,), extra)


def sext(a: Term, extra: int) -> Term:
    if extra == 0:
        return a
    w = a.width + extra
    if a.is_const:
        return const(to_signed(a.value, a.width), w)
    return _mk("sext", bv(w), (a,), extra)


# ---------------------------------------------------------------------------
# Predicates
# ---------------------------------------------------------------------------


def eq(a: Term, b: Term) -> Term:
    if a.sort is BOOL and b.sort is BOOL:
        return iff(a, b)
    assert a.sort == b.sort, (a.sort, b.sort)
    if a is b:
        return true()
    if _c2(a, b):
        return boolval(a.value == b.value)
    if b.is_const and not a.is_const:
        a, b = b, a
    # eq(const, ite(c, x, y)) with constant branches folds to c / ¬c — this is
    # the `If(cond, 1, 0) == 0` pattern every comparison+JUMPI produces
    if a.is_const and b.op == "ite":
        c, x, y = b.args
        if x.is_const and y.is_const:
            ex, ey = a.value == x.value, a.value == y.value
            if ex and ey:
                return true()
            if ex:
                return c
            if ey:
                return lnot(c)
            return false()
    return _mk("eq", BOOL, (a, b))


def ne(a: Term, b: Term) -> Term:
    return lnot(eq(a, b))


def ult(a: Term, b: Term) -> Term:
    if a is b:
        return false()
    if _c2(a, b):
        return boolval(a.value < b.value)
    if b.is_const and b.value == 0:
        return false()
    return _mk("ult", BOOL, (a, b))


def ule(a: Term, b: Term) -> Term:
    if a is b:
        return true()
    if _c2(a, b):
        return boolval(a.value <= b.value)
    return _mk("ule", BOOL, (a, b))


def ugt(a: Term, b: Term) -> Term:
    return ult(b, a)


def uge(a: Term, b: Term) -> Term:
    return ule(b, a)


def slt(a: Term, b: Term) -> Term:
    if a is b:
        return false()
    if _c2(a, b):
        return boolval(to_signed(a.value, a.width) < to_signed(b.value, b.width))
    return _mk("slt", BOOL, (a, b))


def sle(a: Term, b: Term) -> Term:
    if a is b:
        return true()
    if _c2(a, b):
        return boolval(to_signed(a.value, a.width) <= to_signed(b.value, b.width))
    return _mk("sle", BOOL, (a, b))


def sgt(a: Term, b: Term) -> Term:
    return slt(b, a)


def sge(a: Term, b: Term) -> Term:
    return sle(b, a)


# ---------------------------------------------------------------------------
# Boolean connectives
# ---------------------------------------------------------------------------


def land(*xs: Term) -> Term:
    flat = []
    for x in xs:
        if x.op == "const":
            if not x.aux:
                return false()
            continue
        if x.op == "and":
            flat.extend(x.args)
        else:
            flat.append(x)
    # dedupe preserving order
    seen, out = set(), []
    for x in flat:
        if x.tid not in seen:
            seen.add(x.tid)
            out.append(x)
    if not out:
        return true()
    if len(out) == 1:
        return out[0]
    return _mk("and", BOOL, tuple(out))


def lor(*xs: Term) -> Term:
    flat = []
    for x in xs:
        if x.op == "const":
            if x.aux:
                return true()
            continue
        if x.op == "or":
            flat.extend(x.args)
        else:
            flat.append(x)
    seen, out = set(), []
    for x in flat:
        if x.tid not in seen:
            seen.add(x.tid)
            out.append(x)
    if not out:
        return false()
    if len(out) == 1:
        return out[0]
    return _mk("or", BOOL, tuple(out))


def lnot(a: Term) -> Term:
    if a.op == "const":
        return boolval(not a.aux)
    if a.op == "not":
        return a.args[0]
    # push negation through comparisons: Not(a<b) == b<=a
    if a.op == "ult":
        return ule(a.args[1], a.args[0])
    if a.op == "ule":
        return ult(a.args[1], a.args[0])
    if a.op == "slt":
        return sle(a.args[1], a.args[0])
    if a.op == "sle":
        return slt(a.args[1], a.args[0])
    return _mk("not", BOOL, (a,))


def lxor(a: Term, b: Term) -> Term:
    if _c2(a, b):
        return boolval(bool(a.aux) != bool(b.aux))
    if a is b:
        return false()
    return _mk("xor", BOOL, (a, b))


def iff(a: Term, b: Term) -> Term:
    return lnot(lxor(a, b))


def implies(a: Term, b: Term) -> Term:
    return lor(lnot(a), b)


def ite(c: Term, a: Term, b: Term) -> Term:
    assert c.sort is BOOL
    assert a.sort == b.sort
    if c.op == "const":
        return a if c.aux else b
    if a is b:
        return a
    return _mk("ite", a.sort, (c, a, b))


# ---------------------------------------------------------------------------
# Arrays
# ---------------------------------------------------------------------------


def store(arr: Term, idx: Term, val: Term) -> Term:
    assert is_array_sort(arr.sort)
    _, dw, rw = arr.sort
    assert idx.width == dw and val.width == rw
    return _mk("store", arr.sort, (arr, idx, val))


def select(arr: Term, idx: Term) -> Term:
    assert is_array_sort(arr.sort)
    _, dw, rw = arr.sort
    assert idx.width == dw
    # read-over-write simplification where indices are decidable
    a = arr
    while a.op == "store":
        base, k, v = a.args
        if k is idx:
            return v
        if k.is_const and idx.is_const:
            if k.value == idx.value:
                return v
            a = base
            continue
        break
    if a.op == "const_array":
        return a.args[0]
    if a is not arr and a.op != "store":
        arr = a
    return _mk("select", bv(rw), (arr, idx))


# ---------------------------------------------------------------------------
# Keccak + uninterpreted functions
# ---------------------------------------------------------------------------


def keccak(data: Term) -> Term:
    """keccak256 of a byte-aligned bitvector, as a first-class 256-bit op."""
    assert data.width % 8 == 0
    if data.is_const:
        from mythril_tpu.ops.keccak import keccak256_int

        return const(keccak256_int(data.value, data.width // 8), 256)
    return _mk("keccak", bv(256), (data,))


def apply_func(name: str, out_width: int, *args: Term) -> Term:
    """Generic uninterpreted function application (reference smt/function.py:7)."""
    sig = (name, tuple(a.width for a in args), out_width)
    return _mk("apply", bv(out_width), tuple(args), sig)


# ---------------------------------------------------------------------------
# DAG utilities
# ---------------------------------------------------------------------------


# Root-set -> post-order list.  Hash-consed DAGs make the walk a pure
# function of the root tids, and the solver's cheap tiers re-walk the SAME
# conjunction once per cached model — measured at ~40% of wide-frontier
# harvest time before memoization.  Terms are interned for process lifetime
# (see _INTERN), so holding them here adds no retention.
_TOPO_CACHE: Dict[tuple, list] = {}
_TOPO_CACHE_MAX = 1024


def topo_order(roots: Iterable[Term]):
    """Post-order (children first) over the DAG reachable from roots.

    Returns a memoized tuple (immutable: the cache is shared across
    callers, and a mutation would corrupt unrelated queries)."""
    roots = tuple(roots)
    key = tuple(r.tid for r in roots)
    cached = _TOPO_CACHE.get(key)
    if cached is not None:
        return cached
    seen = set()
    out = []
    stack = [(r, False) for r in roots]
    while stack:
        node, done = stack.pop()
        if done:
            out.append(node)
            continue
        if node.tid in seen:
            continue
        seen.add(node.tid)
        stack.append((node, True))
        for a in node.args:
            if a.tid not in seen:
                stack.append((a, False))
    if len(_TOPO_CACHE) >= _TOPO_CACHE_MAX:
        _TOPO_CACHE.clear()
    out = tuple(out)
    _TOPO_CACHE[key] = out
    return out


def free_vars(roots: Iterable[Term]):
    """All var/array_var leaves reachable from roots, in deterministic order."""
    out = []
    for t in topo_order(roots):
        if t.op in ("var", "array_var"):
            out.append(t)
    return out


def substitute(root: Term, mapping: Dict[Term, Term]) -> Term:
    """Rebuild ``root`` with leaves (or arbitrary subterms) replaced."""
    cache: Dict[int, Term] = {t.tid: r for t, r in mapping.items()}

    order = topo_order([root])
    for t in order:
        if t.tid in cache:
            continue
        if not t.args:
            cache[t.tid] = t
            continue
        new_args = tuple(cache[a.tid] for a in t.args)
        if all(n is o for n, o in zip(new_args, t.args)):
            cache[t.tid] = t
        else:
            cache[t.tid] = rebuild(t.op, t.sort, new_args, t.aux)
    return cache[root.tid]


def rebuild(op: str, sort, args: Tuple[Term, ...], aux) -> Term:
    """Re-apply a node's constructor so folding/rewrites fire on new children."""
    if op == "bvadd":
        return add(*args)
    if op == "bvsub":
        return sub(*args)
    if op == "bvmul":
        return mul(*args)
    if op == "bvudiv":
        return udiv(*args)
    if op == "bvsdiv":
        return sdiv(*args)
    if op == "bvurem":
        return urem(*args)
    if op == "bvsrem":
        return srem(*args)
    if op == "bvand":
        return band(*args)
    if op == "bvor":
        return bor(*args)
    if op == "bvxor":
        return bxor(*args)
    if op == "bvnot":
        return bnot(*args)
    if op == "bvneg":
        return neg(*args)
    if op == "bvshl":
        return shl(*args)
    if op == "bvlshr":
        return lshr(*args)
    if op == "bvashr":
        return ashr(*args)
    if op == "bvexp":
        return bvexp(*args)
    if op == "concat":
        return concat2(*args)
    if op == "extract":
        return extract(aux[0], aux[1], args[0])
    if op == "zext":
        return zext(args[0], aux)
    if op == "sext":
        return sext(args[0], aux)
    if op == "eq":
        return eq(*args)
    if op == "ult":
        return ult(*args)
    if op == "ule":
        return ule(*args)
    if op == "slt":
        return slt(*args)
    if op == "sle":
        return sle(*args)
    if op == "and":
        return land(*args)
    if op == "or":
        return lor(*args)
    if op == "not":
        return lnot(*args)
    if op == "xor":
        return lxor(*args)
    if op == "ite":
        return ite(*args)
    if op == "store":
        return store(*args)
    if op == "select":
        return select(*args)
    if op == "keccak":
        return keccak(*args)
    if op == "apply":
        return apply_func(aux[0], aux[2], *args)
    if op == "const_array":
        return const_array(sort[1], sort[2], args[0])
    return _mk(op, sort, args, aux)
