"""Analysis-as-a-service: a long-lived multi-tenant daemon.

Every pre-service entry point is a cold one-shot process: each request
re-pays XLA compilation, SMT query-cache warmup, and runs its contract
alone on the device even when the slot batch is mostly empty.  This
package converts the batch tool into a server:

* ``daemon.AnalysisService`` — the admission plane + its workers.  With
  ``workers=1`` (default) one worker thread owns the (non-reentrant)
  analysis singletons and runs admitted requests as shared wide device
  batches via the cooperative corpus sweep
  (``analysis/cooperative.run_cooperative_batch``), streaming issues back
  per request as they confirm.  With ``workers=N`` a horizontal pool of
  N worker *processes* (``pool``/``worker``) runs N batches concurrently
  behind the same admission queue, sharing the on-disk caches and the
  cross-process completed-result LRU (``resultstore``) under one
  ``--cache-root``.
* ``admission.AdmissionController`` — queue + dedup + scheduling.
  Submissions are keyed by canonical codehash + options; duplicate
  submitters subscribe to the in-flight result (replay-then-live
  ordering) or get a cached replay of a completed one.  An optional
  ``scheduling.SchedulerPolicy`` adds tenant quotas, batch-tier load
  shedding, and priority aging.
* ``server.run_server`` / ``client.ServiceClient`` — a thin JSON-lines
  TCP layer (``myth serve`` / ``myth submit``) over the in-process API.
* ``telemetry.RequestTelemetry`` — the request-scoped telemetry plane:
  per-phase latency decomposition (queue-wait/batch-wait/execute/stream
  histograms + percentiles in ``stats()``), per-tenant accounting,
  per-request trace span trees flow-joined to the frontier's segment
  spans, and the ``--request-log`` JSONL.  ``top.run_top`` renders a
  live operator view (``myth top``) from polled stats.

Determinism contract: each request's issue set (by
``codehash.issue_digest``) is bit-identical to a solo run of the same
contract — shared batching changes scheduling, never findings.  See
docs/source/service.rst.
"""

from mythril_tpu.service.codehash import (  # noqa: F401
    canonical_codehash,
    issue_digest,
    normalize_code,
    options_key,
)
from mythril_tpu.service.request import (  # noqa: F401
    AnalysisOptions,
    AnalysisRequest,
    ResultStream,
    issue_to_wire,
)
from mythril_tpu.service.admission import AdmissionController  # noqa: F401
from mythril_tpu.service.scheduling import (  # noqa: F401
    AdmissionRejected,
    SchedulerPolicy,
)
from mythril_tpu.service.telemetry import RequestTelemetry  # noqa: F401
from mythril_tpu.service.daemon import (  # noqa: F401
    AnalysisService,
    ServiceConfig,
)
