"""Adaptive steering on/off parity — the ``--no-adaptive`` contract.

The controller only reorders and retimes frontier compute; it must never
change WHAT is explored to completion.  The fast tests pin the engine's
FIFO fallback gates (tier-1); the ``slow``-marked e2e runs the real
cooperative device frontier twice and asserts bit-identical issue sets,
mirroring ``bench.py --adaptive-compare``.
"""

import pytest

from mythril_tpu.adaptive import get_adaptive_controller
from mythril_tpu.frontier.engine import (
    _adaptive_coverage_stop,
    _adaptive_pick,
)
from mythril_tpu.observability.exploration import get_exploration_ledger
from mythril_tpu.observability.metrics import get_registry
from mythril_tpu.support.support_args import args as global_args

# selector(kill()=0x41c0e1b5) -> CALLER;SELFDESTRUCT, else revert
SUICIDE_HEX = "60003560e01c6341c0e1b51460145760006000fd5b33ff"
# value-gated kill: two nested comparisons guard the SELFDESTRUCT
GATED_HEX = "60003580600a9010600c57005b80600514601c5780601414601c57005b33ff"
# selector -> kill at 0x1e, fallthrough into a 511-iteration concrete
# loop ending in STOP: coverage saturates (only the loop-exit STOP stays
# uncovered) segments before the unroll finishes, so --coverage-target
# must latch its stop verdict mid-run, never racing the natural end
LOOP_TAIL_HEX = (
    "60003560e01c6341c0e1b514601e5760005b600101806102001160115700"
    "5b33ff"
)


class TestEngineGates:
    """The actuation sites' FIFO fallbacks, no devices involved."""

    def test_pick_fifo_with_single_seed(self):
        assert _adaptive_pick([7], [0], ["a" * 64]) == 0

    def test_pick_fifo_when_disabled(self, monkeypatch):
        monkeypatch.setattr(global_args, "adaptive", False)
        get_adaptive_controller().reset_scope()
        before = get_registry().counter("adaptive.resteered_slots").value
        for _ in range(8):
            assert _adaptive_pick(
                [0, 1], [0, 1], ["a" * 64, "b" * 64]
            ) == 0
        after = get_registry().counter("adaptive.resteered_slots").value
        assert after == before, "--no-adaptive run still resteered"

    def test_coverage_stop_gate_requires_target(self, monkeypatch):
        monkeypatch.setattr(global_args, "coverage_target", None)
        assert _adaptive_coverage_stop() is False


def _clear_module_caches():
    """Detection modules memoize (code, address) pairs per process; a
    parity re-run must see a cold analysis, not the memo."""
    from mythril_tpu.analysis.module.loader import ModuleLoader
    from mythril_tpu.analysis.security import reset_callback_modules

    reset_callback_modules()
    for module in ModuleLoader().get_detection_modules():
        module.cache.clear()


def _cooperative_run(adaptive: bool, coverage_target=None, jobs=None):
    from mythril_tpu.analysis.cooperative import analyze_cooperative

    _clear_module_caches()
    get_registry().reset()
    get_exploration_ledger().reset_scope()
    ctrl = get_adaptive_controller()
    ctrl.reset_scope()
    saved = (global_args.adaptive, global_args.coverage_target,
             global_args.frontier, global_args.frontier_force,
             global_args.frontier_width, global_args.pipeline,
             global_args.loop_bound)
    global_args.adaptive = adaptive
    global_args.coverage_target = coverage_target
    global_args.frontier = True
    global_args.frontier_force = True
    global_args.frontier_width = 64
    global_args.pipeline = True
    global_args.loop_bound = 600  # above LOOP_TAIL's natural exit at 512
    try:
        per_name, _states = analyze_cooperative(
            jobs if jobs is not None else [
                ("suicide", bytes.fromhex(SUICIDE_HEX)),
                ("gated", bytes.fromhex(GATED_HEX)),
            ],
            transaction_count=1,
            execution_timeout=120,
        )
    finally:
        (global_args.adaptive, global_args.coverage_target,
         global_args.frontier, global_args.frontier_force,
         global_args.frontier_width, global_args.pipeline,
         global_args.loop_bound) = saved
    issues = sorted(
        (name, i.swc_id, i.address, i.bytecode_hash)
        for name, found in per_name.items()
        for i in found
    )
    snap = {
        k: v for k, v in get_registry().snapshot().items()
        if k.startswith("adaptive.")
    }
    return issues, snap, ctrl.stop_state()


@pytest.mark.slow
def test_cooperative_issue_sets_bit_identical_on_vs_off():
    on_issues, on_snap, _ = _cooperative_run(adaptive=True)
    off_issues, off_snap, _ = _cooperative_run(adaptive=False)
    assert on_issues, "steered run found nothing (workload broken)"
    assert on_issues == off_issues, (
        "adaptive steering changed the issue set (parity broken): "
        f"{on_issues} != {off_issues}"
    )
    assert not off_snap.get("adaptive.plans", 0), (
        f"--no-adaptive run still planned: {off_snap}"
    )
    assert not off_snap.get("adaptive.resteered_slots", 0), (
        f"--no-adaptive run still resteered: {off_snap}"
    )


@pytest.mark.slow
def test_coverage_target_latches_stop_without_losing_issues():
    jobs = [("loop_tail", bytes.fromhex(LOOP_TAIL_HEX)),
            ("suicide", bytes.fromhex(SUICIDE_HEX))]
    base_issues, _, base_stop = _cooperative_run(adaptive=True, jobs=jobs)
    assert base_stop is None, "run without a target latched a stop"
    issues, _, stop = _cooperative_run(
        adaptive=True, coverage_target=90.0, jobs=jobs
    )
    assert stop is not None, "--coverage-target never latched a verdict"
    assert stop["coverage_target_met"] is True
    assert stop["coverage_target"] == 90.0
    # the 90% bar is only reachable once every kill path executed (the
    # kill instructions sit in the denominator), so the early stop must
    # not cost recall on this workload
    assert issues == base_issues
