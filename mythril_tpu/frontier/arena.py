"""Host mirror of the device term arena: encode / decode vs the host term IR.

The arena is the frontier's constraint pool (SURVEY.md §7.1): a flat table of
rows ``(op, a, b, c, width, val[16 limbs], isconst)`` shared by every path in
the batch.  The host seeds it (PUSH constants, environment symbols, storage /
balance array bases), the device appends rows as instructions produce symbolic
results, and at harvest time the host pulls the new rows and decodes each into
a host ``terms.Term`` — the same IR the solver, the detectors, and the report
pipeline consume.

Decoding calls the ordinary term constructors, so eager constant folding and
hash-consing make the decoded terms semantically identical to what the host
instruction handlers (mythril_tpu/core/instructions.py) would have built for
the same path; macro rows (A_CDLOAD, A_ADDMOD, ...) decode into the exact
composites those handlers construct (reference: mythril/laser/ethereum/
instructions.py:778, :274-288).
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from mythril_tpu.frontier import ops as O
from mythril_tpu.smt import terms as T

# NOTE: ops.bitvec imports jax at module level; this module must stay
# jax-free (frontier.taint -> frontier.code -> here is imported by every
# detection module at load time), so from_ints/to_ints bind lazily on
# first use — they are pure-numpy despite living in bitvec.py, and the
# call sites run per-row in encode/decode hot paths
_from_ints = None
_to_ints = None


def _bitvec_fns():
    global _from_ints, _to_ints
    if _from_ints is None:
        from mythril_tpu.ops.bitvec import from_ints, to_ints

        _from_ints, _to_ints = from_ints, to_ints
    return _from_ints, _to_ints

LIMBS = 16  # 256 bits as 16-bit limbs in uint32


# monotonically increasing arena generation ids: the pipelined engine keeps
# two arena generations logically in flight (segment N's pulled rows, segment
# N+1's pending device appends) and asserts they never alias host buffers
_GENERATION = [0]


class HostArena:
    """Append-only row table with host-side interning and decode memo.

    Every instance owns FRESH numpy columns (no shared/aliased buffers
    between generations — the pipelined engine depends on this) and carries
    a process-unique ``generation`` id.  ``freeze()`` guards the pipelined
    loop's no-append window: while a device segment is in flight the device
    appends rows at the same indices the host would, so host-side appends
    raise until ``thaw()`` at a sync point."""

    def __init__(self, cap: int = 1 << 17):
        _GENERATION[0] += 1
        self.generation = _GENERATION[0]
        self._frozen = False
        self.cap = cap
        self.op = np.zeros(cap, np.int32)
        self.a = np.full(cap, -1, np.int32)
        self.b = np.full(cap, -1, np.int32)
        self.c = np.full(cap, -1, np.int32)
        self.width = np.zeros(cap, np.int32)
        self.val = np.zeros((cap, LIMBS), np.uint32)
        self.isconst = np.zeros(cap, bool)
        # host-only taint bitmask per row (frontier/taint.py): seeded on env
        # source rows and on mid-frame re-entry rows; device rows stay 0 and
        # inherit taint through the ref graph (walker._annos closure) — the
        # device never reads or ships this column
        self.taint = np.zeros(cap, np.int32)
        self.length = 0

        self._const_memo: Dict[tuple, int] = {}
        self._taint_memo: Dict[tuple, int] = {}
        # var table: row id -> host Term (opaque encode / seed symbols)
        self._vars: List[T.Term] = []
        self._var_memo: Dict[T.Term, int] = {}
        self._encode_memo: Dict[T.Term, int] = {}
        self._decode_memo: Dict[int, T.Term] = {}
        # per-seed context for macro rows (calldata objects etc.)
        self.seeds: List = []

    # ------------------------------------------------------------------
    # row creation (host side)
    # ------------------------------------------------------------------

    def freeze(self) -> None:
        """Forbid host appends (a device segment is in flight and owns the
        append indices); decode/read stays allowed."""
        self._frozen = True

    def thaw(self) -> None:
        self._frozen = False

    @property
    def frozen(self) -> bool:
        """True while a device segment owns the append indices.  The
        pipelined runner's sync-point machinery (spill re-injection, pod
        rebalance) asserts on this before encoding: appends outside a sync
        point would alias in-flight device rows."""
        return self._frozen

    def _append(self, op, a=-1, b=-1, c=-1, width=0, value: Optional[int] = None) -> int:
        if self._frozen:
            raise RuntimeError(
                "arena is frozen: host appends while a device segment is "
                "in flight would alias the device's append indices"
            )
        if self.length >= self.cap:
            raise MemoryError("arena capacity exhausted")
        i = self.length
        self.op[i], self.a[i], self.b[i], self.c[i] = op, a, b, c
        self.width[i] = width
        if value is not None:
            self.val[i] = _bitvec_fns()[0](value & ((1 << 256) - 1), 256)
            self.isconst[i] = True
        self.length += 1
        return i

    def add_taint(self, row: int, bits: int) -> None:
        """OR taint bits onto a row (rows are interned, so bits accumulate
        — matching host annotation sets, which are shared and append-only)."""
        if row >= 0 and bits:
            self.taint[row] |= bits

    def const_row(self, value: int, width: int = 256) -> int:
        key = (value, width)
        row = self._const_memo.get(key)
        if row is None:
            row = self._append(O.A_CONST, width=width, value=value)
            self._const_memo[key] = row
        return row

    def var_row(self, term: T.Term) -> int:
        """Opaque row bound to an arbitrary host term (totalizes encoding)."""
        row = self._var_memo.get(term)
        if row is None:
            row = self.fresh_var_row(term)
            self._var_memo[term] = row
        return row

    def fresh_var_row(self, term: T.Term, no_fold: bool = False) -> int:
        """A DEDICATED (non-interned) opaque row for a term.

        Taint bits are per-row, but host taint is per-USE: the symbolic tx
        driver sets ``origin = caller = sender_n`` (transaction/symbolic.py
        seed_message_call), so seeding TAINT_ORIGIN on the interned row of
        that term would taint every ``msg.sender`` comparison and fabricate
        SWC-115s the host engine (which annotates only the wrapper the
        ORIGIN opcode pushed) never reports.  Source ctx slots therefore
        get their own row; it decodes to the same term, so solver and
        report semantics are untouched.

        ``no_fold``: leave the const payload off even for constant terms.
        Device constant folds emit REF-LESS rows (a folded comparison
        becomes the shared row_one/row_zero), which would cut a tainted
        constant source (gaslimit) out of the walker's taint closure — on
        the host the annotation survives folding because it rides the
        wrapper.  A no-fold row keeps the dataflow edge; the decode still
        yields the constant term, so every downstream fold happens exactly
        at decode/solve time."""
        self._vars.append(term)
        row = self._append(
            O.A_VAR,
            a=len(self._vars) - 1,
            width=term.width if T.is_bv_sort(term.sort) else 0,
        )
        if term.is_const and not no_fold:
            self.val[row] = _bitvec_fns()[0](term.value, 256)
            self.isconst[row] = True
        self._decode_memo[row] = term
        return row

    def tainted_row(self, term: T.Term, mask: int) -> int:
        """Dedicated row carrying taint bits, memoized per (term, mask) so
        repeated mid-frame re-entries of annotated values do not grow the
        arena unboundedly (identical term + identical taint is semantically
        the same use)."""
        key = (term, mask)
        row = self._taint_memo.get(key)
        if row is None:
            row = self.fresh_var_row(term, no_fold=True)
            self.taint[row] |= mask
            self._taint_memo[key] = row
        return row

    # ------------------------------------------------------------------
    # structural encode: host term -> rows (fold-friendly on device)
    # ------------------------------------------------------------------

    _ENC_BIN = {
        "add": O.A_ADD, "sub": O.A_SUB, "mul": O.A_MUL, "udiv": O.A_UDIV,
        "sdiv": O.A_SDIV, "urem": O.A_UREM, "srem": O.A_SREM, "and": O.A_AND,
        "or": O.A_OR, "xor": O.A_XOR, "shl": O.A_SHL, "lshr": O.A_LSHR,
        "ashr": O.A_ASHR, "exp": O.A_EXP,
        "ult": O.A_ULT, "ugt": O.A_UGT, "ule": O.A_ULE, "uge": O.A_UGE,
        "slt": O.A_SLT, "sgt": O.A_SGT, "eq": O.A_EQ, "ne": O.A_NE,
    }

    def encode(self, term: T.Term) -> int:
        """Host term -> arena row, structurally where the device understands
        the op (enables device-side constant folding), opaque VAR otherwise."""
        memo = self._encode_memo
        row = memo.get(term)
        if row is not None:
            return row
        # iterative post-order walk (term DAGs can be deep)
        stack = [(term, False)]
        while stack:
            t, ready = stack.pop()
            if t in memo:
                continue
            if not ready:
                stack.append((t, True))
                for ch in t.args:
                    if ch not in memo:
                        stack.append((ch, False))
                continue
            memo[t] = self._encode_one(t)
        return memo[term]

    def _encode_one(self, t: T.Term) -> int:
        op = t.op
        if op == "const":
            if t.sort is T.BOOL:
                return self.var_row(t)
            return self.const_row(t.value, t.width)
        if op in ("var", "array_var"):
            return self.var_row(t)
        ch = [self._encode_memo[c] for c in t.args]
        w = t.width if T.is_bv_sort(t.sort) else 0
        if op in self._ENC_BIN and len(ch) == 2:
            return self._append(self._ENC_BIN[op], a=ch[0], b=ch[1], width=w)
        if op == "not" and len(ch) == 1:
            return self._append(O.A_NOT, a=ch[0], width=w)
        if op == "lnot":
            return self._append(O.A_BNOT, a=ch[0])
        if op == "ite" and T.is_bv_sort(t.sort):
            return self._append(O.A_ITEW, a=ch[0], b=ch[1], c=ch[2], width=w)
        if op == "concat":
            return self._append(O.A_CONCAT, a=ch[0], b=ch[1], width=w)
        if op == "extract":
            hi, lo = t.aux
            return self._append(O.A_EXTRACT, a=ch[0], b=hi, c=lo, width=w)
        if op == "keccak":
            return self._append(O.A_KECCAK, a=ch[0], width=256)
        if op == "select" and t.args[0].sort == T.array_sort(256, 256):
            return self._append(O.A_SELECT, a=ch[0], b=ch[1], width=256)
        if op == "store" and t.sort == T.array_sort(256, 256):
            return self._append(O.A_STORE, a=ch[0], b=ch[1], c=ch[2])
        return self.var_row(t)

    # ------------------------------------------------------------------
    # device sync
    # ------------------------------------------------------------------

    def pull_from_device(self, dev_arrays, new_length: int) -> None:
        """Copy rows [self.length:new_length) appended by the device.

        Chunked packed transfer (step.pull_arena_rows): ONE fixed-shape
        dispatch and ONE host copy per chunk — per-slice pulls with fresh
        bounds paid a remote compile + round trip each on tunneled chips."""
        if new_length <= self.length:
            return
        from mythril_tpu.frontier.step import pull_arena_rows

        lo, hi = self.length, int(new_length)
        op, a, b, c, width, isconst, val = pull_arena_rows(dev_arrays, lo, hi)
        self.op[lo:hi] = op
        self.a[lo:hi] = a
        self.b[lo:hi] = b
        self.c[lo:hi] = c
        self.width[lo:hi] = width
        self.val[lo:hi] = val
        self.isconst[lo:hi] = isconst.astype(bool)
        self.length = hi

    # ------------------------------------------------------------------
    # decode: arena row -> host term
    # ------------------------------------------------------------------

    def const_value(self, row: int) -> int:
        vals = _bitvec_fns()[1](self.val[row], 256)
        width = int(self.width[row])  # numpy int32 cannot shift past 63
        return vals[0] & ((1 << width) - 1) if width else vals[0]

    def decode(self, row: int) -> T.Term:
        memo = self._decode_memo
        got = memo.get(row)
        if got is not None:
            return got
        stack = [(int(row), False)]
        while stack:
            r, ready = stack.pop()
            if r in memo:
                continue
            if not ready:
                stack.append((r, True))
                for ch in (self.a[r], self.b[r], self.c[r]):
                    ch = int(ch)
                    if ch >= 0 and ch not in memo and self._row_has_term_arg(r, ch):
                        stack.append((ch, False))
                continue
            memo[r] = self._decode_one(r)
        return memo[row]

    def _row_has_term_arg(self, r: int, ch: int) -> bool:
        op = int(self.op[r])
        if op in (O.A_CONST, O.A_VAR, O.A_VARF):
            return False
        if op == O.A_EXTRACT:  # b, c are immediates
            return ch == int(self.a[r])
        if op == O.A_CDLOAD:  # b is a seed index
            return ch == int(self.a[r])
        return True

    def _decode_one(self, r: int) -> T.Term:
        op = int(self.op[r])
        m = self._decode_memo

        # Sort coercion: the device kernel keeps EVM comparison results as
        # 0/1 limb WORDS, but comparison rows decode to host BOOL terms (a
        # JUMPI condition wants exactly that).  A word-op consuming a
        # comparison row (solc emits LT;NOT, ISZERO;MUL, ...) must coerce
        # the bool back to the 0/1 word the device actually computed —
        # previously this crashed the walker ("not a bitvector: eq") and
        # dropped the path.
        def _word(t: T.Term) -> T.Term:
            if t.sort is T.BOOL:
                return T.ite(t, T.const(1, 256), T.const(0, 256))
            return t  # bitvectors unchanged; arrays (select/store) too

        def _bool(t: T.Term) -> T.Term:
            if T.is_bv_sort(t.sort):
                return T.ne(t, T.const(0, t.width))
            return t

        A = lambda: _word(m[int(self.a[r])])  # noqa: E731
        B = lambda: _word(m[int(self.b[r])])  # noqa: E731
        C = lambda: _word(m[int(self.c[r])])  # noqa: E731
        w = int(self.width[r])

        if op == O.A_CONST:
            return T.const(self.const_value(r), w)
        if op == O.A_VAR:
            return self._vars[int(self.a[r])]
        if op == O.A_VARF:
            return T.var(f"dev_fresh_{int(self.a[r])}_{r}", w or 256)
        simple = {
            O.A_ADD: T.add, O.A_SUB: T.sub, O.A_MUL: T.mul, O.A_UDIV: T.udiv,
            O.A_SDIV: T.sdiv, O.A_UREM: T.urem, O.A_SREM: T.srem,
            O.A_AND: T.band, O.A_OR: T.bor, O.A_XOR: T.bxor,
            O.A_SHL: T.shl, O.A_LSHR: T.lshr, O.A_ASHR: T.ashr,
            O.A_EXP: T.bvexp,
            O.A_ULT: T.ult, O.A_UGT: T.ugt, O.A_ULE: T.ule, O.A_UGE: T.uge,
            O.A_SLT: T.slt, O.A_SGT: T.sgt, O.A_EQ: T.eq, O.A_NE: T.ne,
        }
        if op in simple:
            return simple[op](A(), B())
        if op == O.A_EQZ:
            raw = m[int(self.a[r])]
            if not T.is_bv_sort(raw.sort):
                return T.lnot(raw)  # ISZERO over a comparison: logical not
            return T.eq(raw, T.const(0, raw.width))
        if op == O.A_NOT:
            return T.bnot(A())
        if op == O.A_BNOT:
            return T.lnot(_bool(m[int(self.a[r])]))
        if op == O.A_ITEW:
            return T.ite(_bool(m[int(self.a[r])]), B(), C())
        if op == O.A_CONCAT:
            return T.concat2(A(), B())
        if op == O.A_EXTRACT:
            return T.extract(int(self.b[r]), int(self.c[r]), A())
        if op == O.A_KECCAK:
            return T.keccak(A())
        if op == O.A_SELECT:
            return T.select(A(), B())
        if op == O.A_STORE:
            return T.store(A(), B(), C())
        if op == O.A_CDLOAD:
            from mythril_tpu.smt import BitVec

            calldata = self.seeds[int(self.b[r])].environment.calldata
            return calldata.get_word_at(BitVec(A())).raw
        if op == O.A_ADDMOD or op == O.A_MULMOD:
            # mirror mythril_tpu/core/instructions.py addmod_/mulmod_
            # (reference mythril/laser/ethereum/instructions.py:274-288)
            wide_op = T.add if op == O.A_ADDMOD else T.mul
            wide = T.urem(
                wide_op(T.zext(A(), 256), T.zext(B(), 256)), T.zext(C(), 256)
            )
            return T.extract(255, 0, wide)
        if op == O.A_SIGNEXT:
            # mirror signextend_ symbolic composite (instructions.py:297-321)
            b_t, x = A(), B()
            result = x
            for i in range(31):
                bits = 8 * (i + 1)
                result = T.ite(
                    T.eq(b_t, T.const(i, 256)),
                    T.sext(T.extract(bits - 1, 0, x), 256 - bits),
                    result,
                )
            return result
        if op == O.A_BYTE:
            # mirror byte_ symbolic composite (instructions.py:392-410)
            idx, word = A(), B()
            shift = T.mul(T.sub(T.const(31, 256), idx), T.const(8, 256))
            return T.ite(
                T.ult(idx, T.const(32, 256)),
                T.band(T.lshr(word, shift), T.const(0xFF, 256)),
                T.const(0, 256),
            )
        raise ValueError(f"cannot decode arena op {op} at row {r}")

    # ------------------------------------------------------------------
    # device view
    # ------------------------------------------------------------------

    def device_arrays(self):
        """Full-capacity numpy views to ship to the device."""
        return (self.op, self.a, self.b, self.c, self.width, self.val, self.isconst)
